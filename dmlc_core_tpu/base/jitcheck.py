"""XLA-compile tracer (``DMLC_JITCHECK=1``): zero steady-state recompiles.

Dynamic companion to dmlcheck's ``recompile-hazard`` pass.  The static
rule proves cache *keys* are stable shapes; this module proves the
dynamic half: after a drill or bench declares its warmup over, **zero**
further XLA compilations happen in the process.  A steady-state compile
is the bug class PR 18 fixed by postmortem — a 98 s recompile hiding
behind a warm persistent cache — and the one PR 6's "zero recompiles on
refresh" promise depends on.  Nothing enforced it until now.

Mechanics: :func:`install` wraps ``jax._src.compiler
.compile_or_get_cached`` — the one choke point every in-process
compilation funnels through (``pxla`` calls it via the module
attribute, so assignment is enough).  It is deliberately BELOW the
persistent compile cache's entry: a compilation-cache *hit* still
passes through here, because a hit still costs a trace + lowering +
deserialize stall at steady state (exactly how the PR 18 bug hid).
Each call records the lowered module name, wall seconds, the current
phase tag (``warmup`` until :func:`steady` is called) and up to three
repo-relative stack frames, and bumps ``dmlc_recompiles_total{phase}``.

The CI drills install this next to lockcheck/racecheck/leakcheck,
archive :func:`write_report` JSON (``*_JITCHECK_OUT``) and gate GREEN
on :func:`check` — which raises on any ``steady``-phase record.  When
the env gate is off nothing is patched and dispatch runs untouched.
"""

from __future__ import annotations

import _thread
import os
import sys
import time
from typing import Any, Dict, List, Optional

__all__ = ["JitCompileError", "install", "uninstall", "installed",
           "compiles", "current_phase", "steady", "warmup", "reset",
           "check", "write_report", "env_enabled"]


class JitCompileError(RuntimeError):
    """At least one XLA compilation happened after steady() at check()."""


#: guards the record table; a RAW interpreter lock, immune to
#: lockcheck's factory patching regardless of import order
_state_lock = _thread.allocate_lock()

_enabled = False
_phase = "warmup"
_records: List[Dict[str, Any]] = []

#: original captured at install() time (NOT import time) so repeated
#: install/uninstall cycles restore the true jax entry point
_saved: Dict[str, Any] = {}


def _repo_site(depth: int) -> Optional[str]:
    """Up to three repo-relative ``file:line(func)`` frames above the
    hook (compiles are synchronous on the dispatch path, so the
    triggering repo call site is on the stack)."""
    frames: List[str] = []
    try:
        f: Any = sys._getframe(depth)
    except ValueError:
        return None
    hops = 0
    while f is not None and len(frames) < 3 and hops < 80:
        fn = f.f_code.co_filename
        if fn == __file__:                  # our own hook is not a site
            f = f.f_back
            hops += 1
            continue
        for marker in ("dmlc_core_tpu", "tests", "scripts"):
            i = fn.find(os.sep + marker + os.sep)
            if i >= 0:
                frames.append(f"{fn[i + 1:]}:{f.f_lineno}"
                              f"({f.f_code.co_name})")
                break
        f = f.f_back
        hops += 1
    return " <- ".join(frames) if frames else None


def _module_name(computation: Any) -> str:
    """Best-effort name of the lowered MLIR module (``jit__round_fn``
    etc.) — identifies WHAT recompiled without holding the module."""
    try:
        from jax._src.lib.mlir import ir

        return ir.StringAttr(
            computation.operation.attributes["sym_name"]).value
    except Exception:  # noqa: BLE001 — any mlir shape change
        return getattr(computation, "name", None) or "<unknown>"


def _traced_compile(*args: Any, **kwargs: Any) -> Any:
    computation = args[1] if len(args) > 1 else kwargs.get("computation")
    t0 = time.perf_counter()
    try:
        return _saved["compile"](*args, **kwargs)
    finally:
        if _enabled:
            with _state_lock:
                phase = _phase
                rec = {
                    "module": _module_name(computation),
                    "phase": phase,
                    "seconds": round(time.perf_counter() - t0, 4),
                    "site": _repo_site(2),
                }
                _records.append(rec)
            from dmlc_core_tpu.base import metrics as _metrics

            if _metrics.enabled():
                _metrics.default_registry().counter(
                    "recompiles_total",
                    "XLA compilations observed by jitcheck, by phase "
                    "(warmup|steady) — steady-state compiles fail drills",
                    labels=("phase",)).inc(1, phase=phase)


# -- lifecycle --------------------------------------------------------------

def install() -> None:
    """Patch the jax compile choke point and start recording.
    Idempotent.  The original is captured here (not at import) so
    repeated cycles restore the true entry point."""
    global _enabled
    if _enabled:
        return
    from jax._src import compiler as _compiler

    _saved["compile"] = _compiler.compile_or_get_cached
    _compiler.compile_or_get_cached = _traced_compile  # type: ignore
    _enabled = True


def uninstall() -> None:
    """Stop recording and restore the jax entry point.  Idempotent."""
    global _enabled
    if not _enabled:
        return
    _enabled = False
    from jax._src import compiler as _compiler

    _compiler.compile_or_get_cached = _saved["compile"]  # type: ignore
    _saved.clear()


def installed() -> bool:
    """True while jitcheck is actively recording compilations."""
    return _enabled


# -- phase tagging ----------------------------------------------------------

def steady() -> None:
    """Declare warmup over: every compile from here on is a violation.
    Call exactly where the bench/drill's steady state begins (stream
    window full, routed warmup predict verified, ...)."""
    global _phase
    with _state_lock:
        _phase = "steady"


def warmup() -> None:
    """Re-enter the warmup phase (a new model's first compile is
    legitimate — e.g. between drill sections, or in tests)."""
    global _phase
    with _state_lock:
        _phase = "warmup"


def current_phase() -> str:
    """The tag the next recorded compile will carry."""
    return _phase


# -- reporting --------------------------------------------------------------

def compiles(phase: Optional[str] = None) -> List[Dict[str, Any]]:
    """Every recorded compilation (module, phase, seconds, site),
    optionally filtered to one phase."""
    with _state_lock:
        recs = [dict(r) for r in _records]
    if phase is not None:
        recs = [r for r in recs if r["phase"] == phase]
    return recs


def reset() -> None:
    """Forget every recorded compile and return to warmup (test
    isolation)."""
    global _phase
    with _state_lock:
        _records.clear()
        _phase = "warmup"


def check() -> None:
    """Raise :class:`JitCompileError` when any compilation was recorded
    after :func:`steady` — the zero-post-warmup-compiles gate."""
    bad = compiles("steady")
    if not bad:
        return
    lines = [f"{r['module']} ({r['seconds']}s) at "
             f"{r['site'] or '<no repo frame>'}" for r in bad]
    raise JitCompileError(
        f"{len(bad)} steady-state XLA compilation(s): " + "; ".join(lines))


def write_report(path: str) -> Dict[str, Any]:
    """Archive the compile report as JSON (the drills' ``*_JITCHECK_OUT``
    artifact); returns the report dict."""
    import json

    recs = compiles()
    report = {
        "enabled": _enabled,
        "phase": _phase,
        "compiles_total": len(recs),
        "compiles_steady": sum(1 for r in recs if r["phase"] == "steady"),
        "compiles": recs,
    }
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    return report


def env_enabled() -> bool:
    """The ``DMLC_JITCHECK`` import-time gate."""
    return os.environ.get("DMLC_JITCHECK", "0").lower() in (
        "1", "true", "on", "yes", "raise")
