"""Old-style ``key = value`` config files.

Reference parity: ``include/dmlc/config.h + src/config.cc :: dmlc::Config``
(SURVEY.md §2a) — iterate ``(key, value)`` pairs from a config text, with
optional multi-value keys and quoted "proto-style" string values.
"""

from __future__ import annotations

import io as _pyio
from typing import Dict, Iterator, List, Tuple, Union

from dmlc_core_tpu.base.logging import log_fatal

__all__ = ["Config"]


class Config:
    """Parse ``key = value`` config text.

    * ``#`` starts a comment (outside quotes).
    * Values may be double-quoted and may span multiple tokens; quoted values
      keep embedded ``=`` and whitespace (the reference's proto-string case).
    * ``multi_value=True`` keeps every occurrence of a repeated key (in order);
      otherwise later occurrences overwrite earlier ones.
    """

    def __init__(self, source: Union[str, _pyio.TextIOBase], multi_value: bool = False):
        text = source if isinstance(source, str) else source.read()
        self.multi_value = multi_value
        self._order: List[Tuple[str, str]] = []
        self._latest: Dict[str, str] = {}
        self._parse(text)

    def _parse(self, text: str) -> None:
        for lineno, raw in enumerate(text.splitlines(), 1):
            line = self._strip_comment(raw).strip()
            if not line:
                continue
            if "=" not in line:
                log_fatal(f"Config: line {lineno} has no '=': {raw!r}")
            key, _, value = line.partition("=")
            key = key.strip()
            value = value.strip()
            if not key:
                log_fatal(f"Config: line {lineno} has empty key: {raw!r}")
            if len(value) >= 2 and value[0] == '"' and value[-1] == '"':
                value = value[1:-1].replace('\\"', '"').replace("\\n", "\n")
            if not self.multi_value and key in self._latest:
                self._order = [(k, v) for (k, v) in self._order if k != key]
            self._order.append((key, value))
            self._latest[key] = value

    @staticmethod
    def _strip_comment(line: str) -> str:
        out = []
        in_quote = False
        i = 0
        while i < len(line):
            ch = line[i]
            if ch == '"' and (i == 0 or line[i - 1] != "\\"):
                in_quote = not in_quote
            if ch == "#" and not in_quote:
                break
            out.append(ch)
            i += 1
        return "".join(out)

    def __iter__(self) -> Iterator[Tuple[str, str]]:
        return iter(self._order)

    def __getitem__(self, key: str) -> str:
        if key not in self._latest:
            log_fatal(f"Config: unknown key {key!r}")
        return self._latest[key]

    def get(self, key: str, default: str = "") -> str:
        return self._latest.get(key, default)

    def items(self) -> List[Tuple[str, str]]:
        return list(self._order)

    def to_dict(self) -> Dict[str, str]:
        return dict(self._latest)
