"""Process-resource leak tracer (``DMLC_LEAKCHECK=1``).

Sixth layer of the verification suite: dmlcheck's ``resource-leak`` /
``thread-lifecycle`` passes prove acquisition *shape* statically — this
module proves the dynamic half: at drill exit, **zero** repo-created
sockets, threads, subprocesses or tempfiles are still live.  A leaked
server socket keeps a port wedged for the next drill, an unjoined
thread can segfault interpreter teardown, an unwaited child is a
zombie the CI host accumulates — exactly the rot that long-lived
tracker/PS/fleet processes die of in production.

Mechanics — creation hooks only, liveness evaluated lazily:

* ``socket.socket`` is replaced by a recording subclass (``accept``,
  ``create_connection`` and ``socketpair`` all construct through the
  module global, so accepted connections are traced too);
* ``threading.Thread.start``, ``subprocess.Popen.__init__``,
  ``tempfile.NamedTemporaryFile`` and ``tempfile.mkstemp`` are wrapped
  to record each creation.

Every record keeps a short repo-relative creation stack; creations
whose stack never touches this repo (jax compile pools, stdlib
internals) are ignored.  Nothing hooks the release side — ``leaks()``
asks each recorded object whether it is *still* live: a socket whose
``_closed`` is false, a thread that ``is_alive()``, a ``Popen`` whose
``returncode`` was never reaped (an exited-but-unwaited child — a
zombie — stays live on purpose), an unclosed ``NamedTemporaryFile``,
an ``mkstemp`` fd that still fstats to the inode it was created as.

The CI drills install this next to lockcheck/racecheck, archive
:func:`write_report` JSON (``*_LEAKCHECK_OUT``) and gate GREEN on
:func:`check`; each detected leak also increments the
``dmlc_leaks_detected_total`` counter by resource kind.  When the env
gate is off nothing is patched — creation paths run at full speed.
"""

from __future__ import annotations

import _thread
import os
import socket as _socket_mod
import subprocess as _subprocess_mod
import sys
import tempfile as _tempfile_mod
import threading
from typing import Any, Dict, List, Optional

__all__ = ["LeakError", "install", "uninstall", "installed", "leaks",
           "reset", "check", "write_report", "env_enabled"]

_KINDS = ("socket", "thread", "subprocess", "tempfile")


class LeakError(RuntimeError):
    """At least one repo-created resource was still live at check()."""


#: guards the record table; a RAW interpreter lock, immune to
#: lockcheck's factory patching regardless of import order
_state_lock = _thread.allocate_lock()

_enabled = False
#: id(obj) -> record dict; strong refs on purpose — a resource that was
#: never explicitly released must not escape detection via gc
_records: Dict[int, Dict[str, Any]] = {}
_created_count: Dict[str, int] = {k: 0 for k in _KINDS}

#: originals captured at install() time (NOT import time) so the hooks
#: chain correctly with racecheck's Thread.start tracing
_saved: Dict[str, Any] = {}


def _repo_site(depth: int) -> Optional[str]:
    """Up to three repo-relative ``file:line(func)`` frames above the
    hook, or ``None`` when the creation never passes through this repo
    (third-party resources are not ours to police)."""
    frames: List[str] = []
    try:
        f: Any = sys._getframe(depth)
    except ValueError:
        return None
    hops = 0
    while f is not None and len(frames) < 3 and hops < 30:
        fn = f.f_code.co_filename
        if fn == __file__:                  # our own hooks are not a site
            f = f.f_back
            hops += 1
            continue
        for marker in ("dmlc_core_tpu", "tests", "scripts"):
            i = fn.find(os.sep + marker + os.sep)
            if i >= 0:
                frames.append(f"{fn[i + 1:]}:{f.f_lineno}"
                              f"({f.f_code.co_name})")
                break
        f = f.f_back
        hops += 1
    return " <- ".join(frames) if frames else None


def _record(kind: str, obj: Any, detail: str, depth: int,
            extra: Optional[Dict[str, Any]] = None) -> None:
    site = _repo_site(depth)
    if site is None:
        return
    rec = {"kind": kind, "detail": detail, "site": site, "obj": obj}
    if extra:
        rec.update(extra)
    with _state_lock:
        _created_count[kind] += 1
        _records[id(obj)] = rec


# -- liveness (lazy, per kind) ----------------------------------------------

def _live(rec: Dict[str, Any]) -> bool:
    kind, obj = rec["kind"], rec["obj"]
    if kind == "socket":
        return not getattr(obj, "_closed", True)
    if kind == "thread":
        return bool(obj.is_alive())
    if kind == "subprocess":
        # returncode (NOT poll()): poll() would reap the zombie we are
        # here to report — an exited child nobody waited stays a leak
        return obj.returncode is None
    if kind == "tempfile":
        fd = rec.get("fd")
        if fd is None:                       # NamedTemporaryFile wrapper
            return not getattr(obj, "closed", True)
        try:
            st = os.fstat(fd)
        except OSError:
            return False
        # fd numbers are recycled: only the original inode counts
        return (st.st_dev, st.st_ino) == rec["stat"]
    return False


# -- creation hooks ---------------------------------------------------------

class _TracedSocket(_socket_mod.socket):
    """Socket subclass recording its creation site.  ``accept()``/
    ``create_connection``/``dup()`` construct via the module global or
    ``self.__class__`` — accepted and duped sockets are traced too."""

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__(*args, **kwargs)
        if _enabled:
            _record("socket", self, "socket", depth=2)


def _traced_thread_start(self: threading.Thread, *a: Any, **kw: Any) -> Any:
    if _enabled:
        _record("thread", self,
                f"thread {self.name!r}"
                f"{' daemon' if self.daemon else ''}", depth=2)
    return _saved["thread_start"](self, *a, **kw)


def _traced_popen_init(self: Any, *a: Any, **kw: Any) -> None:
    _saved["popen_init"](self, *a, **kw)
    if _enabled:
        args = a[0] if a else kw.get("args")
        _record("subprocess", self, f"Popen pid={self.pid} "
                f"argv={str(args)[:120]}", depth=2)


def _traced_ntf(*a: Any, **kw: Any) -> Any:
    f = _saved["ntf"](*a, **kw)
    if _enabled:
        _record("tempfile", f, f"NamedTemporaryFile {f.name}", depth=2)
    return f


def _traced_mkstemp(*a: Any, **kw: Any) -> Any:
    fd, path = _saved["mkstemp"](*a, **kw)
    if _enabled:
        try:
            st = os.fstat(fd)
            _record("tempfile", path, f"mkstemp fd={fd} {path}", depth=2,
                    extra={"fd": fd, "stat": (st.st_dev, st.st_ino)})
        except OSError:
            pass
    return fd, path


# -- lifecycle --------------------------------------------------------------

def install() -> None:
    """Patch the creation vocabulary and start recording.  Idempotent.
    Originals are captured here (not at import) so the Thread hook
    chains with whatever racecheck already installed."""
    global _enabled
    if _enabled:
        return
    _saved["socket_cls"] = _socket_mod.socket
    _saved["thread_start"] = threading.Thread.start
    _saved["popen_init"] = _subprocess_mod.Popen.__init__
    _saved["ntf"] = _tempfile_mod.NamedTemporaryFile
    _saved["mkstemp"] = _tempfile_mod.mkstemp
    if _saved["socket_cls"] is not _TracedSocket:
        _socket_mod.socket = _TracedSocket       # type: ignore[misc]
    threading.Thread.start = _traced_thread_start  # type: ignore
    _subprocess_mod.Popen.__init__ = _traced_popen_init  # type: ignore
    _tempfile_mod.NamedTemporaryFile = _traced_ntf  # type: ignore
    _tempfile_mod.mkstemp = _traced_mkstemp      # type: ignore[assignment]
    _enabled = True


def uninstall() -> None:
    """Stop recording and restore every patched hook.  Idempotent."""
    global _enabled
    if not _enabled:
        return
    _enabled = False
    _socket_mod.socket = _saved["socket_cls"]    # type: ignore[misc]
    threading.Thread.start = _saved["thread_start"]  # type: ignore
    _subprocess_mod.Popen.__init__ = _saved["popen_init"]  # type: ignore
    _tempfile_mod.NamedTemporaryFile = _saved["ntf"]  # type: ignore
    _tempfile_mod.mkstemp = _saved["mkstemp"]    # type: ignore[assignment]
    _saved.clear()


def installed() -> bool:
    """True while leakcheck is actively recording creations."""
    return _enabled


def leaks() -> List[Dict[str, Any]]:
    """Every recorded resource that is STILL live right now, each with
    kind, detail and creation stack."""
    with _state_lock:
        recs = list(_records.values())
    return [{"kind": r["kind"], "detail": r["detail"], "site": r["site"]}
            for r in recs if _live(r)]


def reset() -> None:
    """Forget every recorded creation (test isolation)."""
    with _state_lock:
        _records.clear()
        for k in _KINDS:
            _created_count[k] = 0


def check() -> None:
    """Raise :class:`LeakError` when any recorded resource is still
    live; bumps ``dmlc_leaks_detected_total`` per leak by kind."""
    found = leaks()
    if not found:
        return
    from dmlc_core_tpu.base import metrics as _metrics

    if _metrics.enabled():
        c = _metrics.default_registry().counter(
            "leaks_detected_total",
            "live leaked resources found by leakcheck at drill exit, "
            "by resource kind (socket|thread|subprocess|tempfile)",
            labels=("kind",))
        for x in found:
            c.inc(1, kind=x["kind"])
    lines = [f"{x['kind']}: {x['detail']} created at {x['site']}"
             for x in found]
    raise LeakError(f"{len(found)} leaked resource(s): " + "; ".join(lines))


def write_report(path: str) -> Dict[str, Any]:
    """Archive the leak report as JSON (the drills' ``*_LEAKCHECK_OUT``
    artifact); returns the report dict."""
    import json

    with _state_lock:
        created = dict(_created_count)
    report = {"enabled": _enabled, "created": created, "leaks": leaks()}
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1)
        f.write("\n")
    return report


def env_enabled() -> bool:
    """The ``DMLC_LEAKCHECK`` import-time gate."""
    return os.environ.get("DMLC_LEAKCHECK", "0").lower() in (
        "1", "true", "on", "yes", "raise")
