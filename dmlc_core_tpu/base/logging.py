"""Logging and assertion layer.

Reference parity: ``include/dmlc/logging.h :: LOG(severity), CHECK*,
CHECK_NOTNULL, dmlc::Error, LogMessage/LogMessageFatal`` (SURVEY.md §2a).

Design notes (TPU-first, not a port):

* Fatal checks raise :class:`Error` (the reference's ``DMLC_LOG_FATAL_THROW=1``
  behaviour, which is what every DMLC consumer uses in practice).  There is no
  abort() mode — in a JAX world an exception that unwinds through the Python
  frame is strictly more useful than a core dump.
* ``LOG`` routes through a standard :mod:`logging` logger named ``"dmlc"`` so
  host applications can redirect/format it (the reference's
  ``DMLC_LOG_CUSTOMIZE`` hook generalised).
* CHECK macros become functions.  They must NEVER be called inside a
  ``jax.jit``-traced region with traced values — they are host-side control
  checks.  For on-device assertions use ``dmlc_core_tpu.ops`` checkify
  helpers.
"""

from __future__ import annotations

import logging as _pylogging
import sys
import traceback
from typing import Any, NoReturn, Optional, TypeVar

__all__ = [
    "Error",
    "LOG",
    "LogMessage",
    "CHECK",
    "CHECK_EQ",
    "CHECK_NE",
    "CHECK_LT",
    "CHECK_GT",
    "CHECK_LE",
    "CHECK_GE",
    "CHECK_NOTNULL",
    "log_fatal",
    "set_log_level",
    "get_logger",
]

T = TypeVar("T")


class Error(RuntimeError):
    """Exception thrown by fatal checks.

    Reference parity: ``dmlc::Error`` (include/dmlc/logging.h).  Carries an
    optional captured stack trace like ``DMLC_LOG_STACK_TRACE``.
    """

    def __init__(self, message: str, stack_trace: Optional[str] = None):
        self.stack_trace = stack_trace
        super().__init__(message)


_logger = _pylogging.getLogger("dmlc")
if not _logger.handlers:  # default handler: stderr, glog-ish format
    _handler = _pylogging.StreamHandler(sys.stderr)
    _handler.setFormatter(
        _pylogging.Formatter("[%(asctime)s] %(levelname)s %(filename)s:%(lineno)d: %(message)s")
    )
    _logger.addHandler(_handler)
    _logger.setLevel(_pylogging.INFO)

_LEVELS = {
    "DEBUG": _pylogging.DEBUG,
    "INFO": _pylogging.INFO,
    "WARNING": _pylogging.WARNING,
    "ERROR": _pylogging.ERROR,
    "FATAL": _pylogging.CRITICAL,
}


def get_logger() -> _pylogging.Logger:
    """Return the shared ``"dmlc"`` logger (the DMLC_LOG_CUSTOMIZE hook)."""
    return _logger


def set_log_level(level: str) -> None:
    """Set the minimum severity, one of DEBUG/INFO/WARNING/ERROR/FATAL."""
    _logger.setLevel(_LEVELS[level.upper()])


def _capture_stack(skip: int = 2) -> str:
    return "".join(traceback.format_stack()[: -skip or None])


def log_fatal(message: str) -> NoReturn:
    """Log at FATAL severity and raise :class:`Error`.

    Reference parity: ``dmlc::LogMessageFatal`` with ``DMLC_LOG_FATAL_THROW``.
    """
    stack = _capture_stack()
    _logger.critical(message, stacklevel=3)
    raise Error(message, stack_trace=stack)


def LOG(severity: str, message: str, *args: Any) -> None:
    """``LOG(INFO/WARNING/ERROR/FATAL, msg)``.  FATAL raises :class:`Error`."""
    severity = severity.upper()
    if severity == "FATAL":
        log_fatal(message % args if args else message)
    if severity not in _LEVELS:
        raise Error(f"unknown log severity {severity!r}; valid: {sorted(_LEVELS)}")
    _logger.log(_LEVELS[severity], message, *args, stacklevel=2)


class LogMessage:
    """Stream-style log message, for code that prefers the C++ idiom::

        with LogMessage("INFO") as log:
            log << "read " << n << " records"
    """

    def __init__(self, severity: str = "INFO"):
        self._severity = severity.upper()
        self._parts: list[str] = []

    def __lshift__(self, other: Any) -> "LogMessage":
        self._parts.append(str(other))
        return self

    def __enter__(self) -> "LogMessage":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc_type is None:
            LOG(self._severity, "".join(self._parts))


def _fail(op: str, lhs: Any, rhs: Any, msg: str) -> NoReturn:
    detail = f"Check failed: {lhs!r} {op} {rhs!r}"
    if msg:
        detail += f": {msg}"
    log_fatal(detail)


def CHECK(cond: Any, msg: str = "") -> None:
    """Fatal unless ``cond`` is truthy.  Reference: ``CHECK(x)``."""
    if not cond:
        log_fatal(f"Check failed: {msg or cond!r}")


def CHECK_EQ(lhs: Any, rhs: Any, msg: str = "") -> None:
    """Fatal unless ``lhs == rhs`` (reference ``CHECK_EQ``); the failure
    message prints both operands."""
    if not (lhs == rhs):
        _fail("==", lhs, rhs, msg)


def CHECK_NE(lhs: Any, rhs: Any, msg: str = "") -> None:
    """Fatal unless ``lhs != rhs`` (reference ``CHECK_NE``)."""
    if not (lhs != rhs):
        _fail("!=", lhs, rhs, msg)


def CHECK_LT(lhs: Any, rhs: Any, msg: str = "") -> None:
    """Fatal unless ``lhs < rhs`` (reference ``CHECK_LT``)."""
    if not (lhs < rhs):
        _fail("<", lhs, rhs, msg)


def CHECK_GT(lhs: Any, rhs: Any, msg: str = "") -> None:
    """Fatal unless ``lhs > rhs`` (reference ``CHECK_GT``)."""
    if not (lhs > rhs):
        _fail(">", lhs, rhs, msg)


def CHECK_LE(lhs: Any, rhs: Any, msg: str = "") -> None:
    """Fatal unless ``lhs <= rhs`` (reference ``CHECK_LE``)."""
    if not (lhs <= rhs):
        _fail("<=", lhs, rhs, msg)


def CHECK_GE(lhs: Any, rhs: Any, msg: str = "") -> None:
    """Fatal unless ``lhs >= rhs`` (reference ``CHECK_GE``)."""
    if not (lhs >= rhs):
        _fail(">=", lhs, rhs, msg)


def CHECK_NOTNULL(value: Optional[T], msg: str = "") -> T:
    """Fatal if ``value`` is None; returns it otherwise (chainable like C++)."""
    if value is None:
        log_fatal(f"Check notnull failed: {msg}")
    return value
