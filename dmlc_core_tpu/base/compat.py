"""JAX version compatibility shims.

The substrate targets current JAX (``jax.shard_map`` with the
``check_vma`` kwarg), but must still import — and run its tier-1 suite —
on older runtimes where ``shard_map`` lives in ``jax.experimental`` and
the replication check is spelled ``check_rep``.  Every in-package
``shard_map`` consumer imports it from here instead of from ``jax``, so
the version split is decided exactly once.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

__all__ = ["shard_map", "donation_safe", "donate_argnums", "axis_size"]

try:
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
    _KWARG_RENAME = None
except ImportError:  # pre-0.6 JAX: experimental home, check_rep spelling
    from jax.experimental.shard_map import shard_map as _shard_map
    _KWARG_RENAME = ("check_vma", "check_rep")


def donation_safe() -> bool:
    """Whether buffer donation is trusted on this runtime.

    On the legacy (experimental-shard_map) JAX/CPU combination, donated
    inputs whose buffers alias a jit output are intermittently handed
    back to the allocator while the aliased output is still live —
    later dispatches then scribble over the head of a buffer the caller
    still reads (observed as denormal garbage in the first vector lane
    of boosted margins, ~1-in-6 runs of the external-memory suite).
    Donation is a memory optimization, never a semantic one, so the
    legacy runtime simply runs without it.
    """
    return _KWARG_RENAME is None


def donate_argnums(*nums: int):
    """``donate_argnums=compat.donate_argnums(3)`` — the requested
    donation on runtimes where it is safe, no donation elsewhere."""
    return nums if donation_safe() else ()


def axis_size(axis_name: str) -> int:
    """``jax.lax.axis_size`` where it exists; the classic
    ``psum(1, axis)`` constant fold (static under tracing) on legacy
    runtimes that predate it."""
    import jax

    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:
        return jax.lax.psum(1, axis_name)


def shard_map(f: Optional[Callable[..., Any]] = None, **kwargs: Any):
    """Call through to the runtime's shard_map, translating kwargs.

    Usable both directly (``shard_map(fn, mesh=..., ...)``) and via
    ``partial(shard_map, mesh=..., ...)`` as a decorator, matching the
    real API's two spellings.
    """
    if _KWARG_RENAME is not None and _KWARG_RENAME[0] in kwargs:
        kwargs[_KWARG_RENAME[1]] = kwargs.pop(_KWARG_RENAME[0])
    if f is None:
        return lambda fn: _shard_map(fn, **kwargs)
    return _shard_map(f, **kwargs)
