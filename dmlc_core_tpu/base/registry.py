"""Global factory registries.

Reference parity: ``include/dmlc/registry.h :: Registry<EntryType>::Get()
->Register(name)/Find(name)/ListAllNames(), FunctionRegEntryBase,
DMLC_REGISTRY_ENABLE/REGISTER`` (SURVEY.md §2a).

This is how parsers, filesystems, input splits, ops and models self-register
by name.  Python needs none of the C++ link-tag tricks (`DMLC_REGISTRY_FILE_
TAG` existed to defeat static-library dead-stripping); import of the defining
module is the registration event.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Generic, List, Optional, TypeVar

from dmlc_core_tpu.base.logging import log_fatal

__all__ = ["Registry", "FunctionRegEntry"]

E = TypeVar("E")


class FunctionRegEntry:
    """A registry entry carrying a factory plus self-documentation.

    Reference parity: ``dmlc::FunctionRegEntryBase`` — ``set_body``,
    ``describe``, ``add_argument``, ``set_return_type``.
    """

    def __init__(self, name: str):
        self.name = name
        self.body: Optional[Callable[..., Any]] = None
        self.description: str = ""
        self.arguments: List[Dict[str, str]] = []
        self.return_type: str = ""

    def set_body(self, fn: Callable[..., Any]) -> "FunctionRegEntry":
        self.body = fn
        return self

    def describe(self, text: str) -> "FunctionRegEntry":
        self.description = text
        return self

    def add_argument(self, name: str, type_str: str, description: str) -> "FunctionRegEntry":
        self.arguments.append({"name": name, "type": type_str, "description": description})
        return self

    def set_return_type(self, type_str: str) -> "FunctionRegEntry":
        self.return_type = type_str
        return self

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        if self.body is None:
            log_fatal(f"Registry entry {self.name!r} has no body")
        return self.body(*args, **kwargs)


class Registry(Generic[E]):
    """A named global registry of factories/entries.

    Usage (mirrors ``DMLC_REGISTRY_ENABLE`` + ``DMLC_REGISTRY_REGISTER``)::

        parsers = Registry("data_parser")

        @parsers.register("libsvm")
        def _make_libsvm(...): ...

        parsers.find("libsvm")          # -> entry (None if absent)
        parsers["libsvm"]               # -> entry (fatal if absent)
        parsers.list_all_names()
    """

    _instances: Dict[str, "Registry[Any]"] = {}

    def __new__(cls, kind: str) -> "Registry[E]":
        # Per-kind singleton: Registry("x") and Registry.get("x") are the
        # same object, matching the C++ Registry<Entry>::Get() contract.
        inst = cls._instances.get(kind)
        if inst is None:
            inst = super().__new__(cls)
            inst.kind = kind
            inst._entries = {}
            cls._instances[kind] = inst
        return inst  # type: ignore[return-value]

    def __init__(self, kind: str):
        pass  # state set once in __new__; re-construction returns the singleton

    # -- the Registry<Entry>::Get() singleton pattern --------------------
    @classmethod
    def get(cls, kind: str) -> "Registry[Any]":
        """Return (creating if needed) the global registry named ``kind``."""
        return cls(kind)

    # -- registration ----------------------------------------------------
    def register(self, name: str, entry: Optional[E] = None, override: bool = False):
        """Register ``entry`` under ``name``.

        With no ``entry``, returns a decorator: plain functions are wrapped
        in a :class:`FunctionRegEntry` (carrying ``__doc__`` as the
        description); classes and existing FunctionRegEntry objects are
        registered as themselves (a class's docs live on the class).
        """
        if entry is not None:
            self._register(name, entry, override)
            return entry

        def deco(obj: Any) -> Any:
            if isinstance(obj, FunctionRegEntry) or isinstance(obj, type):
                # entries and classes register as themselves; plain
                # functions get wrapped so they carry docs/arguments
                self._register(name, obj, override)
            else:
                e = FunctionRegEntry(name).set_body(obj)
                if getattr(obj, "__doc__", None):
                    e.describe(obj.__doc__)
                self._register(name, e, override)
            return obj

        return deco

    def _register(self, name: str, entry: Any, override: bool) -> None:
        if name in self._entries and not override:
            log_fatal(f"{self.kind} registry: name {name!r} already registered")
        self._entries[name] = entry

    # -- lookup ----------------------------------------------------------
    def find(self, name: str) -> Optional[E]:
        """Return the entry or None.  Reference: ``Registry::Find``."""
        return self._entries.get(name)

    def __getitem__(self, name: str) -> E:
        entry = self.find(name)
        if entry is None:
            log_fatal(
                f"{self.kind} registry: unknown entry {name!r}. "
                f"Known: {sorted(self._entries)}"
            )
        return entry  # type: ignore[return-value]

    def __contains__(self, name: str) -> bool:
        return name in self._entries

    def list_all_names(self) -> List[str]:
        """Reference: ``Registry::ListAllNames``."""
        return sorted(self._entries)

    def remove(self, name: str) -> None:
        """Unregister (mostly for tests)."""
        self._entries.pop(name, None)
