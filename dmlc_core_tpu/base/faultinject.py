"""Deterministic, seed-driven fault injection — failure as a testable input.

Nothing in a distributed stack is trustworthy until it has been watched
surviving faults; this module makes faults a first-class, reproducible
input instead of an ops anecdote.  Production code declares **named
injection points** (``http``, ``stream``, ``checkpoint``, ``iter``,
``serve``…) by calling :func:`check` at the place a real-world failure
would land; with no spec configured that call is a few dict/env lookups
and returns ``None`` — the no-chaos path stays the production path.

Spec grammar (``DMLC_FAULT_INJECT`` or :class:`inject`)::

    spec  := rule ("," rule)*
    rule  := point ":" kind ["=" value] (":" opt)*
    opt   := "p=" float        # fire probability per check (default 1)
           | "n=" int          # max fires for this rule (default unlimited)
           | "after=" int      # skip the first k checks (default 0)
           | "at=" float       # eligible once >= at seconds have elapsed
           | "every=" float    # wave trigger: at most one draw per
                               #   every-second wave (wave k spans
                               #   [at + k*every, at + (k+1)*every))

Examples::

    DMLC_FAULT_INJECT="http:error=503:p=0.3,stream:truncate:p=0.1"
    DMLC_FAULT_INJECT="checkpoint:kill:after=1"   # 2nd checkpoint dies
    DMLC_FAULT_INJECT="worker:kill:after=7"       # SIGKILL at round 8
    DMLC_FAULT_INJECT="allreduce:abort:after=30"  # void the round
    DMLC_FAULT_INJECT="prodsim_replica:kill:at=5:n=1"   # T+5s, once
    DMLC_FAULT_INJECT="launch_host:wave=0.3:at=10:n=1"  # spot wave T+10s
    with faultinject.inject("serve:error=503:p=0.5:n=20"): ...

Wall-clock triggers (the **chaos scheduler**): ``at=`` makes a rule
eligible only once the schedule clock has advanced past that many
seconds since :func:`configure` (re)anchored the epoch; ``every=``
partitions elapsed time into waves and allows at most ONE probability
draw per wave, so ``launch_host:wave=0.3:every=30:p=0.5`` models a
spot-preemption front that may (seed-deterministically) sweep the
cluster every 30 s.  The schedule clock defaults to
``time.monotonic`` and is injectable via :func:`set_clock`, so tests
drive waves with a fake clock and the whole schedule — which waves
fire, in which order — is a pure function of (spec, seed, clock),
asserted in ``tests/test_resilience.py``.

Kinds are interpreted by the injection SITE (the injector only decides
*whether* to fire): ``error=<status>`` fabricates an HTTP failure,
``reset`` a connection reset, ``truncate`` a short ranged-read body,
``kill`` a SIGKILL of the current process (mid-checkpoint at the
``checkpoint`` point, mid-boost at the ``worker`` point — the elastic
chaos drill's trigger — or mid-collective at ``allreduce``), ``abort``
an in-flight abort (IOError mid-checkpoint; at ``allreduce`` it voids
the epoch on EVERY worker — the all-or-nothing round drill), ``corrupt``
a post-commit byte flip, plain ``error`` a producer exception.  The
``worker`` point is checked once per boosting round and at each commit,
so ``worker:kill:after=N`` dies at a deterministic, seed-reproducible
round.  See ``doc/robustness.md`` for the per-point table.

Determinism: each rule draws from its own ``random.Random`` seeded by
``DMLC_FAULT_SEED`` (default 1234) and the rule's index, so a given
call sequence fires the identical faults run after run.  Every fire is
counted in ``dmlc_faults_injected_total{point,kind}`` — a chaos run
that injected nothing is a configuration bug, and the counter is the
evidence either way.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from dmlc_core_tpu.base import metrics as _metrics

__all__ = ["Fault", "check", "configure", "inject", "active",
           "fired_total", "stats", "rules", "set_clock"]

_ENV_SPEC = "DMLC_FAULT_INJECT"
_ENV_SEED = "DMLC_FAULT_SEED"
_DEFAULT_SEED = 1234


class Fault:
    """One fired fault: the injection point, the kind, and an optional
    value (``error=503`` → kind ``"error"``, value ``"503"``)."""

    __slots__ = ("point", "kind", "value")

    def __init__(self, point: str, kind: str, value: Optional[str] = None):
        self.point = point
        self.kind = kind
        self.value = value

    def int_value(self, default: int) -> int:
        """The value as an int (``default`` when absent/garbled)."""
        try:
            return int(self.value) if self.value else default
        except ValueError:
            return default

    def __repr__(self) -> str:
        v = f"={self.value}" if self.value is not None else ""
        return f"Fault({self.point}:{self.kind}{v})"


class _Rule:
    __slots__ = ("point", "kind", "value", "p", "n", "after",
                 "at", "every", "last_wave", "checked", "fires", "rng")

    def __init__(self, point: str, kind: str, value: Optional[str],
                 p: float, n: Optional[int], after: int, seed: int,
                 at: Optional[float] = None,
                 every: Optional[float] = None):
        self.point = point
        self.kind = kind
        self.value = value
        self.p = p
        self.n = n
        self.after = after
        self.at = at
        self.every = every
        self.last_wave = -1       # highest wave index already drawn for
        self.checked = 0
        self.fires = 0
        self.rng = random.Random(seed)


def _parse(spec: str, seed: int) -> List[_Rule]:
    rules: List[_Rule] = []
    for idx, raw in enumerate(s for s in spec.split(",") if s.strip()):
        fields = [f.strip() for f in raw.strip().split(":")]
        if len(fields) < 2:
            raise ValueError(
                f"fault spec rule {raw!r}: want point:kind[...], "
                f"see doc/robustness.md")
        point = fields[0]
        kind, value = fields[1], None
        if "=" in kind:
            kind, value = kind.split("=", 1)
        p, n, after, at, every = 1.0, None, 0, None, None
        for opt in fields[2:]:
            k, _, v = opt.partition("=")
            if k == "p":
                p = float(v)
            elif k == "n":
                n = int(v)
            elif k == "after":
                after = int(v)
            elif k == "at":
                at = float(v)
                if at < 0:
                    raise ValueError(
                        f"fault spec rule {raw!r}: at= must be >= 0")
            elif k == "every":
                every = float(v)
                if every <= 0:
                    raise ValueError(
                        f"fault spec rule {raw!r}: every= must be > 0")
            else:
                raise ValueError(
                    f"fault spec rule {raw!r}: unknown option {opt!r}")
        rules.append(_Rule(point, kind, value, p, n, after,
                           seed=seed * 1000003 + idx, at=at, every=every))
    return rules


_LOCK = threading.Lock()
_RULES: List[_Rule] = []
_CONFIGURED_SPEC: Optional[str] = None  # spec the rules were parsed from
_PINNED = 0                             # >0: inject() overrides the env
_CLOCK: Callable[[], float] = time.monotonic  # schedule clock (injectable)
_EPOCH = 0.0                            # clock value when configure() ran
_FM = None


def set_clock(clock: Optional[Callable[[], float]] = None) -> None:
    """Install the schedule clock ``at=``/``every=`` rules are timed
    against (``None`` restores ``time.monotonic``).  Call *before*
    :func:`configure`/:class:`inject` — the epoch is anchored there."""
    global _CLOCK
    with _LOCK:
        _CLOCK = clock if clock is not None else time.monotonic


def _fi_metrics():
    global _FM
    if _FM is None:
        _FM = _metrics.default_registry().counter(
            "faults_injected_total",
            "faults fired by the deterministic injector",
            labels=("point", "kind"))
    return _FM


def configure(spec: Optional[str] = None, seed: Optional[int] = None) -> None:
    """(Re)parse the fault spec — ``None`` reads ``DMLC_FAULT_INJECT`` /
    ``DMLC_FAULT_SEED``.  Resets per-rule counters and RNG streams."""
    global _RULES, _CONFIGURED_SPEC
    global _EPOCH
    spec = os.environ.get(_ENV_SPEC, "") if spec is None else spec
    if seed is None:
        try:
            seed = int(os.environ.get(_ENV_SEED, "") or _DEFAULT_SEED)
        except ValueError:
            seed = _DEFAULT_SEED
    with _LOCK:
        _RULES = _parse(spec, seed) if spec else []
        _CONFIGURED_SPEC = spec
        _EPOCH = _CLOCK()       # anchor the at=/every= schedule epoch


def _ensure_current() -> None:
    """Track env changes (monkeypatched tests, subprocess inheritance)
    unless an :class:`inject` context has pinned an explicit spec."""
    if _PINNED:
        return
    env_spec = os.environ.get(_ENV_SPEC, "")
    if env_spec != _CONFIGURED_SPEC:
        configure(env_spec)


def active() -> bool:
    """Is any fault rule live right now?"""
    _ensure_current()
    return bool(_RULES)


def check(point: str, ctx: str = "") -> Optional[Fault]:
    """The injection-point call: returns a :class:`Fault` when a rule
    for ``point`` fires (counted), else ``None``.  ``ctx`` is a free
    hint (URL, iter name) used only for logging by the site."""
    _ensure_current()
    if not _RULES:
        return None
    with _LOCK:
        elapsed = None          # schedule clock read at most once/check
        for rule in _RULES:
            if rule.point != point:
                continue
            rule.checked += 1
            if rule.checked <= rule.after:
                continue
            if rule.n is not None and rule.fires >= rule.n:
                continue
            if rule.at is not None or rule.every is not None:
                if elapsed is None:
                    elapsed = _CLOCK() - _EPOCH
                if rule.at is not None and elapsed < rule.at:
                    continue
                if rule.every is not None:
                    wave = int((elapsed - (rule.at or 0.0)) // rule.every)
                    if wave <= rule.last_wave:
                        continue
                    # one probability draw per wave, hit or miss — the
                    # fired-wave set is then a pure function of the seed
                    rule.last_wave = wave
            if rule.p < 1.0 and rule.rng.random() >= rule.p:
                continue
            rule.fires += 1
            fault = Fault(point, rule.kind, rule.value)
            break
        else:
            return None
    if _metrics.enabled():
        _fi_metrics().inc(1, point=point, kind=fault.kind)
    return fault


def fired_total() -> int:
    """Total faults fired by the CURRENT rule set (process-local rule
    counters; the cross-run evidence is the metrics counter)."""
    with _LOCK:
        return sum(r.fires for r in _RULES)


class inject:
    """Context manager for tests: pin a spec (and seed) for the block,
    restoring the previous configuration — env-driven or an enclosing
    ``inject`` — on exit.

    ::

        with faultinject.inject("http:error=503:p=1:n=2"):
            ...  # exactly the first two http checks fire
    """

    def __init__(self, spec: str, seed: int = _DEFAULT_SEED):
        self._spec = spec
        self._seed = seed
        self._saved: Optional[List[_Rule]] = None
        self._saved_spec: Optional[str] = None
        self._saved_epoch = 0.0

    def __enter__(self) -> "inject":
        global _PINNED
        with _LOCK:
            self._saved = _RULES
            self._saved_spec = _CONFIGURED_SPEC
            self._saved_epoch = _EPOCH
        configure(self._spec, self._seed)
        with _LOCK:
            _PINNED += 1
        return self

    def __exit__(self, *exc: Any) -> None:
        global _PINNED, _RULES, _CONFIGURED_SPEC, _EPOCH
        with _LOCK:
            _PINNED -= 1
            _RULES = self._saved or []
            _CONFIGURED_SPEC = self._saved_spec
            _EPOCH = self._saved_epoch


def stats() -> Dict[str, int]:
    """Per-rule fire counts keyed ``point:kind`` (diagnostics)."""
    with _LOCK:
        out: Dict[str, int] = {}
        for r in _RULES:
            key = f"{r.point}:{r.kind}"
            out[key] = out.get(key, 0) + r.fires
        return out


def rules() -> List[Dict[str, Any]]:
    """Full per-rule view — parsed grammar fields plus live counters —
    in spec order.  The chaos drills assert the schedule round-trips
    (grammar in == rules out) and that every scheduled rule fired."""
    with _LOCK:
        return [{"point": r.point, "kind": r.kind, "value": r.value,
                 "p": r.p, "n": r.n, "after": r.after, "at": r.at,
                 "every": r.every, "checked": r.checked,
                 "fires": r.fires, "last_wave": r.last_wave}
                for r in _RULES]
