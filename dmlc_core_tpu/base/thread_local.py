"""Per-thread singleton store.

Reference parity: ``include/dmlc/thread_local.h :: ThreadLocalStore<T>``
(SURVEY.md §2a) — lazily constructs one instance of a type per thread and
keeps a registry so instances can be enumerated/cleared (the reference
uses this for per-thread scratch buffers and error strings).
``threading.local`` alone loses the registry, so this keeps one — keyed
weakly by the Thread object and pruned of dead threads, so a long-lived
process spawning short-lived workers doesn't pin their scratch instances
forever, and OS thread-id reuse can't alias entries.
"""

from __future__ import annotations

import threading
import weakref
from typing import Callable, Generic, List, Tuple, TypeVar

__all__ = ["ThreadLocalStore"]

T = TypeVar("T")


class ThreadLocalStore(Generic[T]):
    """``store.get()`` → this thread's lazily-created instance.

    >>> store = ThreadLocalStore(list)
    >>> store.get() is store.get()        # same object within a thread
    True
    """

    def __init__(self, factory: Callable[[], T]):
        self._factory = factory
        self._local = threading.local()
        self._lock = threading.Lock()
        self._registry: "weakref.WeakKeyDictionary[threading.Thread, T]" = (
            weakref.WeakKeyDictionary()
        )

    def get(self) -> T:
        try:
            return self._local.value
        except AttributeError:
            value = self._factory()
            self._local.value = value
            with self._lock:
                self._registry[threading.current_thread()] = value
            return value

    def instances(self) -> List[Tuple[str, T]]:
        """(thread name, instance) for every *live* thread that called get()."""
        with self._lock:
            return [
                (th.name, value)
                for th, value in list(self._registry.items())
                if th.is_alive()
            ]

    def clear(self) -> None:
        """Drop the registry (existing threads re-create on next get())."""
        with self._lock:
            self._registry = weakref.WeakKeyDictionary()
        self._local = threading.local()
