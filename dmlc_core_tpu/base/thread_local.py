"""Per-thread singleton store.

Reference parity: ``include/dmlc/thread_local.h :: ThreadLocalStore<T>``
(SURVEY.md §2a) — lazily constructs one instance of a type per thread and
keeps a registry so instances can be enumerated/cleared (the reference
uses this for per-thread scratch buffers and error strings).
``threading.local`` alone loses the registry, so this keeps one.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Generic, List, Tuple, TypeVar

__all__ = ["ThreadLocalStore"]

T = TypeVar("T")


class ThreadLocalStore(Generic[T]):
    """``store.get()`` → this thread's lazily-created instance.

    >>> store = ThreadLocalStore(list)
    >>> store.get() is store.get()        # same object within a thread
    True
    """

    def __init__(self, factory: Callable[[], T]):
        self._factory = factory
        self._local = threading.local()
        self._lock = threading.Lock()
        self._registry: Dict[int, Tuple[str, T]] = {}

    def get(self) -> T:
        try:
            return self._local.value
        except AttributeError:
            value = self._factory()
            self._local.value = value
            th = threading.current_thread()
            with self._lock:
                self._registry[th.ident or id(th)] = (th.name, value)
            return value

    def instances(self) -> List[Tuple[str, T]]:
        """(thread name, instance) for every thread that called get()."""
        with self._lock:
            return list(self._registry.values())

    def clear(self) -> None:
        """Drop the registry (existing threads re-create on next get())."""
        with self._lock:
            self._registry.clear()
        self._local = threading.local()
