"""Dynamic lock-order verifier (``DMLC_LOCKCHECK=1``).

The runtime counterpart of dmlcheck's static ``lock-discipline`` pass:
where the AST pass proves accesses stay *behind* locks, this module
proves the locks themselves are acquired in a consistent *order* across
threads — the property whose violation is a deadlock, which no amount
of single-threaded testing surfaces.

How: :func:`install` replaces ``threading.Lock`` / ``threading.RLock``
with factories returning a traced wrapper (locks created *before*
install are untouched).  Each wrapper records its creation site
(``file:line``) as its identity — one node per *site*, so every
``ConcurrentBlockingQueue`` instance maps to the same node and an
ordering observed on one instance constrains all of them (the
cross-instance generalization is what makes short tests predictive).
On every acquisition, an edge ``held-site -> acquired-site`` is added
to a process-wide digraph; a new edge that closes a directed cycle is a
lock-order violation, recorded (and raised from :func:`check`).
Self-edges (site -> itself) are skipped: two instances from one site
have no static order, and flagging them would condemn every per-series
metric lock.

Validation hook: the chaos-soak test installs this around its
train+serve+faults workload and asserts :func:`violations` stays empty
— and ``DMLC_LOCKCHECK=1`` turns it on for any process at import
(``dmlc_core_tpu/__init__``).  Condition objects built on a traced lock
participate automatically (waits release and reacquire through the
wrapper).
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Any, Dict, List, Optional, Set, Tuple

__all__ = ["LockOrderError", "install", "uninstall", "installed",
           "violations", "reset", "check", "add_listener",
           "remove_listener"]


class LockOrderError(RuntimeError):
    """A cross-thread lock-order cycle was observed."""


_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock

#: guards the graph; a RAW lock so the verifier never traces itself
_graph_lock = _ORIG_LOCK()
_edges: Dict[str, Set[str]] = {}
#: (edge, thread) examples for reporting
_edge_info: Dict[Tuple[str, str], str] = {}
_violations: List[str] = []
_seen_cycles: Set[frozenset] = set()
_installed = False

#: observers of traced-lock transitions (``base/racecheck`` layers its
#: vector clocks on these). Protocol: ``on_acquire(lock, site)`` fires
#: AFTER the underlying acquire succeeds, ``on_release(lock, site)``
#: fires BEFORE the underlying release — so a happens-before listener
#: publishes the holder's state before any other thread can acquire.
_listeners: List[Any] = []

_tls = threading.local()


def add_listener(listener: Any) -> None:
    """Register a traced-lock observer (see ``_listeners``)."""
    if listener not in _listeners:
        _listeners.append(listener)


def remove_listener(listener: Any) -> None:
    """Remove a previously registered observer (no-op if absent)."""
    if listener in _listeners:
        _listeners.remove(listener)


def _held() -> List[str]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = []
        _tls.held = h
    return h


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS: a path src -> ... -> dst in the edge graph, or None."""
    stack = [(src, [src])]
    visited = {src}
    while stack:
        node, path = stack.pop()
        for nxt in _edges.get(node, ()):
            if nxt == dst:
                return path + [dst]
            if nxt not in visited:
                visited.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _note_acquire(site: str) -> None:
    held = _held()
    if held:
        tname = threading.current_thread().name
        with _graph_lock:
            for h in set(held):
                if h == site or site in _edges.get(h, ()):
                    continue
                # adding h -> site: a pre-existing site -> ... -> h path
                # means both orders have now been observed — a cycle
                path = _find_path(site, h)
                _edges.setdefault(h, set()).add(site)
                _edge_info[(h, site)] = tname
                if path is not None:
                    cyc = path + [site]
                    key = frozenset(cyc)
                    if key not in _seen_cycles:
                        _seen_cycles.add(key)
                        legs = " -> ".join(cyc)
                        owners = ", ".join(
                            f"{a}->{b} on {_edge_info.get((a, b), '?')}"
                            for a, b in zip(cyc, cyc[1:]))
                        _violations.append(
                            f"lock-order cycle: {legs} (edges: {owners})")
    held.append(site)


def _note_release(site: str) -> None:
    held = _held()
    # remove the most recent acquisition of this site (LIFO typical,
    # but out-of-order release is legal for raw acquire/release)
    for i in range(len(held) - 1, -1, -1):
        if held[i] == site:
            del held[i]
            return


class _TracedLock:
    """Wraps one plain Lock; quacks enough for ``with``, Condition's
    plain-lock fallback, and raw acquire/release call sites.

    NOTE: ``__getattr__`` delegates unknown attributes to the inner
    lock, so ``hasattr(lock, '_release_save')`` stays False here (the
    inner plain lock has none) and Condition takes its acquire/release
    fallback — which routes through the traced methods."""

    __slots__ = ("_inner", "_site")

    def __init__(self, inner: Any, site: str) -> None:
        self._inner = inner
        self._site = site

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _note_acquire(self._site)
            for lst in _listeners:
                lst.on_acquire(self, self._site)
        return ok

    def release(self) -> None:
        for lst in _listeners:
            lst.on_release(self, self._site)
        self._inner.release()
        _note_release(self._site)

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<TracedLock {self._site} {self._inner!r}>"

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


class _TracedRLock(_TracedLock):
    """RLock variant: defines the Condition protocol ON THE CLASS so
    Condition binds the traced versions (``__getattr__`` delegation
    would hand it the inner RLock's methods and waits would release
    invisibly)."""

    __slots__ = ()

    def _release_save(self) -> Any:
        # Condition.wait drops the monitor: that IS a release for
        # happens-before purposes, so listeners fire first (publish,
        # then let waiters in)
        for lst in _listeners:
            lst.on_release(self, self._site)
        state = self._inner._release_save()
        # a reentrant owner held this site k times; wait() drops them all
        held = _held()
        k = 0
        for i in range(len(held) - 1, -1, -1):
            if held[i] == self._site:
                del held[i]
                k += 1
        return (state, k)

    def _acquire_restore(self, state: Any) -> None:
        inner_state, k = state
        self._inner._acquire_restore(inner_state)
        held = _held()
        held.extend([self._site] * k)
        for lst in _listeners:
            lst.on_acquire(self, self._site)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


def _site_of_caller() -> str:
    f = sys._getframe(2)
    fn = f.f_code.co_filename
    # repo-relative where possible: stable across checkouts
    for marker in ("dmlc_core_tpu", "tests", "scripts"):
        idx = fn.find(os.sep + marker + os.sep)
        if idx >= 0:
            fn = fn[idx + 1:]
            break
    return f"{fn}:{f.f_lineno}"


def _lock_factory() -> _TracedLock:
    return _TracedLock(_ORIG_LOCK(), _site_of_caller())


def _rlock_factory() -> _TracedRLock:
    return _TracedRLock(_ORIG_RLOCK(), _site_of_caller())


def install() -> None:
    """Start tracing: locks created from here on are order-checked.
    Idempotent."""
    global _installed
    if _installed:
        return
    threading.Lock = _lock_factory          # type: ignore[assignment]
    threading.RLock = _rlock_factory        # type: ignore[assignment]
    _installed = True


def uninstall() -> None:
    """Stop tracing (existing traced locks keep working — they only
    stop growing the graph once released and re-created)."""
    global _installed
    if not _installed:
        return
    threading.Lock = _ORIG_LOCK             # type: ignore[assignment]
    threading.RLock = _ORIG_RLOCK           # type: ignore[assignment]
    _installed = False


def installed() -> bool:
    """True while the factories are patched in."""
    return _installed


def violations() -> List[str]:
    """Every distinct lock-order cycle observed so far."""
    with _graph_lock:
        return list(_violations)


def reset() -> None:
    """Clear the graph and violation history (test isolation)."""
    with _graph_lock:
        _edges.clear()
        _edge_info.clear()
        _violations.clear()
        _seen_cycles.clear()


def check() -> None:
    """Raise :class:`LockOrderError` if any cycle was observed."""
    v = violations()
    if v:
        raise LockOrderError("; ".join(v))


def env_enabled() -> bool:
    """The ``DMLC_LOCKCHECK`` import-time gate."""
    return os.environ.get("DMLC_LOCKCHECK", "0").lower() in (
        "1", "true", "on", "yes", "raise")
