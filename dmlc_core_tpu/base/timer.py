"""Wall-clock timing.

Reference parity: ``include/dmlc/timer.h :: dmlc::GetTime()`` (SURVEY.md §2a),
extended with a ``Timer`` context manager and a device-aware
:func:`block_until_ready_time` helper, because on TPU the number you almost
always want is *device* step time (dispatch is async; naive wall-clock timing
measures nothing).
"""

from __future__ import annotations

import time
from typing import Any

__all__ = ["get_time", "Timer", "block_until_ready_time"]


def get_time() -> float:
    """Seconds since an arbitrary epoch, monotonic, high resolution."""
    return time.perf_counter()


class Timer:
    """``with Timer() as t: ...; t.elapsed`` — simple scoped timer."""

    def __init__(self) -> None:
        self.start: float = 0.0
        self.elapsed: float = 0.0

    def __enter__(self) -> "Timer":
        self.start = get_time()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.elapsed = get_time() - self.start


def block_until_ready_time(fn, *args, **kwargs) -> tuple[Any, float]:
    """Run ``fn`` and block on its jax outputs; return (result, seconds).

    The correct way to time a jitted step: async dispatch means wall-clock
    around the call alone under-reports.  Non-jax results pass through.
    """
    import jax

    t0 = get_time()
    out = fn(*args, **kwargs)
    out = jax.block_until_ready(out)
    return out, get_time() - t0
