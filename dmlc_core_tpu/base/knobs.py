"""Central registry of every ``DMLC_*`` environment knob.

The reference scatters ``dmlc::GetEnv<T>`` reads across subsystems and
documents them nowhere; after four PRs this substrate had grown ~40
``DMLC_*`` reads with exactly the same drift.  This module is the single
source of truth: every knob the codebase reads MUST be declared here
(name, default, one-line doc), and ``scripts/dmlcheck.py``'s
``knob-registry`` pass fails CI on any literal ``DMLC_*`` string in code
that has no entry — plus any entry that never shows up under ``doc/``
(``doc/configuration.md`` is generated from this registry by
``scripts/gen_api_docs.py`` and gated stale-vs-committed in CI).

Declaring a knob does not change how call sites read it (``os.environ``
/ :func:`~dmlc_core_tpu.base.parameter.get_env` stay as they are); the
registry is the contract layer, not a read path.  :func:`value` is
provided for new call sites that want the declared default applied
automatically.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional

__all__ = ["Knob", "declare", "get", "all_knobs", "names", "value"]


class Knob(NamedTuple):
    """One declared environment knob."""

    #: full environment-variable name (``DMLC_...``)
    name: str
    #: default the reading call site applies when the var is unset
    default: Any
    #: one-line description (becomes the doc/configuration.md table row)
    doc: str
    #: subsystem bucket for the generated doc table ordering
    group: str


_REGISTRY: Dict[str, Knob] = {}


def declare(name: str, default: Any, doc: str, group: str = "misc") -> Knob:
    """Register a knob; re-declaring with identical fields is a no-op,
    conflicting re-declaration raises (same discipline as the metrics
    registry)."""
    if not name.startswith("DMLC_"):
        raise ValueError(f"knob {name!r} must start with DMLC_")
    existing = _REGISTRY.get(name)
    k = Knob(name, default, doc, group)
    if existing is not None:
        if existing != k:
            raise ValueError(f"knob {name!r} re-declared with different "
                            f"fields: {existing} vs {k}")
        return existing
    _REGISTRY[name] = k
    return k


def get(name: str) -> Optional[Knob]:
    """Look up a declared knob (None when unknown)."""
    return _REGISTRY.get(name)


def names() -> List[str]:
    """All declared knob names, sorted."""
    return sorted(_REGISTRY)


def all_knobs() -> List[Knob]:
    """All declared knobs, sorted by (group, name) — the order the
    generated doc table uses."""
    return sorted(_REGISTRY.values(), key=lambda k: (k.group, k.name))


def value(name: str) -> Any:
    """Read a declared knob from the environment with its declared
    default applied (type inferred from the default, via
    :func:`~dmlc_core_tpu.base.parameter.get_env`)."""
    from dmlc_core_tpu.base.parameter import get_env

    k = _REGISTRY.get(name)
    if k is None:
        raise KeyError(f"knob {name!r} is not declared in base/knobs.py")
    return get_env(k.name, k.default)


# ---------------------------------------------------------------------------
# The declarations.  Grouped by subsystem; each ``doc`` line is exactly
# what doc/configuration.md renders.  Defaults mirror the reading call
# site — the knob-registry pass checks presence, the doc gate checks
# documentation, and drift between this default and the call site's is a
# review-visible diff in one place instead of a silent env archaeology.
# ---------------------------------------------------------------------------

# -- runtime / debugging ----------------------------------------------------
declare("DMLC_TPU_FORCE_CPU", "",
        "Force jax onto N host CPU devices before first backend init "
        "(tests/CI); empty disables.", "runtime")
declare("DMLC_TPU_NATIVE_LIB", "",
        "Explicit path to the native helper shared library (overrides "
        "the bundled lookup).", "runtime")
declare("DMLC_TPU_NATIVE_IO", "1",
        "0 disables the C fast paths (recordio/parsers/queues) in favor "
        "of pure-Python fallbacks.", "runtime")
declare("DMLC_TRACE", "0",
        "1 enables the process-wide event Tracer "
        "(utils/profiler.set_tracing).", "observability")
declare("DMLC_METRICS", "1",
        "0 disables the metrics registry: instruments become no-ops "
        "(base/metrics).", "observability")
declare("DMLC_METRICS_GBT_PHASES", "0",
        "1 adds per-phase hist/split/leaf/apply timing in the external "
        "GBT engine (adds device syncs).", "observability")
declare("DMLC_DRYRUN_NESTED", "0",
        "Internal recursion guard for the multichip dryrun harness "
        "(__graft_entry__); not user-facing.", "runtime")
declare("DMLC_LOCKCHECK", "0",
        "1 installs the dynamic lock-order verifier at import: lock "
        "acquisitions build a cross-thread order graph and cycles are "
        "reported (base/lockcheck).", "observability")
declare("DMLC_RACECHECK", "0",
        "1 installs the vector-clock happens-before race detector at "
        "import (implies lock tracing): shared-attribute accesses on "
        "the instrumented serving/tracker classes are checked for "
        "unordered cross-thread pairs (base/racecheck).", "observability")
declare("DMLC_LEAKCHECK", "0",
        "1 installs the resource-leak tracer at import: every "
        "socket/thread/subprocess/tempfile created through repo code "
        "is recorded with its creation stack, and whatever is still "
        "live at drill exit is reported (base/leakcheck).",
        "observability")
declare("DMLC_JITCHECK", "0",
        "1 installs the XLA-compile tracer at import: every "
        "compilation is recorded with its repo-frame stack and phase "
        "tag (warmup/steady), and any compile after the bench/drill "
        "declares steady state fails check() (base/jitcheck).",
        "observability")
declare("DMLC_INTERLEAVE_SCHEDULES", 200,
        "Schedule budget per model for the interleave model checker "
        "(analysis/interleave).", "observability")
declare("DMLC_METRICS_SPOOL", "",
        "Directory for the cross-process metrics spool: each process "
        "writes its registry snapshot (and trace shard) there for "
        "fleet-wide merging (base/metrics_agg); empty disables.",
        "observability")
declare("DMLC_METRICS_SPOOL_S", 2.0,
        "Seconds between periodic spool snapshot flushes; <= 0 keeps "
        "only the at-exit flush.", "observability")
declare("DMLC_TRACE_CTX", "",
        "Wire-encoded trace context a launcher injects so child "
        "processes join the parent's distributed trace "
        "(base/tracectx); empty starts fresh.", "observability")
declare("DMLC_SLO_SPEC", "",
        "Default SLO spec JSON path for scorecard evaluation "
        "(base/slo; bench.py --slo overrides); empty disables.",
        "observability")

# -- GBT / compute ----------------------------------------------------------
declare("DMLC_TPU_ROUNDS_PER_DISPATCH", 25,
        "Boosting rounds fused per device dispatch in the dense "
        "engine.", "gbt")
declare("DMLC_TPU_SPARSE_ROUNDS_PER_DISPATCH", 8,
        "Rounds fused per device dispatch in the sparse engine.", "gbt")
declare("DMLC_TPU_FUSED_DESCEND", "0",
        "1 selects the fused tree-descent prediction kernel "
        "variant.", "gbt")
declare("DMLC_TPU_BIN_BACKEND", "",
        "'cpu' forces host-numpy feature binning; empty bins on "
        "device.", "gbt")
declare("DMLC_TPU_SKETCH_BACKEND", "",
        "'cpu' forces the host quantile-sketch path; empty sketches on "
        "device.", "gbt")
declare("DMLC_TPU_EXTERNAL_DEVICE_BUDGET", 6 << 30,
        "Device-memory budget in bytes for resident bin pages in the "
        "external-memory engine.", "gbt")
declare("DMLC_INGEST_CHUNK_ROWS", 2_000_000,
        "Rows per double-buffered host-to-device ingest slab "
        "(cold-start streaming).", "gbt")
declare("DMLC_COLDSTART_OVERLAP", "1",
        "0 restores the serial bin-then-compile cold start (no "
        "ingest/compile overlap).", "gbt")
declare("DMLC_SHARDED_INGEST", "1",
        "0 restores the single global device_put staging path; 1 "
        "streams each chip's row slice onto that chip only "
        "(bit-identical either way).", "gbt")
declare("DMLC_HIST_BLOCKS", 0,
        "N>0 enables the mesh-shape-invariant deterministic histogram "
        "reduction with N fixed row blocks (rounded up to a power of "
        "two >= the data-axis size): trees become bit-identical across "
        "mesh shapes; 0 keeps the faster plain psum.", "gbt")
declare("DMLC_GROW_POLICY", "depthwise",
        "'lossguide' grows each tree leaf-wise: a gain-priority queue "
        "expands the best open leaf, building ONE histogram per "
        "expansion (sibling subtraction covers the other child) instead "
        "of a whole level at a time; tree structure is identical to "
        "depthwise when the leaf budget is unlimited.", "gbt")
declare("DMLC_MAX_LEAVES", 0,
        "Leaf budget per tree under DMLC_GROW_POLICY=lossguide (0 = "
        "unlimited, i.e. up to 2^max_depth); the queue stops after "
        "max_leaves - 1 profitable expansions.", "gbt")
declare("DMLC_BIN_PACK", "0",
        "1 packs narrow features two-per-byte (int4) in the transposed "
        "bin matrix: features whose OCCUPIED bin count is <= 16 are "
        "compact-remapped and nibble-paired, shrinking the HBM bin "
        "traffic every histogram pass pays; split decisions and "
        "save_model bytes are bit-identical.", "gbt")
declare("DMLC_FUSED_ROUND", "auto",
        "Fully-fused Pallas round kernel: ONE program per level "
        "(depthwise) or expansion (lossguide) doing bin-read, node "
        "descend, g/h accumulation and sibling subtraction with the "
        "node histograms VMEM-resident — no HBM round-trip between "
        "phases.  'auto' engages on TPU at eligible shapes "
        "(single-chip, no DMLC_HIST_BLOCKS, no missing values, pallas "
        "hist_method), '1' forces it everywhere (interpret mode "
        "off-TPU — the byte-parity test hook), '0' pins the "
        "three-dispatch path; save_model bytes identical either "
        "way.", "gbt")
declare("DMLC_HIST_QUANT", "0",
        "1 quantizes the multi-chip histogram sync to int8 codes plus "
        "an exact f32 per-column total (the correction term): ~4x "
        "fewer allreduce bytes at n_bins=256, bounded per-cell error "
        "(n_chips*scale/2), EXACT per-(node,feature) grad/hess totals. "
        "No-op on one chip and under DMLC_HIST_BLOCKS.", "gbt")
declare("DMLC_WARMUP_EXEC", "auto",
        "Whether the fit warmup EXECUTES the round programs after "
        "compiling them: 'auto' executes on TPU only (first dispatch "
        "pays real staging there), '1' forces execution everywhere, "
        "'0' compiles/AOT-warms only — on CPU an exec-warmup just runs "
        "the whole first dispatch chunk twice (the BENCH_r06 98s "
        "warm_dispatch).", "gbt")
declare("DMLC_FEATURE_BUNDLE", "0",
        "1 fuses mutually-exclusive (near-one-hot) feature blocks into "
        "one multi-bin storage feature (LightGBM's EFB with the "
        "most-frequent bin as the default): histograms build on fewer "
        "rows and are exactly unbundled at split evaluation; the "
        "default-bin cell is reconstructed as total - segment, so this "
        "lever is off by default (last-ulp float reassociation).", "gbt")

# -- compile cache ----------------------------------------------------------
declare("DMLC_COMPILE_CACHE", "1",
        "0 disables the persistent compilation cache "
        "(base/compile_cache).", "compile-cache")
declare("DMLC_COMPILE_CACHE_DIR", "",
        "Cache directory; empty adopts an already-configured dir or the "
        "default location.", "compile-cache")
declare("DMLC_COMPILE_CACHE_EXPECT", "",
        "CI drill only: scripts/check_compile_cache.py asserts this "
        "outcome ('miss' or 'hit').", "compile-cache")

# -- io ---------------------------------------------------------------------
declare("DMLC_HDFS_NAMENODE", "",
        "Default namenode host:port for hdfs:// URIs "
        "(WebHDFS).", "io")
declare("DMLC_HDFS_USER", "$USER",
        "WebHDFS user.name query parameter.", "io")
declare("DMLC_IO_NO_ENDIAN_SWAP", "0",
        "1 disables the endianness swap in the binary serializer "
        "(big-endian hosts).", "io")
declare("DMLC_ITER_PRODUCER_RESTARTS", 0,
        "Process-wide default for ThreadedIter max_restarts (bounded "
        "producer-exception absorption).", "io")

# -- resilience -------------------------------------------------------------
declare("DMLC_RETRY_MAX_ATTEMPTS", 4,
        "RetryPolicy default attempt cap.", "resilience")
declare("DMLC_RETRY_DEADLINE_S", 60.0,
        "RetryPolicy default total-deadline seconds.", "resilience")
declare("DMLC_RETRY_BASE_S", 0.05,
        "RetryPolicy default base backoff seconds (exponential + full "
        "jitter).", "resilience")
declare("DMLC_RETRY_MAX_BACKOFF_S", 5.0,
        "RetryPolicy default per-sleep backoff cap in "
        "seconds.", "resilience")
declare("DMLC_CB_THRESHOLD", 5,
        "CircuitBreaker default consecutive-failure threshold before "
        "opening.", "resilience")
declare("DMLC_CB_RESET_S", 30.0,
        "CircuitBreaker default open-to-half-open probe delay in "
        "seconds.", "resilience")
declare("DMLC_CKPT_KEEP", "",
        "How many previous checkpoint versions to retain (.prev "
        "chain); empty = 1.", "resilience")
declare("DMLC_FAULT_INJECT", "",
        "Deterministic fault-injection spec "
        "('point:kind[=v][:p=][:n=][:after=][:at=][:every=],...'); "
        "empty disables.", "resilience")
declare("DMLC_FAULT_SEED", 1234,
        "Seed for the per-rule fault-injection RNG streams.", "resilience")
declare("DMLC_PRODSIM_SECONDS", 24.0,
        "Duration of the bench.py --prodsim production-day simulation "
        "load window in seconds (the chaos schedule scales with it).",
        "resilience")
declare("DMLC_PRODSIM_CHAOS", "",
        "Override chaos schedule for bench.py --prodsim (faultinject "
        "grammar with at=/every= wall-clock triggers); empty derives "
        "the default all-tier schedule from DMLC_PRODSIM_SECONDS.",
        "resilience")
declare("DMLC_RECOVERY_STRIDE", 5,
        "Boosting rounds between round-versioned collective checkpoint "
        "commits (the elastic-recovery floor granularity).", "resilience")
declare("DMLC_ELASTIC", "0",
        "1 re-shards the surviving workers (shrunk world, re-cut row "
        "shards) once a lost worker's grace lapses; 0 holds the world "
        "for a rejoining replacement.", "resilience")
declare("DMLC_RECOVERY_DIR", "",
        "Directory for per-rank round-versioned recovery checkpoints "
        "(parallel/recovery); empty requires an explicit "
        "recovery_dir=.", "resilience")

# -- serving ----------------------------------------------------------------
declare("DMLC_SERVE_PREWARM", "0",
        "1 pre-compiles the batch-bucket ladder at ModelRunner "
        "construction (serve cold-start).", "serve")

# -- fleet serving ----------------------------------------------------------
declare("DMLC_FLEET_VNODES", 64,
        "Virtual nodes per replica on the router's consistent-hash ring "
        "(more = smoother balance, larger ring).", "fleet")
declare("DMLC_FLEET_MAX_QUEUE", 512,
        "Fleet-wide queued-request bound for router admission control; "
        "beyond it predicts are shed with 503 + Retry-After.", "fleet")
declare("DMLC_FLEET_PROBE_S", 0.5,
        "Router health-probe / membership-refresh interval in "
        "seconds.", "fleet")
declare("DMLC_FLEET_FAILOVER", 2,
        "Extra replicas the router tries after the hash-primary fails "
        "(total attempts = 1 + this).", "fleet")
declare("DMLC_FLEET_HEARTBEAT_S", 0.5,
        "Replica load-report (serve_report) interval in "
        "seconds.", "fleet")
declare("DMLC_FLEET_WAVE_SIZE", 1,
        "Replicas activated per staged-rollout wave.", "fleet")
declare("DMLC_FLEET_SCALE_OUT_S", 0.05,
        "Queue-wait p99 seconds above which the autoscale policy "
        "recommends scale-out.", "fleet")
declare("DMLC_FLEET_SCALE_IN_S", 0.005,
        "Queue-wait p99 seconds below which the autoscale policy "
        "recommends scale-in.", "fleet")
declare("DMLC_FLEET_PATIENCE", 3,
        "Consecutive out-of-band autoscale observations required before "
        "a recommendation fires (hysteresis).", "fleet")
declare("DMLC_FLEET_MIN_REPLICAS", 1,
        "Autoscale floor on replica count.", "fleet")
declare("DMLC_FLEET_MAX_REPLICAS", 8,
        "Autoscale ceiling on replica count.", "fleet")

# -- multi-tenant serving ----------------------------------------------------
declare("DMLC_TENANT_RESIDENT_CAP", 0,
        "Maximum tenant models kept warm (runner resident) per replica; "
        "beyond it the least-recently-served tenant is paged out to its "
        "retained checkpoint bytes and warm-restored on next use. "
        "0 = unlimited (no paging).", "tenancy")
declare("DMLC_TENANT_CLASSES", "",
        "Tenant SLO class map, e.g. 'gold:acme,bar;bronze:baz' — "
        "semicolon-separated class:tenant,... groups.  Unlisted tenants "
        "get DMLC_TENANT_DEFAULT_CLASS.", "tenancy")
declare("DMLC_TENANT_DEFAULT_CLASS", "silver",
        "SLO class assumed for tenants absent from "
        "DMLC_TENANT_CLASSES (gold|silver|bronze).", "tenancy")
declare("DMLC_TENANT_QUOTA", 0,
        "Per-tenant cap on concurrent in-flight predicts at the router; "
        "beyond it THAT tenant is shed with 429 (no other tenant "
        "notices).  0 = no per-tenant quota.", "tenancy")
declare("DMLC_TENANT_MAX_INFLIGHT", 64,
        "Router-wide cap on concurrent tenant-tagged predicts; the "
        "overload axis tenant shedding is graded against (bronze shed "
        "at DMLC_TENANT_SHED_FRACTION of it, everyone at it).", "tenancy")
declare("DMLC_TENANT_SHED_FRACTION", 0.5,
        "Fraction of DMLC_TENANT_MAX_INFLIGHT at which bronze tenants "
        "start shedding with 429 — the 'bronze sheds before gold "
        "queues' contract (doc/serving.md).", "tenancy")
declare("DMLC_TENANT_HEDGE_MS", 0,
        "Gold-tenant hedge delay in milliseconds: when > 0 and a second "
        "ring candidate exists, a gold predict still in flight after "
        "this long is raced against the next replica; first success "
        "wins.  0 disables hedging.", "tenancy")

# -- streaming / online learning --------------------------------------------
declare("DMLC_STREAM_POLL_S", 0.05,
        "Tailer base poll interval in seconds; idle polls back off "
        "exponentially (with jitter) from here.", "stream")
declare("DMLC_STREAM_MAX_BACKOFF_S", 1.0,
        "Cap on the tailer's jittered idle-poll backoff in "
        "seconds.", "stream")
declare("DMLC_STREAM_CURSOR", "",
        "Default cursor checkpoint URI for RecordIOTailer.commit "
        "(crash-safe resume); empty = no default.", "stream")
declare("DMLC_STREAM_CHUNK_ROWS", 2048,
        "Fresh event rows gathered per online-trainer refresh.", "stream")
declare("DMLC_STREAM_WINDOW_CHUNKS", 4,
        "Sliding training window length in chunks; steady-state window "
        "row count (and compiled shapes) stay fixed once full.", "stream")
declare("DMLC_STREAM_DECAY", 1.0,
        "Per-chunk-age sample-weight decay in (0, 1]; 1.0 = pure "
        "sliding window (no weights, warm-start parity).", "stream")
declare("DMLC_STREAM_EVAL_GATE", 0.1,
        "Publisher eval-gate relative tolerance: a refresh is rejected "
        "when holdout score exceeds the active version's by more than "
        "this fraction.", "stream")

# -- distributed ABI (set by tracker/launchers, read by workers) ------------
declare("DMLC_ROLE", "worker",
        "Process role in a distributed job: worker / server / "
        "scheduler.", "distributed")
declare("DMLC_TRACKER_URI", "",
        "Tracker host the worker handshakes with.", "distributed")
declare("DMLC_TRACKER_PORT", "",
        "Tracker TCP port.", "distributed")
declare("DMLC_LEGACY_TRACKER_PORT", "",
        "Port of the legacy one-shot tracker protocol (elastic-recovery "
        "example ABI).", "distributed")
declare("DMLC_NUM_WORKER", 1,
        "Worker count the tracker coordinates.", "distributed")
declare("DMLC_NUM_SERVER", 0,
        "Parameter-server count (PS ABI only; the engine itself is the "
        "KVStore shim).", "distributed")
declare("DMLC_TASK_ID", 0,
        "This worker's task index within the job.", "distributed")
declare("DMLC_NUM_ATTEMPT", 0,
        "Restart attempt number of this task (elastic "
        "recovery).", "distributed")
declare("DMLC_PS_ROOT_URI", "",
        "PS scheduler host (PSTracker env ABI).", "distributed")
declare("DMLC_PS_ROOT_PORT", "",
        "PS scheduler port (PSTracker env ABI).", "distributed")
declare("DMLC_WORKDIR", "",
        "Remote working directory launchers cd into before exec'ing the "
        "worker command.", "distributed")
declare("DMLC_TRACKER_GRACE_S", 0.0,
        "Reconnect grace window in seconds before a lost persistent "
        "worker is declared dead.", "distributed")
declare("DMLC_KVSTORE_CHECK", 0,
        "1 enables out-of-mesh KVStore consistency checks (debug).",
        "distributed")

# -- multi-host launch ------------------------------------------------------
declare("DMLC_LAUNCH_RESTART_LIMIT", 2,
        "Per-rank respawn budget for a supervised JobSet (spawn "
        "failures and unexpected exits both consume it; 0 disables "
        "restarts).", "launch")
declare("DMLC_LAUNCH_MONITOR_S", 0.2,
        "JobSet supervisor poll interval in seconds (liveness poll, "
        "respawn scheduling, tracker cross-check).", "launch")
declare("DMLC_LAUNCH_GRACEFUL_S", 5.0,
        "Teardown grace in seconds between SIGTERM and SIGKILL when a "
        "JobSet shuts its workers down.", "launch")
declare("DMLC_LAUNCH_LOG_DIR", "",
        "Directory for per-worker launch log files; empty uses a fresh "
        "temp dir per transport.", "launch")
declare("DMLC_LAUNCH_WEDGE_CYCLES", 25,
        "Consecutive monitor cycles a rank may stay process-alive but "
        "tracker-lost before the JobSet declares it wedged and kills "
        "it for respawn.", "launch")

# -- parameter server -------------------------------------------------------
declare("DMLC_PS_STALENESS", 4,
        "Bounded-staleness window tau for dist_async pulls: a pull at "
        "worker clock c blocks until every worker committed c - tau; "
        "0 = BSP, negative = fully async (never block).", "ps")
declare("DMLC_PS_PIPELINE", 8,
        "In-flight request window per server connection: async pushes "
        "beyond this many unacked requests block the sender.", "ps")
declare("DMLC_PS_PULL_TIMEOUT_S", 60.0,
        "Seconds a pull may wait on the server-side staleness gate "
        "before erroring out.", "ps")
declare("DMLC_PS_RECONNECT_S", 30.0,
        "Deadline in seconds for re-resolving and re-dialing a lost "
        "server connection (respawn failover window).", "ps")
declare("DMLC_PS_SNAPSHOT_DIR", "",
        "Directory for per-server shard snapshots (atomic CRC'd "
        "checkpoints); empty disables durability.", "ps")
declare("DMLC_PS_SNAPSHOT_STRIDE", 0,
        "Committed clock ticks between shard snapshots; 0 disables "
        "periodic snapshots.", "ps")
declare("DMLC_PS_SERVER_ID", -1,
        "Server shard id for DMLC_ROLE=server processes; -1 lets the "
        "scheduler assign the next free id (a respawn passes its old "
        "id to reclaim the shard).", "ps")
declare("DMLC_PS_SERVER_URI", "127.0.0.1",
        "Host/interface a DMLC_ROLE=server process binds its data "
        "plane to (advertised to the scheduler).", "ps")
