"""Typed, validated, self-documenting parameter structs.

Reference parity: ``include/dmlc/parameter.h :: Parameter<PType>`` CRTP —
``Init(kwargs)``, ``InitAllowUnknown``, ``UpdateDict``, ``__DICT__()``,
``__FIELDS__()``, ``Save/Load(JSON)``, ``DMLC_DECLARE_FIELD(f).set_default()
.set_range().set_lower_bound().add_enum().describe()``, ``FieldEntry<T>``
specializations, ``ParamInitOption`` and ``dmlc::GetEnv<T>`` (SURVEY.md §2a).

Pythonic redesign: fields are declared with :func:`field` descriptors on a
:class:`Parameter` subclass; a metaclass collects them in declaration order.
Values are parsed from strings exactly like the reference (so env vars and
``key=value`` config files feed straight in), range/enum-validated, and
round-trip through JSON.  This is also the config surface for every model/op
in :mod:`dmlc_core_tpu.models` — hyperparameters on a ``Parameter`` are
static, hashable jit-compile-time constants by construction (plain Python
scalars, never traced arrays), which is exactly what ``jax.jit`` wants.
"""

from __future__ import annotations

import json
import os
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
    Type,
    TypeVar,
    Union,
)

from dmlc_core_tpu.base.logging import Error

__all__ = ["Parameter", "field", "FieldEntry", "get_env", "ParamInitOption"]

T = TypeVar("T")

_MISSING = object()


class ParamInitOption:
    """Reference parity: ``dmlc::parameter::ParamInitOption``."""

    kAllowUnknown = "allow_unknown"
    kAllMatch = "all_match"
    kAllowHidden = "allow_hidden"  # unknown keys starting with '__' pass


def _parse_bool(s: str) -> bool:
    s = s.strip().lower()
    if s in ("1", "true", "yes", "on"):
        return True
    if s in ("0", "false", "no", "off"):
        return False
    raise ValueError(f"cannot parse bool from {s!r}")


def _str2type(value: Any, ty: type) -> Any:
    """Parse ``value`` (usually a string) into ``ty``.

    Reference parity: ``include/dmlc/strtonum.h :: Str2Type`` /
    ``FieldEntry<T>::Set``.  Host-side config parsing is not a TPU hot path,
    so Python parsing is the right engine here (the data-plane hot loop is in
    cpp/fastparse.cc instead).
    """
    if ty is Any or ty is None:
        return value
    origin = getattr(ty, "__origin__", None)
    if origin is Union:  # Optional[T]
        args = [a for a in ty.__args__ if a is not type(None)]
        if value is None or (isinstance(value, str) and value.strip() in ("None", "none", "")):
            return None
        return _str2type(value, args[0])
    if isinstance(value, ty) and not (ty is int and isinstance(value, bool)):
        return value
    if ty is bool:
        if isinstance(value, (int, float)):
            return bool(value)
        return _parse_bool(str(value))
    if ty in (int, float, str):
        try:
            return ty(value)
        except (TypeError, ValueError) as e:
            raise ValueError(f"cannot parse {ty.__name__} from {value!r}") from e
    if ty in (list, tuple):
        if isinstance(value, str):
            items = [v.strip() for v in
                     value.replace("(", "").replace(")", "").split(",")
                     if v.strip()]
            return ty(items)
        return ty(value)
    return value


class FieldEntry:
    """One declared field: type, default, bounds, enum, docs.

    Reference parity: ``dmlc::parameter::FieldEntry<T>`` and the
    ``DMLC_DECLARE_FIELD`` fluent API, collapsed into keyword arguments of
    :func:`field`.
    """

    def __init__(
        self,
        type: type = str,
        default: Any = _MISSING,
        description: str = "",
        lower_bound: Optional[Any] = None,
        upper_bound: Optional[Any] = None,
        enum: Optional[Sequence[Any]] = None,
        validator: Optional[Callable[[Any], bool]] = None,
    ):
        self.type = type
        self.default = default
        self.description = description
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.enum = list(enum) if enum is not None else None
        self.validator = validator
        self.name: str = "?"  # filled by the metaclass

    # fluent API kept for source-level familiarity with the reference
    def set_default(self, v: Any) -> "FieldEntry":
        self.default = v
        return self

    def set_range(self, lo: Any, hi: Any) -> "FieldEntry":
        self.lower_bound, self.upper_bound = lo, hi
        return self

    def set_lower_bound(self, lo: Any) -> "FieldEntry":
        self.lower_bound = lo
        return self

    def set_upper_bound(self, hi: Any) -> "FieldEntry":
        self.upper_bound = hi
        return self

    def add_enum(self, v: Any) -> "FieldEntry":
        self.enum = (self.enum or []) + [v]
        return self

    def describe(self, text: str) -> "FieldEntry":
        self.description = text
        return self

    @property
    def has_default(self) -> bool:
        return self.default is not _MISSING

    def check(self, value: Any) -> Any:
        """Parse + validate a candidate value; raise dmlc Error on violation."""
        try:
            value = _str2type(value, self.type)
        except ValueError as e:
            raise Error(f"parameter {self.name!r}: {e}") from e
        if self.lower_bound is not None and value is not None and value < self.lower_bound:
            raise Error(
                f"parameter {self.name!r}: value {value!r} below lower bound {self.lower_bound!r}"
            )
        if self.upper_bound is not None and value is not None and value > self.upper_bound:
            raise Error(
                f"parameter {self.name!r}: value {value!r} above upper bound {self.upper_bound!r}"
            )
        if self.enum is not None and value not in self.enum:
            raise Error(
                f"parameter {self.name!r}: value {value!r} not in allowed set {self.enum!r}"
            )
        if self.validator is not None and not self.validator(value):
            raise Error(f"parameter {self.name!r}: value {value!r} rejected by validator")
        return value


def field(
    type: type = str,
    default: Any = _MISSING,
    description: str = "",
    lower_bound: Optional[Any] = None,
    upper_bound: Optional[Any] = None,
    enum: Optional[Sequence[Any]] = None,
    validator: Optional[Callable[[Any], bool]] = None,
) -> FieldEntry:
    """Declare a parameter field — the ``DMLC_DECLARE_FIELD`` equivalent."""
    return FieldEntry(type, default, description, lower_bound, upper_bound, enum, validator)


class _ParameterMeta(type):
    def __new__(mcls, name, bases, ns):
        fields: Dict[str, FieldEntry] = {}
        for base in bases:
            fields.update(getattr(base, "__param_fields__", {}))
        for key, val in list(ns.items()):
            if isinstance(val, FieldEntry):
                val.name = key
                fields[key] = val
                del ns[key]
        ns["__param_fields__"] = fields
        return super().__new__(mcls, name, bases, ns)


class Parameter(metaclass=_ParameterMeta):
    """Base class for typed parameter structs.

    Usage::

        class TreeParam(Parameter):
            max_depth = field(int, default=6, lower_bound=1,
                              description="maximum tree depth")
            eta = field(float, default=0.3, lower_bound=0.0, upper_bound=1.0)
            tree_method = field(str, default="hist", enum=["hist", "exact"])

        p = TreeParam()
        unknown = p.init({"max_depth": "8"}, allow_unknown=True)

    Reference parity: ``Parameter<PType>::Init / InitAllowUnknown /
    UpdateDict / __DICT__ / __FIELDS__ / Save / Load``.
    """

    __param_fields__: Dict[str, FieldEntry] = {}

    def __init__(self, **kwargs: Any):
        for name, entry in self.__param_fields__.items():
            if entry.has_default:
                object.__setattr__(self, name, entry.check(entry.default))
        if kwargs:
            self.init(kwargs)

    # -- init / update ---------------------------------------------------
    def init(
        self,
        kwargs: Union[Mapping[str, Any], Iterable[Tuple[str, Any]]],
        allow_unknown: bool = False,
        option: Optional[str] = None,
    ) -> List[Tuple[str, Any]]:
        """Set fields from (string-keyed) kwargs with validation.

        Returns the list of unknown ``(key, value)`` pairs if
        ``allow_unknown`` (reference: ``InitAllowUnknown``); raises
        :class:`Error` on unknown keys otherwise, and always on missing
        required fields or validation failure.

        ``option`` overrides the mode explicitly (reference:
        ``ParamInitOption``): ``kAllMatch`` raises on every unknown key,
        ``kAllowHidden`` (the default strict mode) tolerates only hidden
        ``__key__`` entries, ``kAllowUnknown`` collects all unknowns.
        """
        if option is None:
            option = (
                ParamInitOption.kAllowUnknown if allow_unknown else ParamInitOption.kAllowHidden
            )
        items = list(kwargs.items()) if isinstance(kwargs, Mapping) else list(kwargs)
        unknown: List[Tuple[str, Any]] = []
        for key, value in items:
            entry = self.__param_fields__.get(key)
            if entry is None:
                hidden = key.startswith("__") and key.endswith("__")
                if option == ParamInitOption.kAllowUnknown or (
                    option == ParamInitOption.kAllowHidden and hidden
                ):
                    unknown.append((key, value))
                    continue
                raise Error(
                    f"{type(self).__name__}: unknown parameter {key!r}. "
                    f"Candidates: {sorted(self.__param_fields__)}"
                )
            object.__setattr__(self, key, entry.check(value))
        missing = [
            n
            for n, e in self.__param_fields__.items()
            if not e.has_default and not hasattr(self, n)
        ]
        if missing:
            raise Error(
                f"{type(self).__name__}: required parameters not set: {missing}"
            )
        return unknown

    def update_dict(self, kwargs: Dict[str, Any]) -> None:
        """Init from dict, then write the struct's values back into it.

        Reference parity: ``Parameter::UpdateDict`` — keeps an external
        string-dict (e.g. an XGBoost-style config) in sync with the struct.
        """
        self.init(kwargs, allow_unknown=True)
        kwargs.update({k: getattr(self, k) for k in self.__param_fields__})

    def __setattr__(self, name: str, value: Any) -> None:
        entry = self.__param_fields__.get(name)
        if entry is not None:
            value = entry.check(value)
        object.__setattr__(self, name, value)

    # -- introspection ---------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        """Reference parity: ``__DICT__()``."""
        return {k: getattr(self, k) for k in self.__param_fields__ if hasattr(self, k)}

    @classmethod
    def fields(cls) -> Dict[str, FieldEntry]:
        """Reference parity: ``__FIELDS__()``."""
        return dict(cls.__param_fields__)

    @classmethod
    def doc_string(cls) -> str:
        """Generated docs for all fields (the reference's __DOC__ output)."""
        lines = []
        for name, e in cls.__param_fields__.items():
            constraints = []
            if e.has_default:
                constraints.append(f"default={e.default!r}")
            if e.lower_bound is not None:
                constraints.append(f">={e.lower_bound!r}")
            if e.upper_bound is not None:
                constraints.append(f"<={e.upper_bound!r}")
            if e.enum is not None:
                constraints.append(f"one of {e.enum!r}")
            suffix = f" ({', '.join(constraints)})" if constraints else ""
            lines.append(f"{name} : {e.type.__name__}{suffix}\n    {e.description}")
        return "\n".join(lines)

    def __repr__(self) -> str:
        body = ", ".join(f"{k}={v!r}" for k, v in self.to_dict().items())
        return f"{type(self).__name__}({body})"

    def __eq__(self, other: Any) -> bool:
        return type(other) is type(self) and other.to_dict() == self.to_dict()

    def __hash__(self) -> int:
        # hashable → usable as a static arg to jax.jit, even with list fields
        def _freeze(v: Any) -> Any:
            if isinstance(v, (list, tuple)):
                return tuple(_freeze(x) for x in v)
            if isinstance(v, dict):
                return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
            return v

        items = sorted(self.to_dict().items(), key=lambda kv: kv[0])
        return hash((type(self).__name__, tuple((k, _freeze(v)) for k, v in items)))

    # -- JSON round trip -------------------------------------------------
    def save(self, stream) -> None:
        """Write JSON to a dmlc Stream.  Reference: ``Parameter::Save(JSONWriter)``."""
        stream.write(json.dumps(self.to_dict(), indent=2).encode("utf-8"))

    def load(self, stream) -> None:
        """Read JSON from a dmlc Stream.  Reference: ``Parameter::Load(JSONReader)``."""
        data = stream.read_all() if hasattr(stream, "read_all") else stream.read(-1)
        self.init(json.loads(bytes(data).decode("utf-8")))

    def save_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2)

    def load_json(self, text: str) -> None:
        self.init(json.loads(text))


def get_env(name: str, default: T, type: Optional[Type[T]] = None) -> T:
    """Typed environment-variable read.

    Reference parity: ``dmlc::GetEnv<T>(name, default)`` (parameter.h).
    The type is inferred from ``default`` unless given explicitly.
    """
    raw = os.environ.get(name)
    if raw is None:
        return default
    ty = type if type is not None else (default.__class__ if default is not None else str)
    try:
        return _str2type(raw, ty)  # type: ignore[return-value]
    except ValueError as e:
        raise Error(f"environment variable {name}: {e}") from e
