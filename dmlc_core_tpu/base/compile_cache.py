"""Persistent XLA compilation cache wiring + cold-start instrumentation.

BENCH_r05 measured `warmup_seconds: 31.0` against `seconds: 12.4` of
actual training on the north-star config — the XLA compiles that
dominate that half minute are re-paid by every ``bench.py`` run, every
elastic-recovery relaunch, and every serve restart, even though the
programs are byte-identical each time.  JAX ships a persistent
compilation cache (serialized executables keyed on the HLO + device
topology) that turns a repeat compile into a disk read; this module is
the ONE place that wires it, so every engine (in-core / external /
sparse GBT, serve runners, bench) gets warm-start behavior through a
single pair of env knobs:

* ``DMLC_COMPILE_CACHE`` — default on; ``0`` disables (no jax config is
  touched at all);
* ``DMLC_COMPILE_CACHE_DIR`` — cache directory.  Unset: an already-
  configured jax cache dir (e.g. the test harness's) is adopted as-is,
  else ``~/.cache/dmlc_core_tpu/xla_compile_cache``.

When enabled, the write thresholds are opened up
(``jax_persistent_cache_min_compile_time_secs=0``, no minimum entry
size): this substrate compiles a few dozen distinct programs at most,
and a sub-second program that a serve restart would otherwise recompile
per bucket is exactly what the cache exists to skip.

Instrumentation: jax's monitoring events for cache hits / misses /
compile-time-saved are forwarded into :mod:`dmlc_core_tpu.base.metrics`
(``dmlc_compile_cache_events_total{event=hit|miss}``,
``dmlc_compile_cache_saved_seconds_total``) and mirrored in process-
local counters that :func:`stats` reports even with metrics disabled —
``bench.py`` stamps its final JSON with ``compile_cache: hit|miss``
from exactly this.

:class:`BackgroundCompiler` is the shared cold-start overlap helper:
it runs AOT ``lower(...).compile()`` thunks concurrently on
:class:`~dmlc_core_tpu.io.thread_group.ThreadGroup` workers so compiles
proceed while ingest (quantile sketch, binning, H2D staging) runs on
the main thread — see ``models/histgbt.py`` for the flagship consumer
and ``doc/performance.md`` for the full cold-start story.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional, Tuple

import jax

from dmlc_core_tpu.base import metrics as _metrics
from dmlc_core_tpu.base.logging import LOG
from dmlc_core_tpu.base.parameter import get_env
from dmlc_core_tpu.base.timer import get_time

__all__ = [
    "BackgroundCompiler", "cache_dir", "compile_cache_metrics",
    "configure", "enabled", "set_cache_dir", "stats",
]

#: default on-disk location when neither ``DMLC_COMPILE_CACHE_DIR`` nor
#: an existing jax cache dir says otherwise
_DEFAULT_DIR = os.path.join(os.path.expanduser("~"), ".cache",
                            "dmlc_core_tpu", "xla_compile_cache")

_lock = threading.Lock()
#: process-local event counts (kept even when base.metrics is disabled
#: — stats() is evidence for bench records, not optional telemetry)
_counts = {"hits": 0, "misses": 0, "saved_seconds": 0.0}
_listeners_registered = False

_M: Dict[str, Any] = {}


def compile_cache_metrics() -> Dict[str, Any]:
    """Lazily declared instrument handles in the default registry."""
    if not _M:
        r = _metrics.default_registry()
        _M.update({
            "events": r.counter(
                "compile_cache_events_total",
                "persistent XLA compile cache events (hit = executable "
                "deserialized from disk, miss = compiled then written)",
                labels=("event",)),
            "saved": r.counter(
                "compile_cache_saved_seconds_total",
                "compile seconds skipped via persistent-cache hits "
                "(original compile time minus retrieval time)"),
            "compile": r.histogram(
                "compile_seconds",
                "wall seconds per AOT program compile (cache hits "
                "included — they appear as near-zero observations)",
                labels=("what",)),
        })
    return _M


def _on_event(event: str, **kw: Any) -> None:
    name = {"/jax/compilation_cache/cache_hits": "hit",
            "/jax/compilation_cache/cache_misses": "miss"}.get(event)
    if name is None:
        return
    with _lock:
        _counts[name + ("s" if name == "hit" else "es")] += 1
    if _metrics.enabled():
        compile_cache_metrics()["events"].inc(1, event=name)


def _on_duration(event: str, duration_secs: float, **kw: Any) -> None:
    if event != "/jax/compilation_cache/compile_time_saved_sec":
        return
    with _lock:
        _counts["saved_seconds"] += max(duration_secs, 0.0)
    if _metrics.enabled():
        compile_cache_metrics()["saved"].inc(max(duration_secs, 0.0))


def _register_listeners() -> None:
    """Forward jax's cache monitoring events — once per process.  The
    listeners only count, so they are registered unconditionally: the
    test harness enables the jax cache on its own and the counters must
    reflect that reality too."""
    global _listeners_registered
    with _lock:
        if _listeners_registered:
            return
        _listeners_registered = True
    from jax._src import monitoring
    monitoring.register_event_listener(_on_event)
    monitoring.register_event_duration_secs_listener(_on_duration)


_register_listeners()


def enabled() -> bool:
    """``DMLC_COMPILE_CACHE`` (default on)."""
    return get_env("DMLC_COMPILE_CACHE", True, bool)


def cache_dir() -> Optional[str]:
    """The jax cache directory currently in effect (None = no cache)."""
    return jax.config.jax_compilation_cache_dir


def configure() -> bool:
    """Idempotently wire jax's persistent compilation cache from env.

    Safe to call before every compile site (each engine does).  Returns
    True when the cache is active.  ``DMLC_COMPILE_CACHE=0`` is a
    strict no-op: nothing in jax.config is touched.  A cache dir the
    process already configured (e.g. tests/conftest.py) is adopted
    unless ``DMLC_COMPILE_CACHE_DIR`` explicitly overrides it.
    """
    if not enabled():
        return False
    env_dir = get_env("DMLC_COMPILE_CACHE_DIR", "")
    current = jax.config.jax_compilation_cache_dir
    target = env_dir or current or _DEFAULT_DIR
    if target != current:
        set_cache_dir(target)
    else:
        _open_thresholds()
    return True


def set_cache_dir(path: str) -> None:
    """Point the persistent cache at ``path`` (created lazily by jax).

    Also resets jax's sticky cache handle so a redirect AFTER a compile
    has happened takes effect — without the reset the first-initialized
    directory would silently keep winning (test isolation needs this).
    """
    from jax.experimental.compilation_cache import compilation_cache as cc

    jax.config.update("jax_compilation_cache_dir", path)
    _open_thresholds()
    cc.reset_cache()
    LOG("DEBUG", "compile_cache: persistent XLA cache at %s", path)


def _open_thresholds() -> None:
    """Cache EVERY program: the default 1 s compile-time floor would
    skip most CPU-backend programs and every small serve bucket — the
    exact compiles a warm restart must not re-pay."""
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)


def stats() -> Dict[str, Any]:
    """Process-local cache evidence: enabled state, directory, and
    hit/miss/saved-seconds counts since process start."""
    with _lock:
        counts = dict(_counts)
    return {"enabled": enabled(), "dir": cache_dir(), **counts}


def marker() -> Tuple[int, int]:
    """(hits, misses) snapshot; pair with :func:`verdict`."""
    with _lock:
        return _counts["hits"], _counts["misses"]


def verdict(mark: Tuple[int, int]) -> Optional[str]:
    """Classify cache activity since ``mark``: ``"hit"`` (served at
    least partly from disk, nothing newly compiled), ``"miss"``
    (something compiled + written), or None (no cache traffic — cache
    off, or every program came from jax's in-memory caches)."""
    hits, misses = marker()
    dh, dm = hits - mark[0], misses - mark[1]
    if dm > 0:
        return "miss"
    if dh > 0:
        return "hit"
    return None


class BackgroundCompiler:
    """Run named compile thunks concurrently on daemon workers.

    The cold-start overlap primitive (see module docstring): each thunk
    typically does ``jit(fn).lower(*avals).compile()`` and returns the
    compiled executable; workers run while the caller's main thread
    does ingest work, and :meth:`join` blocks only for whatever compile
    time the ingest did not already cover.

    Failures never propagate: a thunk that raises is logged once and
    simply missing from the results — callers fall back to the inline
    jit path, which recompiles (and usually hits the just-written
    persistent cache).  ``compile_seconds`` after join is the longest
    single worker wall (the critical path; workers run concurrently),
    ``join_wait_seconds`` the non-overlapped residue the caller paid.
    """

    def __init__(self, jobs: Dict[str, Callable[[], Any]],
                 what: str = "warmup") -> None:
        from dmlc_core_tpu.io.thread_group import ThreadGroup

        configure()
        self._what = what
        self._results: Dict[str, Any] = {}
        self._errors: Dict[str, BaseException] = {}
        self._walls: Dict[str, float] = {}
        self._mark = marker()
        self._joined = False
        self.compile_seconds = 0.0
        self.join_wait_seconds = 0.0
        self.cache_verdict: Optional[str] = None
        self._grp = ThreadGroup()
        for name, thunk in jobs.items():
            self._grp.create(f"compile-{name}",
                             self._runner(name, thunk))

    def _runner(self, name: str, thunk: Callable[[], Any]):
        def run(_shutdown) -> None:
            t0 = get_time()
            try:
                self._results[name] = thunk()
            except BaseException as e:  # noqa: BLE001 — surfaced at join
                self._errors[name] = e
            finally:
                self._walls[name] = get_time() - t0
                if _metrics.enabled():
                    compile_cache_metrics()["compile"].observe(
                        self._walls[name], what=f"{self._what}:{name}")
        return run

    def join(self) -> Dict[str, Any]:
        """Wait for every worker; returns name → compiled result
        (failed thunks are absent — see class docstring)."""
        if self._joined:
            return self._results
        t0 = get_time()
        self._grp.join_all()
        self._joined = True
        self.join_wait_seconds = get_time() - t0
        self.compile_seconds = max(self._walls.values(), default=0.0)
        self.cache_verdict = verdict(self._mark)
        for name, err in self._errors.items():
            LOG("WARNING", "background compile %r failed "
                "(%s: %s) — falling back to inline jit compile",
                name, type(err).__name__, err)
        return self._results
