"""Process-wide metrics: Counter / Gauge / Histogram + exporters.

The reference's entire timing story is ``timer.h::GetTime()`` (SURVEY.md
§5: tracing/profiling "essentially none").  On TPU the numbers that
decide everything — step time vs infeed stall, prefetch queue occupancy,
collective bytes on the wire — need a first-class home that the bench
harness and perf PRs read instead of guessing.  This module is that
home; ``utils/profiler.py``'s Tracer remains the *event* (when) side,
metrics are the *aggregate* (how much / how long, distribution) side.

Design points:

* **Label-aware**: a metric is declared once with its label *names*;
  each distinct label-value combination is an independent series
  (``counter.inc(1, op="allreduce")``), exactly Prometheus's data model.
* **Thread-safe**: every metric guards its series map with its own lock
  — producer threads (ThreadedIter), tracker connection threads and the
  main loop all record concurrently.
* **Near-zero disabled cost**: one module-level bool; every instrument
  method begins ``if not _ENABLED: return`` and hot call sites guard
  with :func:`enabled` so a disabled build does no dict lookups, no
  locking, no timestamp reads.  Toggle with :func:`set_enabled` or the
  ``DMLC_METRICS=0`` env var.
* **Histograms** carry fixed cumulative buckets (default log-spaced
  seconds-oriented bounds), a streaming reservoir (bounded memory) for
  quantile summaries, and exact sum/count/min/max.
* **Exporters**: :meth:`MetricsRegistry.to_prometheus` (text exposition
  format, parseable by any Prometheus scraper) and
  :meth:`MetricsRegistry.snapshot` (JSON-serializable dict; the bench
  harness archives one per run).
* :func:`default_registry` mirrors ``utils.profiler.global_tracer`` —
  one process-wide instance, created on first use.
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from dmlc_core_tpu.base.timer import get_time

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "default_registry", "enabled", "set_enabled",
    "DEFAULT_TIME_BUCKETS",
]

#: log-spaced seconds buckets covering 10 µs .. 60 s — the host-path
#: latency range (queue waits, parse chunks, collective calls, boost
#: round dispatches) this substrate actually produces
DEFAULT_TIME_BUCKETS: Tuple[float, ...] = (
    1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4,
    1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

#: per-series reservoir size for streaming quantiles (algorithm R);
#: bounded regardless of observation count
_RESERVOIR_SIZE = 256

_ENABLED = os.environ.get("DMLC_METRICS", "1").lower() not in (
    "0", "false", "off", "no")


def enabled() -> bool:
    """Fast global collection switch — hot call sites guard on this so a
    disabled build pays one global read and a branch, nothing else."""
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Turn collection on/off process-wide (also: ``DMLC_METRICS=0``)."""
    global _ENABLED
    _ENABLED = bool(on)


def _label_key(names: Tuple[str, ...], labels: Dict[str, Any]) -> Tuple[str, ...]:
    """Validate + order label kwargs into the series key.  Strict: a
    typo'd or missing label is a bug at the call site, not a new
    series."""
    if set(labels) != set(names):
        raise ValueError(
            f"metric labels mismatch: declared {sorted(names)}, "
            f"got {sorted(labels)}")
    return tuple(str(labels[n]) for n in names)


def _escape_label(value: str) -> str:
    """Label-value escaping per the text exposition format: backslash,
    double-quote and newline (in that order — backslash first so the
    escapes themselves don't get re-escaped).  Model names and
    checkpoint URIs become label values on the serving ``/metrics``
    endpoint, so hostile values are a live concern, not a formality."""
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(text: str) -> str:
    """HELP-text escaping: the exposition format escapes backslash and
    newline there (quotes are legal raw in HELP, unlike label values)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    """Prometheus number formatting: integers render bare."""
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _MetricBase:
    """Shared declaration + series bookkeeping."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labels: Sequence[str] = ()) -> None:
        self.name = name
        self.help = help
        self.label_names: Tuple[str, ...] = tuple(labels)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, ...], Any] = {}

    def _series_items(self) -> List[Tuple[Tuple[str, ...], Any]]:
        with self._lock:
            return list(self._series.items())

    def _render_labels(self, key: Tuple[str, ...],
                       extra: Tuple[Tuple[str, str], ...] = ()) -> str:
        pairs = [(n, v) for n, v in zip(self.label_names, key)] + list(extra)
        if not pairs:
            return ""
        inner = ",".join(f'{n}="{_escape_label(v)}"' for n, v in pairs)
        return "{" + inner + "}"

    def clear(self) -> None:
        with self._lock:
            self._series.clear()


class Counter(_MetricBase):
    """Monotonically increasing count (events, rows, bytes)."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        if not _ENABLED:
            return
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative inc {amount}")
        key = _label_key(self.label_names, labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels: Any) -> float:
        key = _label_key(self.label_names, labels)
        with self._lock:
            return self._series.get(key, 0.0)

    def _export(self) -> Iterator[str]:
        for key, v in sorted(self._series_items()):
            yield f"{self.name}{self._render_labels(key)} {_fmt(v)}"

    def _snap(self) -> List[Dict[str, Any]]:
        return [{"labels": dict(zip(self.label_names, key)), "value": v}
                for key, v in sorted(self._series_items())]


class Gauge(_MetricBase):
    """Point-in-time value that can go up and down (queue depth, alive
    workers)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 labels: Sequence[str] = ()) -> None:
        super().__init__(name, help, labels)
        # wall-clock write time per series: the cross-process merge
        # (base/metrics_agg) resolves gauge collisions last-write-wins,
        # which needs a clock every process shares
        self._ts: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels: Any) -> None:
        if not _ENABLED:
            return
        key = _label_key(self.label_names, labels)
        now = time.time()
        with self._lock:
            self._series[key] = float(value)
            self._ts[key] = now

    def inc(self, amount: float = 1.0, **labels: Any) -> None:
        if not _ENABLED:
            return
        key = _label_key(self.label_names, labels)
        now = time.time()
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount
            self._ts[key] = now

    def dec(self, amount: float = 1.0, **labels: Any) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: Any) -> float:
        key = _label_key(self.label_names, labels)
        with self._lock:
            return self._series.get(key, 0.0)

    def clear(self) -> None:
        with self._lock:
            self._series.clear()
            self._ts.clear()

    def _export(self) -> Iterator[str]:
        for key, v in sorted(self._series_items()):
            yield f"{self.name}{self._render_labels(key)} {_fmt(v)}"

    def _snap(self) -> List[Dict[str, Any]]:
        with self._lock:
            items = sorted(self._series.items())
            ts = dict(self._ts)
        return [{"labels": dict(zip(self.label_names, key)), "value": v,
                 "ts": ts.get(key, 0.0)}
                for key, v in items]


class _HistSeries:
    """One label combination's state: fixed bucket counts + exact
    sum/count/min/max + a bounded reservoir (algorithm R) for streaming
    quantiles."""

    __slots__ = ("counts", "sum", "count", "min", "max", "reservoir", "_rng")

    def __init__(self, n_buckets: int, seed: int) -> None:
        self.counts = [0] * (n_buckets + 1)          # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0
        self.min = float("inf")
        self.max = float("-inf")
        self.reservoir: List[float] = []
        self._rng = random.Random(seed)

    def observe(self, v: float, bounds: Tuple[float, ...]) -> None:
        # linear scan beats bisect for the ~20-bound default (cache-hot,
        # no function call); bounds are sorted ascending
        i = 0
        for b in bounds:
            if v <= b:
                break
            i += 1
        self.counts[i] += 1
        self.sum += v
        self.count += 1
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        if len(self.reservoir) < _RESERVOIR_SIZE:
            self.reservoir.append(v)
        else:
            j = self._rng.randrange(self.count)
            if j < _RESERVOIR_SIZE:
                self.reservoir[j] = v

    def quantile(self, q: float) -> Optional[float]:
        if not self.reservoir:
            return None
        s = sorted(self.reservoir)
        idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
        return s[idx]


class Histogram(_MetricBase):
    """Distribution of observations: cumulative fixed buckets for
    Prometheus, streaming reservoir quantiles for the JSON snapshot."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labels: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None) -> None:
        super().__init__(name, help, labels)
        bs = tuple(sorted(buckets)) if buckets else DEFAULT_TIME_BUCKETS
        if not bs:
            raise ValueError(f"histogram {self.name}: empty buckets")
        self.buckets: Tuple[float, ...] = bs

    def observe(self, value: float, **labels: Any) -> None:
        if not _ENABLED:
            return
        key = _label_key(self.label_names, labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = _HistSeries(len(self.buckets),
                                     seed=hash((self.name, key)) & 0xFFFF)
                self._series[key] = series
            series.observe(float(value), self.buckets)

    def time(self, **labels: Any):
        """``with hist.time(...):`` — observe the block's wall seconds.
        Disabled mode returns a no-op context without touching locks."""
        return _HistTimer(self, labels)

    def count(self, **labels: Any) -> int:
        key = _label_key(self.label_names, labels)
        with self._lock:
            s = self._series.get(key)
            return s.count if s is not None else 0

    def sum(self, **labels: Any) -> float:
        key = _label_key(self.label_names, labels)
        with self._lock:
            s = self._series.get(key)
            return s.sum if s is not None else 0.0

    def quantile(self, q: float, **labels: Any) -> Optional[float]:
        key = _label_key(self.label_names, labels)
        with self._lock:
            s = self._series.get(key)
            return s.quantile(q) if s is not None else None

    def _export(self) -> Iterator[str]:
        for key, s in sorted(self._series_items()):
            cum = 0
            for bound, c in zip(self.buckets, s.counts):
                cum += c
                le = (("le", _fmt(bound)),)
                yield (f"{self.name}_bucket"
                       f"{self._render_labels(key, le)} {cum}")
            cum += s.counts[-1]
            yield (f"{self.name}_bucket"
                   f"{self._render_labels(key, (('le', '+Inf'),))} {cum}")
            yield f"{self.name}_sum{self._render_labels(key)} {_fmt(s.sum)}"
            yield f"{self.name}_count{self._render_labels(key)} {s.count}"

    def _snap(self) -> List[Dict[str, Any]]:
        out = []
        for key, s in sorted(self._series_items()):
            cum = 0
            bkt = []
            for bound, c in zip(self.buckets, s.counts):
                cum += c
                bkt.append([bound, cum])
            bkt.append(["+Inf", cum + s.counts[-1]])
            out.append({
                "labels": dict(zip(self.label_names, key)),
                "count": s.count,
                "sum": s.sum,
                "min": s.min if s.count else None,
                "max": s.max if s.count else None,
                "buckets": bkt,
                "quantiles": {f"p{int(q * 100)}": s.quantile(q)
                              for q in (0.5, 0.9, 0.99)},
                # raw reservoir rides the snapshot so the cross-process
                # merge can re-sample quantiles weighted by count
                # instead of averaging pre-baked percentiles
                "reservoir": list(s.reservoir),
            })
        return out


class _HistTimer:
    """Context manager behind :meth:`Histogram.time`."""

    __slots__ = ("_hist", "_labels", "_t0")

    def __init__(self, hist: Histogram, labels: Dict[str, Any]) -> None:
        self._hist = hist
        self._labels = labels
        self._t0 = 0.0

    def __enter__(self) -> "_HistTimer":
        if _ENABLED:
            self._t0 = get_time()
        return self

    def __exit__(self, *exc: Any) -> None:
        if _ENABLED and self._t0:
            self._hist.observe(get_time() - self._t0, **self._labels)


class MetricsRegistry:
    """Named collection of metrics with get-or-create declaration.

    Declaring the same (name, kind) twice returns the existing metric —
    instrumented modules can independently declare the metrics they
    touch without an init-order protocol.  Re-declaring a name as a
    different kind or with different labels is a bug and raises.
    """

    def __init__(self, namespace: str = "dmlc") -> None:
        self.namespace = namespace
        self._lock = threading.Lock()
        self._metrics: Dict[str, _MetricBase] = {}

    def _declare(self, cls, name: str, help: str,
                 labels: Sequence[str], **kw: Any) -> Any:
        full = f"{self.namespace}_{name}" if self.namespace else name
        with self._lock:
            existing = self._metrics.get(full)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {full!r} already declared as "
                        f"{existing.kind}, not {cls.kind}")
                if existing.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {full!r} label mismatch: "
                        f"{existing.label_names} vs {tuple(labels)}")
                return existing
            m = cls(full, help, labels, **kw)
            self._metrics[full] = m
            return m

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._declare(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._declare(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._declare(Histogram, name, help, labels, buckets=buckets)

    def metrics(self) -> List[_MetricBase]:
        with self._lock:
            return list(self._metrics.values())

    # -- exporters -------------------------------------------------------
    def to_prometheus(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        lines: List[str] = []
        for m in sorted(self.metrics(), key=lambda m: m.name):
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m._export())
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable dump of every series (counters/gauges:
        value; histograms: count/sum/min/max/buckets/quantiles)."""
        out: Dict[str, Any] = {"namespace": self.namespace,
                               "metrics": {}}
        for m in sorted(self.metrics(), key=lambda m: m.name):
            out["metrics"][m.name] = {
                "kind": m.kind,
                "help": m.help,
                "labels": list(m.label_names),
                "series": m._snap(),
            }
        return out

    def save_json(self, path: str) -> str:
        """Write :meth:`snapshot` to ``path`` (dirs created) — the bench
        harness's per-run metrics artifact."""
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.snapshot(), f, indent=1)
        return path

    def reset(self) -> None:
        """Zero every series (metric declarations survive) — test
        isolation for the process-wide default registry."""
        for m in self.metrics():
            m.clear()


_default: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    """Process-wide registry (created on first use) — mirrors
    ``utils.profiler.global_tracer``."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricsRegistry()
        return _default
