"""Base layer (L0–L1): logging/CHECK/Error, timer, env, registry, parameter,
config, thread-local store.  Reference: include/dmlc/{logging,timer,parameter,
registry,config,thread_local}.h (see SURVEY.md §2a)."""

from dmlc_core_tpu.base.thread_local import ThreadLocalStore  # noqa: F401
