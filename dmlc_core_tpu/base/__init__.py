"""Base layer (L0–L1): logging/CHECK/Error, timer, env, registry, parameter,
config.  Reference: include/dmlc/{logging,timer,parameter,registry,config}.h
(see SURVEY.md §2a)."""
