"""Base layer (L0–L1): logging/CHECK/Error, timer, env, registry, parameter,
config, thread-local store, metrics.  Reference: include/dmlc/{logging,timer,
parameter,registry,config,thread_local}.h (see SURVEY.md §2a); the metrics
registry is this framework's own (the reference has none — SURVEY.md §5)."""

from dmlc_core_tpu.base.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
)
from dmlc_core_tpu.base.resilience import (  # noqa: F401
    CircuitBreaker,
    CircuitOpenError,
    RetryPolicy,
)
from dmlc_core_tpu.base.thread_local import ThreadLocalStore  # noqa: F401
