"""Cross-process metrics aggregation: the spool-dir protocol + merge.

``base/metrics.py`` is deliberately process-local; since the fleet PRs,
every interesting run is N processes (PS scheduler/servers/workers,
routers/replicas/loadgen, JobSet ranks) and "what is the fleet-wide
p99" has no answer.  This module adds one without any new network
surface:

* **spool protocol** — every participating process periodically (and at
  exit, via ``atexit``) writes its registry
  :meth:`~dmlc_core_tpu.base.metrics.MetricsRegistry.snapshot` to
  ``$DMLC_METRICS_SPOOL/<role>-<rank>-<pid>.json`` through the atomic
  checkpoint writer (tmp + ``os.replace``), so a reader never sees a
  torn file.  When host tracing is on, the process's Tracer shard lands
  next to it as ``trace-<role>-<rank>-<pid>.json`` for
  ``scripts/trace_collect.py``.  :func:`install_spool` is the one-call
  wiring for role entrypoints: a no-op unless ``DMLC_METRICS_SPOOL`` is
  set, idempotent per process.
* **pure merge** — :func:`merge_snapshots` folds any number of
  snapshots into one fleet-wide view: counters sum, gauges resolve
  last-write-wins by their wall-clock ``ts``, histograms merge
  bucket-by-bucket (cumulative counts add exactly) with reservoir
  quantiles re-sampled weighted by each side's observation count.
  Merging a snapshot from a ``DMLC_METRICS=0`` process (no series) is
  a no-op by construction.

``scripts/check_*.py`` drills and ``bench.py`` call
:func:`merge_spool` at the end of a run to archive ONE fleet metrics
artifact instead of N invisible per-process registries; ``base/slo.py``
evaluates scorecards against the merged snapshot.
"""

from __future__ import annotations

import atexit
import json
import os
import random
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from dmlc_core_tpu.base import knobs as _knobs
from dmlc_core_tpu.base import metrics as _metrics
from dmlc_core_tpu.utils import profiler as _profiler

__all__ = ["SpoolWriter", "install_spool", "installed_spool",
           "merge_snapshots", "merge_spool", "write_snapshot"]

#: deterministic seed for reservoir re-sampling during merges — merging
#: the same shards twice must produce the same artifact
_MERGE_SEED = 0x51007


def _sanitize(token: str) -> str:
    return "".join(c if (c.isalnum() or c in "._") else "_"
                   for c in str(token)) or "proc"


def write_snapshot(path: str, snapshot: Dict[str, Any]) -> str:
    """Write one snapshot (or merged view) as JSON, atomically — readers
    racing the writer see the previous complete file, never a torn one."""
    d = os.path.dirname(os.path.abspath(path))
    if d:
        os.makedirs(d, exist_ok=True)
    data = json.dumps(snapshot, indent=1).encode()
    # lazy import: base -> parallel only inside the call, so module
    # import order stays acyclic
    from dmlc_core_tpu.parallel.checkpoint import _write_blob

    _write_blob(path, lambda stream: stream.write(data))
    return path


class SpoolWriter:
    """Periodic + at-exit snapshot spooler for one process.

    Writes ``<dir>/<role>-<rank>-<pid>.json`` every
    ``DMLC_METRICS_SPOOL_S`` seconds from a daemon flusher thread, and a
    ``trace-<role>-<rank>-<pid>.json`` Tracer shard at :meth:`close`
    when host tracing is enabled.  Respects ``DMLC_METRICS=0``: the
    metrics file is skipped entirely when collection is off.
    """

    def __init__(self, directory: str, role: str, rank: int = 0,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 period_s: Optional[float] = None) -> None:
        self.role = str(role)
        self.rank = int(rank)
        self._registry = (registry if registry is not None
                          else _metrics.default_registry())
        self._period = (float(period_s) if period_s is not None
                        else float(_knobs.value("DMLC_METRICS_SPOOL_S")))
        stem = f"{_sanitize(role)}-{self.rank}-{os.getpid()}"
        self.path = os.path.join(directory, stem + ".json")
        self.trace_path = os.path.join(directory, "trace-" + stem + ".json")
        self._writes = self._registry.counter(
            "spool_writes_total",
            "Metrics-spool snapshot files written by this process "
            "(base/metrics_agg).", labels=("role",))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "SpoolWriter":
        """First flush + start the periodic flusher (skipped when the
        period is <= 0; the at-exit flush still runs)."""
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self.flush()
        if self._period > 0 and self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"metrics-spool-{self.role}-{self.rank}")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self._period):
            try:
                self.flush(save_trace=False)
            except Exception:  # noqa: BLE001 — spooling must never kill work
                pass

    def flush(self, save_trace: bool = False) -> None:
        """Write the current snapshot (and, optionally, the trace
        shard) now."""
        if _metrics.enabled():
            self._writes.inc(1, role=self.role)
            write_snapshot(self.path, self._registry.snapshot())
        if save_trace and _profiler.tracing_enabled():
            tracer = _profiler.global_tracer()
            if tracer.events():
                tracer.save(self.trace_path)

    def close(self) -> None:
        """Stop the flusher thread and write the final snapshot + trace
        shard (also registered with ``atexit`` by
        :func:`install_spool`).  Idempotent."""
        self._stop.set()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=max(1.0, 2 * self._period))
        try:
            self.flush(save_trace=True)
        except Exception:  # noqa: BLE001 — exit path must not raise
            pass


_installed: Optional[SpoolWriter] = None
_install_lock = threading.Lock()


def install_spool(role: str, rank: int = 0,
                  registry: Optional[_metrics.MetricsRegistry] = None
                  ) -> Optional[SpoolWriter]:
    """Wire this process into the metrics spool: no-op (returns None)
    unless ``DMLC_METRICS_SPOOL`` names a directory; otherwise starts
    the periodic :class:`SpoolWriter`, stamps the process role/rank
    into the global Tracer's metadata, and registers the final flush
    with ``atexit``.  Idempotent — the first call wins."""
    global _installed
    directory = str(_knobs.value("DMLC_METRICS_SPOOL") or "")
    if not directory:
        return None
    with _install_lock:
        if _installed is not None:
            return _installed
        _installed = writer = SpoolWriter(directory, role, rank,
                                          registry=registry)
    _profiler.global_tracer().set_meta(role=role, rank=int(rank))
    atexit.register(writer.close)
    writer.start()
    return writer


def installed_spool() -> Optional[SpoolWriter]:
    """The process's active :class:`SpoolWriter`, if any."""
    return _installed


# ---------------------------------------------------------------------------
# pure merge
# ---------------------------------------------------------------------------

def _series_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _merge_counter(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    return {"labels": a["labels"], "value": a["value"] + b["value"]}


def _merge_gauge(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    # last write wins by wall timestamp; ties keep the later snapshot
    # (b), matching "the most recently read file is freshest"
    return a if a.get("ts", 0.0) > b.get("ts", 0.0) else b


def _quantiles(reservoir: List[float]) -> Dict[str, Optional[float]]:
    out: Dict[str, Optional[float]] = {}
    s = sorted(reservoir)
    for q in (0.5, 0.9, 0.99):
        if not s:
            out[f"p{int(q * 100)}"] = None
        else:
            idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
            out[f"p{int(q * 100)}"] = s[idx]
    return out


def _merge_reservoirs(ra: List[float], ca: int, rb: List[float], cb: int,
                      rng: random.Random) -> List[float]:
    if not ra:
        return list(rb)[:_metrics._RESERVOIR_SIZE]
    if not rb:
        return list(ra)[:_metrics._RESERVOIR_SIZE]
    size = min(_metrics._RESERVOIR_SIZE, len(ra) + len(rb))
    total = max(1, ca + cb)
    out = []
    for _ in range(size):
        pool = ra if rng.random() < ca / total else rb
        out.append(pool[rng.randrange(len(pool))])
    return out


def _merge_hist(name: str, a: Dict[str, Any], b: Dict[str, Any],
                rng: random.Random) -> Dict[str, Any]:
    bounds_a = [bk[0] for bk in a["buckets"]]
    bounds_b = [bk[0] for bk in b["buckets"]]
    if bounds_a != bounds_b:
        raise ValueError(
            f"merge_snapshots: histogram {name!r} bucket bounds differ "
            f"across processes ({bounds_a} vs {bounds_b})")
    # cumulative counts are additive: cum_union(b) = cum_a(b) + cum_b(b)
    buckets = [[bound, ca + cb] for (bound, ca), (_, cb)
               in zip(a["buckets"], b["buckets"])]
    count = a["count"] + b["count"]
    mins = [m for m in (a.get("min"), b.get("min")) if m is not None]
    maxs = [m for m in (a.get("max"), b.get("max")) if m is not None]
    reservoir = _merge_reservoirs(list(a.get("reservoir", ())), a["count"],
                                  list(b.get("reservoir", ())), b["count"],
                                  rng)
    return {
        "labels": a["labels"],
        "count": count,
        "sum": a["sum"] + b["sum"],
        "min": min(mins) if mins else None,
        "max": max(maxs) if maxs else None,
        "buckets": buckets,
        "quantiles": _quantiles(reservoir),
        "reservoir": reservoir,
    }


def merge_snapshots(snaps: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold registry snapshots into one fleet-wide view (pure, and
    deterministic for a given input order).

    Per series (metric name x label values): counters **sum**, gauges
    resolve **last-write-wins** by their ``ts``, histograms merge
    cumulative buckets exactly and re-sample reservoir quantiles
    weighted by count.  A metric declared with conflicting kinds across
    processes raises ``ValueError``; an empty snapshot (``DMLC_METRICS=0``
    process) contributes nothing."""
    rng = random.Random(_MERGE_SEED)
    merged: Dict[str, Any] = {"namespace": "dmlc", "metrics": {}}
    for snap in snaps:
        if not snap:
            continue
        if snap.get("namespace"):
            merged["namespace"] = snap["namespace"]
        for name, metric in (snap.get("metrics") or {}).items():
            have = merged["metrics"].get(name)
            if have is None:
                merged["metrics"][name] = {
                    "kind": metric["kind"],
                    "help": metric.get("help", ""),
                    "labels": list(metric.get("labels", ())),
                    "series": [dict(s) for s in metric.get("series", ())],
                }
                continue
            if have["kind"] != metric["kind"]:
                raise ValueError(
                    f"merge_snapshots: metric {name!r} declared as "
                    f"{have['kind']} and {metric['kind']} across "
                    "processes")
            by_key = {_series_key(s["labels"]): s for s in have["series"]}
            for s in metric.get("series", ()):
                key = _series_key(s["labels"])
                prev = by_key.get(key)
                if prev is None:
                    by_key[key] = dict(s)
                elif have["kind"] == "counter":
                    by_key[key] = _merge_counter(prev, s)
                elif have["kind"] == "gauge":
                    by_key[key] = dict(_merge_gauge(prev, s))
                else:
                    by_key[key] = _merge_hist(name, prev, s, rng)
            have["series"] = [by_key[k] for k in sorted(by_key)]
    merged["metrics"] = dict(sorted(merged["metrics"].items()))
    return merged


def merge_spool(directory: str) -> Tuple[Dict[str, Any], int]:
    """Read every snapshot file in a spool directory (trace shards are
    skipped) and return ``(merged_snapshot, processes_merged)``.  The
    merged snapshot carries ``processes_merged`` and the contributing
    ``spool_files`` so archived artifacts are self-describing."""
    snaps: List[Dict[str, Any]] = []
    files: List[str] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        names = []
    for name in names:
        if not name.endswith(".json") or name.startswith("trace-"):
            continue
        try:
            with open(os.path.join(directory, name)) as f:
                snap = json.load(f)
        except (OSError, ValueError):
            continue   # racing writer / foreign file: skip, don't fail
        if not isinstance(snap, dict) or "metrics" not in snap:
            continue   # not a registry snapshot (e.g. archived artifact)
        snaps.append(snap)
        files.append(name)
    merged = merge_snapshots(snaps)
    merged["processes_merged"] = len(files)
    merged["spool_files"] = files
    return merged, len(files)
