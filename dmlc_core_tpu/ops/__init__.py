"""Device ops (TPU-first additions; no reference counterpart).

The reference has no compute kernels — its consumers (XGBoost) brought
their own.  A TPU-native substrate must supply the device-side primitives
those consumers need, designed for XLA/MXU rather than translated:

* :mod:`histogram` — gradient histograms for hist-method tree growth
  (the FLOP core of BASELINE configs 1/3).
* :mod:`quantile` — distributed weighted quantile sketch for feature
  binning (config 3's variable-size sketch allreduce, done the TPU way:
  fixed-size summaries + allgather-merge).
"""

from dmlc_core_tpu.ops.histogram import build_histogram  # noqa: F401
from dmlc_core_tpu.ops.quantile import compute_cuts, apply_bins  # noqa: F401
