"""Gradient histograms for hist-method gradient boosting.

The hot op of XGBoost-style training (BASELINE config 1): for every tree
node, feature and bin, accumulate Σgrad and Σhess of the rows that land
there.  XLA formulations, selected by ``method``:

* ``"segment"`` — one flat ``segment_sum`` over the combined
  ``(node, feature, bin)`` index, run separately for grad and hess.
  Lowers to XLA scatter-add: fast on CPU, slow on TPU (scatter
  serializes); the CPU default.
* ``"matmul"`` — MXU formulation, the TPU default: scan over row blocks;
  per block the LHS ``[R, 2N]`` holds the node one-hot scaled by g (then
  h) and the RHS ``[R, F·B]`` is the bin one-hot, so ONE bf16 matmul
  with f32 accumulation (``preferred_element_type``) yields the whole
  block's contribution.  Blocking bounds the one-hot materialization to
  ~100MB regardless of n.
* ``"auto"`` — picks by backend platform (tpu → matmul, else segment).

TPU layout note: the result is ``[2, n_nodes, F, n_bins]`` with the
grad/hess plane LEADING.  A trailing axis of size 2 is catastrophic under
the TPU ``T(8,128)`` tiled layout — the minor dimension pads 2 → 128, a
64× memory blowup (observed as a 57GB alloc for a ``f32[112e6, 2]`` on a
16GB chip).  Never stack grad/hess on the minor axis of a large array.

All formulations are pure functions of arrays — safe inside
jit/shard_map; the data-parallel trainer psums the result over the mesh's
``data`` axis (the histogram-sync allreduce that replaces rabit's socket
tree, SURVEY.md §5; reference: rabit's Allreduce over
``tracker/dmlc_tracker/tracker.py :: get_tree`` topology).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from dmlc_core_tpu.base.logging import CHECK, log_fatal
from dmlc_core_tpu.ops import binlayout as _bl

__all__ = ["build_histogram", "fused_descend_histogram", "fused_round",
           "select_feature_bins", "histogram_methods",
           "reference_histogram", "hist_psum_bytes_per_round",
           "bins_bytes_per_round", "leaves_built_per_round",
           "quantize_hist_partial", "dequantize_hist_sum"]


def leaves_built_per_round(depth: int, grow_policy: str = "depthwise",
                           max_leaves: int = 0) -> int:
    """Histogram BUILDS one boosting round pays (sibling subtraction
    derives the rest for free).  Depth-wise: the root plus every level's
    left children — ``2^(depth-1)``.  Loss-guide builds only for the
    expanded leaf: the root plus one per expansion — ``max_leaves``
    total, independent of depth.  Feeds bench.py's
    ``kernel.leaves_built_per_round`` regression field."""
    if grow_policy == "lossguide":
        return min(max_leaves, 1 << depth) if max_leaves else 1 << depth
    return 1 if depth <= 1 else 1 << (depth - 1)


def hist_psum_bytes_per_round(depth: int, n_features: int,
                              n_bins: int, *, layout=None,
                              grow_policy: str = "depthwise",
                              max_leaves: int = 0,
                              quant: bool = False) -> int:
    """Per-chip bytes contributed to the in-step histogram-sync
    allreduce by ONE boosting round (one tree).

    Per level ℓ of the sibling-subtracted depth-wise engine only the
    built histograms cross the wire: the root at level 0, then LEFT
    children only (``n_build = 2^(ℓ-1)``).  The loss-guide engine syncs
    one built node per expansion (root + ``max_leaves − 1``).  Each
    built node is ``[2, S, Bs]`` f32 (grad + hess planes) where a
    non-trivial :class:`~dmlc_core_tpu.ops.binlayout.BinLayout` shrinks
    S below F (bundling) and Bs below B (histograms build and sync at
    the widest USED storage width, then zero-pad back before split
    evaluation).  This is the single analytic model behind bench.py's
    ``hist_psum_bytes_per_round`` field and the live
    ``dmlc_histogram_psum_bytes_total`` counter — the cross-chip
    traffic the multi-chip flagship pays per round (the rabit-allreduce
    replacement's byte bill).

    ``quant=True`` models the ``DMLC_HIST_QUANT`` int8 sync: per built
    node each (plane, feature) column crosses the wire as ``Bs`` int8
    cells plus one f32 scale and one f32 exact column total (the
    correction term) — ``2·S·(Bs + 8)`` bytes instead of
    ``2·S·Bs·4``, a ~3.9× cut at ``Bs = 256``.
    """
    if layout is not None:
        n_features = layout.storage_features
        n_bins = layout.sync_bins
    if quant:
        node_bytes = 2 * n_features * (n_bins + 8)
    else:
        node_bytes = 2 * n_features * n_bins * 4
    if grow_policy == "lossguide":
        return leaves_built_per_round(depth, "lossguide",
                                      max_leaves) * node_bytes
    total = 0
    for level in range(depth):
        n_build = 1 if level == 0 else 1 << (level - 1)
        total += n_build * node_bytes
    return total


def bins_bytes_per_round(depth: int, rows: int, row_bytes: int, *,
                         grow_policy: str = "depthwise",
                         max_leaves: int = 0,
                         fused: bool = False) -> int:
    """Bin-matrix HBM bytes ONE boosting round streams: the number of
    full passes over the ``[phys_rows, n]`` matrix times its size.

    Unfused depth-wise: level 0 is a histogram-only pass, every deeper
    level pays a descend pass plus a histogram pass, and the final leaf
    assignment is one more descend — ``2·depth − 1`` passes.  The fused
    round kernel (``DMLC_FUSED_ROUND``) collapses each level's descend +
    histogram + subtraction into ONE read of the bin tile, so the bill
    drops to ``depth`` passes (root build, ``depth − 2`` fused levels,
    final descend).  Loss-guide: one pass per expansion plus the
    root/final passes — ``2·leaves − 1`` unfused, ``leaves`` fused.
    Feeds bench.py's ``kernel.bins_bytes_per_round`` field and the HBM
    roofline estimate.
    """
    if grow_policy == "lossguide":
        leaves = leaves_built_per_round(depth, "lossguide", max_leaves)
        passes = leaves if fused else 2 * leaves - 1
    else:
        passes = depth if fused else 2 * depth - 1
    return max(passes, 1) * rows * row_bytes


def quantize_hist_partial(hist: jax.Array, gmax: jax.Array):
    """Quantize one chip's PARTIAL histogram for the int8 sync
    (``DMLC_HIST_QUANT=1``).  ``hist`` is the shard-local storage-space
    histogram ``[..., Bs]`` f32; ``gmax`` the GLOBAL (pmax-reduced)
    per-column ``[..., 1]`` absolute max, so every chip quantizes
    against the same scale and the int32 psum of the int8 codes is
    well-defined.  Returns ``(q int8, scale f32, tot f32)`` where
    ``tot`` is the EXACT f32 column total — the correction term that
    rides along the allreduce so per-(node, feature) grad/hess sums
    (what leaf weights integrate) stay exact."""
    scale = jnp.maximum(gmax, 1e-30) / 127.0
    q = jnp.clip(jnp.round(hist / scale), -127, 127).astype(jnp.int8)
    tot = jnp.sum(hist, axis=-1, keepdims=True)
    return q, scale, tot


def dequantize_hist_sum(q_sum: jax.Array, scale: jax.Array,
                        tot_sum: jax.Array) -> jax.Array:
    """Reconstruct the synced histogram from the psum of int8 codes.
    ``q_sum`` is the int32 psum of per-chip codes, ``scale`` the shared
    quantization scale, ``tot_sum`` the psum of EXACT column totals.
    The per-column correction spreads the (tiny) total quantization
    error uniformly so the reconstructed column sums to the exact
    total: cell error is bounded by ``n_chips · scale / 2`` while the
    (node, feature) totals — and hence leaf weights at a fixed split —
    carry NO quantization error."""
    approx = q_sum.astype(jnp.float32) * scale
    n_cells = approx.shape[-1]
    corr = (tot_sum - jnp.sum(approx, axis=-1, keepdims=True)) / n_cells
    return approx + corr

# rows per MXU block: one-hot RHS is [R, F·B] bf16 — at F=28, B=256 and
# R=8192 that is ~117MB, safely inside HBM working set while keeping the
# matmul [2N, R]·[R, F·B] large enough to saturate the systolic array.
_BLOCK_ROWS = 8192


def histogram_methods() -> list[str]:
    """Names of the available histogram engines (``auto`` resolves per
    platform: Pallas on TPU, matmul/segment elsewhere)."""
    return ["auto", "segment", "matmul", "pallas"]


#: pallas row-tile.  v5e sweeps: 8192 beat 4096 by 3-8% (round 2, 4M
#: rows); 16384 beats 8192 at the north-star 10M shape at most levels
#: (L0/L2/L3/L5 by 5-25%, L1/L4 within noise) — scripts/sweep_hist.py.
_TILE_ROWS = 16384


def _pack_factor(n_nodes: int, n_bins: int) -> int:
    """Row-subtiles packed per MXU dot (block-structured LHS so S row
    ranges share one [S·A, T] dot).  Measured on v5e: ALWAYS 1 — narrow
    dots do not pad to 128 sublanes (a [A, T]·[T, 128] dot costs ~A/128
    of a full pass), so packing only inflates the [S·A, T] one-hot
    construction, which is the actual per-level floor.  Kept as a
    seam for hardware where narrow matmuls do pay full freight."""
    return 1


def _pallas_ok(n_bins: int, n_features: int, n_nodes: int = 1,
               bins_itemsize: int = 1, tile_rows: int = 0) -> bool:
    """The factored kernel works for any n_bins; the binding constraints
    are (a) the [Fp, S·A, lo] f32 accumulator block — empirically
    calibrated on v5e at tile_rows=4096: nominal accumulators up to 32MB
    compile and run (Mosaic windows the out block; fori_loop temporaries
    are reused, so per-row working-set formulas wildly overestimate),
    64MB fails, 24MB keeps margin — and (b) the tile-scaled VMEM stack:
    per row-tile of T rows the kernel holds the [Fp, T] bins block, the
    int32 prep ([8,T] blk/t0s/los), the per-feature one-hots (oh [nh,T] +
    lhs [2nh,T] bf16, rhs [lo,T] bf16) and ~6 [1,T] i32/f32 vectors —
    ≈ T·(Fp·itemsize + 120 + 6·nh + 2·lo) bytes.  Calibration anchor:
    tile 65536 at lo=32, nh=8, Fp=32 predicts 17.3MB and measurably
    OOMs the 16MB scoped-vmem limit (sweep_hist, 10M rows); tile 16384
    at the deepest default level predicts 9.8MB and runs.  The 15MB
    budget keeps margin under the measured 16MB wall."""
    lo = _lo_factor(n_nodes, n_bins)
    hi = -(-n_bins // lo)
    fp = -(-n_features // 8) * 8
    nh = n_nodes * hi
    sa = _pack_factor(n_nodes, n_bins) * 2 * nh
    acc = fp * sa * max(lo, 128) * 4
    T = tile_rows or _TILE_ROWS
    tile_stack = T * (fp * bins_itemsize + 120 + 6 * nh + 2 * lo)
    return acc <= 24 << 20 and tile_stack <= 15 << 20


def build_histogram(
    bins: jax.Array,        # [n, F] uint8/int32 — binned feature matrix
    node_id: jax.Array,     # [n] int32 — tree-node assignment of each row
    grad: jax.Array,        # [n] f32
    hess: jax.Array,        # [n] f32
    n_nodes: int,
    n_bins: int,
    method: str = "auto",
    *,
    transposed: bool = False,
    layout=None,
) -> jax.Array:
    """Return ``hist[2, n_nodes, F, n_bins]`` — plane 0 Σgrad, plane 1 Σhess.

    Static ``n_nodes``/``n_bins`` keep shapes XLA-compilable; rows with
    ``node_id < 0`` (e.g. padding) contribute nothing.

    ``transposed=True`` means ``bins`` is already ``[F, n]`` — the Pallas
    kernel's native layout.  The training loop stores bins transposed so
    the per-level kernel never re-transposes the matrix (a full HBM
    round-trip per histogram otherwise).

    ``layout`` (a :class:`~dmlc_core_tpu.ops.binlayout.BinLayout`) means
    ``bins`` is the PHYSICAL ``[phys_rows, n]`` matrix (nibble-packed /
    bundled) and the result is the STORAGE-space histogram
    ``[2, n_nodes, S, layout.sync_bins]`` — callers unbundle/pad back to
    ``[2, N, F, n_bins]`` via ``binlayout.unbundle_hist`` before split
    evaluation.  The Pallas kernel reads packed bytes natively (the HBM
    win); segment/matmul unpack to the storage matrix first (exact
    integer nibble extraction, so cell values stay bit-identical to an
    unpacked build — the cross-method parity contract).
    """
    if layout is not None:
        CHECK(transposed, "layout= requires the transposed [F, n] matrix")
        n_bins = layout.sync_bins
        if method == "auto":
            if jax.default_backend() == "tpu":
                method = ("pallas" if _pallas_ok(n_bins, layout.phys_rows,
                                                 n_nodes, 1)
                          else "matmul")
            else:
                method = "segment"
        if method == "pallas" and not _pallas_ok(n_bins, layout.phys_rows,
                                                 n_nodes, 1):
            method = "matmul"
        if method == "pallas":
            if layout.pairs:
                return _hist_pallas(bins, node_id, grad, hess, n_nodes,
                                    n_bins, transposed=True, layout=layout)
            # bundle-only layout: physical == storage, plain kernel
            return _hist_pallas(bins, node_id, grad, hess, n_nodes,
                                n_bins, transposed=True)
        storage = _bl.unpack_matrix(bins, layout)
        if method == "segment":
            return _hist_segment(storage.T, node_id, grad, hess,
                                 n_nodes, n_bins)
        return _hist_matmul(storage.T, node_id, grad, hess,
                            n_nodes, n_bins)
    F = bins.shape[0] if transposed else bins.shape[1]
    itemsize = jnp.dtype(bins.dtype).itemsize
    if method == "auto":
        if jax.default_backend() == "tpu":
            method = ("pallas" if _pallas_ok(n_bins, F, n_nodes, itemsize)
                      else "matmul")
        else:
            method = "segment"
    if method == "pallas" and not _pallas_ok(n_bins, F, n_nodes, itemsize):
        method = "matmul"  # shapes the kernel can't tile — use the XLA path
    if method == "segment":
        return _hist_segment(bins.T if transposed else bins,
                             node_id, grad, hess, n_nodes, n_bins)
    if method == "matmul":
        return _hist_matmul(bins.T if transposed else bins,
                            node_id, grad, hess, n_nodes, n_bins)
    if method == "pallas":
        return _hist_pallas(bins, node_id, grad, hess, n_nodes, n_bins,
                            transposed=transposed)
    log_fatal(f"build_histogram: unknown method {method!r}")


@partial(jax.jit, static_argnums=(4, 5))
def _hist_segment(bins, node_id, grad, hess, n_nodes, n_bins):
    n, F = bins.shape
    valid = node_id >= 0
    safe_node = jnp.where(valid, node_id, 0)
    # combined segment id per (row, feature)
    feat_ids = jnp.arange(F, dtype=jnp.int32)[None, :]                    # [1, F]
    seg = (safe_node[:, None] * (F * n_bins)
           + feat_ids * n_bins
           + bins.astype(jnp.int32))                                      # [n, F]
    num = n_nodes * F * n_bins
    seg_flat = seg.reshape(n * F)

    def one(v):
        data = jnp.broadcast_to(jnp.where(valid, v, 0.0)[:, None], (n, F))
        return jax.ops.segment_sum(data.reshape(n * F), seg_flat, num_segments=num)

    return jnp.stack([one(grad), one(hess)]).reshape(2, n_nodes, F, n_bins)


@partial(jax.jit, static_argnums=(4, 5, 6))
def _hist_matmul(bins, node_id, grad, hess, n_nodes, n_bins,
                 block_rows: int = _BLOCK_ROWS):
    n, F = bins.shape
    # even out block sizes (rounded to sublane multiples) so padding is at
    # most nblk·8 rows — a fixed R would pad up to R-1 rows (≈2× work for
    # n just above a block multiple)
    nblk = -(-n // block_rows)
    per_blk = -(-n // nblk)
    R = -(-per_blk // 8) * 8
    pad = nblk * R - n
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        node_id = jnp.pad(node_id, (0, pad), constant_values=-1)
        grad = jnp.pad(grad, (0, pad))
        hess = jnp.pad(hess, (0, pad))
    nblk = (n + pad) // R
    blocks = (
        bins.reshape(nblk, R, F),
        node_id.reshape(nblk, R),
        grad.reshape(nblk, R),
        hess.reshape(nblk, R),
    )

    def body(acc, blk):
        b_bins, b_node, b_g, b_h = blk
        valid = b_node >= 0
        safe = jnp.where(valid, b_node, 0)
        node_oh = jax.nn.one_hot(safe, n_nodes, dtype=jnp.bfloat16)       # [R, N]
        g = jnp.where(valid, b_g, 0.0).astype(jnp.bfloat16)
        h = jnp.where(valid, b_h, 0.0).astype(jnp.bfloat16)
        lhs = jnp.concatenate(
            [node_oh * g[:, None], node_oh * h[:, None]], axis=1)         # [R, 2N]
        bin_oh = jax.nn.one_hot(
            b_bins.astype(jnp.int32), n_bins, dtype=jnp.bfloat16
        ).reshape(R, F * n_bins)                                          # [R, F·B]
        m = jax.lax.dot_general(
            lhs, bin_oh,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                                  # [2N, F·B]
        return acc + m, None

    acc0 = jnp.zeros((2 * n_nodes, F * n_bins), jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, blocks)
    return acc.reshape(2, n_nodes, F, n_bins)


def _hist_pallas_kernel(bins_ref, node_ref, g_ref, h_ref, out_ref,
                        *, n_nodes, hi, lo, pack, n_pack_groups=0):
    """One row-tile of the FACTORED, SUBTILE-PACKED one-hot matmul.

    bin = hi_part·lo + lo_part.  Per feature, ONE MXU dot
    ``[S·A, T] · [lo, T]ᵀ`` where A = 2·N·hi one-hot sublanes encode
    (grad/hess plane, node, hi_part) scaled by g/h, the RHS encodes
    lo_part, and ``pack`` = S independent row subtiles of T/S rows each
    share the dot: subtile s's rows one-hot only into sublane block
    [s·A, (s+1)·A), so cross-subtile terms vanish and the [S, A, lo]
    output slabs just sum.  This keeps the systolic array FULL at
    shallow tree levels — without packing a level with A=8 (root, 256
    bins) pads 8→128 sublanes and wastes 94% of the MXU; with it every
    level costs ~A/128 of a full pass and a depth-6 tree's histogram
    work drops from 6 full passes to ~1 (sibling subtraction at the
    call site halves A again).  One-hots live only in VMEM values
    (never HBM); HBM traffic is the bin matrix itself.

    Layout: everything arrives TRANSPOSED (rows on lanes — bins [F, T],
    node/g/h [1, T]) so the per-feature loop can be a fori_loop that
    dynamically slices the ref's major dim; a Python unroll over 28
    features blows the scoped-vmem stack, and Mosaic lowers neither
    dynamic_slice on values nor lane-dim dynamic ref slices.  Vector
    compares run in int32 (bf16/int16 compares rejected by this target).
    """
    i = pl.program_id(0)
    node = node_ref[:].astype(jnp.int32)                              # [1, T]
    g = g_ref[:].astype(jnp.bfloat16)                                 # [1, T]
    h = h_ref[:].astype(jnp.bfloat16)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    _accum_hist(bins_ref, out_ref, node, g, h,
                n_nodes=n_nodes, hi=hi, lo=lo, pack=pack,
                n_pack_groups=n_pack_groups)


def _accum_hist(bins_ref, out_ref, node, g, h, *, n_nodes, hi, lo, pack,
                n_pack_groups=0):
    """Shared histogram accumulation loop (see _hist_pallas_kernel doc).

    ``n_pack_groups`` > 0 marks the first ``8·n_pack_groups`` physical
    rows as NIBBLE-PACKED (two int4 storage features per byte, see
    ops/binlayout.py): each packed physical row emits TWO logical
    output rows — low nibble to ``2r``, high nibble to ``2r+1`` — so
    one HBM byte feeds two features' one-hot dots (the packed-bin HBM
    win).  The unpacked remainder follows at logical offset
    ``16·n_pack_groups``.  With ``n_pack_groups == 0`` the trace is
    IDENTICAL to the pre-layout kernel (the packed loop is not even
    traced), preserving bit-parity for the default path.
    """
    F, T = bins_ref.shape
    nh = n_nodes * hi
    nh_iota = jax.lax.broadcasted_iota(jnp.int32, (pack * nh, T), 0)
    lo_iota = jax.lax.broadcasted_iota(jnp.int32, (lo, T), 0)
    # sublane base of each row's subtile block: (r // (T/S)) · nh
    sub_base = (jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
                // (T // pack)) * nh
    valid = node >= 0
    t0_node = jnp.where(valid, sub_base + jnp.where(valid, node, 0) * hi,
                        jnp.int32(-(1 << 20)))                        # [1, T]

    def emit(t0s, los, k, row):
        # ONE [nh, T] compare then scale by g and h (the grad/hess
        # planes share the one-hot) — 2× cheaper than comparing a
        # [2·nh, T] iota twice.  compare→astype→mul (NOT where):
        # Mosaic can't relayout an i1 mask against a [1, T]-
        # replicated where operand.
        oh = (nh_iota == t0s[k:k + 1]).astype(jnp.bfloat16)           # [Snh, T]
        lhs = jnp.concatenate([oh * g, oh * h], axis=0)               # [2Snh, T]
        rhs = (lo_iota == los[k:k + 1]).astype(jnp.bfloat16)          # [lo, T]
        d = jax.lax.dot_general(
            lhs, rhs,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                              # [2Snh, lo]
        idx = (pl.ds(row, 1), slice(None), slice(None))
        out_ref[idx] = out_ref[idx] + d[None]

    if n_pack_groups:
        def pbody(fg, carry):
            base = pl.multiple_of(fg * 8, 8)
            blk = bins_ref[pl.ds(base, 8), :].astype(jnp.int32)       # [8, T]
            for nb, vals in ((0, blk & 15), (1, blk >> 4)):
                t0s = t0_node + vals // lo                            # [8, T]
                los = vals % lo                                       # [8, T]
                for k in range(8):
                    emit(t0s, los, k, 2 * (fg * 8 + k) + nb)
            return carry

        jax.lax.fori_loop(0, n_pack_groups, pbody, 0)
    log_off = 16 * n_pack_groups

    def body(fg, carry):
        # feature GROUPS of 8: sublane-dim ref slices must be 8-aligned
        # (pl.multiple_of proves it); within a group a static unroll —
        # a full 28-feature unroll blows the scoped-vmem stack.  The
        # integer prep runs BATCHED on [8, T] (a [1, T] op costs the
        # same VPU tiles as [8, T] — sublane padding), only the one-hot
        # compares are per-feature.
        base = pl.multiple_of(fg * 8 + 8 * n_pack_groups if n_pack_groups
                              else fg * 8, 8)
        blk = bins_ref[pl.ds(base, 8), :].astype(jnp.int32)           # [8, T]
        # padding rows carry t0_node ≈ -2^20 → t0 < 0 → match nothing
        t0s = t0_node + blk // lo                                     # [8, T]
        los = blk % lo                                                # [8, T]
        for k in range(8):
            emit(t0s, los, k, log_off + fg * 8 + k if n_pack_groups
                 else fg * 8 + k)
        return carry

    jax.lax.fori_loop(0, F // 8 - n_pack_groups, body, 0)


def _fused_kernel(bins_ref, node_ref, feat_ref, thr_ref, g_ref, h_ref,
                  out_ref, node_out_ref, *, n_prev, hi, lo, pack):
    """Descend one tree level AND build the new level's left-child
    histograms in one pass over the bin tile.

    Each row arrives with its level-(ℓ−1) node id and that node's chosen
    split (feat_sel, thr_sel, gathered outside).  Phase 1 extracts the
    selected feature's bin during a cheap batched sweep of the tile
    (compare-and-sum over sublane groups — the tile is already in VMEM,
    so the standalone descend's second HBM pass over the bin matrix
    disappears).  The advanced node id is written out, then phase 2 runs
    the shared histogram loop over LEFT children only (odd ids one-hot
    to nothing — sibling subtraction happens at the call site)."""
    i = pl.program_id(0)
    F, T = bins_ref.shape

    node = node_ref[:].astype(jnp.int32)                              # [1, T]
    g = g_ref[:].astype(jnp.bfloat16)
    h = h_ref[:].astype(jnp.bfloat16)
    fsel = feat_ref[:].astype(jnp.int32)                              # [1, T]
    tsel = thr_ref[:].astype(jnp.int32)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    g8_iota = jax.lax.broadcasted_iota(jnp.int32, (8, T), 0)

    def sel_body(fg, sel):
        base = pl.multiple_of(fg * 8, 8)
        blk = bins_ref[pl.ds(base, 8), :].astype(jnp.int32)           # [8, T]
        pick = (g8_iota + base == fsel).astype(jnp.int32)             # [8, T]
        return sel + jnp.sum(pick * blk, axis=0, keepdims=True)

    sel_bin = jax.lax.fori_loop(0, F // 8, sel_body,
                                jnp.zeros((1, T), jnp.int32))
    valid = node >= 0
    new_node = jnp.where(valid, 2 * node + (sel_bin > tsel), -1)      # [1, T]
    node_out_ref[:] = new_node

    # left children only: even ids → parent index, odd → build nothing
    node_h = jnp.where(valid & (new_node % 2 == 0), new_node >> 1, -1)
    _accum_hist(bins_ref, out_ref, node_h, g, h,
                n_nodes=n_prev, hi=hi, lo=lo, pack=pack)


def _fused_round_kernel(*refs, n_prev, hi, lo, pack, n_pack_groups,
                        with_layout):
    """ONE Pallas program for a whole tree level: bin-read → node
    descend → g/h scatter-accumulate → sibling subtraction, with the
    bin tile and both child histogram slabs resident in VMEM.

    Phase A (every row tile): extract each row's selected feature's bin
    during one batched sweep of the tile — with a layout the PHYSICAL
    byte is selected by physical source row, then nibble-extracted,
    bundle-decoded and compact-unmapped to the ORIGINAL bin id via
    per-row decode vectors (gathered outside from the static layout
    tables), so the threshold compare runs in the same original bin
    space as the XLA fallback (bit-exact integer descend).  The
    advanced node id is written out and the LEFT children accumulate
    into the left slab (the slab doubles as the cross-tile VMEM
    accumulator — the sequential TPU grid revisits block (0,0,0)).

    Phase B (last row tile only): sibling subtraction.  The previous
    level's histograms arrive PRE-MAPPED into the same accumulator
    layout ``[L, 2·N·hi, lo]``, so ``right = prev − left`` is one
    elementwise VPU pass over VMEM — the subtraction state never makes
    an HBM round-trip between phases.  The kernel emits only the two
    child slabs plus the new node vector; canonicalization back to
    ``[2, 2N, S, Bs]`` happens on the (node-sized, KB-scale) outputs.
    """
    if with_layout:
        (bins_ref, node_ref, src_ref, thr_ref, g_ref, h_ref,
         nib_ref, bnd_ref, off_ref, wid_ref, rmp_ref, occ_ref,
         prev_ref, left_ref, right_ref, node_out_ref) = refs
    else:
        (bins_ref, node_ref, src_ref, thr_ref, g_ref, h_ref,
         prev_ref, left_ref, right_ref, node_out_ref) = refs
    i = pl.program_id(0)
    F, T = bins_ref.shape

    node = node_ref[:].astype(jnp.int32)                              # [1, T]
    g = g_ref[:].astype(jnp.bfloat16)
    h = h_ref[:].astype(jnp.bfloat16)
    key = src_ref[:].astype(jnp.int32)     # physical row (layout) / feature
    tsel = thr_ref[:].astype(jnp.int32)

    @pl.when(i == 0)
    def _():
        left_ref[:] = jnp.zeros_like(left_ref)
        right_ref[:] = jnp.zeros_like(right_ref)

    g8_iota = jax.lax.broadcasted_iota(jnp.int32, (8, T), 0)

    def sel_body(fg, sel):
        base = pl.multiple_of(fg * 8, 8)
        blk = bins_ref[pl.ds(base, 8), :].astype(jnp.int32)           # [8, T]
        pick = (g8_iota + base == key).astype(jnp.int32)              # [8, T]
        return sel + jnp.sum(pick * blk, axis=0, keepdims=True)

    v = jax.lax.fori_loop(0, F // 8, sel_body,
                          jnp.zeros((1, T), jnp.int32))
    if with_layout:
        # physical byte → ORIGINAL bin id, mirroring binlayout.select_bins
        # exactly (integer relabelings — the descend stays bit-exact):
        # nibble extract, bundle segment decode, compact-remap inverse.
        nib = nib_ref[:].astype(jnp.int32)
        v = jnp.where(nib == 1, v >> 4, jnp.where(nib == 0, v & 15, v))
        off = off_ref[:].astype(jnp.int32)
        wid = wid_ref[:].astype(jnp.int32)
        in_seg = (v >= off) & (v < off + wid - 1)
        v = jnp.where(bnd_ref[:].astype(jnp.int32) == 1,
                      jnp.where(in_seg, v - off + 1, 0), v)
        occ_blk = occ_ref[:].astype(jnp.int32)                # [16, T]
        orig = jnp.zeros_like(v)
        for k in range(_bl.PACK_WIDTH):
            orig = orig + (v == k).astype(jnp.int32) * occ_blk[k:k + 1]
        v = jnp.where(rmp_ref[:].astype(jnp.int32) == 1, orig, v)
    valid = node >= 0
    new_node = jnp.where(valid, 2 * node + (v > tsel), -1)            # [1, T]
    node_out_ref[:] = new_node

    # left children only — the right slab comes from sibling subtraction
    node_h = jnp.where(valid & (new_node % 2 == 0), new_node >> 1, -1)
    _accum_hist(bins_ref, left_ref, node_h, g, h,
                n_nodes=n_prev, hi=hi, lo=lo, pack=pack,
                n_pack_groups=n_pack_groups)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        right_ref[:] = prev_ref[:] - left_ref[:]


def fused_round_ok(n_bins: int, n_features: int, n_prev: int = 1,
                   bins_itemsize: int = 1, tile_rows: int = 0,
                   with_layout: bool = False) -> bool:
    """Eligibility of the fused ROUND kernel (cf. :func:`_pallas_ok`):
    it holds THREE accumulator-shaped slabs in VMEM (prev, left, right)
    instead of one, and the layout mode streams five extra [1, T] int32
    decode vectors plus the [16, T] compact-remap table per tile."""
    lo = _lo_factor(n_prev, n_bins)
    hi = -(-n_bins // lo)
    fp = -(-n_features // 8) * 8
    nh = n_prev * hi
    sa = _pack_factor(n_prev, n_bins) * 2 * nh
    acc = fp * sa * max(lo, 128) * 4
    T = tile_rows or _TILE_ROWS
    extra = (5 * 4 + 16 * 4) if with_layout else 0
    tile_stack = T * (fp * bins_itemsize + 136 + extra + 6 * nh + 2 * lo)
    return 3 * acc <= 24 << 20 and tile_stack <= 15 << 20


def fused_round(
    bins_t: jax.Array,      # [F, n] (or physical [phys_rows, n] w/ layout)
    node_id: jax.Array,     # [n] — node ids at level ℓ−1 (−1 = padding)
    feat_sel: jax.Array,    # [n] — each row's node's chosen split feature
    thr_sel: jax.Array,     # [n] — chosen split threshold (ORIGINAL bin id)
    grad: jax.Array,
    hess: jax.Array,
    prev_hist: jax.Array,   # [2, n_prev, S, Bs] level-(ℓ−1) histograms
    n_prev: int,
    n_bins: int,
    *,
    tile_rows: int = _TILE_ROWS,
    lo: int = 0,
    layout=None,
    score_fn=None,
):
    """Advance rows one level AND produce BOTH children's histograms in
    one pass over the bin matrix: descend, left-child accumulation and
    sibling subtraction run inside one Pallas program per level (the
    fully-fused round kernel), so the only HBM traffic is the bin tile
    itself plus the per-node outputs.  Returns ``(new_node, hist,
    scores)`` with ``hist[_, c]`` the histogram of child ``c``
    (``2p``/``2p+1`` interleaved, STORAGE space under a layout — same
    shape/values as the unfused build+subtract+stack sequence, exactly)
    and ``scores = score_fn(hist)`` when a scoring closure is supplied
    (the per-node ``(feat, thr, gain, child stats)`` tuple), evaluated
    on the kernel's emitted histograms without re-reading any
    row-dimension array.

    Parity contract: the descend is exact integer relabeling and the
    accumulation order equals the plain Pallas histogram's, so with
    order-exact gradients (or on-TPU where the unfused path is the same
    kernel family) the result is bit-identical to the three-dispatch
    path — ``save_model`` byte parity, pinned by tests/test_fused_round.
    """
    Fphys, n = bins_t.shape
    Bs = layout.sync_bins if layout is not None else n_bins
    lo = min(lo or _lo_factor(n_prev, Bs), Bs)
    hi = -(-Bs // lo)
    A = 2 * n_prev * hi
    S = _pack_factor(n_prev, Bs)
    Fp = -(-Fphys // 8) * 8
    if layout is not None:
        npg = layout.packed_rows // 8
        L = 16 * npg + (Fp - 8 * npg)
        t = _bl.layout_tables(layout)
        perm = t["logical"]
        src_of = t["src"][t["owner"]]
        nib_of = t["nib"][t["owner"]]
        fs = feat_sel.astype(jnp.int32)
        key = jnp.asarray(src_of)[fs]
        extras = [jnp.asarray(nib_of)[fs],
                  jnp.asarray(t["bundled"].astype(np.int32))[fs],
                  jnp.asarray(t["off"])[fs],
                  jnp.asarray(t["wid"])[fs],
                  jnp.asarray(t["remap"].astype(np.int32))[fs]]
        occ = jnp.asarray(t["occ_pad"])[fs].T                  # [16, n]
    else:
        npg = 0
        L = Fp
        perm = np.arange(Fphys, dtype=np.int32)
        key = feat_sel.astype(jnp.int32)
        extras, occ = [], None
    pad = (-n) % tile_rows
    if pad:
        node_id = jnp.pad(node_id, (0, pad), constant_values=-1)
        key = jnp.pad(key, (0, pad))
        thr_sel = jnp.pad(thr_sel, (0, pad))
        grad = jnp.pad(grad, (0, pad))
        hess = jnp.pad(hess, (0, pad))
        extras = [jnp.pad(e, (0, pad)) for e in extras]
        if occ is not None:
            occ = jnp.pad(occ, ((0, 0), (0, pad)))
    n_pad = n + pad
    grid = n_pad // tile_rows
    bins_p = jnp.pad(bins_t, ((0, Fp - Fphys), (0, pad)))

    # previous level's histograms, PRE-MAPPED into the accumulator
    # layout [L, (gh, node, hi), lo] so the in-kernel subtraction is
    # elementwise (dead rows/cells are exact zeros on both sides)
    Sn = prev_hist.shape[2]
    prev_p = jnp.pad(prev_hist.astype(jnp.float32),
                     ((0, 0), (0, 0), (0, 0), (0, hi * lo - Bs)))
    prev_r = prev_p.reshape(2, n_prev, Sn, hi, lo)
    prev_r = prev_r.transpose(2, 0, 1, 3, 4).reshape(Sn, A, lo)
    prev_acc = jnp.zeros((L, A, lo), jnp.float32
                         ).at[jnp.asarray(perm)].set(prev_r)

    row_spec = pl.BlockSpec((1, tile_rows), lambda i: (0, i))
    in_specs = [pl.BlockSpec((Fp, tile_rows), lambda i: (0, i)),
                row_spec, row_spec, row_spec, row_spec, row_spec]
    operands = [bins_p, node_id.reshape(1, n_pad), key.reshape(1, n_pad),
                thr_sel.reshape(1, n_pad), grad.reshape(1, n_pad),
                hess.reshape(1, n_pad)]
    if layout is not None:
        in_specs += [row_spec] * 5
        operands += [e.reshape(1, n_pad) for e in extras]
        in_specs.append(pl.BlockSpec((_bl.PACK_WIDTH, tile_rows),
                                     lambda i: (0, i)))
        operands.append(occ)
    in_specs.append(pl.BlockSpec((L, S * A, lo), lambda i: (0, 0, 0)))
    operands.append(prev_acc)

    left, right, new_node = pl.pallas_call(
        partial(_fused_round_kernel, n_prev=n_prev, hi=hi, lo=lo, pack=S,
                n_pack_groups=npg, with_layout=layout is not None),
        out_shape=(
            jax.ShapeDtypeStruct((L, S * A, lo), jnp.float32),
            jax.ShapeDtypeStruct((L, S * A, lo), jnp.float32),
            jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
        ),
        grid=(grid,),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((L, S * A, lo), lambda i: (0, 0, 0)),
            pl.BlockSpec((L, S * A, lo), lambda i: (0, 0, 0)),
            pl.BlockSpec((1, tile_rows), lambda i: (0, i)),
        ),
        interpret=jax.default_backend() != "tpu",
    )(*operands)

    def canon(slab):
        x = slab.reshape(L, 2, S, n_prev, hi * lo).sum(axis=2)
        x = x[jnp.asarray(perm)]
        return x.transpose(1, 2, 0, 3)[..., :Bs]

    hist = jnp.stack([canon(left), canon(right)], axis=2)
    hist = hist.reshape(2, 2 * n_prev, Sn, Bs)
    new_node = new_node.reshape(n_pad)[:n]
    scores = score_fn(hist) if score_fn is not None else None
    return new_node, hist, scores


#: measured-best lo per n_build at n_bins=256 on v5e, tile 16384, 10M
#: rows (scripts/sweep_hist.py, 48-config sweep): the analytic 5A+2lo
#: model below agrees except n_build=2, where hardware prefers 32 over
#: the model's 64 (12.7 vs 14.9 ms).
_LO_MEASURED_256 = {1: 32, 2: 32, 4: 64, 8: 128, 16: 128}


def _lo_factor(n_nodes: int, n_bins: int) -> int:
    """Bin-factor split ``bin = hi·lo + lo_part``.  MXU work A·lo =
    2·N·n_bins is invariant in ``lo``, but the per-feature construction
    is ~c₁·A (LHS one-hots) + c₂·lo (RHS one-hot), so small ``lo``
    trades RHS compare traffic for LHS height.  At the default
    n_bins=256 the choice is pinned by measurement (sweep table above);
    other bin counts fall back to the op-count model, whose knee matched
    v5e hardware at every level except one."""
    if n_bins == 256 and n_nodes in _LO_MEASURED_256:
        return _LO_MEASURED_256[n_nodes]
    best, best_cost = 128, None
    for lo in (32, 64, 128):
        if lo > max(n_bins, 8):
            continue
        hi = -(-n_bins // lo)
        A = 2 * n_nodes * hi
        cost = 5 * A + 2 * lo          # construction op counts per element
        if best_cost is None or cost < best_cost:
            best, best_cost = lo, cost
    return best


@partial(jax.jit, static_argnums=(4, 5, 6, 7, 8, 9))
def _hist_pallas(bins, node_id, grad, hess, n_nodes, n_bins,
                 tile_rows: int = _TILE_ROWS, lo: int = 0,
                 transposed: bool = False, layout=None):
    """Pallas TPU path: grid over row tiles, all tiles accumulate into the
    same [F, S·A, lo] VMEM output block (sequential TPU grid ⇒ safe),
    then the S packed subtile slabs sum and one small reshape/transpose
    yields [2, N, F, B].

    With a nibble-packed ``layout`` the input is the PHYSICAL matrix:
    the kernel's packed region emits two logical rows per byte row, the
    logical output rows are permuted back to STORAGE feature order, and
    the result is the storage-space histogram [2, N, S, Bs]."""
    if transposed:
        F, n = bins.shape
    else:
        n, F = bins.shape
    lo = min(lo or _lo_factor(n_nodes, n_bins), n_bins)
    hi = -(-n_bins // lo)
    A = 2 * n_nodes * hi
    S = _pack_factor(n_nodes, n_bins)
    Fp = -(-F // 8) * 8          # feature groups of 8 (sublane alignment)
    npg = 0
    if layout is not None:
        npg = layout.packed_rows // 8          # packed physical groups
        # logical rows: 2 per packed physical row + the unpacked rest
        L = 16 * npg + (Fp - 8 * npg)
    else:
        L = Fp
    pad = (-n) % tile_rows
    if pad:
        node_id = jnp.pad(node_id, (0, pad), constant_values=-1)
        grad = jnp.pad(grad, (0, pad))
        hess = jnp.pad(hess, (0, pad))
    n_pad = n + pad
    grid = n_pad // tile_rows
    if transposed:
        bins_t = jnp.pad(bins, ((0, Fp - F), (0, pad)))
    else:
        bins_t = jnp.pad(bins.T, ((0, Fp - F), (0, pad)))

    out = pl.pallas_call(
        partial(_hist_pallas_kernel, n_nodes=n_nodes, hi=hi, lo=lo, pack=S,
                n_pack_groups=npg),
        out_shape=jax.ShapeDtypeStruct((L, S * A, lo), jnp.float32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((Fp, tile_rows), lambda i: (0, i)),
            pl.BlockSpec((1, tile_rows), lambda i: (0, i)),
            pl.BlockSpec((1, tile_rows), lambda i: (0, i)),
            pl.BlockSpec((1, tile_rows), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((L, S * A, lo), lambda i: (0, 0, 0)),
        interpret=jax.default_backend() != "tpu",
    )(bins_t, node_id.reshape(1, n_pad), grad.reshape(1, n_pad),
      hess.reshape(1, n_pad))
    if layout is not None:
        # kernel-logical rows → storage order (static permutation)
        perm = _bl.layout_tables(layout)["logical"]
        out = out.reshape(L, 2, S, n_nodes, hi * lo).sum(axis=2)
        out = out[jnp.asarray(perm)]
        out = out.transpose(1, 2, 0, 3)
        return out[..., :n_bins]
    # [Fp, (gh, S, N, hi), lo] → Σ over S → [gh, N, F, hi·lo] → slice pads
    out = out[:F].reshape(F, 2, S, n_nodes, hi * lo).sum(axis=2)
    out = out.transpose(1, 2, 0, 3)
    return out[..., :n_bins]


@partial(jax.jit, static_argnums=(6, 7, 8, 9))
def _fused_pallas(bins_t, node_id, feat_sel, thr_sel, grad, hess,
                  n_prev, n_bins, tile_rows: int = _TILE_ROWS, lo: int = 0):
    """Fused descend+histogram wrapper (bins already [F, n]).  Returns
    ``(left_hist [2, n_prev, F, B], new_node [n])`` where new_node is
    the level-ℓ assignment and left_hist[_, p] is the histogram of
    parent p's LEFT child."""
    F, n = bins_t.shape
    lo = min(lo or _lo_factor(n_prev, n_bins), n_bins)
    hi = -(-n_bins // lo)
    A = 2 * n_prev * hi
    S = _pack_factor(n_prev, n_bins)
    Fp = -(-F // 8) * 8
    pad = (-n) % tile_rows
    if pad:
        node_id = jnp.pad(node_id, (0, pad), constant_values=-1)
        feat_sel = jnp.pad(feat_sel, (0, pad))
        thr_sel = jnp.pad(thr_sel, (0, pad))
        grad = jnp.pad(grad, (0, pad))
        hess = jnp.pad(hess, (0, pad))
    n_pad = n + pad
    grid = n_pad // tile_rows
    bins_p = jnp.pad(bins_t, ((0, Fp - F), (0, pad)))

    hist, new_node = pl.pallas_call(
        partial(_fused_kernel, n_prev=n_prev, hi=hi, lo=lo, pack=S),
        out_shape=(
            jax.ShapeDtypeStruct((Fp, S * A, lo), jnp.float32),
            jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
        ),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((Fp, tile_rows), lambda i: (0, i)),
            pl.BlockSpec((1, tile_rows), lambda i: (0, i)),
            pl.BlockSpec((1, tile_rows), lambda i: (0, i)),
            pl.BlockSpec((1, tile_rows), lambda i: (0, i)),
            pl.BlockSpec((1, tile_rows), lambda i: (0, i)),
            pl.BlockSpec((1, tile_rows), lambda i: (0, i)),
        ],
        out_specs=(
            pl.BlockSpec((Fp, S * A, lo), lambda i: (0, 0, 0)),
            pl.BlockSpec((1, tile_rows), lambda i: (0, i)),
        ),
        interpret=jax.default_backend() != "tpu",
    )(bins_p, node_id.reshape(1, n_pad), feat_sel.reshape(1, n_pad),
      thr_sel.reshape(1, n_pad), grad.reshape(1, n_pad),
      hess.reshape(1, n_pad))
    out = hist[:F].reshape(F, 2, S, n_prev, hi * lo).sum(axis=2)
    out = out.transpose(1, 2, 0, 3)[..., :n_bins]
    return out, new_node.reshape(n_pad)[:n]


def fused_descend_histogram(
    bins_t: jax.Array,      # [F, n] — transposed binned matrix
    node_id: jax.Array,     # [n] — node ids at level ℓ−1 (−1 = padding)
    feat_sel: jax.Array,    # [n] — each row's node's chosen split feature
    thr_sel: jax.Array,     # [n] — chosen split threshold (bin index)
    grad: jax.Array,
    hess: jax.Array,
    n_prev: int,            # number of level-(ℓ−1) nodes
    n_bins: int,
    method: str = "auto",
    fuse: bool = False,
    dir_sel: jax.Array = None,  # [n] learned missing direction (1=left)
    miss_bin: int = None,       # bin index reserved for NaN rows
    layout=None,                # BinLayout: bins_t is the physical matrix
):
    """Advance rows one level down the tree and build the new level's
    LEFT-child histograms.  Returns ``(left_hist, new_node)`` with
    ``left_hist[_, p]`` the histogram of parent p's left child (node
    2p) — the caller derives the right child by sibling subtraction.
    Replaces rabit's per-level hist allreduce prep (SURVEY.md §2e
    data-parallel row).

    ``fuse=True`` runs descend + histogram as ONE Pallas kernel (single
    HBM read of the bin tile).  Measured on v5e it is mildly NEGATIVE
    (−5%: the serial in-kernel select loop beats XLA's overlapped
    standalone descend pass), so the default is the two-pass form; the
    fused kernel is kept for parts where HBM bandwidth, not VPU issue
    rate, binds."""
    F = bins_t.shape[0]
    itemsize = jnp.dtype(bins_t.dtype).itemsize
    use_pallas = (fuse and dir_sel is None and layout is None
                  and method in ("auto", "pallas")
                  and jax.default_backend() == "tpu"
                  and _pallas_ok(n_bins, F, n_prev, itemsize))
    if use_pallas:
        return _fused_pallas(bins_t, node_id, feat_sel, thr_sel,
                             grad, hess, n_prev, n_bins)
    # unfused fallback: XLA descend, then the regular histogram
    valid = node_id >= 0
    row_bin = select_feature_bins(bins_t, feat_sel, layout=layout)
    go_right = row_bin > thr_sel
    if dir_sel is not None:
        # learned missing direction: NaN rows (bin == miss_bin) follow
        # their node's dir bit (1 = left) instead of the threshold
        go_right = jnp.where(row_bin == miss_bin, dir_sel == 0, go_right)
    new_node = jnp.where(valid, 2 * node_id + go_right, -1)
    node_h = jnp.where(valid & (new_node % 2 == 0), new_node >> 1, -1)
    hist = build_histogram(bins_t, node_h, grad, hess, n_prev, n_bins,
                           method, transposed=True, layout=layout)
    return hist, new_node


def select_feature_bins(bins_t: jax.Array, feat_sel: jax.Array,
                        layout=None) -> jax.Array:
    """``bins_t[feat_sel[r], r]`` for every row r, gather-free.

    ``bins_t`` is feature-major [F, n]; a per-row gather over the row
    dimension serializes badly on TPU, so the selected feature's bin is
    extracted by compare-and-sum over the F rows (one [F, n] VPU pass).
    Shared by the tree descend in HistGBT (in-core and external-memory)
    and the unfused fused_descend_histogram fallback.  With ``layout``
    the matrix is physical (packed/bundled) and ``feat_sel`` indexes
    ORIGINAL features — ``binlayout.select_bins`` decodes nibbles and
    bundle segments after the same compare-and-sum pass.
    """
    if layout is not None:
        return _bl.select_bins(bins_t, feat_sel, layout)
    f_iota = jnp.arange(bins_t.shape[0], dtype=jnp.int32)[:, None]
    return jnp.sum(jnp.where(feat_sel[None, :] == f_iota,
                             bins_t.astype(jnp.int32), 0), axis=0)


def reference_histogram(bins, node_id, grad, hess, n_nodes, n_bins):
    """Numpy oracle for tests — same [2, N, F, B] shape as build_histogram."""
    bins = np.asarray(bins)
    node_id = np.asarray(node_id)
    out = np.zeros((2, n_nodes, bins.shape[1], n_bins), np.float64)
    for i in range(bins.shape[0]):
        if node_id[i] < 0:
            continue
        for f in range(bins.shape[1]):
            out[0, node_id[i], f, bins[i, f]] += grad[i]
            out[1, node_id[i], f, bins[i, f]] += hess[i]
    return out.astype(np.float32)
