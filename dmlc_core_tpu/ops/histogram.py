"""Gradient histograms for hist-method gradient boosting.

The hot op of XGBoost-style training (BASELINE config 1): for every tree
node, feature and bin, accumulate Σgrad and Σhess of the rows that land
there.  XLA formulations, selected by ``method``:

* ``"segment"`` — one flat ``segment_sum`` over the combined
  ``(node, feature, bin)`` index, run separately for grad and hess.
  Lowers to XLA scatter-add: fast on CPU, slow on TPU (scatter
  serializes); the CPU default.
* ``"matmul"`` — MXU formulation, the TPU default: scan over row blocks;
  per block the LHS ``[R, 2N]`` holds the node one-hot scaled by g (then
  h) and the RHS ``[R, F·B]`` is the bin one-hot, so ONE bf16 matmul
  with f32 accumulation (``preferred_element_type``) yields the whole
  block's contribution.  Blocking bounds the one-hot materialization to
  ~100MB regardless of n.
* ``"auto"`` — picks by backend platform (tpu → matmul, else segment).

TPU layout note: the result is ``[2, n_nodes, F, n_bins]`` with the
grad/hess plane LEADING.  A trailing axis of size 2 is catastrophic under
the TPU ``T(8,128)`` tiled layout — the minor dimension pads 2 → 128, a
64× memory blowup (observed as a 57GB alloc for a ``f32[112e6, 2]`` on a
16GB chip).  Never stack grad/hess on the minor axis of a large array.

All formulations are pure functions of arrays — safe inside
jit/shard_map; the data-parallel trainer psums the result over the mesh's
``data`` axis (the histogram-sync allreduce that replaces rabit's socket
tree, SURVEY.md §5; reference: rabit's Allreduce over
``tracker/dmlc_tracker/tracker.py :: get_tree`` topology).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from dmlc_core_tpu.base.logging import log_fatal

__all__ = ["build_histogram", "histogram_methods", "reference_histogram"]

# rows per MXU block: one-hot RHS is [R, F·B] bf16 — at F=28, B=256 and
# R=8192 that is ~117MB, safely inside HBM working set while keeping the
# matmul [2N, R]·[R, F·B] large enough to saturate the systolic array.
_BLOCK_ROWS = 8192


def histogram_methods() -> list[str]:
    return ["auto", "segment", "matmul", "pallas"]


_TILE_ROWS = 4096  # pallas row-tile; shared by the kernel and its guard


def _pallas_ok(n_bins: int, n_features: int, n_nodes: int = 1,
               bins_itemsize: int = 1) -> bool:
    """The factored kernel works for any n_bins; the binding constraint is
    the [Fp, A, lo] f32 accumulator block.  Empirically calibrated on
    v5e at tile_rows=4096: nominal accumulators up to 32MB compile and
    run (Mosaic windows the out block; fori_loop temporaries are reused,
    so per-row working-set formulas wildly overestimate), 64MB fails —
    the 24MB budget keeps a safety margin below the measured boundary.
    The [Fp, R] bins input block scales with the bin dtype
    (``bins_itemsize``): uint8 from apply_bins, int32 for >256 bins."""
    lo = min(n_bins, 128)
    hi = -(-n_bins // lo)
    fp = -(-n_features // 8) * 8
    acc = fp * 2 * n_nodes * hi * max(lo, 128) * 4
    bins_tile = fp * _TILE_ROWS * bins_itemsize
    return acc <= 24 << 20 and bins_tile <= 8 << 20


def build_histogram(
    bins: jax.Array,        # [n, F] uint8/int32 — binned feature matrix
    node_id: jax.Array,     # [n] int32 — tree-node assignment of each row
    grad: jax.Array,        # [n] f32
    hess: jax.Array,        # [n] f32
    n_nodes: int,
    n_bins: int,
    method: str = "auto",
) -> jax.Array:
    """Return ``hist[2, n_nodes, F, n_bins]`` — plane 0 Σgrad, plane 1 Σhess.

    Static ``n_nodes``/``n_bins`` keep shapes XLA-compilable; rows with
    ``node_id < 0`` (e.g. padding) contribute nothing.
    """
    itemsize = jnp.dtype(bins.dtype).itemsize
    if method == "auto":
        if jax.default_backend() == "tpu":
            method = ("pallas" if _pallas_ok(n_bins, bins.shape[1], n_nodes,
                                             itemsize)
                      else "matmul")
        else:
            method = "segment"
    if method == "pallas" and not _pallas_ok(n_bins, bins.shape[1], n_nodes,
                                             itemsize):
        method = "matmul"  # shapes the kernel can't tile — use the XLA path
    if method == "segment":
        return _hist_segment(bins, node_id, grad, hess, n_nodes, n_bins)
    if method == "matmul":
        return _hist_matmul(bins, node_id, grad, hess, n_nodes, n_bins)
    if method == "pallas":
        return _hist_pallas(bins, node_id, grad, hess, n_nodes, n_bins)
    log_fatal(f"build_histogram: unknown method {method!r}")


@partial(jax.jit, static_argnums=(4, 5))
def _hist_segment(bins, node_id, grad, hess, n_nodes, n_bins):
    n, F = bins.shape
    valid = node_id >= 0
    safe_node = jnp.where(valid, node_id, 0)
    # combined segment id per (row, feature)
    feat_ids = jnp.arange(F, dtype=jnp.int32)[None, :]                    # [1, F]
    seg = (safe_node[:, None] * (F * n_bins)
           + feat_ids * n_bins
           + bins.astype(jnp.int32))                                      # [n, F]
    num = n_nodes * F * n_bins
    seg_flat = seg.reshape(n * F)

    def one(v):
        data = jnp.broadcast_to(jnp.where(valid, v, 0.0)[:, None], (n, F))
        return jax.ops.segment_sum(data.reshape(n * F), seg_flat, num_segments=num)

    return jnp.stack([one(grad), one(hess)]).reshape(2, n_nodes, F, n_bins)


@partial(jax.jit, static_argnums=(4, 5, 6))
def _hist_matmul(bins, node_id, grad, hess, n_nodes, n_bins,
                 block_rows: int = _BLOCK_ROWS):
    n, F = bins.shape
    # even out block sizes (rounded to sublane multiples) so padding is at
    # most nblk·8 rows — a fixed R would pad up to R-1 rows (≈2× work for
    # n just above a block multiple)
    nblk = -(-n // block_rows)
    per_blk = -(-n // nblk)
    R = -(-per_blk // 8) * 8
    pad = nblk * R - n
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        node_id = jnp.pad(node_id, (0, pad), constant_values=-1)
        grad = jnp.pad(grad, (0, pad))
        hess = jnp.pad(hess, (0, pad))
    nblk = (n + pad) // R
    blocks = (
        bins.reshape(nblk, R, F),
        node_id.reshape(nblk, R),
        grad.reshape(nblk, R),
        hess.reshape(nblk, R),
    )

    def body(acc, blk):
        b_bins, b_node, b_g, b_h = blk
        valid = b_node >= 0
        safe = jnp.where(valid, b_node, 0)
        node_oh = jax.nn.one_hot(safe, n_nodes, dtype=jnp.bfloat16)       # [R, N]
        g = jnp.where(valid, b_g, 0.0).astype(jnp.bfloat16)
        h = jnp.where(valid, b_h, 0.0).astype(jnp.bfloat16)
        lhs = jnp.concatenate(
            [node_oh * g[:, None], node_oh * h[:, None]], axis=1)         # [R, 2N]
        bin_oh = jax.nn.one_hot(
            b_bins.astype(jnp.int32), n_bins, dtype=jnp.bfloat16
        ).reshape(R, F * n_bins)                                          # [R, F·B]
        m = jax.lax.dot_general(
            lhs, bin_oh,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                                  # [2N, F·B]
        return acc + m, None

    acc0 = jnp.zeros((2 * n_nodes, F * n_bins), jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, blocks)
    return acc.reshape(2, n_nodes, F, n_bins)


def _hist_pallas_kernel(bins_ref, node_ref, g_ref, h_ref, out_ref,
                        *, n_nodes, hi, lo):
    """One row-tile of the FACTORED one-hot matmul.

    bin = hi_part·lo + lo_part.  Per feature, ONE MXU dot
    ``[A, R] · [lo, R]ᵀ`` where the LHS one-hot encodes
    (grad/hess plane, node, hi_part) scaled by g/h and the RHS encodes
    lo_part.  With lo=128 and A = 2·N·hi ≤ 128 (true for every level of
    a depth-≤6 tree at 256 bins) both MXU dimensions are FULL — the
    naive ``[R, 2N]ᵀ·[R, F·B]`` layout pads 2N→128 sublanes and streams
    B/128 lane-tiles, wasting ≥2× the MXU cycles.  One-hots live only in
    VMEM values (never HBM); HBM traffic is the bin matrix itself.

    Layout: everything arrives TRANSPOSED (rows on lanes — bins [F, R],
    node/g/h [1, R]) so the per-feature loop can be a fori_loop that
    dynamically slices the ref's major dim; a Python unroll over 28
    features blows the scoped-vmem stack, and Mosaic lowers neither
    dynamic_slice on values nor lane-dim dynamic ref slices.  Vector
    compares run in int32 (bf16/int16 compares rejected by this target).
    """
    i = pl.program_id(0)
    F, R = bins_ref.shape

    node = node_ref[:].astype(jnp.int32)                              # [1, R]
    g = g_ref[:].astype(jnp.bfloat16)                                 # [1, R]
    h = h_ref[:].astype(jnp.bfloat16)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    a_iota = jax.lax.broadcasted_iota(jnp.int32, (n_nodes * hi, R), 0)
    lo_iota = jax.lax.broadcasted_iota(jnp.int32, (lo, R), 0)

    def body(fg, carry):
        # feature GROUPS of 8: sublane-dim ref slices must be 8-aligned
        # (pl.multiple_of proves it); within a group a static unroll —
        # a full 28-feature unroll blows the scoped-vmem stack
        base = pl.multiple_of(fg * 8, 8)
        blk = bins_ref[pl.ds(base, 8), :].astype(jnp.int32)           # [8, R]
        for k in range(8):
            bf = blk[k:k + 1]                                         # [1, R]
            # node<0 (padding) → acol negative → matches no row → 0 col
            acol = node * hi + bf // lo                               # [1, R]
            oh = (a_iota == acol).astype(jnp.bfloat16)                # [N·hi, R]
            lhs = jnp.concatenate([oh * g, oh * h], axis=0)           # [A, R]
            rhs = (lo_iota == bf % lo).astype(jnp.bfloat16)           # [lo, R]
            d = jax.lax.dot_general(
                lhs, rhs,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                                          # [A, lo]
            idx = (pl.ds(fg * 8 + k, 1), slice(None), slice(None))
            out_ref[idx] = out_ref[idx] + d[None]
        return carry

    jax.lax.fori_loop(0, F // 8, body, 0)


@partial(jax.jit, static_argnums=(4, 5, 6))
def _hist_pallas(bins, node_id, grad, hess, n_nodes, n_bins,
                 tile_rows: int = _TILE_ROWS):
    """Pallas TPU path: grid over row tiles, all tiles accumulate into the
    same [F, A, lo] VMEM output block (sequential TPU grid ⇒ safe), then
    one small reshape/transpose back to [2, N, F, B]."""
    n, F = bins.shape
    lo = min(n_bins, 128)
    hi = -(-n_bins // lo)
    A = 2 * n_nodes * hi
    Fp = -(-F // 8) * 8          # feature groups of 8 (sublane alignment)
    pad = (-n) % tile_rows
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        node_id = jnp.pad(node_id, (0, pad), constant_values=-1)
        grad = jnp.pad(grad, (0, pad))
        hess = jnp.pad(hess, (0, pad))
    n_pad = n + pad
    grid = n_pad // tile_rows
    bins_t = jnp.pad(bins.T, ((0, Fp - F), (0, 0)))

    out = pl.pallas_call(
        partial(_hist_pallas_kernel, n_nodes=n_nodes, hi=hi, lo=lo),
        out_shape=jax.ShapeDtypeStruct((Fp, A, lo), jnp.float32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((Fp, tile_rows), lambda i: (0, i)),
            pl.BlockSpec((1, tile_rows), lambda i: (0, i)),
            pl.BlockSpec((1, tile_rows), lambda i: (0, i)),
            pl.BlockSpec((1, tile_rows), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((Fp, A, lo), lambda i: (0, 0, 0)),
        interpret=jax.default_backend() != "tpu",
    )(bins_t, node_id.reshape(1, n_pad), grad.reshape(1, n_pad),
      hess.reshape(1, n_pad))
    # [Fp, (gh, N, hi), lo] → [gh, N, F, hi·lo] → slice feature/bin pads
    out = out[:F].reshape(F, 2, n_nodes, hi * lo).transpose(1, 2, 0, 3)
    return out[..., :n_bins]


def reference_histogram(bins, node_id, grad, hess, n_nodes, n_bins):
    """Numpy oracle for tests — same [2, N, F, B] shape as build_histogram."""
    bins = np.asarray(bins)
    node_id = np.asarray(node_id)
    out = np.zeros((2, n_nodes, bins.shape[1], n_bins), np.float64)
    for i in range(bins.shape[0]):
        if node_id[i] < 0:
            continue
        for f in range(bins.shape[1]):
            out[0, node_id[i], f, bins[i, f]] += grad[i]
            out[1, node_id[i], f, bins[i, f]] += hess[i]
    return out.astype(np.float32)
