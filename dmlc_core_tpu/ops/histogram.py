"""Gradient histograms for hist-method gradient boosting.

The hot op of XGBoost-style training (BASELINE config 1): for every tree
node, feature and bin, accumulate Σgrad and Σhess of the rows that land
there.  XLA formulations, selected by ``method``:

* ``"segment"`` — one flat ``segment_sum`` over the combined
  ``(node, feature, bin)`` index, run separately for grad and hess.
  Lowers to XLA scatter-add: fast on CPU, slow on TPU (scatter
  serializes); the CPU default.
* ``"matmul"`` — MXU formulation, the TPU default: scan over row blocks;
  per block the LHS ``[R, 2N]`` holds the node one-hot scaled by g (then
  h) and the RHS ``[R, F·B]`` is the bin one-hot, so ONE bf16 matmul
  with f32 accumulation (``preferred_element_type``) yields the whole
  block's contribution.  Blocking bounds the one-hot materialization to
  ~100MB regardless of n.
* ``"auto"`` — picks by backend platform (tpu → matmul, else segment).

TPU layout note: the result is ``[2, n_nodes, F, n_bins]`` with the
grad/hess plane LEADING.  A trailing axis of size 2 is catastrophic under
the TPU ``T(8,128)`` tiled layout — the minor dimension pads 2 → 128, a
64× memory blowup (observed as a 57GB alloc for a ``f32[112e6, 2]`` on a
16GB chip).  Never stack grad/hess on the minor axis of a large array.

All formulations are pure functions of arrays — safe inside
jit/shard_map; the data-parallel trainer psums the result over the mesh's
``data`` axis (the histogram-sync allreduce that replaces rabit's socket
tree, SURVEY.md §5; reference: rabit's Allreduce over
``tracker/dmlc_tracker/tracker.py :: get_tree`` topology).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from dmlc_core_tpu.base.logging import log_fatal

__all__ = ["build_histogram", "histogram_methods", "reference_histogram"]

# rows per MXU block: one-hot RHS is [R, F·B] bf16 — at F=28, B=256 and
# R=8192 that is ~117MB, safely inside HBM working set while keeping the
# matmul [2N, R]·[R, F·B] large enough to saturate the systolic array.
_BLOCK_ROWS = 8192


def histogram_methods() -> list[str]:
    return ["auto", "segment", "matmul", "pallas"]


def _pallas_ok(n_bins: int, n_features: int, n_nodes: int = 1) -> bool:
    """The pallas kernel needs every per-feature one-hot slice
    ``oh_ref[:, f·B:(f+1)·B]`` lane-aligned — i.e. ``n_bins % 128 == 0``,
    not merely F·B — and a VMEM-resident accumulator (one-hot scratch
    ~7MB at HIGGS shapes + [2N, F·B] f32)."""
    fb = n_features * n_bins
    vmem = 512 * fb * 2 + 2 * n_nodes * fb * 4
    return n_bins % 128 == 0 and vmem <= 12 << 20


def build_histogram(
    bins: jax.Array,        # [n, F] uint8/int32 — binned feature matrix
    node_id: jax.Array,     # [n] int32 — tree-node assignment of each row
    grad: jax.Array,        # [n] f32
    hess: jax.Array,        # [n] f32
    n_nodes: int,
    n_bins: int,
    method: str = "auto",
) -> jax.Array:
    """Return ``hist[2, n_nodes, F, n_bins]`` — plane 0 Σgrad, plane 1 Σhess.

    Static ``n_nodes``/``n_bins`` keep shapes XLA-compilable; rows with
    ``node_id < 0`` (e.g. padding) contribute nothing.
    """
    if method == "auto":
        if jax.default_backend() == "tpu":
            method = ("pallas" if _pallas_ok(n_bins, bins.shape[1], n_nodes)
                      else "matmul")
        else:
            method = "segment"
    if method == "pallas" and not _pallas_ok(n_bins, bins.shape[1], n_nodes):
        method = "matmul"  # shapes the kernel can't tile — use the XLA path
    if method == "segment":
        return _hist_segment(bins, node_id, grad, hess, n_nodes, n_bins)
    if method == "matmul":
        return _hist_matmul(bins, node_id, grad, hess, n_nodes, n_bins)
    if method == "pallas":
        return _hist_pallas(bins, node_id, grad, hess, n_nodes, n_bins)
    log_fatal(f"build_histogram: unknown method {method!r}")


@partial(jax.jit, static_argnums=(4, 5))
def _hist_segment(bins, node_id, grad, hess, n_nodes, n_bins):
    n, F = bins.shape
    valid = node_id >= 0
    safe_node = jnp.where(valid, node_id, 0)
    # combined segment id per (row, feature)
    feat_ids = jnp.arange(F, dtype=jnp.int32)[None, :]                    # [1, F]
    seg = (safe_node[:, None] * (F * n_bins)
           + feat_ids * n_bins
           + bins.astype(jnp.int32))                                      # [n, F]
    num = n_nodes * F * n_bins
    seg_flat = seg.reshape(n * F)

    def one(v):
        data = jnp.broadcast_to(jnp.where(valid, v, 0.0)[:, None], (n, F))
        return jax.ops.segment_sum(data.reshape(n * F), seg_flat, num_segments=num)

    return jnp.stack([one(grad), one(hess)]).reshape(2, n_nodes, F, n_bins)


@partial(jax.jit, static_argnums=(4, 5, 6))
def _hist_matmul(bins, node_id, grad, hess, n_nodes, n_bins,
                 block_rows: int = _BLOCK_ROWS):
    n, F = bins.shape
    # even out block sizes (rounded to sublane multiples) so padding is at
    # most nblk·8 rows — a fixed R would pad up to R-1 rows (≈2× work for
    # n just above a block multiple)
    nblk = -(-n // block_rows)
    per_blk = -(-n // nblk)
    R = -(-per_blk // 8) * 8
    pad = nblk * R - n
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        node_id = jnp.pad(node_id, (0, pad), constant_values=-1)
        grad = jnp.pad(grad, (0, pad))
        hess = jnp.pad(hess, (0, pad))
    nblk = (n + pad) // R
    blocks = (
        bins.reshape(nblk, R, F),
        node_id.reshape(nblk, R),
        grad.reshape(nblk, R),
        hess.reshape(nblk, R),
    )

    def body(acc, blk):
        b_bins, b_node, b_g, b_h = blk
        valid = b_node >= 0
        safe = jnp.where(valid, b_node, 0)
        node_oh = jax.nn.one_hot(safe, n_nodes, dtype=jnp.bfloat16)       # [R, N]
        g = jnp.where(valid, b_g, 0.0).astype(jnp.bfloat16)
        h = jnp.where(valid, b_h, 0.0).astype(jnp.bfloat16)
        lhs = jnp.concatenate(
            [node_oh * g[:, None], node_oh * h[:, None]], axis=1)         # [R, 2N]
        bin_oh = jax.nn.one_hot(
            b_bins.astype(jnp.int32), n_bins, dtype=jnp.bfloat16
        ).reshape(R, F * n_bins)                                          # [R, F·B]
        m = jax.lax.dot_general(
            lhs, bin_oh,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                                  # [2N, F·B]
        return acc + m, None

    acc0 = jnp.zeros((2 * n_nodes, F * n_bins), jnp.float32)
    acc, _ = jax.lax.scan(body, acc0, blocks)
    return acc.reshape(2, n_nodes, F, n_bins)


def _hist_pallas_kernel(bins_ref, node_ref, g_ref, h_ref, out_ref, oh_ref):
    """One row-tile: build the [R, F·B] bin one-hot IN VMEM and dot it.

    The fusion is the whole point: the XLA matmul formulation writes the
    one-hot to HBM every level (~F·B bytes/row/level — hundreds of GB per
    round at HIGGS scale); here it lives in a VMEM scratch and never
    leaves the chip, so HBM traffic drops to the bin matrix itself and the
    VPU compare + one MXU dot set the pace (measured 3.2× over the XLA
    matmul path at HIGGS shapes on v5e).

    Notes from target bring-up: one-hots are built per feature at
    ``[R, B]`` (B on lanes — collapsing a 3D ``[R, F, B]`` is an
    unsupported shape cast in Mosaic) and compares run in int32 (bf16 and
    int16 vector compares are rejected by this target).
    """
    i = pl.program_id(0)
    R, F = bins_ref.shape
    two_n, FB = out_ref.shape
    B = FB // F
    n_nodes = two_n // 2

    bins_i = bins_ref[:].astype(jnp.int32)                            # [R, F]
    node = node_ref[:].astype(jnp.int32)                              # [R, 1]
    n_iota = jax.lax.broadcasted_iota(jnp.int32, (R, n_nodes), 1)
    node_oh = (n_iota == node).astype(jnp.bfloat16)  # node<0 → all-zero row
    g = g_ref[:].astype(jnp.bfloat16)                                 # [R, 1]
    h = h_ref[:].astype(jnp.bfloat16)
    lhs = jnp.concatenate([node_oh * g, node_oh * h], axis=1)         # [R, 2N]

    b_iota = jax.lax.broadcasted_iota(jnp.int32, (R, B), 1)
    for f in range(F):  # F is static; unrolled at trace time
        oh_ref[:, f * B:(f + 1) * B] = (
            bins_i[:, f:f + 1] == b_iota).astype(jnp.bfloat16)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[:] += jax.lax.dot_general(
        lhs, oh_ref[:],
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@partial(jax.jit, static_argnums=(4, 5, 6))
def _hist_pallas(bins, node_id, grad, hess, n_nodes, n_bins,
                 tile_rows: int = 512):
    """Pallas TPU path: grid over row tiles, all tiles accumulate into the
    same [2N, F·B] VMEM output block (sequential TPU grid ⇒ safe)."""
    n, F = bins.shape
    pad = (-n) % tile_rows
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        node_id = jnp.pad(node_id, (0, pad), constant_values=-1)
        grad = jnp.pad(grad, (0, pad))
        hess = jnp.pad(hess, (0, pad))
    n_pad = n + pad
    grid = n_pad // tile_rows
    from jax.experimental.pallas import tpu as pltpu

    out = pl.pallas_call(
        _hist_pallas_kernel,
        out_shape=jax.ShapeDtypeStruct((2 * n_nodes, F * n_bins), jnp.float32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((tile_rows, F), lambda i: (i, 0)),
            pl.BlockSpec((tile_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((tile_rows, 1), lambda i: (i, 0)),
            pl.BlockSpec((tile_rows, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((2 * n_nodes, F * n_bins), lambda i: (0, 0)),
        scratch_shapes=[pltpu.VMEM((tile_rows, F * n_bins), jnp.bfloat16)],
        interpret=jax.default_backend() != "tpu",
    )(bins, node_id.reshape(n_pad, 1), grad.reshape(n_pad, 1),
      hess.reshape(n_pad, 1))
    return out.reshape(2, n_nodes, F, n_bins)


def reference_histogram(bins, node_id, grad, hess, n_nodes, n_bins):
    """Numpy oracle for tests — same [2, N, F, B] shape as build_histogram."""
    bins = np.asarray(bins)
    node_id = np.asarray(node_id)
    out = np.zeros((2, n_nodes, bins.shape[1], n_bins), np.float64)
    for i in range(bins.shape[0]):
        if node_id[i] < 0:
            continue
        for f in range(bins.shape[1]):
            out[0, node_id[i], f, bins[i, f]] += grad[i]
            out[1, node_id[i], f, bins[i, f]] += hess[i]
    return out.astype(np.float32)
