"""Gradient histograms for hist-method gradient boosting.

The hot op of XGBoost-style training (BASELINE config 1): for every tree
node, feature and bin, accumulate Σgrad and Σhess of the rows that land
there.  Two XLA formulations, selected by ``method``:

* ``"segment"`` — one flat ``segment_sum`` over the combined
  ``(node, feature, bin)`` index.  O(n·F) memory traffic; lowers to XLA
  scatter-add.  Best on CPU and the general-purpose default.
* ``"onehot"`` — MXU formulation: per feature, a ``[2·nodes, n] @ [n, B]``
  bf16 matmul where the LHS rows are the node one-hot scaled by g (then h)
  and the RHS is the bin one-hot.  Turns the scatter into dense matmuls the
  systolic array eats; preferable on TPU when ``nodes`` is small (early
  levels) and B is moderate.  fp32 accumulation via
  ``preferred_element_type``.

Both are pure functions of arrays — safe inside jit/shard_map; the
data-parallel trainer psums the result over the mesh's ``data`` axis
(the histogram-sync allreduce that replaces rabit's socket tree,
SURVEY.md §5).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from dmlc_core_tpu.base.logging import log_fatal

__all__ = ["build_histogram", "histogram_methods"]


def histogram_methods() -> list[str]:
    return ["segment", "onehot"]


def build_histogram(
    bins: jax.Array,        # [n, F] uint8/int32 — binned feature matrix
    node_id: jax.Array,     # [n] int32 — tree-node assignment of each row
    grad: jax.Array,        # [n] f32
    hess: jax.Array,        # [n] f32
    n_nodes: int,
    n_bins: int,
    method: str = "segment",
) -> jax.Array:
    """Return ``hist[n_nodes, F, n_bins, 2]`` with (Σgrad, Σhess).

    Static ``n_nodes``/``n_bins`` keep shapes XLA-compilable; rows with
    ``node_id < 0`` (e.g. padding) contribute nothing.
    """
    if method == "segment":
        return _hist_segment(bins, node_id, grad, hess, n_nodes, n_bins)
    if method == "onehot":
        return _hist_onehot(bins, node_id, grad, hess, n_nodes, n_bins)
    log_fatal(f"build_histogram: unknown method {method!r}")


@partial(jax.jit, static_argnums=(4, 5))
def _hist_segment(bins, node_id, grad, hess, n_nodes, n_bins):
    n, F = bins.shape
    valid = node_id >= 0
    safe_node = jnp.where(valid, node_id, 0)
    # combined segment id per (row, feature)
    feat_ids = jnp.arange(F, dtype=jnp.int32)[None, :]                    # [1, F]
    seg = (safe_node[:, None] * (F * n_bins)
           + feat_ids * n_bins
           + bins.astype(jnp.int32))                                      # [n, F]
    gmask = jnp.where(valid, grad, 0.0)
    hmask = jnp.where(valid, hess, 0.0)
    data = jnp.stack(
        [jnp.broadcast_to(gmask[:, None], (n, F)),
         jnp.broadcast_to(hmask[:, None], (n, F))], axis=-1)              # [n, F, 2]
    flat = jax.ops.segment_sum(
        data.reshape(n * F, 2),
        seg.reshape(n * F),
        num_segments=n_nodes * F * n_bins,
    )
    return flat.reshape(n_nodes, F, n_bins, 2)


@partial(jax.jit, static_argnums=(4, 5))
def _hist_onehot(bins, node_id, grad, hess, n_nodes, n_bins):
    n, F = bins.shape
    valid = node_id >= 0
    safe_node = jnp.where(valid, node_id, 0)
    node_oh = jax.nn.one_hot(safe_node, n_nodes, dtype=jnp.bfloat16)      # [n, N]
    gmask = jnp.where(valid, grad, 0.0).astype(jnp.bfloat16)
    hmask = jnp.where(valid, hess, 0.0).astype(jnp.bfloat16)
    # LHS [n, 2N]: node one-hot scaled by g | by h → one matmul per feature
    lhs = jnp.concatenate([node_oh * gmask[:, None], node_oh * hmask[:, None]], axis=1)

    def per_feature(bins_f):
        bin_oh = jax.nn.one_hot(bins_f, n_bins, dtype=jnp.bfloat16)       # [n, B]
        m = jax.lax.dot_general(
            lhs, bin_oh,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                                  # [2N, B]
        return m

    ms = jax.lax.map(per_feature, bins.T.astype(jnp.int32))               # [F, 2N, B]
    ms = ms.reshape(F, 2, n_nodes, n_bins)
    return jnp.transpose(ms, (2, 0, 3, 1))                                # [N, F, B, 2]


def reference_histogram(bins, node_id, grad, hess, n_nodes, n_bins):
    """Numpy oracle for tests."""
    bins = np.asarray(bins)
    node_id = np.asarray(node_id)
    out = np.zeros((n_nodes, bins.shape[1], n_bins, 2), np.float64)
    for i in range(bins.shape[0]):
        if node_id[i] < 0:
            continue
        for f in range(bins.shape[1]):
            out[node_id[i], f, bins[i, f], 0] += grad[i]
            out[node_id[i], f, bins[i, f], 1] += hess[i]
    return out.astype(np.float32)
