"""Per-feature bin-width layouts: int4 bin packing + exclusive feature
bundling for the histogram round kernel.

The transposed bin matrix the round program streams every level is
``uint8 [F, n]`` regardless of how many bins each feature actually
uses — a 2-valued flag burns the same HBM bandwidth as a 256-bin
continuous feature.  A :class:`BinLayout` describes two exact,
independently-gated storage transforms (LightGBM's EFB and int4
packing, adapted to the TPU feature-major layout):

* **Packing** (``DMLC_BIN_PACK=1``): storage features whose OCCUPIED
  bin count is ≤ 16 are compact-remapped (occupied original bin ids →
  dense ``[0, count)``) and paired two-per-byte (low/high nibble) in
  the physical matrix — halving the HBM bin traffic the histogram
  kernel pays for narrow features.  Remap + nibble extraction are
  exact integer relabelings, so every histogram method produces
  bit-identical cell values once :func:`unbundle_hist` scatters cells
  back to original bin positions (pinned by tests/test_binpack.py).
* **Bundling** (``DMLC_FEATURE_BUNDLE=1``): mutually-exclusive
  (near-one-hot) feature blocks fuse into ONE multi-bin storage
  feature.  Member f's bins ``[1, w_f)`` map to the storage segment
  ``[off_f, off_f + w_f - 1)``; storage bin 0 means "every member at
  its default bin 0".  Exclusivity is verified EXACTLY on the full
  matrix before a bundle is kept (the sampled detector only proposes),
  and :func:`unbundle_hist` reconstructs per-member histograms at
  split-evaluation time, so split decisions stay in the ORIGINAL
  feature space and ``save_model`` bytes are unchanged whenever no
  bundle fires (a trivial layout is represented as ``None`` — the
  untouched seed code path).

Layouts are hashable (jit-static) and mesh-shape-independent: widths
come from a global max over the binned matrix, so 1-chip and N-chip
fits derive the SAME layout and the ``DMLC_HIST_BLOCKS`` byte-parity
contract survives both knobs.
"""

from __future__ import annotations

from functools import lru_cache
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dmlc_core_tpu.base.logging import CHECK

__all__ = ["BinLayout", "compute_layout", "used_bin_widths",
           "bin_counts", "compact_counts", "default_bins",
           "detect_bundles", "pack_matrix", "unpack_matrix",
           "unbundle_hist", "select_bins", "PACK_WIDTH"]

#: max COMPACT bin count eligible for nibble packing (two features/byte)
PACK_WIDTH = 16


class BinLayout(NamedTuple):
    """Static storage layout of the transposed bin matrix.

    ``members[s]`` lists the original features carried by storage
    feature ``s`` as ``(orig_feat, offset, width)`` triples — length 1
    for a plain feature (offset 0), >1 for a bundle.  ``pairs`` holds
    nibble-packed storage-row pairs (``byte = lo | hi << 4``) and
    ``singles`` the remaining storage rows in physical order; the
    packed region is padded to an 8-row multiple (Pallas sublane
    groups) with zero rows.

    ``bin_maps[f]`` is the COMPACT bin remap of original feature ``f``:
    the sorted tuple of occupied original bin ids (always including 0),
    or ``None`` for a wide feature stored at its raw ids.  Quantile
    cuts are eps-bumped to stay strictly increasing, so a 3-valued
    feature's raw bin ids spread over ~n_bins — only the remap makes it
    4-bit-packable.  Storage holds compact ids; split evaluation
    scatters histogram cells back to the ORIGINAL bin positions
    (:func:`unbundle_hist`), which is EXACT: unoccupied bins hold
    exact zeros in both the remapped and the plain build, so the eval
    histogram is bit-identical cell-for-cell and split decisions (and
    ``save_model`` bytes) cannot move.
    """
    n_features: int                                  # original F
    n_bins: int                                      # split-eval width B
    widths: Tuple[int, ...]                          # per-storage width
    members: Tuple[Tuple[Tuple[int, int, int], ...], ...]
    pairs: Tuple[Tuple[int, int], ...]
    singles: Tuple[int, ...]
    bin_maps: Tuple[Optional[Tuple[int, ...]], ...]  # per-ORIGINAL feat

    @property
    def storage_features(self) -> int:
        return len(self.widths)

    @property
    def sync_bins(self) -> int:
        """Histogram build/psum width: the widest storage feature."""
        return max(self.widths)

    @property
    def packed_rows(self) -> int:
        """Physical rows in the packed region (8-row padded)."""
        p = len(self.pairs)
        return -(-p // 8) * 8 if p else 0

    @property
    def phys_rows(self) -> int:
        return self.packed_rows + len(self.singles)

    @property
    def has_bundles(self) -> bool:
        return any(len(m) > 1 for m in self.members)

    def phys_bytes_per_row(self) -> int:
        """Bin-matrix bytes per data row — the HBM bill one kernel pass
        pays per row (uint8 physical rows)."""
        return self.phys_rows


def used_bin_widths(bins_t: jax.Array) -> np.ndarray:
    """Per-feature used bin width (max bin + 1) of a ``[F, n]`` binned
    matrix.  Quantile cuts CANNOT be the source of this: the sketch's
    eps-bump keeps cut vectors strictly increasing, so a 2-valued
    feature still carries ~n_bins distinct cuts — only the binned data
    reveals the real width.  The max reduces over the (sharded) row
    axis, so every mesh shape derives identical widths.
    """
    return np.asarray(jax.device_get(jnp.max(bins_t.astype(jnp.int32),
                                             axis=1))) + 1


def bin_counts(bins_t: jax.Array, n_bins: int,
               n_valid: Optional[int] = None) -> np.ndarray:
    """Per-feature bin occupancy COUNTS ``int [F, n_bins]`` of a
    ``[F, n]`` binned matrix, over the first ``n_valid`` rows (padding
    rows hold an arbitrary bin id and MUST be excluded — they differ
    between mesh shapes).  The eps-bumped quantile sketch SPREADS a
    low-cardinality feature's bin ids across ``[0, n_bins)`` (a
    3-valued feature lands at e.g. {0, 11, 22}), so ``max + 1`` is
    useless as a packability signal — per-bin occupancy is the real
    one, and the count argmax picks each feature's DEFAULT (most
    frequent) bin for bundling.  An integer scatter-add over the
    (sharded) row axis — exactly row-order independent, so every mesh
    shape derives the identical count matrix.
    """
    F, n = bins_t.shape
    if n_valid is None or n_valid >= n:
        vals = jnp.ones((), jnp.int32)
    else:
        vals = (jnp.arange(n, dtype=jnp.int32) < n_valid
                ).astype(jnp.int32)
    cnt = jnp.zeros((F, n_bins), jnp.int32).at[
        jnp.arange(F, dtype=jnp.int32)[:, None],
        bins_t.astype(jnp.int32)].add(vals)
    return np.asarray(jax.device_get(cnt))


def compact_counts(counts: np.ndarray) -> np.ndarray:
    """Per-feature COMPACT bin count: number of occupied bins."""
    return (np.asarray(counts) > 0).sum(axis=1).astype(np.int64)


def default_bins(counts: np.ndarray) -> np.ndarray:
    """Per-feature DEFAULT bin: the most frequent occupied bin (ties →
    lowest id; deterministic).  The bundle encode treats a member at
    its default as "absent" — LightGBM's EFB default-bin rule, needed
    because quantile binning does NOT put the common value at bin 0."""
    return np.asarray(counts).argmax(axis=1).astype(np.int64)


def compute_layout(counts: np.ndarray, n_features: int, n_bins: int, *,
                   pack: bool = True,
                   bundles: Tuple[Tuple[int, ...], ...] = (),
                   ) -> Optional[BinLayout]:
    """Build a :class:`BinLayout` from the per-feature bin occupancy
    counts (``int [F, n_bins]``, see :func:`bin_counts`) and verified
    exclusive bundles.  Features whose occupied count is ≤
    ``PACK_WIDTH`` get a compact remap (``bin_maps``); storage widths
    are compact counts for remapped features and raw ``max + 1`` for
    wide ones.  A bundled member's map lists its DEFAULT bin first
    (compact id 0 ⇒ "absent from the bundle row").  Returns ``None``
    when the layout would be trivial (no pair packs, no bundles) so
    callers fall back to the untouched uint8 path — the "no bundle
    fires ⇒ byte-identical save_model" contract is then free.
    """
    counts = np.asarray(counts)
    CHECK(counts.shape == (n_features, n_bins),
          "counts/feature-count mismatch")
    presence = counts > 0
    occs = [tuple(int(i) for i in np.nonzero(presence[f])[0]) or (0,)
            for f in range(n_features)]
    defaults = default_bins(counts)
    maxw = [max(int(np.nonzero(presence[f])[0][-1]) + 1, 1)
            if presence[f].any() else 1 for f in range(n_features)]
    remapped = [len(occs[f]) <= PACK_WIDTH for f in range(n_features)]
    cnt = [len(occs[f]) for f in range(n_features)]
    in_bundle = {}
    for b in bundles:
        for f in b:
            CHECK(f not in in_bundle, f"feature {f} in two bundles")
            CHECK(remapped[f],
                  f"bundle member {f} not compact (count {cnt[f]})")
            in_bundle[f] = b
    bin_maps = []
    for f in range(n_features):
        if not remapped[f]:
            bin_maps.append(None)
        elif f in in_bundle:               # default-first compact order
            d = int(defaults[f])
            bin_maps.append((d,) + tuple(i for i in occs[f] if i != d))
        else:
            bin_maps.append(occs[f])
    bin_maps = tuple(bin_maps)
    st_widths, st_members = [], []
    emitted = set()
    for f in range(n_features):
        b = in_bundle.get(f)
        if b is None:
            w = cnt[f] if remapped[f] else maxw[f]
            st_widths.append(max(w, 1))
            st_members.append(((f, 0, w),))
            continue
        if b[0] != f or b in emitted:
            continue                       # bundle emitted at first member
        emitted.add(b)
        off, mems = 1, []
        for g in b:                        # member widths are COMPACT
            mems.append((g, off, cnt[g]))
            off += max(cnt[g] - 1, 0)
        CHECK(off <= n_bins,
              f"bundle width {off} exceeds n_bins={n_bins}")
        st_widths.append(off)
        st_members.append(tuple(mems))
    packable = ([s for s, w in enumerate(st_widths) if w <= PACK_WIDTH]
                if pack else [])
    pairs = tuple(zip(packable[0::2], packable[1::2]))
    paired = {s for pr in pairs for s in pr}
    singles = tuple(s for s in range(len(st_widths)) if s not in paired)
    if not pairs and not any(len(m) > 1 for m in st_members):
        return None
    return BinLayout(n_features=n_features, n_bins=n_bins,
                     widths=tuple(st_widths), members=tuple(st_members),
                     pairs=pairs, singles=singles, bin_maps=bin_maps)


def detect_bundles(sample_bins_t: np.ndarray, counts: np.ndarray,
                   n_bins: int, max_conflicts: int = 0,
                   ) -> Tuple[Tuple[int, ...], ...]:
    """Greedy exclusive-feature-bundle PROPOSER over a host bin sample
    ``[F, m]`` (LightGBM's EFB, exact-conflict variant): two features
    conflict when any sampled row has both OFF THEIR DEFAULT bin (the
    most frequent bin per :func:`default_bins` — quantile binning does
    not place the common value at bin 0).  ``counts`` is the full-data
    ``[F, n_bins]`` occupancy matrix (:func:`bin_counts`) — defaults
    must be mesh-invariant, so they come from the full data even though
    conflicts are only sampled here.  Members must be compact
    (≤ ``PACK_WIDTH``) so the layout can carry their remap tables.
    Proposals MUST still be verified against the full matrix (mutual
    exclusivity on a sample is not exclusivity) — see
    ``HistGBT._bundle_exclusive``.
    """
    F = sample_bins_t.shape[0]
    ccnt = compact_counts(counts)
    dflt = default_bins(counts)
    nz = sample_bins_t != dflt[:, None]                  # [F, m] off-default
    # near-one-hot candidates: sparse compact features
    density = nz.mean(axis=1)
    cand = sorted((f for f in range(F)
                   if density[f] <= 0.5 and 2 <= ccnt[f] <= PACK_WIDTH),
                  key=lambda f: density[f])
    bundles, used = [], set()
    for f in cand:
        if f in used:
            continue
        group, mask, width = [f], nz[f].copy(), int(ccnt[f])
        for g in cand:
            if g in used or g == f or g in group:
                continue
            if width + int(ccnt[g]) - 1 > n_bins:
                continue
            if int(np.count_nonzero(mask & nz[g])) > max_conflicts:
                continue
            group.append(g)
            mask |= nz[g]
            width += int(ccnt[g]) - 1
        if len(group) >= 2:
            used.update(group)
            bundles.append(tuple(sorted(group)))
    return tuple(bundles)


@lru_cache(maxsize=64)
def layout_tables(layout: BinLayout) -> dict:
    """Static numpy index tables derived from a layout (cached — the
    layout is hashable and lives for the fit)."""
    S = layout.storage_features
    Pp = layout.packed_rows
    src = np.zeros(S, np.int32)            # physical row of storage s
    nib = np.full(S, -1, np.int32)         # 0=low nibble, 1=high, -1=byte
    logical = np.zeros(S, np.int32)        # kernel-logical row of storage s
    for i, (a, b) in enumerate(layout.pairs):
        src[a], nib[a], logical[a] = i, 0, 2 * i
        src[b], nib[b], logical[b] = i, 1, 2 * i + 1
    for j, s in enumerate(layout.singles):
        src[s], logical[s] = Pp + j, 2 * Pp + j
    F = layout.n_features
    owner = np.zeros(F, np.int32)          # storage feature of original f
    off = np.zeros(F, np.int32)
    wid = np.zeros(F, np.int32)
    bundled = np.zeros(F, bool)
    for s, mems in enumerate(layout.members):
        for f, o, w in mems:
            owner[f], off[f], wid[f] = s, o, w
            bundled[f] = len(mems) > 1
    # compact remap tables: occ_pad[f, c] = original bin of compact id c
    # (sentinel n_bins beyond the width — never matched, never scattered)
    remap = np.array([m is not None for m in layout.bin_maps], bool)
    occ_pad = np.full((F, PACK_WIDTH), layout.n_bins, np.int32)
    for f, m in enumerate(layout.bin_maps):
        if m is not None:
            occ_pad[f, :len(m)] = m
    # storage-cell → eval-cell scatter (plain features; bundles are
    # reconstructed by the tot − segment pass in unbundle_hist)
    Bs = layout.sync_bins
    sc_feat = np.full((S, Bs), F, np.int32)      # F ⇒ dropped
    sc_bin = np.zeros((S, Bs), np.int32)
    for s, mems in enumerate(layout.members):
        if len(mems) != 1:
            continue
        f, _, w = mems[0]
        m = layout.bin_maps[f]
        for c in range(w):
            sc_feat[s, c] = f
            sc_bin[s, c] = m[c] if m is not None else c
    return dict(src=src, nib=nib, logical=logical, owner=owner,
                off=off, wid=wid, bundled=bundled,
                bundled_feats=tuple(int(f) for f in np.nonzero(bundled)[0]),
                remap=remap, any_remap=bool(remap.any()),
                occ_pad=occ_pad, sc_feat=sc_feat, sc_bin=sc_bin)


def pack_matrix(bins_t: jax.Array, layout: BinLayout) -> jax.Array:
    """``[F, n]`` uint8 original matrix → ``[phys_rows, n]`` uint8
    physical matrix (bundle encode, then nibble-pack).  Pure elementwise
    /feature-axis work — row sharding is untouched, so it runs
    shard-local under any mesh."""
    t = layout_tables(layout)
    v = bins_t.astype(jnp.int32)
    if t["any_remap"]:
        # original bin id → compact id: c = Σ_k k·[v == occ[f, k]].
        # Equality (not rank) because a bundled member's map is
        # default-first, not sorted; the n_bins sentinel in the padding
        # never matches.  Only padding rows hold unoccupied ids — they
        # fall to compact 0 and carry zero gradient weight anyway.
        # Unrolled over the 16-entry table to keep memory at O(F·n).
        c = jnp.zeros_like(v)
        occ_pad = t["occ_pad"]
        for k in range(1, PACK_WIDTH):
            c = c + k * (v == jnp.asarray(occ_pad[:, k])[:, None]
                         ).astype(jnp.int32)
        v = jnp.where(jnp.asarray(t["remap"])[:, None], c, v)
    if layout.has_bundles:
        # member encode: default (bin 0) → 0, bin v ≥ 1 → off + v - 1;
        # exclusivity (verified) makes the per-storage sum exact
        enc = jnp.where(jnp.asarray(t["bundled"])[:, None],
                        jnp.where(v > 0,
                                  jnp.asarray(t["off"])[:, None] + v - 1, 0),
                        v)
        storage = jnp.zeros((layout.storage_features, bins_t.shape[1]),
                            jnp.int32).at[jnp.asarray(t["owner"])].add(enc)
    else:
        storage = v                        # storage order == original order
    if layout.pairs:
        a_idx = jnp.asarray(np.array([p[0] for p in layout.pairs],
                                     np.int32))
        b_idx = jnp.asarray(np.array([p[1] for p in layout.pairs],
                                     np.int32))
        packed = storage[a_idx] | (storage[b_idx] << 4)
        pad = layout.packed_rows - len(layout.pairs)
        if pad:
            packed = jnp.pad(packed, ((0, pad), (0, 0)))
        parts = [packed]
    else:
        parts = []
    if layout.singles:
        parts.append(storage[jnp.asarray(np.array(layout.singles,
                                                  np.int32))])
    return jnp.concatenate(parts, axis=0).astype(jnp.uint8)


def unpack_matrix(phys: jax.Array, layout: BinLayout) -> jax.Array:
    """``[phys_rows, n]`` physical matrix → ``[S, n]`` uint8 STORAGE
    matrix (nibbles extracted; bundles left fused — histograms build in
    storage space).  Exact inverse of the packing step."""
    t = layout_tables(layout)
    m = phys[jnp.asarray(t["src"])].astype(jnp.int32)        # [S, n]
    nib = jnp.asarray(t["nib"])[:, None]
    v = jnp.where(nib == 1, m >> 4, jnp.where(nib == 0, m & 15, m))
    return v.astype(jnp.uint8)


def unbundle_hist(hist: jax.Array, layout: Optional[BinLayout],
                  n_bins: int) -> jax.Array:
    """Storage-space histogram ``[2, N, S, Bs]`` → split-eval histogram
    ``[2, N, F, B]`` in the ORIGINAL feature AND bin space.

    Plain storage cells scatter back to their original bin positions
    (compact id ``c`` of feature ``f`` → ``bin_maps[f][c]``; identity
    for wide features) — bins unoccupied in the data hold exact zeros
    on both paths, so split evaluation sees a bit-identical histogram.
    A bundle member's bins slice out of its storage segment onto the
    member's occupied positions; its bin 0 is ``node_total − Σ segment``
    — mathematically exact, with last-ulp float reassociation relative
    to a direct build (why ``DMLC_FEATURE_BUNDLE`` defaults off and the
    byte-parity contract is scoped to "no bundle fires").
    """
    if layout is None:
        return hist
    t = layout_tables(layout)
    Bs = hist.shape[-1]
    if not layout.has_bundles and not t["any_remap"]:
        if Bs == n_bins:
            return hist
        return jnp.pad(hist, ((0, 0), (0, 0), (0, 0), (0, n_bins - Bs)))
    # plain storage cells scatter to their ORIGINAL (feat, bin) positions
    # via static index tables; sentinel feat F drops the dead cells.  The
    # result is cell-for-cell bit-identical to the unpacked build: each
    # target cell accumulates the same rows in the same order, and the
    # compact remap only RELABELS cells — unoccupied bins are exact
    # zeros on both paths.
    out = jnp.zeros(hist.shape[:2] + (layout.n_features, n_bins),
                    hist.dtype)
    out = out.at[:, :, jnp.asarray(t["sc_feat"]),
                 jnp.asarray(t["sc_bin"])].set(hist, mode="drop")
    if layout.has_bundles:
        tot = jnp.cumsum(hist, axis=-1)[..., -1]             # [2, N, S]
        for f in t["bundled_feats"]:
            s, o, w = int(t["owner"][f]), int(t["off"][f]), int(t["wid"][f])
            occ = np.asarray(layout.bin_maps[f], np.int32)   # len w
            seg = hist[:, :, s, o:o + w - 1]                 # [2, N, w-1]
            b0 = tot[:, :, s] - seg.sum(-1)
            col = jnp.concatenate([b0[..., None], seg], axis=-1)
            out = out.at[:, :, f, jnp.asarray(occ)].set(col)
    return out


def select_bins(phys: jax.Array, feat_sel: jax.Array,
                layout: BinLayout) -> jax.Array:
    """Per-row bin of each row's selected ORIGINAL feature, from the
    physical matrix: one compare-and-sum pass over the physical rows
    (same gather-free idiom as ``select_feature_bins``), then nibble
    extraction and bundle decode by per-row table lookups."""
    t = layout_tables(layout)
    # per-original-feature tables: storage tables composed through owner
    src = jnp.asarray(t["src"][t["owner"]])[feat_sel]        # [n]
    f_iota = jnp.arange(phys.shape[0], dtype=jnp.int32)[:, None]
    raw = jnp.sum(jnp.where(src[None, :] == f_iota,
                            phys.astype(jnp.int32), 0), axis=0)
    nib = jnp.asarray(t["nib"][t["owner"]])[feat_sel]
    v = jnp.where(nib == 1, raw >> 4, jnp.where(nib == 0, raw & 15, raw))
    if layout.has_bundles:
        bundled = jnp.asarray(t["bundled"])[feat_sel]
        off = jnp.asarray(t["off"])[feat_sel]
        wid = jnp.asarray(t["wid"])[feat_sel]
        in_seg = (v >= off) & (v < off + wid - 1)
        v = jnp.where(bundled, jnp.where(in_seg, v - off + 1, 0), v)
    if t["any_remap"]:
        # compact id → ORIGINAL bin id (thresholds from split eval are
        # original-space): orig = occ_pad[f, v] by 16-way compare-sum
        occ_pad = t["occ_pad"]
        orig = jnp.zeros_like(v)
        for k in range(PACK_WIDTH):
            orig = orig + jnp.where(v == k,
                                    jnp.asarray(occ_pad[:, k])[feat_sel], 0)
        v = jnp.where(jnp.asarray(t["remap"])[feat_sel], orig, v)
    return v
