"""Local (single-device) attention with a fused Pallas fast path.

The hot op of the transformer family.  ``local_attention`` keeps this
repo's ``[B, S, H, D]`` convention and dispatches:

* **TPU + eligible shapes** → the Pallas TPU flash-attention kernel
  (fused online-softmax: scores never materialize in HBM, O(S) memory
  instead of O(S²)) — the kernel the sequence-parallel wrappers
  (:func:`~dmlc_core_tpu.parallel.ulysses.ulysses_attention`) want for
  their dense full-sequence local compute;
* otherwise → the exact dense softmax oracle
  (:func:`~dmlc_core_tpu.parallel.ring_attention.reference_attention`).

Eligibility: flash's TPU block pipeline needs the sequence a multiple of
its block size and head_dim lane-friendly; small/odd shapes stay on the
dense path (they fit VMEM anyway).

Measured on v5e (B=4, H=8, D=64, causal): S=4096 — flash 14.0ms ≈ dense
14.1ms; S=16384 — flash 186ms while the dense path cannot even compile
(the [B,H,S,S] f32 score tensor is 34GB).  Flash is what makes
long-context local blocks feasible at all.

Attribution (to be plain about what is whose): the flash kernel itself
is ``jax.experimental.pallas.ops.tpu.flash_attention`` — a library
kernel this module wraps with shape gating and layout glue, not an
in-repo kernel.  This repo's own Pallas engineering lives in
``ops/histogram.py`` (the factored descend/histogram kernels).
"""

from __future__ import annotations

from typing import Optional

import jax

from dmlc_core_tpu.parallel.ring_attention import reference_attention

__all__ = ["local_attention", "flash_eligible"]

_FLASH_BLOCK = 128


def flash_eligible(B: int, S: int, H: int, D: int) -> bool:
    """Shapes the Pallas TPU flash kernel handles (validated on v5e)."""
    return (jax.default_backend() == "tpu"
            and S % _FLASH_BLOCK == 0 and S >= 2 * _FLASH_BLOCK
            and D % 8 == 0 and D >= 64)


def local_attention(
    q: jax.Array,           # [B, S, H, D]
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact attention on one device, flash-fused when possible."""
    B, S, H, D = q.shape
    scale = scale if scale is not None else D ** -0.5
    if flash_eligible(B, S, H, D):
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention)

        # kernel convention is [B, H, S, D]
        out = flash_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal=causal, sm_scale=scale)
        return out.transpose(0, 2, 1, 3).astype(q.dtype)
    return reference_attention(q, k, v, causal=causal, scale=scale)
