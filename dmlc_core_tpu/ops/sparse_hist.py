"""Sparse histogram substrate: ragged per-feature bins over PRESENT entries.

The dense engine (``ops/histogram.py``) bins every cell of an ``[n, F]``
matrix — impossible for LibSVM's natural workloads (bag-of-words /
hashed one-hot, F ≈ 10⁴–10⁶, density < 1%), where the bin matrix alone
would be 10–1000 GB.  This module is the sparsity-aware substrate
(SURVEY.md §7 hard part (a), BASELINE config 3 "sparse CSR"; XGBoost's
``SparsePage`` + sparsity-aware split finding re-derived for XLA):

* **Ragged global bin space**: feature ``j`` owns bins
  ``[bin_ptr[j], bin_ptr[j+1])`` — per-feature cut counts adapt to the
  feature's distinct values (a binary indicator takes 2 bins, not 256),
  so ``total_bins = Σ_j (ncuts_j + 1)`` stays ~O(nnz-distinct), not
  ``F × max_bins``.
* **Histograms by segment-sum over entries**: one ``jax.ops.segment_sum``
  of per-row gradients over ``node(row) × total_bins + gb(entry)`` per
  level — O(nnz) work, static shapes, no densification ever.
* **Absent = missing**: a node's absent mass for feature j is
  ``G_node − Σ present_j`` (no storage at all); the split scan evaluates
  both default directions exactly like the dense NaN engine
  (models/histgbt.py missing mode), so sparse-absent semantics equal
  XGBoost's.

Everything here is representation-level (host numpy for the one-time
cut/bin passes, jitted segment-sums for the per-round work); the tree
loop lives in ``models/histgbt_sparse.py``.

Measured floor (v5e, 24M nnz, TB=1.6M, fetch-synced — block_until_ready
is a no-op through the remote tunnel): histogram scatter ~1.1 s/level,
routing ~1.0 s/level (now ~halved by the single coded scatter), split
scan 0.3 s, totals negligible.  Dead end, kept so it is not re-derived:
packing (g, h) into ONE complex64 scatter — ``segment_sum`` over
complex64 raises ``UNIMPLEMENTED: TPU backend error``; the apparent 2×
in a slice-synced microbenchmark was dead-code elimination.  The honest
remaining lever is a Pallas sorted-segment reduction (entries pre-sorted
by gb are static across rounds), left for a future round.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from dmlc_core_tpu.base.logging import CHECK

__all__ = ["SparseCuts", "build_sparse_cuts", "sparse_cut_candidates",
           "merge_sparse_cut_candidates", "bin_sparse_entries",
           "csr_rows", "level_histogram", "node_totals",
           "sparse_best_split", "route_level"]


class SparseCuts(NamedTuple):
    """Ragged per-feature quantile cuts.

    ``cut_vals[cut_ptr[j]:cut_ptr[j+1]]`` are feature j's strictly
    increasing cut points; its local bin of value v is
    ``#cuts_j ≤ v  ∈ [0, ncuts_j]`` and its global bin is
    ``bin_ptr[j] + local`` with ``bin_ptr[j+1] − bin_ptr[j] =
    ncuts_j + 1``.  ``feat_of_bin[gb]`` inverts the layout.
    """
    cut_vals: np.ndarray     # [total_cuts] f32
    cut_ptr: np.ndarray      # [F+1] int64
    bin_ptr: np.ndarray      # [F+1] int64
    feat_of_bin: np.ndarray  # [total_bins] int32

    @property
    def n_features(self) -> int:
        return len(self.cut_ptr) - 1

    @property
    def total_bins(self) -> int:
        return int(self.bin_ptr[-1])


def sparse_cut_candidates(cols: np.ndarray, values: np.ndarray,
                          n_features: int,
                          max_bins: int = 256) -> np.ndarray:
    """Per-feature cut CANDIDATES ``[F, max_bins-1]`` (f32; all-NaN row
    for a feature with no local entries), fully vectorized.

    One ``lexsort`` of the nnz entries by (feature, value), then every
    feature's candidates are gathered at evenly spaced ranks of its own
    segment — no per-feature Python loop (F can be 10⁶).  Unweighted
    ranks (the sparse path's v1 contract; the dense engine keeps
    weighted sketches).  This fixed-shape matrix is also the
    distributed message: workers allgather their candidate matrices and
    :func:`merge_sparse_cut_candidates` re-quantiles the union —
    the sparse analogue of the dense cut allgather-merge.
    """
    CHECK(max_bins >= 2, "need at least 2 bins")
    cols = np.asarray(cols)
    values = np.asarray(values, np.float32)
    CHECK(len(cols) == len(values), "cols/values length mismatch")
    if len(cols):
        CHECK(int(cols.max()) < n_features, "feature index out of range")
        CHECK(int(cols.min()) >= 0, "negative feature index")
        CHECK(np.isfinite(values).all(),
              "sparse values must be finite (absent entries ARE the "
              "missing mass; explicit NaN has no sparse meaning)")
    order = np.lexsort((values, cols))
    cv = values[order]
    counts = np.bincount(cols, minlength=n_features)          # [F]
    starts = np.concatenate([[0], np.cumsum(counts)])         # [F+1]
    nb = max_bins - 1                                         # cut slots
    # candidate ranks: k/nb quantile positions inside each segment
    k = np.arange(1, nb + 1)                                  # [nb]
    m = counts[:, None]                                       # [F, 1]
    idx = starts[:-1, None] + np.minimum(
        np.ceil(k[None, :] * m / (nb + 1)).astype(np.int64),
        np.maximum(m - 1, 0))
    cand = cv[np.minimum(idx, len(cv) - 1 if len(cv) else 0)] \
        if len(cv) else np.zeros((n_features, nb), np.float32)  # [F, nb]
    cand[counts == 0] = np.nan
    return cand


def merge_sparse_cut_candidates(cands: np.ndarray) -> SparseCuts:
    """Merge ``[W, F, max_bins-1]`` worker candidate matrices into
    ragged :class:`SparseCuts`.

    Per feature the union of the workers' candidate points is
    re-quantiled onto the candidates' own grid width (NaN rows — workers
    whose shard lacked the feature — contribute nothing; like the dense
    ``merge_summaries``, worker summaries weigh equally, which is exact
    for the similar-size shards data-parallel splits produce).  With
    ``W = 1`` the merge is the identity on the candidates, so single-
    and multi-worker paths share one code path.  De-duplication keeps
    strictly increasing runs; a feature with no finite candidate
    anywhere keeps 0 cuts (1 bin, never a split).
    """
    cands = np.asarray(cands, np.float32)
    W, F, nb = cands.shape
    pts = np.sort(cands.transpose(1, 0, 2).reshape(F, W * nb), axis=1)
    m = (~np.isnan(pts)).sum(axis=1, keepdims=True)           # [F, 1]
    k = np.arange(1, nb + 1)                                  # [nb]
    # candidate j of a worker sits at quantile (j+1)/(nb+1) of its
    # shard; selecting rank ceil(k·(m+1)/(nb+1))−1 of the union puts
    # target k/(nb+1) back on the same grid — and makes W=1 the exact
    # identity on the candidates
    idx = np.clip(np.ceil(k[None, :] * (m + 1) / (nb + 1)).astype(
        np.int64) - 1, 0, np.maximum(m - 1, 0))
    cand = np.take_along_axis(pts, idx, axis=1)               # [F, nb]
    # keep strictly increasing runs only; empty features keep 0 cuts.
    # A cut equal to the feature's MINIMUM value is useless as a
    # threshold only if nothing sorts below it — but bin-of-value uses
    # "#cuts ≤ v", so any duplicate-free subset is valid.
    keep = np.ones_like(cand, bool)
    keep[:, 1:] = cand[:, 1:] > cand[:, :-1]
    keep[m[:, 0] == 0] = False
    keep &= ~np.isnan(cand)
    ncuts = keep.sum(axis=1)                                  # [F]
    cut_ptr = np.concatenate([[0], np.cumsum(ncuts)])
    cut_vals = cand[keep].astype(np.float32)
    widths = ncuts + 1
    bin_ptr = np.concatenate([[0], np.cumsum(widths)])
    feat_of_bin = np.repeat(np.arange(F, dtype=np.int32), widths)
    return SparseCuts(cut_vals, cut_ptr.astype(np.int64),
                      bin_ptr.astype(np.int64), feat_of_bin)


def build_sparse_cuts(cols: np.ndarray, values: np.ndarray, n_features: int,
                      max_bins: int = 256) -> SparseCuts:
    """Single-worker cuts: candidates → (W=1) merge.  One code path
    with the distributed build, which allgathers the candidate stage."""
    cand = sparse_cut_candidates(cols, values, n_features, max_bins)
    return merge_sparse_cut_candidates(cand[None])


def bin_sparse_entries(cols: np.ndarray, values: np.ndarray,
                       cuts: SparseCuts) -> np.ndarray:
    """Global bin id per entry (vectorized grouped searchsorted).

    The grouped "``#cuts_j ≤ v``" count has no direct numpy form, so it
    is computed by MERGING cuts and entries per feature: sort the
    combined multiset by (feature, value, kind) with cuts ordered before
    entries at equal value; each entry's local bin is then the running
    cut count within its feature segment.  O((nnz+C)·log) once per
    dataset.
    """
    cols = np.asarray(cols)
    values = np.asarray(values, np.float32)
    C = len(cuts.cut_vals)
    n = len(values)
    if n == 0:
        return np.zeros(0, np.int32)
    cut_cols = np.repeat(np.arange(cuts.n_features),
                         np.diff(cuts.cut_ptr)).astype(cols.dtype)
    all_cols = np.concatenate([cut_cols, cols])
    all_vals = np.concatenate([cuts.cut_vals, values])
    kind = np.concatenate([np.zeros(C, np.int8), np.ones(n, np.int8)])
    order = np.lexsort((kind, all_vals, all_cols))
    is_cut = kind[order] == 0
    run_cuts = np.cumsum(is_cut)                     # cuts so far, global
    # cuts before each feature's segment start = cut_ptr[feature]
    pos_of_entry = np.empty(C + n, np.int64)
    pos_of_entry[order] = np.arange(C + n)
    entry_pos = pos_of_entry[C:]
    local = run_cuts[entry_pos] - cuts.cut_ptr[cols]
    gb = cuts.bin_ptr[cols] + local
    return gb.astype(np.int32)


def csr_rows(indptr: np.ndarray) -> np.ndarray:
    """Row index per entry from a CSR indptr (int32)."""
    indptr = np.asarray(indptr)
    return np.repeat(np.arange(len(indptr) - 1, dtype=np.int32),
                     np.diff(indptr))


# ---------------------------------------------------------------------------
# jitted per-level kernels
# ---------------------------------------------------------------------------

@partial(jax.jit, static_argnames=("n_build", "total_bins", "level"))
def level_histogram(row_e, gb_e, node, g, h, *, n_build: int,
                    total_bins: int, level: int):
    """Left-child gradient histograms ``[2, n_build, total_bins]`` for one
    level, by ONE segment-sum over the nnz entries.

    ``node`` [n] is each row's node at this level (−1 = padding).  At
    level 0 every row builds node 0; deeper levels build LEFT children
    only (sibling subtraction: right = parent − left, like the dense
    engines) — entries whose row's node is odd (a right child) or
    invalid dump into an overflow segment that is sliced away.
    """
    nd = node
    if level > 0:
        nd = jnp.where((nd >= 0) & (nd % 2 == 0), nd >> 1, -1)
    n_entry = nd[row_e]                                  # [nnz]
    valid = n_entry >= 0
    seg = jnp.where(valid, n_entry * total_bins + gb_e,
                    n_build * total_bins)
    ge = jnp.where(valid, g[row_e], 0.0)
    he = jnp.where(valid, h[row_e], 0.0)
    hist_g = jax.ops.segment_sum(ge, seg,
                                 num_segments=n_build * total_bins + 1)
    hist_h = jax.ops.segment_sum(he, seg,
                                 num_segments=n_build * total_bins + 1)
    return jnp.stack([hist_g[:-1].reshape(n_build, total_bins),
                      hist_h[:-1].reshape(n_build, total_bins)])


@partial(jax.jit, static_argnames=("n_nodes",))
def node_totals(node, g, h, *, n_nodes: int):
    """Per-node TOTAL g/h sums over all rows (present + absent mass) —
    ``[2, n_nodes]``; padding rows (node < 0) dump into the overflow."""
    safe = jnp.where(node >= 0, node, n_nodes)
    return jnp.stack([
        jax.ops.segment_sum(g, safe, num_segments=n_nodes + 1)[:-1],
        jax.ops.segment_sum(h, safe, num_segments=n_nodes + 1)[:-1]])


@partial(jax.jit, static_argnames=("n_dense", "b_max", "lam", "gamma",
                                   "mcw", "alpha"))
def sparse_best_split(hist, totals, bin_ptr_d, feat_of_bin_d, last_mask,
                      dense_pos_d, *, n_dense: int, b_max: int,
                      lam: float, gamma: float, mcw: float,
                      alpha: float = 0.0):
    """Sparsity-aware split chooser over the ragged flat bin space.

    ``hist`` [2, N, TB] (present-entry g/h per global bin), ``totals``
    [2, N] (ALL rows), ``bin_ptr_d`` [F+1], ``feat_of_bin_d`` [TB],
    ``last_mask`` [TB] (True at each feature's LAST bin — not a valid
    threshold), ``dense_pos_d`` [TB] (each global bin's slot in the
    feature-padded ``[F, b_max]`` layout, ``n_dense = F · b_max``).
    For every candidate bin the absent mass ``totals −
    feature_present`` is tried on both sides (the learned default
    direction).  Returns (feat [N], thr_local [N], dir [N] (1 =
    missing left), gain [N]) with the dense engine's degenerate
    convention: gain ≤ gamma → feat 0 / thr = width(f0)−1 / dir 1
    (everyone, missing included, goes left).

    Numerics: within-feature prefixes are computed by scattering the
    ragged hist into the padded per-feature layout and cumsumming along
    the SHORT minor axis — each feature's prefix sees only its OWN
    mass.  A single global cumsum with start-subtraction (the first
    formulation) rides the whole dataset's magnitude (f32 ulp ~0.25 at
    a 10⁶ Hessian prefix), drowning rare features; a segmented
    associative_scan is exact but measured ~10× slower than cumsum on
    this backend (bench went 110 s → timeout).  The scatter/gather pair
    is memory-bound like the cumsum itself.
    """
    g, h = hist[0], hist[1]                              # [N, TB]
    N, TB = g.shape

    def seg_cumsum(x):
        dense = jnp.zeros((N, n_dense), x.dtype).at[:, dense_pos_d].set(x)
        cum = jnp.cumsum(dense.reshape(N, n_dense // b_max, b_max),
                         axis=2).reshape(N, n_dense)
        return cum[:, dense_pos_d]

    gl = seg_cumsum(g)                                   # [N, TB]
    hl = seg_cumsum(h)
    # the feature's TOTAL present mass = its prefix at its LAST bin
    end1 = bin_ptr_d[feat_of_bin_d + 1] - 1              # [TB] last bin
    Tg = gl[:, end1]
    Th = hl[:, end1]
    gt = totals[0][:, None]                              # [N, 1] all rows
    ht = totals[1][:, None]
    miss_g = gt - Tg                                     # absent mass
    miss_h = ht - Th

    # the ONE home of XGBoost's ThresholdL1 semantics (alpha=0 keeps the
    # exact G**2 primitive) — shared with the dense engines
    from dmlc_core_tpu.models.gbt_split import _soft_threshold

    if alpha > 0.0:
        def _score(G, H):
            t = _soft_threshold(G, alpha)
            return t * t / (H + lam)
    else:
        def _score(G, H):
            return G ** 2 / (H + lam)

    def side_gain(gl_, hl_):
        gr_ = gt - gl_
        hr_ = ht - hl_
        gn = _score(gl_, hl_) + _score(gr_, hr_) - _score(gt, ht)
        ok = (hl_ >= mcw) & (hr_ >= mcw)
        return jnp.where(ok, gn, -jnp.inf)

    gain_r = side_gain(gl, hl)                           # missing right
    gain_l = side_gain(gl + miss_g, hl + miss_h)         # missing left
    gain = jnp.maximum(gain_r, gain_l)
    dir_l = gain_l > gain_r
    gain = jnp.where(last_mask[None, :], -jnp.inf, gain)
    best = jnp.argmax(gain, axis=1)                      # [N] global bin
    best_gain = jnp.take_along_axis(gain, best[:, None], axis=1)[:, 0]
    feat = feat_of_bin_d[best]
    thr = (best - bin_ptr_d[feat]).astype(jnp.int32)
    dirv = jnp.take_along_axis(dir_l, best[:, None], axis=1)[:, 0]
    # XGBoost convention, matching the dense chooser (gbt_split.py): the
    # acceptance test and the reported gain both carry the ½ factor —
    # the same `gamma` must mean the same thing whichever engine the
    # sklearn wrappers route to, and importance_type="gain" must agree
    ok = 0.5 * best_gain > gamma
    width0 = (bin_ptr_d[1] - bin_ptr_d[0]).astype(jnp.int32)
    feat = jnp.where(ok, feat, 0).astype(jnp.int32)
    thr = jnp.where(ok, thr, width0 - 1)
    dirv = jnp.where(ok, dirv, True)
    gain_out = jnp.where(ok, 0.5 * best_gain, 0.0)
    return feat, thr, dirv, gain_out


@jax.jit
def route_level(row_e, gb_e, node, feat, thr, dirv, bin_ptr_d,
                feat_of_bin_d):
    """Advance every row one level down using only PRESENT entries.

    Default: rows follow their node's missing direction.  Rows that DO
    have the split feature override via two conflict-free segment-sums
    (each row holds at most one entry of a given feature): ``cnt[r]``
    flags a present entry of the split feature, ``side[r]`` its
    left/right verdict.  Padding rows stay −1.
    """
    n = node.shape[0]
    valid = node >= 0
    safe = jnp.where(valid, node, 0)
    # default child: missing direction (dir=1 → left)
    default = 2 * safe + jnp.where(dirv[safe], 0, 1)
    # entry overrides — ONE integer-coded scatter (a row has at most
    # one entry of its split feature, so code ∈ {0, 2, 3} after the
    # sum: bit 1 = "entry present", bit 0 = its right-verdict.  Two
    # separate segment_sums cost ~2× here; the scatter is the
    # per-level floor at 10⁷+ nnz — measured 1.0 s → ~0.55 s at 24M).
    n_e = node[row_e]
    ok_e = n_e >= 0
    safe_e = jnp.where(ok_e, n_e, 0)
    split_gb = bin_ptr_d[feat[safe_e]] + thr[safe_e]     # [nnz] threshold
    match = ok_e & (feat_of_bin_d[gb_e] == feat[safe_e])
    side = match & (gb_e > split_gb)                     # right verdict
    seg = jnp.where(ok_e, row_e, n)
    code = jax.ops.segment_sum(
        match.astype(jnp.int32) * 2 + side.astype(jnp.int32), seg,
        num_segments=n + 1)[:-1]
    routed = 2 * safe + jnp.where(code >= 2, code & 1, default - 2 * safe)
    return jnp.where(valid, routed, -1)
