"""Distributed weighted quantile binning (the sketch layer).

XGBoost's hist method bins features at per-feature (weighted) quantile cut
points, merged across workers.  The reference world does this with
variable-size quantile sketches allreduced over rabit (BASELINE config 3's
hard part).  The TPU-native design replaces the variable-size merge with a
**fixed-size summary + allgather-merge** (SURVEY.md §7 hard part (c)):

1. each worker summarizes every feature with a fixed grid of
   ``n_summary`` weighted quantiles of its local rows — fixed shape
   ``[F, n_summary]``, psum/allgather-friendly;
2. summaries are allgathered (one XLA AllGather over ICI instead of a
   variable-size sketch protocol);
3. the merged multiset of summary points is re-quantiled into ``n_bins-1``
   cut points, identically on every worker (deterministic, no broadcast
   needed).

**Error bound** (the fixed-size analogue of GK/WQSummary's ε guarantee):
every summarization stage approximates a weighted quantile function by
``S = n_summary`` points on an even probability grid with midpoint
interpolation, so reconstructing any quantile from one stage incurs rank
error ≤ 1/(S−1) (≈ 1/(2(S−1)) typically — the grid midpoint rule).
Stage errors add.  A value streamed through the accumulator passes
through: 1 page summary + ≤ ⌈log_C P⌉ ladder merges (C =
``buffer_pages``, P = pages seen; see :class:`SketchAccumulator`) +
1 cross-level merge (``summary()``) + 1 cross-worker collapse
(``finalize`` re-quantiles the gathered summaries even single-worker) +
1 final re-quantile into bins, giving

    eps(S, P, C)  ≤  (⌈log_C P⌉ + 4) / (S − 1)

rank error per cut — conservative by ~2× (midpoint rule).  At the
defaults (S = 8·n_bins = 2048, C = 32) even a million pages stay under
(4+4)/2047 ≈ 0.0039 ≈ 1.0 bin width at 256 bins, and realistic page
counts (≤ 32k) under 0.7 bin widths.  ``tests/test_external_memory.py``
property-checks this bound against adversarial distributions
(heavy-tail, atom-dominated, 10⁶:1 weight skew, sorted streams).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dmlc_core_tpu.base.logging import CHECK
from dmlc_core_tpu.base.parameter import get_env

__all__ = ["local_summary", "merge_summaries", "compute_cuts", "apply_bins",
           "apply_bins_missing", "SketchAccumulator"]


@partial(jax.jit, static_argnums=(2, 3))
def local_summary(x: jax.Array, weight: Optional[jax.Array],
                  n_summary: int, missing: bool = False) -> jax.Array:
    """Fixed-size weighted quantile summary of local rows.

    ``x``: [n, F] f32; ``weight``: [n] or None.  Returns [F, n_summary]
    (per-feature weighted quantiles on an even probability grid).

    ``missing=True``: NaN entries are excluded from the summary by
    rewriting them to the feature's max finite value with weight 0 —
    a zero-weight duplicate knot that cannot move any quantile (the
    fixed-shape alternative to per-feature nan-filtering, which would
    break the [F, n_summary] contract when NaN counts differ by
    feature).  A feature with NO finite value on this worker emits an
    explicit all-NaN sentinel row (total weight 0), which
    :func:`merge_summaries` excludes — a shard-local all-NaN column is
    legal in distributed fits as long as the feature is finite on SOME
    worker (callers enforce the global check, histgbt's finite_any
    allreduce).
    """
    n, F = x.shape
    qs = jnp.linspace(0.0, 1.0, n_summary)
    if missing:
        nan = jnp.isnan(x)
        w2d = (jnp.ones_like(x) if weight is None
               else jnp.broadcast_to(weight[:, None], x.shape))
        w2d = jnp.where(nan, 0.0, w2d)
        fmax = jnp.max(jnp.where(nan, -jnp.inf, x), axis=0)    # [F]
        x = jnp.where(nan, fmax[None, :], x)
    elif weight is None:
        return jnp.quantile(x, qs, axis=0).T  # [F, n_summary]
    else:
        w2d = jnp.broadcast_to(weight[:, None], x.shape)
    order = jnp.argsort(x, axis=0)                                    # [n, F]
    xs = jnp.take_along_axis(x, order, axis=0)
    ws = jnp.take_along_axis(w2d, order, axis=0)                      # [n, F]
    cw = jnp.cumsum(ws, axis=0)
    total = cw[-1:, :]
    probs = (cw - 0.5 * ws) / total                                   # midpoint rule
    def per_f(xf, pf):
        return jnp.interp(qs, pf, xf)
    out = jax.vmap(per_f, in_axes=(1, 1))(xs, probs)                  # [F, n_summary]
    if missing:
        # zero total weight = all-NaN column on this shard: the -inf/0-div
        # garbage above is made a deterministic NaN sentinel row here.
        out = jnp.where((total[0] <= 0.0)[:, None], jnp.nan, out)
    return out


@partial(jax.jit, static_argnums=(1,))
def merge_summaries(gathered: jax.Array, n_bins: int) -> jax.Array:
    """Merge ``[W, F, n_summary]`` worker summaries into ``[F, n_bins-1]``
    cut points (interior boundaries; bin b = count of cuts ≤ x).

    NaN summary points (a worker whose shard had no finite value for the
    feature — :func:`local_summary`'s sentinel rows) are excluded via
    ``nanquantile``, so a feature all-NaN on one shard but finite globally
    still gets finite cuts from the workers that saw it.  A feature with no
    finite point on ANY worker (callers reject this up front) degrades to a
    deterministic finite ramp rather than NaN cuts — NaN cuts would make
    ``searchsorted`` silently bin every finite value to 0.
    """
    W, F, S = gathered.shape
    merged = jnp.transpose(gathered, (1, 0, 2)).reshape(F, W * S)
    qs = jnp.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    cuts = jnp.nanquantile(merged, qs, axis=1).T                      # [F, n_bins-1]
    cuts = jnp.where(jnp.isnan(cuts),
                     jnp.arange(n_bins - 1, dtype=cuts.dtype)[None, :], cuts)
    # Strictly-increasing guard: s_i = max(c_i, s_{i-1} + eps_{i-1}) —
    # an atom-dominated feature (e.g. a sparse column densified to 0.0)
    # puts a RUN of quantile targets on one value, and a single-pass bump
    # against the unadjusted neighbor leaves runs ≥ 3 non-strict.  The
    # recurrence is a prefix max in disguise: with E = exclusive-prefix
    # sum of eps, s_i = E_i + cummax(c − E)_i, so duplicates fan upward
    # by one eps per position (rows still route identically — the bumped
    # copies sit between the atom and the next real value).
    eps = jnp.maximum(jnp.abs(cuts) * 1e-6, 1e-6)
    E = jnp.cumsum(eps, axis=1) - eps
    return E + jax.lax.cummax(cuts - E, axis=1)


def compute_cuts(
    x: np.ndarray,
    n_bins: int = 256,
    weight: Optional[np.ndarray] = None,
    n_summary: Optional[int] = None,
    allgather_fn=None,
    missing: bool = False,
) -> jax.Array:
    """End-to-end cut computation.

    ``allgather_fn(summary) -> [W, F, S]`` injects the distributed gather
    (e.g. ``collectives.allgather`` across processes, or an in-mesh
    all_gather); None means single worker.

    ``missing=True`` computes cuts over finite values only (NaN = missing;
    see :func:`local_summary`); callers reserve a bin for NaN separately
    (:func:`apply_bins` with ``missing=True``).
    """
    CHECK(n_bins >= 2, "need at least 2 bins")
    n_summary = n_summary or max(8 * n_bins, 64)
    summary = local_summary(jnp.asarray(x), None if weight is None else jnp.asarray(weight),
                            n_summary, missing)
    if allgather_fn is not None:
        gathered = jnp.asarray(allgather_fn(np.asarray(summary)))
    else:
        gathered = summary[None]
    return merge_summaries(gathered, n_bins)


@partial(jax.jit, static_argnums=(2,))
def _weighted_collapse(stack: jax.Array, wts: jax.Array, n_out: int) -> jax.Array:
    """Merge ``[K, F, S]`` summaries with per-summary weights ``[K]`` into
    one ``[F, n_out]`` summary.

    Each summary point carries ``w_k / S`` mass; the merged multiset is
    re-quantiled on an even grid — the fixed-shape equivalent of the
    reference world's variable-size sketch merge (``GK/WQSummary.Merge``).
    """
    K, F, S = stack.shape
    pts = jnp.transpose(stack, (1, 0, 2)).reshape(F, K * S)            # [F, K·S]
    w = jnp.broadcast_to((wts / S)[:, None], (K, S)).reshape(K * S)    # [K·S]
    order = jnp.argsort(pts, axis=1)
    xs = jnp.take_along_axis(pts, order, axis=1)
    ws = jnp.broadcast_to(w[None, :], (F, K * S))
    ws = jnp.take_along_axis(ws, order, axis=1)
    cw = jnp.cumsum(ws, axis=1)
    total = cw[:, -1:]
    probs = (cw - 0.5 * ws) / total                                    # midpoint rule
    qs = jnp.linspace(0.0, 1.0, n_out)
    return jax.vmap(lambda xf, pf: jnp.interp(qs, pf, xf))(xs, probs)  # [F, n_out]


class SketchAccumulator:
    """Streaming quantile sketch with bounded memory (BASELINE config 3).

    The out-of-core path: pages of rows arrive one at a time (DiskRowIter /
    Parser over a 1TB input); each page contributes a fixed-size weighted
    summary, and summaries merge through a **C-ary ladder** (C =
    ``buffer_pages``): page summaries buffer at level 0; whenever a level
    holds C summaries they collapse into ONE summary at the next level.
    Any value therefore traverses at most ``⌈log_C P⌉`` merge stages — the
    rank-error bound grows *logarithmically* in the page count (see the
    module docstring's eps(S, P, C)), where a flat collapse-all buffer
    would compound error linearly in P.  Host memory stays
    ``O(C · log_C P · F · n_summary)``.

    ``finalize`` optionally allreduces (as an allgather+merge) across
    workers — the TPU-native replacement for the reference world's
    variable-size quantile-sketch allreduce (``tracker.py``-coordinated
    rabit ``SerializeReducer``).
    """

    def __init__(self, n_features: int, n_summary: int = 2048,
                 buffer_pages: int = 32):
        CHECK(buffer_pages >= 2, "need at least 2 buffered summaries")
        self._F = n_features
        self._S = n_summary
        self._cap = buffer_pages
        # merge ladder: _levels[ℓ] = list of ([F, S] summary, weight)
        self._levels: list = [[]]
        self.pages_seen = 0
        # Per-page summaries are jax ops.  On a locally attached
        # accelerator that's the right home; through a remote-device
        # tunnel every page pays an upload+dispatch round trip (measured
        # ~20 s/page at Criteo shape — 2 h for a 50M-row pass), so the
        # sketch can be pinned to the host CPU backend instead.
        backend = get_env("DMLC_TPU_SKETCH_BACKEND", "", str)
        self._device = (jax.local_devices(backend=backend)[0]
                        if backend else None)

    def add(self, x: np.ndarray, weight: Optional[np.ndarray] = None) -> None:
        """Absorb a page of rows ``[n, F]`` (``weight``: [n] or None)."""
        x = np.asarray(x, np.float32)
        CHECK(x.shape[1] == self._F, "feature-count mismatch")
        if x.shape[0] == 0:
            return
        with self._on_device():
            s = local_summary(
                jnp.asarray(x),
                None if weight is None else jnp.asarray(weight),
                self._S)
            s = np.asarray(s)
        wt = float(x.shape[0] if weight is None else np.sum(weight))
        self.pages_seen += 1
        self._levels[0].append((s, wt))
        lvl = 0
        while len(self._levels[lvl]) >= self._cap:   # carry up the ladder
            merged = self._merge_group(self._levels[lvl])
            self._levels[lvl] = []
            if lvl + 1 == len(self._levels):
                self._levels.append([])
            self._levels[lvl + 1].append(merged)
            lvl += 1

    def _on_device(self):
        import contextlib

        return (jax.default_device(self._device) if self._device is not None
                else contextlib.nullcontext())

    def _merge_group(self, group: list) -> tuple:
        with self._on_device():
            stack = jnp.asarray(np.stack([s for s, _ in group]))
            wts = np.asarray([w for _, w in group], np.float32)
            merged = np.asarray(
                _weighted_collapse(stack, jnp.asarray(wts), self._S))
        return merged, float(wts.sum())

    def summary(self) -> tuple:
        """Current ``([F, S] summary, total_weight)`` — the fixed-size
        message exchanged between workers.  Merges whatever sits on the
        ladder (one cross-level stage) without disturbing it."""
        pending = [sw for level in self._levels for sw in level]
        CHECK(pending, "no data added")
        if len(pending) == 1:
            return pending[0]
        return self._merge_group(pending)

    def finalize(self, n_bins: int, allgather_fn=None) -> jax.Array:
        """Merged cut points ``[F, n_bins-1]``.

        ``allgather_fn(arr) -> [W, ...]`` gathers across workers (e.g.
        ``collectives.allgather``); every worker computes identical cuts
        deterministically from the gathered summaries — no broadcast step.
        """
        local, wt = self.summary()
        if allgather_fn is not None:
            # allgather stacks rank contributions on a new leading axis
            gathered = np.asarray(allgather_fn(local))            # [W, F, S]
            wts = np.asarray(
                allgather_fn(np.asarray(wt, np.float32))).reshape(-1)  # [W]
        else:
            gathered = local[None]
            wts = np.asarray([wt], np.float32)
        merged = _weighted_collapse(
            jnp.asarray(gathered), jnp.asarray(wts), self._S)     # [F, S]
        return merge_summaries(merged[None], n_bins)


@jax.jit
def apply_bins(x: jax.Array, cuts: jax.Array) -> jax.Array:
    """Digitize ``x`` [n, F] by per-feature ``cuts`` [F, n_bins-1] →
    integer bins [n, F] (bin = #cuts ≤ value, so bins ∈ [0, n_bins-1]).

    Per-feature ``searchsorted`` (binary search, O(n·log C)) rather than a
    broadcast-compare, which would materialize an [n, F, C] intermediate —
    prohibitive at HIGGS scale (10M × 28 × 255).

    dtype: uint8 when bins fit (n_bins ≤ 256, the XGBoost max_bin default)
    — the bin matrix is the largest resident training array and the
    narrow dtype quarters its HBM footprint under TPU tiling; int32
    otherwise.
    """
    out = jax.vmap(
        lambda col, c: jnp.searchsorted(c, col, side="right"),
        in_axes=(1, 0), out_axes=1,
    )(x, cuts)
    dtype = jnp.uint8 if cuts.shape[1] < 256 else jnp.int32
    return out.astype(dtype)


@partial(jax.jit, static_argnums=(2,))
def apply_bins_missing(x: jax.Array, cuts: jax.Array,
                       miss_bin: int) -> jax.Array:
    """:func:`apply_bins` with a reserved NaN bin: finite values digitize
    into ``[0, n_cuts]`` as usual and NaN maps to ``miss_bin`` (the
    caller reserves its top bin — searchsorted alone would silently
    alias NaN with the top VALUE bin, scoring garbage).
    """
    out = jax.vmap(
        lambda col, c: jnp.searchsorted(c, col, side="right"),
        in_axes=(1, 0), out_axes=1,
    )(x, cuts)
    out = jnp.where(jnp.isnan(x), miss_bin, out)
    dtype = jnp.uint8 if miss_bin < 256 else jnp.int32
    return out.astype(dtype)
