"""Distributed weighted quantile binning (the sketch layer).

XGBoost's hist method bins features at per-feature (weighted) quantile cut
points, merged across workers.  The reference world does this with
variable-size quantile sketches allreduced over rabit (BASELINE config 3's
hard part).  The TPU-native design replaces the variable-size merge with a
**fixed-size summary + allgather-merge** (SURVEY.md §7 hard part (c)):

1. each worker summarizes every feature with a fixed grid of
   ``n_summary`` weighted quantiles of its local rows — fixed shape
   ``[F, n_summary]``, psum/allgather-friendly;
2. summaries are allgathered (one XLA AllGather over ICI instead of a
   variable-size sketch protocol);
3. the merged multiset of summary points is re-quantiled into ``n_bins-1``
   cut points, identically on every worker (deterministic, no broadcast
   needed).

Exactness matches sketch-based binning in spirit: with ``n_summary ≥
8·n_bins`` the cut error is far below a bin width in practice.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dmlc_core_tpu.base.logging import CHECK

__all__ = ["local_summary", "merge_summaries", "compute_cuts", "apply_bins"]


@partial(jax.jit, static_argnums=(2,))
def local_summary(x: jax.Array, weight: Optional[jax.Array], n_summary: int) -> jax.Array:
    """Fixed-size weighted quantile summary of local rows.

    ``x``: [n, F] f32; ``weight``: [n] or None.  Returns [F, n_summary]
    (per-feature weighted quantiles on an even probability grid).
    """
    n, F = x.shape
    qs = jnp.linspace(0.0, 1.0, n_summary)
    if weight is None:
        return jnp.quantile(x, qs, axis=0).T  # [F, n_summary]
    order = jnp.argsort(x, axis=0)                                    # [n, F]
    xs = jnp.take_along_axis(x, order, axis=0)
    ws = weight[order]                                                # [n, F]
    cw = jnp.cumsum(ws, axis=0)
    total = cw[-1:, :]
    probs = (cw - 0.5 * ws) / total                                   # midpoint rule
    def per_f(xf, pf):
        return jnp.interp(qs, pf, xf)
    return jax.vmap(per_f, in_axes=(1, 1))(xs, probs)                 # [F, n_summary]


@partial(jax.jit, static_argnums=(1,))
def merge_summaries(gathered: jax.Array, n_bins: int) -> jax.Array:
    """Merge ``[W, F, n_summary]`` worker summaries into ``[F, n_bins-1]``
    cut points (interior boundaries; bin b = count of cuts ≤ x)."""
    W, F, S = gathered.shape
    merged = jnp.transpose(gathered, (1, 0, 2)).reshape(F, W * S)
    qs = jnp.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    cuts = jnp.quantile(merged, qs, axis=1).T                         # [F, n_bins-1]
    # strictly increasing guard: collapse duplicate cuts upward by epsilon
    eps = jnp.maximum(jnp.abs(cuts) * 1e-6, 1e-6)
    cuts = jnp.maximum(cuts, jnp.concatenate(
        [cuts[:, :1] - 1.0, cuts[:, :-1] + eps[:, :-1]], axis=1))
    return cuts


def compute_cuts(
    x: np.ndarray,
    n_bins: int = 256,
    weight: Optional[np.ndarray] = None,
    n_summary: Optional[int] = None,
    allgather_fn=None,
) -> jax.Array:
    """End-to-end cut computation.

    ``allgather_fn(summary) -> [W, F, S]`` injects the distributed gather
    (e.g. ``collectives.allgather`` across processes, or an in-mesh
    all_gather); None means single worker.
    """
    CHECK(n_bins >= 2, "need at least 2 bins")
    n_summary = n_summary or max(8 * n_bins, 64)
    summary = local_summary(jnp.asarray(x), None if weight is None else jnp.asarray(weight),
                            n_summary)
    if allgather_fn is not None:
        gathered = jnp.asarray(allgather_fn(np.asarray(summary)))
    else:
        gathered = summary[None]
    return merge_summaries(gathered, n_bins)


@jax.jit
def apply_bins(x: jax.Array, cuts: jax.Array) -> jax.Array:
    """Digitize ``x`` [n, F] by per-feature ``cuts`` [F, n_bins-1] →
    integer bins [n, F] (bin = #cuts ≤ value, so bins ∈ [0, n_bins-1]).

    Per-feature ``searchsorted`` (binary search, O(n·log C)) rather than a
    broadcast-compare, which would materialize an [n, F, C] intermediate —
    prohibitive at HIGGS scale (10M × 28 × 255).

    dtype: uint8 when bins fit (n_bins ≤ 256, the XGBoost max_bin default)
    — the bin matrix is the largest resident training array and the
    narrow dtype quarters its HBM footprint under TPU tiling; int32
    otherwise.
    """
    out = jax.vmap(
        lambda col, c: jnp.searchsorted(c, col, side="right"),
        in_axes=(1, 0), out_axes=1,
    )(x, cuts)
    dtype = jnp.uint8 if cuts.shape[1] < 256 else jnp.int32
    return out.astype(dtype)
