"""Host→device infeed pipeline: double-buffered, stall-accounted.

The TPU re-founding of the reference's threaded prefetch stack
(``src/io/threaded_input_split.h`` + ``include/dmlc/threadediter.h``,
SURVEY.md §3.1's two thread boundaries): boundary #1 (storage read) and
#2 (parse) stay host-side in :class:`~dmlc_core_tpu.io.threaded_iter.
ThreadedIter`; this module adds boundary #3 — the host→device transfer —
which the reference never had and which decides whether a TPU trainer is
compute- or infeed-bound (BASELINE config 2's metric).

Design: ``jax.device_put`` onto a ``NamedSharding`` is asynchronous — it
returns a ``jax.Array`` whose transfer proceeds in the background.  The
feed therefore keeps ``depth`` batches dispatched ahead of the consumer:
while step N computes, batch N+1 is crossing PCIe and batch N+2 is being
parsed.  ``stats`` records the time the consumer actually blocked on the
host pipeline (``stall_s``) vs total wall — the "infeed stall %" of
BASELINE config 2.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dmlc_core_tpu.base.logging import CHECK
from dmlc_core_tpu.base.timer import get_time
from dmlc_core_tpu.io.threaded_iter import ThreadedIter

__all__ = ["DeviceFeed", "FeedStats", "assemble_row_sharded"]


def assemble_row_sharded(per_device, mesh: Mesh, dim: int = 0,
                         axis: str = "data") -> jax.Array:
    """Stitch per-device shards into ONE global array sharded on ``dim``.

    ``per_device`` holds one equal-shape array per device of a 1-axis
    mesh, in axis order; host arrays are device_put (committed) onto
    their device, already-committed device arrays pass through.  The
    result is a global ``jax.Array`` with
    ``NamedSharding(mesh, P(..., axis, ...))`` — byte-identical to a
    whole-matrix ``device_put`` of the concatenation, without the
    concatenated host (or single-device) copy ever existing.  This is
    the assembly step of sharded ingest (boundary #3 of the data
    pipeline, per-chip edition): each chip's slice arrives on that chip
    and nowhere else.
    """
    devs = list(np.asarray(mesh.devices).flat)
    CHECK(len(per_device) == len(devs),
          f"assemble_row_sharded: {len(per_device)} shards for "
          f"{len(devs)} devices")
    shards = []
    for arr, dev in zip(per_device, devs):
        if isinstance(arr, jax.Array) and arr.committed:
            shards.append(arr)
        else:
            shards.append(jax.device_put(arr, dev))
    ndim = shards[0].ndim
    CHECK(0 <= dim < ndim, f"assemble_row_sharded: dim {dim} out of range")
    shape = list(shards[0].shape)
    shape[dim] *= len(devs)
    spec = P(*[axis if i == dim else None for i in range(ndim)])
    return jax.make_array_from_single_device_arrays(
        tuple(shape), NamedSharding(mesh, spec), shards)


class FeedStats:
    """Infeed counters: batches, bytes, consumer stall time."""

    def __init__(self) -> None:
        self.batches = 0
        self.bytes = 0
        self.stall_s = 0.0
        self.start_t = get_time()

    def stall_fraction(self) -> float:
        wall = max(get_time() - self.start_t, 1e-9)
        return self.stall_s / wall

    def as_dict(self) -> Dict[str, float]:
        return {"batches": self.batches, "bytes": self.bytes,
                "stall_s": round(self.stall_s, 4),
                "stall_fraction": round(self.stall_fraction(), 4)}


def _nbytes(tree: Any) -> int:
    return sum(np.asarray(leaf).nbytes for leaf in jax.tree.leaves(tree))


class DeviceFeed:
    """Stream host batches onto mesh-sharded device buffers, ``depth`` ahead.

    ``host_iter``: an iterable (or callable returning an iterator, so the
    feed can rewind for multi-epoch training) yielding pytrees of numpy
    arrays — typically ``(images, labels)`` from a RecordIO batch iterator.

    ``sharding``: a pytree of ``NamedSharding`` matching each batch's
    structure, or a single sharding applied to every leaf, or a ``Mesh``
    (shorthand: shard every leaf's dim 0 on ``data``).

    Iterating yields pytrees of ``jax.Array`` already (or soon) resident
    on device.  Host-side parsing runs in a ``ThreadedIter`` producer
    thread; device transfers are dispatched ``depth`` batches ahead.
    """

    def __init__(
        self,
        host_iter: Iterable[Any] | Callable[[], Iterator[Any]],
        sharding: Any,
        depth: int = 2,
        host_prefetch: int = 4,
    ):
        CHECK(depth >= 1, "DeviceFeed: depth must be >= 1")
        self._make_iter = host_iter if callable(host_iter) else (lambda: iter(host_iter))
        self._sharding = sharding
        self._depth = depth
        self._titer: ThreadedIter = ThreadedIter(max_capacity=host_prefetch)
        self._host_it: Optional[Iterator[Any]] = None
        self._inflight: deque = deque()
        self._exhausted = False
        self.stats = FeedStats()

        def next_fn(_reuse):
            # lazy: the producer thread may call this before the first
            # before_first_fn (epoch 0 starts immediately)
            if self._host_it is None:
                self._host_it = self._make_iter()
            try:
                return next(self._host_it)
            except StopIteration:
                return None

        def before_first_fn():
            self._host_it = self._make_iter()

        self._titer.init(next_fn, before_first_fn)

    # -- sharding resolution -------------------------------------------
    def _put(self, batch: Any) -> Any:
        sh = self._sharding
        if isinstance(sh, Mesh):
            def put_leaf(leaf):
                arr = np.asarray(leaf)
                spec = P("data", *([None] * (arr.ndim - 1)))
                return jax.device_put(arr, NamedSharding(sh, spec))
            return jax.tree.map(put_leaf, batch)
        if isinstance(sh, jax.sharding.Sharding):
            return jax.tree.map(lambda leaf: jax.device_put(leaf, sh), batch)
        return jax.tree.map(jax.device_put, batch, sh)

    # -- pipeline ------------------------------------------------------
    def _fill(self) -> None:
        while not self._exhausted and len(self._inflight) < self._depth:
            t0 = get_time()
            item = self._titer.next()
            if item is None:
                self._exhausted = True
                return
            # time blocked on the host pipeline = infeed stall
            self.stats.stall_s += get_time() - t0
            self.stats.bytes += _nbytes(item)
            # NOT recycled: device_put may alias the host buffer (zero-copy
            # on the CPU backend), so refilling it in place would corrupt an
            # in-flight batch
            self._inflight.append(self._put(item))

    def __iter__(self) -> Iterator[Any]:
        self.before_first()
        return self

    def __next__(self) -> Any:
        self._fill()
        if not self._inflight:
            raise StopIteration
        batch = self._inflight.popleft()
        self._fill()  # keep the pipe full while the caller computes
        self.stats.batches += 1
        return batch

    def before_first(self) -> None:
        """Rewind for a new epoch (reference ``BeforeFirst`` semantics)."""
        self._titer.before_first()
        self._inflight.clear()
        self._exhausted = False

    def close(self) -> None:
        self._titer.destroy()

    def __enter__(self) -> "DeviceFeed":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
