"""Row-block iterators: in-memory and external-memory (disk-cached).

Reference parity: ``include/dmlc/data.h :: RowBlockIter<I>::Create``,
``src/data/basic_row_iter.h :: BasicRowIter`` (slurp whole input),
``src/data/disk_row_iter.h :: DiskRowIter`` (parse once → binary pages on a
cache file → prefetch-iterate pages) (SURVEY.md §2b).

A ``#cachefile`` suffix on the URI selects the external-memory path, exactly
like the reference (``RowBlockIter::Create("big.libsvm#cache.bin", ...)``):
pass 1 streams parser output into RowBlockContainer pages on the cache URI;
later epochs replay pages through a ThreadedIter so storage read overlaps
consumption — the same pipeline shape the TPU infeed path reuses
(``dmlc_core_tpu.data.device``).
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import numpy as np

from dmlc_core_tpu.base import metrics as _metrics
from dmlc_core_tpu.base.logging import CHECK
from dmlc_core_tpu.base.timer import get_time
from dmlc_core_tpu.data.parsers import Parser, parse_uri_spec
from dmlc_core_tpu.data.row_block import RowBlock, RowBlockContainer
from dmlc_core_tpu.io.stream import Stream
from dmlc_core_tpu.io.threaded_iter import ThreadedIter
from dmlc_core_tpu.utils.profiler import global_tracer, tracing_enabled

__all__ = ["RowBlockIter", "BasicRowIter", "DiskRowIter", "ArrayRowIter",
           "iter_dense_slabs", "iter_csr_minibatches", "slab_shard_slices"]

# target bytes per cache page (reference uses a row-count heuristic; byte
# budget maps better to fixed host-staging buffers)
_PAGE_BYTES = 64 << 20

_DM = None


def _data_metrics():
    """``path="build"`` counts the pass-1 parse→cache write; ``"replay"``
    counts cache-hit page reads on later epochs — the external-memory
    question (is this run paying the parse again?) answered by two
    counters."""
    global _DM
    if _DM is None:
        r = _metrics.default_registry()
        _DM = {
            "pages": r.counter("data_pages_total",
                               "row-block pages through DiskRowIter",
                               labels=("path",)),
            "rows": r.counter("data_page_rows_total",
                              "rows through DiskRowIter pages",
                              labels=("path",)),
            "build_s": r.histogram("data_cache_build_seconds",
                                   "DiskRowIter pass-1 cache build time"),
        }
    return _DM


class RowBlockIter:
    """Iterator over CSR RowBlocks with rewind.

    Reference: ``dmlc::RowBlockIter<IndexType>`` (DataIter contract:
    before_first / next / value).
    """

    @staticmethod
    def create(uri: str, part: int = 0, nparts: int = 1,
               format: Optional[str] = None, nthread: int = 0) -> "RowBlockIter":
        """``#cachefile`` in the URI → external-memory DiskRowIter, else
        in-memory BasicRowIter.  Reference: ``src/data.cc :: CreateIter_``."""
        _path, _args, cache = parse_uri_spec(uri)
        parser = Parser.create(uri, part, nparts, format, nthread)
        if cache:
            return DiskRowIter(parser, cache + (f".part{part}" if nparts > 1 else ""))
        return BasicRowIter(parser)

    # -- DataIter contract ----------------------------------------------
    def before_first(self) -> None:
        raise NotImplementedError

    def next_block(self) -> Optional[RowBlock]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[RowBlock]:
        self.before_first()
        while True:
            block = self.next_block()
            if block is None:
                return
            yield block

    @property
    def num_col(self) -> int:
        raise NotImplementedError

    @property
    def num_rows(self) -> Optional[int]:
        """Total rows, when known without a decode pass (None otherwise).
        Consumers sizing a preallocation (GBLinear.fit_iter) use this to
        avoid re-reading the whole input just to count."""
        return None

    def close(self) -> None:
        pass


class BasicRowIter(RowBlockIter):
    """Slurp the whole parser output into one block at construction.

    Reference: ``basic_row_iter.h`` — the small-data path.
    """

    def __init__(self, parser: Parser):
        container = RowBlockContainer()
        for block in parser:
            container.push_block(block)
        parser.close()
        self._block = container.to_block()
        self._max_index = container.max_index
        self._done = False

    def before_first(self) -> None:
        self._done = False

    def next_block(self) -> Optional[RowBlock]:
        if self._done:
            return None
        self._done = True
        return self._block

    @property
    def value(self) -> RowBlock:
        return self._block

    @property
    def num_col(self) -> int:
        return self._max_index + 1

    @property
    def num_rows(self) -> int:
        return self._block.size


class ArrayRowIter(RowBlockIter):
    """In-memory dense arrays as a rewindable :class:`RowBlockIter`.

    The adapter the elastic recovery layer uses to re-cut row shards
    over a changing world: ``ArrayRowIter(X[lo:hi], y[lo:hi])`` turns
    any contiguous row range into the page-stream contract
    ``fit_external`` consumes, without a serialization round trip.
    Pages are CSR views of ``page_rows`` rows each (dense: every entry
    present, so zeros stay explicit and bin identically to the
    densified parser path).
    """

    def __init__(self, X, y, weight=None, page_rows: int = 65536):
        X = np.ascontiguousarray(X, dtype=np.float32)
        y = np.ascontiguousarray(y, dtype=np.float32)
        n, F = X.shape
        self._ncol = F
        self._pages = []
        for lo in range(0, max(n, 1), page_rows):
            hi = min(lo + page_rows, n)
            rows = hi - lo
            self._pages.append(RowBlock(
                offset=np.arange(rows + 1, dtype=np.int64) * F,
                label=y[lo:hi],
                index=np.tile(np.arange(F, dtype=np.int64), rows),
                value=X[lo:hi].reshape(-1),
                weight=None if weight is None else np.ascontiguousarray(
                    weight[lo:hi], dtype=np.float32),
            ))
        self._n = n
        self._pos = 0

    def before_first(self) -> None:
        self._pos = 0

    def next_block(self) -> Optional[RowBlock]:
        if self._pos >= len(self._pages):
            return None
        block = self._pages[self._pos]
        self._pos += 1
        return block

    @property
    def num_col(self) -> int:
        return self._ncol

    @property
    def num_rows(self) -> int:
        return self._n


class DiskRowIter(RowBlockIter):
    """Parse once to binary pages on a cache URI; iterate pages with
    prefetch.  Reference: ``disk_row_iter.h`` — the external-memory path
    (ancestor of XGBoost external memory)."""

    def __init__(self, parser: Parser, cache_uri: str, page_bytes: int = _PAGE_BYTES):
        self._cache_uri = cache_uri
        self._max_index = 0
        self._num_pages = 0
        self._num_rows = 0
        self._build_cache(parser, page_bytes)
        self._iter: Optional[ThreadedIter] = None
        self._read_stream: Optional[Stream] = None

    def _build_cache(self, parser: Parser, page_bytes: int) -> None:
        t0 = get_time()
        ctx = (global_tracer().scope("disk_row_iter.build_cache",
                                     cache=self._cache_uri)
               if tracing_enabled() else contextlib.nullcontext())
        with ctx:
            out = Stream.create(self._cache_uri, "w")
            container = RowBlockContainer()
            held = 0
            for block in parser:
                container.push_block(block)
                self._num_rows += block.size
                held += block.memory_cost()
                if held >= page_bytes:
                    container.save(out)
                    self._num_pages += 1
                    self._max_index = max(self._max_index, container.max_index)
                    container.clear()
                    held = 0
            if container.size:
                container.save(out)
                self._num_pages += 1
                self._max_index = max(self._max_index, container.max_index)
            out.close()
            parser.close()
        if _metrics.enabled():
            m = _data_metrics()
            m["pages"].inc(self._num_pages, path="build")
            m["rows"].inc(self._num_rows, path="build")
            m["build_s"].observe(get_time() - t0)

    def _start_reader(self) -> None:
        self._stop_reader()
        self._read_stream = Stream.create(self._cache_uri, "r")

        def next_page(_cell) -> Optional[RowBlock]:
            container = RowBlockContainer()
            if not container.load(self._read_stream):
                return None
            block = container.to_block()
            if _metrics.enabled():
                m = _data_metrics()
                m["pages"].inc(1, path="replay")
                m["rows"].inc(block.size, path="replay")
            return block

        def rewind() -> None:
            self._read_stream.close()
            self._read_stream = Stream.create(self._cache_uri, "r")

        self._iter = ThreadedIter(max_capacity=2, name="disk_row_iter")
        self._iter.init(next_page, rewind)

    def _stop_reader(self) -> None:
        if self._iter is not None:
            self._iter.destroy()
            self._iter = None
        if self._read_stream is not None:
            self._read_stream.close()
            self._read_stream = None

    def before_first(self) -> None:
        if self._iter is None:
            self._start_reader()
        else:
            self._iter.before_first()

    def next_block(self) -> Optional[RowBlock]:
        if self._iter is None:
            self._start_reader()
        return self._iter.next()

    @property
    def num_col(self) -> int:
        return self._max_index + 1

    @property
    def num_rows(self) -> int:
        return self._num_rows

    def close(self) -> None:
        self._stop_reader()


def slab_shard_slices(lo: int, length: int, shard_rows: int):
    """Map an ingest slab occupying global rows ``[lo, lo+length)`` onto
    the equal-block device layout (device ``k`` owns rows
    ``[k·shard_rows, (k+1)·shard_rows)``): returns
    ``[(shard, src_lo, src_hi, dst_lo), ...]`` pieces, in order, whose
    source slices tile the slab exactly.

    This is the tail math of sharded ingest: a streamed chunk rarely
    aligns with shard boundaries — the last chunk of a
    ``nrows % (chips · chunk)`` tail may start mid-shard and end
    mid-shard — so every piece must land at its exact per-shard offset
    ``dst_lo`` with no row dropped or written twice (property-pinned in
    tests/test_multichip.py).
    """
    out = []
    pos = lo
    end = lo + length
    while pos < end:
        k = pos // shard_rows
        take = min(end, (k + 1) * shard_rows) - pos
        out.append((k, pos - lo, pos - lo + take, pos - k * shard_rows))
        pos += take
    return out


def iter_dense_slabs(row_iter, num_col: int, batch_rows: int):
    """Yield dense ``(X, y, w)`` float32 slabs of ≤ ``batch_rows`` rows
    from a :class:`RowBlockIter` — the shared staging loop under
    streaming fit/predict (GBLinear.fit_iter, HistGBT.predict_iter,
    GBLinear.predict_iter).

    Since the ``stream.dataset`` refactor this is a thin adapter over
    the shared :class:`~dmlc_core_tpu.stream.dataset.Dataset`
    abstraction (``Dataset.from_row_iter(...).dense_slabs(...)``) —
    batch and online paths stage slabs through one implementation.

    CSR pages densify straight into one reused staging buffer; pages
    straddling a slab boundary split transparently (RowBlock.slice row
    ranges).  Host memory stays bounded by one slab regardless of the
    dataset.  Pages whose column index reaches ``num_col`` fail loudly —
    a silently truncated feature would corrupt whatever consumes the
    slab.

    The yielded arrays are VIEWS of the reused buffers: consumers must
    copy (or upload with an explicit host copy) before advancing the
    generator.  ``w`` is 1.0 where the page carries no weights.
    """
    from dmlc_core_tpu.stream.dataset import Dataset

    return iter(Dataset.from_row_iter(row_iter)
                .dense_slabs(num_col, batch_rows))


def iter_csr_minibatches(row_iter, batch_rows: int):
    """Yield CSR :class:`RowBlock` minibatches of ≤ ``batch_rows`` rows.

    The sparse twin of :func:`iter_dense_slabs`: pages stream through
    UNDENSIFIED so a 10M+-column CTR dataset never materialises a dense
    slab — consumers (GBLinear.fit_ps, FM.fit_ps) work straight off the
    ``offset``/``index``/``value`` arrays and only ever touch the
    feature ids present in the batch.  Pages larger than ``batch_rows``
    split via zero-copy :meth:`RowBlock.slice`; smaller pages pass
    through whole (ragged tails are fine for SGD — no cross-page
    re-batching, which would force copies).
    """
    CHECK(batch_rows > 0, f"batch_rows must be positive, got {batch_rows}")
    for block in row_iter:
        if block.size <= batch_rows:
            if block.size:
                yield block
            continue
        lo = 0
        while lo < block.size:
            hi = min(block.size, lo + batch_rows)
            yield block.slice(lo, hi)
            lo = hi
