"""Text parsers: LibSVM / CSV / LibFM chunks → CSR RowBlocks.

Reference parity: ``src/data/parser.h :: ParserImpl (FillData, BytesRead)``,
``text_parser.h :: TextParserBase`` (the multithreaded parse hot loop),
``libsvm_parser.h``, ``csv_parser.h :: CSVParserParam``, ``libfm_parser.h``,
and ``src/data.cc``'s parser factory registry / ``src/io/uri_spec.h``'s
URI-embedded kwargs (SURVEY.md §2b).

Engine split: the hot loop lives in ``cpp/fastparse.cc`` (OpenMP over line
ranges, from_chars number parsing) reached via ctypes; a pure-numpy fallback
keeps the package dependency-free.  Parsers pull chunks from a (threaded)
InputSplit, so storage read, parse, and device staging pipeline into each
other exactly like the reference's two thread boundaries (SURVEY.md §3.1).
"""

from __future__ import annotations

import urllib.parse
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from dmlc_core_tpu.base import metrics as _metrics
from dmlc_core_tpu.base.logging import Error, log_fatal
from dmlc_core_tpu.base.parameter import Parameter, field
from dmlc_core_tpu.base.registry import Registry
from dmlc_core_tpu.base.timer import get_time
from dmlc_core_tpu.data import _native
from dmlc_core_tpu.data.row_block import RowBlock
from dmlc_core_tpu.io.input_split import InputSplit
from dmlc_core_tpu.utils.profiler import global_tracer, tracing_enabled

__all__ = ["Parser", "LibSVMParser", "CSVParser", "LibFMParser", "parse_uri_spec"]

PARSER_REGISTRY: Registry = Registry.get("data_parser")

_PM = None


def _parser_metrics():
    global _PM
    if _PM is None:
        r = _metrics.default_registry()
        _PM = {
            "bytes": r.counter("data_parse_bytes_total",
                               "raw input bytes parsed", labels=("format",)),
            "rows": r.counter("data_parse_rows_total",
                              "rows produced by parsers", labels=("format",)),
            "seconds": r.histogram("data_parse_seconds",
                                   "per-chunk parse time",
                                   labels=("format",)),
        }
    return _PM


def parse_uri_spec(uri: str) -> Tuple[str, Dict[str, str], Optional[str]]:
    """Split ``path?key=val&key2=val2#cachefile`` into (path, args, cache).

    Reference parity: ``src/io/uri_spec.h :: URISpec`` — parser kwargs ride
    inside the URI so consumer call sites stay one-string.
    """
    cache = None
    if "#" in uri:
        uri, _, cache = uri.rpartition("#")
    args: Dict[str, str] = {}
    if "?" in uri:
        uri, _, query = uri.partition("?")
        for key, val in urllib.parse.parse_qsl(query, keep_blank_values=True):
            args[key] = val
    return uri, args, cache


class CSVParserParam(Parameter):
    """Reference parity: ``csv_parser.h :: CSVParserParam``."""

    format = field(str, default="csv")
    label_column = field(int, default=0, description="column used as label")
    weight_column = field(int, default=-1, description="column used as weight (-1: none)")
    delimiter = field(str, default=",", description="field delimiter")


class Parser:
    """Chunk-pulling parser producing RowBlocks.

    Reference parity: ``dmlc::Parser<IndexType>`` — created by format name
    via the ``data_parser`` registry; iterating yields CSR
    :class:`RowBlock` batches; ``bytes_read`` tracks raw input consumed.
    """

    #: metrics label; each registered parser class overrides
    format_name = "unknown"

    def __init__(self, split: InputSplit, nthread: int = 0):
        self._split = split
        self._nthread = nthread
        self.bytes_read = 0

    # -- factory ---------------------------------------------------------
    @staticmethod
    def create(uri: str, part: int = 0, nparts: int = 1,
               format: Optional[str] = None, nthread: int = 0) -> "Parser":
        """Reference: ``Parser<I>::Create(uri, part, nparts, type)``.

        Format comes from the explicit arg or a ``?format=`` URI key
        (default libsvm, like the reference).
        """
        path, args, _cache = parse_uri_spec(uri)
        fmt = format or args.get("format", "libsvm")
        entry = PARSER_REGISTRY.find(fmt)
        if entry is None:
            log_fatal(
                f"Parser.create: unknown format {fmt!r}; known: "
                f"{PARSER_REGISTRY.list_all_names()}"
            )
        return entry(path, part, nparts, args, nthread)

    # -- iteration -------------------------------------------------------
    def _parse_chunk(self, chunk: bytes) -> Optional[RowBlock]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[RowBlock]:
        while True:
            chunk = self._split.next_chunk()
            if chunk is None:
                return
            self.bytes_read += len(chunk)
            if _metrics.enabled():
                m = _parser_metrics()
                fmt = self.format_name
                m["bytes"].inc(len(chunk), format=fmt)
                t0 = get_time()
                if tracing_enabled():
                    with global_tracer().scope("parse", format=fmt,
                                               bytes=len(chunk)):
                        block = self._parse_chunk(chunk)
                else:
                    block = self._parse_chunk(chunk)
                m["seconds"].observe(get_time() - t0, format=fmt)
                if block is not None:
                    m["rows"].inc(block.size, format=fmt)
            else:
                block = self._parse_chunk(chunk)
            if block is not None and block.size > 0:
                yield block

    def before_first(self) -> None:
        self._split.before_first()
        self.bytes_read = 0

    def hint_chunk_size(self, nbytes: int) -> None:
        self._split.hint_chunk_size(nbytes)

    def close(self) -> None:
        self._split.close()

    @staticmethod
    def _from_arrays(d: dict) -> Optional[RowBlock]:
        if len(d["label"]) == 0:
            return None
        return RowBlock(
            offset=d["offset"], label=d["label"], index=d["index"],
            value=d.get("value"), weight=d.get("weight"), qid=d.get("qid"),
            field=d.get("field"),
        )


@PARSER_REGISTRY.register("libsvm")
class LibSVMParser(Parser):
    """``label [qid:n] idx:val ...`` — XGBoost's classic input format."""

    format_name = "libsvm"

    def __init__(self, path: str, part: int, nparts: int,
                 args: Optional[Dict[str, str]] = None, nthread: int = 0):
        super().__init__(InputSplit.create(path, part, nparts, "text"), nthread)

    def _parse_chunk(self, chunk: bytes) -> Optional[RowBlock]:
        if _native.native_available():
            return self._from_arrays(_native.parse_libsvm(chunk, self._nthread))
        return self._from_arrays(_py_parse_libsvm(chunk))


@PARSER_REGISTRY.register("csv")
class CSVParser(Parser):
    """Dense CSV → CSR (zeros kept, feature index = column position
    excluding label/weight columns)."""

    format_name = "csv"

    def __init__(self, path: str, part: int, nparts: int,
                 args: Optional[Dict[str, str]] = None, nthread: int = 0):
        super().__init__(InputSplit.create(path, part, nparts, "text"), nthread)
        self.param = CSVParserParam()
        self.param.init(args or {}, allow_unknown=True)

    def _parse_chunk(self, chunk: bytes) -> Optional[RowBlock]:
        p = self.param
        if _native.native_available():
            return self._from_arrays(
                _native.parse_csv(chunk, p.delimiter, p.label_column,
                                  p.weight_column, self._nthread)
            )
        return self._from_arrays(
            _py_parse_csv(chunk, p.delimiter, p.label_column, p.weight_column)
        )


@PARSER_REGISTRY.register("libfm")
class LibFMParser(Parser):
    """``label field:idx:val ...`` — field-aware FM format."""

    format_name = "libfm"

    def __init__(self, path: str, part: int, nparts: int,
                 args: Optional[Dict[str, str]] = None, nthread: int = 0):
        super().__init__(InputSplit.create(path, part, nparts, "text"), nthread)

    def _parse_chunk(self, chunk: bytes) -> Optional[RowBlock]:
        if _native.native_available():
            return self._from_arrays(_native.parse_libfm(chunk, self._nthread))
        return self._from_arrays(_py_parse_libfm(chunk))


# -- pure-python fallbacks (correctness reference for the native engine) --

def _py_parse_libsvm(chunk: bytes) -> dict:
    offsets = [0]
    labels: list = []
    qids: list = []
    idx_parts: list = []
    val_parts: list = []
    any_qid = False
    nnz = 0
    for line in chunk.split(b"\n"):
        tokens = line.split()
        if not tokens:
            continue
        try:
            labels.append(float(tokens[0]))
        except ValueError as e:
            raise Error(f"libsvm: bad label {tokens[0]!r}") from e
        qid = 0
        for tok in tokens[1:]:
            if tok.startswith(b"qid:"):
                qid = int(tok[4:])
                any_qid = True
                continue
            feat, _, val = tok.partition(b":")
            try:
                idx_parts.append(int(feat))
                val_parts.append(float(val) if val else 1.0)
            except ValueError as e:
                raise Error(f"libsvm: bad feature {tok!r}") from e
            nnz += 1
        qids.append(qid)
        offsets.append(nnz)
    return {
        "offset": np.asarray(offsets, np.int64),
        "label": np.asarray(labels, np.float32),
        "index": np.asarray(idx_parts, np.int64),
        "value": np.asarray(val_parts, np.float32),
        "weight": None,
        "qid": np.asarray(qids, np.int64) if any_qid else None,
        "field": None,
    }


def _py_parse_csv(chunk: bytes, delimiter: str, label_col: int, weight_col: int) -> dict:
    delim = delimiter.encode()
    offsets = [0]
    labels: list = []
    weights: list = []
    values: list = []
    indices: list = []
    any_weight = False
    nnz = 0
    for line in chunk.split(b"\n"):
        line = line.strip()
        if not line:
            continue
        cells = line.split(delim)
        try:
            row = [float(c) if c.strip() else 0.0 for c in cells]
        except ValueError as e:
            raise Error(f"csv: bad number in line {line!r}") from e
        label = 0.0
        weight = 1.0
        feat = 0
        for col, v in enumerate(row):
            if col == label_col:
                label = v
            elif col == weight_col:
                weight = v
                any_weight = True
            else:
                indices.append(feat)
                values.append(v)
                feat += 1
                nnz += 1
        labels.append(label)
        weights.append(weight)
        offsets.append(nnz)
    return {
        "offset": np.asarray(offsets, np.int64),
        "label": np.asarray(labels, np.float32),
        "index": np.asarray(indices, np.int64),
        "value": np.asarray(values, np.float32),
        "weight": np.asarray(weights, np.float32) if any_weight else None,
        "qid": None,
        "field": None,
    }


def _py_parse_libfm(chunk: bytes) -> dict:
    offsets = [0]
    labels: list = []
    fields: list = []
    indices: list = []
    values: list = []
    nnz = 0
    for line in chunk.split(b"\n"):
        tokens = line.split()
        if not tokens:
            continue
        try:
            labels.append(float(tokens[0]))
        except ValueError as e:
            raise Error(f"libfm: bad label {tokens[0]!r}") from e
        for tok in tokens[1:]:
            parts = tok.split(b":")
            if len(parts) < 2:
                raise Error(f"libfm: bad token {tok!r}")
            fields.append(int(parts[0]))
            indices.append(int(parts[1]))
            values.append(float(parts[2]) if len(parts) > 2 else 1.0)
            nnz += 1
        offsets.append(nnz)
    return {
        "offset": np.asarray(offsets, np.int64),
        "label": np.asarray(labels, np.float32),
        "index": np.asarray(indices, np.int64),
        "value": np.asarray(values, np.float32),
        "weight": None,
        "qid": None,
        "field": np.asarray(fields, np.int32),
    }
