"""CSR row blocks — the tabular data contract.

Reference parity: ``include/dmlc/data.h :: Row<I>, RowBlock<I>`` (CSR arrays
offset/label/weight/qid/field/index/value, slice) and ``src/data/row_block.h
:: RowBlockContainer<I>`` (Push/GetBlock/Clear/Save/Load/max_index)
(SURVEY.md §2a-b).

TPU-first redesign: where the reference stores C++ vectors, a RowBlock here
is a bundle of **contiguous numpy arrays** — zero-copy views into parser
output, directly consumable by ``np.asarray``-free ``jax.device_put`` and by
the Pallas/XLA histogram kernels (``dmlc_core_tpu.ops``).  The binary page
format (``save``/``load``) is the external-memory cache format used by
``DiskRowIter``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Optional, Sequence, Union

import numpy as np

from dmlc_core_tpu.base.logging import CHECK_EQ, CHECK_LE
from dmlc_core_tpu.io import serializer as ser
from dmlc_core_tpu.io.stream import Serializable, Stream

__all__ = ["Row", "RowBlock", "RowBlockContainer"]


@dataclass
class Row:
    """One sparse row view.  Reference: ``dmlc::Row<IndexType, DType>``."""

    label: float
    index: np.ndarray
    value: Optional[np.ndarray]  # None → all ones (binary features)
    weight: float = 1.0
    qid: int = 0
    field: Optional[np.ndarray] = None

    def __len__(self) -> int:
        return len(self.index)

    def get_value(self, i: int) -> float:
        return 1.0 if self.value is None else float(self.value[i])

    def sdot(self, weights: np.ndarray) -> float:
        """Sparse dot with a dense weight vector.  Reference: ``Row::SDot``."""
        if self.value is None:
            return float(weights[self.index].sum())
        return float(np.dot(weights[self.index], self.value))


class RowBlock:
    """A block of sparse rows in CSR form.

    Arrays: ``offset`` int64[n+1]; ``label`` float32[n]; optional ``weight``
    float32[n], ``qid`` int64[n], ``field`` int32[nnz]; ``index`` int64[nnz];
    optional ``value`` float32[nnz] (None → implicit ones).
    """

    def __init__(
        self,
        offset: np.ndarray,
        label: np.ndarray,
        index: np.ndarray,
        value: Optional[np.ndarray] = None,
        weight: Optional[np.ndarray] = None,
        qid: Optional[np.ndarray] = None,
        field: Optional[np.ndarray] = None,
    ):
        self.offset = np.ascontiguousarray(offset, dtype=np.int64)
        self.label = np.ascontiguousarray(label, dtype=np.float32)
        self.index = np.ascontiguousarray(index, dtype=np.int64)
        self.value = None if value is None else np.ascontiguousarray(value, dtype=np.float32)
        self.weight = None if weight is None else np.ascontiguousarray(weight, dtype=np.float32)
        self.qid = None if qid is None else np.ascontiguousarray(qid, dtype=np.int64)
        self.field = None if field is None else np.ascontiguousarray(field, dtype=np.int32)
        n = len(self.label)
        CHECK_EQ(len(self.offset), n + 1, "RowBlock: offset size mismatch")
        nnz = int(self.offset[-1])
        CHECK_EQ(len(self.index), nnz, "RowBlock: index size mismatch")
        if self.value is not None:
            CHECK_EQ(len(self.value), nnz, "RowBlock: value size mismatch")

    @property
    def size(self) -> int:
        return len(self.label)

    @property
    def nnz(self) -> int:
        return int(self.offset[-1])

    def __len__(self) -> int:
        return self.size

    def __getitem__(self, i: Union[int, slice]) -> Union[Row, "RowBlock"]:
        if isinstance(i, slice):
            start, stop, step = i.indices(self.size)
            CHECK_EQ(step, 1, "RowBlock slices must be contiguous")
            return self.slice(start, stop)
        if i < 0:
            i += self.size
        lo, hi = int(self.offset[i]), int(self.offset[i + 1])
        return Row(
            label=float(self.label[i]),
            index=self.index[lo:hi],
            value=None if self.value is None else self.value[lo:hi],
            weight=1.0 if self.weight is None else float(self.weight[i]),
            qid=0 if self.qid is None else int(self.qid[i]),
            field=None if self.field is None else self.field[lo:hi],
        )

    def __iter__(self) -> Iterator[Row]:
        for i in range(self.size):
            yield self[i]  # type: ignore[misc]

    def slice(self, begin: int, end: int) -> "RowBlock":
        """Zero-copy contiguous row range.  Reference: ``RowBlock::Slice``."""
        CHECK_LE(begin, end)
        CHECK_LE(end, self.size)
        lo, hi = int(self.offset[begin]), int(self.offset[end])
        return RowBlock(
            offset=self.offset[begin : end + 1] - lo,
            label=self.label[begin:end],
            index=self.index[lo:hi],
            value=None if self.value is None else self.value[lo:hi],
            weight=None if self.weight is None else self.weight[begin:end],
            qid=None if self.qid is None else self.qid[begin:end],
            field=None if self.field is None else self.field[lo:hi],
        )

    @property
    def max_index(self) -> int:
        return int(self.index.max()) if len(self.index) else 0

    def memory_cost(self) -> int:
        """Approximate bytes held (reference: ``RowBlock::MemCostBytes``)."""
        total = self.offset.nbytes + self.label.nbytes + self.index.nbytes
        for arr in (self.value, self.weight, self.qid, self.field):
            if arr is not None:
                total += arr.nbytes
        return total

    def to_dense(self, num_col: Optional[int] = None) -> np.ndarray:
        """Densify to float32 [n, num_col] (missing → 0)."""
        ncol = num_col if num_col is not None else self.max_index + 1
        # np.empty, not zeros: to_dense_into zero-fills each chunk
        # itself, so zeros here would write every byte twice
        out = np.empty((self.size, ncol), dtype=np.float32)
        self.to_dense_into(out)
        return out

    def to_dense_into(self, out: np.ndarray,
                      chunk_rows: int = 1 << 20) -> None:
        """Scatter this block into a preallocated float32 ``[size, F]``
        array in bounded row chunks.

        For a whole-dataset block (BasicRowIter slurps everything into
        one RowBlock) ``to_dense`` would build nnz-sized scatter
        temporaries for the full dataset at once; chunking bounds the
        transient to ``chunk_rows`` worth regardless of block size —
        the consumer (e.g. GBLinear.fit_iter) writes straight into its
        slice of one preallocated matrix."""
        CHECK_EQ(out.shape[0], self.size, "to_dense_into: row mismatch")
        for s in range(0, self.size, chunk_rows):
            e = min(s + chunk_rows, self.size)
            o0, o1 = int(self.offset[s]), int(self.offset[e])
            rows = np.repeat(np.arange(e - s),
                             np.diff(self.offset[s:e + 1]))
            sl = out[s:e]
            sl.fill(0.0)
            vals = (self.value[o0:o1] if self.value is not None
                    else np.ones(o1 - o0, np.float32))
            sl[rows, self.index[o0:o1]] = vals


class RowBlockContainer(Serializable):
    """Growable CSR builder with binary page (de)serialization.

    Reference parity: ``src/data/row_block.h :: RowBlockContainer<I>`` —
    this is the external-memory cache-file format.
    """

    def __init__(self) -> None:
        self.clear()

    def clear(self) -> None:
        self._offsets: List[int] = [0]
        self._labels: List[float] = []
        self._weights: List[float] = []
        self._qids: List[int] = []
        self._index_chunks: List[np.ndarray] = []
        self._value_chunks: List[Optional[np.ndarray]] = []
        self._field_chunks: List[Optional[np.ndarray]] = []
        self._nnz = 0
        self.max_index = 0
        # column presence is schema, not data: a weight column of all 1.0s
        # must survive the cache round trip as a present column
        self._has_weight = False
        self._has_qid = False

    @property
    def size(self) -> int:
        return len(self._labels)

    def __len__(self) -> int:
        return self.size

    def push(
        self,
        label: float,
        index: Sequence[int],
        value: Optional[Sequence[float]] = None,
        weight: Optional[float] = None,
        qid: Optional[int] = None,
        field: Optional[Sequence[int]] = None,
    ) -> None:
        """Append one row.  Reference: ``RowBlockContainer::Push(Row)``.

        ``weight``/``qid`` of None mean "column absent" (defaults 1.0 / 0
        are substituted if other rows establish the column).
        """
        idx = np.asarray(index, dtype=np.int64)
        self._index_chunks.append(idx)
        self._value_chunks.append(
            None if value is None else np.asarray(value, dtype=np.float32)
        )
        self._field_chunks.append(
            None if field is None else np.asarray(field, dtype=np.int32)
        )
        self._nnz += len(idx)
        self._offsets.append(self._nnz)
        self._labels.append(float(label))
        self._weights.append(1.0 if weight is None else float(weight))
        self._qids.append(0 if qid is None else int(qid))
        self._has_weight |= weight is not None
        self._has_qid |= qid is not None
        if len(idx):
            self.max_index = max(self.max_index, int(idx.max()))

    def push_block(self, block: RowBlock) -> None:
        """Append a whole RowBlock (bulk path used by parsers)."""
        self._index_chunks.append(block.index)
        self._value_chunks.append(block.value)
        self._field_chunks.append(block.field)
        base = self._nnz
        self._nnz += block.nnz
        self._offsets.extend((block.offset[1:] + base).tolist())
        self._labels.extend(block.label.tolist())
        w = block.weight if block.weight is not None else np.ones(block.size, np.float32)
        self._weights.extend(w.tolist())
        q = block.qid if block.qid is not None else np.zeros(block.size, np.int64)
        self._qids.extend(q.tolist())
        self._has_weight |= block.weight is not None
        self._has_qid |= block.qid is not None
        if block.nnz:
            self.max_index = max(self.max_index, block.max_index)

    def to_block(self) -> RowBlock:
        """Materialize the accumulated rows.  Reference: ``GetBlock``."""
        nnz = self._nnz
        index = (
            np.concatenate(self._index_chunks)
            if self._index_chunks
            else np.empty(0, np.int64)
        )
        has_value = any(v is not None for v in self._value_chunks)
        value = None
        if has_value:
            value = np.concatenate(
                [
                    v if v is not None else np.ones(len(i), np.float32)
                    for v, i in zip(self._value_chunks, self._index_chunks)
                ]
            ) if self._value_chunks else np.empty(0, np.float32)
        has_field = any(f is not None for f in self._field_chunks)
        field = None
        if has_field:
            field = np.concatenate(
                [
                    f if f is not None else np.zeros(len(i), np.int32)
                    for f, i in zip(self._field_chunks, self._index_chunks)
                ]
            )
        return RowBlock(
            offset=np.asarray(self._offsets, dtype=np.int64),
            label=np.asarray(self._labels, dtype=np.float32),
            index=index,
            value=value,
            weight=np.asarray(self._weights, np.float32) if self._has_weight else None,
            qid=np.asarray(self._qids, np.int64) if self._has_qid else None,
            field=field,
        )

    # -- binary page format (the disk-cache format) ----------------------
    _PAGE_MAGIC = 0xD317B10C

    def save(self, stream: Stream) -> None:
        block = self.to_block()
        ser.write_uint32(stream, self._PAGE_MAGIC)
        flags = (
            (1 if block.value is not None else 0)
            | (2 if block.weight is not None else 0)
            | (4 if block.qid is not None else 0)
            | (8 if block.field is not None else 0)
        )
        ser.write_uint32(stream, flags)
        ser.write_uint64(stream, self.max_index)
        ser.write_ndarray(stream, block.offset)
        ser.write_ndarray(stream, block.label)
        ser.write_ndarray(stream, block.index)
        for arr in (block.value, block.weight, block.qid, block.field):
            if arr is not None:
                ser.write_ndarray(stream, arr)

    def load(self, stream: Stream) -> bool:
        """Load one page; returns False on clean EOF."""
        head = stream.read(4)
        if len(head) == 0:
            return False
        CHECK_EQ(len(head), 4, "RowBlockContainer: truncated page header")
        magic = int.from_bytes(head, "little")
        CHECK_EQ(magic, self._PAGE_MAGIC, "RowBlockContainer: bad page magic")
        flags = ser.read_uint32(stream)
        max_index = ser.read_uint64(stream)
        offset = ser.read_ndarray(stream)
        label = ser.read_ndarray(stream)
        index = ser.read_ndarray(stream)
        value = ser.read_ndarray(stream) if flags & 1 else None
        weight = ser.read_ndarray(stream) if flags & 2 else None
        qid = ser.read_ndarray(stream) if flags & 4 else None
        field = ser.read_ndarray(stream) if flags & 8 else None
        self.clear()
        self.push_block(
            RowBlock(offset, label, index, value=value, weight=weight, qid=qid, field=field)
        )
        self.max_index = int(max_index)
        return True
