"""Image records over RecordIO — the ImageNet shard format (config 2).

Reference parity: MXNet's ``.rec`` image pipeline is RecordIO records of
``IRHeader + payload`` consumed through ``InputSplit::Create(uri, part,
nparts, "recordio")`` (SURVEY.md §3.2).  The header here mirrors IRHeader's
fields (flag, label, id) plus an explicit shape so tests and synthetic
data can carry raw tensors; payload is either raw uint8 HWC bytes
(``flag=0``) or an encoded image (``flag=1``, decoder pluggable — JPEG
decode is host-side and orthogonal to the substrate).

``batch_iterator`` is the host half of BASELINE config 2's pipeline:
RecordIO shard → records → fixed-shape ``(images[B,H,W,C] u8,
labels[B] i32)`` numpy batches, ready for :class:`DeviceFeed`.
"""

from __future__ import annotations

import struct
from typing import Callable, Iterator, Optional, Tuple

import numpy as np

from dmlc_core_tpu.base.logging import CHECK, CHECK_EQ
from dmlc_core_tpu.io.input_split import InputSplit

__all__ = ["pack_image_record", "unpack_image_record", "batch_iterator"]

# flag:u32  label:f32  id:u64  h:u16 w:u16 c:u16  (little-endian)
_HEADER = struct.Struct("<IfQHHH")


def pack_image_record(
    image: np.ndarray,
    label: float,
    record_id: int = 0,
    flag: int = 0,
) -> bytes:
    """Serialize one image record (raw uint8 HWC when ``flag=0``)."""
    img = np.ascontiguousarray(image, dtype=np.uint8)
    CHECK_EQ(img.ndim, 3, "image must be HWC")
    h, w, c = img.shape
    return _HEADER.pack(flag, float(label), record_id, h, w, c) + img.tobytes()


def unpack_image_record(
    rec: bytes,
    decoder: Optional[Callable[[bytes, Tuple[int, int, int]], np.ndarray]] = None,
) -> Tuple[np.ndarray, float, int]:
    """Parse one record → (image u8 HWC, label, id)."""
    CHECK(len(rec) >= _HEADER.size, "image record too short")
    flag, label, rid, h, w, c = _HEADER.unpack_from(rec)
    payload = rec[_HEADER.size:]
    if flag == 0:
        img = np.frombuffer(payload, dtype=np.uint8)
        CHECK_EQ(img.size, h * w * c, "image record payload size mismatch")
        img = img.reshape(h, w, c)
    else:
        CHECK(decoder is not None, "encoded image record needs a decoder")
        img = decoder(payload, (h, w, c))
    return img, label, rid


def batch_iterator(
    uri: str,
    part: int,
    nparts: int,
    batch_size: int,
    image_shape: Tuple[int, int, int],
    decoder: Optional[Callable[[bytes, Tuple[int, int, int]], np.ndarray]] = None,
    drop_last: bool = True,
    shuffle_buffer: int = 0,
    seed: int = 0,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """Stream ``(images[B,H,W,C] u8, labels[B] i32)`` batches from a
    RecordIO shard — this worker reads only its byte range
    (``part``/``nparts``), the reference's input-sharding contract.
    """
    h, w, c = image_shape
    split = InputSplit.create(uri, part, nparts, "recordio",
                              shuffle_buffer=shuffle_buffer, seed=seed)
    images = np.empty((batch_size, h, w, c), np.uint8)
    labels = np.empty(batch_size, np.int32)
    fill = 0
    try:
        for rec in split:
            img, label, _rid = unpack_image_record(rec, decoder)
            CHECK_EQ(img.shape, (h, w, c), "image shape mismatch in shard")
            images[fill] = img
            labels[fill] = int(label)
            fill += 1
            if fill == batch_size:
                yield images.copy(), labels.copy()
                fill = 0
        if fill and not drop_last:
            yield images[:fill].copy(), labels[:fill].copy()
    finally:
        split.close()
