"""Data layer (L6): CSR row blocks, text parsers, row-block iterators and
the TPU device staging path.

Reference parity: ``include/dmlc/data.h`` (Row/RowBlock/Parser/RowBlockIter),
``src/data/*`` (row_block, text parsers, basic/disk row iters)
(SURVEY.md §2a-b), re-founded on numpy CSR buffers that stage directly into
``jax.Array`` device memory (``dmlc_core_tpu.data.device``).
"""

from dmlc_core_tpu.data.row_block import Row, RowBlock, RowBlockContainer  # noqa: F401
from dmlc_core_tpu.data.parsers import Parser  # noqa: F401
from dmlc_core_tpu.data.iter import RowBlockIter  # noqa: F401
from dmlc_core_tpu.data.device_feed import DeviceFeed, FeedStats  # noqa: F401
from dmlc_core_tpu.data.image_record import (  # noqa: F401
    batch_iterator, pack_image_record, unpack_image_record)
