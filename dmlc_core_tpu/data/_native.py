"""ctypes binding to the native parse hot loop (build/libdmlctpu.so).

The .so is optional: every caller falls back to the pure-numpy path when it
is absent (``native_available() == False``), so the package works untouched
in environments without a toolchain.  ``make -C cpp`` builds it.

Buffers returned by the native parser are wrapped zero-copy as numpy arrays
whose lifetime is tied to a finalizer that calls ``dmlc_rows_free``.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from dmlc_core_tpu.base.logging import Error

__all__ = ["native_available", "parse_libsvm", "parse_csv", "parse_libfm"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SO_PATHS = [
    os.environ.get("DMLC_TPU_NATIVE_LIB", ""),
    os.path.join(_REPO_ROOT, "build", "libdmlctpu.so"),
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "libdmlctpu.so"),
]


class _DmlcRows(ctypes.Structure):
    _fields_ = [
        ("n_rows", ctypes.c_int64),
        ("nnz", ctypes.c_int64),
        ("offset", ctypes.POINTER(ctypes.c_int64)),
        ("label", ctypes.POINTER(ctypes.c_float)),
        ("weight", ctypes.POINTER(ctypes.c_float)),
        ("qid", ctypes.POINTER(ctypes.c_int64)),
        ("field", ctypes.POINTER(ctypes.c_int32)),
        ("index", ctypes.POINTER(ctypes.c_int64)),
        ("value", ctypes.POINTER(ctypes.c_float)),
        ("has_weight", ctypes.c_int32),
        ("has_qid", ctypes.c_int32),
        ("has_field", ctypes.c_int32),
        ("has_value", ctypes.c_int32),
        ("error", ctypes.c_char * 256),
    ]


_lib: Optional[ctypes.CDLL] = None


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    for path in _SO_PATHS:
        if path and os.path.exists(path):
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                continue
            for fn, argtypes in (
                ("dmlc_parse_libsvm",
                 [ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.POINTER(_DmlcRows)]),
                ("dmlc_parse_csv",
                 [ctypes.c_char_p, ctypes.c_int64, ctypes.c_char, ctypes.c_int64,
                  ctypes.c_int64, ctypes.c_int, ctypes.POINTER(_DmlcRows)]),
                ("dmlc_parse_libfm",
                 [ctypes.c_char_p, ctypes.c_int64, ctypes.c_int, ctypes.POINTER(_DmlcRows)]),
            ):
                getattr(lib, fn).argtypes = argtypes
                getattr(lib, fn).restype = ctypes.c_int
            lib.dmlc_rows_free.argtypes = [ctypes.POINTER(_DmlcRows)]
            lib.dmlc_rows_free.restype = None
            _lib = lib
            return lib
    return None


def native_available() -> bool:
    return _load() is not None


def _as_np(ptr, n, dtype):
    if not ptr or n == 0:
        return np.empty(0, dtype=dtype)
    return np.ctypeslib.as_array(ptr, shape=(n,))


def _collect(rows: _DmlcRows, lib: ctypes.CDLL) -> dict:
    """Copy native buffers into numpy arrays and free the arena.

    A copy (rather than a finalizer-tied view) keeps ownership simple; the
    copy cost is dwarfed by parse time and the buffers are short-lived.
    """
    n, nnz = rows.n_rows, rows.nnz
    out = {
        "offset": _as_np(rows.offset, n + 1, np.int64).copy(),
        "label": _as_np(rows.label, n, np.float32).copy(),
        "index": _as_np(rows.index, nnz, np.int64).copy(),
        "value": _as_np(rows.value, nnz, np.float32).copy() if rows.has_value else None,
        "weight": _as_np(rows.weight, n, np.float32).copy() if rows.has_weight else None,
        "qid": _as_np(rows.qid, n, np.int64).copy() if rows.has_qid else None,
        "field": _as_np(rows.field, nnz, np.int32).copy() if rows.has_field else None,
    }
    lib.dmlc_rows_free(ctypes.byref(rows))
    return out


def _run(fn_name: str, data: bytes, *args, nthread: int = 0) -> dict:
    lib = _load()
    if lib is None:
        raise RuntimeError("native library not available")
    rows = _DmlcRows()
    rc = getattr(lib, fn_name)(data, len(data), *args, nthread, ctypes.byref(rows))
    if rc != 0:
        msg = rows.error.decode("utf-8", "replace")
        lib.dmlc_rows_free(ctypes.byref(rows))
        raise Error(f"native parse failed: {msg}")
    return _collect(rows, lib)


def parse_libsvm(data: bytes, nthread: int = 0) -> dict:
    return _run("dmlc_parse_libsvm", data, nthread=nthread)


def parse_csv(data: bytes, delimiter: str = ",", label_col: int = 0,
              weight_col: int = -1, nthread: int = 0) -> dict:
    return _run(
        "dmlc_parse_csv", data, delimiter.encode()[:1], label_col, weight_col,
        nthread=nthread,
    )


def parse_libfm(data: bytes, nthread: int = 0) -> dict:
    return _run("dmlc_parse_libfm", data, nthread=nthread)
