"""Named device meshes — the substrate every distributed op runs on.

The reference has no mesh concept (its only strategy is data parallelism
over rabit sockets, SURVEY.md §2e); here the mesh is first-class so the
same substrate scales past DP without rework: axes are reserved for
data / model (tensor) / pipe (pipeline) / seq (sequence/context, ring
attention) / expert parallelism.  XLA lowers collectives onto ICI within a
slice and DCN across hosts based purely on these shardings — that is the
entire "communication backend" (SURVEY.md §5).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dmlc_core_tpu.base.logging import CHECK, CHECK_EQ
from dmlc_core_tpu.base.parameter import Parameter, field

__all__ = [
    "AXES",
    "MeshSpec",
    "create_mesh",
    "data_sharding",
    "replicated_sharding",
    "local_mesh",
    "device_count",
    "shard_row_ranges",
    "row_shard_layout",
]

# canonical axis order; unused axes get size 1 and cost nothing
AXES: Tuple[str, ...] = ("data", "model", "pipe", "seq", "expert")


class MeshSpec(Parameter):
    """Mesh shape as a Parameter (env/config/CLI-settable).

    ``-1`` on exactly one axis means "all remaining devices" (like a numpy
    reshape wildcard); the default puts every device on ``data``.
    """

    data = field(int, default=-1, description="data-parallel axis size")
    model = field(int, default=1, description="tensor-parallel axis size")
    pipe = field(int, default=1, description="pipeline-parallel axis size")
    seq = field(int, default=1, description="sequence/context-parallel axis size")
    expert = field(int, default=1, description="expert-parallel axis size")

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {ax: getattr(self, ax) for ax in AXES}
        wild = [ax for ax, s in sizes.items() if s == -1]
        CHECK(len(wild) <= 1, "at most one mesh axis may be -1")
        fixed = int(np.prod([s for s in sizes.values() if s != -1]))
        if wild:
            CHECK_EQ(n_devices % fixed, 0,
                     f"{n_devices} devices not divisible by fixed axes {sizes}")
            sizes[wild[0]] = n_devices // fixed
        else:
            CHECK_EQ(fixed, n_devices, f"mesh {sizes} != {n_devices} devices")
        return sizes


def create_mesh(
    spec: Optional[MeshSpec] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_names: Sequence[str] = AXES,
) -> Mesh:
    """Build a named Mesh over ``devices`` (default: all global devices).

    On a multi-host pod this uses the global device set — XLA routes
    intra-slice traffic over ICI and cross-host traffic over DCN from the
    device coordinates; nothing else to configure.
    """
    devs = list(devices) if devices is not None else jax.devices()
    spec = spec or MeshSpec()
    sizes = spec.resolve(len(devs))
    shape = tuple(sizes[ax] for ax in axis_names)
    arr = np.asarray(devs).reshape(shape)
    return Mesh(arr, axis_names=tuple(axis_names))


def local_mesh(n: Optional[int] = None, axis: str = "data") -> Mesh:
    """A 1-axis mesh over the first ``n`` devices (test/bench convenience)."""
    devs = jax.devices()[: n or len(jax.devices())]
    return Mesh(np.asarray(devs), axis_names=(axis,))


def device_count(mesh: Mesh) -> int:
    """Total devices in the mesh (the row-padding granularity: rows are
    sharded over ``data`` and replicated over every other axis, so the
    padded row count must divide by the full device product)."""
    return int(np.prod([mesh.shape[a] for a in mesh.axis_names]))


def shard_row_ranges(n_rows: int, nparts: int) -> List[Tuple[int, int]]:
    """Exact row partition over ``nparts`` — the reference's
    ``InputSplit(part, nparts)`` byte-range contract lifted to row
    indices: part ``k`` owns rows ``[n·k/nparts, n·(k+1)/nparts)``.

    Tiling invariant (the ``unittest_inputsplit`` oracle, property-pinned
    in tests/test_multichip.py): for ANY ``(n_rows, nparts)`` — including
    ``n_rows < nparts`` and odd remainders — the ranges are disjoint,
    ordered, and their union is exactly ``[0, n_rows)``; no row is
    dropped or duplicated, and the remainder spreads over parts instead
    of piling onto the last one.
    """
    CHECK(nparts >= 1, f"shard_row_ranges: nparts must be >= 1, got {nparts}")
    CHECK(n_rows >= 0, f"shard_row_ranges: n_rows must be >= 0, got {n_rows}")
    return [(n_rows * k // nparts, n_rows * (k + 1) // nparts)
            for k in range(nparts)]


def row_shard_layout(n_rows: int, mesh: Mesh,
                     pad_multiple: int = 0) -> Tuple[int, int]:
    """``(n_padded, shard_rows)`` of the device layout rows land in when
    sharded on the mesh: rows pad to a device-count multiple (or to
    ``pad_multiple`` when larger — the deterministic-histogram block
    granularity needs a coarser pad) and device ``k`` owns the equal
    block ``[k·shard_rows, (k+1)·shard_rows)``.  Unlike
    :func:`shard_row_ranges` (exact, possibly unequal — a *read*
    assignment), this is the *placement* math: jax shards are equal by
    construction, the tail padding weighs 0.
    """
    ndev = device_count(mesh)
    m = max(pad_multiple, ndev)
    CHECK_EQ(m % ndev, 0,
             f"pad_multiple {pad_multiple} must be a device-count "
             f"({ndev}) multiple")
    n_padded = n_rows + ((-n_rows) % m)
    if n_padded == 0:
        n_padded = m
    return n_padded, n_padded // ndev


def data_sharding(mesh: Mesh, ndim: int = 1, axis: str = "data") -> NamedSharding:
    """Shard dim 0 on the data axis, replicate the rest — the input-batch
    sharding for DP (the reference's ``InputSplit(part, nparts)`` byte
    sharding, lifted to device buffers)."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated (every device holds the full array)."""
    return NamedSharding(mesh, P())
