"""Distributed layer (L7): named device meshes, rabit-shaped collectives
over XLA (ICI/DCN), KVStore shim, sharded checkpointing.

Reference parity: the tracker-coordinated rabit protocol (tree allreduce +
ring allgather over raw TCP, topology from ``tracker.py``) and the ps-lite
bootstrap (SURVEY.md §2c, §5).  Re-founded: collectives are XLA ops
(``psum``/``all_gather``/``ppermute``) on a GSPMD mesh — the "engine" is the
TPU interconnect itself, coordination collapses onto
``jax.distributed.initialize``, and the tracker survives as the launch/ABI
layer (``dmlc_core_tpu.tracker``).
"""

from dmlc_core_tpu.parallel.mesh import (  # noqa: F401
    MeshSpec,
    create_mesh,
    data_sharding,
    replicated_sharding,
)
from dmlc_core_tpu.parallel.collectives import (  # noqa: F401
    init,
    finalize,
    rank,
    world_size,
    is_distributed,
    allreduce,
    broadcast,
    allgather,
    barrier,
)
from dmlc_core_tpu.parallel.kvstore import KVStore  # noqa: F401
from dmlc_core_tpu.parallel.recovery import (  # noqa: F401
    ElasticSession, ElasticTracker, ElasticTrainer, RoundCheckpointer)
from dmlc_core_tpu.parallel.ring_attention import (  # noqa: F401
    reference_attention, ring_attention)
from dmlc_core_tpu.parallel.ulysses import ulysses_attention  # noqa: F401
from dmlc_core_tpu.parallel.zero import ZeroAdam, ZeroState  # noqa: F401
