"""Elastic fault-tolerant distributed training — rabit's recovery story.

Reference parity: the coordination promise at the center of dmlc-core
(PAPER.md §1): a worker that dies mid-allreduce can rejoin and recover
from the last agreed-upon state with bounded loss.  Rabit implements it
with version-numbered ``CheckPoint()/LoadCheckPoint()`` plus a tracker
that re-admits reborn workers; this module composes the substrate this
repo already has — the tracker's reconnect grace + liveness
(``tracker.tracker``), atomic CRC'd versioned checkpoints
(``parallel.checkpoint``), deterministic fault injection
(``base.faultinject``) and the deterministic histogram fold
(``DMLC_HIST_BLOCKS``) — into that loop:

* **Round-versioned collective commits.**  Every ``DMLC_RECOVERY_STRIDE``
  boosting rounds each worker atomically commits ``(round, ensemble,
  cursor)`` through :class:`RoundCheckpointer` and then passes a commit
  barrier at the tracker; the tracker tracks the **recovery floor** —
  the last round committed by every member — behind
  ``dmlc_recovery_floor_round``.  A round either commits on all workers
  or on none.
* **Abort on membership change.**  Cross-worker collectives run through
  the tracker hub (:class:`ElasticTracker` server side,
  :class:`ElasticSession` client side — rabit's actual wire role, used
  where multiprocess XLA collectives don't exist, e.g. the CPU backend).
  A worker death — detected instantly via the socket close, or by the
  deadline-driven grace sweep during silent stretches — breaks the
  current *epoch*: every in-flight collective returns ``abort``,
  surviving workers raise :class:`CollectiveAborted`, roll their
  ensembles back to the floor, and re-``join``.
* **Rejoin or elastically re-shard.**  A worker that restarts inside the
  grace window ``recover``s its rank, loads the floor checkpoint and
  replays forward — byte-stable, since the deterministic fold makes
  replayed rounds bit-identical.  With ``DMLC_ELASTIC=1``, once every
  lost rank's grace lapses the tracker re-forms the epoch over the
  survivors instead: ranks compact, ``shard_row_ranges`` re-cuts the
  rows over the smaller world at the round boundary, and training
  continues with N−k workers (``dmlc_elastic_reshards_total``).

On a real multi-host pod the in-step histogram sync stays the in-jit
psum over the global mesh (PR 7); this layer adds only the round-boundary
protocol.  On hosts without multiprocess XLA (CI's CPU backend) the
tracker hub carries the host collectives too, so the identical protocol
— and the chaos drill ``scripts/check_elastic.py`` — runs anywhere.
"""

from __future__ import annotations

import base64
import json
import os
import signal
import socket
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from dmlc_core_tpu.base import faultinject as _fi
from dmlc_core_tpu.base import knobs as _knobs
from dmlc_core_tpu.base import metrics as _metrics
from dmlc_core_tpu.base.logging import CHECK, LOG, log_fatal
from dmlc_core_tpu.io.stream import Stream
from dmlc_core_tpu.parallel import collectives as coll
from dmlc_core_tpu.parallel.checkpoint import checkpoint, load_checkpoint
from dmlc_core_tpu.parallel.mesh import shard_row_ranges
from dmlc_core_tpu.tracker.tracker import RabitTracker

__all__ = [
    "CollectiveAborted", "WorkerAborted", "EvictedError",
    "RecoveryConfig", "RoundCheckpointer", "ElasticTracker",
    "ElasticSession", "ElasticTrainer", "ElasticLauncher", "fold_parts",
    "truncate_to_round",
]


class CollectiveAborted(RuntimeError):
    """An in-flight collective was aborted (membership changed or a peer
    requested an abort): the current round is void on every worker —
    roll back to the recovery floor and re-join."""


class WorkerAborted(RuntimeError):
    """The ``worker`` fault-injection point fired with a non-kill kind —
    this worker abandons training (the in-process stand-in for SIGKILL
    in tests)."""


class EvictedError(RuntimeError):
    """The tracker re-formed the epoch without this rank (elastic shrink
    won the race); this worker has no seat in the surviving world."""


_RM = None


def _recovery_metrics():
    global _RM
    if _RM is None:
        r = _metrics.default_registry()
        _RM = {
            "replayed": r.counter(
                "recovery_rounds_replayed_total",
                "boosting rounds re-run after a rollback to the "
                "recovery floor"),
            "reshards": r.counter(
                "elastic_reshards_total",
                "elastic re-formations of the worker group onto a "
                "smaller survivor set"),
        }
    return _RM


class RecoveryConfig:
    """Resolved recovery knobs (each overridable per instance):

    * ``stride`` — rounds between collective commits
      (``DMLC_RECOVERY_STRIDE``); smaller = tighter recovery floor,
      more commit barriers.
    * ``elastic`` — after a lost worker's grace lapses, re-shard over
      the survivors instead of waiting for a replacement
      (``DMLC_ELASTIC``).
    * ``directory`` — where round-versioned commit files live
      (``DMLC_RECOVERY_DIR``).
    """

    def __init__(self, stride: Optional[int] = None,
                 elastic: Optional[bool] = None,
                 directory: Optional[str] = None):
        if stride is None:
            stride = int(_knobs.value("DMLC_RECOVERY_STRIDE"))
        CHECK(stride >= 1, f"recovery stride must be >= 1, got {stride}")
        self.stride = stride
        if elastic is None:
            elastic = str(_knobs.value("DMLC_ELASTIC")).lower() in (
                "1", "true", "on", "yes")
        self.elastic = bool(elastic)
        if directory is None:
            directory = str(_knobs.value("DMLC_RECOVERY_DIR"))
        self.directory = directory


def fold_parts(parts: List[np.ndarray]) -> np.ndarray:
    """Deterministic pairwise tree fold of per-worker partials, in rank
    order — the same fixed reduction tree ``DMLC_HIST_BLOCKS`` uses
    inside the round program (``histgbt._tree_fold``), so a worker
    group's sum is reproducible run after run regardless of message
    arrival order, and a shard's blocks stay an aligned subtree of the
    global fold.  Odd counts carry the unpaired tail up a level."""
    parts = [np.asarray(p) for p in parts]
    CHECK(len(parts) >= 1, "fold_parts: empty")
    while len(parts) > 1:
        nxt = [parts[i] + parts[i + 1] for i in range(0, len(parts) - 1, 2)]
        if len(parts) % 2:
            nxt.append(parts[-1])
        parts = nxt
    return parts[0]


def truncate_to_round(model: Any, round_no: int) -> Any:
    """Roll an ensemble back to ``round_no`` boosting rounds (every
    engine keeps one ``trees`` entry per round, multiclass included).
    Clears the carried training margins — they describe the discarded
    tail — so the next fit replays margins from the surviving trees."""
    if len(model.trees) > round_no:
        model.trees = model.trees[:round_no]
    model._train_preds = None
    model.best_iteration = None
    model.best_score = None
    return model


# ---------------------------------------------------------------------------
# round-versioned commits (rabit CheckPoint / LoadCheckPoint)
# ---------------------------------------------------------------------------

class RoundCheckpointer:
    """Atomic, CRC-checked, round-versioned commits of a GBT ensemble.

    Layers on :func:`parallel.checkpoint.checkpoint` (temp-file +
    ``os.replace`` commit, per-leaf CRC sidecar, previous-version
    fallback) with the model's ``save_model`` bytes as the one state
    leaf — the same magic-sniffed contract the serve ModelRegistry uses,
    so any engine with save/load round-trips works.  ``version`` is the
    boosting round: rabit's ``version_number``.

    Each worker writes its own ``gbt-rank<k>.ckpt`` (``local=True``
    commits: no collective in the commit path — a dying peer must not
    wedge it).  Because the floor only advances when EVERY member
    committed, a restore may find its own file *ahead* of the floor
    (died between local write and the barrier) — the caller truncates —
    and a diskless replacement worker finds no file at all, so
    :meth:`restore` falls back to scanning sibling rank files for one at
    or past the floor (ensembles are bit-identical across workers under
    the deterministic fold, so any member's file serves).
    """

    def __init__(self, directory: str, rank: int = 0):
        CHECK(bool(directory), "RoundCheckpointer needs a directory "
              "(DMLC_RECOVERY_DIR or explicit)")
        self.directory = directory
        self.rank = rank
        os.makedirs(directory, exist_ok=True)

    def uri(self, rank: Optional[int] = None) -> str:
        r = self.rank if rank is None else rank
        return os.path.join(self.directory, f"gbt-rank{r}.ckpt")

    @staticmethod
    def _like() -> Dict[str, Any]:
        return {"cursor": "", "model": np.zeros(0, np.uint8)}

    def commit(self, model: Any, round_no: int,
               cursor: Optional[Dict[str, Any]] = None) -> None:
        """Durably commit ``model`` as the state of ``round_no``."""
        stage = f"mem://recovery/{os.getpid()}/{self.rank}/stage"
        model.save_model(stage)
        with Stream.create(stage, "r") as s:
            blob = s.read_all()
        state = {"cursor": json.dumps(cursor or {}),
                 "model": np.frombuffer(blob, np.uint8)}
        checkpoint(self.uri(), state, version=round_no, local=True)

    def _load(self, uri: str) -> Tuple[int, Optional[bytes], Dict[str, Any]]:
        version, state = load_checkpoint(uri, self._like())
        if version == 0 and state["model"].size == 0:
            return 0, None, {}
        cursor = json.loads(state["cursor"]) if state["cursor"] else {}
        return version, state["model"].tobytes(), cursor

    def restore(self, floor: Optional[int] = None
                ) -> Tuple[int, Optional[bytes], Dict[str, Any]]:
        """Newest committed ``(round, save_model bytes, cursor)`` —
        ``(0, None, {})`` for a cold start.  When ``floor`` is given and
        this rank's own file is behind it (fresh replacement worker),
        sibling rank files are scanned for one at or past the floor."""
        version, blob, cursor = self._load(self.uri())
        if floor is None or version >= floor or floor <= 0:
            return version, blob, cursor
        try:
            names = sorted(os.listdir(self.directory))
        except OSError:
            names = []
        for name in names:
            if not (name.startswith("gbt-rank") and name.endswith(".ckpt")):
                continue
            cand = os.path.join(self.directory, name)
            if cand == self.uri():
                continue
            v, b, c = self._load(cand)
            if v >= floor and b is not None:
                LOG("WARNING", "recovery: rank %d file is at v%d < floor "
                    "%d; adopting sibling %s (v%d)", self.rank, version,
                    floor, name, v)
                return v, b, c
        return version, blob, cursor

    def restore_model(self, model_cls: Any, mesh: Any = None,
                      floor: Optional[int] = None
                      ) -> Tuple[int, Optional[Any], Dict[str, Any]]:
        """:meth:`restore`, deserialized through ``model_cls.load_model``."""
        version, blob, cursor = self.restore(floor)
        if blob is None:
            return 0, None, cursor
        stage = f"mem://recovery/{os.getpid()}/{self.rank}/restore"
        with Stream.create(stage, "w") as s:
            s.write(blob)
        model = model_cls.load_model(stage, mesh=mesh)
        return version, model, cursor


# ---------------------------------------------------------------------------
# tracker-side consensus: epochs, commit barrier, collective hub
# ---------------------------------------------------------------------------

def _enc_payload(value: Any) -> Dict[str, Any]:
    if isinstance(value, np.ndarray) or isinstance(value, (np.generic,)):
        a = np.ascontiguousarray(value)
        return {"kind": "nd", "dtype": str(a.dtype),
                "shape": list(a.shape),
                "data": base64.b64encode(a.tobytes()).decode("ascii")}
    return {"kind": "py", "value": value}


def _dec_payload(d: Optional[Dict[str, Any]]) -> Any:
    if d is None:
        return None
    if d.get("kind") == "py":
        return d.get("value")
    a = np.frombuffer(base64.b64decode(d["data"]),
                      dtype=np.dtype(d["dtype"]))
    return a.reshape(d["shape"]).copy()


class ElasticTracker(RabitTracker):
    """RabitTracker + the elastic recovery consensus.

    Adds three commands on the persistent worker protocol:

    * ``join`` — blocks until an *epoch* (a stable worker group) forms:
      all ``nworker`` ranks alive and joined, or — ``elastic`` mode,
      once every lost rank's grace has lapsed — the survivors alone.
      Replies with the epoch id, the member list, this rank's position
      (``wrank``) and the recovery floor.
    * ``coll`` — the collective hub: contributions for ``(epoch, seq)``
      from every member are reduced (deterministic pairwise fold for
      sums) and the one result fanned back.  Any membership change
      breaks the epoch first, so every waiter — and every straggler
      arriving with the stale epoch id — gets ``abort`` instead of a
      half-reduced value.  ``op="commit"`` doubles as the commit
      barrier and advances the recovery floor.
    * ``abort`` — a worker voluntarily voids the epoch (the
      ``allreduce:abort`` fault-injection kind rides this), exercising
      the all-or-nothing round without a death.
    """

    _WAIT_S = 60.0

    def __init__(self, host_ip: str = "127.0.0.1", nworker: int = 1,
                 port: int = 0, grace_s: Optional[float] = None,
                 elastic: Optional[bool] = None):
        super().__init__(host_ip=host_ip, nworker=nworker, port=port,
                         grace_s=grace_s)
        if elastic is None:
            elastic = RecoveryConfig().elastic
        self.elastic = bool(elastic)
        self._cv = threading.Condition(self._lock)
        self._epoch = 0
        self._epoch_ready = False
        self._members: List[int] = []
        self._joined: set = set()
        self._colls: Dict[Tuple[int, int], Dict[str, Any]] = {}
        self._prev_world = nworker
        self._broken_reason = ""

    # -- membership → epoch lifecycle -----------------------------------
    def _membership_event_locked(self, kind: str, rank: int) -> None:
        if kind in ("lost", "death", "shutdown"):
            self._joined.discard(rank)
        if kind in ("lost", "death"):
            self._break_epoch_locked(f"rank {rank} {kind}")
        # reconnect/death may complete a pending formation (rejoin or
        # survivor-only world); join waiters re-evaluate either way
        self._try_form_locked()
        self._cv.notify_all()

    def _break_epoch_locked(self, reason: str) -> None:
        if not self._epoch_ready:
            return
        self._epoch_ready = False
        self._members = []
        self._joined.clear()
        self._colls.clear()
        self._epoch += 1
        self._broken_reason = reason
        LOG("WARNING", "elastic: epoch %d broken (%s); in-flight round "
            "aborts on every worker", self._epoch - 1, reason)

    def _try_form_locked(self) -> None:
        if self._epoch_ready or self._done.is_set():
            return
        alive = set(self._alive)
        joined = self._joined & alive
        full = set(range(self.nworker))
        members: Optional[List[int]] = None
        if full <= joined:
            members = sorted(full)
        elif (self.elastic and joined and not self._pending_death
              and joined == alive
              and (full - joined) <= set(self.dead_workers)):
            # every missing rank is past its grace (the deadline sweep
            # declared it dead) and every survivor has re-joined:
            # re-form the world over the survivors at the round boundary
            members = sorted(joined)
        if members is None:
            return
        self._members = members
        self._epoch_ready = True
        self._broken_reason = ""
        if len(members) < self._prev_world:
            self._prev_world = len(members)
            if _metrics.enabled():
                _recovery_metrics()["reshards"].inc(1)
            LOG("WARNING", "elastic: epoch %d re-formed with %d survivors "
                "%s (was %d)", self._epoch, len(members), members,
                self.nworker)
        else:
            LOG("INFO", "elastic: epoch %d formed with %d members",
                self._epoch, len(members))
        self._cv.notify_all()

    def _expected_ranks_locked(self) -> List[int]:
        # the recovery floor is gated on the CURRENT epoch's members (an
        # evicted rank's stale commit must not hold the floor back)
        return list(self._members) if self._members else list(
            range(self.nworker))

    # -- protocol --------------------------------------------------------
    def _handle(self, msg: Dict[str, Any],
                conn: Optional[socket.socket] = None,
                state: Optional[Dict[str, Any]] = None
                ) -> Optional[Dict[str, Any]]:
        cmd = msg.get("cmd")
        if cmd == "join":
            return self._handle_join(msg)
        if cmd == "coll":
            return self._handle_coll(msg)
        if cmd == "abort":
            return self._handle_abort(msg)
        return super()._handle(msg, conn, state)

    def _handle_join(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        rank = int(msg.get("rank", -1))
        timeout_s = float(msg.get("timeout_s", self._WAIT_S))
        with self._cv:
            self._joined.add(rank)
            self._try_form_locked()
            waited = 0.0
            while True:
                if self._done.is_set():
                    return {"error": "tracker stopped"}
                if self._epoch_ready:
                    if rank in self._members:
                        break
                    self._joined.discard(rank)
                    return {"error": "evicted: epoch formed without "
                            f"rank {rank}"}
                if timeout_s > 0 and waited >= timeout_s:
                    self._joined.discard(rank)
                    return {"error": "join timeout"}
                self._cv.wait(timeout=1.0)
                waited += 1.0
            return {"epoch": self._epoch, "world": len(self._members),
                    "wrank": self._members.index(rank),
                    "members": list(self._members), "floor": self._floor}

    def _handle_abort(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with self._cv:
            if (int(msg.get("epoch", -1)) == self._epoch
                    and self._epoch_ready):
                self._break_epoch_locked(
                    f"rank {msg.get('rank')} abort: "
                    f"{msg.get('reason', 'unspecified')}")
            self._cv.notify_all()
            return {"ok": True}

    def _handle_coll(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        rank = int(msg.get("rank", -1))
        epoch = int(msg.get("epoch", -1))
        seq = int(msg.get("seq", -1))
        op = str(msg.get("op", ""))
        with self._cv:
            if (not self._epoch_ready or epoch != self._epoch
                    or rank not in self._members):
                return {"abort": self._broken_reason or "epoch changed",
                        "epoch": self._epoch}
            key = (epoch, seq)
            ent = self._colls.get(key)
            if ent is None:
                ent = self._colls[key] = {
                    "op": op, "root": int(msg.get("root", 0)),
                    "parts": {}, "done": False, "result": None,
                    "served": set(),
                }
            if ent["op"] != op:
                # the workers disagree on the collective sequence —
                # divergence must abort the round, never mix payloads
                self._break_epoch_locked(
                    f"collective {seq} op mismatch: {ent['op']!r} vs "
                    f"{op!r} from rank {rank}")
                return {"abort": "collective op mismatch",
                        "epoch": self._epoch}
            ent["parts"][rank] = _dec_payload(msg.get("payload"))
            if set(ent["parts"]) == set(self._members):
                ent["result"] = self._reduce_locked(ent)
                ent["done"] = True
                self._cv.notify_all()
            while not ent["done"]:
                if (not self._epoch_ready or epoch != self._epoch
                        or self._done.is_set()):
                    return {"abort": self._broken_reason or "epoch changed",
                            "epoch": self._epoch}
                if not self._cv.wait(timeout=self._WAIT_S):
                    self._break_epoch_locked(
                        f"collective {seq} timed out waiting for "
                        f"{sorted(set(self._members) - set(ent['parts']))}")
                    return {"abort": "collective timeout",
                            "epoch": self._epoch}
            ent["served"].add(rank)
            if ent["served"] == set(self._members):
                self._colls.pop(key, None)
            return {"payload": _enc_payload(ent["result"])}

    def _reduce_locked(self, ent: Dict[str, Any]) -> Any:
        op = ent["op"]
        order = [ent["parts"][r] for r in self._members]
        if op == "barrier":
            return None
        if op == "commit":
            rounds = [int(v) for v in order]
            if len(set(rounds)) != 1:
                self._break_epoch_locked(
                    f"commit barrier round mismatch: {rounds}")
                return None
            for r in self._members:
                self._record_commit_locked(r, rounds[0])
            return self._floor
        if op == "bcast":
            return ent["parts"][self._members[ent["root"]]]
        if op == "allgather":
            return np.stack([np.asarray(p) for p in order], axis=0)
        if op in ("sum", "prod"):
            if op == "prod":
                parts = [np.asarray(p) for p in order]
                out = parts[0]
                for p in parts[1:]:
                    out = out * p
                return out
            return fold_parts(order)
        if op == "max":
            return np.maximum.reduce([np.asarray(p) for p in order])
        if op == "min":
            return np.minimum.reduce([np.asarray(p) for p in order])
        if op == "bitor":
            return np.bitwise_or.reduce([np.asarray(p) for p in order])
        self._break_epoch_locked(f"unknown collective op {op!r}")
        return None


# ---------------------------------------------------------------------------
# multi-host elastic launch: tracker + supervised JobSet
# ---------------------------------------------------------------------------

class ElasticLauncher:
    """An :class:`ElasticTracker` plus the supervised
    :class:`~dmlc_core_tpu.launch.JobSet` that keeps its worker set full.

    Before the launch subsystem, elastic recovery could only *tolerate*
    a dead rank (grace-window rejoin, or elastic shrink once grace
    lapsed) — nothing relaunched it.  This closes the loop: the JobSet
    respawns a dead rank (with backoff, under the restart budget) on a
    surviving host, the replacement reclaims its rank via the tracker's
    ``recover`` path inside the grace window, rolls to the recovery
    floor and replays — so a host failure costs replayed rounds, not a
    shrunken world.  The JobSet's ``tracker=`` cross-check also reaps
    wedged workers (process alive, heartbeat lost).

    Workers must pin their tracker rank to ``DMLC_TASK_ID`` (i.e.
    ``ElasticSession(uri, port, rank=int(env["DMLC_TASK_ID"]))``) so a
    respawned attempt reclaims the rank it replaces.
    """

    def __init__(self, command: List[str], nworker: int,
                 transport: Any = None, host_ip: str = "127.0.0.1",
                 grace_s: Optional[float] = None,
                 elastic: Optional[bool] = None,
                 envs: Optional[Dict[str, str]] = None,
                 restart_limit: Optional[int] = None,
                 monitor_s: Optional[float] = None,
                 name: str = "elastic",
                 env_for: Optional[Callable[[int, int],
                                            Dict[str, str]]] = None):
        self.tracker = ElasticTracker(host_ip=host_ip, nworker=nworker,
                                      grace_s=grace_s, elastic=elastic)
        self._command = list(command)
        self._nworker = nworker
        self._transport = transport
        self._envs = dict(envs or {})
        self._restart_limit = restart_limit
        self._monitor_s = monitor_s
        self._name = name
        self._env_for = env_for
        self.jobset: Any = None

    def launch(self) -> "ElasticLauncher":
        """Start the tracker, then the supervised worker set wired to it
        (env ABI = ``slave_envs()``, liveness cross-check = tracker)."""
        from dmlc_core_tpu.launch import JobSet

        self.tracker.start()
        envs = dict(self.tracker.slave_envs())
        envs.update(self._envs)
        self.jobset = JobSet(
            self._command, self._nworker, transport=self._transport,
            envs=envs, name=self._name,
            restart_limit=self._restart_limit, monitor_s=self._monitor_s,
            tracker=self.tracker, env_for=self._env_for)
        self.jobset.launch()
        return self

    def wait(self, timeout: Optional[float] = None) -> Dict[int, int]:
        CHECK(self.jobset is not None, "ElasticLauncher: launch() first")
        return self.jobset.wait(timeout=timeout)

    def run(self, timeout: Optional[float] = None) -> List[int]:
        """launch + wait + teardown; exit codes in rank order."""
        self.launch()
        try:
            codes = self.wait(timeout=timeout)
        finally:
            self.shutdown()
        return [codes[r] for r in sorted(codes)]

    def shutdown(self) -> None:
        if self.jobset is not None:
            self.jobset.shutdown()
        self.tracker.stop()


# ---------------------------------------------------------------------------
# worker-side session: protocol client + host-collective transport
# ---------------------------------------------------------------------------

class ElasticSession:
    """Persistent worker session speaking the elastic protocol.

    Doubles as the host-collective transport
    (:func:`parallel.collectives.set_host_transport`): ``rank`` /
    ``world`` are epoch-relative, and ``allreduce`` / ``allgather`` /
    ``broadcast`` / ``barrier`` run through the tracker hub.  The
    ``allreduce`` fault-injection point sits on every collective
    (``allreduce:abort`` voids the epoch on ALL workers — the
    all-or-nothing round drill; ``allreduce:kill`` SIGKILLs mid-round).
    """

    def __init__(self, uri: str, port: int, rank: int = -1, host: str = "",
                 connect_timeout_s: float = 30.0):
        from dmlc_core_tpu.base.resilience import RetryPolicy

        # a rejoining worker races the tracker noticing the old socket's
        # death: retry the TCP connect with backoff instead of failing
        # the whole recovery on one ECONNREFUSED
        self._sock = RetryPolicy.from_env().run(
            lambda: socket.create_connection((uri, port),
                                             timeout=connect_timeout_s),
            op="tracker_connect",
            retryable=lambda e: isinstance(e, OSError))
        self._sock.settimeout(None)
        cmd = "recover" if rank >= 0 else "start"
        self.info = self._request({"cmd": cmd, "rank": rank, "host": host,
                                   "persistent": True})
        if "error" in self.info:
            self._sock.close()
            log_fatal("tracker rejected worker: %s" % self.info["error"])
        #: tracker-global rank (stable across epochs for a rejoiner)
        self.grank = int(self.info["rank"])
        self.nworker = int(self.info["num_worker"])
        self.epoch = -1
        self.world = 0
        self.wrank = -1
        self.members: List[int] = []
        self.floor = 0
        self._seq = 0

    # transport duck-type: epoch-relative identity
    @property
    def rank(self) -> int:
        return self.wrank if self.wrank >= 0 else self.grank

    def _request(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        self._sock.sendall(json.dumps(msg).encode() + b"\n")
        buf = b""
        while b"\n" not in buf:
            data = self._sock.recv(1 << 20)
            if not data:
                raise CollectiveAborted("tracker connection closed")
            buf += data
        return json.loads(buf.split(b"\n", 1)[0])

    def join(self, timeout_s: float = 120.0) -> Dict[str, Any]:
        """Block until a stable epoch admits this worker; resets the
        collective sequence.  Raises :class:`EvictedError` when the
        world re-formed without this rank."""
        reply = self._request({"cmd": "join", "rank": self.grank,
                               "timeout_s": timeout_s})
        if "error" in reply:
            if reply["error"].startswith("evicted"):
                raise EvictedError(reply["error"])
            raise CollectiveAborted(f"join failed: {reply['error']}")
        self.epoch = int(reply["epoch"])
        self.world = int(reply["world"])
        self.wrank = int(reply["wrank"])
        self.members = list(reply["members"])
        self.floor = int(reply["floor"])
        self._seq = 0
        return reply

    def _coll(self, op: str, payload: Any = None, root: int = 0) -> Any:
        fault = _fi.check("allreduce", ctx=op)
        if fault is not None:
            if fault.kind == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            if fault.kind in ("abort", "error"):
                # void the round on EVERY worker (all-or-nothing), then
                # surface the abort locally
                try:
                    self._request({"cmd": "abort", "epoch": self.epoch,
                                   "rank": self.grank,
                                   "reason": "fault injected"})
                except CollectiveAborted:
                    pass
                raise CollectiveAborted("fault injected: allreduce abort")
        self._seq += 1
        msg: Dict[str, Any] = {"cmd": "coll", "op": op, "rank": self.grank,
                               "epoch": self.epoch, "seq": self._seq,
                               "root": int(root)}
        if payload is not None or op in ("bcast",):
            msg["payload"] = _enc_payload(payload)
        reply = self._request(msg)
        if "abort" in reply:
            raise CollectiveAborted(str(reply["abort"]))
        return _dec_payload(reply.get("payload"))

    # -- transport surface ----------------------------------------------
    def allreduce(self, x: np.ndarray, op: str = "sum") -> np.ndarray:
        x = np.asarray(x)
        out = self._coll(op, x)
        return np.asarray(out, dtype=x.dtype).reshape(x.shape)

    def allgather(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self._coll("allgather", np.asarray(x)))

    def broadcast(self, value: Any, root: int = 0) -> Any:
        return self._coll("bcast", value if self.wrank == root else None,
                          root=root)

    def barrier(self, name: str = "dmlc") -> None:
        del name
        self._coll("barrier")

    def commit(self, round_no: int) -> int:
        """Commit barrier: blocks until every member committed
        ``round_no``; returns the advanced recovery floor."""
        floor = self._coll("commit", int(round_no))
        self.floor = int(floor)
        return self.floor

    def shutdown(self) -> None:
        try:
            self._request({"cmd": "shutdown"})
        except (CollectiveAborted, OSError):
            pass
        self.close()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ElasticSession":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


# ---------------------------------------------------------------------------
# the recovery loop
# ---------------------------------------------------------------------------

class ElasticTrainer:
    """Round-versioned recovery loop around HistGBT boosting.

    Single-worker (:meth:`run_device`) it is crash-safe training: boost
    in ``stride``-round legs over a ``make_device_data`` handle,
    committing after each leg; a process that dies at any round ``r``
    restarts from ``floor(r/stride)*stride`` and — under the
    deterministic fold — reproduces the uninterrupted run's bytes.

    Distributed (:meth:`run`) it adds the tracker consensus: commit
    barriers advance the global floor, any membership change aborts the
    in-flight leg on every worker (:class:`CollectiveAborted`), the
    group rolls back to the floor, re-forms (rejoin or elastic
    re-shard) and replays forward.  The ``worker`` fault-injection
    point fires once per boosting round and at every commit
    (``worker:kill:after=N`` SIGKILLs deterministically mid-boost —
    the chaos drill's trigger).
    """

    def __init__(self, model: Any, total_rounds: int,
                 recovery_dir: Optional[str] = None,
                 stride: Optional[int] = None,
                 elastic: Optional[bool] = None):
        cfg = RecoveryConfig(stride=stride, elastic=elastic,
                             directory=recovery_dir)
        CHECK(bool(cfg.directory),
              "ElasticTrainer needs a recovery dir (DMLC_RECOVERY_DIR "
              "or recovery_dir=)")
        self.model = model
        self.total = int(total_rounds)
        self.stride = cfg.stride
        self.elastic = cfg.elastic
        self.directory = cfg.directory
        #: rounds re-run after rollbacks (evidence for tests/drills)
        self.rounds_replayed = 0
        #: the committed round training resumed from (None = cold start)
        self.resumed_from: Optional[int] = None
        #: rounds completed by this process (committed + current leg)
        self.rounds_trained = 0
        self._committed = 0

    # -- shared plumbing -------------------------------------------------
    def _worker_fault(self) -> None:
        fault = _fi.check("worker")
        if fault is None:
            return
        if fault.kind == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        raise WorkerAborted(f"fault injected: worker {fault.kind}")

    def _chunk_cb(self, rounds_fetched: int, _elapsed: float) -> None:
        # per-dispatch-chunk hook from the boost loop: with
        # DMLC_TPU_ROUNDS_PER_DISPATCH=1 this is a per-round heartbeat —
        # the site where worker:kill lands "mid-round"
        self.rounds_trained = self._committed + int(rounds_fetched)
        self._worker_fault()

    def _adopt(self, loaded: Any) -> None:
        m = self.model
        if m.cuts is not None and loaded.cuts is not None:
            CHECK(np.array_equal(np.asarray(m.cuts),
                                 np.asarray(loaded.cuts)),
                  "recovery: restored cuts differ from the model's — "
                  "same data/config required for replay")
        m.cuts = loaded.cuts
        m.trees = loaded.trees
        m._missing = loaded._missing
        m._obj = loaded._obj
        m._train_preds = None
        m.best_iteration = None
        m.best_score = None

    def _restore_local(self, ck: RoundCheckpointer,
                       floor: Optional[int] = None) -> int:
        version, loaded, _cursor = ck.restore_model(
            type(self.model), mesh=self.model.mesh, floor=floor)
        if loaded is None:
            return 0
        self._adopt(loaded)
        target = version if floor is None else min(version, max(floor, 0))
        truncate_to_round(self.model, target)
        return target

    # -- single-worker crash-safe loop -----------------------------------
    def run_device(self, device_data: Dict[str, Any],
                   warmup_rounds: int = 0) -> Any:
        """Crash-safe boosting over a ``make_device_data`` handle."""
        model = self.model
        ck = RoundCheckpointer(self.directory)
        committed = self._restore_local(ck)
        if committed:
            self.resumed_from = committed
            LOG("INFO", "recovery: resuming from committed round %d",
                committed)
        self._committed = self.rounds_trained = committed
        while committed < self.total:
            k = min(self.stride, self.total - committed)
            model.param.n_trees = k
            try:
                model.fit_device(device_data, warmup_rounds=warmup_rounds,
                                 chunk_callback=self._chunk_cb,
                                 resume=committed > 0)
            finally:
                # committed state (and save_model bytes) must describe
                # the JOB's config, not the last leg's stride
                model.param.n_trees = self.total
            warmup_rounds = 0
            committed += k
            self._committed = self.rounds_trained = committed
            ck.commit(model, committed, cursor={"rounds": committed})
            self._worker_fault()
        return model

    # -- distributed loop -------------------------------------------------
    def run(self, session: ElasticSession,
            data_factory: Callable[[int, int], Any], n_rows: int,
            cuts: Any = None, eval_every: int = 0,
            join_timeout_s: float = 120.0) -> Any:
        """Elastic data-parallel boosting.

        ``data_factory(lo, hi)`` must return a rewindable
        ``RowBlockIter``-shaped source over global rows ``[lo, hi)`` —
        re-invoked whenever the world re-forms, because an elastic
        re-shard re-cuts ``shard_row_ranges`` over the survivors.
        """
        model = self.model
        ck = RoundCheckpointer(self.directory, rank=session.grank)
        while True:
            session.join(timeout_s=join_timeout_s)
            committed = self._sync_to_floor(ck, session.floor)
            self._committed = self.rounds_trained = committed
            if committed >= self.total:
                break
            lo, hi = shard_row_ranges(n_rows, session.world)[session.wrank]
            row_iter = data_factory(lo, hi)
            coll.set_host_transport(session)
            try:
                while committed < self.total:
                    k = min(self.stride, self.total - committed)
                    model.param.n_trees = k
                    stride_cuts = cuts if cuts is not None else model.cuts
                    try:
                        model.fit_external(row_iter, cuts=stride_cuts,
                                           eval_every=eval_every)
                    finally:
                        model.param.n_trees = self.total
                    committed += k
                    self.rounds_trained = committed
                    ck.commit(model, committed,
                              cursor={"rounds": committed,
                                      "world": session.world,
                                      "wrank": session.wrank,
                                      "rows": [lo, hi]})
                    self._worker_fault()
                    session.commit(committed)
                    self._committed = committed
                break
            except CollectiveAborted as e:
                LOG("WARNING", "recovery: round aborted (%s); rolling "
                    "back to floor and re-joining", e)
                continue
            finally:
                coll.set_host_transport(None)
        return model

    def _sync_to_floor(self, ck: RoundCheckpointer, floor: int) -> int:
        model = self.model
        have = len(model.trees)
        if have > floor:
            # the uncommitted tail never passed the commit barrier: a
            # round commits on all workers or on none
            self.rounds_replayed += have - floor
            if _metrics.enabled():
                _recovery_metrics()["replayed"].inc(have - floor)
            truncate_to_round(model, floor)
        elif have < floor:
            restored = self._restore_local(ck, floor=floor)
            CHECK(restored >= floor,
                  f"recovery: no checkpoint at or past floor {floor} "
                  f"(best {restored}); cannot catch up")
            self.resumed_from = floor
            LOG("INFO", "recovery: rank %d caught up to floor %d from "
                "checkpoint", ck.rank, floor)
        if floor == 0 and not model.trees:
            # virgin state: quantile cuts must be re-derived by the NEW
            # group collectively (a survivor keeping stale cuts would
            # diverge from a diskless rejoiner's sketch sequence)
            model.cuts = None
            model._train_preds = None
        return floor
