"""Sharded parameter server behind the KVStore surface.

The ps-lite role dmlc-core's ``PSTracker`` only ever exported as an env
ABI, built for real: key-range-partitioned server shards with
server-side aggregation (Li et al., OSDI'14) and bounded-staleness
async push/pull (SSP, Ho et al. NIPS'13).

Layout::

    partition.py  key-range cut, routing, rebalance plans
    wire.py       JSON-header + raw-array-frame socket framing
    server.py     PSScheduler (discovery) + PSServer (range shard)
    client.py     PSClient: pipelined async push/pull, SSP window

Process roles bind through the same ``DMLC_ROLE`` + ``DMLC_PS_ROOT_*``
env ABI the tracker launchers already export: a launched process calls
:func:`run_role` (or ``KVStore.create("dist_async")``, which defers to
it for non-worker roles) and becomes the scheduler, a server shard, or
returns a worker-side client.
"""

from __future__ import annotations

import sys
from typing import Optional

from dmlc_core_tpu.parallel.ps.client import PSClient
from dmlc_core_tpu.parallel.ps.partition import (rebalance_plan,
                                                 route_hashed,
                                                 server_of,
                                                 server_ranges,
                                                 split_by_server)
from dmlc_core_tpu.parallel.ps.server import (PSScheduler, PSServer,
                                              ps_metrics)

__all__ = ["PSClient", "PSScheduler", "PSServer", "ps_metrics",
           "server_ranges", "server_of", "split_by_server",
           "rebalance_plan", "route_hashed", "run_role"]


def run_role(role: Optional[str] = None) -> Optional[PSClient]:
    """Bind this process to its PS role from the env ABI.

    ``worker`` returns a connected :class:`PSClient`; ``scheduler``
    and ``server`` run their service loop to job completion and then
    ``sys.exit(0)`` — the launched-subprocess contract, mirroring
    dmlc-core's ps-lite launchers where non-worker roles never return
    to user code.
    """
    from dmlc_core_tpu.base import knobs as _knobs
    from dmlc_core_tpu.base.logging import Error

    if role is None:
        role = str(_knobs.value("DMLC_ROLE"))
    uri = str(_knobs.value("DMLC_PS_ROOT_URI")) or "127.0.0.1"
    port = int(_knobs.value("DMLC_PS_ROOT_PORT") or 0)
    if role == "worker":
        return PSClient(root_uri=uri, root_port=port)
    if role == "scheduler":
        sched = PSScheduler(
            host_ip=uri, port=port,
            nworker=int(_knobs.value("DMLC_NUM_WORKER")),
            nserver=int(_knobs.value("DMLC_NUM_SERVER") or 1))
        sched.start()
        sched.join()
        sys.exit(0)
    if role == "server":
        server = PSServer(
            scheduler_uri=uri, scheduler_port=port,
            host_ip=str(_knobs.value("DMLC_PS_SERVER_URI")),
            server_id=int(_knobs.value("DMLC_PS_SERVER_ID")))
        server.start()
        server.serve_forever()
        sys.exit(0)
    raise Error(f"unknown DMLC_ROLE {role!r} for parameter server")
