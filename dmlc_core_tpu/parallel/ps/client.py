"""PS worker side: pipelined async push/pull under bounded staleness.

The client half of ps-lite's ``KVWorker``: resolve the server fleet
through the scheduler (``DMLC_PS_ROOT_URI/PORT``), route sparse id
batches to their range owners (``partition.py``), and keep every server
connection *pipelined* — pushes are fired without waiting for acks (a
reader thread drains them, a semaphore bounds the in-flight window to
``DMLC_PS_PIPELINE``), so a minibatch's push cost is one socket write,
not one round trip per server.

Consistency: bounded staleness (SSP).  The client stamps every push
with its logical clock and advances the clock with :meth:`PSClient.
tick` once per minibatch; a pull carries ``DMLC_PS_STALENESS`` and the
SERVER blocks it only when the slowest worker's committed clock lags
more than that window — ``tau = 0`` degenerates to BSP, ``tau < 0`` to
totally-async.  Observed lag lands on the ``dmlc_ps_staleness_rounds``
gauge and in :attr:`PSClient.staleness_samples` (the bench's p95).

Failover: a dead server connection (respawned server, new port) is
re-resolved through the scheduler and re-dialed inside a deadline
(``DMLC_PS_RECONNECT_S``); in-flight *async* pushes on the dead socket
are lost — bounded by the pipeline depth, which is exactly the
gradient-loss window the snapshot/restore drill budgets for.
"""

from __future__ import annotations

import socket
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dmlc_core_tpu.base import metrics as _metrics
from dmlc_core_tpu.base import tracectx as _tracectx
from dmlc_core_tpu.base.logging import CHECK, LOG, Error
from dmlc_core_tpu.base.timer import get_time
from dmlc_core_tpu.parallel.ps import wire
from dmlc_core_tpu.parallel.ps.partition import (server_ranges,
                                                 split_by_server)
from dmlc_core_tpu.parallel.ps.server import ps_metrics

__all__ = ["PSClient"]


class _ServerConn:
    """One pipelined connection to a PS server.

    Replies arrive strictly in request order, so matching is a FIFO of
    slots: the sender enqueues a slot per request, a reader thread
    fills the oldest on each reply.  ``wait=False`` requests (async
    push / clock) only hold a semaphore permit until their ack drains —
    the bounded in-flight window."""

    def __init__(self, host: str, port: int, pipeline: int):
        self._sock = socket.create_connection((host, port), timeout=30)
        self._f = self._sock.makefile("rwb")
        self._lock = threading.Lock()
        self._slots: deque = deque()          # FIFO of pending slots
        self._window = threading.Semaphore(max(1, pipeline))
        self._dead: Optional[BaseException] = None
        self._reader = threading.Thread(target=self._drain, daemon=True)
        self._reader.start()

    def _drain(self) -> None:
        while True:
            try:
                reply, arrays = wire.recv_msg(self._f)
            except (ConnectionError, OSError) as e:
                with self._lock:
                    self._dead = e
                    slots = list(self._slots)
                    self._slots.clear()
                for s in slots:
                    s["error"] = e
                    self._window.release()
                    s["event"].set()
                return
            with self._lock:
                slot = self._slots.popleft() if self._slots else None
            if slot is None:
                continue
            slot["reply"], slot["out"] = reply, arrays
            if _metrics.enabled() and slot.get("hist") is not None:
                slot["hist"].observe(get_time() - slot["t0"])
            self._window.release()
            slot["event"].set()

    def request(self, header: Dict[str, Any],
                arrays: Sequence[np.ndarray] = (),
                wait: bool = True,
                hist: Optional[Any] = None) -> Optional[Dict[str, Any]]:
        """Send one framed request.  ``wait=True`` blocks for the reply
        and returns ``(reply, arrays)``; ``wait=False`` returns None
        immediately once the request is on the wire (the pipeline
        window may block first)."""
        # Once the slot is in the deque the reader thread owns the
        # window permit (it releases on reply AND on connection death),
        # so the finally below releases only when we bail out first.
        committed = False
        self._window.acquire()
        try:
            slot = {"event": threading.Event(), "reply": None, "out": None,
                    "error": None, "t0": get_time(), "hist": hist}
            with self._lock:
                if self._dead is not None:
                    raise ConnectionError(f"ps conn dead: {self._dead}")
                self._slots.append(slot)
            committed = True
        finally:
            if not committed:
                self._window.release()
        try:
            wire.send_msg(self._f, header, arrays)
        except (ConnectionError, OSError):
            self.close()
            raise
        if not wait:
            return None
        slot["event"].wait()
        if slot["error"] is not None:
            raise ConnectionError(f"ps conn dead: {slot['error']}")
        reply = slot["reply"]
        if "error" in reply:
            raise Error(f"ps server error: {reply['error']}")
        return {"reply": reply, "out": slot["out"]}

    def flush(self) -> None:
        """Block until every in-flight request has been acked."""
        while True:
            with self._lock:
                if self._dead is not None:
                    raise ConnectionError(f"ps conn dead: {self._dead}")
                if not self._slots:
                    return
            time.sleep(0.001)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        # the reader wakes on the closed socket; reap it bounded so
        # failover churn does not accumulate dead reader threads that
        # still own self._lock
        if self._reader is not threading.current_thread():
            self._reader.join(timeout=2.0)


class PSClient:
    """Worker-side handle on the sharded parameter server.

    ``init`` declares arrays, ``push``/``pull`` move sparse id batches
    (contiguous range routing — each touched shard sees one request
    per call), ``tick`` advances this worker's SSP clock,
    ``pull_dense`` reassembles a full array from every shard.  All
    knobs default from the ``DMLC_PS_*`` env group."""

    def __init__(self, root_uri: Optional[str] = None,
                 root_port: Optional[int] = None,
                 rank: Optional[int] = None,
                 staleness: Optional[int] = None,
                 pipeline: Optional[int] = None,
                 resolve_timeout_s: float = 60.0):
        from dmlc_core_tpu.base import knobs as _knobs

        if root_uri is None:
            root_uri = str(_knobs.value("DMLC_PS_ROOT_URI")) or "127.0.0.1"
        if root_port is None:
            root_port = int(_knobs.value("DMLC_PS_ROOT_PORT") or 0)
        if rank is None:
            rank = int(_knobs.value("DMLC_TASK_ID"))
        if staleness is None:
            staleness = int(_knobs.value("DMLC_PS_STALENESS"))
        if pipeline is None:
            pipeline = int(_knobs.value("DMLC_PS_PIPELINE"))
        self._sched = (root_uri, int(root_port))
        self.rank = int(rank)
        self.staleness = int(staleness)
        self._pipeline = int(pipeline)
        self._pull_timeout_s = float(_knobs.value("DMLC_PS_PULL_TIMEOUT_S"))
        self._reconnect_s = float(_knobs.value("DMLC_PS_RECONNECT_S"))
        self.clock = 0
        self._specs: Dict[str, Dict[str, Any]] = {}
        self._conns: Dict[int, _ServerConn] = {}
        #: observed (clock - min_clock) per pull — the bench's
        #: staleness_p95 source; bounded, newest kept
        self.staleness_samples: List[int] = []
        self._endpoints: Dict[int, Tuple[str, int]] = {}
        self.nserver = 0
        self.nworker = 0
        # join the fleet metrics spool (no-op without DMLC_METRICS_SPOOL)
        from dmlc_core_tpu.base import metrics_agg as _agg

        _agg.install_spool("ps_worker", self.rank)
        self._resolve(resolve_timeout_s)

    # -- membership ------------------------------------------------------
    def _sched_request(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        with socket.create_connection(self._sched, timeout=10) as s:
            f = s.makefile("rwb")
            wire.send_msg(f, msg)
            reply, _ = wire.recv_msg(f)
        return reply

    def _resolve(self, timeout_s: float) -> None:
        """Poll the scheduler until every server registered."""
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                reply = self._sched_request({"cmd": "ps_servers"})
            except (ConnectionError, OSError) as e:
                reply = {"ready": False, "_err": str(e)}
            if reply.get("ready"):
                self._endpoints = {int(k): (v[0], int(v[1]))
                                   for k, v in reply["servers"].items()}
                self.nserver = len(self._endpoints)
                self.nworker = int(reply.get("nworker", 1))
                return
            if time.monotonic() > deadline:
                raise Error(f"ps client: servers never became ready "
                            f"({reply})")
            time.sleep(0.05)

    def _conn(self, sid: int) -> _ServerConn:
        c = self._conns.get(sid)
        if c is None:
            host, port = self._endpoints[sid]
            c = _ServerConn(host, port, self._pipeline)
            self._conns[sid] = c
        return c

    def _with_failover(self, sid: int, fn):
        """Run ``fn(conn)`` against server ``sid``; on a dead
        connection, re-resolve endpoints through the scheduler (a
        respawned server re-registers under the same id with a new
        port) and retry until ``DMLC_PS_RECONNECT_S`` lapses."""
        deadline = time.monotonic() + self._reconnect_s
        while True:
            try:
                return fn(self._conn(sid))
            except (ConnectionError, OSError) as e:
                old = self._conns.pop(sid, None)
                if old is not None:
                    old.close()
                if time.monotonic() > deadline:
                    raise Error(f"ps client: server {sid} unreachable "
                                f"past {self._reconnect_s}s: {e}")
                LOG("WARNING", "ps.client rank %d: server %d connection "
                    "lost (%s); re-resolving", self.rank, sid, e)
                time.sleep(0.2)
                try:
                    self._resolve(max(1.0,
                                      deadline - time.monotonic()))
                except Error:
                    pass

    # -- data plane ------------------------------------------------------
    def init(self, name: str, n_keys: int, width: Sequence[int] = (),
             dtype: Any = np.float32, lr: float = 0.1,
             value: Optional[np.ndarray] = None,
             init_scale: float = 0.0, seed: int = 0) -> None:
        """Declare a sharded array on every server (idempotent across
        workers: the first init wins).  ``value`` ships initial
        contents (split by range); None initializes zeros — unless
        ``init_scale`` > 0, in which case each server draws its own
        slice ~ Normal(0, init_scale) seeded by ``(seed, lo)`` so no
        host ever materializes the full array (FM factor matrices at
        10M+ rows need a nonzero start: the v-gradient vanishes at
        v = 0)."""
        dtype = np.dtype(dtype)
        width = tuple(int(w) for w in width)
        if value is not None:
            value = np.asarray(value, dtype)
            CHECK(value.shape == (n_keys,) + width,
                  f"ps init {name!r}: value shape {value.shape} != "
                  f"{(n_keys,) + width}")
        ranges = server_ranges(n_keys, self.nserver)
        for sid, (lo, hi) in enumerate(ranges):
            header = {"cmd": "init", "name": name, "n_keys": n_keys,
                      "width": list(width), "dtype": str(dtype),
                      "lr": lr}
            if value is None and init_scale > 0.0:
                header["init_scale"] = float(init_scale)
                header["seed"] = int(seed)
            arrays = [value[lo:hi]] if value is not None else []
            self._with_failover(
                sid, lambda c: c.request(header, arrays))
        self._specs[name] = {"n_keys": n_keys, "width": width,
                             "dtype": str(dtype), "lr": lr}

    def _route(self, name: str,
               ids: np.ndarray) -> Dict[int, np.ndarray]:
        spec = self._specs[name]
        ids = np.asarray(ids, np.int64)
        return split_by_server(ids, spec["n_keys"], self.nserver)

    def push(self, name: str, ids: np.ndarray, grads: np.ndarray,
             wait: bool = False) -> None:
        """Push sparse gradients for the touched ids (async by default:
        the call returns once the frames are written; acks drain on the
        reader threads inside the pipeline window)."""
        parts = self._route(name, np.asarray(ids, np.int64))
        ids = np.asarray(ids, np.int64)
        grads = np.asarray(grads)
        hist = ps_metrics()["push"] if _metrics.enabled() else None
        # the span's context rides the wire framing (ps/wire.send_msg)
        # to the touched servers — the worker->server trace edge
        with _tracectx.span("ps.push", array=name):
            for sid, pos in parts.items():
                header = {"cmd": "push", "name": name, "rank": self.rank,
                          "clock": self.clock}
                payload = [np.ascontiguousarray(ids[pos]),
                           np.ascontiguousarray(grads[pos])]
                self._with_failover(
                    sid, lambda c: c.request(header, payload, wait=wait,
                                             hist=hist))

    def pull(self, name: str, ids: np.ndarray) -> np.ndarray:
        """Pull current values for a sparse id batch.  Requests to all
        touched shards go out concurrently, then the replies are
        gathered — so a pull costs one round trip, not one per server.
        Blocks server-side per the staleness window."""
        spec = self._specs[name]
        ids = np.asarray(ids, np.int64)
        parts = self._route(name, ids)
        hist = ps_metrics()["pull"] if _metrics.enabled() else None
        t0 = get_time()
        results: Dict[int, Any] = {}
        errors: Dict[int, BaseException] = {}
        trace_hdr: List[Optional[str]] = [None]

        def _one(sid: int, pos: np.ndarray) -> None:
            header = {"cmd": "pull", "name": name, "rank": self.rank,
                      "clock": self.clock, "staleness": self.staleness,
                      "timeout_s": self._pull_timeout_s}
            try:
                # re-attach the pull span's context: trace state is
                # thread-local and these are fresh threads
                with _tracectx.attach(trace_hdr[0]):
                    results[sid] = self._with_failover(
                        sid, lambda c: c.request(
                            header, [np.ascontiguousarray(ids[pos])]))
            except BaseException as e:  # noqa: BLE001 — joined below
                errors[sid] = e

        with _tracectx.span("ps.pull", array=name) as _span:
            trace_hdr[0] = _span.encode() if _span is not None else None
            threads = [threading.Thread(target=_one, args=(sid, pos))
                       for sid, pos in parts.items()]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        if errors:
            raise Error(f"ps pull failed: {errors}")
        out = np.empty((len(ids),) + spec["width"],
                       np.dtype(spec["dtype"]))
        min_clock = self.clock
        for sid, pos in parts.items():
            r = results[sid]
            out[pos] = r["out"][0]
            min_clock = min(min_clock, int(r["reply"]["min_clock"]))
        lag = max(0, self.clock - min_clock)
        if _metrics.enabled():
            ps_metrics()["staleness"].set(lag)
        if hist is not None:
            hist.observe(get_time() - t0)
        if len(self.staleness_samples) >= 65536:
            del self.staleness_samples[:32768]
        self.staleness_samples.append(lag)
        return out

    def tick(self) -> None:
        """Advance this worker's SSP clock and announce it to every
        shard (async): a shard no push touched this round must still
        see the worker's progress, or its staleness gate would starve
        other workers' pulls."""
        self.clock += 1
        for sid in self._endpoints:
            self._with_failover(
                sid, lambda c: c.request(
                    {"cmd": "clock", "rank": self.rank,
                     "clock": self.clock}, wait=False))

    def pull_dense(self, name: str) -> np.ndarray:
        """Reassemble the full array from every shard's owned range."""
        spec = self._specs[name]
        out = np.zeros((spec["n_keys"],) + spec["width"],
                       np.dtype(spec["dtype"]))
        for sid in sorted(self._endpoints):
            r = self._with_failover(
                sid, lambda c: c.request({"cmd": "pull_range",
                                          "name": name}))
            lo, hi = int(r["reply"]["lo"]), int(r["reply"]["hi"])
            if hi > lo:
                out[lo:hi] = r["out"][0]
        return out

    def flush(self) -> None:
        """Drain every pipelined connection (all pushes acked)."""
        for c in list(self._conns.values()):
            c.flush()

    def close(self, shutdown_job: bool = True) -> None:
        """Say bye to every server (a server exits once all workers
        did) and count this worker's shutdown at the scheduler."""
        for sid in list(self._conns):
            try:
                self._conn(sid).request({"cmd": "bye",
                                         "rank": self.rank})
            except (ConnectionError, OSError, Error):
                pass
        for c in self._conns.values():
            c.close()
        self._conns.clear()
        if shutdown_job:
            try:
                self._sched_request({"cmd": "shutdown"})
            except (ConnectionError, OSError):
                pass
