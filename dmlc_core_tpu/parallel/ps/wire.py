"""PS wire framing: one JSON header line + raw array payload.

The tracker's protocol is newline-delimited JSON (tracker/tracker.py);
the PS data plane keeps that idiom for the *header* — every message
starts with one JSON line carrying ``cmd`` and metadata — but gradients
and weights ride AFTER the header as raw little-endian bytes, described
by an ``arrays`` descriptor list in the header.  JSON-encoding a
100k-float gradient batch would cost ~10x the bytes and a parse per
element; raw frames keep ``keys_per_sec`` a function of the socket, not
the codec.

Framing::

    {"cmd": "push", ..., "arrays": [{"dtype": "float32",
                                     "shape": [N]}, ...]}\\n
    <array 0 bytes><array 1 bytes>...

Both sides speak through a buffered socket file (``sock.makefile``), so
partial reads/writes are absorbed by the file object.
"""

from __future__ import annotations

import json
from typing import Any, BinaryIO, Dict, List, Sequence, Tuple

import numpy as np

from dmlc_core_tpu.base import tracectx as _tracectx
from dmlc_core_tpu.base.logging import CHECK

__all__ = ["send_msg", "recv_msg"]

#: refuse to allocate for absurd descriptors (a garbled header must not
#: OOM the receiver) — 1 GiB per array is far above any real PS frame
_MAX_ARRAY_BYTES = 1 << 30


def send_msg(f: BinaryIO, header: Dict[str, Any],
             arrays: Sequence[np.ndarray] = ()) -> None:
    """Write one framed message: JSON header line, then each array's
    raw bytes in order.  The ``arrays`` descriptor is appended to the
    header automatically."""
    desc = []
    blobs: List[bytes] = []
    for a in arrays:
        a = np.ascontiguousarray(a)
        desc.append({"dtype": str(a.dtype), "shape": list(a.shape)})
        blobs.append(a.tobytes())
    msg = dict(header)
    msg["arrays"] = desc
    # distributed trace context rides the framing layer (declared in
    # base/wire_schemas.WIRE_FRAMING), so every PS hop is correlated
    # without per-call-site plumbing; a no-op when DMLC_TRACE is off
    trace = _tracectx.current_header()
    if trace is not None:
        msg.setdefault(_tracectx.WIRE_KEY, trace)
    f.write(json.dumps(msg).encode() + b"\n")
    for b in blobs:
        f.write(b)
    f.flush()


def recv_msg(f: BinaryIO) -> Tuple[Dict[str, Any], List[np.ndarray]]:
    """Read one framed message; returns ``(header, arrays)``.  Raises
    ``ConnectionError`` on EOF (peer closed) — callers treat that as
    the liveness signal, exactly like the tracker's serve loop."""
    line = f.readline()
    if not line:
        raise ConnectionError("ps wire: peer closed")
    header = json.loads(line)
    arrays: List[np.ndarray] = []
    for d in header.pop("arrays", []):
        dtype = np.dtype(d["dtype"])
        shape = tuple(int(s) for s in d["shape"])
        nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        CHECK(0 <= nbytes <= _MAX_ARRAY_BYTES,
              f"ps wire: bad array frame ({nbytes} bytes)")
        buf = f.read(nbytes)
        if len(buf) != nbytes:
            raise ConnectionError("ps wire: truncated array frame")
        arrays.append(np.frombuffer(buf, dtype).reshape(shape))
    return header, arrays
