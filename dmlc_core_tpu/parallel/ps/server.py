"""Parameter-server processes: the scheduler and the key-range server.

Reference parity: ps-lite's ``Postoffice`` (scheduler: node discovery,
id assignment) and ``KVServer`` (range shard: server-side aggregation +
optimizer), bootstrapped off the same ``DMLC_PS_ROOT_URI/PORT`` +
``DMLC_ROLE`` env ABI dmlc-core's ``PSTracker`` exports (SURVEY.md
§2c).  The wire is the tracker's JSON-lines idiom with raw array frames
(``ps/wire.py``); the consistency model is bounded staleness (SSP, Ho
et al. NIPS'13): each server tracks a vector clock of worker progress
and a pull at worker clock ``c`` with window ``tau`` blocks until every
worker has reached ``c - tau``.

Durability: a server snapshots its shard (weights + meta + vector
clock, pickled into one leaf) through the atomic CRC'd checkpoint
substrate (``parallel/checkpoint.py``, ``local=True`` — no collective
in the commit path) every ``DMLC_PS_SNAPSHOT_STRIDE`` committed clock
ticks, and restores from the newest valid snapshot at startup — a
SIGKILLed server respawned with the same ``server_id`` rejoins at most
one stride behind (the ``scripts/check_ps.py`` drill).

Fault injection: the ``ps_push`` point fires in the push handler
(``DMLC_FAULT_INJECT="ps_push:kill:after=K"`` SIGKILLs the server on
its K+1-th push — the drill's trigger).
"""

from __future__ import annotations

import os
import pickle
import signal
import socket
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dmlc_core_tpu.base import faultinject as _fi
from dmlc_core_tpu.base import metrics as _metrics
from dmlc_core_tpu.base import tracectx as _tracectx
from dmlc_core_tpu.base.logging import CHECK, LOG
from dmlc_core_tpu.parallel.ps import wire
from dmlc_core_tpu.parallel.ps.partition import server_ranges
from dmlc_core_tpu.tracker.tracker import RabitTracker

__all__ = ["PSScheduler", "PSServer", "ps_metrics"]

_PM = None


def ps_metrics():
    """Lazy ``dmlc_ps_*`` instrument bundle (shared by server and
    client; declared once per process on the default registry)."""
    global _PM
    if _PM is None:
        r = _metrics.default_registry()
        _PM = {
            "push": r.histogram(
                "ps_push_seconds",
                "client-observed push RPC latency (send to ack)"),
            "pull": r.histogram(
                "ps_pull_seconds",
                "client-observed pull RPC latency, staleness wait "
                "included"),
            "keys": r.counter(
                "ps_keys_synced_total",
                "sparse keys moved through push/pull",
                labels=("op",)),
            "staleness": r.gauge(
                "ps_staleness_rounds",
                "clock lag behind the slowest worker observed at the "
                "last pull (bounded-staleness window occupancy)"),
            "requests": r.counter(
                "ps_server_requests_total",
                "requests handled by this PS server shard",
                labels=("cmd",)),
            "restores": r.counter(
                "ps_server_restores_total",
                "server startups that restored state from a "
                "snapshot"),
        }
    return _PM


class PSScheduler(RabitTracker):
    """The PS control plane: server-id assignment + endpoint discovery.

    A :class:`~dmlc_core_tpu.tracker.tracker.RabitTracker` subclass —
    same TCP/JSON-lines service, same locking and liveness machinery —
    with the PS commands added through the ``_handle_ext`` hook:

    * ``ps_register`` ``{host, port, server_id}`` — a server announces
      its data-plane endpoint.  ``server_id`` -1 assigns the next free
      id; a respawned server passes its old id and just overwrites the
      endpoint (restore-in-place, the drill's recovery path).
    * ``ps_servers`` ``{}`` — the current endpoint map plus ``ready``
      (all ``nserver`` registered); clients poll until ready.

    Workers end the job with the base protocol's ``shutdown`` (counted
    to ``nworker``), so ``join()`` keeps its meaning.
    """

    def __init__(self, host_ip: str = "127.0.0.1", nworker: int = 1,
                 nserver: int = 1, port: int = 0,
                 grace_s: Optional[float] = None):
        super().__init__(host_ip=host_ip, nworker=nworker, port=port,
                         grace_s=grace_s)
        CHECK(nserver >= 1, "PSScheduler needs at least one server")
        self.nserver = nserver
        # guarded by the base tracker's self._lock, like all membership
        self._ps_endpoints: Dict[int, Tuple[str, int]] = {}
        self._ps_next_id = 0

    def _handle_ext(self, cmd: Any, msg: Dict[str, Any],
                    conn: Optional[socket.socket],
                    state: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        if cmd == "ps_register":
            sid = int(msg.get("server_id", -1))
            with self._lock:
                if sid < 0:
                    sid = self._ps_next_id
                    self._ps_next_id += 1
                elif sid >= self._ps_next_id:
                    self._ps_next_id = sid + 1
                self._ps_endpoints[sid] = (str(msg["host"]),
                                           int(msg["port"]))
            if sid >= self.nserver:
                return {"error": f"too many servers (nserver="
                                 f"{self.nserver})"}
            LOG("INFO", "ps.scheduler: server %d registered at %s:%s",
                sid, msg["host"], msg["port"])
            return {"server_id": sid, "nserver": self.nserver,
                    "nworker": self.nworker}
        if cmd == "ps_servers":
            with self._lock:
                eps = {str(k): list(v)
                       for k, v in self._ps_endpoints.items()}
            return {"ready": len(eps) >= self.nserver, "servers": eps,
                    "nworker": self.nworker}
        return super()._handle_ext(cmd, msg, conn, state)


class PSServer:
    """One key-range shard: aggregation buffers + SGD + vector clock.

    Owns the contiguous slice ``server_ranges(n_keys, nserver)[sid]``
    of every named array (the cut is re-derived per array at ``init``
    from the array's own key cardinality).  Handles, per connection
    thread (the tracker's serve-loop idiom):

    * ``init``    — declare an array (idempotent; first writer wins)
    * ``push``    — ``ids, grads`` → ``w[ids] -= lr * grads`` under the
      shard lock (server-side aggregation: duplicate ids within a
      batch accumulate via ``np.add.at``), then advance the pusher's
      vector-clock entry
    * ``pull``    — block while ``min(vclock) < clock - staleness``
      (SSP), then return ``w[ids]``
    * ``clock``   — explicit clock advance (a worker whose minibatch
      touched no key in this shard must still make progress visible)
    * ``pull_range`` — the full owned slice (final weights / rebalance)
    * ``bye``     — worker disconnect; the server exits once every
      worker said bye

    Start with :meth:`start` (registers with the scheduler, spawns the
    accept loop); :meth:`serve_forever` blocks until shutdown.
    """

    def __init__(self, scheduler_uri: str, scheduler_port: int,
                 host_ip: str = "127.0.0.1", port: int = 0,
                 server_id: int = -1,
                 snapshot_dir: Optional[str] = None,
                 snapshot_stride: Optional[int] = None):
        from dmlc_core_tpu.base import knobs as _knobs

        if snapshot_dir is None:
            snapshot_dir = str(_knobs.value("DMLC_PS_SNAPSHOT_DIR"))
        if snapshot_stride is None:
            snapshot_stride = int(_knobs.value("DMLC_PS_SNAPSHOT_STRIDE"))
        self._snap_dir = snapshot_dir
        self._snap_stride = snapshot_stride
        self._sched = (scheduler_uri, scheduler_port)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host_ip, port))
        self._sock.listen(64)
        self.host_ip = host_ip
        self.port = self._sock.getsockname()[1]
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._done = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._serve_threads: List[threading.Thread] = []
        # shard state, all guarded by self._lock
        self._meta: Dict[str, Dict[str, Any]] = {}
        self._arrays: Dict[str, np.ndarray] = {}
        self._vclock: Dict[int, int] = {}
        self._byes = 0
        self._last_snap = 0
        self.restored_version = 0       # drill-observable restore marker
        # registration: one-shot scheduler round trip (tracker JSON
        # framing; the header-only side of the ps wire)
        with socket.create_connection((scheduler_uri, scheduler_port),
                                      timeout=10) as s:
            f = s.makefile("rwb")
            wire.send_msg(f, {"cmd": "ps_register", "host": host_ip,
                              "port": self.port, "server_id": server_id})
            reply, _ = wire.recv_msg(f)
        CHECK("error" not in reply,
              f"ps.server: scheduler rejected registration: {reply}")
        self.server_id = int(reply["server_id"])
        self.nserver = int(reply["nserver"])
        self.nworker = int(reply["nworker"])
        with self._lock:
            for r in range(self.nworker):
                self._vclock[r] = 0
        if self._snap_dir:
            self._restore()
        # join the fleet metrics spool (no-op without DMLC_METRICS_SPOOL)
        from dmlc_core_tpu.base import metrics_agg as _agg

        _agg.install_spool("ps_server", self.server_id)

    # -- snapshot / restore ----------------------------------------------
    def _snapshot_uri(self) -> str:
        return os.path.join(self._snap_dir,
                            f"ps-server-{self.server_id}.ckpt")

    def _maybe_snapshot_locked(self) -> None:
        """Snapshot when the committed clock advanced a full stride
        past the last snapshot (caller holds the lock)."""
        if not self._snap_dir or self._snap_stride <= 0:
            return
        floor = min(self._vclock.values()) if self._vclock else 0
        if floor < self._last_snap + self._snap_stride:
            return
        from dmlc_core_tpu.parallel.checkpoint import checkpoint

        blob = pickle.dumps({"meta": self._meta,
                             "arrays": self._arrays,
                             "vclock": self._vclock})
        checkpoint(self._snapshot_uri(),
                   {"blob": np.frombuffer(blob, np.uint8)},
                   version=floor, local=True)
        self._last_snap = floor

    def _restore(self) -> None:
        from dmlc_core_tpu.parallel.checkpoint import load_checkpoint

        like = {"blob": np.zeros(0, np.uint8)}
        version, state = load_checkpoint(self._snapshot_uri(), like)
        if not version:
            return
        payload = pickle.loads(state["blob"].tobytes())
        with self._lock:
            self._meta = payload["meta"]
            self._arrays = payload["arrays"]
            self._vclock = {int(k): int(v)
                            for k, v in payload["vclock"].items()}
            self._last_snap = int(version)
        self.restored_version = int(version)
        if _metrics.enabled():
            ps_metrics()["restores"].inc(1)
        LOG("INFO", "ps.server %d: restored snapshot v%d (%d arrays)",
            self.server_id, version, len(payload["arrays"]))

    # -- service loop ----------------------------------------------------
    def start(self) -> None:
        """Spawn the accept loop (daemon thread)."""
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()

    def serve_forever(self, timeout_s: Optional[float] = None) -> bool:
        """Block until every worker said ``bye`` (or ``timeout_s``).
        Returns True on clean shutdown."""
        done = self._done.wait(timeout_s)
        self.stop()
        return done

    def stop(self) -> None:
        """Close the listening socket and wake the accept loop."""
        self._done.set()
        with self._cond:
            self._cond.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass
        # reap connection threads (daemon threads that own self._lock
        # must not outlive stop()) and the accept loop, bounded
        with self._lock:
            serve_threads = list(self._serve_threads)
            self._serve_threads.clear()
        me = threading.current_thread()
        for t in serve_threads:
            if t is not me:
                t.join(timeout=2.0)
        if self._thread is not None and self._thread is not me:
            self._thread.join(timeout=2.0)

    def _accept_loop(self) -> None:
        while not self._done.is_set():
            try:
                self._sock.settimeout(0.2)
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            with self._lock:
                self._serve_threads = [x for x in self._serve_threads
                                       if x.is_alive()]
                self._serve_threads.append(t)
            t.start()

    def _serve(self, conn: socket.socket) -> None:
        """One worker connection: framed request/reply until EOF."""
        try:
            with conn:
                f = conn.makefile("rwb")
                while not self._done.is_set():
                    msg, arrays = wire.recv_msg(f)
                    # join the sender's distributed trace for this
                    # request so the server-side span lands in the same
                    # timeline (no-op when DMLC_TRACE is off)
                    with _tracectx.attach(msg.get(_tracectx.WIRE_KEY)):
                        with _tracectx.span(
                                f"ps.server.{msg.get('cmd')}"):
                            reply, out = self._handle(msg, arrays)
                        wire.send_msg(f, reply, out)
        except (ConnectionError, OSError):
            pass

    # -- request dispatch ------------------------------------------------
    def _handle(self, msg: Dict[str, Any], arrays: List[np.ndarray]
                ) -> Tuple[Dict[str, Any], List[np.ndarray]]:
        cmd = msg.get("cmd")
        if _metrics.enabled():
            ps_metrics()["requests"].inc(1, cmd=str(cmd))
        if cmd == "init":
            return self._handle_init(msg, arrays)
        if cmd == "push":
            return self._handle_push(msg, arrays)
        if cmd == "pull":
            return self._handle_pull(msg, arrays)
        if cmd == "clock":
            return self._handle_clock(msg)
        if cmd == "pull_range":
            return self._handle_pull_range(msg)
        if cmd == "bye":
            with self._cond:
                self._byes += 1
                byes = self._byes
                # a departed worker must not hold the SSP floor: its
                # clock is frozen forever, so leaving it in the vector
                # clock deadlocks every surviving reader that is more
                # than tau ahead of it
                self._vclock.pop(int(msg.get("rank", -1)), None)
                self._cond.notify_all()
            if byes >= self.nworker:
                self._done.set()
            return {"ok": 1}, []
        if cmd == "ping":
            return {"ok": 1, "server_id": self.server_id}, []
        return {"error": f"unknown cmd {cmd!r}"}, []

    def _range_of(self, n_keys: int) -> Tuple[int, int]:
        return server_ranges(n_keys, self.nserver)[self.server_id]

    def _handle_init(self, msg: Dict[str, Any],
                     arrays: List[np.ndarray]
                     ) -> Tuple[Dict[str, Any], List[np.ndarray]]:
        name = str(msg["name"])
        n_keys = int(msg["n_keys"])
        width = tuple(int(w) for w in msg.get("width", []))
        lo, hi = self._range_of(n_keys)
        with self._cond:
            if name not in self._meta:     # first init wins (idempotent)
                if arrays:
                    a = np.array(arrays[0], np.dtype(str(msg["dtype"])))
                    CHECK(a.shape == (hi - lo,) + width,
                          f"ps init {name!r}: slice shape {a.shape} != "
                          f"{(hi - lo,) + width}")
                elif float(msg.get("init_scale", 0.0)) > 0.0:
                    # server-local random init: seeded by (seed, lo) so
                    # the draw is a pure function of the key range —
                    # identical across respawns and re-ranges, and no
                    # host ever holds the whole array
                    rng = np.random.default_rng(
                        (int(msg.get("seed", 0)), lo))
                    a = (rng.standard_normal((hi - lo,) + width)
                         * float(msg["init_scale"])
                         ).astype(np.dtype(str(msg["dtype"])))
                else:
                    a = np.zeros((hi - lo,) + width,
                                 np.dtype(str(msg["dtype"])))
                self._meta[name] = {"n_keys": n_keys, "width": width,
                                    "dtype": str(msg["dtype"]),
                                    "lr": float(msg.get("lr", 0.1)),
                                    "lo": lo, "hi": hi}
                self._arrays[name] = a
        return {"ok": 1, "lo": lo, "hi": hi}, []

    def _handle_push(self, msg: Dict[str, Any],
                     arrays: List[np.ndarray]
                     ) -> Tuple[Dict[str, Any], List[np.ndarray]]:
        fault = _fi.check("ps_push")
        if fault is not None and fault.kind == "kill":
            os.kill(os.getpid(), signal.SIGKILL)
        name = str(msg["name"])
        rank, clock = int(msg["rank"]), int(msg["clock"])
        ids, grads = arrays[0], arrays[1]
        with self._cond:
            meta = self._meta.get(name)
            if meta is None:
                return {"error": f"ps push: unknown array {name!r}"}, []
            a = self._arrays[name]
            idx = np.asarray(ids, np.int64) - meta["lo"]
            # server-side aggregation + SGD in one pass: duplicate ids
            # within the batch accumulate exactly (np.add.at)
            np.add.at(a, idx, (-meta["lr"] * grads).astype(a.dtype,
                                                           copy=False))
            if rank in self._vclock and clock > self._vclock[rank]:
                self._vclock[rank] = clock
            self._maybe_snapshot_locked()
            floor = min(self._vclock.values()) if self._vclock else 0
            self._cond.notify_all()
        if _metrics.enabled():
            ps_metrics()["keys"].inc(len(ids), op="push")
        return {"ok": 1, "min_clock": floor}, []

    def _handle_pull(self, msg: Dict[str, Any],
                     arrays: List[np.ndarray]
                     ) -> Tuple[Dict[str, Any], List[np.ndarray]]:
        from dmlc_core_tpu.base.timer import get_time

        name = str(msg["name"])
        clock = int(msg.get("clock", 0))
        tau = int(msg.get("staleness", -1))
        timeout_s = float(msg.get("timeout_s", 60.0))
        ids = arrays[0]
        deadline = get_time() + timeout_s
        with self._cond:
            meta = self._meta.get(name)
            if meta is None:
                return {"error": f"ps pull: unknown array {name!r}"}, []
            # SSP gate: a reader at clock c may proceed only once every
            # worker's committed clock reached c - tau
            while tau >= 0 and self._vclock and (
                    min(self._vclock.values()) < clock - tau):
                left = deadline - get_time()
                if left <= 0 or self._done.is_set():
                    return {"error": "ps pull: staleness wait timed "
                                     f"out (clock={clock} tau={tau} "
                                     f"vclock={self._vclock})"}, []
                self._cond.wait(min(left, 0.5))
            a = self._arrays[name]
            idx = np.asarray(ids, np.int64) - meta["lo"]
            vals = np.ascontiguousarray(a[idx])
            floor = min(self._vclock.values()) if self._vclock else 0
        if _metrics.enabled():
            ps_metrics()["keys"].inc(len(ids), op="pull")
        return {"ok": 1, "min_clock": floor}, [vals]

    def _handle_clock(self, msg: Dict[str, Any]
                      ) -> Tuple[Dict[str, Any], List[np.ndarray]]:
        rank, clock = int(msg["rank"]), int(msg["clock"])
        with self._cond:
            if rank in self._vclock and clock > self._vclock[rank]:
                self._vclock[rank] = clock
            self._maybe_snapshot_locked()
            floor = min(self._vclock.values()) if self._vclock else 0
            self._cond.notify_all()
        return {"ok": 1, "min_clock": floor}, []

    def _handle_pull_range(self, msg: Dict[str, Any]
                           ) -> Tuple[Dict[str, Any], List[np.ndarray]]:
        name = str(msg["name"])
        with self._cond:
            meta = self._meta.get(name)
            if meta is None:
                return {"error": f"ps pull_range: unknown array "
                                 f"{name!r}"}, []
            vals = np.ascontiguousarray(self._arrays[name])
            lo, hi = meta["lo"], meta["hi"]
        return {"ok": 1, "lo": lo, "hi": hi}, [vals]
