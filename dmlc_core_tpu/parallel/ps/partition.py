"""Key-range partitioner for the parameter-server shard layout.

Reference parity: ps-lite's ``Range``/``Postoffice::GetServerKeyRanges``
— the key space ``[0, n_keys)`` is cut into one contiguous range per
server (Li et al., OSDI'14 §3.2: range partitioning keeps server-side
state contiguous so aggregation buffers are flat slices, and a pull of
a sorted id batch touches each server once).  The cut uses the same
exact-tiling arithmetic as :func:`~dmlc_core_tpu.parallel.mesh.
shard_row_ranges` (``lo = n*k // s``), so the ranges tile the key space
with no gaps/overlap for ANY server count, odd ones included — the
property tests in tests/test_ps.py sweep it.

Membership change (a server joins or leaves) re-cuts the ranges with
the same formula; :func:`rebalance_plan` emits the minimal contiguous
segment moves from the old layout to the new one, and its property is
the one that matters: every key appears in exactly one move target.

For id spaces where contiguous ranges would skew (sparse feature ids
clustered in a sub-range), :func:`route_hashed` routes ids through a
stable multiplicative hash — deterministic across processes and runs
(no Python hash randomization), which is what makes hashed routing a
*partition* and not a lottery.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from dmlc_core_tpu.base.logging import CHECK

__all__ = ["server_ranges", "server_of", "split_by_server",
           "rebalance_plan", "route_hashed"]

#: Knuth's multiplicative hash constant (2^32 / phi); the classic
#: integer-scrambling multiplier — fixed, so routing is stable across
#: processes, restarts and Python versions
_HASH_MULT = np.uint64(2654435761)
_HASH_MASK = np.uint64(0xFFFFFFFF)


def server_ranges(n_keys: int, nservers: int) -> List[Tuple[int, int]]:
    """Contiguous ``[lo, hi)`` key range per server.

    Exact tiling: ``lo_k = n_keys * k // nservers`` — ranges cover
    ``[0, n_keys)`` with no gap and no overlap for any ``nservers``
    (including odd counts and ``nservers > n_keys``, where trailing
    servers get empty ranges).
    """
    CHECK(nservers >= 1, f"need at least one server, got {nservers}")
    CHECK(n_keys >= 0, f"negative key space {n_keys}")
    return [(n_keys * k // nservers, n_keys * (k + 1) // nservers)
            for k in range(nservers)]


def server_of(ids: np.ndarray, n_keys: int, nservers: int) -> np.ndarray:
    """Vectorized owner lookup: server index for each id (range
    routing).  Inverse of :func:`server_ranges`'s cut — computed by
    searchsorted over the range starts so it stays exact for every
    server count."""
    ids = np.asarray(ids, np.int64)
    starts = np.asarray([n_keys * k // nservers for k in range(nservers)],
                        np.int64)
    return (np.searchsorted(starts, ids, side="right") - 1).astype(np.int64)


def split_by_server(ids: np.ndarray, n_keys: int,
                    nservers: int) -> Dict[int, np.ndarray]:
    """Group a sparse id batch by owning server (range routing).

    Returns ``{server: positions}`` where ``positions`` indexes into
    the ORIGINAL ``ids`` array — callers slice their value arrays with
    the same positions, so one pass routes ids and payload together.
    Servers with no ids in the batch are absent (sparse push/pull only
    talks to touched shards).
    """
    ids = np.asarray(ids, np.int64)
    owners = server_of(ids, n_keys, nservers)
    out: Dict[int, np.ndarray] = {}
    for sid in np.unique(owners):
        out[int(sid)] = np.nonzero(owners == sid)[0]
    return out


def rebalance_plan(n_keys: int, old_nservers: int,
                   new_nservers: int) -> List[Tuple[int, int, int, int]]:
    """Segment moves for a membership change: re-cut the key space from
    ``old_nservers`` to ``new_nservers`` ranges and intersect the two
    grids.  Returns ``(src_server, dst_server, lo, hi)`` segments —
    the contiguous key runs each destination must fetch from each
    source.  Segments whose src == dst never move and are omitted —
    the plan is MINIMAL.  Property (tested): replaying the plan over
    the old ownership map yields exactly the new tiling, so a re-range
    after join/leave preserves every key.
    """
    old = server_ranges(n_keys, old_nservers)
    new = server_ranges(n_keys, new_nservers)
    cuts = sorted({b for lo, hi in old + new for b in (lo, hi)})
    plan: List[Tuple[int, int, int, int]] = []
    for lo, hi in zip(cuts, cuts[1:]):
        if lo == hi:
            continue
        src = int(server_of(np.asarray([lo]), n_keys, old_nservers)[0])
        dst = int(server_of(np.asarray([lo]), n_keys, new_nservers)[0])
        if src != dst:
            plan.append((src, dst, lo, hi))
    return plan


def route_hashed(ids: np.ndarray, nservers: int) -> np.ndarray:
    """Stable hashed routing: server index per id via a fixed
    multiplicative hash (no range locality assumption — the mode for
    id spaces where contiguous ranges would skew load).  Deterministic
    across calls, processes and runs: the multiplier is a module
    constant, not a salted ``hash()``."""
    CHECK(nservers >= 1, f"need at least one server, got {nservers}")
    ids = np.asarray(ids, np.int64).astype(np.uint64)
    h = (ids * _HASH_MULT) & _HASH_MASK
    return ((h * np.uint64(nservers)) >> np.uint64(32)).astype(np.int64)
