"""Ring attention: exact attention over sequence-sharded Q/K/V.

Long-context support is first-class in this framework (the substrate
obligation SURVEY.md §5 notes: the data plane already streams unbounded
records; this module is the compute-side counterpart).  The sequence axis
of a mesh (``seq``) shards tokens across devices; full attention then
needs every (query, key) pair, which ring attention provides without ever
materializing the full sequence on one chip:

* each device holds local blocks ``q/k/v [B, S/P, H, D]``;
* K/V blocks rotate around the ``seq`` axis with ``lax.ppermute`` — P
  steps over the ICI ring, communication overlapped with the block
  attention compute;
* softmax is accumulated **online** (flash-attention style running max /
  normalizer), so the result is exact, not approximate — bf16 inputs,
  f32 accumulation on the MXU.

Designed for use inside ``shard_map`` (see :func:`ring_attention`'s
contract) and composed by the BERT family for sequence parallelism; causal
masking uses global token positions so decoder stacks shard identically.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ring_attention", "reference_attention"]


def ring_attention(
    q: jax.Array,           # [B, S_local, H, D] — this device's query block
    k: jax.Array,           # [B, S_local, H, D]
    v: jax.Array,           # [B, S_local, H, D]
    axis_name: str = "seq",
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact attention over the sequence axis ``axis_name``.

    MUST be called inside a ``shard_map`` (or pmap) that maps the token
    dimension over ``axis_name``.  Returns this device's output block
    ``[B, S_local, H, D]``.
    """
    n_dev = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    s_local = q.shape[1]
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    qf = q.astype(jnp.float32) * scale

    q_pos = my_idx * s_local + jnp.arange(s_local)               # global positions

    def one_block(k_blk, v_blk, src_idx):
        """Attention of local q against one rotated K/V block."""
        s = jnp.einsum("bqhd,bkhd->bhqk", qf, k_blk.astype(jnp.float32))
        if causal:
            k_pos = src_idx * s_local + jnp.arange(s_local)
            mask = q_pos[:, None] >= k_pos[None, :]              # [Sq, Sk]
            s = jnp.where(mask[None, None], s, -jnp.inf)
        return s

    perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]

    def body(carry, i):
        o, m, l, k_blk, v_blk = carry
        # after i rotations this device holds the block born on (my_idx - i)
        src_idx = (my_idx - i) % n_dev
        s = one_block(k_blk, v_blk, src_idx)                     # [B,H,Sq,Sk]
        m_new = jnp.maximum(m, s.max(axis=-1))
        # guard fully-masked rows: exp(-inf - (-inf)) → use finite floor
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        corr = jnp.exp(jnp.where(jnp.isneginf(m), m, m - m_safe))
        p = jnp.exp(s - m_safe[..., None])
        if causal:
            p = jnp.where(jnp.isneginf(s), 0.0, p)
        l_new = l * corr + p.sum(axis=-1)
        o_new = (o * corr[..., None]
                 + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)))
        k_rot = lax.ppermute(k_blk, axis_name, perm)
        v_rot = lax.ppermute(v_blk, axis_name, perm)
        return (o_new, m_new, l_new, k_rot, v_rot), None

    B, S, H, D = q.shape
    o0 = jnp.zeros((B, H, S, D), jnp.float32)
    m0 = jnp.full((B, H, S), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, S), jnp.float32)
    (o, _m, l, _k, _v), _ = lax.scan(
        body, (o0, m0, l0, k, v), jnp.arange(n_dev))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)      # [B,S,H,D]


def reference_attention(q, k, v, causal: bool = False, scale=None):
    """Single-device oracle (full softmax) for tests."""
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk",
                   q.astype(jnp.float32) * scale, k.astype(jnp.float32))
    if causal:
        S = q.shape[1]
        mask = jnp.arange(S)[:, None] >= jnp.arange(S)[None, :]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)
