"""ZeRO-style sharded optimizer state over the mesh's data axis.

The PS-replacement promised in SURVEY.md §2e: instead of a parameter
server holding optimizer state (the reference world's ps-lite role),
each data-parallel worker owns 1/P of every parameter's optimizer state:

* backward produces per-shard gradients (summed over local batches);
* ``psum_scatter`` reduces them across the axis while leaving each
  device ONLY its 1/P slice (half an allreduce's bandwidth);
* the optimizer update (here Adam) runs on the slice — P× less state
  and update compute per device;
* one ``all_gather`` rebuilds the full parameter for the next forward.

Designed for use INSIDE ``shard_map`` (axis collectives), composing with
the same mesh the models train on.  ``shard/unshard`` handle padding so
any parameter size works on any axis size.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ZeroAdam", "ZeroState"]


class ZeroState(NamedTuple):
    """Per-device optimizer shard: first/second moments + step count."""
    mu: Any       # pytree of [ceil(size/P)] f32 slices
    nu: Any
    count: jax.Array


def _flat_pad(x: jax.Array, P: int) -> jax.Array:
    flat = x.reshape(-1)
    pad = (-flat.shape[0]) % P
    return jnp.pad(flat, (0, pad))


class ZeroAdam:
    """Adam with parameters replicated but optimizer state sharded 1/P.

    All methods must run inside a ``shard_map`` over ``axis``:

    >>> opt = ZeroAdam(lr=1e-3)
    >>> state = opt.init(params)                    # per-device shards
    >>> params, state = opt.step(params, grads, state)  # psum_scatter+gather
    """

    def __init__(self, lr: float = 1e-3, b1: float = 0.9, b2: float = 0.999,
                 eps: float = 1e-8, axis: str = "data"):
        self.lr, self.b1, self.b2, self.eps = lr, b1, b2, eps
        self.axis = axis

    def init(self, params: Dict[str, jax.Array]) -> ZeroState:
        P = lax.psum(1, self.axis)
        zeros = jax.tree.map(
            lambda p: jnp.zeros(_flat_pad(p, P).shape[0] // P, jnp.float32),
            params)
        return ZeroState(mu=zeros, nu=jax.tree.map(jnp.copy, zeros),
                         count=jnp.zeros((), jnp.int32))

    def step(self, params, grads, state: ZeroState):
        """One update.  ``grads`` are this device's local gradients (e.g.
        from its batch shard); the reduce happens in here."""
        P = lax.psum(1, self.axis)
        count = state.count + 1
        b1c = 1.0 - self.b1 ** count.astype(jnp.float32)
        b2c = 1.0 - self.b2 ** count.astype(jnp.float32)

        def upd(p, g, mu, nu):
            flat_g = _flat_pad(g, P)
            # mean-reduce across workers, keep only my 1/P slice
            g_slice = lax.psum_scatter(flat_g, self.axis, tiled=True) / P
            mu2 = self.b1 * mu + (1 - self.b1) * g_slice
            nu2 = self.b2 * nu + (1 - self.b2) * g_slice * g_slice
            delta = (self.lr * (mu2 / b1c)
                     / (jnp.sqrt(nu2 / b2c) + self.eps))
            # rebuild the full parameter delta for the replicated params
            full = lax.all_gather(delta, self.axis, tiled=True)
            p2 = p - full[: p.size].reshape(p.shape)
            return p2, mu2, nu2

        # tree.map (like init) so arbitrarily nested param pytrees work
        triples = jax.tree.map(upd, params, grads, state.mu, state.nu)
        params2 = jax.tree.map(lambda t: t[0], triples,
                               is_leaf=lambda x: isinstance(x, tuple))
        mu2 = jax.tree.map(lambda t: t[1], triples,
                           is_leaf=lambda x: isinstance(x, tuple))
        nu2 = jax.tree.map(lambda t: t[2], triples,
                           is_leaf=lambda x: isinstance(x, tuple))
        return params2, ZeroState(mu=mu2, nu=nu2, count=count)
