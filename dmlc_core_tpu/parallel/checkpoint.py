"""Sharded checkpoint / resume of jax pytrees over any Stream URI.

Reference parity: dmlc-core provides checkpoint *mechanism*, not policy —
``Stream``/``Serializable`` binary round-trip to any URI, which rabit's
``CheckPoint()/LoadCheckPoint()`` and XGBoost model I/O build on
(SURVEY.md §5).  Here the same layering carries jax state:

* ``save(uri, pytree)`` — host-gathers each leaf (or saves only this
  process's addressable shards in per-rank files when ``sharded=True``)
  and serializes through the Stream layer, so checkpoints inherit every
  filesystem backend (local/mem://, later object stores) for free.
* rabit parity: ``version_number`` round-trips with the state, and
  ``load_checkpoint`` returns ``(version, state)`` with version 0 when no
  checkpoint exists — exactly the resume-loop contract XGBoost uses.
"""

from __future__ import annotations

from typing import Any, Tuple

import numpy as np

import jax

from dmlc_core_tpu.base.logging import CHECK
from dmlc_core_tpu.io import serializer as ser
from dmlc_core_tpu.io.stream import Stream
from dmlc_core_tpu.parallel import collectives as coll

__all__ = ["checkpoint", "load_checkpoint"]

_MAGIC = 0xC4EC7A90


def _to_host(leaf: Any) -> Any:
    if isinstance(leaf, jax.Array):
        return np.asarray(leaf)
    return leaf


def checkpoint(uri: str, state: Any, version: int = 0, sharded: bool = False) -> None:
    """Save a pytree of arrays/scalars.  Reference: rabit ``CheckPoint``.

    ``sharded=True`` writes one file per process (``uri.shard-K-of-N``),
    each holding only locally-addressable shard data — the multi-host path
    where no single host can materialize the full arrays.
    """
    if sharded and coll.world_size() > 1:
        uri = f"{uri}.shard-{coll.rank()}-of-{coll.world_size()}"
        leaves, treedef = jax.tree.flatten(state)
        host_leaves = []
        for leaf in leaves:
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                shards = sorted(leaf.addressable_shards, key=lambda s: s.index)
                host_leaves.append([(str(s.index), np.asarray(s.data)) for s in shards])
            else:
                host_leaves.append(_to_host(leaf))
        payload = host_leaves
    else:
        if coll.world_size() > 1 and coll.rank() != 0:
            coll.barrier("ckpt")
            return  # replicated state: rank 0 writes
        payload = jax.tree.map(_to_host, state)
        payload = jax.tree.flatten(payload)[0]
    stream = Stream.create(uri, "w")
    ser.write_uint32(stream, _MAGIC)
    ser.write_uint64(stream, version)
    ser.write_obj(stream, payload)
    stream.close()
    if coll.world_size() > 1 and not sharded:
        coll.barrier("ckpt")


def load_checkpoint(uri: str, like: Any, sharded: bool = False) -> Tuple[int, Any]:
    """Load a checkpoint into the structure of ``like``.

    Returns ``(version, state)``; ``(0, like)`` when no checkpoint exists —
    rabit's ``LoadCheckPoint`` contract for cold starts.
    """
    if sharded and coll.world_size() > 1:
        uri = f"{uri}.shard-{coll.rank()}-of-{coll.world_size()}"
    stream = Stream.create(uri, "r", allow_null=True)
    if stream is None:
        return 0, like
    magic = ser.read_uint32(stream)
    CHECK(magic == _MAGIC, "checkpoint: bad magic")
    version = ser.read_uint64(stream)
    payload = ser.read_obj(stream)
    stream.close()
    leaves, treedef = jax.tree.flatten(like)
    CHECK(len(payload) == len(leaves), "checkpoint: leaf count mismatch")
    out_leaves = []
    for saved, ref in zip(payload, leaves):
        if isinstance(saved, list) and saved and isinstance(saved[0], tuple):
            # sharded leaf: reassemble only this process's shards into the
            # reference sharding via device_put per shard
            CHECK(isinstance(ref, jax.Array), "checkpoint: sharded leaf vs non-array ref")
            arrays = {idx: data for idx, data in saved}
            shards = []
            for s in sorted(ref.addressable_shards, key=lambda s: s.index):
                data = arrays.get(str(s.index))
                CHECK(data is not None, "checkpoint: missing shard")
                shards.append(jax.device_put(data, s.device))
            out_leaves.append(
                jax.make_array_from_single_device_arrays(ref.shape, ref.sharding, shards)
            )
        elif isinstance(ref, jax.Array):
            out_leaves.append(jax.device_put(np.asarray(saved), ref.sharding))
        else:
            out_leaves.append(saved)
    return int(version), jax.tree.unflatten(treedef, out_leaves)
