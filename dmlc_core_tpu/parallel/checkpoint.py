"""Sharded checkpoint / resume of jax pytrees over any Stream URI.

Reference parity: dmlc-core provides checkpoint *mechanism*, not policy —
``Stream``/``Serializable`` binary round-trip to any URI, which rabit's
``CheckPoint()/LoadCheckPoint()`` and XGBoost model I/O build on
(SURVEY.md §5).  Here the same layering carries jax state:

* ``save(uri, pytree)`` — host-gathers each leaf (or saves only this
  process's addressable shards in per-rank files when ``sharded=True``)
  and serializes through the Stream layer, so checkpoints inherit every
  filesystem backend (local/mem://, later object stores) for free.
* rabit parity: ``version_number`` round-trips with the state, and
  ``load_checkpoint`` returns ``(version, state)`` with version 0 when no
  checkpoint exists — exactly the resume-loop contract XGBoost uses.

Durability (doc/robustness.md):

* **Atomic commit** — local files are written to ``<uri>.tmp`` and
  ``os.replace``d into place, so a SIGKILL mid-checkpoint can never
  destroy the previous version; object-store backends already commit
  on close (``BufferedWriteStream``), and ``mem://`` now does too.
* **Per-leaf CRC32** — a JSON *sidecar* (``<uri>.crc``) records one
  CRC per serialized leaf.  The checkpoint file's own bytes are
  unchanged from the pre-sidecar format, so old checkpoints still load
  (no sidecar → no validation) and new files stay bit-compatible.
* **Prior-version retention + fallback** — before overwriting, the
  previous checkpoint is kept as ``<uri>.prev`` (local/mem by default;
  ``DMLC_CKPT_KEEP=0`` disables, ``=1`` forces it on for remote URIs
  at the cost of a copy).  ``load_checkpoint`` falls back to the
  newest valid prior version when the primary is corrupt — detected by
  magic, framing, CRC, or leaf-count failure — and counts the event on
  ``dmlc_checkpoint_fallbacks_total``.

The ``checkpoint`` fault-injection point (``base.faultinject``) sits
between payload write and commit: ``kill`` SIGKILLs the process there
(the crash-mid-write drill ``scripts/check_resilience.py`` runs),
``abort`` raises instead, and ``corrupt`` flips a byte post-commit to
exercise CRC detection and fallback.
"""

from __future__ import annotations

import json
import os
import signal
import zlib
from typing import Any, List, Optional, Tuple

import numpy as np

import jax

from dmlc_core_tpu.base import faultinject as _fi
from dmlc_core_tpu.base import metrics as _metrics
from dmlc_core_tpu.base.logging import CHECK, LOG, Error
from dmlc_core_tpu.io import serializer as ser
from dmlc_core_tpu.io.filesystem import URI
from dmlc_core_tpu.io.stream import Stream
from dmlc_core_tpu.parallel import collectives as coll

__all__ = ["checkpoint", "load_checkpoint"]

_MAGIC = 0xC4EC7A90
_CRC_SUFFIX = ".crc"
_PREV_SUFFIX = ".prev"

_CM = None


def _ckpt_metrics():
    global _CM
    if _CM is None:
        r = _metrics.default_registry()
        _CM = {
            "corrupt": r.counter(
                "checkpoint_corrupt_total",
                "checkpoint candidates rejected as corrupt at load"),
            "fallbacks": r.counter(
                "checkpoint_fallbacks_total",
                "loads served from a prior retained version"),
        }
    return _CM


def _to_host(leaf: Any) -> Any:
    if isinstance(leaf, jax.Array):
        return np.asarray(leaf)
    return leaf


def _local_path(uri: str) -> Optional[str]:
    """Filesystem path for local URIs (where rename-atomicity exists)."""
    parsed = URI(uri)
    if parsed.protocol in ("", "file://"):
        return parsed.name
    return None


def _keep_prev(uri: str) -> bool:
    """Retain the previous version?  Default: yes where the copy is free
    (local rename, in-memory), no for remote object stores (it would
    cost a download per save) — ``DMLC_CKPT_KEEP`` overrides both."""
    raw = os.environ.get("DMLC_CKPT_KEEP", "")
    if raw != "":
        return raw.lower() not in ("0", "false", "off", "no")
    return _local_path(uri) is not None or uri.startswith("mem://")


class _CrcStream(Stream):
    """Pass-through Stream accumulating CRC32 of the bytes moved —
    resettable, so one wrapper yields per-leaf checksums."""

    def __init__(self, inner: Stream):
        self._inner = inner
        self.crc = 0

    def reset(self) -> None:
        self.crc = 0

    def read(self, nbytes: int) -> bytes:
        data = self._inner.read(nbytes)
        self.crc = zlib.crc32(data, self.crc)
        return data

    def write(self, data: bytes) -> int:
        self.crc = zlib.crc32(bytes(data), self.crc)
        return self._inner.write(data)


def _write_body(stream: Stream, version: int, leaves: List[Any]) -> List[int]:
    """Serialize header + leaf list (byte-identical to the historical
    ``write_obj(list)`` framing) and return one CRC32 per leaf."""
    ser.write_uint32(stream, _MAGIC)
    ser.write_uint64(stream, version)
    stream.write(bytes([ser._TAG_LIST]))
    ser.write_uint64(stream, len(leaves))
    crc = _CrcStream(stream)
    crcs = []
    for leaf in leaves:
        crc.reset()
        ser.write_obj(crc, leaf)
        crcs.append(crc.crc)
    return crcs


def _read_body(stream: Stream,
               crcs: Optional[List[int]]) -> Tuple[int, List[Any]]:
    """Inverse of :func:`_write_body`; validates per-leaf CRCs when a
    sidecar supplied them."""
    magic = ser.read_uint32(stream)
    CHECK(magic == _MAGIC, "checkpoint: bad magic")
    version = ser.read_uint64(stream)
    tag = stream.read_exact(1)[0]
    CHECK(tag == ser._TAG_LIST, "checkpoint: bad payload framing")
    n = ser.read_uint64(stream)
    if crcs is not None:
        CHECK(len(crcs) == n,
              f"checkpoint: sidecar lists {len(crcs)} leaves, file has {n}")
    crc = _CrcStream(stream)
    leaves = []
    for i in range(n):
        crc.reset()
        leaves.append(ser.read_obj(crc))
        if crcs is not None:
            CHECK(crc.crc == crcs[i],
                  f"checkpoint: CRC mismatch on leaf {i}")
    return int(version), leaves


def _write_blob(uri: str, write_fn) -> None:
    """Write through ``write_fn(stream)`` atomically: local URIs go via
    ``<path>.tmp`` + ``os.replace``; other backends commit on close."""
    path = _local_path(uri)
    if path is None:
        stream = Stream.create(uri, "w")
        write_fn(stream)
        stream.close()
        return
    tmp = path + f".tmp.{os.getpid()}"
    try:
        stream = Stream.create(tmp, "w")
        write_fn(stream)
        stream.close()
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _copy_blob(src: str, dst: str) -> bool:
    """Retain ``src`` as ``dst`` (rename locally, byte copy elsewhere).
    Returns False when ``src`` does not exist."""
    spath, dpath = _local_path(src), _local_path(dst)
    if spath is not None and dpath is not None:
        if not os.path.exists(spath):
            return False
        os.replace(spath, dpath)
        return True
    s = Stream.create(src, "r", allow_null=True)
    if s is None:
        return False
    data = s.read_all()
    s.close()
    _write_blob(dst, lambda out: out.write(data))
    return True


def _read_sidecar(uri: str) -> Optional[List[int]]:
    """Leaf CRCs from ``<uri>.crc`` — ``None`` when absent (pre-sidecar
    checkpoint: skip validation); raises on a garbled sidecar (treated
    as corruption by the caller)."""
    s = Stream.create(uri + _CRC_SUFFIX, "r", allow_null=True)
    if s is None:
        return None
    try:
        doc = json.loads(s.read_all())
    finally:
        s.close()
    crcs = doc["leaf_crcs"]
    CHECK(isinstance(crcs, list), "checkpoint: bad sidecar")
    return [int(c) for c in crcs]


def _corrupt_blob(uri: str) -> None:
    """``checkpoint:corrupt`` fault: flip one mid-file byte post-commit."""
    path = _local_path(uri)
    if path is not None:
        with open(path, "r+b") as f:
            size = os.path.getsize(path)
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([b[0] ^ 0xFF]))
        return
    s = Stream.create(uri, "r", allow_null=True)
    if s is None:
        return
    data = bytearray(s.read_all())
    s.close()
    data[len(data) // 2] ^= 0xFF
    with Stream.create(uri, "w") as out:
        out.write(bytes(data))


def checkpoint(uri: str, state: Any, version: int = 0, sharded: bool = False,
               local: bool = False) -> None:
    """Save a pytree of arrays/scalars.  Reference: rabit ``CheckPoint``.

    ``sharded=True`` writes one file per process (``uri.shard-K-of-N``),
    each holding only locally-addressable shard data — the multi-host path
    where no single host can materialize the full arrays.

    ``local=True`` skips the collective semantics entirely (no rank-0
    election, no barrier): THIS caller writes ``uri`` as given.  The
    elastic recovery layer uses it for per-rank round-versioned commit
    files, where every worker must write its own file without dragging a
    collective into the commit path (a dying peer would wedge it).

    The write is crash-safe: payload lands in a temp file (or a commit-
    on-close backend stream) and only a complete write replaces ``uri``;
    with retention on (see ``DMLC_CKPT_KEEP``) the replaced version
    survives as ``uri + ".prev"`` for corruption fallback.
    """
    if local:
        payload = jax.tree.map(_to_host, state)
        payload = jax.tree.flatten(payload)[0]
    elif sharded and coll.world_size() > 1:
        uri = f"{uri}.shard-{coll.rank()}-of-{coll.world_size()}"
        leaves, treedef = jax.tree.flatten(state)
        host_leaves = []
        for leaf in leaves:
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                shards = sorted(leaf.addressable_shards, key=lambda s: s.index)
                host_leaves.append([(str(s.index), np.asarray(s.data)) for s in shards])
            else:
                host_leaves.append(_to_host(leaf))
        payload = host_leaves
    else:
        if coll.world_size() > 1 and coll.rank() != 0:
            # symmetric by construction: non-root ranks barrier here and
            # return, rank 0 barriers at the end of the write path below
            # — every rank reaches exactly one "ckpt" barrier
            coll.barrier("ckpt")  # dmlcheck: off:collective-discipline
            return  # replicated state: rank 0 writes
        payload = jax.tree.map(_to_host, state)
        payload = jax.tree.flatten(payload)[0]

    if _keep_prev(uri):
        # the current version becomes the fallback BEFORE anything is
        # replaced; its sidecar must travel with it
        if _copy_blob(uri, uri + _PREV_SUFFIX):
            _copy_blob(uri + _CRC_SUFFIX, uri + _PREV_SUFFIX + _CRC_SUFFIX)

    crcs: List[int] = []

    def _write(stream: Stream) -> None:
        crcs.extend(_write_body(stream, version, payload))
        fault = _fi.check("checkpoint", ctx=uri)
        if fault is not None:
            if fault.kind == "kill":
                os.kill(os.getpid(), signal.SIGKILL)
            if fault.kind in ("abort", "error"):
                raise IOError(f"fault injected: checkpoint abort ({uri})")

    _write_blob(uri, _write)
    _write_blob(uri + _CRC_SUFFIX, lambda s: s.write(json.dumps(
        {"version": version, "algo": "crc32", "leaf_crcs": crcs}).encode()))

    fault = _fi.check("checkpoint-post", ctx=uri)
    if fault is not None and fault.kind == "corrupt":
        _corrupt_blob(uri)

    if coll.world_size() > 1 and not sharded and not local:
        coll.barrier("ckpt")


def _rebuild(payload: List[Any], like: Any) -> Any:
    """Reassemble a leaf payload into the structure/sharding of ``like``."""
    leaves, treedef = jax.tree.flatten(like)
    CHECK(len(payload) == len(leaves), "checkpoint: leaf count mismatch")
    out_leaves = []
    for saved, ref in zip(payload, leaves):
        if isinstance(saved, list) and saved and isinstance(saved[0], tuple):
            # sharded leaf: reassemble only this process's shards into the
            # reference sharding via device_put per shard
            CHECK(isinstance(ref, jax.Array), "checkpoint: sharded leaf vs non-array ref")
            arrays = {idx: data for idx, data in saved}
            shards = []
            for s in sorted(ref.addressable_shards, key=lambda s: s.index):
                data = arrays.get(str(s.index))
                CHECK(data is not None, "checkpoint: missing shard")
                shards.append(jax.device_put(data, s.device))
            out_leaves.append(
                jax.make_array_from_single_device_arrays(ref.shape, ref.sharding, shards)
            )
        elif isinstance(ref, jax.Array):
            out_leaves.append(jax.device_put(np.asarray(saved), ref.sharding))
        else:
            out_leaves.append(saved)
    return jax.tree.unflatten(treedef, out_leaves)


def load_checkpoint(uri: str, like: Any, sharded: bool = False) -> Tuple[int, Any]:
    """Load a checkpoint into the structure of ``like``.

    Returns ``(version, state)``; ``(0, like)`` when no checkpoint exists —
    rabit's ``LoadCheckPoint`` contract for cold starts.

    Corruption recovery: a primary that fails magic/framing/CRC/leaf
    validation is rejected (``dmlc_checkpoint_corrupt_total``) and the
    newest valid prior version (``uri + ".prev"``) is served instead
    (``dmlc_checkpoint_fallbacks_total``); only when every candidate is
    corrupt does the load raise.
    """
    if sharded and coll.world_size() > 1:
        uri = f"{uri}.shard-{coll.rank()}-of-{coll.world_size()}"
    first_error: Optional[BaseException] = None
    any_present = False
    for idx, cand in enumerate((uri, uri + _PREV_SUFFIX)):
        stream = Stream.create(cand, "r", allow_null=True)
        if stream is None:
            continue
        any_present = True
        try:
            try:
                crcs = _read_sidecar(cand)
                version, payload = _read_body(stream, crcs)
            finally:
                stream.close()
            state = _rebuild(payload, like)
        except Exception as e:  # noqa: BLE001 — any parse failure = corrupt
            if _metrics.enabled():
                _ckpt_metrics()["corrupt"].inc(1)
            LOG("WARNING", "checkpoint %s: corrupt (%s: %s)%s", cand,
                type(e).__name__, e,
                "; trying prior version" if idx == 0 else "")
            if first_error is None:
                first_error = e
            continue
        if idx > 0:
            if _metrics.enabled():
                _ckpt_metrics()["fallbacks"].inc(1)
            LOG("WARNING", "checkpoint %s: recovered from prior version "
                "%s (v%d)", uri, cand, version)
        return version, state
    if not any_present:
        return 0, like
    raise Error(f"checkpoint {uri}: no valid version "
                f"(last error: {first_error})")
