"""MXNet-KVStore-shaped API over XLA collectives.

Reference parity: dmlc-core bootstraps ps-lite's parameter server
(``PSTracker`` env ABI: ``DMLC_PS_ROOT_URI/PORT``, ``DMLC_ROLE`` —
SURVEY.md §2c); the KVStore itself lived in MXNet/ps-lite.  This module
provides the consumer-facing surface (``init/push/pull``, ``dist_sync``)
so KVStore-based training loops port unchanged — but there are no servers:

* ``local``: single-process store (values live as jax.Arrays on device).
* ``dist_sync``: push accumulates local gradients; pull returns the value
  after a cross-worker allreduce of pending gradients and an optimizer
  update — the parameter-server round-trip collapsed onto one XLA
  AllReduce over ICI/DCN (the north-star replacement of PS/NCCL traffic;
  BASELINE config 4).

For gradient sync *inside* a jitted train step, use
``collectives.device_allreduce`` / shard_map psum directly; this class is
the between-step host API.
"""

from __future__ import annotations

import hashlib
from functools import lru_cache
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp

from dmlc_core_tpu.base.logging import CHECK, log_fatal
from dmlc_core_tpu.base.parameter import get_env
from dmlc_core_tpu.parallel import collectives as coll

__all__ = ["KVStore"]

Key = Union[int, str]


@lru_cache(maxsize=None)
def _fused_mesh_reducer(mesh, axis):
    """Jitted fused gradient sync: tuple of [W, sz] arrays (sharded on
    ``axis`` along dim 0) → tuple of [sz] reduced arrays.  Concatenate,
    one psum, split — all inside one XLA program, so a whole fusion
    bucket costs a single dispatch and a single collective.  The factory
    is lru_cached so repeated calls return the SAME jitted callable
    (jax's dispatch cache is keyed on function identity — a fresh jit
    object per pull would retrace and recompile every training step);
    within it jax.jit caches per bucket composition (shapes tuple)."""
    from functools import partial

    from dmlc_core_tpu.base.compat import shard_map
    from jax.sharding import PartitionSpec as P

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(P(axis),), out_specs=P(),
             check_vma=False)
    def _reduce(flats):
        cat = jnp.concatenate([jnp.sum(f, axis=0) for f in flats])
        red = jax.lax.psum(cat, axis)
        out = []
        off = 0
        for f in flats:
            out.append(red[off:off + f.shape[1]])
            off += f.shape[1]
        return tuple(out)

    return _reduce


class KVStore:
    """``KVStore.create("local" | "dist_sync")`` — init/push/pull.

    The optimizer hook (``set_updater``) matches MXNet's contract:
    ``updater(key, grad, value) -> new_value``; default is SGD with
    ``learning_rate`` (so push/pull alone implements dist-SGD).
    """

    def __init__(self, kv_type: str = "local", learning_rate: float = 0.1,
                 mesh: Optional[Any] = None, axis: str = "data",
                 bucket_bytes: int = 64 << 20):
        CHECK(kv_type in ("local", "dist_sync"), f"unknown kvstore type {kv_type!r}")
        self.type = kv_type
        self._store: Dict[Key, jax.Array] = {}
        self._pending: Dict[Key, jax.Array] = {}
        self._lr = learning_rate
        # in-mesh dist_sync: "workers" are the shards along ``axis`` of
        # ``mesh``; pushed values carry a leading worker dim sharded on
        # that axis and pull reduces it with one XLA AllReduce (config 4)
        self._mesh = mesh
        self._axis = axis
        #: gradient-fusion bucket cap (bytes): pending keys in one pull
        #: batch are flattened and concatenated up to this size per
        #: collective — ps-lite/Horovod-style fusion, so a BERT-sized
        #: model syncs in O(1) allreduces per step instead of O(keys)
        self._bucket_bytes = bucket_bytes
        #: observability for tests/benches: collective launches vs keys
        self.stats = {"sync_calls": 0, "keys_synced": 0}
        # bounded-staleness recovery (rabit's round-version protocol
        # applied to the PS surface): see enable_recovery()
        self._rec_uri: Optional[str] = None
        self._rec_stride = 0
        self._pull_rounds = 0
        self._updater: Callable[[Key, jax.Array, jax.Array], jax.Array] = (
            lambda key, grad, value: value - self._lr * grad
        )

    @staticmethod
    def create(kv_type: str = "local", **kw: Any) -> "KVStore":
        return KVStore(kv_type, **kw)

    # -- MXNet KVStore surface -------------------------------------------
    def init(self, keys: Union[Key, Sequence[Key]], values: Any) -> None:
        """Register initial values.  In dist_sync mode rank 0's value wins
        (broadcast), matching KVStore semantics."""
        keys, values = self._normalize(keys, values)
        for k, v in zip(keys, values):
            if k in self._store:
                log_fatal(f"KVStore.init: key {k!r} already initialized")
            v = np.asarray(v)
            if self.type == "dist_sync":
                v = coll.broadcast(v, root=0)
            self._store[k] = jnp.asarray(v)

    def push(self, keys: Union[Key, Sequence[Key]], grads: Any) -> None:
        """Accumulate gradients (summed over multiple pushes per key)."""
        keys, grads = self._normalize(keys, grads)
        for k, g in zip(keys, grads):
            self._check_key(k)
            g = jnp.asarray(g)
            self._pending[k] = self._pending[k] + g if k in self._pending else g

    def pull(self, keys: Union[Key, Sequence[Key]]) -> Union[jax.Array, List[jax.Array]]:
        """Sync pending gradients (allreduce across workers in dist_sync),
        apply the updater, return current value(s).

        All pending keys in the batch sync TOGETHER: flattened,
        concatenated into ≤ ``bucket_bytes`` fusion buckets (grouped by
        dtype) and allreduced as one collective per bucket — a BERT-base
        pull of a few hundred keys costs ~1 AllReduce launch instead of
        hundreds of small ones (what ps-lite's message batching and
        Horovod's fusion buffer do; BASELINE config 4's bus-bandwidth
        target is unreachable with per-key launches).  Workers must pull
        the same key batch in the same order — the same contract MXNet's
        dist_sync KVStore imposes.
        """
        single = not isinstance(keys, (list, tuple))
        key_list: List[Key] = [keys] if single else list(keys)
        for k in key_list:
            self._check_key(k)
        # dedupe while keeping order: a key listed twice syncs once and
        # both positions return the updated value (old per-key behavior)
        pend = list(dict.fromkeys(k for k in key_list
                                  if k in self._pending))
        grads = {k: self._pending.pop(k) for k in pend}
        if self.type == "dist_sync" and grads:
            grads = self._sync_bucketed(grads)
        for k in pend:
            self._store[k] = self._updater(k, grads[k], self._store[k])
        if pend:
            self._pull_rounds += 1
            if (self._rec_uri and self._rec_stride
                    and self._pull_rounds % self._rec_stride == 0):
                self._snapshot()
        out = [self._store[k] for k in key_list]
        return out[0] if single else out

    # -- bounded-staleness recovery (ps-lite's role, rabit's protocol) ---
    def enable_recovery(self, uri: str, stride: Optional[int] = None) -> None:
        """Round-versioned store snapshots every ``stride`` gradient-
        applying pulls (default ``DMLC_RECOVERY_STRIDE``), through the
        atomic CRC'd checkpoint writer — the bounded-staleness recovery
        mode for GBLinear/FM parameter-server training: a restarted
        worker :meth:`restore_recovery`-s at most ``stride`` updates
        behind the last applied state.  Only rank 0 writes (values are
        identical on every worker after the allreduce); the write is
        ``local`` (no barrier), so a dying peer can never wedge a
        snapshot — that is what keeps the staleness *bounded* instead
        of synchronous.
        """
        if stride is None:
            from dmlc_core_tpu.base import knobs as _knobs

            stride = int(_knobs.value("DMLC_RECOVERY_STRIDE"))
        CHECK(stride >= 1, f"recovery stride must be >= 1, got {stride}")
        self._rec_uri = uri
        self._rec_stride = stride

    def _snapshot(self) -> None:
        from dmlc_core_tpu.parallel.checkpoint import checkpoint

        if coll.rank() == 0:
            state = {str(k): np.asarray(v) for k, v in self._store.items()}
            checkpoint(self._rec_uri, state, version=self._pull_rounds,
                       local=True)

    def restore_recovery(self, uri: Optional[str] = None) -> int:
        """Load the newest snapshot into the store (keys must already be
        :meth:`init`-ed — shapes/dtypes come from the live values).
        Returns the snapshot's pull-round version, 0 when none exists;
        the caller replays at most ``stride`` pulls of updates."""
        from dmlc_core_tpu.parallel.checkpoint import load_checkpoint

        uri = uri or self._rec_uri
        CHECK(uri is not None, "restore_recovery: no snapshot URI")
        like = {str(k): np.asarray(self._store[k]) for k in self._store}
        version, state = load_checkpoint(uri, like)
        if version:
            by_name = {str(k): k for k in self._store}
            for name, value in state.items():
                self._store[by_name[name]] = jnp.asarray(value)
            self._pull_rounds = int(version)
        return int(version)

    def _sync_bucketed(self, grads: Dict[Key, jax.Array]) -> Dict[Key, jax.Array]:
        """Allreduce pending grads in fused buckets; returns synced grads."""
        in_mesh = self._mesh is not None
        if not in_mesh and coll.world_size() <= 1:
            return grads
        if not in_mesh and get_env("DMLC_KVSTORE_CHECK", 0, int):
            # Fused pull is only correct when every worker pulls the
            # identical key batch in the identical order (the documented
            # dist_sync contract); a skewed batch would silently
            # concatenate mismatched buckets and corrupt every gradient
            # in them.  Under the debug flag, cross-check a digest of the
            # (key, shape, dtype) sequence before reducing: two tiny
            # collectives, fail-fast on divergence.
            sig = repr([(str(k), tuple(jnp.asarray(grads[k]).shape),
                         str(jnp.asarray(grads[k]).dtype)) for k in grads])
            h = np.array([int.from_bytes(
                hashlib.sha1(sig.encode()).digest()[:8], "big") >> 1],
                np.int64)
            if (coll.allreduce(h, "min")[0] != coll.allreduce(h, "max")[0]):
                log_fatal(
                    "KVStore dist_sync: workers pulled DIFFERENT key "
                    f"batches (rank {coll.rank()} batch signature differs); "
                    "fused bucketing requires identical pull order on "
                    f"every worker. Local batch: {sig[:500]}")
        out: Dict[Key, jax.Array] = {}

        def flush(bucket: List[Key]) -> None:
            if not bucket:
                return
            self.stats["sync_calls"] += 1
            self.stats["keys_synced"] += len(bucket)
            if in_mesh:
                # mesh grads carry a leading worker dim sharded on the
                # axis: flatten per key to [W, sz] and run concat → psum
                # → split as ONE jitted shard_map program (one XLA
                # AllReduce, no per-key dispatches — eager concat/split
                # would reintroduce O(keys) launches and measured SLOWER
                # than per-key sync on the CPU proxy)
                flat = tuple(jnp.reshape(grads[k], (grads[k].shape[0], -1))
                             for k in bucket)
                red = _fused_mesh_reducer(self._mesh, self._axis)(flat)
                for k, r in zip(bucket, red):
                    out[k] = jnp.reshape(r, grads[k].shape[1:])
            else:
                flat_np = [np.asarray(grads[k]).ravel() for k in bucket]
                red_np = coll.allreduce(np.concatenate(flat_np), "sum")
                off = 0
                for k, f in zip(bucket, flat_np):
                    out[k] = jnp.asarray(
                        red_np[off:off + f.size].reshape(
                            np.asarray(grads[k]).shape))
                    off += f.size

        by_dtype: Dict[Any, List[Key]] = {}
        for k in grads:                     # batch order = caller's order
            by_dtype.setdefault(jnp.asarray(grads[k]).dtype, []).append(k)
        for _dtype, kg in by_dtype.items():
            bucket: List[Key] = []
            size = 0
            for k in kg:
                g = grads[k]
                # mesh grads carry a leading worker dim that the program
                # reduces away — the fused payload per collective is the
                # per-worker size, so that is what the cap must count
                shape = g.shape[1:] if in_mesh else g.shape
                nbytes = (int(np.prod(shape))
                          * jnp.asarray(g).dtype.itemsize)
                if bucket and size + nbytes > self._bucket_bytes:
                    flush(bucket)
                    bucket, size = [], 0
                bucket.append(k)
                size += nbytes
            flush(bucket)
        return out

    def set_updater(self, updater: Callable[[Key, jax.Array, jax.Array], jax.Array]) -> None:
        self._updater = updater

    @property
    def rank(self) -> int:
        return coll.rank()

    @property
    def num_workers(self) -> int:
        return coll.world_size()

    # -- helpers ---------------------------------------------------------
    def _check_key(self, k: Key) -> None:
        if k not in self._store:
            log_fatal(f"KVStore: key {k!r} not initialized")

    @staticmethod
    def _normalize(keys, values):
        if isinstance(keys, (list, tuple)):
            CHECK(isinstance(values, (list, tuple)) and len(keys) == len(values),
                  "KVStore: keys/values length mismatch")
            return list(keys), list(values)
        return [keys], [values]
