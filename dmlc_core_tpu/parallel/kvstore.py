"""MXNet-KVStore-shaped API over XLA collectives.

Reference parity: dmlc-core bootstraps ps-lite's parameter server
(``PSTracker`` env ABI: ``DMLC_PS_ROOT_URI/PORT``, ``DMLC_ROLE`` —
SURVEY.md §2c); the KVStore itself lived in MXNet/ps-lite.  This module
provides the consumer-facing surface (``init/push/pull``, ``dist_sync``)
so KVStore-based training loops port unchanged — but there are no servers:

* ``local``: single-process store (values live as jax.Arrays on device).
* ``dist_sync``: push accumulates local gradients; pull returns the value
  after a cross-worker allreduce of pending gradients and an optimizer
  update — the parameter-server round-trip collapsed onto one XLA
  AllReduce over ICI/DCN (the north-star replacement of PS/NCCL traffic;
  BASELINE config 4).
* ``dist_async``: REAL parameter-server processes (``parallel/ps``):
  key-range-sharded servers with server-side SGD, pipelined async push
  and bounded-staleness pull (SSP).  ``create("dist_async")`` reads the
  ``DMLC_ROLE`` env ABI — server/scheduler roles run their service loop
  to completion, workers get a :class:`DistAsyncKVStore` whose
  init/push/pull drop into existing KVStore loops unchanged.

For gradient sync *inside* a jitted train step, use
``collectives.device_allreduce`` / shard_map psum directly; this class is
the between-step host API.
"""

from __future__ import annotations

import hashlib
from functools import lru_cache
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp

from dmlc_core_tpu.base.logging import CHECK, log_fatal
from dmlc_core_tpu.base.parameter import get_env
from dmlc_core_tpu.parallel import collectives as coll

__all__ = ["KVStore", "DistAsyncKVStore"]

Key = Union[int, str]


@lru_cache(maxsize=None)
def _fused_mesh_reducer(mesh, axis):
    """Jitted fused gradient sync: tuple of [W, sz] arrays (sharded on
    ``axis`` along dim 0) → tuple of [sz] reduced arrays.  Concatenate,
    one psum, split — all inside one XLA program, so a whole fusion
    bucket costs a single dispatch and a single collective.  The factory
    is lru_cached so repeated calls return the SAME jitted callable
    (jax's dispatch cache is keyed on function identity — a fresh jit
    object per pull would retrace and recompile every training step);
    within it jax.jit caches per bucket composition (shapes tuple)."""
    from functools import partial

    from dmlc_core_tpu.base.compat import shard_map
    from jax.sharding import PartitionSpec as P

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(P(axis),), out_specs=P(),
             check_vma=False)
    def _reduce(flats):
        cat = jnp.concatenate([jnp.sum(f, axis=0) for f in flats])
        red = jax.lax.psum(cat, axis)
        out = []
        off = 0
        for f in flats:
            out.append(red[off:off + f.shape[1]])
            off += f.shape[1]
        return tuple(out)

    return _reduce


@lru_cache(maxsize=None)
def _fused_mesh_updater(mesh, axis, lr):
    """Fully-fused dist_sync pull for the default SGD updater: tuples
    of [W, *shape] pending grads (sharded on ``axis``) plus the current
    values → updated values, ONE jitted program per fusion bucket.  The
    reduce runs the exact op sequence of :func:`_fused_mesh_reducer`
    (per-key worker-dim sum, concat once, one psum, split) and the
    ``value - lr * grad`` update happens inside the same trace — so a
    pull batch costs a single dispatch instead of O(keys) eager
    reshape/mul/sub launches round-tripping through the host dispatch
    path.  The bucket still syncs as ONE collective: ``psum`` over the
    tuple of per-key partial sums lowers to a single variadic
    AllReduce, keeping the concat-once launch discipline WITHOUT
    materializing the concatenated buffer (the copy dominated the old
    program's runtime — measured ~1.7x slower than the tree form on
    the CPU proxy).  ``owned`` carries store-owned accumulation
    buffers and is DONATED (XLA may reuse their memory); ``borrowed``
    holds first-push arrays the caller may still reference — donating
    those would invalidate the caller's buffers mid-training-loop.
    ``lr`` is part of the cache key so it stays a Python-float
    constant in the trace, keeping the arithmetic (and its weak-type
    promotion) identical to the eager updater expression.  Results are
    bitwise identical to the pre-fusion reduce+update pipeline
    (tests/test_ps.py asserts it)."""
    from functools import partial

    from dmlc_core_tpu.base.compat import donate_argnums, shard_map
    from jax.sharding import PartitionSpec as P

    @partial(jax.jit, donate_argnums=donate_argnums(0))
    @partial(shard_map, mesh=mesh,
             in_specs=(P(axis), P(axis), P()), out_specs=P(),
             check_vma=False)
    def _update(owned, borrowed, values):
        grads = tuple(owned) + tuple(borrowed)
        red = jax.lax.psum(tuple(jnp.sum(g, axis=0) for g in grads),
                           axis)
        return tuple(v - lr * r for r, v in zip(red, values))

    return _update


class KVStore:
    """``KVStore.create("local" | "dist_sync")`` — init/push/pull.

    The optimizer hook (``set_updater``) matches MXNet's contract:
    ``updater(key, grad, value) -> new_value``; default is SGD with
    ``learning_rate`` (so push/pull alone implements dist-SGD).
    """

    def __init__(self, kv_type: str = "local", learning_rate: float = 0.1,
                 mesh: Optional[Any] = None, axis: str = "data",
                 bucket_bytes: int = 64 << 20):
        CHECK(kv_type in ("local", "dist_sync", "dist_async"),
              f"unknown kvstore type {kv_type!r}")
        self.type = kv_type
        self._store: Dict[Key, jax.Array] = {}
        self._pending: Dict[Key, jax.Array] = {}
        # pending buffers WE allocated (push accumulation results) —
        # safe to donate into the fused reducer; absent keys hold the
        # caller's own array from a single push (never donated)
        self._owned: set = set()
        self._lr = learning_rate
        # in-mesh dist_sync: "workers" are the shards along ``axis`` of
        # ``mesh``; pushed values carry a leading worker dim sharded on
        # that axis and pull reduces it with one XLA AllReduce (config 4)
        self._mesh = mesh
        self._axis = axis
        #: gradient-fusion bucket cap (bytes): pending keys in one pull
        #: batch are flattened and concatenated up to this size per
        #: collective — ps-lite/Horovod-style fusion, so a BERT-sized
        #: model syncs in O(1) allreduces per step instead of O(keys)
        self._bucket_bytes = bucket_bytes
        #: observability for tests/benches: collective launches vs keys
        self.stats = {"sync_calls": 0, "keys_synced": 0}
        # bounded-staleness recovery (rabit's round-version protocol
        # applied to the PS surface): see enable_recovery()
        self._rec_uri: Optional[str] = None
        self._rec_stride = 0
        self._pull_rounds = 0
        # the fully-fused pull path folds the DEFAULT SGD update into
        # the reduction program; a custom updater flips this and falls
        # back to fused-reduce + eager per-key updates
        self._custom_updater = False
        self._updater: Callable[[Key, jax.Array, jax.Array], jax.Array] = (
            lambda key, grad, value: value - self._lr * grad
        )

    @staticmethod
    def create(kv_type: str = "local", **kw: Any) -> "KVStore":
        if kv_type == "dist_async":
            from dmlc_core_tpu.base import knobs as _knobs
            from dmlc_core_tpu.parallel import ps as _ps

            client = kw.pop("client", None)
            if client is None:
                role = str(_knobs.value("DMLC_ROLE"))
                if role != "worker":
                    _ps.run_role(role)     # serves to completion, exits
                client = _ps.run_role("worker")
            return DistAsyncKVStore(client, **kw)
        return KVStore(kv_type, **kw)

    # -- MXNet KVStore surface -------------------------------------------
    def init(self, keys: Union[Key, Sequence[Key]], values: Any) -> None:
        """Register initial values.  In dist_sync mode rank 0's value wins
        (broadcast), matching KVStore semantics — the whole init list
        rides ONE broadcast (the values byte-concatenated and split
        back), so a model-sized init costs a single collective round
        trip instead of one per key."""
        keys, values = self._normalize(keys, values)
        seen: set = set()
        for k in keys:
            if k in self._store or k in seen:
                log_fatal(f"KVStore.init: key {k!r} already initialized")
            seen.add(k)
        vals = [np.asarray(v) for v in values]
        if self.type == "dist_sync" and vals:
            blob = np.concatenate(
                [v.ravel().view(np.uint8) for v in vals]
            ) if any(v.size for v in vals) else np.zeros(0, np.uint8)
            blob = np.asarray(coll.broadcast(blob, root=0))
            off = 0
            for k, v in zip(keys, vals):
                n = v.nbytes
                self._store[k] = jnp.asarray(np.frombuffer(
                    blob[off:off + n].tobytes(), v.dtype).reshape(v.shape))
                off += n
        else:
            for k, v in zip(keys, vals):
                self._store[k] = jnp.asarray(v)

    def push(self, keys: Union[Key, Sequence[Key]], grads: Any) -> None:
        """Accumulate gradients (summed over multiple pushes per key)."""
        keys, grads = self._normalize(keys, grads)
        for k, g in zip(keys, grads):
            self._check_key(k)
            g = jnp.asarray(g)
            if k in self._pending:
                # the sum allocates a buffer only we reference — mark
                # it donatable for the fused pull
                self._pending[k] = self._pending[k] + g
                self._owned.add(k)
            else:
                self._pending[k] = g

    def pull(self, keys: Union[Key, Sequence[Key]]) -> Union[jax.Array, List[jax.Array]]:
        """Sync pending gradients (allreduce across workers in dist_sync),
        apply the updater, return current value(s).

        All pending keys in the batch sync TOGETHER: flattened,
        concatenated into ≤ ``bucket_bytes`` fusion buckets (grouped by
        dtype) and allreduced as one collective per bucket — a BERT-base
        pull of a few hundred keys costs ~1 AllReduce launch instead of
        hundreds of small ones (what ps-lite's message batching and
        Horovod's fusion buffer do; BASELINE config 4's bus-bandwidth
        target is unreachable with per-key launches).  Workers must pull
        the same key batch in the same order — the same contract MXNet's
        dist_sync KVStore imposes.
        """
        single = not isinstance(keys, (list, tuple))
        key_list: List[Key] = [keys] if single else list(keys)
        for k in key_list:
            self._check_key(k)
        # dedupe while keeping order: a key listed twice syncs once and
        # both positions return the updated value (old per-key behavior)
        pend = list(dict.fromkeys(k for k in key_list
                                  if k in self._pending))
        grads = {k: self._pending.pop(k) for k in pend}
        owned = {k for k in pend if k in self._owned}
        self._owned -= owned
        if (self.type == "dist_sync" and grads
                and self._mesh is not None and not self._custom_updater
                and all(jnp.issubdtype(jnp.asarray(g).dtype, jnp.floating)
                        for g in grads.values())):
            # flats never leave the device: reduce + SGD update fused
            # into one program per bucket, pending buffers donated
            self._fused_pull_update(grads, owned)
        else:
            if self.type == "dist_sync" and grads:
                grads = self._sync_bucketed(grads)
            for k in pend:
                self._store[k] = self._updater(k, grads[k], self._store[k])
        if pend:
            self._pull_rounds += 1
            if (self._rec_uri and self._rec_stride
                    and self._pull_rounds % self._rec_stride == 0):
                self._snapshot()
        out = [self._store[k] for k in key_list]
        return out[0] if single else out

    # -- bounded-staleness recovery (ps-lite's role, rabit's protocol) ---
    def enable_recovery(self, uri: str, stride: Optional[int] = None) -> None:
        """Round-versioned store snapshots every ``stride`` gradient-
        applying pulls (default ``DMLC_RECOVERY_STRIDE``), through the
        atomic CRC'd checkpoint writer — the bounded-staleness recovery
        mode for GBLinear/FM parameter-server training: a restarted
        worker :meth:`restore_recovery`-s at most ``stride`` updates
        behind the last applied state.  Only rank 0 writes (values are
        identical on every worker after the allreduce); the write is
        ``local`` (no barrier), so a dying peer can never wedge a
        snapshot — that is what keeps the staleness *bounded* instead
        of synchronous.
        """
        if stride is None:
            from dmlc_core_tpu.base import knobs as _knobs

            stride = int(_knobs.value("DMLC_RECOVERY_STRIDE"))
        CHECK(stride >= 1, f"recovery stride must be >= 1, got {stride}")
        self._rec_uri = uri
        self._rec_stride = stride

    def _snapshot(self) -> None:
        from dmlc_core_tpu.parallel.checkpoint import checkpoint

        if coll.rank() == 0:
            state = {str(k): np.asarray(v) for k, v in self._store.items()}
            checkpoint(self._rec_uri, state, version=self._pull_rounds,
                       local=True)

    def restore_recovery(self, uri: Optional[str] = None) -> int:
        """Load the newest snapshot into the store (keys must already be
        :meth:`init`-ed — shapes/dtypes come from the live values).
        Returns the snapshot's pull-round version, 0 when none exists;
        the caller replays at most ``stride`` pulls of updates."""
        from dmlc_core_tpu.parallel.checkpoint import load_checkpoint

        uri = uri or self._rec_uri
        CHECK(uri is not None, "restore_recovery: no snapshot URI")
        like = {str(k): np.asarray(self._store[k]) for k in self._store}
        version, state = load_checkpoint(uri, like)
        if version:
            by_name = {str(k): k for k in self._store}
            for name, value in state.items():
                self._store[by_name[name]] = jnp.asarray(value)
            self._pull_rounds = int(version)
        return int(version)

    def _sync_bucketed(self, grads: Dict[Key, jax.Array]) -> Dict[Key, jax.Array]:
        """Allreduce pending grads in fused buckets; returns synced grads."""
        in_mesh = self._mesh is not None
        if not in_mesh and coll.world_size() <= 1:
            return grads
        if not in_mesh and get_env("DMLC_KVSTORE_CHECK", 0, int):
            # Fused pull is only correct when every worker pulls the
            # identical key batch in the identical order (the documented
            # dist_sync contract); a skewed batch would silently
            # concatenate mismatched buckets and corrupt every gradient
            # in them.  Under the debug flag, cross-check a digest of the
            # (key, shape, dtype) sequence before reducing: two tiny
            # collectives, fail-fast on divergence.
            sig = repr([(str(k), tuple(jnp.asarray(grads[k]).shape),
                         str(jnp.asarray(grads[k]).dtype)) for k in grads])
            h = np.array([int.from_bytes(
                hashlib.sha1(sig.encode()).digest()[:8], "big") >> 1],
                np.int64)
            if (coll.allreduce(h, "min")[0] != coll.allreduce(h, "max")[0]):
                log_fatal(
                    "KVStore dist_sync: workers pulled DIFFERENT key "
                    f"batches (rank {coll.rank()} batch signature differs); "
                    "fused bucketing requires identical pull order on "
                    f"every worker. Local batch: {sig[:500]}")
        out: Dict[Key, jax.Array] = {}

        def flush(bucket: List[Key]) -> None:
            if not bucket:
                return
            self.stats["sync_calls"] += 1
            self.stats["keys_synced"] += len(bucket)
            if in_mesh:
                # mesh grads carry a leading worker dim sharded on the
                # axis: flatten per key to [W, sz] and run concat → psum
                # → split as ONE jitted shard_map program (one XLA
                # AllReduce, no per-key dispatches — eager concat/split
                # would reintroduce O(keys) launches and measured SLOWER
                # than per-key sync on the CPU proxy)
                flat = tuple(jnp.reshape(grads[k], (grads[k].shape[0], -1))
                             for k in bucket)
                red = _fused_mesh_reducer(self._mesh, self._axis)(flat)
                for k, r in zip(bucket, red):
                    out[k] = jnp.reshape(r, grads[k].shape[1:])
            else:
                flat_np = [np.asarray(grads[k]).ravel() for k in bucket]
                red_np = coll.allreduce(np.concatenate(flat_np), "sum")
                off = 0
                for k, f in zip(bucket, flat_np):
                    out[k] = jnp.asarray(
                        red_np[off:off + f.size].reshape(
                            np.asarray(grads[k]).shape))
                    off += f.size

        for bucket in self._fusion_buckets(grads, in_mesh):
            flush(bucket)
        return out

    def _fusion_buckets(self, grads: Dict[Key, jax.Array],
                        in_mesh: bool) -> List[List[Key]]:
        """Group pending keys into dtype-homogeneous fusion buckets of
        at most ``bucket_bytes``, preserving the caller's batch order
        within each dtype group."""
        buckets: List[List[Key]] = []
        by_dtype: Dict[Any, List[Key]] = {}
        for k in grads:                     # batch order = caller's order
            by_dtype.setdefault(jnp.asarray(grads[k]).dtype, []).append(k)
        for _dtype, kg in by_dtype.items():
            bucket: List[Key] = []
            size = 0
            for k in kg:
                g = grads[k]
                # mesh grads carry a leading worker dim that the program
                # reduces away — the fused payload per collective is the
                # per-worker size, so that is what the cap must count
                shape = g.shape[1:] if in_mesh else g.shape
                nbytes = (int(np.prod(shape))
                          * jnp.asarray(g).dtype.itemsize)
                if bucket and size + nbytes > self._bucket_bytes:
                    buckets.append(bucket)
                    bucket, size = [], 0
                bucket.append(k)
                size += nbytes
            if bucket:
                buckets.append(bucket)
        return buckets

    def _fused_pull_update(self, grads: Dict[Key, jax.Array],
                           owned: set) -> None:
        """The no-host-round-trip dist_sync pull: per fusion bucket,
        ONE jitted program reduces every pending grad and applies the
        default SGD update in the same trace (see
        :func:`_fused_mesh_updater`); store-owned accumulation buffers
        are donated, first-push caller arrays are not."""
        upd = _fused_mesh_updater(self._mesh, self._axis, self._lr)
        for bucket in self._fusion_buckets(grads, in_mesh=True):
            self.stats["sync_calls"] += 1
            self.stats["keys_synced"] += len(bucket)
            ob = [k for k in bucket if k in owned]
            bb = [k for k in bucket if k not in owned]
            new_vals = upd(tuple(grads[k] for k in ob),
                           tuple(grads[k] for k in bb),
                           tuple(self._store[k] for k in ob + bb))
            for k, v in zip(ob + bb, new_vals):
                self._store[k] = v

    def set_updater(self, updater: Callable[[Key, jax.Array, jax.Array], jax.Array]) -> None:
        self._updater = updater
        self._custom_updater = True

    @property
    def rank(self) -> int:
        return coll.rank()

    @property
    def num_workers(self) -> int:
        return coll.world_size()

    # -- helpers ---------------------------------------------------------
    def _check_key(self, k: Key) -> None:
        if k not in self._store:
            log_fatal(f"KVStore: key {k!r} not initialized")

    @staticmethod
    def _normalize(keys, values):
        if isinstance(keys, (list, tuple)):
            CHECK(isinstance(values, (list, tuple)) and len(keys) == len(values),
                  "KVStore: keys/values length mismatch")
            return list(keys), list(values)
        return [keys], [values]


class DistAsyncKVStore(KVStore):
    """The KVStore surface over real parameter-server shards.

    Construct through ``KVStore.create("dist_async")`` (worker role) —
    the dense ``init/push/pull`` surface keeps existing training loops
    unchanged: each key's value is range-sharded on dim 0 across the
    server fleet, push sends this worker's gradient asynchronously
    (server-side SGD applies it on arrival — no accumulate-then-pull
    round like dist_sync), and pull gathers the current weights under
    the bounded-staleness window.  The sparse surface
    (``init_sparse/push_sparse/pull_sparse``) is what web-scale CTR
    uses: only the feature ids a minibatch touched cross the wire.

    The optimizer runs server-side (SGD with this store's
    ``learning_rate``); ``set_updater`` is a hard error rather than a
    silent divergence from dist_sync semantics.
    """

    def __init__(self, client: Any, learning_rate: float = 0.1):
        super().__init__("dist_async", learning_rate=learning_rate)
        self._ps = client
        self._shapes: Dict[Key, tuple] = {}

    @staticmethod
    def _name(k: Key) -> str:
        return f"kv:{k}"

    def _check_key(self, k: Key) -> None:
        if k not in self._shapes:
            log_fatal(f"KVStore: key {k!r} not initialized")

    def init(self, keys: Union[Key, Sequence[Key]], values: Any) -> None:
        """Declare dense keys on the server fleet (idempotent across
        workers: the first worker's value wins, the PS analogue of
        dist_sync's rank-0 broadcast)."""
        keys, values = self._normalize(keys, values)
        for k, v in zip(keys, values):
            if k in self._shapes:
                log_fatal(f"KVStore.init: key {k!r} already initialized")
            v = np.atleast_1d(np.asarray(v))
            self._ps.init(self._name(k), n_keys=v.shape[0],
                          width=v.shape[1:], dtype=v.dtype,
                          lr=self._lr, value=v)
            self._shapes[k] = v.shape

    def init_sparse(self, key: Key, n_keys: int, width: Sequence[int] = (),
                    dtype: Any = np.float32, init_scale: float = 0.0,
                    seed: int = 0) -> None:
        """Declare a sparse (10M+-cardinality) key on the fleet — no
        value ships; the array never materializes whole on any single
        host.  Zeros by default; ``init_scale`` > 0 draws each server's
        slice ~ Normal(0, init_scale) seeded by the key range (FM
        factors need a nonzero start)."""
        if key in self._shapes:
            log_fatal(f"KVStore.init: key {key!r} already initialized")
        self._ps.init(self._name(key), n_keys=n_keys, width=width,
                      dtype=dtype, lr=self._lr, init_scale=init_scale,
                      seed=seed)
        self._shapes[key] = (n_keys,) + tuple(int(w) for w in width)

    def push(self, keys: Union[Key, Sequence[Key]], grads: Any) -> None:
        """Async push of whole-key gradients (applied server-side on
        arrival), then advance this worker's clock — one dense push
        call is one committed SSP round."""
        keys, grads = self._normalize(keys, grads)
        for k, g in zip(keys, grads):
            self._check_key(k)
            g = np.atleast_1d(np.asarray(g))
            ids = np.arange(self._shapes[k][0], dtype=np.int64)
            self._ps.push(self._name(k), ids, g.reshape(self._shapes[k]))
            self.stats["keys_synced"] += 1
        self._ps.tick()

    def push_sparse(self, key: Key, ids: np.ndarray,
                    grads: np.ndarray) -> None:
        """Async push for the touched ids only (the caller ticks the
        clock per minibatch via :meth:`tick`)."""
        self._check_key(key)
        self._ps.push(self._name(key), ids, grads)
        self.stats["keys_synced"] += len(ids)

    def pull(self, keys: Union[Key, Sequence[Key]]
             ) -> Union[jax.Array, List[jax.Array]]:
        """Gather current whole-key weights (staleness-gated)."""
        single = not isinstance(keys, (list, tuple))
        key_list: List[Key] = [keys] if single else list(keys)
        out = []
        for k in key_list:
            self._check_key(k)
            ids = np.arange(self._shapes[k][0], dtype=np.int64)
            v = self._ps.pull(self._name(k), ids)
            out.append(jnp.asarray(v.reshape(self._shapes[k])))
            self.stats["sync_calls"] += 1
        return out[0] if single else out

    def pull_sparse(self, key: Key, ids: np.ndarray) -> np.ndarray:
        """Current values for the touched ids only (staleness-gated)."""
        self._check_key(key)
        return self._ps.pull(self._name(key), ids)

    def tick(self) -> None:
        """Commit one SSP round (sparse-surface callers, once per
        minibatch after its pushes)."""
        self._ps.tick()

    def flush(self) -> None:
        """Drain async pushes (all acked server-side)."""
        self._ps.flush()

    def set_updater(self, updater: Callable[..., Any]) -> None:
        log_fatal("dist_async runs the optimizer server-side (SGD with "
                  "the store's learning_rate); custom updaters are a "
                  "dist_sync/local feature")

    def enable_recovery(self, uri: str, stride: Optional[int] = None) -> None:
        log_fatal("dist_async durability is server-side: set "
                  "DMLC_PS_SNAPSHOT_DIR / DMLC_PS_SNAPSHOT_STRIDE on "
                  "the server processes")

    @property
    def rank(self) -> int:
        return self._ps.rank

    @property
    def num_workers(self) -> int:
        return getattr(self._ps, "nworker", 1)

    @property
    def staleness_samples(self) -> List[int]:
        return self._ps.staleness_samples

    def close(self, shutdown_job: bool = True) -> None:
        """Say bye to the fleet (servers exit once every worker did)."""
        self._ps.close(shutdown_job=shutdown_job)
