"""MXNet-KVStore-shaped API over XLA collectives.

Reference parity: dmlc-core bootstraps ps-lite's parameter server
(``PSTracker`` env ABI: ``DMLC_PS_ROOT_URI/PORT``, ``DMLC_ROLE`` —
SURVEY.md §2c); the KVStore itself lived in MXNet/ps-lite.  This module
provides the consumer-facing surface (``init/push/pull``, ``dist_sync``)
so KVStore-based training loops port unchanged — but there are no servers:

* ``local``: single-process store (values live as jax.Arrays on device).
* ``dist_sync``: push accumulates local gradients; pull returns the value
  after a cross-worker allreduce of pending gradients and an optimizer
  update — the parameter-server round-trip collapsed onto one XLA
  AllReduce over ICI/DCN (the north-star replacement of PS/NCCL traffic;
  BASELINE config 4).

For gradient sync *inside* a jitted train step, use
``collectives.device_allreduce`` / shard_map psum directly; this class is
the between-step host API.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

import jax
import jax.numpy as jnp

from dmlc_core_tpu.base.logging import CHECK, log_fatal
from dmlc_core_tpu.parallel import collectives as coll

__all__ = ["KVStore"]

Key = Union[int, str]


class KVStore:
    """``KVStore.create("local" | "dist_sync")`` — init/push/pull.

    The optimizer hook (``set_updater``) matches MXNet's contract:
    ``updater(key, grad, value) -> new_value``; default is SGD with
    ``learning_rate`` (so push/pull alone implements dist-SGD).
    """

    def __init__(self, kv_type: str = "local", learning_rate: float = 0.1,
                 mesh: Optional[Any] = None, axis: str = "data"):
        CHECK(kv_type in ("local", "dist_sync"), f"unknown kvstore type {kv_type!r}")
        self.type = kv_type
        self._store: Dict[Key, jax.Array] = {}
        self._pending: Dict[Key, jax.Array] = {}
        self._lr = learning_rate
        # in-mesh dist_sync: "workers" are the shards along ``axis`` of
        # ``mesh``; pushed values carry a leading worker dim sharded on
        # that axis and pull reduces it with one XLA AllReduce (config 4)
        self._mesh = mesh
        self._axis = axis
        self._updater: Callable[[Key, jax.Array, jax.Array], jax.Array] = (
            lambda key, grad, value: value - self._lr * grad
        )

    @staticmethod
    def create(kv_type: str = "local", **kw: Any) -> "KVStore":
        return KVStore(kv_type, **kw)

    # -- MXNet KVStore surface -------------------------------------------
    def init(self, keys: Union[Key, Sequence[Key]], values: Any) -> None:
        """Register initial values.  In dist_sync mode rank 0's value wins
        (broadcast), matching KVStore semantics."""
        keys, values = self._normalize(keys, values)
        for k, v in zip(keys, values):
            if k in self._store:
                log_fatal(f"KVStore.init: key {k!r} already initialized")
            v = np.asarray(v)
            if self.type == "dist_sync":
                v = coll.broadcast(v, root=0)
            self._store[k] = jnp.asarray(v)

    def push(self, keys: Union[Key, Sequence[Key]], grads: Any) -> None:
        """Accumulate gradients (summed over multiple pushes per key)."""
        keys, grads = self._normalize(keys, grads)
        for k, g in zip(keys, grads):
            self._check_key(k)
            g = jnp.asarray(g)
            self._pending[k] = self._pending[k] + g if k in self._pending else g

    def pull(self, keys: Union[Key, Sequence[Key]]) -> Union[jax.Array, List[jax.Array]]:
        """Sync pending gradients (allreduce across workers in dist_sync),
        apply the updater, return current value(s)."""
        single = not isinstance(keys, (list, tuple))
        key_list: List[Key] = [keys] if single else list(keys)
        for k in key_list:
            self._check_key(k)
            if k in self._pending:
                grad = self._pending.pop(k)
                if self.type == "dist_sync":
                    if self._mesh is not None:
                        grad = coll.device_allreduce(grad, self._mesh, "sum",
                                                     axis=self._axis)
                    elif coll.world_size() > 1:
                        grad = jnp.asarray(coll.allreduce(np.asarray(grad), "sum"))
                self._store[k] = self._updater(k, grad, self._store[k])
        out = [self._store[k] for k in key_list]
        return out[0] if single else out

    def set_updater(self, updater: Callable[[Key, jax.Array, jax.Array], jax.Array]) -> None:
        self._updater = updater

    @property
    def rank(self) -> int:
        return coll.rank()

    @property
    def num_workers(self) -> int:
        return coll.world_size()

    # -- helpers ---------------------------------------------------------
    def _check_key(self, k: Key) -> None:
        if k not in self._store:
            log_fatal(f"KVStore: key {k!r} not initialized")

    @staticmethod
    def _normalize(keys, values):
        if isinstance(keys, (list, tuple)):
            CHECK(isinstance(values, (list, tuple)) and len(keys) == len(values),
                  "KVStore: keys/values length mismatch")
            return list(keys), list(values)
        return [keys], [values]
