"""Rabit-shaped collectives, re-founded on XLA.

Reference parity: the worker-side rabit API (``Allreduce<op>``,
``Broadcast``, ``rank``/``world_size``, ``CheckPoint``) that dmlc-core's
tracker coordinates, plus the tracker's topology math
(``tracker/dmlc_tracker/tracker.py :: get_tree / find_share_ring /
get_link_map`` — SURVEY.md §2c).

Engine replacement (the north star): there are no sockets here.

* **In-jit path (the fast path)**: ``device_allreduce`` /
  ``device_allgather`` are ``shard_map``-based XLA collectives on a named
  mesh — histogram sync, gradient sync, anything inside a train step rides
  ICI/DCN with XLA-scheduled overlap.  This is what the hist-GBT flagship
  and the KVStore shim compile onto.
* **Host path (rabit API parity)**: ``allreduce(np_array)`` etc. work on
  host values *between* steps, across processes, via the JAX runtime's
  global device set.  Coordination (rank assignment, liveness) is
  ``jax.distributed`` — bootstrapped from the ``DMLC_*`` env ABI by
  :func:`init`, keeping the reference's launch contract intact.

Topology functions are retained because (a) the tracker still serves them
to non-JAX legacy workers, and (b) they are the oracle for our tests'
parity with the reference's coordination brain.
"""

from __future__ import annotations

import contextlib
import os
import threading
from functools import lru_cache, partial
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from dmlc_core_tpu.base.compat import shard_map

from dmlc_core_tpu.base import metrics as _metrics
from dmlc_core_tpu.base.logging import CHECK, LOG, log_fatal
from dmlc_core_tpu.base.timer import get_time
from dmlc_core_tpu.utils.profiler import global_tracer, tracing_enabled

__all__ = [
    "init", "finalize", "rank", "world_size", "is_distributed",
    "allreduce", "broadcast", "allgather", "barrier",
    "allreduce_device",
    "device_allreduce", "device_allgather", "device_reduce_scatter",
    "replicate_fwd_psum_bwd", "record_hist_psum",
    "set_host_transport", "get_host_transport",
    "get_tree", "find_share_ring", "get_link_map",
]

_initialized = False

# ---------------------------------------------------------------------------
# pluggable host-collective transport (rabit wire parity)
# ---------------------------------------------------------------------------
# When multi-process XLA collectives are unavailable (the CPU backend
# refuses multiprocess computations entirely) the elastic recovery layer
# (``parallel.recovery``) runs the host collectives over the tracker's
# TCP protocol instead — rabit's actual wire role.  An installed
# transport overrides rank/world_size and every HOST-path collective in
# this module; the in-jit device collectives are untouched (they stay
# mesh-local).  Storage is thread-local so in-process multi-worker
# harnesses (one worker per thread, each with its own transport+rank)
# compose — exactly how the drill tests exercise the protocol.

_HOST_TRANSPORT = threading.local()


def set_host_transport(transport: Optional[Any]) -> None:
    """Install (``None`` clears) this thread's host-collective transport.

    A transport duck-types ``rank``/``world`` attributes and
    ``allreduce(np_array, op)`` / ``allgather(np_array)`` /
    ``broadcast(value, root)`` / ``barrier(name)`` methods — see
    ``parallel.recovery.ElasticSession``.
    """
    _HOST_TRANSPORT.t = transport


def get_host_transport() -> Optional[Any]:
    """The transport installed on this thread (None = native jax path)."""
    return getattr(_HOST_TRANSPORT, "t", None)

_REDUCERS = {
    "sum": np.add.reduce,
    "max": np.maximum.reduce,
    "min": np.minimum.reduce,
    "prod": np.multiply.reduce,
    "bitor": np.bitwise_or.reduce,
}

_CM = None


def _coll_metrics():
    global _CM
    if _CM is None:
        r = _metrics.default_registry()
        _CM = {
            "calls": r.counter("collective_calls_total",
                               "collective invocations", labels=("op",)),
            "bytes": r.counter("collective_bytes_total",
                               "payload bytes entering collectives",
                               labels=("op",)),
            "seconds": r.histogram("collective_seconds",
                                   "host-path collective latency",
                                   labels=("op",)),
            "hist_psum": r.counter(
                "histogram_psum_bytes_total",
                "per-chip bytes contributed to in-step histogram-sync "
                "allreduces (analytic traffic model; XLA hides the "
                "collective itself from host instrumentation)",
                labels=("engine",)),
        }
    return _CM


def record_hist_psum(nbytes: int, engine: str = "incore") -> None:
    """Account the histogram-sync psum traffic of a dispatched round
    program.

    The per-level psum rides INSIDE the jitted shard_map program, so the
    host-path instrumentation around :func:`allreduce` /
    :func:`allreduce_device` never sees it — the training engine calls
    this with the analytic per-dispatch byte count
    (:func:`~dmlc_core_tpu.ops.histogram.hist_psum_bytes_per_round` ×
    rounds × output trees) instead.  No-op when metrics are disabled.
    """
    if nbytes > 0 and _metrics.enabled():
        _coll_metrics()["hist_psum"].inc(nbytes, engine=engine)


@contextlib.contextmanager
def _host_op_span(op: str, nbytes: int):
    """Metrics + trace span around one host-path collective.

    The host collectives run BETWEEN steps, so their wall time is real
    blocked-training time — worth a latency histogram (the in-jit device
    collectives dispatch async and are timed by the device profiler, not
    here).  Fast-exits to a bare yield when both sinks are off.
    """
    collect = _metrics.enabled()
    if not collect and not tracing_enabled():
        yield
        return
    ctx = (global_tracer().scope(f"collective.{op}", bytes=int(nbytes))
           if tracing_enabled() else contextlib.nullcontext())
    t0 = get_time()
    try:
        with ctx:
            yield
    finally:
        if collect:
            m = _coll_metrics()
            m["calls"].inc(1, op=op)
            if nbytes:
                m["bytes"].inc(nbytes, op=op)
            m["seconds"].observe(get_time() - t0, op=op)


# ---------------------------------------------------------------------------
# bootstrap: DMLC_* env ABI → jax.distributed
# ---------------------------------------------------------------------------

def init(args: Optional[Dict[str, str]] = None) -> None:
    """Initialize distributed state from the ``DMLC_*`` env ABI.

    Reference parity: rabit's ``Init(argc, argv)`` reading
    ``DMLC_TRACKER_URI``/``DMLC_TRACKER_PORT``/``DMLC_TASK_ID``/
    ``DMLC_NUM_WORKER`` (SURVEY.md §2c env-var ABI).  Here those map onto
    ``jax.distributed.initialize(coordinator, num_processes, process_id)``
    — the JAX coordination service replaces the rabit tracker protocol.

    Single-process (no env set) is a no-op: everything below degrades to
    identity collectives, so the same program runs 1-chip or pod-scale.
    """
    global _initialized
    if _initialized:
        return
    env = dict(os.environ)
    if args:
        env.update(args)
    nworker = int(env.get("DMLC_NUM_WORKER", "1"))
    if nworker <= 1:
        _initialized = True
        return
    uri = env.get("DMLC_TRACKER_URI")
    port = env.get("DMLC_TRACKER_PORT", "9091")
    task_id = int(env.get("DMLC_TASK_ID", "0"))
    CHECK(uri is not None, "DMLC_NUM_WORKER > 1 but DMLC_TRACKER_URI unset")
    jax.distributed.initialize(
        coordinator_address=f"{uri}:{port}",
        num_processes=nworker,
        process_id=task_id,
    )
    _initialized = True
    LOG("INFO", "dmlc collectives: process %d/%d online", task_id, nworker)


def finalize() -> None:
    """Reference parity: rabit ``Finalize()``."""
    global _initialized
    if _initialized and jax.process_count() > 1:
        try:
            jax.distributed.shutdown()
        except Exception:
            pass
    _initialized = False


def rank() -> int:
    """This worker's rank.  Reference: rabit ``GetRank`` = process index
    (or the installed host transport's rank)."""
    t = get_host_transport()
    if t is not None:
        return t.rank
    return jax.process_index()


def world_size() -> int:
    """Number of workers.  Reference: rabit ``GetWorldSize``."""
    t = get_host_transport()
    if t is not None:
        return t.world
    return jax.process_count()


def is_distributed() -> bool:
    """True once :func:`init` has joined a multi-process
    ``jax.distributed`` cluster (world size > 1) or a host transport
    spanning multiple workers is installed."""
    return world_size() > 1


# ---------------------------------------------------------------------------
# host-level collectives (rabit API parity, between-step granularity)
# ---------------------------------------------------------------------------

def allreduce(x: np.ndarray, op: str = "sum") -> np.ndarray:
    """Allreduce a host array across processes.

    Reference parity: rabit ``Allreduce<op>(ptr, count)``.  Implemented as
    process-allgather + local reduce through the JAX runtime (exact for
    every op incl. non-commutative-sensitive float sums: every rank reduces
    in the same rank order, so results are bitwise identical across
    workers — the determinism rabit guaranteed via its fixed tree).
    For in-step sync use :func:`device_allreduce`, which stays on ICI.
    """
    x = np.asarray(x)
    if op not in _REDUCERS:
        log_fatal(f"allreduce: unknown op {op!r}; valid: {sorted(_REDUCERS)}")
    with _host_op_span("allreduce", x.nbytes):
        t = get_host_transport()
        if t is not None:
            return t.allreduce(x, op)
        if world_size() == 1:
            return x
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(x, tiled=False)  # [world, ...]
        return _REDUCERS[op](np.asarray(gathered), axis=0)


def broadcast(x: Any, root: int = 0) -> Any:
    """Broadcast a host value from ``root``.  Reference: rabit ``Broadcast``."""
    with _host_op_span("broadcast", getattr(x, "nbytes", 0)):
        t = get_host_transport()
        if t is not None:
            return t.broadcast(x, root)
        if world_size() == 1:
            return x
        from jax.experimental import multihost_utils

        return multihost_utils.broadcast_one_to_all(x, is_source=rank() == root)


def allgather(x: np.ndarray) -> np.ndarray:
    """Gather arrays from all processes, stacked on axis 0 in rank order."""
    x = np.asarray(x)
    with _host_op_span("allgather", x.nbytes):
        t = get_host_transport()
        if t is not None:
            return t.allgather(x)
        if world_size() == 1:
            return x[None]
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=False))


def barrier(name: str = "dmlc") -> None:
    """Cross-process barrier (rabit's implicit sync points, made explicit)."""
    with _host_op_span("barrier", 0):
        t = get_host_transport()
        if t is not None:
            t.barrier(name)
            return
        if world_size() == 1:
            return
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


@lru_cache(maxsize=None)
def _world_mesh() -> Mesh:
    return Mesh(np.asarray(jax.devices()), ("world",))


@lru_cache(maxsize=None)
def _jitted_world_psum(mesh: Mesh):
    @partial(shard_map, mesh=mesh, in_specs=P("world"), out_specs=P(),
             check_vma=False)
    def _ps(shard):                      # [1, ...] per device
        return jax.lax.psum(shard[0], "world")

    return jax.jit(_ps)


def allreduce_device(x: jax.Array) -> jax.Array:
    """Sum a per-process DEVICE array across all processes, returning a
    device array — no host round-trip.

    The fix for the external-memory training loop (BASELINE config 3):
    per-level page histograms accumulate on device and sync here as one
    XLA AllReduce over ICI/DCN, where :func:`allreduce` would fetch to
    host, allgather, and re-reduce in numpy every level.  Each process
    contributes its value once (staged on its first local device; other
    local devices contribute zeros), so multi-device processes are safe.

    With a host transport installed this degrades to a host round trip
    (fetch → tracker-mediated deterministic sum → device) — the rabit
    wire path for backends without multiprocess XLA collectives.
    """
    t = get_host_transport()
    if t is not None:
        return jnp.asarray(t.allreduce(np.asarray(x), "sum"))
    if world_size() == 1:
        return x
    if _metrics.enabled():
        # calls + bytes only: the result is returned un-synced, so wall
        # time here would measure dispatch, not the collective
        m = _coll_metrics()
        m["calls"].inc(1, op="allreduce_device")
        m["bytes"].inc(getattr(x, "nbytes", 0), op="allreduce_device")
    mesh = _world_mesh()
    locals_ = jax.local_devices()
    x = jnp.asarray(x)
    shards = [jax.device_put(x[None] if i == 0
                             else jnp.zeros((1, *x.shape), x.dtype), d)
              for i, d in enumerate(locals_)]
    garr = jax.make_array_from_single_device_arrays(
        (len(jax.devices()), *x.shape),
        NamedSharding(mesh, P("world")), shards)
    out = _jitted_world_psum(mesh)(garr)
    return jnp.asarray(out.addressable_data(0))


# ---------------------------------------------------------------------------
# in-jit collectives (the TPU fast path)
# ---------------------------------------------------------------------------

_LAX_REDUCE = {
    "sum": jax.lax.psum,
    "max": jax.lax.pmax,
    "min": jax.lax.pmin,
}


@lru_cache(maxsize=None)
def _jitted_allreduce(mesh: Mesh, op: str, axis: str):
    """One stable jitted reducer per (mesh, op, axis).

    jax.jit caches compilations by function identity + input avals, so
    returning the SAME jitted callable here means repeated calls (e.g. the
    KVStore pulling every gradient key each step) hit the jit cache instead
    of retracing and recompiling per call.
    """
    lax_op = _LAX_REDUCE[op]
    local_op = {"sum": jnp.sum, "max": jnp.max, "min": jnp.min}[op]

    @partial(shard_map, mesh=mesh, in_specs=P(axis), out_specs=P())
    def _reduce(shard):
        return lax_op(local_op(shard, axis=0), axis)

    return jax.jit(_reduce)


def device_allreduce(x: jax.Array, mesh: Mesh, op: str = "sum",
                     axis: str = "data") -> jax.Array:
    """Allreduce per-device shards over a mesh axis, on-device.

    ``x`` is sharded on ``axis`` along dim 0 (one shard per device); the
    result is the reduced array, replicated.  Lowers to a single XLA
    AllReduce riding ICI — this is the histogram-sync primitive
    (north star: replaces rabit's socket tree allreduce).

    Composable: call inside your own jit/shard_map too — this helper is
    just the standalone spelling.
    """
    if op not in _LAX_REDUCE:
        log_fatal(f"device_allreduce: unknown op {op!r}")
    return _jitted_allreduce(mesh, op, axis)(x)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def replicate_fwd_psum_bwd(x: jax.Array, axis: str) -> jax.Array:
    """Identity forward, ``psum`` over ``axis`` backward (Megatron's *f*).

    Marks the boundary where a replicated activation enters computation
    sharded over ``axis`` (tensor parallelism): the forward is free, and
    the backward all-reduces the partial cotangents so every shard holds
    the COMPLETE gradient.  Without it, parameters upstream of the
    boundary would see only their shard's contribution — and a blanket
    per-parameter psum instead double-counts the residual-stream path.
    Use inside shard_map.
    """
    return x


def _rfpb_fwd(x, axis):
    del axis
    return x, None


def _rfpb_bwd(axis, _res, ct):
    return (jax.lax.psum(ct, axis),)


replicate_fwd_psum_bwd.defvjp(_rfpb_fwd, _rfpb_bwd)


@lru_cache(maxsize=None)
def _jitted_allgather(mesh: Mesh, axis: str):
    @partial(shard_map, mesh=mesh, in_specs=P(axis), out_specs=P(), check_vma=False)
    def _gather(shard):
        return jax.lax.all_gather(shard, axis, tiled=True)

    return jax.jit(_gather)


def device_allgather(x: jax.Array, mesh: Mesh, axis: str = "data") -> jax.Array:
    """All-gather shards over a mesh axis (XLA AllGather on ICI)."""
    return _jitted_allgather(mesh, axis)(x)


@lru_cache(maxsize=None)
def _jitted_reduce_scatter(mesh: Mesh, axis: str, op: str):
    def _rs(full):
        if op == "sum":
            return jax.lax.psum_scatter(full, axis, tiled=True)
        # max/min have no fused scatter primitive: reduce then slice
        red = (jax.lax.pmax if op == "max" else jax.lax.pmin)(full, axis)
        k = mesh.shape[axis]          # static (lax.axis_size is newer jax)
        i = jax.lax.axis_index(axis)
        piece = full.shape[0] // k
        return jax.lax.dynamic_slice_in_dim(red, i * piece, piece, axis=0)

    return jax.jit(partial(shard_map, mesh=mesh, in_specs=P(),
                           out_specs=P(axis), check_vma=False)(_rs))


def device_reduce_scatter(x: jax.Array, mesh: Mesh, op: str = "sum",
                          axis: str = "data") -> jax.Array:
    """Reduce over the mesh axis, leaving each device its 1/k slice of
    dim 0 (XLA ReduceScatter on ICI) — the bandwidth-optimal half of an
    allreduce, the building block for ZeRO-style sharded optimizers.

    ``x`` is replicated input with dim 0 divisible by the axis size; the
    result is sharded over ``axis`` along dim 0.
    """
    if op not in ("sum", "max", "min"):
        log_fatal(f"reduce_scatter: unknown op {op!r}; valid: sum/max/min")
    if x.shape[0] % mesh.shape[axis]:
        log_fatal(
            f"reduce_scatter: dim 0 ({x.shape[0]}) not divisible by "
            f"axis {axis!r} size {mesh.shape[axis]}")
    return _jitted_reduce_scatter(mesh, axis, op)(x)


# ---------------------------------------------------------------------------
# topology math (tracker parity; oracle-tested)
# ---------------------------------------------------------------------------

def get_tree(n: int) -> Tuple[Dict[int, int], Dict[int, List[int]]]:
    """Binary reduction tree over ranks 0..n-1.

    Reference parity: ``tracker.py :: get_tree`` — parent(r) = (r-1)//2.
    Returns (parent_map, children_map); root's parent is -1.
    """
    parent: Dict[int, int] = {0: -1}
    children: Dict[int, List[int]] = {r: [] for r in range(n)}
    for r in range(1, n):
        p = (r - 1) // 2
        parent[r] = p
        children[p].append(r)
    return parent, children


def find_share_ring(children: Dict[int, List[int]], root: int = 0) -> List[int]:
    """Ring order as a depth-first traversal of the tree.

    Reference parity: ``tracker.py :: find_share_ring`` — DFS of the
    reduction tree yields a ring where every hop is also a tree edge or
    close to one, so the two topologies share physical links.
    """
    order: List[int] = []

    def dfs(r: int) -> None:
        order.append(r)
        for c in children[r]:
            dfs(c)

    dfs(root)
    return order


def get_link_map(n: int) -> Dict[int, Dict[str, Any]]:
    """Per-rank connection map: tree parent/children + ring prev/next.

    Reference parity: ``tracker.py :: get_link_map`` — this is the payload
    the tracker sends each worker at 'start'.
    """
    parent, children = get_tree(n)
    ring = find_share_ring(children)
    pos = {r: i for i, r in enumerate(ring)}
    out: Dict[int, Dict[str, Any]] = {}
    for r in range(n):
        i = pos[r]
        out[r] = {
            "parent": parent[r],
            "children": list(children[r]),
            "ring_prev": ring[(i - 1) % n],
            "ring_next": ring[(i + 1) % n],
        }
    return out
