"""Expert parallelism (Switch-style mixture-of-experts) over an
``expert`` mesh axis.

Beyond reference parity — upstream dmlc-core has no model math
(SURVEY.md §2e marks EP absent) — but the substrate reserves the
``expert`` axis; this populates it the TPU way: experts shard over the
axis, and tokens move to their expert and back as TWO ``all_to_all``
collectives riding ICI (the reference world would build this with NCCL
all-to-all + a CUDA dispatch kernel).

Formulation (inside ``shard_map``; E experts over P shards, E/P each):

1. route: top-1 over router logits, gate = that expert's softmax prob
   (Switch Transformer); per-expert positions by cumsum, tokens beyond
   the capacity ``C = ceil(cf · T / E)`` are DROPPED (output 0 — the
   caller's residual connection carries them, standard Switch behavior);
2. dispatch: a ``[T, E, C]`` one-hot einsum packs tokens into per-expert
   slots — gather-free, MXU-friendly, static shapes;
3. ``all_to_all`` the ``[P, E_local, C, D]`` slabs so every shard holds
   ALL shards' slots for ITS experts; batched expert FFN; ``all_to_all``
   back; combine with gate · dispatch.

An auxiliary load-balancing loss (mean expert fraction · mean router
prob, Switch eq. 4) is returned so trainers can keep routing uniform.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from dmlc_core_tpu.base.compat import axis_size

__all__ = ["moe_ffn", "reference_moe_ffn"]


def moe_ffn(
    x: jax.Array,            # [T, D] local tokens
    wr: jax.Array,           # [D, E] router (replicated)
    w1: jax.Array,           # [E_local, D, F] this shard's experts
    b1: jax.Array,           # [E_local, F]
    w2: jax.Array,           # [E_local, F, D]
    b2: jax.Array,           # [E_local, D]
    axis: Optional[str] = "expert",
    capacity_factor: float = 1.25,
    stats: bool = False,
) -> Tuple[jax.Array, Any]:
    """Top-1 expert FFN; returns ``(y [T, D], aux_loss scalar)``.

    ``axis=None`` runs the same math unsharded (w1 then holds ALL
    experts) — the single-device reference path and the oracle the
    sharded run is tested against.

    ``stats=True`` returns ``(y, (assign_sum [E], prob_sum [E], T))``
    instead of the scalar aux: raw routing-statistic SUMS the caller can
    psum over its batch axes and combine into the aux loss GLOBALLY —
    the only way a data-sharded trainer reproduces the unsharded aux
    exactly (a mean of per-shard aux values is a different statistic).
    """
    T, D = x.shape
    E = wr.shape[1]
    P = axis_size(axis) if axis is not None else 1
    e_local = w1.shape[0]
    cap = max(1, int(np.ceil(capacity_factor * T / E)))

    logits = x @ wr                                       # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)               # [T]
    gate = jnp.take_along_axis(probs, expert_idx[:, None], 1)[:, 0]

    onehot = jax.nn.one_hot(expert_idx, E, dtype=x.dtype)  # [T, E]
    # aux load-balance loss (Switch eq. 4): E · Σ_e fraction_e · prob_e
    assign_sum = jnp.sum(onehot, axis=0)                  # [E]
    prob_sum = jnp.sum(probs, axis=0)                     # [E]
    aux = E * jnp.sum((assign_sum / T) * (prob_sum / T))
    pos = (jnp.cumsum(onehot, axis=0) * onehot).astype(jnp.int32)  # 1-based
    keep = (pos > 0) & (pos <= cap)
    slot = jax.nn.one_hot(pos - 1, cap, dtype=x.dtype) * keep[..., None]
    dispatch = onehot[..., None] * slot                   # [T, E, C]

    xe = jnp.einsum("tec,td->ecd", dispatch, x)           # [E, C, D]
    if axis is not None:
        # send each expert-slab to its owner; receive every shard's
        # tokens for the local experts: [P, E_local, C, D]
        xe = xe.reshape(P, e_local, cap, D)
        xe = lax.all_to_all(xe, axis, split_axis=0, concat_axis=0,
                            tiled=False)
        xe = jnp.moveaxis(xe, 0, 1).reshape(e_local, P * cap, D)
    # batched expert FFN on [E_local, slots, D]
    h = jax.nn.gelu(jnp.einsum("esd,edf->esf", xe, w1) + b1[:, None, :])
    ye = jnp.einsum("esf,efd->esd", h, w2) + b2[:, None, :]
    if axis is not None:
        ye = jnp.moveaxis(ye.reshape(e_local, P, cap, D), 1, 0)
        ye = lax.all_to_all(ye, axis, split_axis=0, concat_axis=0,
                            tiled=False)
        ye = ye.reshape(E, cap, D)
    y = jnp.einsum("tec,ecd->td", dispatch, ye) * gate[:, None]
    if stats:
        return y, (assign_sum, prob_sum, jnp.float32(T))
    return y, aux


def reference_moe_ffn(x, wr, w1_all, b1_all, w2_all, b2_all,
                      capacity_factor=1e9):
    """Numpy oracle: per-token dense expert application (no capacity
    pressure unless ``capacity_factor`` is set low, matching moe_ffn's
    drop rule)."""
    x = np.asarray(x)
    T, D = x.shape
    E = np.asarray(wr).shape[1]
    cap = max(1, int(np.ceil(capacity_factor * T / E)))
    logits = x @ np.asarray(wr)
    z = np.exp(logits - logits.max(-1, keepdims=True))
    probs = z / z.sum(-1, keepdims=True)
    idx = probs.argmax(-1)
    gate = probs[np.arange(T), idx]
    y = np.zeros_like(x)
    counts = np.zeros(E, np.int64)
    for t in range(T):
        e = idx[t]
        counts[e] += 1
        if counts[e] > cap:
            continue                       # dropped: residual only
        h = x[t] @ np.asarray(w1_all)[e] + np.asarray(b1_all)[e]
        h = 0.5 * h * (1 + np.tanh(np.sqrt(2 / np.pi)
                                   * (h + 0.044715 * h ** 3)))
        y[t] = (h @ np.asarray(w2_all)[e] + np.asarray(b2_all)[e]) * gate[t]
    return y
