"""Pipeline parallelism (GPipe-style circular schedule) over a ``pipe``
mesh axis.

Beyond reference parity — upstream dmlc-core has no model math at all
(SURVEY.md §2e marks PP absent) — but the substrate reserves the ``pipe``
axis and a TPU-complete framework must populate it: at pod scale, layers
that don't fit one slice shard across stages and microbatches stream
through them over ICI.

The TPU-native formulation (no schedulers, no send/recv threads — the
reference world would build this with NCCL P2P + a runtime scheduler):

* every stage holds a CONTIGUOUS slab of layers as stacked ``[L_local,
  ...]`` arrays (a global ``[L, ...]`` array sharded over ``pipe``);
* one ``lax.scan`` runs ``n_micro + n_stages − 1`` ticks; each tick every
  stage applies its slab to its live microbatch and the activations
  ``ppermute`` one hop down the ring — the pipeline "schedule" is just a
  scan body the compiler overlaps;
* bubble ticks compute on zeros and are masked out of the loss, so
  ``jax.grad`` THROUGH the scan+ppermute yields exactly the pipelined
  backward (reverse ppermutes) with no hand-written schedule;
* ``jax.checkpoint`` on the stage function keeps the scan's saved state
  O(ticks · microbatch) instead of O(ticks · layers).

``pipeline_apply`` is the generic combinator (works inside any
``shard_map`` whose mesh has the axis); :class:`PipelineLM` is the
self-contained consumer — a masked-LM transformer on a (data, pipe) mesh
— used by tests and the multichip dryrun.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from dmlc_core_tpu.base.compat import axis_size, donate_argnums, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dmlc_core_tpu.base.logging import CHECK, CHECK_EQ
from dmlc_core_tpu.base.parameter import Parameter, field
from dmlc_core_tpu.base.timer import get_time
from dmlc_core_tpu.parallel.mesh import local_mesh

__all__ = ["pipeline_apply", "PipelineLM", "PipelineLMParam"]


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def _replicated_loss_boundary(x: jax.Array, axis: str) -> jax.Array:
    """Identity forward; backward divides the cotangent by the axis size.

    After the ring-closing psum every shard redundantly computes the SAME
    downstream loss from the replicated pipeline output, so the psum's
    VJP sums S identical cotangents — S× the true gradient for everything
    upstream (all stage params, embeddings).  This boundary cancels the
    redundancy; downstream (head) grads are genuinely complete per shard
    and untouched."""
    return x


def _rlb_fwd(x, axis):
    return x, None


def _rlb_bwd(axis, _res, ct):
    return (ct / axis_size(axis),)


_replicated_loss_boundary.defvjp(_rlb_fwd, _rlb_bwd)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x_micro: jax.Array,          # [M, mb, ...] microbatched stage-0 input
    axis: str = "pipe",
) -> jax.Array:
    """Run ``x_micro`` through all pipeline stages; return [M, mb, ...]
    outputs as produced by the LAST stage (valid on every shard — the
    result is ppermuted back to close the ring, so callers can compute
    the loss on any stage).

    ``stage_fn(stage_params, x) -> y`` is THIS shard's slab of layers
    (already local under shard_map).  Ticks run ``M + S − 1`` times; at
    tick t, stage s works on microbatch ``t − s`` (zeros during bubble
    ticks).  Differentiable end-to-end: reverse-mode AD through the scan
    emits the reverse ppermutes of the backward pipeline.
    """
    S = axis_size(axis)
    idx = lax.axis_index(axis)
    M = x_micro.shape[0]
    mb_shape = x_micro.shape[1:]
    n_ticks = M + S - 1
    perm_fwd = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        buf, outs = carry                     # buf: [mb, ...] live input
        # stage 0 injects microbatch t (zeros when t ≥ M — bubble)
        inject = lax.dynamic_index_in_dim(
            x_micro, jnp.minimum(t, M - 1), 0, keepdims=False)
        inject = jnp.where(t < M, inject, jnp.zeros_like(inject))
        buf = jnp.where(idx == 0, inject, buf)
        y = jax.checkpoint(stage_fn)(stage_params, buf)
        # last stage completed microbatch t − (S−1): record it
        done_mb = t - (S - 1)
        outs = lax.cond(
            done_mb >= 0,
            lambda o: o.at[jnp.maximum(done_mb, 0)].set(
                jnp.where(idx == S - 1, y, o[jnp.maximum(done_mb, 0)])),
            lambda o: o,
            outs)
        # rotate activations one hop down the ring for the next tick
        buf_next = lax.ppermute(y, axis, perm_fwd)
        return (buf_next, outs), None

    buf0 = jnp.zeros(mb_shape, x_micro.dtype)
    outs0 = jnp.zeros((M, *mb_shape), x_micro.dtype)
    (_, outs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(n_ticks))
    # only the last stage holds real outputs; close the ring so every
    # stage returns them (psum over a one-hot mask — cheap and exact);
    # the loss boundary cancels the S-fold cotangent of the redundant
    # per-shard downstream loss computation
    mine = jnp.where(idx == S - 1, outs, jnp.zeros_like(outs))
    return _replicated_loss_boundary(lax.psum(mine, axis), axis)


class PipelineLMParam(Parameter):
    """Small-transformer defaults sized for tests/dryruns; scale freely."""

    n_layers = field(int, default=4, lower_bound=1)
    d_model = field(int, default=64, lower_bound=8)
    n_heads = field(int, default=4, lower_bound=1)
    d_ff = field(int, default=128, lower_bound=8)
    vocab_size = field(int, default=256, lower_bound=16)
    max_len = field(int, default=64, lower_bound=8)
    n_micro = field(int, default=4, lower_bound=1,
                    description="microbatches per step (pipeline depth)")
    learning_rate = field(float, default=1e-2, lower_bound=0.0)


def _norm(x, eps=1e-6):
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mu) * lax.rsqrt(var + eps)


class PipelineLM:
    """Masked-LM transformer on a (data, pipe) mesh.

    Layers live as ``[n_layers, ...]`` stacked arrays sharded over
    ``pipe`` (each stage scans its local slab); embedding/head are
    replicated and their grads psum over ``pipe`` (only the stage that
    touches them contributes non-zero cotangents).  The train step is
    one jitted shard_map program: DP grad sync (psum over ``data``) and
    the pipeline schedule compile into a single XLA module.
    """

    def __init__(self, param: Optional[PipelineLMParam] = None,
                 mesh: Optional[Mesh] = None, **kwargs: Any):
        self.param = param or PipelineLMParam()
        if kwargs:
            self.param.init(kwargs)
        self.mesh = mesh if mesh is not None else local_mesh()
        CHECK("data" in self.mesh.axis_names, "mesh needs a 'data' axis")
        self._has_pipe = "pipe" in self.mesh.axis_names
        self._pp = self.mesh.shape.get("pipe", 1)
        CHECK_EQ(self.param.n_layers % max(self._pp, 1), 0,
                 "n_layers % pipe != 0")
        self.params: Optional[Dict[str, jax.Array]] = None
        self._step_fn = None

    # -- parameters -----------------------------------------------------
    def _specs(self) -> Dict[str, P]:
        pipe = "pipe" if self._has_pipe else None
        return {
            "embed": P(), "pos": P(), "head": P(),
            # stacked per-layer arrays, layer dim sharded over pipe
            "wqkv": P(pipe), "wo": P(pipe),
            "w1": P(pipe), "b1": P(pipe), "w2": P(pipe), "b2": P(pipe),
        }

    def init_params(self, seed: int = 0) -> None:
        p = self.param
        rng = np.random.default_rng(seed)

        def g(*shape, scale=0.05):
            return (rng.normal(size=shape) * scale).astype(np.float32)

        L, D, F = p.n_layers, p.d_model, p.d_ff
        host = {
            "embed": g(p.vocab_size, D),
            "pos": g(p.max_len, D),
            "head": g(D, p.vocab_size),
            "wqkv": g(L, 3, D, D),
            "wo": g(L, D, D),
            "w1": g(L, D, F),
            "b1": np.zeros((L, F), np.float32),
            "w2": g(L, F, D),
            "b2": np.zeros((L, D), np.float32),
        }
        specs = self._specs()
        self.params = {k: jax.device_put(v, NamedSharding(self.mesh, specs[k]))
                       for k, v in host.items()}
        self._build_step()

    # -- stage computation ---------------------------------------------
    def _stage_fn(self, sp, x):
        """Apply this stage's slab of layers to activations [mb, s, D]."""
        p = self.param
        dh = p.d_model // p.n_heads

        def layer(x, lp):
            wqkv, wo, w1, b1, w2, b2 = lp
            h = _norm(x)
            qkv = jnp.einsum("bsd,cde->cbse", h, wqkv)
            q, k, v = [y.reshape(*y.shape[:2], p.n_heads, dh)
                       for y in (qkv[0], qkv[1], qkv[2])]
            scores = jnp.einsum("bshk,bthk->bhst", q, k) / np.sqrt(dh)
            attn = jnp.einsum("bhst,bthk->bshk", jax.nn.softmax(scores, -1), v)
            attn = attn.reshape(*attn.shape[:2], p.d_model)
            x = x + jnp.einsum("bse,ed->bsd", attn, wo)
            h = _norm(x)
            x = x + jnp.einsum("bsf,fd->bsd",
                               jax.nn.gelu(jnp.einsum("bsd,df->bsf", h, w1)
                                           + b1), w2) + b2
            return x, None

        x, _ = lax.scan(layer, x, (sp["wqkv"], sp["wo"], sp["w1"],
                                   sp["b1"], sp["w2"], sp["b2"]))
        return x

    def _build_step(self) -> None:
        p = self.param
        specs = self._specs()
        lr = p.learning_rate
        M = p.n_micro
        has_pipe = self._has_pipe

        def step(params, tokens, labels, mask):
            def loss_fn(ps):
                B, S = tokens.shape
                CHECK_EQ(B % M, 0, "local batch % n_micro != 0")
                mb = B // M
                x = (jnp.take(ps["embed"], tokens, axis=0)
                     + ps["pos"][None, :S])
                x_micro = x.reshape(M, mb, S, p.d_model)
                stage_params = {k: ps[k] for k in
                                ("wqkv", "wo", "w1", "b1", "w2", "b2")}
                if has_pipe:
                    y = pipeline_apply(self._stage_fn, stage_params,
                                       x_micro, axis="pipe")
                else:
                    y = jax.vmap(lambda xm: self._stage_fn(stage_params, xm)
                                 )(x_micro)
                y = _norm(y.reshape(B, S, p.d_model))
                logits = jnp.einsum("bsd,dv->bsv", y, ps["head"])
                logp = jax.nn.log_softmax(logits, -1)
                tok = jnp.take_along_axis(logp, labels[..., None], -1)[..., 0]
                mf = mask.astype(jnp.float32)
                return -(tok * mf).sum(), mf.sum()

            (ls, n), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
            n_glob = lax.psum(n, "data")
            loss = lax.psum(ls, "data") / n_glob
            grads = jax.tree.map(lambda g: lax.psum(g, "data") / n_glob,
                                 grads)
            if has_pipe:
                # embed/pos flow through the stage-0 injection gate, so
                # only stage 0 holds non-zero cotangents — psum over pipe
                # completes them.  head/final-norm grads are ALREADY
                # complete on every stage (the pipeline output is psum-
                # replicated before the loss, so each stage differentiates
                # the full loss) and must NOT be psummed again.  Stacked
                # layer grads are pipe-sharded and local-complete.
                for k in ("embed", "pos"):
                    grads[k] = lax.psum(grads[k], "pipe")
            new_params = {k: params[k] - lr * grads[k] for k in params}
            return new_params, loss

        batch_spec = P("data")
        in_specs = ({k: specs[k] for k in specs},
                    batch_spec, batch_spec, batch_spec)
        mapped = shard_map(
            step, mesh=self.mesh, in_specs=in_specs,
            out_specs=({k: specs[k] for k in specs}, P()),
            check_vma=False)
        self._step_fn = jax.jit(mapped, donate_argnums=donate_argnums(0))

        # scan-chunked multi-step program (fit_chunked): K steps per
        # dispatch, same rationale as BERT.fit_chunked — a per-step host
        # sync through a remote-device tunnel dominates a sub-100ms step
        self._multi_cache: Dict[int, Any] = {}

        def make_multi(K: int):
            if K not in self._multi_cache:
                def multi(params, tokens, labels, mask):
                    def body(ps, _):
                        return step(ps, tokens, labels, mask)

                    return lax.scan(body, params, None, length=K)

                mapped_k = shard_map(
                    multi, mesh=self.mesh, in_specs=in_specs,
                    out_specs=({k: specs[k] for k in specs}, P()),
                    check_vma=False)
                self._multi_cache[K] = jax.jit(mapped_k, donate_argnums=donate_argnums(0))
            return self._multi_cache[K]

        self._make_multi = make_multi

    # -- checkpointing (Stream/serializer consumer layer) ---------------
    _MODEL_MAGIC = b"DMLCTPU.PIPELM.v1\n"

    def save_model(self, uri: str) -> None:
        """Serialize hyperparams + params to any Stream URI (SURVEY.md
        §5 checkpoint layering; see models/checkpoint.py).  Pipe-sharded
        layer slabs gather to full arrays on save and re-shard on load,
        so the checkpoint is portable across pipe widths."""
        from dmlc_core_tpu.models.checkpoint import gather_tree, save_payload

        CHECK(self.params is not None, "save_model before init_params")
        save_payload(uri, self._MODEL_MAGIC, {
            "param": self.param.to_dict(),
            "params": gather_tree(self.params),
        })

    @classmethod
    def load_model(cls, uri: str,
                   mesh: Optional[Mesh] = None) -> "PipelineLM":
        from dmlc_core_tpu.models.checkpoint import load_payload

        payload = load_payload(uri, cls._MODEL_MAGIC)
        model = cls(mesh=mesh, **payload["param"])
        specs = model._specs()
        model.params = {
            k: jax.device_put(v, NamedSharding(model.mesh, specs[k]))
            for k, v in payload["params"].items()}
        model._build_step()
        return model

    # -- public API -----------------------------------------------------
    def train_step(self, tokens: np.ndarray, labels: np.ndarray,
                   mask: np.ndarray) -> float:
        CHECK(self.params is not None, "call init_params() first")
        sh = NamedSharding(self.mesh, P("data"))
        t = jax.device_put(np.asarray(tokens, np.int32), sh)
        y = jax.device_put(np.asarray(labels, np.int32), sh)
        m = jax.device_put(np.asarray(mask, np.float32), sh)
        self.params, loss = self._step_fn(self.params, t, y, m)
        return float(loss)

    def fit_chunked(self, tokens: np.ndarray, labels: np.ndarray,
                    mask: np.ndarray, n_steps: int, chunk: int = 10,
                    warmup_chunks: int = 1):
        """Run ``n_steps`` SGD steps as lax.scan chunks of ``chunk`` per
        dispatch; returns ``(final_loss, seconds, chunk_times)`` with
        in-order per-chunk loss-arrival timestamps (the bench audit
        pattern).  Steady-state timing: warmup chunks run first."""
        CHECK(self.params is not None, "call init_params() first")
        CHECK(n_steps % chunk == 0,
              f"n_steps {n_steps} must be a multiple of chunk {chunk}")
        sh = NamedSharding(self.mesh, P("data"))
        t = jax.device_put(np.asarray(tokens, np.int32), sh)
        y = jax.device_put(np.asarray(labels, np.int32), sh)
        m = jax.device_put(np.asarray(mask, np.float32), sh)
        fn = self._make_multi(chunk)
        for _ in range(max(warmup_chunks, 1)):
            self.params, losses = fn(self.params, t, y, m)
        np.asarray(losses[-1:])
        t0 = get_time()
        loss_chunks = []
        done = 0
        while done < n_steps:
            self.params, losses = fn(self.params, t, y, m)
            loss_chunks.append(losses)
            done += chunk
        chunk_times = []
        fetched = 0
        final_loss = float("nan")
        for losses in loss_chunks:
            arr = np.asarray(losses)
            fetched += len(arr)
            chunk_times.append((fetched, get_time() - t0))
            final_loss = float(arr[-1])
        return final_loss, get_time() - t0, chunk_times
