"""Ulysses-style all-to-all sequence parallelism.

The second of the two long-context shardings (SURVEY.md §5 obligation;
sibling of :mod:`~dmlc_core_tpu.parallel.ring_attention`): instead of
rotating K/V blocks around a ring, ONE ``all_to_all`` re-shards the
activations from sequence-sharded to head-sharded, every device then runs
*full-sequence* attention for its subset of heads (dense local compute —
ideal for the MXU / a fused flash kernel), and a second ``all_to_all``
restores sequence sharding.

Trade-offs vs ring attention (why both exist):

* Ulysses moves ``2·B·S·H·D`` elements in two collective bursts and needs
  ``n_heads % P == 0``; compute is one dense local attention (best MXU
  utilization, trivially composable with a flash kernel).
* Ring keeps K/V moving in P overlappable hops and has no head-count
  constraint; better when heads < devices or when overlap hides the ICI
  time.

Both are exact. Like ``ring_attention``, this MUST run inside a
``shard_map`` that maps the token axis over ``axis_name``.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
from jax import lax

__all__ = ["ulysses_attention"]


def ulysses_attention(
    q: jax.Array,           # [B, S_local, H, D]
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "seq",
    causal: bool = False,
    scale: Optional[float] = None,
    local_attn: Optional[Callable] = None,
) -> jax.Array:
    """Exact attention over sequence-sharded Q/K/V via two all-to-alls.

    ``local_attn(q, k, v, causal, scale) -> out`` runs the full-sequence
    attention for this device's head subset (default: the dense softmax
    oracle; pass a flash kernel for long sequences).  Requires
    ``H % axis_size == 0``.  Returns ``[B, S_local, H, D]``.
    """
    P = lax.psum(1, axis_name)
    B, S_loc, H, D = q.shape
    if H % P:
        raise ValueError(f"ulysses: n_heads {H} not divisible by axis {P}")

    import jax.numpy as jnp

    # ONE stacked all_to_all for q/k/v (not three): same bytes, one
    # collective launch — this plus the output's inverse are the module's
    # advertised "two collective bursts"
    qkv = jnp.stack([q, k, v])                     # [3, B, S/P, H, D]
    qkv = lax.all_to_all(qkv, axis_name, split_axis=3, concat_axis=2,
                         tiled=True)               # [3, B, S, H/P, D]
    qh, kh, vh = qkv[0], qkv[1], qkv[2]
    if local_attn is None:
        # flash-fused on TPU when shapes allow, dense oracle otherwise
        from dmlc_core_tpu.ops.attention import local_attention
        out = local_attention(qh, kh, vh, causal=causal, scale=scale)
    else:
        out = local_attn(qh, kh, vh, causal, scale)
    # inverse: [B, S, H/P, D] → [B, S/P, H, D]; received head blocks
    # concatenate in device order = original head order
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)
