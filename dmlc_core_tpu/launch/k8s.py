"""Kubernetes transport: one indexed Job per supervised worker.

Reuses :func:`dmlc_core_tpu.tracker.kubernetes.build_manifest` — the
pure indexed-Job renderer the one-shot ``tracker/kubernetes.py`` backend
already ships — but under the :class:`~dmlc_core_tpu.launch.transport.
Transport` interface, so the JobSet supervisor owns ranks, restarts and
teardown while k8s only runs pods.  Each spawned worker becomes a
single-completion Job named ``<jobname>-<label>`` whose pod carries the
env overlay verbatim (``backoffLimit`` 0: the JobSet's restart budget is
the ONE restart authority — double supervision would fork rank history).

**Dry-run by default**: without a cluster the transport renders and
records manifests (``self.manifests``) and reports every worker as
instantly completed, which is exactly what the manifest-snapshot tests
and ``dmlc-submit --dry-run`` consume.  With ``dry_run=False`` it shells
out to ``kubectl`` (apply / get -o json / delete / logs) — optional by
design; CI never needs a real cluster.
"""

from __future__ import annotations

import json
import re
import signal
import subprocess
from typing import Any, Dict, List, Optional

from dmlc_core_tpu.base.logging import CHECK, LOG
from dmlc_core_tpu.launch.transport import (Transport, TransportError,
                                            WorkerHandle)
from dmlc_core_tpu.tracker.kubernetes import build_manifest

__all__ = ["K8sTransport"]


def _job_name(jobname: str, label: str) -> str:
    """RFC-1123 label: lowercase alnum + '-', 63 chars."""
    raw = f"{jobname}-{label}".lower()
    raw = re.sub(r"[^a-z0-9-]+", "-", raw).strip("-")
    return raw[:63] or "dmlc-job"


class K8sTransport(Transport):
    """Spawn = render (and optionally ``kubectl apply``) one indexed Job.

    ``hosts()`` exposes one virtual ``k8s`` slot per expected worker —
    placement is the cluster scheduler's job, the slot list only sizes
    the JobSet's round-robin.
    """

    name = "k8s"

    def __init__(self, image: str, jobname: str = "dmlc-job",
                 namespace: Optional[str] = None,
                 kubectl: str = "kubectl", dry_run: bool = True,
                 worker_cores: Optional[int] = None,
                 worker_memory_mb: Optional[int] = None,
                 tpu_topology: Optional[str] = None,
                 tpu_accelerator: Optional[str] = None,
                 slots: int = 8):
        CHECK(bool(image), "K8sTransport needs a container image")
        self.image = image
        self.jobname = jobname
        self.namespace = namespace
        self.kubectl = kubectl
        self.dry_run = dry_run
        self.worker_cores = worker_cores
        self.worker_memory_mb = worker_memory_mb
        self.tpu_topology = tpu_topology
        self.tpu_accelerator = tpu_accelerator
        self._slots = max(1, int(slots))
        #: every manifest rendered by this transport, in spawn order —
        #: the dry-run evidence the snapshot tests assert on
        self.manifests: List[Dict[str, Any]] = []

    def hosts(self) -> List[str]:
        return ["k8s"] * self._slots

    def render(self, command: List[str], env: Dict[str, str],
               label: str) -> Dict[str, Any]:
        """The manifest for one worker (pure — no cluster contact)."""
        return build_manifest(
            1, command, env, self.image,
            jobname=_job_name(self.jobname, label),
            worker_cores=self.worker_cores,
            worker_memory_mb=self.worker_memory_mb,
            max_attempts=0,     # completions=1, backoffLimit=0: the
            tpu_topology=self.tpu_topology,          # JobSet restarts
            tpu_accelerator=self.tpu_accelerator)

    def _kubectl(self, *args: str, input_text: Optional[str] = None
                 ) -> subprocess.CompletedProcess:
        argv = [self.kubectl]
        if self.namespace:
            argv += ["-n", self.namespace]
        argv += list(args)
        return subprocess.run(argv, input=input_text, text=True,
                              capture_output=True)

    def spawn(self, command: List[str], env: Dict[str, str],
              host: str, label: str = "worker") -> WorkerHandle:
        manifest = self.render(command, env, label)
        self.manifests.append(manifest)
        job = manifest["metadata"]["name"]
        handle = WorkerHandle(host, label, env,
                              extra={"job": job, "manifest": manifest})
        if self.dry_run:
            # rendered == done: dry-run proves the configuration, it
            # does not simulate pod lifetimes
            handle.extra["exit_code"] = 0
            return handle
        p = self._kubectl("apply", "-f", "-",
                          input_text=json.dumps(manifest))
        if p.returncode != 0:
            raise TransportError(
                f"kubectl apply failed for job {job}: {p.stderr.strip()}")
        LOG("INFO", "k8s transport: applied job %s", job)
        return handle

    def poll(self, handle: WorkerHandle) -> Optional[int]:
        if "exit_code" in handle.extra:
            return int(handle.extra["exit_code"])  # type: ignore[arg-type]
        p = self._kubectl("get", "job", str(handle.extra["job"]),
                          "-o", "json")
        if p.returncode != 0:
            return None         # API blip: stay optimistic, poll again
        try:
            status = json.loads(p.stdout).get("status", {})
        except ValueError:
            return None
        if int(status.get("succeeded") or 0) >= 1:
            handle.extra["exit_code"] = 0
            return 0
        if int(status.get("failed") or 0) >= 1:
            handle.extra["exit_code"] = 1
            return 1
        return None

    def signal(self, handle: WorkerHandle, sig: int) -> None:
        # k8s has no per-signal channel: any kill-ish signal deletes the
        # Job (foreground propagation SIGTERMs the pod)
        if sig not in (signal.SIGTERM, signal.SIGKILL, signal.SIGINT):
            return
        if "exit_code" in handle.extra:
            return
        if self.dry_run:
            handle.extra["exit_code"] = 128 + int(sig)
            return
        self._kubectl("delete", "job", str(handle.extra["job"]),
                      "--ignore-not-found=true", "--wait=false")
        handle.extra["exit_code"] = 128 + int(sig)

    def log_tail(self, handle: WorkerHandle, max_bytes: int = 4096) -> str:
        if self.dry_run:
            return ""
        p = self._kubectl("logs", f"job/{handle.extra['job']}",
                          "--tail", "100")
        return p.stdout[-max_bytes:] if p.returncode == 0 else ""
