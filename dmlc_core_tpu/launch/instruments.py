"""Shared metric handles for the launch tier.

Same pattern as ``serve.fleet.instruments``: every launch layer
(transports, the JobSet supervisor, the backend glue) records into the
process-wide registry (``base.metrics.default_registry``) so one scrape
shows spawn latency, respawn churn and supervised-worker counts next to
the tracker and fleet instruments.

The rows that matter operationally (see ``doc/observability.md``):
``launch_respawns_total`` says workers are dying and being brought back
(a rising rate is a failing host or a crash-looping command);
``launch_workers`` is the supervised head-count per JobSet;
``launch_spawn_seconds`` p95 is the cold-start tax each respawn pays.
"""

from __future__ import annotations

from typing import Dict

from dmlc_core_tpu.base import metrics as _metrics

__all__ = ["launch_metrics"]

_M: Dict[str, object] = {}


def launch_metrics() -> Dict[str, object]:
    """Lazily declared instrument handles (get-or-create, shared by all
    launch layers — one dict lookup per event on the hot path)."""
    if not _M:
        r = _metrics.default_registry()
        _M.update({
            "spawn": r.histogram(
                "launch_spawn_seconds",
                "time to spawn one worker process, by transport",
                labels=("transport",)),
            "respawns": r.counter(
                "launch_respawns_total",
                "workers restarted by a JobSet supervisor after an "
                "unexpected exit", labels=("jobset",)),
            "workers": r.gauge(
                "launch_workers",
                "worker processes a JobSet currently supervises",
                labels=("jobset",)),
            "events": r.counter(
                "launch_events_total",
                "JobSet lifecycle events, by kind (spawn|exit|respawn|"
                "spawn_error|giveup|wedged|stop|teardown)",
                labels=("event",)),
        })
    return _M
