"""Multi-host launch: supervised ranked worker sets over pluggable
transports.

The paper's ``dmlc_tracker`` is not just a rank-assignment socket — it
is the layer that *launches and supervises* a ranked job set on
whatever substrate the operator has (a dev box, an SSH host file, a
Kubernetes cluster).  This package is that layer:

* :mod:`transport` — :class:`Transport` (spawn/poll/signal/kill one
  process on a host, stream its env + log tail) with
  :class:`LocalTransport` (pdeathsig'd subprocesses),
  :class:`SSHTransport` (host-file slots, ``ssh -tt``) and
  :class:`FakeTransport` (a deterministic in-process cluster whose host
  failures are scripted through the ``base/faultinject`` grammar — the
  CI story).
* :mod:`k8s` — :class:`K8sTransport`: one indexed-Job manifest per
  worker, dry-run by default, ``kubectl`` exec optional.
* :mod:`jobset` — :class:`JobSet`: the supervisor.  DMLC env ABI
  injection, liveness poll + tracker-heartbeat cross-check,
  restart-with-backoff under ``DMLC_LAUNCH_RESTART_LIMIT``, targeted
  kill, graceful teardown, ``dmlc_launch_*`` metrics + lifecycle
  events.
* :mod:`config` — dmlc-submit options → JobSet configurations (the
  ``tracker/submit.py`` local/ssh/kubernetes backends).

Spawn sites routed through here: ``tracker/local.py`` +
``tracker/ssh.py`` (thin shims), ``parallel/recovery.ElasticLauncher``
(multi-host elastic training), ``serve/fleet`` replica spawning and the
``LauncherScaler`` autoscale backend.  See ``doc/distributed.md``
"Multi-host launch".
"""

from dmlc_core_tpu.launch.config import (jobset_from_opts,  # noqa: F401
                                         transport_from_opts)
from dmlc_core_tpu.launch.instruments import launch_metrics  # noqa: F401
from dmlc_core_tpu.launch.jobset import JobSet, LaunchTimeout  # noqa: F401
from dmlc_core_tpu.launch.k8s import K8sTransport  # noqa: F401
from dmlc_core_tpu.launch.transport import (FakeTransport,  # noqa: F401
                                            LocalTransport, SSHTransport,
                                            Transport, TransportError,
                                            WorkerHandle)

__all__ = [
    "Transport", "TransportError", "WorkerHandle",
    "LocalTransport", "SSHTransport", "FakeTransport", "K8sTransport",
    "JobSet", "LaunchTimeout",
    "jobset_from_opts", "transport_from_opts", "launch_metrics",
]
