"""JobSet: a supervised ranked worker set over any Transport.

This is the launcher role PAPER.md §1 assigns ``dmlc_tracker`` — not
just *starting* N ranked workers but owning their lifecycle:

* **launch** — spawn ranks 0..n-1 round-robin over the transport's live
  host slots, each with the DMLC env ABI injected (``DMLC_TASK_ID`` =
  rank, ``DMLC_ROLE``, ``DMLC_NUM_ATTEMPT``) plus a per-rank overlay
  hook (``env_for``) for FLEET_*-style ABIs.
* **monitor** — a supervisor thread polls every handle each
  ``DMLC_LAUNCH_MONITOR_S`` and, when a tracker is attached,
  cross-checks process liveness against the tracker's heartbeat view:
  a rank whose process is alive but which the tracker has carried as
  lost for ``DMLC_LAUNCH_WEDGE_CYCLES`` cycles is *wedged* — killed so
  the ordinary respawn path replaces it.
* **restart-with-backoff** — an unexpected exit (nonzero / signaled,
  not an intentional stop) schedules a respawn after
  :meth:`~dmlc_core_tpu.base.resilience.RetryPolicy.backoff_for`, under
  a per-rank ``DMLC_LAUNCH_RESTART_LIMIT`` budget; placement re-runs
  against the *currently live* hosts, so a dead host's ranks land on
  survivors.  ``DMLC_NUM_ATTEMPT`` counts up so the worker (and the
  tracker's ``recover`` path) knows it is a replacement.  The budget is
  **cause-fair**: the transport's ``classify_exit`` attributes each
  exit, and only a ``crash`` (the rank's own fault) spends the rank's
  budget — a ``host_death`` (spot preemption, SSH connect failure)
  charges the host's fault map instead, so a rank preempted twice by a
  spot wave keeps its full restart budget for a genuine crash.
  ``events()`` carries the cause per exit; ``stats()`` breaks respawns
  down by cause.
* **targeted kill / graceful teardown** — ``kill(rank)`` stops one rank
  (optionally letting it respawn); ``shutdown()`` SIGTERMs everything,
  waits ``DMLC_LAUNCH_GRACEFUL_S``, SIGKILLs stragglers.

Evidence: lifecycle events (``events()``), spawn-latency samples and
respawn counts (``stats()``), and the ``dmlc_launch_*`` metrics rows
documented in ``doc/observability.md``.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from dmlc_core_tpu.base import knobs as _knobs
from dmlc_core_tpu.base import metrics as _metrics
from dmlc_core_tpu.base import tracectx as _tracectx
from dmlc_core_tpu.base.logging import CHECK, LOG
from dmlc_core_tpu.base.racecheck import instrument_class
from dmlc_core_tpu.base.resilience import RetryPolicy
from dmlc_core_tpu.base.timer import get_time
from dmlc_core_tpu.launch.instruments import launch_metrics
from dmlc_core_tpu.launch.transport import (LocalTransport, Transport,
                                            TransportError, WorkerHandle)

__all__ = ["JobSet", "LaunchTimeout"]


class LaunchTimeout(RuntimeError):
    """`JobSet.wait` ran past its deadline with ranks still running."""


class _Rank:
    """Supervision state for one rank (all mutation under the JobSet
    lock; ``spawning`` guards the out-of-lock spawn window).

    ``attempt`` counts every respawn (it drives ``DMLC_NUM_ATTEMPT`` and
    backoff); ``crashes`` counts only the rank's OWN faults — the subset
    that consumes ``DMLC_LAUNCH_RESTART_LIMIT``.  A host death (spot
    preemption, node failure) respawns the rank without charging it:
    the fault is the host's, tracked in the JobSet's per-host map."""

    __slots__ = ("rank", "handle", "last_handle", "attempt", "crashes",
                 "spawn_errors", "code", "done", "stopping", "retry_at",
                 "spawning", "lost_cycles")

    def __init__(self, rank: int):
        self.rank = rank
        self.handle: Optional[WorkerHandle] = None
        self.last_handle: Optional[WorkerHandle] = None
        self.attempt = 0
        self.crashes = 0
        self.spawn_errors = 0
        self.code: Optional[int] = None
        self.done = False
        self.stopping = False
        self.retry_at: Optional[float] = None
        self.spawning = False
        self.lost_cycles = 0


@instrument_class
class JobSet:
    """Launch + supervise ``nworker`` ranked processes over a transport.

    ``envs`` is the shared env ABI (typically ``tracker.slave_envs()``);
    ``env_for(rank, attempt)`` adds per-rank overlay vars.  ``tracker``
    is any object with ``lost_ranks() -> List[int]`` (RabitTracker and
    subclasses) keyed by the same rank space as ``DMLC_TASK_ID`` — the
    heartbeat half of the liveness cross-check.
    """

    def __init__(self, command: List[str], nworker: int,
                 transport: Optional[Transport] = None,
                 envs: Optional[Dict[str, str]] = None,
                 name: str = "jobset", role: str = "worker",
                 restart_limit: Optional[int] = None,
                 monitor_s: Optional[float] = None,
                 graceful_s: Optional[float] = None,
                 retry: Optional[RetryPolicy] = None,
                 tracker: Optional[Any] = None,
                 env_for: Optional[
                     Callable[[int, int], Dict[str, str]]] = None):
        CHECK(len(command) > 0, "JobSet: empty worker command")
        CHECK(nworker >= 0, f"JobSet: bad nworker {nworker}")
        self._command = list(command)
        self._nworker = nworker
        self._transport = transport if transport is not None else LocalTransport()
        self._envs = dict(envs or {})
        self.name = name
        self._role = role
        self._restart_limit = (restart_limit if restart_limit is not None
                               else int(_knobs.value("DMLC_LAUNCH_RESTART_LIMIT")))
        self._monitor_s = (monitor_s if monitor_s is not None
                           else float(_knobs.value("DMLC_LAUNCH_MONITOR_S")))
        self._graceful_s = (graceful_s if graceful_s is not None
                            else float(_knobs.value("DMLC_LAUNCH_GRACEFUL_S")))
        self._wedge_cycles = int(_knobs.value("DMLC_LAUNCH_WEDGE_CYCLES"))
        self._retry = retry if retry is not None else RetryPolicy.from_env()
        self._tracker = tracker
        self._env_for = env_for
        self._lock = threading.Lock()
        self._ranks: Dict[int, _Rank] = {}
        self._next_rank = nworker
        self._events: List[Dict[str, Any]] = []
        self._spawn_ms: List[float] = []
        self._respawns = 0
        #: respawns scheduled, broken down by exit cause
        #: ("crash" | "host_death" | "spawn_error")
        self._respawns_by_cause: Dict[str, int] = {}
        #: host-death charges per host — the budget a preemption burns
        self._host_faults: Dict[str, int] = {}
        self._launched = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def transport(self) -> Transport:
        return self._transport

    # -- env ABI ---------------------------------------------------------
    def worker_env(self, rank: int, attempt: int = 0) -> Dict[str, str]:
        """The env OVERLAY rank ``rank`` is spawned with (pure given a
        fixed observability env — this is what the golden per-backend
        env tests snapshot; with no spool/trace configured nothing
        extra is injected, so the snapshots are exact)."""
        env = dict(self._envs)
        env["DMLC_TASK_ID"] = str(rank)
        env["DMLC_ROLE"] = self._role
        env["DMLC_NUM_ATTEMPT"] = str(attempt)
        env.setdefault("DMLC_NUM_WORKER", str(self._nworker))
        # observability overlay: children join the launcher's metrics
        # spool and trace so the whole job aggregates into one artifact
        spool = os.environ.get("DMLC_METRICS_SPOOL", "")
        if spool:
            env.setdefault("DMLC_METRICS_SPOOL", spool)
        trace = _tracectx.current_header()
        if trace is not None:
            env.setdefault(_tracectx.ENV_KEY, trace)
        if self._env_for is not None:
            env.update(self._env_for(rank, attempt) or {})
        return env

    # -- evidence --------------------------------------------------------
    def _event_locked(self, kind: str, rank: int, host: str = "",
                      detail: str = "", cause: str = "") -> None:
        ev = {"ts": get_time(), "event": kind, "rank": rank,
              "host": host, "detail": detail}
        if cause:
            ev["cause"] = cause
        self._events.append(ev)
        if _metrics.enabled():
            launch_metrics()["events"].inc(1, event=kind)

    def events(self) -> List[Dict[str, Any]]:
        """Lifecycle event log (copies; spawn/exit/respawn/giveup/...)."""
        with self._lock:
            return [dict(e) for e in self._events]

    def stats(self) -> Dict[str, Any]:
        """Supervision evidence: backend, respawns (total AND per exit
        cause — crash vs host_death vs spawn_error), per-host fault
        charges, spawn-latency p95, and per-rank state — the ``bench.py
        --fleet`` launch record."""
        with self._lock:
            ms = sorted(self._spawn_ms)
            p95 = ms[min(len(ms) - 1, int(round(0.95 * (len(ms) - 1))))] if ms else 0.0
            return {
                "backend": self._transport.name,
                "respawns": self._respawns,
                "respawns_by_cause": dict(self._respawns_by_cause),
                "host_faults": dict(self._host_faults),
                "spawn_ms_p95": p95,
                "spawns": len(ms),
                "ranks": {
                    st.rank: {"attempt": st.attempt,
                              "crashes": st.crashes, "code": st.code,
                              "done": st.done,
                              "host": st.handle.host if st.handle else None}
                    for st in self._ranks.values()},
            }

    def respawns(self) -> int:
        with self._lock:
            return self._respawns

    def alive_count(self) -> int:
        with self._lock:
            handles = [st.handle for st in self._ranks.values()
                       if not st.done and st.handle is not None
                       and not st.spawning]
        return sum(1 for h in handles if self._transport.poll(h) is None)

    def rank_host(self, rank: int) -> Optional[str]:
        with self._lock:
            st = self._ranks.get(rank)
            return st.handle.host if st is not None and st.handle else None

    def log_tail(self, rank: int, max_bytes: int = 4096) -> str:
        with self._lock:
            st = self._ranks.get(rank)
            handle = (st.handle or st.last_handle) if st is not None else None
        return self._transport.log_tail(handle, max_bytes) if handle else ""

    # -- spawning --------------------------------------------------------
    def _place(self, rank: int) -> str:
        """Slot-aware bin-packing over live hosts.  ``hosts()`` is the
        slot-expanded host file (a host contributing k slots appears k
        times); the winner is the live host with the most FREE slots —
        declared slots minus the ranks currently placed on it — so a
        4-slot host absorbs four ranks before a 1-slot host sees a
        second, and a respawn after a host death lands on the survivor
        with capacity instead of at ``rank % len(hosts)`` (which is
        blind to both slot counts and occupancy)."""
        slots: Dict[str, int] = {}
        for h in self._transport.hosts():
            if self._transport.host_alive(h):
                slots[h] = slots.get(h, 0) + 1
        if not slots:
            raise TransportError(
                f"jobset {self.name}: no live hosts to place rank {rank}")
        with self._lock:
            for st in self._ranks.values():
                if st.rank == rank or st.done or st.handle is None:
                    continue
                if st.handle.host in slots:
                    slots[st.handle.host] -= 1
        # most free slots wins; host-file order breaks ties
        return max(slots, key=lambda h: slots[h])

    def _do_spawn(self, rank: int) -> bool:
        """Spawn one rank whose state is marked ``spawning`` (transport
        work happens OUTSIDE the lock; state commits back under it)."""
        with self._lock:
            st = self._ranks[rank]
            attempt = st.attempt
        label = f"{self.name}-r{rank}-a{attempt}"
        try:
            t0 = get_time()
            host = self._place(rank)
            handle = self._transport.spawn(
                self._command, self.worker_env(rank, attempt), host,
                label=label)
            dt = get_time() - t0
        except TransportError as e:
            with self._lock:
                st.spawning = False
                # spawn failures have their own budget counter: with
                # host deaths no longer charging the rank, ``attempt``
                # may legitimately exceed the restart limit
                st.spawn_errors += 1
                if st.stopping or st.spawn_errors > self._restart_limit:
                    st.done = True
                    if st.code is None:
                        st.code = 1
                    self._event_locked("giveup", rank, "", str(e),
                                       cause="spawn_error")
                else:
                    st.attempt = attempt + 1
                    st.retry_at = (get_time()
                                   + self._retry.backoff_for(st.attempt))
                    self._respawns_by_cause["spawn_error"] = \
                        self._respawns_by_cause.get("spawn_error", 0) + 1
                    self._event_locked("spawn_error", rank, "", str(e))
            LOG("WARNING", "jobset %s: spawn of rank %d failed: %s",
                self.name, rank, e)
            return False
        with self._lock:
            st.handle = handle
            st.last_handle = handle
            st.spawning = False
            st.code = None
            st.retry_at = None
            st.lost_cycles = 0
            self._spawn_ms.append(dt * 1e3)
            if attempt > 0:
                self._respawns += 1
            self._event_locked("spawn" if attempt == 0 else "respawn",
                               rank, handle.host, f"attempt={attempt}")
        if _metrics.enabled():
            launch_metrics()["spawn"].observe(dt,
                                              transport=self._transport.name)
            if attempt > 0:
                launch_metrics()["respawns"].inc(1, jobset=self.name)
        LOG("INFO", "jobset %s: rank %d attempt %d → %s (%s)",
            self.name, rank, attempt, handle.host, label)
        return True

    def launch(self) -> "JobSet":
        """Spawn every rank and start the supervisor thread."""
        with self._lock:
            CHECK(not self._launched, f"jobset {self.name} already launched")
            self._launched = True
            for rank in range(self._nworker):
                st = _Rank(rank)
                st.spawning = True
                self._ranks[rank] = st
        for rank in range(self._nworker):
            self._do_spawn(rank)
        self._publish_workers()
        if self._monitor_s > 0:
            self._thread = threading.Thread(
                target=self._monitor, daemon=True,
                name=f"jobset-{self.name}")
            self._thread.start()
        return self

    def add_rank(self) -> int:
        """Grow the set by one rank (launcher-backed scale-out);
        returns the new rank index."""
        with self._lock:
            CHECK(self._launched, "add_rank before launch()")
            rank = self._next_rank
            self._next_rank += 1
            st = _Rank(rank)
            st.spawning = True
            self._ranks[rank] = st
        self._do_spawn(rank)
        self._publish_workers()
        return rank

    # -- supervision -----------------------------------------------------
    def _monitor(self) -> None:
        while not self._stop.wait(self._monitor_s):
            try:
                self.step()
            except Exception as e:  # noqa: BLE001 — supervisor must not die
                LOG("WARNING", "jobset %s: monitor step failed: %s",
                    self.name, e)

    def step(self) -> None:
        """One supervision cycle (public so tests/drills can drive the
        JobSet without the thread): poll, reap, respawn-due, cross-check."""
        self._transport.tick()
        with self._lock:
            live = [(st.rank, st.handle) for st in self._ranks.values()
                    if not st.done and st.handle is not None
                    and not st.spawning]
        for rank, handle in live:
            code = self._transport.poll(handle)
            if code is not None:
                self._on_exit(rank, handle, code)
        self._respawn_due()
        self._cross_check()
        self._publish_workers()

    def _on_exit(self, rank: int, handle: WorkerHandle, code: int) -> None:
        tail = ""
        # attribute the exit BEFORE taking the lock: SSH classification
        # may read the worker's log tail (file I/O)
        cause = "crash"
        if code != 0:
            try:
                cause = self._transport.classify_exit(handle, code)
            except Exception:  # noqa: BLE001 — classification is advisory
                cause = "crash"
        with self._lock:
            st = self._ranks.get(rank)
            if st is None or st.done or st.handle is not handle:
                return
            st.code = code
            if code == 0 or st.stopping:
                st.done = True
                self._event_locked("stop" if st.stopping else "exit",
                                   rank, handle.host, f"code={code}")
            elif cause == "crash" and st.crashes + 1 > self._restart_limit:
                # only the rank's OWN faults spend its restart budget —
                # a rank preempted N times by host deaths keeps the full
                # budget for a genuine crash
                st.done = True
                self._event_locked("giveup", rank, handle.host,
                                   f"code={code} after "
                                   f"{st.crashes + 1} crashes "
                                   f"({st.attempt + 1} attempts)",
                                   cause=cause)
            else:
                # detach the dead handle: a handle left in place would be
                # re-polled (and re-counted against the budget) every
                # cycle until the backoff lapsed
                st.handle = None
                st.last_handle = handle
                st.attempt += 1
                if cause == "crash":
                    st.crashes += 1
                else:
                    # the host ate the fault, not the rank
                    self._host_faults[handle.host] = \
                        self._host_faults.get(handle.host, 0) + 1
                self._respawns_by_cause[cause] = \
                    self._respawns_by_cause.get(cause, 0) + 1
                st.retry_at = get_time() + self._retry.backoff_for(st.attempt)
                self._event_locked("exit", rank, handle.host,
                                   f"code={code} respawn={st.attempt}",
                                   cause=cause)
            gave_up = st.done and code != 0 and not st.stopping
        if gave_up:
            tail = self._transport.log_tail(handle, 2048)
            LOG("ERROR", "jobset %s: rank %d exited %d, restart budget "
                "spent; log tail:\n%s", self.name, rank, code, tail)
        elif code != 0:
            LOG("WARNING", "jobset %s: rank %d on %s exited %d",
                self.name, rank, handle.host, code)

    def _respawn_due(self) -> None:
        now = get_time()
        with self._lock:
            due = []
            for st in self._ranks.values():
                if (not st.done and not st.spawning
                        and st.retry_at is not None and st.retry_at <= now):
                    st.retry_at = None
                    st.spawning = True
                    due.append(st.rank)
        for rank in due:
            self._do_spawn(rank)

    def _cross_check(self) -> None:
        """Heartbeat cross-check: a rank the tracker holds as LOST whose
        process still polls alive is wedged — kill it so the normal
        respawn path replaces it."""
        if self._tracker is None:
            return
        try:
            lost = set(self._tracker.lost_ranks())
        except Exception:  # noqa: BLE001 — tracker may be stopping
            return
        wedged: List[WorkerHandle] = []
        with self._lock:
            for st in self._ranks.values():
                if st.done or st.spawning or st.handle is None:
                    continue
                if st.rank in lost:
                    st.lost_cycles += 1
                    if st.lost_cycles >= self._wedge_cycles:
                        st.lost_cycles = 0
                        self._event_locked("wedged", st.rank,
                                           st.handle.host)
                        wedged.append(st.handle)
                else:
                    st.lost_cycles = 0
        for handle in wedged:
            LOG("WARNING", "jobset %s: killing wedged worker %r "
                "(process alive, tracker lost it)", self.name, handle)
            self._transport.kill(handle)

    def _publish_workers(self) -> None:
        if not _metrics.enabled():
            return
        with self._lock:
            n = sum(1 for st in self._ranks.values()
                    if not st.done and st.handle is not None)
        launch_metrics()["workers"].set(n, jobset=self.name)

    # -- control plane ---------------------------------------------------
    def kill(self, rank: int, sig: int = signal.SIGTERM,
             respawn: bool = False) -> None:
        """Targeted kill of one rank.  With ``respawn=True`` the exit is
        treated as a fault and the restart budget brings it back."""
        with self._lock:
            st = self._ranks.get(rank)
            CHECK(st is not None, f"jobset {self.name}: unknown rank {rank}")
            handle = st.handle
            if not respawn:
                st.stopping = True
            self._event_locked("stop" if not respawn else "restart",
                               rank, handle.host if handle else "",
                               f"sig={sig}")
        if handle is not None:
            self._transport.signal(handle, sig)

    def wait(self, timeout: Optional[float] = None) -> Dict[int, int]:
        """Block until every rank is done (clean exit, intentional stop
        or spent budget); returns {rank: last exit code}.  Raises
        :class:`LaunchTimeout` past ``timeout`` seconds."""
        deadline = None if timeout is None else get_time() + timeout
        while True:
            if self._thread is None:
                self.step()
            with self._lock:
                if all(st.done for st in self._ranks.values()):
                    return {st.rank: (st.code if st.code is not None else 1)
                            for st in self._ranks.values()}
            if deadline is not None and get_time() > deadline:
                raise LaunchTimeout(
                    f"jobset {self.name}: workers still running after "
                    f"{timeout}s")
            time.sleep(max(0.01, min(self._monitor_s, 0.1)))

    def run(self, timeout: Optional[float] = None) -> List[int]:
        """launch + wait + teardown in one call (the dmlc-submit path);
        returns exit codes in rank order."""
        self.launch()
        try:
            codes = self.wait(timeout=timeout)
        finally:
            self.shutdown()
        return [codes[r] for r in sorted(codes)]

    def shutdown(self, graceful_s: Optional[float] = None) -> None:
        """Graceful teardown: stop supervising, SIGTERM everything, wait
        the grace window, SIGKILL stragglers, close the transport."""
        grace = self._graceful_s if graceful_s is None else graceful_s
        self._stop.set()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=max(1.0, 5 * self._monitor_s))
        with self._lock:
            pending = []
            for st in self._ranks.values():
                st.stopping = True
                st.retry_at = None
                if not st.done and st.handle is not None:
                    pending.append((st.rank, st.handle))
        for _, handle in pending:
            self._transport.signal(handle, signal.SIGTERM)
        deadline = get_time() + grace
        while pending and get_time() < deadline:
            pending = [(r, h) for r, h in pending
                       if self._transport.poll(h) is None]
            if pending:
                time.sleep(0.05)
        for _, handle in pending:
            self._transport.kill(handle)
        kill_deadline = get_time() + 5.0
        while pending and get_time() < kill_deadline:
            pending = [(r, h) for r, h in pending
                       if self._transport.poll(h) is None]
            if pending:
                time.sleep(0.02)
        with self._lock:
            for st in self._ranks.values():
                if not st.done:
                    st.done = True
                    if st.handle is not None and st.code is None:
                        st.code = self._transport_code(st.handle)
            self._event_locked("teardown", -1)
        self._transport.close()
        if _metrics.enabled():
            launch_metrics()["workers"].set(0, jobset=self.name)

    def _transport_code(self, handle: WorkerHandle) -> int:
        code = self._transport.poll(handle)
        return code if code is not None else -9
