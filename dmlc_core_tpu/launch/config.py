"""dmlc-submit option surface → JobSet configurations.

``tracker/submit.py`` used to dispatch each cluster backend to its own
one-shot ``launch()`` function; with the launch subsystem the local,
ssh and kubernetes backends are *configurations of the same supervised
JobSet* — only the transport differs.  :func:`jobset_from_opts` is that
mapping, kept pure enough for the golden per-backend env/manifest tests
to call it straight from parsed CLI options.
"""

from __future__ import annotations

import argparse
from typing import Dict, List, Optional

from dmlc_core_tpu.base.logging import CHECK
from dmlc_core_tpu.launch.jobset import JobSet
from dmlc_core_tpu.launch.k8s import K8sTransport
from dmlc_core_tpu.launch.transport import (LocalTransport, SSHTransport,
                                            Transport)

__all__ = ["jobset_from_opts", "transport_from_opts"]

#: clusters the JobSet supervisor backs; the rest keep their dedicated
#: scheduler-submission backends (mpi/sge/slurm/yarn/mesos hand the
#: supervision problem to the cluster manager itself)
SUPERVISED_CLUSTERS = ("local", "ssh", "kubernetes")


def transport_from_opts(opts: argparse.Namespace) -> Transport:
    """The Transport a dmlc-submit option namespace selects."""
    if opts.cluster == "local":
        return LocalTransport()
    if opts.cluster == "ssh":
        from dmlc_core_tpu.tracker.ssh import read_host_file

        CHECK(opts.host_file is not None, "--cluster ssh needs --host-file")
        return SSHTransport(read_host_file(opts.host_file))
    if opts.cluster == "kubernetes":
        CHECK(opts.image is not None, "--cluster kubernetes needs --image")
        return K8sTransport(
            opts.image, jobname=opts.jobname,
            dry_run=bool(getattr(opts, "dry_run", False)),
            worker_cores=opts.worker_cores,
            worker_memory_mb=opts.worker_memory,
            slots=opts.num_workers)
    raise ValueError(
        f"cluster {opts.cluster!r} is not JobSet-supervised "
        f"(supported: {', '.join(SUPERVISED_CLUSTERS)})")


def jobset_from_opts(opts: argparse.Namespace, command: List[str],
                     envs: Dict[str, str],
                     extra_env: Optional[Dict[str, str]] = None) -> JobSet:
    """Build the supervised JobSet for a dmlc-submit invocation.

    ``envs`` is the tracker env ABI (``slave_envs()``), ``extra_env``
    the user's ``--env KEY=VALUE`` overlay.  ``--max-attempts`` is the
    restart budget (attempt 0 is the launch itself, so the JobSet gets
    ``max_attempts - 1`` respawns).
    """
    merged = dict(envs)
    merged.update(extra_env or {})
    restart_limit = max(0, int(getattr(opts, "max_attempts", 1)) - 1)
    return JobSet(command, opts.num_workers,
                  transport=transport_from_opts(opts),
                  envs=merged, name=opts.jobname,
                  restart_limit=restart_limit)
