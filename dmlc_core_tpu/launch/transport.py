"""Transports: spawn/poll/signal/kill ONE worker process on a host.

A :class:`Transport` is the narrow waist between the :class:`~dmlc_core_tpu.
launch.jobset.JobSet` supervisor and a cluster substrate: it can spawn a
command on a named host with an env overlay, poll the resulting
:class:`WorkerHandle` for an exit code, deliver signals, and stream the
worker's env + log tail back for diagnosis.  Everything rank-shaped
(DMLC_TASK_ID injection, restart budgets, tracker cross-checks) lives in
the JobSet — a transport knows processes and hosts, nothing else.

* :class:`LocalTransport` — subprocess.Popen with per-worker log files
  and ``PR_SET_PDEATHSIG`` on Linux, so workers die with the launcher
  instead of leaking (the historical ``tracker/local.py`` bug: its
  fire-and-forget children survived a dead parent).
* :class:`SSHTransport` — the ``tracker/ssh.py`` launch idiom behind the
  Transport interface: ``ssh -tt host 'cd dir && env K=V cmd'`` per
  worker, host-file slots for placement, and the forced tty means the
  remote command dies when the local ssh process is killed.
* :class:`FakeTransport` — a deterministic in-process "cluster": local
  subprocesses labeled with virtual host names, with host failures and
  spawn latency scriptable through the ``base/faultinject`` grammar
  (``launch_host:kill=h1:after=20`` downs fake host ``h1`` on the 20th
  supervisor tick).  This is how CI proves multi-host supervision
  without any real SSH/k8s cluster.

The Kubernetes transport lives in :mod:`dmlc_core_tpu.launch.k8s` (it
renders indexed-Job manifests rather than holding a process handle).
"""

from __future__ import annotations

import math
import os
import shlex
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

from dmlc_core_tpu.base import faultinject
from dmlc_core_tpu.base import knobs as _knobs
from dmlc_core_tpu.base.logging import CHECK, LOG

__all__ = ["TransportError", "WorkerHandle", "Transport",
           "LocalTransport", "SSHTransport", "FakeTransport"]


class TransportError(RuntimeError):
    """A transport could not spawn or reach a worker (dead host, spawn
    failure) — the JobSet treats it as a restartable worker fault."""


class WorkerHandle:
    """One spawned worker process: where it runs, how it was started,
    and the live process/remote reference the owning transport polls."""

    __slots__ = ("host", "label", "env", "log_path", "proc", "extra")

    def __init__(self, host: str, label: str, env: Dict[str, str],
                 log_path: str = "", proc: Optional[subprocess.Popen] = None,
                 extra: Optional[Dict[str, object]] = None):
        self.host = host
        self.label = label
        #: env OVERLAY the worker was spawned with (the DMLC_*/FLEET_*
        #: ABI) — not the full inherited environment
        self.env = dict(env)
        self.log_path = log_path
        self.proc = proc
        self.extra = extra or {}

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def __repr__(self) -> str:
        return (f"WorkerHandle({self.label!r} on {self.host!r}, "
                f"pid={self.pid})")


def _pdeathsig_preexec() -> None:
    """Child-side: die with the parent (Linux ``PR_SET_PDEATHSIG``).

    This is the fix for the fire-and-forget leak: a launcher killed with
    SIGKILL used to orphan every worker it had spawned."""
    try:
        import ctypes

        libc = ctypes.CDLL(None, use_errno=True)
        libc.prctl(1, signal.SIGTERM)  # PR_SET_PDEATHSIG = 1
    except Exception:  # noqa: BLE001 — best effort, non-Linux is a no-op
        pass


def _read_tail(path: str, max_bytes: int) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - max_bytes))
            return f.read().decode("utf-8", "replace")
    except OSError:
        return ""


class Transport:
    """Abstract substrate: one method per thing a supervisor needs.

    ``spawn(command, env, host, label)`` starts ONE process; ``env`` is
    an overlay (the DMLC ABI), not a full environment — local transports
    merge it over ``os.environ``, remote ones export exactly it.
    """

    name = "abstract"

    def hosts(self) -> List[str]:
        """Placement slots, one entry per schedulable worker slot (a
        host with k slots appears k times)."""
        raise NotImplementedError

    def host_alive(self, host: str) -> bool:
        """Is ``host`` currently accepting spawns?  (FakeTransport downs
        hosts; real transports default to optimistic True.)"""
        del host
        return True

    def classify_exit(self, handle: WorkerHandle, code: int) -> str:
        """Attribute a worker's unexpected exit: ``"host_death"`` when
        the host itself is gone (spot preemption, node failure — the
        JobSet charges the HOST's fault budget, not the rank's restart
        budget), else ``"crash"`` (the worker's own fault).  The base
        rule is simply the host-liveness view; transports with richer
        evidence (SSH connect errors) refine it."""
        del code
        return "host_death" if not self.host_alive(handle.host) else "crash"

    def spawn(self, command: List[str], env: Dict[str, str],
              host: str, label: str = "worker") -> WorkerHandle:
        raise NotImplementedError

    def poll(self, handle: WorkerHandle) -> Optional[int]:
        """Exit code, or None while the worker is still running."""
        raise NotImplementedError

    def signal(self, handle: WorkerHandle, sig: int) -> None:
        raise NotImplementedError

    def kill(self, handle: WorkerHandle) -> None:
        self.signal(handle, signal.SIGKILL)

    def env_of(self, handle: WorkerHandle) -> Dict[str, str]:
        """The env overlay the worker was spawned with (diagnosis)."""
        return dict(handle.env)

    def log_tail(self, handle: WorkerHandle, max_bytes: int = 4096) -> str:
        """Last ``max_bytes`` of the worker's captured output."""
        return _read_tail(handle.log_path, max_bytes) if handle.log_path else ""

    def tick(self) -> None:
        """Called once per supervisor monitor cycle — fault-injection
        hook point for scriptable transports; default no-op."""

    def close(self) -> None:
        """Release transport resources (log dirs stay for post-mortem)."""


class LocalTransport(Transport):
    """Workers as local subprocesses with captured logs + pdeathsig.

    ``hosts`` may name virtual slots (every slot is this machine); the
    default is one ``localhost`` slot reused round-robin.
    """

    name = "local"

    def __init__(self, hosts: Optional[List[str]] = None,
                 log_dir: Optional[str] = None,
                 capture_logs: bool = True):
        self._hosts = list(hosts) if hosts else ["localhost"]
        CHECK(len(self._hosts) > 0, "LocalTransport: empty host list")
        if log_dir is None:
            log_dir = str(_knobs.value("DMLC_LAUNCH_LOG_DIR")) or ""
        self.log_dir = log_dir or tempfile.mkdtemp(prefix="dmlc-launch-")
        self._capture = capture_logs
        os.makedirs(self.log_dir, exist_ok=True)

    def hosts(self) -> List[str]:
        return list(self._hosts)

    def _popen_kwargs(self) -> Dict[str, object]:
        kw: Dict[str, object] = {}
        if sys.platform.startswith("linux"):
            kw["preexec_fn"] = _pdeathsig_preexec
        return kw

    def spawn(self, command: List[str], env: Dict[str, str],
              host: str, label: str = "worker") -> WorkerHandle:
        CHECK(len(command) > 0, f"{self.name} transport: empty command")
        full_env = dict(os.environ)
        full_env.update(env)
        log_path = ""
        stdout = stderr = subprocess.DEVNULL
        if self._capture:
            log_path = os.path.join(self.log_dir, f"{label}.log")
            log_f = open(log_path, "ab")
            stdout, stderr = log_f, subprocess.STDOUT
        try:
            proc = subprocess.Popen(command, env=full_env, stdout=stdout,
                                    stderr=stderr, **self._popen_kwargs())
        finally:
            if self._capture:
                log_f.close()   # child holds its own descriptor
        return WorkerHandle(host, label, env, log_path=log_path, proc=proc)

    def poll(self, handle: WorkerHandle) -> Optional[int]:
        return handle.proc.poll()

    def signal(self, handle: WorkerHandle, sig: int) -> None:
        if handle.proc.poll() is None:
            try:
                handle.proc.send_signal(sig)
            except (ProcessLookupError, OSError):
                pass            # lost the race with the exit


#: stderr signatures of an ssh CONNECT failure (vs the remote command
#: failing): the host itself is unreachable, so the exit is a host
#: death, not a worker crash
_SSH_CONNECT_ERRORS = (
    "connection refused",
    "connection timed out",
    "no route to host",
    "could not resolve hostname",
    "ssh: connect to host",
    "connection reset by peer",
    "network is unreachable",
)


class SSHTransport(LocalTransport):
    """One worker per ``ssh`` child; the remote command carries the env.

    ``hosts`` is the slot-expanded list from
    :func:`~dmlc_core_tpu.tracker.ssh.read_host_file`.  ``-tt`` forces a
    remote tty so killing the local ssh process (targeted kill,
    teardown, pdeathsig) hangs up the remote side too instead of
    orphaning it — the supervised replacement for the fire-and-forget
    ``tracker/ssh.py`` launch.

    **Dead-host detection**: ssh exits 255 for its OWN failures.  A 255
    whose log tail carries a connect-error signature (connection
    refused/timed out, no route, unresolvable name) — or no output at
    all, the connect died before the remote shell spoke — is classified
    as a *host death*: the host is marked down (``host_alive`` False,
    excluded from placement) until :meth:`restore_host`, and the JobSet
    respawns the rank on a survivor without charging its restart
    budget.  A 255 with remote output is the remote command's own exit
    status — a crash like any other.
    """

    name = "ssh"

    def __init__(self, hosts: List[str], cwd: Optional[str] = None,
                 ssh_binary: str = "ssh",
                 log_dir: Optional[str] = None):
        super().__init__(hosts=hosts, log_dir=log_dir)
        CHECK(len(hosts) > 0, "SSHTransport: empty host list")
        self.cwd = cwd or os.getcwd()
        self.ssh_binary = ssh_binary
        self._dead_lock = threading.Lock()
        self._dead: set = set()

    def host_alive(self, host: str) -> bool:
        with self._dead_lock:
            return host not in self._dead

    def restore_host(self, host: str) -> None:
        """Forget a host death (capacity came back / ops fixed it) so
        placement may use the host again."""
        with self._dead_lock:
            self._dead.discard(host)

    def down_hosts(self) -> List[str]:
        with self._dead_lock:
            return sorted(self._dead)

    def classify_exit(self, handle: WorkerHandle, code: int) -> str:
        if not self.host_alive(handle.host):
            return "host_death"
        if code != 255:
            return "crash"
        tail = self.log_tail(handle, 4096).lower()
        if tail.strip() and not any(sig in tail
                                    for sig in _SSH_CONNECT_ERRORS):
            return "crash"      # remote command's own exit 255
        with self._dead_lock:
            self._dead.add(handle.host)
        LOG("WARNING", "ssh transport: host %s classified dead "
            "(exit 255, connect error) — excluded from placement",
            handle.host)
        return "host_death"

    def build_argv(self, host: str, command: List[str],
                   env: Dict[str, str]) -> List[str]:
        """The exact local argv for one remote worker (pure; tested)."""
        env_part = " ".join(f"{k}={shlex.quote(str(v))}"
                            for k, v in env.items())
        cmd_part = " ".join(shlex.quote(c) for c in command)
        remote = f"cd {shlex.quote(self.cwd)} && env {env_part} {cmd_part}"
        return [self.ssh_binary, "-tt",
                "-o", "StrictHostKeyChecking=no",
                "-o", "BatchMode=yes", host, remote]

    def spawn(self, command: List[str], env: Dict[str, str],
              host: str, label: str = "worker") -> WorkerHandle:
        CHECK(len(command) > 0, "ssh transport: empty command")
        argv = self.build_argv(host, command, env)
        handle = super().spawn(argv, {}, host, label=label)
        handle.env.update(env)  # overlay travels inside argv, not Popen env
        return handle


class FakeTransport(LocalTransport):
    """Deterministic in-process "cluster" for CI drills and tests.

    Real local subprocesses, virtual host placement, and two
    fault-injection points wired into the ``base/faultinject`` grammar:

    * ``launch_spawn`` — checked at every spawn.  ``error`` makes the
      spawn raise :class:`TransportError` (the JobSet retries on another
      host); ``latency=<seconds>`` delays the spawn.
    * ``launch_host`` — checked once per supervisor tick *while the fake
      cluster has live workers*.  ``kill=<host>`` SIGKILLs every worker
      on that host and marks it down (``host_alive`` False, spawns on it
      raise) — the scripted mid-round host death of
      ``scripts/check_launch.py``.  ``wave=<fraction>`` is the scripted
      **spot-preemption wave**: downs ``ceil(fraction * hosts)`` of the
      currently-alive hosts AT ONCE, in host-list order (default 0.3 —
      a 30% capacity loss in one tick, the prodsim drill's scenario);
      ``restore`` brings every downed host back (spot capacity
      returning).

    ``fail_host`` / ``restore_host`` give tests direct control without
    the grammar.
    """

    name = "fake"

    def __init__(self, hosts: Optional[List[str]] = None,
                 log_dir: Optional[str] = None):
        super().__init__(hosts=list(hosts) if hosts else ["h0", "h1", "h2"],
                         log_dir=log_dir)
        self._lock = threading.Lock()
        self._down: set = set()
        self._live: List[WorkerHandle] = []

    def host_alive(self, host: str) -> bool:
        with self._lock:
            return host not in self._down

    def spawn(self, command: List[str], env: Dict[str, str],
              host: str, label: str = "worker") -> WorkerHandle:
        fault = faultinject.check("launch_spawn", host)
        if fault is not None:
            if fault.kind == "latency":
                time.sleep(float(fault.value or "0.05"))
            else:
                raise TransportError(
                    f"fake transport: injected spawn {fault.kind} "
                    f"on {host}")
        with self._lock:
            down = host in self._down
        if down:
            raise TransportError(f"fake transport: host {host} is down")
        handle = super().spawn(command, env, host, label=label)
        with self._lock:
            self._live.append(handle)
        return handle

    def tick(self) -> None:
        with self._lock:
            self._live = [h for h in self._live if h.proc.poll() is None]
            busy = bool(self._live)
        if not busy:
            return
        fault = faultinject.check("launch_host")
        if fault is None:
            return
        if fault.kind in ("kill", "down"):
            host = fault.value or self._hosts[0]
            LOG("WARNING", "fake transport: injected %s of host %s",
                fault.kind, host)
            self.fail_host(host)
        elif fault.kind == "wave":
            self.preempt_wave(float(fault.value or "0.3"))
        elif fault.kind == "restore":
            for host in self.down_hosts():
                LOG("INFO", "fake transport: injected restore of host %s",
                    host)
                self.restore_host(host)

    def preempt_wave(self, fraction: float = 0.3) -> List[str]:
        """Spot-preemption wave: down ``ceil(fraction * hosts)`` of the
        currently-alive hosts at once (host-list order, so the victim
        set is deterministic); returns the victims."""
        uniq = list(dict.fromkeys(self._hosts))    # dedupe, keep order
        alive = [h for h in uniq if self.host_alive(h)]
        n = min(len(alive), max(1, math.ceil(fraction * len(alive))))
        victims = alive[:n]
        LOG("WARNING", "fake transport: spot-preemption wave downs "
            "%d/%d hosts at once: %s", n, len(alive), victims)
        for host in victims:
            self.fail_host(host)
        return victims

    def fail_host(self, host: str) -> None:
        """Down a fake host: SIGKILL its live workers, refuse spawns."""
        with self._lock:
            self._down.add(host)
            victims = [h for h in self._live if h.host == host]
        for h in victims:
            self.signal(h, signal.SIGKILL)

    def restore_host(self, host: str) -> None:
        with self._lock:
            self._down.discard(host)

    def down_hosts(self) -> List[str]:
        with self._lock:
            return sorted(self._down)
