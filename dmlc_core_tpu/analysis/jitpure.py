"""jit-purity pass: traced functions must be pure.

A function handed to ``jax.jit`` (or AOT-compiled via
``lower().compile()`` — the argument is still the jit call's) executes
its Python body ONCE at trace time; anything environmental it reads is
frozen into the executable and anything it mutates happens once, not
per call.  Both are classic silent-wrongness bugs on a warm compile
cache, where the trace may not re-run for days.

Flagged inside a jitted function (and, transitively, every same-module
function it calls):

* ``os.environ`` / ``os.getenv`` reads — knob value baked at trace;
* clock reads (``time.*``, ``get_time``) — timestamp baked at trace;
* Python RNG (``random.*``) — one draw reused forever;
* metrics-registry calls (``default_registry``, ``serve_metrics``,
  ``*_metrics`` helpers, ``_metrics.*``) — a trace-time increment lies
  about per-call behavior;
* mutation of closed-over / global state (``global`` / ``nonlocal``
  declarations, subscript stores or mutator-method calls on free
  variables) — happens at trace, not per call.

Detection of jit roots: ``@jax.jit`` / ``@jit`` decorators,
``@partial(jax.jit, ...)``, and ``jax.jit(f)`` / ``jit(f)`` call sites
where ``f`` is a lambda or a function defined in the same module.
Resolution is same-module and name-based — cross-module roots are out
of scope (each module's kernels live next to their jit wrapper in this
repo).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from dmlc_core_tpu.analysis.engine import AnalysisContext

_METRIC_CALLS = {"default_registry", "serve_metrics"}
_METRIC_MODULES = {"metrics", "_metrics"}
#: NO "update"/"add" here (unlike the lock pass): ``tx.update(...)`` is
#: optax's PURE gradient transform and jnp-style ``.add`` is functional
#: — flagging them would condemn every optimizer step
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popleft", "clear",
    "discard", "setdefault", "appendleft", "sort", "reverse",
}
_MAX_DEPTH = 24


def _is_jit_expr(node: ast.expr) -> bool:
    """``jax.jit`` or bare ``jit`` (however imported)."""
    return ((isinstance(node, ast.Attribute) and node.attr == "jit")
            or (isinstance(node, ast.Name) and node.id == "jit"))


def _partial_jit(call: ast.Call) -> bool:
    """``partial(jax.jit, ...)`` / ``functools.partial(jax.jit, ...)``."""
    f = call.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else "")
    return (name == "partial" and bool(call.args)
            and _is_jit_expr(call.args[0]))


class _FuncIndex(ast.NodeVisitor):
    """name -> FunctionDef for every def in the module (nested included;
    later definitions shadow earlier ones, matching runtime rebinding)."""

    def __init__(self) -> None:
        self.defs: Dict[str, ast.FunctionDef] = {}

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.defs[node.name] = node
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]


def _jit_roots(tree: ast.AST, index: Dict[str, ast.FunctionDef]
               ) -> List[Tuple[str, ast.AST]]:
    """(display name, function node) for every traced function."""
    roots: List[Tuple[str, ast.AST]] = []
    seen: Set[int] = set()

    def add(name: str, fn: ast.AST) -> None:
        if id(fn) not in seen:
            seen.add(id(fn))
            roots.append((name, fn))

    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_expr(dec):
                    add(node.name, node)
                elif isinstance(dec, ast.Call) and (
                        _is_jit_expr(dec.func) or _partial_jit(dec)):
                    add(node.name, node)
        elif (isinstance(node, ast.Call) and _is_jit_expr(node.func)
              and node.args):
            arg = node.args[0]
            if isinstance(arg, ast.Lambda):
                add(f"<lambda:L{arg.lineno}>", arg)
            elif isinstance(arg, ast.Name) and arg.id in index:
                add(arg.id, index[arg.id])
    return roots


class _Impurity:
    __slots__ = ("line", "what", "key")

    def __init__(self, line: int, what: str, key: str) -> None:
        self.line = line
        self.what = what
        self.key = key


def _bound_names(fn: ast.AST) -> Set[str]:
    """Parameters + every name assigned within the function — anything
    else referenced is free (closed-over or global)."""
    bound: Set[str] = set()
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        a = fn.args
        for p in (list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)):
            bound.add(p.arg)
        if a.vararg:
            bound.add(a.vararg.arg)
        if a.kwarg:
            bound.add(a.kwarg.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            bound.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)) and node is not fn:
            bound.add(node.name)
    return bound


def _scan_body(fn: ast.AST, out: List[_Impurity]) -> Set[str]:
    """Collect impurities in one function; return the names it calls
    (for transitive same-module following)."""
    bound = _bound_names(fn)
    called: Set[str] = set()
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Global):
                out.append(_Impurity(
                    node.lineno, "declares global "
                    + ", ".join(node.names), "global"))
            elif isinstance(node, ast.Nonlocal):
                out.append(_Impurity(
                    node.lineno, "declares nonlocal "
                    + ", ".join(node.names), "nonlocal"))
            elif isinstance(node, ast.Attribute):
                base = node.value
                if (isinstance(base, ast.Name) and base.id == "os"
                        and node.attr in ("environ", "getenv")):
                    out.append(_Impurity(
                        node.lineno, f"reads os.{node.attr} at trace time",
                        "os-environ"))
                elif (isinstance(base, ast.Name) and base.id == "time"):
                    out.append(_Impurity(
                        node.lineno, f"reads the clock (time.{node.attr}) "
                        "at trace time", "clock"))
                elif (isinstance(base, ast.Name) and base.id == "random"):
                    out.append(_Impurity(
                        node.lineno, f"Python RNG (random.{node.attr}) "
                        "draws once at trace time", "py-rng"))
                elif (isinstance(base, ast.Name)
                      and base.id in _METRIC_MODULES):
                    out.append(_Impurity(
                        node.lineno, f"touches the metrics registry "
                        f"({base.id}.{node.attr}) at trace time",
                        "metrics"))
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Name):
                    called.add(f.id)
                    if f.id == "get_time":
                        out.append(_Impurity(
                            node.lineno, "reads the clock (get_time) at "
                            "trace time", "clock"))
                    elif (f.id in _METRIC_CALLS
                          or f.id.endswith("_metrics")):
                        out.append(_Impurity(
                            node.lineno, f"touches the metrics registry "
                            f"({f.id}()) at trace time", "metrics"))
                elif (isinstance(f, ast.Attribute)
                      and f.attr in _MUTATORS
                      and isinstance(f.value, ast.Name)
                      and f.value.id not in bound):
                    out.append(_Impurity(
                        node.lineno, f"mutates closed-over "
                        f"{f.value.id!r} (.{f.attr}) at trace time",
                        f"closure-mut:{f.value.id}"))
            elif (isinstance(node, (ast.Assign, ast.AugAssign))
                  or isinstance(node, ast.Delete)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [getattr(node, "target", None)]
                           if not isinstance(node, ast.Delete)
                           else node.targets)
                for t in targets:
                    if (isinstance(t, ast.Subscript)
                            and isinstance(t.value, ast.Name)
                            and t.value.id not in bound):
                        out.append(_Impurity(
                            t.lineno, f"subscript-stores into closed-over "
                            f"{t.value.id!r} at trace time",
                            f"closure-mut:{t.value.id}"))
    return called


def _analyze_root(name: str, fn: ast.AST,
                  index: Dict[str, ast.FunctionDef]
                  ) -> List[Tuple[str, _Impurity]]:
    """Scan ``fn`` and every same-module function it (transitively)
    calls; impurities are attributed to the function they occur in."""
    out: List[Tuple[str, _Impurity]] = []
    visited: Set[str] = set()
    frontier: List[Tuple[str, ast.AST]] = [(name, fn)]
    depth = 0
    while frontier and depth < _MAX_DEPTH:
        depth += 1
        nxt: List[Tuple[str, ast.AST]] = []
        for fname, fnode in frontier:
            if fname in visited:
                continue
            visited.add(fname)
            imps: List[_Impurity] = []
            called = _scan_body(fnode, imps)
            out.extend((fname, i) for i in imps)
            for c in called:
                if c in index and c not in visited:
                    nxt.append((c, index[c]))
        frontier = nxt
    return out


def run(ctx: AnalysisContext) -> None:
    for pf in ctx.files:
        if (pf.kind != "py" or pf.tree is None
                or not pf.rel.startswith("dmlc_core_tpu/")):
            continue
        index_v = _FuncIndex()
        index_v.visit(pf.tree)
        index = index_v.defs
        reported: Set[Tuple[str, str, int]] = set()
        for root_name, fn in _jit_roots(pf.tree, index):
            for where, imp in _analyze_root(root_name, fn, index):
                dedup = (root_name, imp.key, imp.line)
                if dedup in reported:
                    continue
                reported.add(dedup)
                via = "" if where == root_name else f" (via {where})"
                ctx.add(pf, imp.line, "jit-purity",
                        f"jitted {root_name}{via} {imp.what}",
                        key=f"{root_name}:{imp.key}")
