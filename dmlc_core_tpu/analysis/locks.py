"""Lock-discipline pass: shared mutable state must stay behind the lock.

Scope: classes in ``dmlc_core_tpu/`` that own a ``threading.Lock`` /
``RLock`` / ``Condition`` attribute (the repo's convention for
thread-shared objects — ThreadedIter, the serve batcher/registry, the
tracker, metrics, resilience).  For each such class the pass computes,
per ``self._*`` attribute:

* **guarded** — accessed at least once inside ``with self.<lock>:``
  (any of the class's lock attributes; a ``Condition`` built on the
  class lock guards the same monitor);
* **mutated after construction** — assigned / aug-assigned / subscript-
  stored / mutator-method-called (``append``, ``pop``, ``update``, ...)
  anywhere outside ``__init__``.

An attribute that is BOTH is part of the class's locked state, and
every access to it outside a ``with``-lock block (and outside
``__init__``, which happens-before publication) is a ``lock-discipline``
finding.  Attributes that are never locked anywhere are not flagged —
the pass hunts *inconsistent* locking, which is how real races read,
not lock-free designs.

Convention: a method named ``*_locked`` asserts "caller holds the
lock" and its body is treated as guarded (the tracker's
``_expire_graces_locked`` pattern).

``lock-release``: a bare ``x.acquire()`` statement must be immediately
followed by ``try:`` whose ``finally:`` releases — anything else leaks
the lock on the first exception.
"""

from __future__ import annotations

import ast
from typing import List, Set, Tuple

from dmlc_core_tpu.analysis.engine import AnalysisContext, ParsedFile

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popleft", "clear",
    "update", "add", "discard", "setdefault", "appendleft", "sort",
    "reverse",
}


def _is_lock_ctor(node: ast.expr) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else "")
    return name in _LOCK_FACTORIES


def _self_attr(node: ast.expr) -> str:
    """'x' for a ``self.x`` expression, else ''."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return ""


class _Access:
    __slots__ = ("attr", "line", "held", "in_init", "mutation", "method")

    def __init__(self, attr: str, line: int, held: bool, in_init: bool,
                 mutation: bool, method: str) -> None:
        self.attr = attr
        self.line = line
        self.held = held
        self.in_init = in_init
        self.mutation = mutation
        self.method = method


class _MethodScanner(ast.NodeVisitor):
    """Collect every ``self._*`` access in one method (nested closures
    included — they run on whatever thread calls them)."""

    def __init__(self, lock_attrs: Set[str], method: str) -> None:
        self.lock_attrs = lock_attrs
        self.method = method
        self.in_init = method in ("__init__", "__new__")
        # a *_locked method's whole body asserts the caller holds it
        self.held_depth = 1 if method.endswith("_locked") else 0
        self.accesses: List[_Access] = []

    # -- guard tracking --------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        locks_here = sum(
            1 for item in node.items
            if _self_attr(item.context_expr) in self.lock_attrs)
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        self.held_depth += locks_here
        for stmt in node.body:
            self.visit(stmt)
        self.held_depth -= locks_here

    # -- mutation forms --------------------------------------------------
    def _note(self, attr: str, line: int, mutation: bool) -> None:
        if attr and attr not in self.lock_attrs:
            self.accesses.append(_Access(
                attr, line, self.held_depth > 0, self.in_init, mutation,
                self.method))

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = _self_attr(node)
        if attr:
            self._note(attr, node.lineno,
                       mutation=isinstance(node.ctx, (ast.Store, ast.Del)))
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # self._d[k] = v / del self._d[k] mutate the CONTAINER: the
        # inner Attribute is a Load, so catch it here
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            attr = _self_attr(node.value)
            if attr:
                self._note(attr, node.lineno, mutation=True)
                self.visit(node.slice)
                return
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = _self_attr(node.target)
        if attr:
            self._note(attr, node.lineno, mutation=True)
        elif (isinstance(node.target, ast.Subscript)
              and _self_attr(node.target.value)):
            self._note(_self_attr(node.target.value), node.lineno,
                       mutation=True)
        self.visit(node.value)

    def visit_Call(self, node: ast.Call) -> None:
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS):
            attr = _self_attr(node.func.value)
            if attr:
                self._note(attr, node.lineno, mutation=True)
                for a in node.args:
                    self.visit(a)
                for kw in node.keywords:
                    self.visit(kw.value)
                return
        self.generic_visit(node)


def _class_lock_attrs(cls: ast.ClassDef) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and _is_lock_ctor(node.value):
            for t in node.targets:
                attr = _self_attr(t)
                if attr:
                    out.add(attr)
    return out


def _check_class(ctx: AnalysisContext, pf: ParsedFile,
                 cls: ast.ClassDef) -> None:
    lock_attrs = _class_lock_attrs(cls)
    if not lock_attrs:
        return
    accesses: List[_Access] = []
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sc = _MethodScanner(lock_attrs, item.name)
            for stmt in item.body:
                sc.visit(stmt)
            accesses.extend(sc.accesses)
    guarded: Set[str] = set()
    mutated_after_init: Set[str] = set()
    for a in accesses:
        if a.held:
            guarded.add(a.attr)
        if a.mutation and not a.in_init:
            mutated_after_init.add(a.attr)
    hot = guarded & mutated_after_init
    seen: Set[Tuple[str, int]] = set()
    for a in accesses:
        if (a.attr in hot and not a.held and not a.in_init
                and (a.attr, a.line) not in seen):
            seen.add((a.attr, a.line))
            ctx.add(pf, a.line, "lock-discipline",
                    f"{cls.name}.{a.attr} is lock-guarded elsewhere but "
                    f"accessed outside the lock in {a.method}()",
                    key=f"{cls.name}.{a.attr}:{a.method}")


def _check_acquire(ctx: AnalysisContext, pf: ParsedFile) -> None:
    for node in ast.walk(pf.tree):
        body_lists = [getattr(node, f, None)
                      for f in ("body", "orelse", "finalbody")]
        for stmts in body_lists:
            if not isinstance(stmts, list):
                continue
            for i, stmt in enumerate(stmts):
                if not (isinstance(stmt, ast.Expr)
                        and isinstance(stmt.value, ast.Call)
                        and isinstance(stmt.value.func, ast.Attribute)
                        and stmt.value.func.attr == "acquire"):
                    continue
                nxt = stmts[i + 1] if i + 1 < len(stmts) else None
                released = (
                    isinstance(nxt, ast.Try) and any(
                        isinstance(s, ast.Expr)
                        and isinstance(s.value, ast.Call)
                        and isinstance(s.value.func, ast.Attribute)
                        and s.value.func.attr == "release"
                        for fs in nxt.finalbody
                        for s in ast.walk(fs)))
                if not released:
                    target = ast.unparse(stmt.value.func.value)
                    ctx.add(pf, stmt.lineno, "lock-release",
                            f"{target}.acquire() is not followed by "
                            f"try/finally {target}.release() — the lock "
                            f"leaks on the first exception",
                            key=f"acquire:{target}")


def run(ctx: AnalysisContext, selected: Set[str]) -> None:
    for pf in ctx.files:
        if (pf.kind != "py" or pf.tree is None
                or not pf.rel.startswith("dmlc_core_tpu/")):
            continue
        if "lock-discipline" in selected:
            for node in ast.walk(pf.tree):
                if isinstance(node, ast.ClassDef):
                    _check_class(ctx, pf, node)
        if "lock-release" in selected:
            _check_acquire(ctx, pf)
