"""jax-discipline passes: the accelerator substrate's three contracts.

The concurrency layers (locks/blocking/atomicity) make thread bugs
structurally impossible; nothing did the same for the jit/XLA substrate
the perf thesis rests on.  Three rules close that gap, each paired with
the dynamic tracer :mod:`dmlc_core_tpu.base.jitcheck`:

* ``recompile-hazard`` — a compiled program must be built once and
  keyed on stable values.  Flagged: ``jax.jit(f)(x)`` built fresh per
  call (jit's cache keys on function identity, which a fresh wrapper
  always misses); jit/AOT construction inside a loop unless the result
  is stored into a ``*cache*``-named table (the ``_AOT_EXEC_CACHE`` /
  ``_ROUND_FN_CACHE`` idiom); dict/list/set literals or per-call
  f-strings/``.format`` at ``static_argnums`` positions (unhashable →
  TypeError, fresh strings → silent cache miss); and ``os.environ``
  reads inside ``*cache_key*`` functions (a mid-run env mutation flips
  the key and recompiles — route through ``base/knobs.py``).

* ``donation-discipline`` — ``base/compat.py`` disables donation on
  legacy jax because of a real use-after-donate corruption; every
  ``donate_argnums=`` must therefore be the compat gate's return value,
  never a literal, and an argument passed at a donated position is DEAD
  after the call: any later read of that name (before a rebinding
  store) is flagged.

* ``transfer-discipline`` — host↔device traffic belongs at ingest and
  result boundaries, not inside traced code or round loops.  Flagged:
  ``np.*`` / ``.item()`` / ``.tolist()`` / ``float()/int()/bool()`` of
  traced parameters inside jit-traced functions (host round-trip baked
  at trace, or ConcretizationTypeError); ``.item()`` / ``.tolist()``
  and loop-invariant ``device_put`` inside a round loop — a loop that
  dispatches a compiled executable — where every coercion is a device
  sync per round (``device_put`` feeding the executable call itself is
  ingest and exempt).

Jit-root discovery and same-module transitive following are shared
with :mod:`~dmlc_core_tpu.analysis.jitpure` (decorators,
``partial(jax.jit, ...)``, ``jax.jit(f)`` call sites); executable
*handles* additionally include names / ``self.*`` attributes assigned
from ``jax.jit(...)`` or ``.lower(...).compile()``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from dmlc_core_tpu.analysis.engine import AnalysisContext, ParsedFile
from dmlc_core_tpu.analysis.jitpure import (_FuncIndex, _is_jit_expr,
                                            _jit_roots, _partial_jit)

__all__ = ["run", "EXPLAIN"]

RULES = ("recompile-hazard", "donation-discipline", "transfer-discipline")

_MAX_DEPTH = 24

EXPLAIN = {
    "recompile-hazard": {
        "doc": "Call path that defeats jax's compile cache: a fresh "
               "jax.jit wrapper built per call (cache keys on function "
               "identity), jit/AOT construction inside a loop without "
               "storing into a *cache*-named table, an unhashable or "
               "per-call-fresh value (dict/list/set literal, f-string, "
               ".format) at a static_argnums position, or os.environ "
               "read inside a *cache_key* function (env mutation flips "
               "the key mid-run; route through base/knobs.py).  The "
               "dynamic companion is base/jitcheck.py, which fails "
               "drills on any steady-state compile.",
        "flagged": (
            "def step(self, x):\n"
            "    return jax.jit(self._kernel)(x)   # fresh wrapper = "
            "recompile\n"
            "\n"
            "def _cache_key(self):\n"
            "    return (self.depth,\n"
            "            os.environ.get('DMLC_FUSED_ROUND', 'auto'))\n"),
        "clean": (
            "def __init__(self):\n"
            "    self._kernel_jit = jax.jit(self._kernel)  # built once\n"
            "\n"
            "def step(self, x):\n"
            "    return self._kernel_jit(x)\n"
            "\n"
            "def _cache_key(self):\n"
            "    return (self.depth, knobs.value('DMLC_FUSED_ROUND'))\n"),
    },
    "donation-discipline": {
        "doc": "Donated buffers are freed for reuse by XLA the moment "
               "the call dispatches — base/compat.py gates donation off "
               "on legacy jax because a real use-after-donate corrupted "
               "results.  Two contracts: every donate_argnums= value "
               "must be the compat gate's return (donate_argnums(0), "
               "never the literal (0,)), and a name passed at a donated "
               "position must not be read again before it is rebound.",
        "flagged": (
            "step = jax.jit(update, donate_argnums=(0,))  # ungated\n"
            "new = step(state, grads)\n"
            "log(state.mean())      # read after donation: garbage\n"),
        "clean": (
            "from dmlc_core_tpu.base.compat import donate_argnums\n"
            "step = jax.jit(update, donate_argnums=donate_argnums(0))\n"
            "state = step(state, grads)   # rebinding kills the name\n"),
    },
    "transfer-discipline": {
        "doc": "Implicit host<->device traffic on a hot path: np.* / "
               ".item() / .tolist() / float()-of-parameter inside a "
               "jit-traced function (the transfer happens at trace and "
               "bakes a constant, or raises ConcretizationTypeError), "
               "or .item()/.tolist()/loop-invariant device_put inside "
               "a round loop — the loop that dispatches a compiled "
               "executable — where each is a per-round device sync.  "
               "device_put feeding the executable call itself is "
               "ingest and exempt.",
        "flagged": (
            "while done < n_trees:\n"
            "    cfg = jax.device_put(table)   # re-uploaded per round\n"
            "    preds = round_fn(preds, cfg)\n"
            "    total += preds.item()          # device sync per round\n"),
        "clean": (
            "cfg = jax.device_put(table)        # ingest: once\n"
            "while done < n_trees:\n"
            "    preds = round_fn(preds, cfg)\n"
            "total = float(preds.sum())         # one sync at the end\n"),
    },
}


# -- shared module model -----------------------------------------------------

def _call_name(func: ast.expr) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _const_nums(node: Optional[ast.expr]) -> Optional[Tuple[int, ...]]:
    """donate/static argnums as a tuple of ints when statically known:
    a literal int, a literal tuple of ints, or the compat gate call
    ``donate_argnums(0, 1)`` (whose runtime value is the nums or ());
    None when unknowable (a variable, ...)."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (isinstance(elt, ast.Constant)
                    and isinstance(elt.value, int)):
                return None
            out.append(elt.value)
        return tuple(out)
    if (isinstance(node, ast.Call)
            and _call_name(node.func) == "donate_argnums"):
        out = []
        for a in node.args:
            if not (isinstance(a, ast.Constant)
                    and isinstance(a.value, int)):
                return None
            out.append(a.value)
        return tuple(out)
    return None


def _is_compat_gated(node: Optional[ast.expr]) -> bool:
    """True when the donate_argnums= value goes through the
    base/compat.py gate (or is a variable we cannot prove literal)."""
    if node is None:
        return True
    if isinstance(node, ast.Call):
        return _call_name(node.func) == "donate_argnums"
    if isinstance(node, (ast.Constant, ast.Tuple, ast.List)):
        # () / (0,) / 0 literals bypass the gate
        if isinstance(node, ast.Constant) and node.value in ((), None):
            return True                    # empty donation is a no-op
        if isinstance(node, (ast.Tuple, ast.List)) and not node.elts:
            return True
        return False
    return True                            # Name/Attribute: resolved upstream


def _kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _jit_call_info(call: ast.Call) -> Optional[Dict[str, object]]:
    """For ``jax.jit(f, ...)`` / ``partial(jax.jit, ...)`` calls: the
    statically-known donate/static argnums and the gate verdict."""
    if _is_jit_expr(call.func):
        donate = _kwarg(call, "donate_argnums")
    elif _partial_jit(call):
        donate = _kwarg(call, "donate_argnums")
    else:
        return None
    return {
        "donate_kw": donate,
        "donate": _const_nums(donate),
        "static": _const_nums(_kwarg(call, "static_argnums")),
        "gated": _is_compat_gated(donate),
    }


def _compile_chain(call: ast.Call) -> bool:
    """``f.lower(...).compile()`` — AOT construction (same per-call /
    in-loop hazards as jax.jit)."""
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "compile"
            and isinstance(f.value, ast.Call)
            and isinstance(f.value.func, ast.Attribute)
            and f.value.func.attr == "lower")


def _cache_store_target(target: ast.expr) -> bool:
    """Assignment target that parks the executable in a cache table:
    a subscript whose base name mentions "cache" (``_AOT_EXEC_CACHE[k]``,
    ``self._multi_cache[K]``)."""
    if not isinstance(target, ast.Subscript):
        return False
    base = target.value
    name = (base.id if isinstance(base, ast.Name)
            else base.attr if isinstance(base, ast.Attribute) else "")
    return "cache" in name.lower()


class _Module:
    """Per-file model: jitted defs (with argnums), executable handles
    (names / self-attrs bound to compiled callables), function index."""

    def __init__(self, tree: ast.AST) -> None:
        iv = _FuncIndex()
        iv.visit(tree)
        self.index: Dict[str, ast.FunctionDef] = iv.defs
        #: callable ref ("name" or "self.attr") -> info dict
        self.jitted: Dict[str, Dict[str, object]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        info = _jit_call_info(dec)
                        if info is not None:
                            self.jitted[node.name] = info
                    elif _is_jit_expr(dec):
                        self.jitted[node.name] = {
                            "donate_kw": None, "donate": None,
                            "static": None, "gated": True}
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                v = node.value
                if not isinstance(v, ast.Call):
                    continue
                info = _jit_call_info(v)
                if info is None and _compile_chain(v):
                    info = {"donate_kw": None, "donate": None,
                            "static": None, "gated": True}
                if info is None:
                    continue
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    self.jitted[t.id] = info
                elif (isinstance(t, ast.Attribute)
                      and isinstance(t.value, ast.Name)
                      and t.value.id == "self"):
                    self.jitted[f"self.{t.attr}"] = info

    def handle_ref(self, func: ast.expr) -> Optional[str]:
        """The jitted-handle key a call dispatches through, or None."""
        if isinstance(func, ast.Name) and func.id in self.jitted:
            return func.id
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"
                and f"self.{func.attr}" in self.jitted):
            return f"self.{func.attr}"
        return None

    def is_executable_call(self, call: ast.Call) -> bool:
        """A dispatch of a compiled program: a known jitted handle, or
        a subscript of a *cache* table (``execs[label](...)``)."""
        if self.handle_ref(call.func) is not None:
            return True
        return _cache_store_target(call.func)  # Subscript of *cache*


def _enclosing_functions(tree: ast.AST) -> List[ast.FunctionDef]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


# -- recompile-hazard --------------------------------------------------------

_UNSTABLE_STATIC = (ast.Dict, ast.List, ast.Set, ast.JoinedStr)


def _check_recompile(ctx: AnalysisContext, pf: ParsedFile,
                     mod: _Module) -> None:
    for fn in _enclosing_functions(pf.tree):
        for node in ast.walk(fn):
            # (a) jax.jit(f)(x): fresh wrapper per call
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Call)
                    and (_is_jit_expr(node.func.func)
                         or _partial_jit(node.func))):
                ctx.add(pf, node.lineno, "recompile-hazard",
                        f"{fn.name} builds a fresh jax.jit wrapper per "
                        "call — jit's cache keys on function identity, "
                        "so every call recompiles; build the wrapper "
                        "once (module/__init__ scope or a *cache* table)",
                        key=f"{fn.name}:jit-per-call")
            # (b) jit/AOT construction inside a loop without cache store
            if isinstance(node, (ast.For, ast.While)):
                for stmt in node.body:
                    for sub in ast.walk(stmt):
                        if not isinstance(sub, ast.Call):
                            continue
                        is_ctor = (_is_jit_expr(sub.func)
                                   or _partial_jit(sub)
                                   or _compile_chain(sub))
                        if not is_ctor:
                            continue
                        cached = (isinstance(stmt, ast.Assign) and any(
                            _cache_store_target(t) for t in stmt.targets))
                        if not cached:
                            ctx.add(
                                pf, sub.lineno, "recompile-hazard",
                                f"{fn.name} constructs a jit/AOT "
                                "executable inside a loop without "
                                "storing it in a *cache* table — every "
                                "iteration recompiles",
                                key=f"{fn.name}:jit-in-loop")
            # (c) unstable values at static_argnums positions
            if isinstance(node, ast.Call):
                ref = mod.handle_ref(node.func)
                info = mod.jitted.get(ref) if ref else None
                static = info.get("static") if info else None
                if static:
                    for pos in static:
                        if pos >= len(node.args):
                            continue
                        arg = node.args[pos]
                        bad = isinstance(arg, _UNSTABLE_STATIC) or (
                            isinstance(arg, ast.Call)
                            and isinstance(arg.func, ast.Attribute)
                            and arg.func.attr == "format")
                        if bad:
                            what = ("an f-string/.format key built "
                                    "per call" if not isinstance(
                                        arg, (ast.Dict, ast.List,
                                              ast.Set))
                                    else "an unhashable literal")
                            ctx.add(
                                pf, arg.lineno, "recompile-hazard",
                                f"{fn.name} passes {what} at static "
                                f"position {pos} of jitted {ref} — "
                                "unhashable statics raise, fresh "
                                "strings miss the compile cache every "
                                "call",
                                key=f"{fn.name}:unstable-static:{ref}")
        # (d) os.environ reads inside cache-key builders
        if "cache_key" in fn.name:
            for node in ast.walk(fn):
                hit = None
                if (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "os"
                        and node.attr in ("environ", "getenv")):
                    hit = f"os.{node.attr}"
                if hit:
                    ctx.add(pf, node.lineno, "recompile-hazard",
                            f"{fn.name} reads {hit} while building a "
                            "compile-cache key — an env mutation "
                            "mid-run silently flips the key and "
                            "recompiles; read through "
                            "base/knobs.value() instead",
                            key=f"{fn.name}:env-cache-key")


# -- donation-discipline -----------------------------------------------------

def _name_events(fn: ast.AST, name: str) -> List[Tuple[int, str, int]]:
    """(lineno, 'load'|'store', node id) for every use of ``name``."""
    out = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id == name:
            kind = "store" if isinstance(
                node.ctx, (ast.Store, ast.Del)) else "load"
            out.append((node.lineno, kind, id(node)))
    out.sort()
    return out


def _check_donation(ctx: AnalysisContext, pf: ParsedFile,
                    mod: _Module) -> None:
    # (a) donate_argnums literals bypassing the base/compat gate
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Call):
            continue
        info = _jit_call_info(node)
        if info is not None and not info["gated"]:
            ctx.add(pf, node.lineno, "donation-discipline",
                    "donate_argnums passed as a literal — donation must "
                    "go through the base/compat.py gate "
                    "(donate_argnums(...)), which turns it off on jax "
                    "versions with the use-after-donate bug",
                    key=f"ungated:L-{_call_name(node.func) or 'jit'}")
    # (b) donated argument read after the call
    for fn in _enclosing_functions(pf.tree):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            ref = mod.handle_ref(node.func)
            info = mod.jitted.get(ref) if ref else None
            donate = info.get("donate") if info else None
            if not donate:
                continue
            for pos in donate:
                if pos >= len(node.args):
                    continue
                arg = node.args[pos]
                if not isinstance(arg, ast.Name):
                    continue
                for lineno, kind, nid in _name_events(fn, arg.id):
                    if lineno < node.lineno or nid == id(arg):
                        continue
                    if kind == "store":
                        break              # rebound: name is dead
                    ctx.add(pf, lineno, "donation-discipline",
                            f"{fn.name} reads {arg.id!r} after donating "
                            f"it to {ref} (argnum {pos}) — the buffer "
                            "is already reused by XLA; rebind the name "
                            "from the call's result or copy before "
                            "donating",
                            key=f"{fn.name}:use-after-donate:{arg.id}")
                    break
    # decorated defs with ungated literal donate (partial form caught
    # above via the decorator Call walk — nothing extra needed)


# -- transfer-discipline -----------------------------------------------------

def _static_param_names(fn: ast.AST,
                        static: Optional[Tuple[int, ...]]) -> Set[str]:
    if not static or not isinstance(
            fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return set()
    params = [p.arg for p in (list(fn.args.posonlyargs)
                              + list(fn.args.args))]
    return {params[i] for i in static if 0 <= i < len(params)}


def _check_traced_transfers(ctx: AnalysisContext, pf: ParsedFile,
                            mod: _Module) -> None:
    """np/.item/.tolist/float-of-parameter inside jit-traced code
    (root + transitive same-module callees, as in jitpure)."""
    roots = _jit_roots(pf.tree, mod.index)
    for root_name, root_fn in roots:
        static_names = _static_param_names(
            root_fn, (mod.jitted.get(root_name) or {}).get("static"))
        visited: Set[str] = set()
        frontier: List[Tuple[str, ast.AST]] = [(root_name, root_fn)]
        depth = 0
        reported: Set[Tuple[str, int]] = set()
        while frontier and depth < _MAX_DEPTH:
            depth += 1
            nxt: List[Tuple[str, ast.AST]] = []
            for fname, fnode in frontier:
                if fname in visited:
                    continue
                visited.add(fname)
                if fnode is root_fn and isinstance(
                        fnode, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                    params = {p.arg for p in
                              (list(fnode.args.posonlyargs)
                               + list(fnode.args.args)
                               + list(fnode.args.kwonlyargs))}
                else:
                    params = set()
                body = fnode.body if isinstance(fnode.body, list) \
                    else [fnode.body]
                for stmt in body:
                    for node in ast.walk(stmt):
                        if not isinstance(node, ast.Call):
                            continue
                        f = node.func
                        if isinstance(f, ast.Name):
                            if (f.id in ("float", "int", "bool")
                                    and len(node.args) == 1
                                    and isinstance(node.args[0],
                                                   ast.Name)
                                    and node.args[0].id in params
                                    and node.args[0].id
                                    not in static_names
                                    and fnode is root_fn):
                                key = (f"{fname}:coerce", node.lineno)
                                if key not in reported:
                                    reported.add(key)
                                    ctx.add(
                                        pf, node.lineno,
                                        "transfer-discipline",
                                        f"jitted {root_name} coerces "
                                        f"traced parameter "
                                        f"{node.args[0].id!r} with "
                                        f"{f.id}() — a device sync "
                                        "baked at trace time (or "
                                        "ConcretizationTypeError)",
                                        key=f"{root_name}:coerce:"
                                            f"{node.args[0].id}")
                            elif f.id in mod.index \
                                    and f.id not in visited:
                                nxt.append((f.id, mod.index[f.id]))
                        elif isinstance(f, ast.Attribute):
                            base = f.value
                            if (isinstance(base, ast.Name)
                                    and base.id in ("np", "numpy")):
                                key = (f"{fname}:np", node.lineno)
                                if key not in reported:
                                    reported.add(key)
                                    via = "" if fname == root_name \
                                        else f" (via {fname})"
                                    ctx.add(
                                        pf, node.lineno,
                                        "transfer-discipline",
                                        f"jitted {root_name}{via} "
                                        f"calls np.{f.attr} — numpy "
                                        "forces a host transfer of "
                                        "traced values (or raises); "
                                        "use jnp inside traced code",
                                        key=f"{root_name}:np:{f.attr}")
                            elif f.attr in ("item", "tolist"):
                                key = (f"{fname}:sync", node.lineno)
                                if key not in reported:
                                    reported.add(key)
                                    via = "" if fname == root_name \
                                        else f" (via {fname})"
                                    ctx.add(
                                        pf, node.lineno,
                                        "transfer-discipline",
                                        f"jitted {root_name}{via} "
                                        f"calls .{f.attr}() — host "
                                        "materialization inside "
                                        "traced code",
                                        key=f"{root_name}:sync:{f.attr}")
            frontier = nxt


def _check_round_loops(ctx: AnalysisContext, pf: ParsedFile,
                       mod: _Module) -> None:
    """.item()/.tolist()/loop-invariant device_put inside loops that
    dispatch a compiled executable."""
    for fn in _enclosing_functions(pf.tree):
        for loop in ast.walk(fn):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            exec_calls = [n for n in ast.walk(loop)
                          if isinstance(n, ast.Call)
                          and mod.is_executable_call(n)]
            if not exec_calls:
                continue
            #: nodes feeding the executable call = ingest, exempt
            fed: Set[int] = set()
            for c in exec_calls:
                for a in list(c.args) + [kw.value for kw in c.keywords]:
                    fed.update(id(n) for n in ast.walk(a))
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call) or id(node) in fed:
                    continue
                f = node.func
                if _call_name(f) == "device_put":
                    ctx.add(pf, node.lineno, "transfer-discipline",
                            f"{fn.name} calls device_put inside its "
                            "round loop (the loop dispatching a "
                            "compiled executable) — a host->device "
                            "upload per round; hoist to ingest",
                            key=f"{fn.name}:roundloop-device-put")
                elif (isinstance(f, ast.Attribute)
                        and f.attr in ("item", "tolist")):
                    ctx.add(pf, node.lineno, "transfer-discipline",
                            f"{fn.name} calls .{f.attr}() inside its "
                            "round loop — a blocking device sync per "
                            "round; accumulate on device and fetch "
                            "once after the loop",
                            key=f"{fn.name}:roundloop-sync:{f.attr}")


# -- driver ------------------------------------------------------------------

def _in_scope(rel: str) -> bool:
    return (rel.startswith("dmlc_core_tpu/")
            or rel.startswith("scripts/")
            or rel == "bench.py")


def run(ctx: AnalysisContext, selected: Set[str]) -> None:
    """Run the selected jax-discipline rules over every in-scope
    Python file (dmlc_core_tpu/, scripts/, bench.py — tests and
    examples build throwaway programs and are exempt)."""
    for pf in ctx.files:
        if pf.kind != "py" or pf.tree is None or not _in_scope(pf.rel):
            continue
        mod = _Module(pf.tree)
        if "recompile-hazard" in selected:
            _check_recompile(ctx, pf, mod)
        if "donation-discipline" in selected:
            _check_donation(ctx, pf, mod)
        if "transfer-discipline" in selected:
            _check_traced_transfers(ctx, pf, mod)
            _check_round_loops(ctx, pf, mod)
