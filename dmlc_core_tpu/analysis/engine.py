"""dmlcheck core: file walker, suppression grammar, baseline, driver.

Every pass shares ONE ``ast.parse`` per file (the walker parses up
front; ``scripts/lint.py``'s former per-check re-parse is folded in
here).  A pass is a function ``run(ctx)`` that reads ``ctx.files`` and
calls ``ctx.add(...)``; the driver then applies suppressions and the
baseline and reports what survives.

Suppression grammar (checked against the finding's line):

* ``# dmlcheck: off`` — trailing comment: suppress every rule on that
  line; as a standalone comment within the first 10 lines of a file it
  suppresses the whole file.
* ``# dmlcheck: off:rule1[,rule2]`` — same scoping, named rules only.

Baseline: a committed JSON file of finding *fingerprints* (no line
numbers — fingerprints survive unrelated edits).  A finding whose
fingerprint is baselined is reported as grandfathered, not a failure;
stale entries (fingerprints that no longer fire) are surfaced so the
baseline shrinks monotonically.
"""

from __future__ import annotations

import ast
import io
import json
import os
import pickle
import re
import time
import tokenize
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

__all__ = [
    "ALL_RULES", "AnalysisContext", "Finding", "ParsedFile", "analyze",
    "default_files", "load_baseline", "rule_help", "write_baseline",
]

#: every rule dmlcheck knows; ``--rules`` selects a subset
ALL_RULES: Tuple[str, ...] = (
    "syntax", "unused-import", "style",
    "lock-discipline", "lock-release",
    "lock-blocking", "atomicity",
    "jit-purity",
    "recompile-hazard", "donation-discipline", "transfer-discipline",
    "knob-registry", "knob-doc",
    "metric-registry", "metric-doc",
    "resource-leak", "thread-lifecycle",
    "collective-discipline", "wire-schema",
)

#: directories walked relative to the repo root (mirrors scripts/lint.py)
PY_DIRS = ("dmlc_core_tpu", "tests", "scripts", "examples")
CPP_DIRS = ("cpp",)
ROOT_FILES = ("bench.py", "__graft_entry__.py", "dmlc-submit")

_SUPPRESS_RE = re.compile(r"#\s*dmlcheck:\s*off(?::([A-Za-z0-9_,-]+))?")
#: standalone suppression comments this early in the file scope the
#: whole file instead of one line
_FILE_SCOPE_LINES = 10


@dataclass(frozen=True)
class Finding:
    """One reported contract violation."""

    path: str        # repo-relative, '/'-separated
    line: int
    rule: str
    message: str
    #: stable context (class.attr, knob name, ...) — line numbers drift,
    #: fingerprints must not
    key: str

    @property
    def fingerprint(self) -> str:
        return f"{self.path}::{self.rule}::{self.key}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class ParsedFile:
    """One walked file: source + (for Python) its single shared AST."""

    def __init__(self, abspath: str, rel: str, kind: str) -> None:
        self.abspath = abspath
        self.rel = rel
        self.kind = kind                      # "py" | "cpp"
        with open(abspath, encoding="utf-8") as f:
            self.src = f.read()
        self.lines = self.src.splitlines()
        self.tree: Optional[ast.AST] = None
        self.syntax_error: Optional[SyntaxError] = None
        if kind == "py":
            try:
                self.tree = ast.parse(self.src, filename=rel)
            except SyntaxError as e:
                self.syntax_error = e
        # line -> suppressed rule names (empty set == all rules)
        self.suppress: Dict[int, Set[str]] = {}
        self.file_suppress: Optional[Set[str]] = None
        self._scan_suppressions()

    def _iter_comments(self):
        """(lineno, comment_text, standalone) for every real comment —
        tokenized for Python (a docstring describing the suppression
        grammar must not trigger it), regex-per-line for C++."""
        if self.kind == "py":
            try:
                for tok in tokenize.generate_tokens(
                        io.StringIO(self.src).readline):
                    if tok.type == tokenize.COMMENT:
                        standalone = tok.line[:tok.start[1]].strip() == ""
                        yield tok.start[0], tok.string, standalone
            except (tokenize.TokenError, IndentationError, SyntaxError):
                return
        else:
            for i, line in enumerate(self.lines, 1):
                if "#" in line:
                    idx = line.index("#")
                    yield i, line[idx:], line[:idx].strip() == ""

    def _scan_suppressions(self) -> None:
        for i, comment, standalone in self._iter_comments():
            m = _SUPPRESS_RE.search(comment)
            if not m:
                continue
            rules = (set(m.group(1).split(",")) if m.group(1) else set())
            bad = rules - set(ALL_RULES)
            if bad:
                # an unknown rule name silently suppressing nothing is
                # worse than a loud config error
                raise ValueError(
                    f"{self.rel}:{i}: unknown dmlcheck rule(s) in "
                    f"suppression: {sorted(bad)}")
            if standalone and i <= _FILE_SCOPE_LINES:
                if self.file_suppress is None:
                    self.file_suppress = set()
                if rules:
                    self.file_suppress |= rules
                else:
                    self.file_suppress = set(ALL_RULES)
            else:
                cur = self.suppress.setdefault(i, set())
                if rules:
                    cur |= rules
                elif not cur:
                    self.suppress[i] = set(ALL_RULES)

    def suppressed(self, rule: str, line: int) -> bool:
        if self.file_suppress is not None and (
                not self.file_suppress or rule in self.file_suppress):
            return True
        rules = self.suppress.get(line)
        return rules is not None and rule in rules


@dataclass
class AnalysisContext:
    """What every pass sees: the parsed files plus repo-level inputs."""

    root: str
    files: List[ParsedFile]
    #: declared knob names -> declaration line in base/knobs.py
    knobs: Dict[str, int] = field(default_factory=dict)
    knobs_rel: str = "dmlc_core_tpu/base/knobs.py"
    #: doc-page name -> full text (knob/metric documentation checks)
    docs: Dict[str, str] = field(default_factory=dict)
    findings: List[Finding] = field(default_factory=list)
    suppressed_count: int = 0
    #: pass-module name -> wall seconds spent, filled by ``analyze`` so
    #: the CLI can attribute the 10s CI budget
    pass_seconds: Dict[str, float] = field(default_factory=dict)
    #: incremental-cache accounting filled by ``analyze`` when a cache
    #: path was given: files/hits counts plus whether the whole finding
    #: set was reused (``--timings`` reports the hit rate)
    cache_stats: Dict[str, Any] = field(default_factory=dict)

    def add(self, pf: ParsedFile, line: int, rule: str, message: str,
            key: str) -> None:
        if pf.suppressed(rule, line):
            self.suppressed_count += 1
            return
        self.findings.append(Finding(pf.rel, line, rule, message, key))

    def add_at(self, rel: str, line: int, rule: str, message: str,
               key: str) -> None:
        """Report against a path that may not be a walked file (e.g. a
        missing doc page); no suppression applies."""
        self.findings.append(Finding(rel, line, rule, message, key))


def default_files(root: str) -> List[Tuple[str, str]]:
    """(abspath, kind) for the repo's whole analyzable surface — the
    same walk scripts/lint.py used, now shared by every pass."""
    out: List[Tuple[str, str]] = []
    for d in PY_DIRS:
        base = os.path.join(root, d)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames.sort()
            for f in sorted(filenames):
                if f.endswith(".py"):
                    out.append((os.path.join(dirpath, f), "py"))
    for d in CPP_DIRS:
        base = os.path.join(root, d)
        if os.path.isdir(base):
            for f in sorted(os.listdir(base)):
                if f.endswith((".cc", ".h", ".cpp")):
                    out.append((os.path.join(base, f), "cpp"))
    for f in ROOT_FILES:
        p = os.path.join(root, f)
        if os.path.exists(p):
            out.append((p, "py"))
    return out


def _load_knob_registry(root: str, rel: str) -> Dict[str, int]:
    """Parse base/knobs.py statically (no import): every
    ``declare("DMLC_X", ...)`` call is a registry entry."""
    path = os.path.join(root, rel)
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=rel)
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "declare"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            out[node.args[0].value] = node.lineno
    return out


def _load_docs(root: str) -> Dict[str, str]:
    doc_dir = os.path.join(root, "doc")
    out: Dict[str, str] = {}
    if not os.path.isdir(doc_dir):
        return out
    for dirpath, dirnames, filenames in os.walk(doc_dir):
        dirnames.sort()
        for f in sorted(filenames):
            if f.endswith(".md"):
                p = os.path.join(dirpath, f)
                with open(p, encoding="utf-8") as fh:
                    out[os.path.relpath(p, root).replace(os.sep, "/")] = \
                        fh.read()
    return out


#: bump on any ParsedFile / Finding layout change — stale pickled
#: cache entries from an older engine must never deserialize
_CACHE_VERSION = 1


def _stat_key(path: str) -> List[int]:
    st = os.stat(path)
    return [st.st_mtime_ns, st.st_size]


def _extra_state(root: str) -> Dict[str, List[int]]:
    """(mtime, size) of every input that feeds passes OUTSIDE the
    walked file set — the analysis sources themselves and the doc
    pages (knob/metric/wire docs gate findings on files that did not
    change).  Any drift here invalidates the whole-run finding reuse
    (per-file finding caching is unsound anyway: metric-registry and
    wire-schema findings cross files)."""
    out: Dict[str, List[int]] = {}
    adir = os.path.dirname(os.path.abspath(__file__))
    for f in sorted(os.listdir(adir)):
        if f.endswith(".py"):
            out["analysis:" + f] = _stat_key(os.path.join(adir, f))
    doc_dir = os.path.join(root, "doc")
    if os.path.isdir(doc_dir):
        for dirpath, dirnames, filenames in os.walk(doc_dir):
            dirnames.sort()
            for f in sorted(filenames):
                if f.endswith(".md"):
                    p = os.path.join(dirpath, f)
                    out[os.path.relpath(p, root).replace(os.sep, "/")] \
                        = _stat_key(p)
    return out


def _load_cache(path: Optional[str]) -> Optional[Dict[str, Any]]:
    if not path or not os.path.exists(path):
        return None
    try:
        with open(path, "rb") as f:
            data = pickle.load(f)
        if data.get("version") != _CACHE_VERSION:
            return None
        return data
    except Exception:  # noqa: BLE001 — any corrupt cache = cold run
        return None


def _write_cache(path: str, data: Dict[str, Any]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            pickle.dump(data, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, path)
    except OSError:
        # a read-only checkout must not fail the analysis
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def analyze(root: str,
            files: Optional[Sequence[Tuple[str, str]]] = None,
            rules: Optional[Sequence[str]] = None,
            cache_path: Optional[str] = None) -> AnalysisContext:
    """Parse once, run the selected passes, return the context (findings
    NOT yet baseline-filtered — the CLI owns that policy).  Per-pass
    wall time lands in ``ctx.pass_seconds``.

    ``cache_path`` enables the incremental cache: per-file pickled
    parses keyed on (mtime_ns, size) make re-parses cheap, and when
    EVERY input is unchanged (files, docs, analysis sources, rule
    selection) the previous run's findings are reused outright and no
    pass executes.  Finding reuse is all-or-nothing by design — the
    registry/protocol passes emit cross-file findings, so a per-file
    finding cache would silently miss e.g. a duplicate metric declared
    in an unchanged file."""
    # late imports: engine <-> passes would otherwise cycle
    from dmlc_core_tpu.analysis import (atomicity, blocking, jaxpass,
                                        jitpure, locks, protocol,
                                        registries, resources, style)

    if files is None:
        files = default_files(root)
    selected = set(rules) if rules is not None else set(ALL_RULES)
    bad = selected - set(ALL_RULES)
    if bad:
        raise ValueError(f"unknown dmlcheck rule(s): {sorted(bad)}")
    t0 = time.perf_counter()
    cache = _load_cache(cache_path)
    cached_files: Dict[str, Dict[str, Any]] = \
        (cache or {}).get("files", {})
    parsed: List[ParsedFile] = []
    new_entries: Dict[str, Dict[str, Any]] = {}
    hits = 0
    for p, kind in files:
        rel = os.path.relpath(p, root).replace(os.sep, "/")
        key = _stat_key(p) + [kind]
        ent = cached_files.get(rel)
        pf: Optional[ParsedFile] = None
        if ent is not None and ent["key"] == key:
            try:
                pf = pickle.loads(ent["blob"])
                hits += 1
            except Exception:  # noqa: BLE001 — corrupt entry = reparse
                pf = None
        if pf is None:
            pf = ParsedFile(p, rel, kind)
            ent = {"key": key,
                   "blob": pickle.dumps(
                       pf, protocol=pickle.HIGHEST_PROTOCOL)}
        new_entries[rel] = ent
        parsed.append(pf)
    ctx = AnalysisContext(root=root, files=parsed)
    ctx.pass_seconds["parse"] = time.perf_counter() - t0
    extra = _extra_state(root) if cache_path else {}
    if cache_path:
        ctx.cache_stats = {"files": len(parsed), "hits": hits,
                           "findings_reused": False}

    rules_key = sorted(selected)
    if (cache is not None
            and hits == len(parsed)
            and set(cached_files) == set(new_entries)
            and cache.get("extra") == extra
            and cache.get("rules") == rules_key
            and cache.get("findings") is not None):
        # full hit: every input byte-stable since the cached run —
        # reuse its findings, run nothing
        ctx.findings = [Finding(*t) for t in cache["findings"]]
        ctx.suppressed_count = cache.get("suppressed", 0)
        ctx.cache_stats["findings_reused"] = True
        return ctx

    ctx.knobs = _load_knob_registry(root, ctx.knobs_rel)
    ctx.docs = _load_docs(root)

    def _timed(name: str, fn, *args) -> None:
        t = time.perf_counter()
        fn(*args)
        ctx.pass_seconds[name] = time.perf_counter() - t

    if selected & {"syntax", "unused-import", "style"}:
        _timed("style", style.run, ctx, selected)
    if selected & {"lock-discipline", "lock-release"}:
        _timed("locks", locks.run, ctx, selected)
    if "lock-blocking" in selected:
        _timed("blocking", blocking.run, ctx, selected)
    if "atomicity" in selected:
        _timed("atomicity", atomicity.run, ctx, selected)
    if "jit-purity" in selected:
        _timed("jitpure", jitpure.run, ctx)
    if selected & {"recompile-hazard", "donation-discipline",
                   "transfer-discipline"}:
        _timed("jaxpass", jaxpass.run, ctx, selected)
    if selected & {"knob-registry", "knob-doc", "metric-registry",
                   "metric-doc"}:
        _timed("registries", registries.run, ctx, selected)
    if selected & {"resource-leak", "thread-lifecycle"}:
        _timed("resources", resources.run, ctx, selected)
    if selected & {"collective-discipline", "wire-schema"}:
        _timed("protocol", protocol.run, ctx, selected)
    ctx.findings.sort(key=lambda f: (f.path, f.line, f.rule, f.key))
    if cache_path:
        _write_cache(cache_path, {
            "version": _CACHE_VERSION,
            "files": new_entries,
            "extra": extra,
            "rules": rules_key,
            "findings": [(f.path, f.line, f.rule, f.message, f.key)
                         for f in ctx.findings],
            "suppressed": ctx.suppressed_count,
        })
    return ctx


def rule_help(rule: str) -> Dict[str, str]:
    """``--explain`` payload for ``rule``: the pass's one-paragraph doc
    plus a minimal flagged/clean source pair.  Falls back to the pass
    module's docstring for rules without a curated example."""
    from dmlc_core_tpu.analysis import (atomicity, blocking, jaxpass,
                                        jitpure, locks, protocol,
                                        registries, resources, style)

    if rule not in ALL_RULES:
        raise ValueError(f"unknown dmlcheck rule: {rule}")
    owners = {
        "syntax": style, "unused-import": style, "style": style,
        "lock-discipline": locks, "lock-release": locks,
        "lock-blocking": blocking, "atomicity": atomicity,
        "jit-purity": jitpure,
        "recompile-hazard": jaxpass, "donation-discipline": jaxpass,
        "transfer-discipline": jaxpass,
        "knob-registry": registries, "knob-doc": registries,
        "metric-registry": registries, "metric-doc": registries,
        "resource-leak": resources, "thread-lifecycle": resources,
        "collective-discipline": protocol, "wire-schema": protocol,
    }
    mod = owners[rule]
    entry = getattr(mod, "EXPLAIN", {}).get(rule)
    if entry is None:
        entry = {"doc": (mod.__doc__ or "").strip(),
                 "flagged": "", "clean": ""}
    return dict(entry, rule=rule, module=mod.__name__)


# -- baseline ---------------------------------------------------------------

def load_baseline(path: str) -> Set[str]:
    """Grandfathered finding fingerprints from a baseline file (empty
    set when the file does not exist)."""
    if not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return set(data.get("findings", []))


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    """Persist ``findings`` as the new baseline (fingerprints only, so
    entries survive line drift)."""
    data = {
        "comment": "dmlcheck grandfathered findings — shrink, never grow "
                   "(see doc/static_analysis.md)",
        "findings": sorted({f.fingerprint for f in findings}),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1)
        f.write("\n")
