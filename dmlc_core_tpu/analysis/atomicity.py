"""``atomicity`` pass: lost-update shapes on mixed-locking attributes.

``lock-discipline`` (PR 5) answers *"is every access to locked state
under the lock?"*.  This pass answers the sharper question races are
actually made of: *"is a compound — read-modify-write or
check-then-act — executed unlocked on an attribute the class locks
elsewhere?"*.  Scope is the same: classes in ``dmlc_core_tpu/`` that
own a ``Lock``/``RLock``/``Condition`` attribute.

For each ``self._*`` attribute with MIXED discipline — at least one
access inside ``with self.<lock>:`` (or a ``*_locked`` method) and at
least one outside (``__init__`` excluded; construction happens-before
publication) — the pass flags, when they happen *outside* the lock:

* **read-modify-write**: ``self._x += ...``, or
  ``self._x = <expr reading self._x>`` — two threads interleave
  between the read and the store and one update is lost;
* **check-then-act**: an ``if`` whose test reads ``self._x`` and whose
  body (or ``else``) *writes* ``self._x`` — the state can change
  between the check and the act.

Attributes that are never locked anywhere are not flagged (lock-free
designs are a choice, not an accident); neither are compound ops that
sit entirely inside the lock.  Intentional unlocked compounds carry
``# dmlcheck: off:atomicity`` plus a rationale, mirroring the registry
hot-path suppressions.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from dmlc_core_tpu.analysis.engine import AnalysisContext, ParsedFile
from dmlc_core_tpu.analysis.locks import (_MUTATORS, _class_lock_attrs,
                                          _self_attr)

__all__ = ["run", "EXPLAIN"]

EXPLAIN = {
    "atomicity": {
        "doc": "Read-modify-write (`self._x += ...`, "
               "`self._x = self._x + ...`) or check-then-act "
               "(`if self._x: ... self._x = ...`) executed OUTSIDE the "
               "lock on an attribute the class locks elsewhere — the "
               "compound is not atomic, so interleaving threads lose "
               "updates or act on stale checks.  Attributes that are "
               "never locked anywhere are not flagged.",
        "flagged": (
            "class Counter:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0\n"
            "    def snapshot(self):\n"
            "        with self._lock:\n"
            "            return self._n\n"
            "    def bump(self):\n"
            "        self._n += 1        # unlocked RMW: updates lost\n"),
        "clean": (
            "class Counter:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "        self._n = 0\n"
            "    def snapshot(self):\n"
            "        with self._lock:\n"
            "            return self._n\n"
            "    def bump(self):\n"
            "        with self._lock:\n"
            "            self._n += 1    # compound is atomic now\n"),
    },
}


def _reads_of(node: ast.AST) -> Set[str]:
    """Names of every ``self._x`` read anywhere under ``node``."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load):
            attr = _self_attr(n)
            if attr:
                out.add(attr)
    return out


def _writes_of(node: ast.AST) -> Set[str]:
    """Names of every ``self._x`` written / aug-assigned / mutated
    (container store, mutator-method call) anywhere under ``node``."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and isinstance(
                n.ctx, (ast.Store, ast.Del)):
            attr = _self_attr(n)
            if attr:
                out.add(attr)
        elif isinstance(n, ast.AugAssign):
            attr = _self_attr(n.target)
            if attr:
                out.add(attr)
        elif (isinstance(n, ast.Subscript)
              and isinstance(n.ctx, (ast.Store, ast.Del))):
            attr = _self_attr(n.value)
            if attr:
                out.add(attr)
        elif (isinstance(n, ast.Call)
              and isinstance(n.func, ast.Attribute)
              and n.func.attr in _MUTATORS):
            attr = _self_attr(n.func.value)
            if attr:
                out.add(attr)
    return out


class _Compound:
    """One RMW / check-then-act occurrence on a ``self._*`` attribute."""

    __slots__ = ("attr", "line", "held", "in_init", "kind", "method")

    def __init__(self, attr: str, line: int, held: bool, in_init: bool,
                 kind: str, method: str) -> None:
        self.attr = attr
        self.line = line
        self.held = held
        self.in_init = in_init
        self.kind = kind                      # "rmw" | "check-then-act"
        self.method = method


class _AtomicityScanner(ast.NodeVisitor):
    """Collect accesses + compound shapes for one method."""

    def __init__(self, lock_attrs: Set[str], method: str) -> None:
        self.lock_attrs = lock_attrs
        self.method = method
        self.in_init = method in ("__init__", "__new__")
        self.held_depth = 1 if method.endswith("_locked") else 0
        #: attr -> set of held-states seen (True/False), init excluded
        self.access_held: Dict[str, Set[bool]] = {}
        self.compounds: List[_Compound] = []

    def _note_access(self, attr: str) -> None:
        if attr and attr not in self.lock_attrs and not self.in_init:
            self.access_held.setdefault(attr, set()).add(
                self.held_depth > 0)

    def _note_compound(self, attr: str, line: int, kind: str) -> None:
        if attr and attr not in self.lock_attrs:
            self.compounds.append(_Compound(
                attr, line, self.held_depth > 0, self.in_init, kind,
                self.method))

    def visit_With(self, node: ast.With) -> None:
        locks_here = sum(
            1 for item in node.items
            if _self_attr(item.context_expr) in self.lock_attrs)
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        self.held_depth += locks_here
        for stmt in node.body:
            self.visit(stmt)
        self.held_depth -= locks_here

    def visit_Attribute(self, node: ast.Attribute) -> None:
        self._note_access(_self_attr(node))
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = _self_attr(node.target)
        if attr:
            self._note_access(attr)
            self._note_compound(attr, node.lineno, "rmw")
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        reads = _reads_of(node.value)
        for t in node.targets:
            attr = _self_attr(t)
            if attr and attr in reads:
                self._note_compound(attr, node.lineno, "rmw")
        self.generic_visit(node)

    def visit_If(self, node: ast.If) -> None:
        checked = _reads_of(node.test)
        if checked:
            acted = _writes_of(ast.Module(body=node.body, type_ignores=[]))
            if node.orelse:
                acted |= _writes_of(
                    ast.Module(body=node.orelse, type_ignores=[]))
            for attr in sorted(checked & acted):
                self._note_compound(attr, node.lineno, "check-then-act")
        self.generic_visit(node)


def _check_class(ctx: AnalysisContext, pf: ParsedFile,
                 cls: ast.ClassDef) -> None:
    lock_attrs = _class_lock_attrs(cls)
    if not lock_attrs:
        return
    access_held: Dict[str, Set[bool]] = {}
    compounds: List[_Compound] = []
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sc = _AtomicityScanner(lock_attrs, item.name)
            for stmt in item.body:
                sc.visit(stmt)
            for attr, held_states in sc.access_held.items():
                access_held.setdefault(attr, set()).update(held_states)
            compounds.extend(sc.compounds)
    mixed = {a for a, hs in access_held.items() if hs == {True, False}}
    seen: Set[Tuple[str, int]] = set()
    for c in compounds:
        if (c.attr in mixed and not c.held and not c.in_init
                and (c.attr, c.line) not in seen):
            seen.add((c.attr, c.line))
            what = ("read-modify-write" if c.kind == "rmw"
                    else "check-then-act")
            ctx.add(pf, c.line, "atomicity",
                    f"{cls.name}.{c.attr} is locked elsewhere but "
                    f"{c.method}() runs an unlocked {what} on it — the "
                    f"compound is not atomic",
                    key=f"{cls.name}.{c.attr}:{c.method}:{c.kind}")


def run(ctx: AnalysisContext, selected: Set[str]) -> None:
    """Run the ``atomicity`` pass over every parsed repo file."""
    if "atomicity" not in selected:
        return
    for pf in ctx.files:
        if (pf.kind != "py" or pf.tree is None
                or not pf.rel.startswith("dmlc_core_tpu/")):
            continue
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.ClassDef):
                _check_class(ctx, pf, node)
