"""Style + import hygiene pass (``scripts/lint.py`` folded in).

Rules: ``syntax`` (file must parse), ``unused-import`` (names imported
but never referenced; ``# noqa`` opts a line out, ``__init__.py``
re-exports are exempt, ``__all__`` counts as use), ``style`` (trailing
whitespace, tabs in Python indentation, lines > 100 columns).  C++
files under ``cpp/`` get the ``style`` checks only.  The AST comes from
the shared walker — one parse serves this pass and every other.
"""

from __future__ import annotations

import ast
import os
from typing import Set

from dmlc_core_tpu.analysis.engine import AnalysisContext, ParsedFile

MAX_LINE = 100


class _ImportUse(ast.NodeVisitor):
    """Imported names and every referenced name root."""

    def __init__(self) -> None:
        self.imports = {}     # name -> lineno
        self.used: Set[str] = set()

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.imports[(a.asname or a.name).split(".")[0]] = node.lineno

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        if node.module == "__future__":
            return
        for a in node.names:
            if a.name != "*":
                self.imports[a.asname or a.name] = node.lineno

    def visit_Name(self, node: ast.Name) -> None:
        self.used.add(node.id)


def _check_imports(ctx: AnalysisContext, pf: ParsedFile) -> None:
    if os.path.basename(pf.rel) == "__init__.py":
        return                       # packages import purely to re-export
    v = _ImportUse()
    v.visit(pf.tree)
    exported: Set[str] = set()
    for node in ast.walk(pf.tree):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "__all__"
                and isinstance(node.value, (ast.List, ast.Tuple))):
            exported = {e.value for e in node.value.elts
                        if isinstance(e, ast.Constant)}
    for name, lineno in sorted(v.imports.items(), key=lambda kv: kv[1]):
        if name in v.used or name in exported:
            continue
        if lineno <= len(pf.lines) and "noqa" in pf.lines[lineno - 1]:
            continue
        ctx.add(pf, lineno, "unused-import",
                f"unused import {name!r}", key=name)


def _check_text(ctx: AnalysisContext, pf: ParsedFile) -> None:
    for i, line in enumerate(pf.lines, 1):
        if line != line.rstrip():
            ctx.add(pf, i, "style", "trailing whitespace",
                    key=f"ws:{i}")
        if (pf.kind == "py"
                and "\t" in line[:len(line) - len(line.lstrip())]):
            ctx.add(pf, i, "style", "tab in indentation", key=f"tab:{i}")
        if len(line) > MAX_LINE:
            ctx.add(pf, i, "style",
                    f"line longer than {MAX_LINE} columns ({len(line)})",
                    key=f"len:{i}")


def run(ctx: AnalysisContext, selected: Set[str]) -> None:
    for pf in ctx.files:
        if pf.kind == "py" and pf.syntax_error is not None:
            if "syntax" in selected:
                e = pf.syntax_error
                ctx.add(pf, e.lineno or 1, "syntax",
                        f"syntax error: {e.msg}", key=str(e.msg))
        elif (pf.kind == "py" and "unused-import" in selected):
            _check_imports(ctx, pf)
        if "style" in selected:
            _check_text(ctx, pf)
