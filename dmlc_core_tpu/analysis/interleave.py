"""Schedule-exploration model checker for the concurrency suite.

Fourth layer: dmlcheck proves locking *shape* statically, ``lockcheck``
proves lock *order* and ``racecheck`` proves *this run* was race-free —
but none of them explores the runs that did NOT happen.  This module
does: it runs a small concurrent model under a **cooperative
scheduler** where only one thread executes at a time and every context
switch happens at an explicit decision point (traced sync operations,
instrumented attribute accesses, ``sched.choose``).  The sequence of
decisions IS the schedule, so schedules are deterministic, replayable
and enumerable:

* **randomized** exploration — seeded random choices, one schedule per
  seed;
* **bounded-exhaustive** exploration — depth-first over the decision
  tree: replay a prefix, diverge at one decision, run deterministically
  to completion; every alternative of every visited decision goes on
  the frontier (classic stateless model checking, bounded by the
  schedule budget instead of a depth cut).

Time is logical: ``time.monotonic``/``time.sleep``/``get_time`` are
patched to a scheduler clock that only advances when every task is
blocked on a deadline (so timeouts fire deterministically and a
``max_delay=2ms`` batcher flush explores the same schedules as a 2 s
one).

Built-in models (:func:`builtin_models`) prove the serving stack's
four core concurrency invariants — CircuitBreaker's single half-open
probe, the rollout state machine's terminal/ordering contract,
DynamicBatcher's no-request-lost flush/drain, and ModelRegistry's
untorn hot-swap — over ``DMLC_INTERLEAVE_SCHEDULES`` (default 200)
distinct schedules each; ``python -m dmlc_core_tpu.analysis.interleave``
runs them all (a ci.sh stage).  Do not combine with
``DMLC_RACECHECK=1``: coop primitives are invisible to racecheck's
happens-before vocabulary, so it would report false races.
"""

from __future__ import annotations

import _thread
import argparse
import os
import random
import sys
import threading
import time as _time
from contextlib import contextmanager
from typing import (Any, Callable, Dict, Iterator, List, Optional,
                    Tuple)

__all__ = ["Scheduler", "Deadlock", "ScheduleLimit",
           "InvariantViolation", "ExploreResult", "explore", "verify",
           "builtin_models", "env_schedules", "main"]


class Deadlock(RuntimeError):
    """Every task is blocked with no pending timeout — the schedule
    wedged the model."""


class ScheduleLimit(RuntimeError):
    """A single schedule exceeded ``max_steps`` decisions (livelock or
    a runaway model)."""


class InvariantViolation(AssertionError):
    """:func:`verify` found at least one schedule that breaks the
    model's invariant; carries the failing decision trace."""

    def __init__(self, message: str, trace: List[int]):
        super().__init__(message)
        self.trace = trace


class _Abort(BaseException):
    """Internal: unwinds leftover tasks when a run is torn down."""


_RUNNABLE, _BLOCKED, _DONE = "runnable", "blocked", "done"
#: owner sentinel for sync ops issued outside any scheduled task
_MAIN = object()


class _Task:
    __slots__ = ("name", "fn", "gate", "state", "deadline", "timed_out",
                 "exc", "joiners")

    def __init__(self, fn: Callable[[], None], name: str):
        self.fn = fn
        self.name = name
        self.gate = _thread.allocate_lock()
        # handoff token, not a mutex: starts held; the SCHEDULER
        # releases it to grant this task a run slice
        self.gate.acquire()  # dmlcheck: off:lock-release
        self.state = _RUNNABLE
        self.deadline: Optional[float] = None
        self.timed_out = False
        self.exc: Optional[BaseException] = None
        self.joiners: List["_Task"] = []


def _wake(task: "_Task") -> None:
    if task.state == _BLOCKED:
        task.state = _RUNNABLE
        task.timed_out = False


class Scheduler:
    """One run of a model under one schedule.

    The driving thread (the model function itself) creates tasks —
    directly via :meth:`spawn` or through ``threading.Thread`` inside
    :meth:`patched` — then calls :meth:`go`, which runs them one at a
    time, consulting the ``pick`` callback at every point where more
    than one task could run next."""

    def __init__(self, pick: Callable[[int, int], int],
                 max_steps: int = 20000):
        self._pick = pick
        self.max_steps = max_steps
        self.now = 0.0
        self.trace: List[int] = []
        self.counts: List[int] = []
        self._tasks: List[_Task] = []
        #: binary handshake: released exactly once per task run-slice
        self._park = _thread.allocate_lock()
        # handoff token: TASKS release it to return the baton
        self._park.acquire()  # dmlcheck: off:lock-release
        self._tls = threading.local()
        self._aborting = False

    # -- decisions -------------------------------------------------------
    def choose(self, n: int) -> int:
        """Record one ``n``-way decision and return the schedule's pick.
        Models use this directly for nondeterministic inputs (wave
        outcomes, activate-vs-stage); the scheduler uses it to pick the
        next task.  ``n <= 1`` is not a decision and is not recorded."""
        if n <= 1:
            return 0
        if len(self.trace) >= self.max_steps:
            raise ScheduleLimit(
                f"schedule exceeded {self.max_steps} decisions")
        i = self._pick(len(self.trace), n)
        if not 0 <= i < n:
            i = 0
        self.trace.append(i)
        self.counts.append(n)
        return i

    # -- logical time ----------------------------------------------------
    def time(self) -> float:
        """The logical clock (advances only at quiescence)."""
        return self.now

    def advance(self, dt: float) -> None:
        """Manually advance the clock (model setup, e.g. lapsing a
        circuit breaker's reset window)."""
        self.now += dt

    def sleep(self, dt: float) -> None:
        t = self._current()
        if t is None:
            self.now += dt
        elif dt > 0:
            self._block(t, self.now + dt)
        else:
            self.point()

    # -- task machinery --------------------------------------------------
    def _current(self) -> Optional[_Task]:
        return getattr(self._tls, "task", None)

    def spawn(self, fn: Callable[[], None],
              name: Optional[str] = None) -> _Task:
        """Register ``fn`` as a schedulable task (it runs only inside
        :meth:`go`)."""
        t = _Task(fn, name or f"task-{len(self._tasks)}")
        self._tasks.append(t)
        _thread.start_new_thread(self._body, (t,))
        return t

    def _body(self, task: _Task) -> None:
        # token handoff (released by go()), not a critical section
        task.gate.acquire()  # dmlcheck: off:lock-release
        self._tls.task = task
        try:
            if self._aborting:
                raise _Abort()
            task.fn()
        except _Abort:
            pass
        except BaseException as e:  # noqa: BLE001 — reported by go()
            task.exc = e
        task.state = _DONE
        for j in task.joiners:
            _wake(j)
        self._park.release()

    def _switch(self, task: _Task) -> None:
        """Hand the token back to the scheduler; resumes when the
        scheduler picks this task again."""
        self._park.release()
        # token ping-pong: park goes TO the scheduler, gate comes BACK
        task.gate.acquire()  # dmlcheck: off:lock-release
        if self._aborting:
            raise _Abort()

    def point(self) -> None:
        """A preemption point: the scheduler may switch tasks here.
        No-op outside scheduled tasks."""
        t = self._current()
        if t is None or self._aborting:
            return
        t.state = _RUNNABLE
        self._switch(t)

    def _block(self, task: _Task,
               deadline: Optional[float] = None) -> bool:
        """Park ``task`` until something wakes it (True) or its
        ``deadline`` fires at quiescence (False)."""
        if self._aborting:
            raise _Abort()
        task.state = _BLOCKED
        task.deadline = deadline
        task.timed_out = False
        self._switch(task)
        task.deadline = None
        return not task.timed_out

    def go(self) -> None:
        """Run every task to completion under this schedule; re-raise
        the first task exception (invariant asserts inside tasks
        surface here)."""
        while True:
            live = [t for t in self._tasks if t.state != _DONE]
            if not live:
                break
            runnable = [t for t in live if t.state == _RUNNABLE]
            if not runnable:
                timed = [t for t in live
                         if t.state == _BLOCKED and t.deadline is not None]
                if not timed:
                    self._abort_all()
                    raise Deadlock(
                        "all tasks blocked: "
                        + ", ".join(t.name for t in live))
                self.now = min(t.deadline for t in timed
                               if t.deadline is not None)
                for t in timed:
                    if t.deadline is not None and t.deadline <= self.now:
                        t.timed_out = True
                        t.state = _RUNNABLE
                continue
            t = runnable[self.choose(len(runnable))]
            t.gate.release()
            # wait for the task to hand the baton back (see _switch)
            self._park.acquire()  # dmlcheck: off:lock-release
        for t in self._tasks:
            if t.exc is not None:
                raise t.exc

    def _abort_all(self) -> None:
        """Tear down leftover tasks (failed or abandoned run): each one
        raises :class:`_Abort` at its next switch point and unwinds."""
        self._aborting = True
        for t in self._tasks:
            while t.state != _DONE:
                t.gate.release()
                # same baton handoff as go()'s scheduling loop
                self._park.acquire()  # dmlcheck: off:lock-release

    # -- patching --------------------------------------------------------
    @contextmanager
    def patched(self) -> Iterator["Scheduler"]:
        """Swap ``threading`` primitives and the ``time`` module for
        their cooperative twins, so real classes (queues, batchers,
        breakers) run under this scheduler unmodified."""
        sched = self
        saved = (threading.Lock, threading.RLock, threading.Condition,
                 threading.Event, threading.Thread)
        saved_time = (_time.monotonic, _time.time, _time.perf_counter,
                      _time.sleep)
        threading.Lock = lambda: CoopLock(sched)       # type: ignore
        threading.RLock = lambda: CoopRLock(sched)     # type: ignore
        threading.Condition = (                        # type: ignore
            lambda lock=None: CoopCondition(sched, lock))
        threading.Event = lambda: CoopEvent(sched)     # type: ignore
        threading.Thread = (                           # type: ignore
            lambda *a, **k: CoopThread(sched, *a, **k))
        _time.monotonic = self.time                    # type: ignore
        _time.time = self.time                         # type: ignore
        _time.perf_counter = self.time                 # type: ignore
        _time.sleep = self.sleep                       # type: ignore
        try:
            yield self
        finally:
            (threading.Lock, threading.RLock, threading.Condition,
             threading.Event, threading.Thread) = saved  # type: ignore
            (_time.monotonic, _time.time, _time.perf_counter,
             _time.sleep) = saved_time                 # type: ignore

    @contextmanager
    def attr_points(self, cls: type) -> Iterator[None]:
        """Make every ``self._x`` instance-attribute access on ``cls``
        a preemption point — the switches that expose unlocked
        check-then-act windows (sync-valued attributes excluded)."""
        orig_get = cls.__getattribute__
        orig_set = cls.__setattr__
        sched = self

        def _is_sync(v: Any) -> bool:
            return isinstance(v, (CoopLock, CoopRLock, CoopCondition,
                                  CoopEvent, CoopThread))

        def __getattribute__(obj: Any, name: str) -> Any:
            value = orig_get(obj, name)
            if (name.startswith("_") and not name.startswith("__")
                    and sched._current() is not None
                    and not _is_sync(value)
                    and name in orig_get(obj, "__dict__")):
                sched.point()
            return value

        def __setattr__(obj: Any, name: str, value: Any) -> None:
            if (name.startswith("_") and not name.startswith("__")
                    and sched._current() is not None
                    and not _is_sync(value)):
                sched.point()
            orig_set(obj, name, value)

        cls.__getattribute__ = __getattribute__  # type: ignore
        cls.__setattr__ = __setattr__            # type: ignore
        try:
            yield
        finally:
            cls.__getattribute__ = orig_get      # type: ignore
            cls.__setattr__ = orig_set           # type: ignore


# -- cooperative primitives -------------------------------------------------

class CoopLock:
    """``threading.Lock`` twin scheduled by a :class:`Scheduler`."""

    def __init__(self, sched: Scheduler):
        self._sched = sched
        self._owner: Any = None
        self._waiters: List[_Task] = []

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        s = self._sched
        t: Any = s._current() or _MAIN
        if t is _MAIN:
            if self._owner is not None:
                raise RuntimeError(
                    "contended acquire outside scheduled tasks")
            self._owner = t
            return True
        s.point()
        deadline = (s.now + timeout
                    if timeout is not None and timeout >= 0 else None)
        while self._owner is not None:
            if not blocking:
                return False
            self._waiters.append(t)
            ok = s._block(t, deadline)
            if t in self._waiters:
                self._waiters.remove(t)
            if not ok:
                return False
        self._owner = t
        return True

    def release(self) -> None:
        if self._owner is None:
            raise RuntimeError("release of unheld CoopLock")
        self._owner = None
        for w in self._waiters:
            _wake(w)
        if self._sched._current() is not None:
            self._sched.point()

    def locked(self) -> bool:
        return self._owner is not None

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()


class CoopRLock:
    """``threading.RLock`` twin, with the ``Condition`` protocol."""

    def __init__(self, sched: Scheduler):
        self._sched = sched
        self._owner: Any = None
        self._count = 0
        self._waiters: List[_Task] = []

    def acquire(self, blocking: bool = True,
                timeout: float = -1) -> bool:
        s = self._sched
        t: Any = s._current() or _MAIN
        if self._owner is t:
            self._count += 1
            return True
        if t is _MAIN:
            if self._owner is not None:
                raise RuntimeError(
                    "contended acquire outside scheduled tasks")
            self._owner, self._count = t, 1
            return True
        s.point()
        deadline = (s.now + timeout
                    if timeout is not None and timeout >= 0 else None)
        while self._owner is not None:
            if not blocking:
                return False
            self._waiters.append(t)
            ok = s._block(t, deadline)
            if t in self._waiters:
                self._waiters.remove(t)
            if not ok:
                return False
        self._owner, self._count = t, 1
        return True

    def release(self) -> None:
        t: Any = self._sched._current() or _MAIN
        if self._owner is not t:
            raise RuntimeError("release of un-owned CoopRLock")
        self._count -= 1
        if self._count == 0:
            self._owner = None
            for w in self._waiters:
                _wake(w)
            if self._sched._current() is not None:
                self._sched.point()

    # Condition protocol: wait() drops every recursion level at once
    def _release_save(self) -> int:
        count = self._count
        self._count = 1
        self.release()
        return count

    def _acquire_restore(self, count: int) -> None:
        # Condition wait() protocol: the caller's with-block releases
        self.acquire()  # dmlcheck: off:lock-release
        self._count = count

    def _is_owned(self) -> bool:
        return self._owner is (self._sched._current() or _MAIN)

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()


class CoopCondition:
    """``threading.Condition`` twin (Mesa semantics, no spurious
    wakeups beyond notify/timeout)."""

    def __init__(self, sched: Scheduler, lock: Any = None):
        self._sched = sched
        self._lock = lock if lock is not None else CoopRLock(sched)
        self._waiters: List[_Task] = []

    def acquire(self, *a: Any, **k: Any) -> bool:
        return self._lock.acquire(*a, **k)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self) -> "CoopCondition":
        # the with-statement pairs this with __exit__'s release
        self._lock.acquire()  # dmlcheck: off:lock-release
        return self

    def __exit__(self, *exc: Any) -> None:
        self._lock.release()

    def _is_owned(self) -> bool:
        own = getattr(self._lock, "_is_owned", None)
        return own() if own is not None else True

    def wait(self, timeout: Optional[float] = None) -> bool:
        s = self._sched
        t = s._current()
        if t is None:
            raise RuntimeError(
                "Condition.wait outside scheduled tasks would deadlock")
        if not self._is_owned():
            raise RuntimeError("wait on un-acquired CoopCondition")
        self._waiters.append(t)
        saved = (self._lock._release_save()
                 if hasattr(self._lock, "_release_save") else None)
        if saved is None:
            self._lock.release()
        deadline = None if timeout is None else s.now + timeout
        ok = s._block(t, deadline)
        if t in self._waiters:
            self._waiters.remove(t)
        if saved is not None:
            self._lock._acquire_restore(saved)
        else:
            # reacquire after wait; the caller's with-block releases
            self._lock.acquire()  # dmlcheck: off:lock-release
        return ok

    def wait_for(self, predicate: Callable[[], Any],
                 timeout: Optional[float] = None) -> Any:
        s = self._sched
        deadline = None if timeout is None else s.now + timeout
        result = predicate()
        while not result:
            remaining = None if deadline is None else deadline - s.now
            if remaining is not None and remaining <= 0:
                break
            self.wait(remaining)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        for w in list(self._waiters[:n]):
            self._waiters.remove(w)
            _wake(w)

    def notify_all(self) -> None:
        self.notify(len(self._waiters))


class CoopEvent:
    """``threading.Event`` twin."""

    def __init__(self, sched: Scheduler):
        self._sched = sched
        self._flag = False
        self._waiters: List[_Task] = []

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:  # noqa: A003 — stdlib name
        self._flag = True
        for w in self._waiters:
            _wake(w)
        self._waiters.clear()
        if self._sched._current() is not None:
            self._sched.point()

    def clear(self) -> None:
        self._flag = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        s = self._sched
        t = s._current()
        if self._flag:
            if t is not None:
                s.point()
            return True
        if t is None:
            raise RuntimeError(
                "Event.wait outside scheduled tasks would deadlock")
        self._waiters.append(t)
        s._block(t, None if timeout is None else s.now + timeout)
        if t in self._waiters:
            self._waiters.remove(t)
        return self._flag


class CoopThread:
    """``threading.Thread`` twin: ``start`` registers a task with the
    scheduler instead of spawning a free-running OS thread."""

    def __init__(self, sched: Scheduler, group: Any = None,
                 target: Optional[Callable[..., Any]] = None,
                 name: Optional[str] = None,
                 args: Tuple[Any, ...] = (),
                 kwargs: Optional[Dict[str, Any]] = None,
                 *, daemon: Optional[bool] = None):
        self._sched = sched
        self._target = target
        self._args = args
        self._kwargs = kwargs or {}
        self.name = name or f"coop-thread-{id(self):x}"
        self.daemon = bool(daemon)
        self._task: Optional[_Task] = None

    def run(self) -> None:
        if self._target is not None:
            self._target(*self._args, **self._kwargs)

    def start(self) -> None:
        if self._task is not None:
            raise RuntimeError("threads can only be started once")
        self._task = self._sched.spawn(lambda: self.run(),
                                       name=self.name)

    def join(self, timeout: Optional[float] = None) -> None:
        s = self._sched
        task = self._task
        if task is None:
            raise RuntimeError("cannot join an un-started thread")
        t = s._current()
        if task.state == _DONE:
            if t is not None:
                s.point()
            return
        if t is None:
            raise RuntimeError(
                "join outside scheduled tasks would deadlock")
        task.joiners.append(t)
        s._block(t, None if timeout is None else s.now + timeout)
        if t in task.joiners:
            task.joiners.remove(t)

    def is_alive(self) -> bool:
        return self._task is not None and self._task.state != _DONE


# -- exploration ------------------------------------------------------------

class ExploreResult:
    """Outcome of :func:`explore`: how many schedules ran, how many
    were distinct, which failed, and whether the decision tree was
    fully exhausted within the budget."""

    def __init__(self, runs: int, distinct: int,
                 failures: List[Dict[str, Any]], exhausted: bool):
        self.runs = runs
        self.distinct = distinct
        self.failures = failures
        self.exhausted = exhausted

    def __repr__(self) -> str:
        return (f"ExploreResult(runs={self.runs}, "
                f"distinct={self.distinct}, "
                f"failures={len(self.failures)}, "
                f"exhausted={self.exhausted})")


def _run_once(model: Callable[[Scheduler], None],
              pick: Callable[[int, int], int], max_steps: int
              ) -> Tuple[List[int], List[int], Optional[BaseException]]:
    sched = Scheduler(pick, max_steps)
    err: Optional[BaseException] = None
    try:
        model(sched)
    except _Abort:
        err = RuntimeError("model aborted")
    except Exception as e:  # noqa: BLE001 — every failure is a finding
        err = e
    finally:
        sched._abort_all()
    return sched.trace, sched.counts, err


def _replay_pick(prefix: Tuple[int, ...]) -> Callable[[int, int], int]:
    def pick(step: int, n: int) -> int:
        return min(prefix[step], n - 1) if step < len(prefix) else 0
    return pick


def env_schedules() -> int:
    """The ``DMLC_INTERLEAVE_SCHEDULES`` budget (default 200)."""
    raw = os.environ.get("DMLC_INTERLEAVE_SCHEDULES", "").strip()
    return int(raw) if raw else 200


def explore(model: Callable[[Scheduler], None],
            schedules: Optional[int] = None, mode: str = "mixed",
            seed: int = 0, max_steps: int = 20000) -> ExploreResult:
    """Run ``model`` under up to ``schedules`` schedules.

    ``mode``: ``"dfs"`` (bounded-exhaustive), ``"random"`` (seeded), or
    ``"mixed"`` (DFS for half the budget, random for the rest — the
    default: systematic near the root, probabilistic in the tail)."""
    if schedules is None:
        schedules = env_schedules()
    if mode not in ("dfs", "random", "mixed"):
        raise ValueError(f"unknown explore mode {mode!r}")
    traces: set = set()
    failures: List[Dict[str, Any]] = []
    runs = 0
    exhausted = False

    def _record(trace: List[int], err: Optional[BaseException]) -> None:
        traces.add(tuple(trace))
        if err is not None:
            failures.append({"trace": list(trace), "error": err})

    def _dfs_step(stack: List[Tuple[int, ...]]) -> None:
        nonlocal runs
        prefix = stack.pop()
        trace, counts, err = _run_once(
            model, _replay_pick(prefix), max_steps)
        runs += 1
        _record(trace, err)
        for i in range(len(trace) - 1, len(prefix) - 1, -1):
            for alt in range(trace[i] + 1, counts[i]):
                stack.append(tuple(trace[:i]) + (alt,))

    dfs_budget = (schedules if mode == "dfs"
                  else 0 if mode == "random" else schedules // 2)
    stack: List[Tuple[int, ...]] = [()] if dfs_budget else []
    while stack and runs < dfs_budget:
        _dfs_step(stack)
    exhausted = dfs_budget > 0 and not stack
    if not exhausted:
        for k in range(schedules - runs):
            rng = random.Random(seed * 1_000_003 + k)
            trace, _, err = _run_once(
                model, lambda step, n, r=rng: r.randrange(n), max_steps)
            runs += 1
            _record(trace, err)
    # top-up: every DFS run explores a NEW trace (each frontier prefix
    # diverges from its parent's schedule), so resuming the frontier
    # makes up the distinct count that duplicate random draws lost —
    # unless the whole tree is smaller than the budget
    while stack and len(traces) < schedules:
        _dfs_step(stack)
    exhausted = dfs_budget > 0 and not stack
    return ExploreResult(runs, len(traces), failures, exhausted)


def verify(model: Callable[[Scheduler], None], **kwargs: Any
           ) -> ExploreResult:
    """:func:`explore` that raises :class:`InvariantViolation` on the
    first failing schedule (with its replayable decision trace)."""
    result = explore(model, **kwargs)
    if result.failures:
        f = result.failures[0]
        raise InvariantViolation(
            f"{len(result.failures)}/{result.runs} schedules violate "
            f"the invariant; first: {f['error']!r} under trace "
            f"{f['trace']}", f["trace"])
    return result


# -- built-in models --------------------------------------------------------

def model_circuit_breaker(sched: Scheduler) -> None:
    """Half-open circuit admits EXACTLY one probe, no matter how
    ``allow()`` callers interleave (the PR-5 ``_state`` race, proven
    absent rather than just not-observed)."""
    from dmlc_core_tpu.base.resilience import CircuitBreaker

    with sched.patched():
        cb = CircuitBreaker("interleave", failure_threshold=1,
                            reset_timeout_s=1.0, clock=sched.time)
        cb.record_failure()                 # -> OPEN at t=0
        sched.advance(2.0)                  # reset window lapsed
        admitted: List[int] = []

        def prober(i: int) -> None:
            if cb.allow():
                admitted.append(i)

        for i in range(3):
            # sched.go() runs every model thread to completion — the
            # scheduler is the join point for interleave scenarios
            threading.Thread(target=prober, args=(i,)).start()  # dmlcheck: off:thread-lifecycle
        with sched.attr_points(CircuitBreaker):
            sched.go()
    assert len(admitted) == 1, (
        f"half-open circuit admitted {len(admitted)} probes "
        f"({admitted}); must admit exactly one")
    assert cb.state == CircuitBreaker.HALF_OPEN


def model_rollout(sched: Scheduler) -> None:
    """Rollout state machine: activation follows plan order without
    duplicates, terminal state is DONE xor ROLLED_BACK, and rollback
    targets are exactly the activated replicas in reverse."""
    from dmlc_core_tpu.serve.fleet.rollout import RolloutController

    n = 4 + sched.choose(8)                  # 4..11 replicas
    wave_size = 1 + sched.choose(4)          # 1..4 per wave
    ctl = RolloutController(range(n), wave_size)
    assert ctl.state == ctl.STAGING
    ctl.staged()
    flat = [r for w in ctl.waves for r in w]
    assert flat == list(range(n))            # plan covers all, in order
    seen: List[int] = []
    while True:
        wave = ctl.next_wave()
        if wave is None:
            break
        outcome = sched.choose(3)            # ok / ok+probe / failed
        if outcome == 1:
            assert ctl.state == ctl.ACTIVATING   # probe mid-rollout
        if outcome in (0, 1):
            ctl.wave_ok()
            seen.extend(wave)
            assert ctl.activated == seen
        else:
            rollback = ctl.wave_failed()
            seen.extend(wave)
            assert rollback == list(reversed(seen))
            assert ctl.state == ctl.ROLLED_BACK
            break
    if ctl.state != ctl.ROLLED_BACK:
        assert ctl.state == ctl.DONE
        assert ctl.activated == list(range(n))
        assert ctl.next_wave() is None       # DONE is absorbing
    assert len(set(ctl.activated)) == len(ctl.activated)


def model_batcher_flush(sched: Scheduler) -> None:
    """DynamicBatcher flush/drain: every accepted request resolves
    exactly once with its own rows' predictions; ``close(drain=True)``
    loses nothing; the queue ends empty."""
    import numpy as np

    from dmlc_core_tpu.serve.batcher import DynamicBatcher

    with sched.patched():
        b = DynamicBatcher(lambda X: X.sum(axis=1), max_batch=4,
                           max_delay=0.01, max_queue=8,
                           name="interleave")
        results: List[Tuple[int, float]] = []

        def client(i: int) -> None:
            f = b.submit(np.full((1, 2), float(i), np.float32))
            preds, _ = f.result()
            results.append((i, float(preds[0])))

        clients = [threading.Thread(target=client, args=(i,))
                   for i in range(3)]
        for th in clients:
            th.start()

        def closer() -> None:
            for th in clients:
                th.join()
            b.close(drain=True)

        # sched.go() below runs the model thread to completion
        threading.Thread(target=closer).start()  # dmlcheck: off:thread-lifecycle
        sched.go()
    assert sorted(i for i, _ in results) == [0, 1, 2], (
        f"requests lost or duplicated: {results}")
    for i, v in results:
        assert abs(v - 2.0 * i) < 1e-6, (
            f"request {i} got another request's rows: {v}")
    assert b.depth() == 0


def model_registry_hot_swap(sched: Scheduler) -> None:
    """ModelRegistry hot-swap: readers never observe a torn
    ``(version, runner)`` pair, staged versions stay invisible until
    activated, and the final pointer is the last activation."""
    from dmlc_core_tpu.serve import registry as registry_mod

    class _StubRunner:
        def __init__(self, model: Any, name: str = "default",
                     **opts: Any):
            self.model = model

    orig_runner = registry_mod.ModelRunner
    registry_mod.ModelRunner = _StubRunner  # type: ignore[misc]
    try:
        with sched.patched():
            reg = registry_mod.ModelRegistry("interleave")
            reg.publish("m1", version=1)
            observed: List[Tuple[int, Any]] = []

            def publisher() -> None:
                for v in (2, 3):
                    staged = sched.choose(2) == 1
                    reg.publish(f"m{v}", version=v,
                                activate=not staged)
                    if staged:
                        reg.activate(v)

            # sched.go() runs every model thread to completion
            threading.Thread(target=publisher).start()  # dmlcheck: off:thread-lifecycle
            for k in range(2):
                def reader() -> None:
                    for _ in range(3):
                        ver, runner = reg.current()
                        observed.append((ver, runner.model))
                threading.Thread(target=reader).start()  # dmlcheck: off:thread-lifecycle
            with sched.attr_points(registry_mod.ModelRegistry):
                sched.go()
    finally:
        registry_mod.ModelRunner = orig_runner  # type: ignore[misc]
    for ver, m in observed:
        assert m == f"m{ver}", (
            f"torn hot-swap: version {ver} paired with {m!r}")
        assert ver in (1, 2, 3)
    assert reg.current()[0] == 3
    assert reg.versions() == [1, 2, 3]


def builtin_models() -> Dict[str, Callable[[Scheduler], None]]:
    """The four serving-stack invariants the CI interleave stage
    proves (doc/static_analysis.md)."""
    return {
        "circuit-breaker": model_circuit_breaker,
        "rollout": model_rollout,
        "batcher": model_batcher_flush,
        "registry": model_registry_hot_swap,
    }


def main(argv: Optional[List[str]] = None) -> int:
    """CLI: explore every built-in model (or ``--model NAME``) over
    ``--schedules`` schedules; non-zero exit on any violated
    invariant."""
    from dmlc_core_tpu.base.logging import set_log_level

    ap = argparse.ArgumentParser(
        prog="interleave",
        description="schedule-exploration model checker")
    ap.add_argument("--model", choices=sorted(builtin_models()),
                    help="run one model instead of all")
    ap.add_argument("--schedules", type=int, default=env_schedules(),
                    help="schedule budget per model "
                         "(DMLC_INTERLEAVE_SCHEDULES, default 200)")
    ap.add_argument("--mode", choices=("dfs", "random", "mixed"),
                    default="mixed")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    set_log_level("ERROR")                # model churn is not news
    models = builtin_models()
    names = [args.model] if args.model else sorted(models)
    rc = 0
    for name in names:
        r = explore(models[name], schedules=args.schedules,
                    mode=args.mode, seed=args.seed)
        tag = " (tree exhausted)" if r.exhausted else ""
        print(f"interleave: {name}: {r.runs} schedules, "
              f"{r.distinct} distinct, {len(r.failures)} failing{tag}")
        if r.failures:
            f = r.failures[0]
            print(f"interleave: {name}: FIRST FAILURE "
                  f"{f['error']!r} trace={f['trace']}")
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
