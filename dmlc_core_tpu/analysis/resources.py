"""``resource-leak`` and ``thread-lifecycle`` passes.

The process-spawning subsystems (tracker, PS client/server, launch
transports, fleet loadgen) hold OS resources whose lifetime the type
system never sees: a socket that misses its ``close()`` wedges a port,
an unwaited child is a zombie, an unjoined thread can segfault
interpreter teardown.  These passes prove acquisition *shape*
statically; ``base/leakcheck.py`` is the dynamic companion that
catches whatever shape analysis cannot.

``resource-leak``: an acquisition (``socket.socket`` /
``create_connection`` / ``Popen`` / ``NamedTemporaryFile`` /
``TemporaryFile`` / ``mkstemp`` / builtin ``open``) must reach one of
the accepted lifecycle shapes:

* a ``with`` statement (directly or via the bound name);
* an explicit release call on the name (``.close/.terminate/.kill/
  .wait/.join/.shutdown/.stop/.release``) anywhere in the function —
  try/finally placement is the caller's taste, not the lint's;
* **ownership transfer**: the name is returned/yielded, passed as a
  call argument (factories hand resources to owners — registries,
  handles, thread targets), aliased, or stored into a container/
  attribute;
* **registered teardown**: ``self.<attr> = acquisition()`` is clean
  when the class declares a teardown method (``close``/``stop``/
  ``shutdown``/``release``/``terminate``/``join``/``__exit__``/
  ``__del__``) that owns the attribute's lifetime.

A bare ``socket.socket()`` / ``mkstemp()`` expression statement
discards the only handle — always flagged.

``thread-lifecycle``: a ``threading.Thread`` must be joinable and
joined, or daemon *and* lock-free:

* non-daemon thread with no reachable ``join()`` (on the name, via an
  alias, a ``for v in threads: v.join()`` loop, or — for
  ``self.<attr>`` threads — anywhere in the class) and no ownership
  transfer → flagged: interpreter exit blocks on it;
* ``Thread(...).start()`` chained fire-and-forget → never joinable;
* a **daemon** thread whose target (resolved transitively through
  same-class methods) acquires one of the class's locks → flagged
  unless joined: daemonic death at interpreter teardown can leave the
  lock held while non-daemon threads still want it.

Suppress deliberate detached threads with
``# dmlcheck: off:thread-lifecycle`` plus who reaps them.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from dmlc_core_tpu.analysis.engine import AnalysisContext, ParsedFile
from dmlc_core_tpu.analysis.locks import _class_lock_attrs, _self_attr

__all__ = ["run", "EXPLAIN"]

_RELEASE_METHODS = {"close", "terminate", "kill", "wait", "join",
                    "shutdown", "stop", "release", "cancel"}
_TEARDOWN_METHODS = {"close", "stop", "shutdown", "release", "terminate",
                     "join", "__exit__", "__del__"}

EXPLAIN = {
    "resource-leak": {
        "doc": "Socket/subprocess/tempfile/file acquired without a "
               "with-block, an explicit release call, ownership "
               "transfer (returned, passed on, stored) or a registered "
               "class teardown — the OS handle outlives the code that "
               "knew about it.  Factories that hand the resource to an "
               "owner are clean by the transfer rule.",
        "flagged": (
            "def probe(host):\n"
            "    s = socket.socket()\n"
            "    s.connect((host, 80))\n"
            "    data = s.recv(1)          # s never closed/escaped\n"
            "    return data\n"),
        "clean": (
            "def probe(host):\n"
            "    with socket.create_connection((host, 80)) as s:\n"
            "        return s.recv(1)\n"),
    },
    "thread-lifecycle": {
        "doc": "Non-daemon thread with no reachable join() (interpreter "
               "exit blocks on it), a fire-and-forget "
               "Thread(...).start() chain (never joinable), or a daemon "
               "thread that acquires the class's locks (daemonic death "
               "can strand the lock).  Joining with a bounded timeout "
               "in the owner's close()/stop() is the accepted shape.",
        "flagged": (
            "class Server:\n"
            "    def start(self):\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "        self._t.start()       # no join anywhere in class\n"),
        "clean": (
            "class Server:\n"
            "    def start(self):\n"
            "        self._t = threading.Thread(target=self._loop)\n"
            "        self._t.start()\n"
            "    def close(self):\n"
            "        self._t.join(timeout=2.0)\n"),
    },
}


def _call_name(func: ast.expr) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _receiver_name(func: ast.expr) -> str:
    if not isinstance(func, ast.Attribute):
        return ""
    v = func.value
    if isinstance(v, ast.Name):
        return v.id
    if isinstance(v, ast.Attribute):
        return v.attr
    return ""


def _acq_kind(node: ast.Call) -> str:
    """Resource kind for an acquisition call, '' otherwise."""
    name = _call_name(node.func)
    recv = _receiver_name(node.func)
    if name == "socket" and recv == "socket":
        return "socket"
    if name == "create_connection":
        return "socket"
    if name == "Popen":
        return "subprocess"
    if name in ("NamedTemporaryFile", "TemporaryFile"):
        return "tempfile"
    if name == "mkstemp":
        return "mkstemp"
    if name == "open" and recv == "" and isinstance(node.func, ast.Name):
        return "file"
    return ""


def _is_thread_ctor(node: ast.Call) -> bool:
    return _call_name(node.func) == "Thread"


def _kw(node: ast.Call, name: str) -> Optional[ast.expr]:
    for k in node.keywords:
        if k.arg == name:
            return k.value
    return None


def _kw_true(node: ast.Call, name: str) -> bool:
    v = _kw(node, name)
    return (isinstance(v, ast.Constant) and v.value is True)


class _FuncEvidence(ast.NodeVisitor):
    """Release/escape/join evidence for names within one function —
    nested defs included (closures clean up for their owner)."""

    def __init__(self) -> None:
        self.released: Set[str] = set()      # <name>.close()-style
        self.joined: Set[str] = set()        # <name>.join(...)
        self.escaped: Set[str] = set()       # transferred/stored/aliased
        self.with_names: Set[str] = set()    # with <name>:
        #: list name -> loop vars iterating it (for v in threads:)
        self.loop_vars: Dict[str, Set[str]] = {}
        #: local alias -> self attr (t = self._thread)
        self.self_alias: Dict[str, str] = {}
        #: self attrs joined here (self._t.join() or via alias)
        self.joined_attrs: Set[str] = set()
        #: names set daemon post-hoc (t.daemon = True)
        self.daemon_set: Set[str] = set()

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in _RELEASE_METHODS:
                if isinstance(f.value, ast.Name):
                    self.released.add(f.value.id)
                    if f.attr == "join":
                        self.joined.add(f.value.id)
                        alias = self.self_alias.get(f.value.id)
                        if alias:
                            self.joined_attrs.add(alias)
                attr = _self_attr(f.value)
                if attr and f.attr == "join":
                    self.joined_attrs.add(attr)
        for sub in list(node.args) + [k.value for k in node.keywords]:
            for n in ast.walk(sub):
                if isinstance(n, ast.Name):
                    self.escaped.add(n.id)
        self.generic_visit(node)

    def _escape_value(self, value: Optional[ast.expr]) -> None:
        if value is None:
            return
        if isinstance(value, ast.Name):
            self.escaped.add(value.id)
        elif isinstance(value, (ast.Tuple, ast.List)):
            for e in value.elts:
                self._escape_value(e)

    def visit_Return(self, node: ast.Return) -> None:
        if node.value is not None:
            for n in ast.walk(node.value):
                if isinstance(n, ast.Name):
                    self.escaped.add(n.id)
        self.generic_visit(node)

    def visit_Yield(self, node: ast.Yield) -> None:
        if node.value is not None:
            for n in ast.walk(node.value):
                if isinstance(n, ast.Name):
                    self.escaped.add(n.id)
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            if isinstance(item.context_expr, ast.Name):
                self.with_names.add(item.context_expr.id)
        self.generic_visit(node)

    def visit_For(self, node: ast.For) -> None:
        if (isinstance(node.iter, ast.Name)
                and isinstance(node.target, ast.Name)):
            self.loop_vars.setdefault(node.iter.id,
                                      set()).add(node.target.id)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        # alias (y = x / t = self._thread) and container/attr stores
        if isinstance(node.value, ast.Name):
            self.escaped.add(node.value.id)
        elif isinstance(node.value, (ast.Tuple, ast.List)):
            self._escape_value(node.value)
        attr = _self_attr(node.value) if isinstance(node.value,
                                                    ast.Attribute) else None
        for t in node.targets:
            if isinstance(t, ast.Name) and attr:
                self.self_alias[t.id] = attr
            if (isinstance(t, ast.Attribute)
                    and t.attr == "daemon"
                    and isinstance(t.value, ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and node.value.value is True):
                self.daemon_set.add(t.value.id)
        self.generic_visit(node)

    def list_joined(self, name: str) -> bool:
        """True when some ``for v in <name>`` loop joins its loop var."""
        return any(v in self.joined for v in self.loop_vars.get(name, ()))


class _Acq:
    """One acquisition site inside a function."""

    __slots__ = ("kind", "line", "name", "form", "call")

    def __init__(self, kind: str, line: int, name: Optional[str],
                 form: str, call: ast.Call) -> None:
        self.kind = kind
        self.line = line
        self.name = name       # bound local name, or self-attr name
        self.form = form       # bare|name|self|tuple|comp|chain
        self.call = call


def _collect_acqs(fn: ast.AST) -> Tuple[List[_Acq], List[_Acq]]:
    """(resource acquisitions, thread creations) at statement level of
    one function — nested defs excluded (they get their own scan)."""
    res: List[_Acq] = []
    thr: List[_Acq] = []

    def scan_stmts(stmts: List[ast.stmt]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            _scan_stmt(stmt)
            for field in ("body", "orelse", "finalbody", "handlers"):
                sub = getattr(stmt, field, None)
                if not sub:
                    continue
                if field == "handlers":
                    for h in sub:
                        scan_stmts(h.body)
                else:
                    scan_stmts(sub)
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                pass            # with-acquisitions are clean by shape

    def _scan_stmt(stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            kind = _acq_kind(call)
            if kind:
                res.append(_Acq(kind, stmt.lineno, None, "bare", call))
            # Thread(...).start() chained fire-and-forget
            f = call.func
            if (isinstance(f, ast.Attribute) and f.attr == "start"
                    and isinstance(f.value, ast.Call)
                    and _is_thread_ctor(f.value)):
                thr.append(_Acq("thread", stmt.lineno, None, "chain",
                                f.value))
        elif isinstance(stmt, ast.Assign) and isinstance(stmt.value,
                                                         ast.Call):
            call = stmt.value
            kind = _acq_kind(call)
            is_thr = _is_thread_ctor(call)
            if not kind and not is_thr:
                return
            t = stmt.targets[0] if len(stmt.targets) == 1 else None
            if isinstance(t, ast.Name):
                acq = _Acq(kind or "thread", stmt.lineno, t.id, "name",
                           call)
                (thr if is_thr else res).append(acq)
            elif t is not None and _self_attr(t):
                acq = _Acq(kind or "thread", stmt.lineno, _self_attr(t),
                           "self", call)
                (thr if is_thr else res).append(acq)
            elif (isinstance(t, ast.Tuple) and kind == "mkstemp"
                    and t.elts and isinstance(t.elts[0], ast.Name)):
                res.append(_Acq(kind, stmt.lineno, t.elts[0].id, "tuple",
                                call))
        elif (isinstance(stmt, ast.Assign)
                and isinstance(stmt.value, (ast.ListComp,
                                            ast.GeneratorExp))
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)):
            elt = stmt.value.elt
            if isinstance(elt, ast.Call) and _is_thread_ctor(elt):
                thr.append(_Acq("thread", stmt.lineno,
                                stmt.targets[0].id, "comp", elt))

    body = getattr(fn, "body", [])
    scan_stmts(body)
    return res, thr


# -- daemon-owns-locks resolution -------------------------------------------

def _target_method(call: ast.Call) -> Optional[str]:
    """``Thread(target=self._foo)`` → ``"_foo"`` (same-class methods
    only — module-level targets own no class locks)."""
    v = _kw(call, "target")
    if v is not None:
        return _self_attr(v)
    return None


def _method_acquires_locks(cls_methods: Dict[str, ast.AST],
                           lock_attrs: Set[str], method: str,
                           visited: Optional[Set[str]] = None) -> bool:
    """True when ``method`` (transitively through same-class calls)
    enters one of the class's locks."""
    if visited is None:
        visited = set()
    if method in visited or method not in cls_methods:
        return False
    visited.add(method)
    fn = cls_methods[method]
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                ce = item.context_expr
                if _self_attr(ce) in lock_attrs:
                    return True
                if (isinstance(ce, ast.Call)
                        and _self_attr(ce.func) in lock_attrs):
                    return True
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"
                and _self_attr(node.func.value) in lock_attrs):
            return True
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"):
            if _method_acquires_locks(cls_methods, lock_attrs,
                                      node.func.attr, visited):
                return True
    return False


# -- per-class / per-function checks ----------------------------------------

def _fn_label(stack: List[str], fn_name: str) -> str:
    return ".".join(stack + [fn_name])


def _check_function(ctx: AnalysisContext, pf: ParsedFile, fn: ast.AST,
                    label: str, cls: Optional[ast.ClassDef],
                    cls_methods: Dict[str, ast.AST],
                    cls_teardown: bool, cls_joined_attrs: Set[str],
                    lock_attrs: Set[str], selected: Set[str]) -> None:
    res, thr = _collect_acqs(fn)
    if not res and not thr:
        return
    ev = _FuncEvidence()
    for stmt in getattr(fn, "body", []):
        ev.visit(stmt)

    if "resource-leak" in selected:
        for a in res:
            if a.form == "bare":
                ctx.add(pf, a.line, "resource-leak",
                        f"{label}() discards a freshly acquired "
                        f"{a.kind} (bare expression — the only handle "
                        f"is lost)", key=f"{label}:bare-{a.kind}")
            elif a.form in ("name", "tuple"):
                assert a.name is not None
                if (a.name in ev.released or a.name in ev.escaped
                        or a.name in ev.with_names):
                    continue
                ctx.add(pf, a.line, "resource-leak",
                        f"{label}() acquires {a.kind} {a.name!r} but "
                        f"never closes, transfers or stores it — the "
                        f"handle leaks when the function returns",
                        key=f"{label}:{a.name}")
            elif a.form == "self":
                if cls_teardown:
                    continue
                ctx.add(pf, a.line, "resource-leak",
                        f"{label}() stores {a.kind} in self.{a.name} "
                        f"but {cls.name if cls else '<class>'} declares "
                        f"no teardown (close/stop/shutdown/__del__) to "
                        f"release it", key=f"{label}:self.{a.name}")

    if "thread-lifecycle" in selected:
        for a in thr:
            daemon = _kw_true(a.call, "daemon") or (
                a.name is not None and a.name in ev.daemon_set)
            target = _target_method(a.call)
            owns_locks = bool(
                daemon and cls is not None and target is not None
                and lock_attrs
                and _method_acquires_locks(cls_methods, lock_attrs,
                                           target))
            tgt = target or (a.name or "thread")
            if a.form == "chain":
                if not daemon:
                    ctx.add(pf, a.line, "thread-lifecycle",
                            f"{label}() starts a fire-and-forget "
                            f"non-daemon thread ({tgt}) — it can never "
                            f"be joined and blocks interpreter exit",
                            key=f"{label}:chain-{tgt}")
                elif owns_locks:
                    ctx.add(pf, a.line, "thread-lifecycle",
                            f"{label}() starts a fire-and-forget daemon "
                            f"thread whose target {target!r} acquires "
                            f"the class's locks — daemonic death can "
                            f"strand the lock; track and join it with a "
                            f"bounded timeout",
                            key=f"{label}:chain-{tgt}")
            elif a.form in ("name", "comp"):
                assert a.name is not None
                joined = (a.name in ev.joined
                          or (a.form == "comp"
                              and ev.list_joined(a.name)))
                if joined or a.name in ev.escaped:
                    continue
                if not daemon:
                    ctx.add(pf, a.line, "thread-lifecycle",
                            f"{label}() starts non-daemon thread "
                            f"{a.name!r} with no reachable join()",
                            key=f"{label}:{a.name}")
                elif owns_locks:
                    ctx.add(pf, a.line, "thread-lifecycle",
                            f"{label}() starts daemon thread {a.name!r} "
                            f"whose target {target!r} acquires the "
                            f"class's locks, with no join()",
                            key=f"{label}:{a.name}")
            elif a.form == "self":
                assert a.name is not None
                joined = a.name in cls_joined_attrs
                if joined:
                    continue
                if not daemon:
                    ctx.add(pf, a.line, "thread-lifecycle",
                            f"{label}() stores non-daemon thread in "
                            f"self.{a.name} but no method of "
                            f"{cls.name if cls else '<class>'} joins it",
                            key=f"{label}:self.{a.name}")
                elif owns_locks:
                    ctx.add(pf, a.line, "thread-lifecycle",
                            f"{label}() stores daemon thread "
                            f"self.{a.name} whose target {target!r} "
                            f"acquires the class's locks, and no method "
                            f"of {cls.name if cls else '<class>'} joins "
                            f"it — join with a bounded timeout in the "
                            f"teardown path",
                            key=f"{label}:self.{a.name}")


def _class_joined_attrs(cls: ast.ClassDef) -> Set[str]:
    """Self attrs some method of ``cls`` joins (directly or via a local
    alias or a ``for v in self._threads`` loop)."""
    joined: Set[str] = set()
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        ev = _FuncEvidence()
        for stmt in item.body:
            ev.visit(stmt)
        joined |= ev.joined_attrs
        # for v in self._threads: v.join()
        for node in ast.walk(item):
            if (isinstance(node, ast.For)
                    and isinstance(node.target, ast.Name)
                    and _self_attr(node.iter)
                    and node.target.id in ev.joined):
                joined.add(_self_attr(node.iter))
    return joined


def _check_file(ctx: AnalysisContext, pf: ParsedFile,
                selected: Set[str]) -> None:
    def walk_body(body: List[ast.stmt], stack: List[str],
                  cls: Optional[ast.ClassDef],
                  cls_methods: Dict[str, ast.AST], cls_teardown: bool,
                  cls_joined: Set[str], lock_attrs: Set[str]) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                methods = {
                    m.name: m for m in node.body
                    if isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))}
                teardown = bool(_TEARDOWN_METHODS & set(methods))
                joined = _class_joined_attrs(node)
                locks = _class_lock_attrs(node)
                walk_body(node.body, stack + [node.name], node, methods,
                          teardown, joined, locks)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                label = _fn_label(stack, node.name)
                _check_function(ctx, pf, node, label, cls, cls_methods,
                                cls_teardown, cls_joined, lock_attrs,
                                selected)
                walk_body(node.body, stack + [node.name], cls,
                          cls_methods, cls_teardown, cls_joined,
                          lock_attrs)

    walk_body(pf.tree.body, [], None, {}, False, set(), set())


def run(ctx: AnalysisContext, selected: Set[str]) -> None:
    """Run the resource passes over every parsed repo file."""
    if not selected & {"resource-leak", "thread-lifecycle"}:
        return
    for pf in ctx.files:
        if (pf.kind != "py" or pf.tree is None
                or not pf.rel.startswith("dmlc_core_tpu/")):
            continue
        _check_file(ctx, pf, selected)
