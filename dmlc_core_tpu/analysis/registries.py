"""Knob- and metric-registry passes.

``knob-registry``: every literal ``DMLC_*`` string in non-test code
must be declared in ``base/knobs.py`` (the central contract — see that
module's docstring).  Literal matching deliberately catches more than
``os.environ`` call sites: env names flow through helper constants
(``faultinject._ENV_SPEC``), env-dict ABIs (the tracker's
``slave_envs``) and ``get_env`` wrappers, and every one of those spells
the knob as a full literal somewhere.

``knob-doc``: every registry entry must appear somewhere under
``doc/`` (``doc/configuration.md`` is generated from the registry, so
this fails only when generation is skipped or a page regresses).

``metric-registry``: every metric declaration (``.counter(name, help,
labels=...)`` / ``.gauge`` / ``.histogram`` with a literal name) is
collected repo-wide; the same ``dmlc_<name>`` declared twice with a
different kind or label set is a collision the runtime registry would
only catch when both modules happen to load.

``metric-doc``: every declared metric's full name must appear in
``doc/observability.md``.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from dmlc_core_tpu.analysis.engine import AnalysisContext, ParsedFile

_KNOB_RE = re.compile(r"^DMLC_[A-Z0-9_]+$")
_METRIC_KINDS = {"counter": "counter", "gauge": "gauge",
                 "histogram": "histogram"}
#: the registry namespace ``MetricsRegistry.__init__`` prefixes
_NAMESPACE = "dmlc"


def _knob_scope(pf: ParsedFile) -> bool:
    """Knob literals are enforced everywhere except tests (which invent
    fake names on purpose) and the registry itself."""
    return (pf.kind == "py" and pf.tree is not None
            and not pf.rel.startswith("tests/"))


def _check_knobs(ctx: AnalysisContext, selected: Set[str]) -> None:
    doc_text = "\n".join(ctx.docs.values())
    used: Set[str] = set()
    for pf in ctx.files:
        if not _knob_scope(pf) or pf.rel == ctx.knobs_rel:
            continue
        for node in ast.walk(pf.tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and _KNOB_RE.match(node.value)):
                used.add(node.value)
                if ("knob-registry" in selected
                        and node.value not in ctx.knobs):
                    ctx.add(pf, node.lineno, "knob-registry",
                            f"env knob {node.value!r} is not declared in "
                            f"base/knobs.py (name, default, doc line)",
                            key=node.value)
    if "knob-doc" in selected:
        for name, line in sorted(ctx.knobs.items()):
            if name not in doc_text:
                ctx.add_at(ctx.knobs_rel, line, "knob-doc",
                           f"knob {name!r} is declared but appears "
                           f"nowhere under doc/ (regenerate "
                           f"doc/configuration.md)", key=name)


def _literal_str(node: ast.expr) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _literal_labels(node: ast.expr) -> Optional[Tuple[str, ...]]:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            s = _literal_str(e)
            if s is None:
                return None
            out.append(s)
        return tuple(out)
    return None


class _MetricDecl:
    __slots__ = ("name", "kind", "labels", "pf", "line")

    def __init__(self, name: str, kind: str,
                 labels: Optional[Tuple[str, ...]], pf: ParsedFile,
                 line: int) -> None:
        self.name = name
        self.kind = kind
        self.labels = labels
        self.pf = pf
        self.line = line


def _metric_decls(pf: ParsedFile) -> List[_MetricDecl]:
    out: List[_MetricDecl] = []
    for node in ast.walk(pf.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_KINDS
                and node.args):
            continue
        name = _literal_str(node.args[0])
        if name is None:
            continue
        # a declaration carries a help string and/or metric kwargs; the
        # profiler's Tracer.counter(name, value) event API does not
        kw = {k.arg for k in node.keywords}
        has_help = (len(node.args) >= 2
                    and _literal_str(node.args[1]) is not None)
        if not (has_help or kw & {"help", "labels", "buckets"}):
            continue
        labels: Optional[Tuple[str, ...]] = ()
        for k in node.keywords:
            if k.arg == "labels":
                labels = _literal_labels(k.value)
        if len(node.args) >= 3:
            labels = _literal_labels(node.args[2])
        full = (name if name.startswith(_NAMESPACE + "_")
                else f"{_NAMESPACE}_{name}")
        out.append(_MetricDecl(full, _METRIC_KINDS[node.func.attr],
                               labels, pf, node.lineno))
    return out


def _check_metrics(ctx: AnalysisContext, selected: Set[str]) -> None:
    decls: List[_MetricDecl] = []
    for pf in ctx.files:
        if (pf.kind != "py" or pf.tree is None
                or not pf.rel.startswith("dmlc_core_tpu/")):
            continue
        decls.extend(_metric_decls(pf))
    by_name: Dict[str, _MetricDecl] = {}
    obs = ctx.docs.get("doc/observability.md", "")
    doc_reported: Set[str] = set()
    for d in decls:
        first = by_name.setdefault(d.name, d)
        if ("metric-registry" in selected and first is not d
                and (first.kind != d.kind
                     or (first.labels is not None and d.labels is not None
                         and first.labels != d.labels))):
            ctx.add(d.pf, d.line, "metric-registry",
                    f"metric {d.name!r} re-declared as {d.kind}"
                    f"{list(d.labels or ())} — first declared as "
                    f"{first.kind}{list(first.labels or ())} at "
                    f"{first.pf.rel}:{first.line}", key=d.name)
        if ("metric-doc" in selected and d.name not in obs
                and d.name not in doc_reported):
            doc_reported.add(d.name)
            ctx.add(d.pf, d.line, "metric-doc",
                    f"metric {d.name!r} is not documented in "
                    f"doc/observability.md", key=d.name)


def run(ctx: AnalysisContext, selected: Set[str]) -> None:
    if selected & {"knob-registry", "knob-doc"}:
        _check_knobs(ctx, selected)
    if selected & {"metric-registry", "metric-doc"}:
        _check_metrics(ctx, selected)
