"""Project-aware static analysis (``scripts/dmlcheck.py``).

The reference ships a real static-analysis layer — ``scripts/lint.py``
cpplint/pylint wrappers plus CMake ``USE_SANITIZER`` race detection
(SURVEY.md §2d/§5).  This package is that layer re-founded on ``ast``
for the contracts THIS substrate actually breaks: lock discipline in
thread-shared classes, purity of jit-traced functions, and the knob /
metric registries.  One AST parse per file feeds every pass
(:mod:`~dmlc_core_tpu.analysis.engine`); findings flow through a
``# dmlcheck: off[:rule]`` suppression grammar and a committed baseline
for grandfathered findings.  ``doc/static_analysis.md`` is the user
guide; the dynamic counterpart of the lock pass lives in
:mod:`dmlc_core_tpu.base.lockcheck`.
"""

from dmlc_core_tpu.analysis.engine import (
    ALL_RULES, AnalysisContext, Finding, analyze, default_files,
    load_baseline, rule_help, write_baseline,
)

__all__ = [
    "ALL_RULES", "AnalysisContext", "Finding", "analyze", "default_files",
    "load_baseline", "rule_help", "write_baseline",
]
