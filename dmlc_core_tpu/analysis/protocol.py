"""``collective-discipline`` and ``wire-schema`` passes.

The distributed layers die in ways no unit test shows: a collective
issued on *some* ranks wedges every rank (the others wait forever at
the tracker), and a JSON header key one side sends but the other never
reads silently drops a field — or hangs a worker — only when the two
sides come from different versions.

``collective-discipline``: a collective (``allreduce`` / ``allgather``
/ ``broadcast`` / ``bcast`` / ``barrier`` / ``commit``) must be issued
in rank-invariant order.  The pass flags collective calls lexically
inside an ``if``/``else`` whose test reads a rank (``rank`` / ``wrank``
/ ``grank`` / ``task_id`` names or a ``.rank()`` call) — both arms are
rank-conditional: each runs on a complementary rank subset.  Functions
*named* like a collective are exempt (transport implementations
legitimately branch on rank inside ``def broadcast``).  Symmetric
protocols where every rank provably reaches a matching call by a
different path are the suppression case — annotate the site with
``# dmlcheck: off:collective-discipline`` plus the pairing rationale.

``wire-schema``: every literal message dict carrying a ``"cmd"`` key
must use a command and header keys declared in the central
``base/wire_schemas.py`` registry (parsed statically, so fixtures can
ship their own copy); the transport's own framing keys
(``WIRE_FRAMING``) are always allowed.  A dict whose ``cmd`` is
dynamic is checked against the union of all declared keys.  The same
contract covers the launch env ABI: ``DMLC_*`` keys *written into*
worker environments under ``launch/`` or ``tracker/`` must be declared
in ``ENV_ABI``.  Protocol drift thus fails lint at the sending site —
the reminder to update registry and receiving side in the same change.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, FrozenSet, Optional, Set, Tuple

from dmlc_core_tpu.analysis.engine import AnalysisContext, ParsedFile

__all__ = ["run", "EXPLAIN"]

_COLLECTIVES = {"allreduce", "allgather", "broadcast", "bcast", "barrier",
                "commit"}
_RANK_NAMES = {"rank", "wrank", "grank", "task_id"}
_ENV_KEY_RE = re.compile(r"^DMLC_[A-Z0-9_]+$")
_REGISTRY_REL = "dmlc_core_tpu/base/wire_schemas.py"

EXPLAIN = {
    "collective-discipline": {
        "doc": "Collective call (allreduce/allgather/broadcast/barrier/"
               "commit) under a rank-conditional branch — ranks that "
               "skip it leave the others waiting at the tracker "
               "forever.  Hoist the collective out of the branch, or "
               "suppress with the rationale for why every rank reaches "
               "a matching call.  Functions named like a collective "
               "(transport implementations) are exempt.",
        "flagged": (
            "def save(coll, model):\n"
            "    if coll.rank() == 0:\n"
            "        write(model)\n"
            "        coll.barrier('ckpt')   # ranks != 0 never arrive\n"),
        "clean": (
            "def save(coll, model):\n"
            "    if coll.rank() == 0:\n"
            "        write(model)\n"
            "    coll.barrier('ckpt')       # every rank arrives\n"),
    },
    "wire-schema": {
        "doc": "JSON message dict whose \"cmd\" or header keys are not "
               "declared in base/wire_schemas.py (or a DMLC_* env key "
               "injected by launch/tracker code that is missing from "
               "ENV_ABI).  The registry is the wire contract: a key "
               "only one side knows is protocol drift that surfaces as "
               "a hang between client and server versions.",
        "flagged": (
            "# base/wire_schemas.py declares\n"
            "#   'push': {'cmd', 'name', 'rank', 'clock'}\n"
            "conn.request({'cmd': 'push', 'name': n,\n"
            "              'momentum': m})   # undeclared key\n"),
        "clean": (
            "conn.request({'cmd': 'push', 'name': n, 'rank': r,\n"
            "              'clock': c})      # declared schema\n"),
    },
}


# -- registry loading (static, from the analyzed tree) ----------------------

def _const_str_set(node: ast.expr) -> Optional[FrozenSet[str]]:
    """``frozenset({...})`` / set / list / tuple of string constants."""
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id == "frozenset" and len(node.args) == 1):
        node = node.args[0]
    if not isinstance(node, (ast.Set, ast.List, ast.Tuple)):
        return None
    out = set()
    for e in node.elts:
        if not (isinstance(e, ast.Constant) and isinstance(e.value, str)):
            return None
        out.add(e.value)
    return frozenset(out)


def _load_registry(ctx: AnalysisContext) -> Tuple[
        Optional[Dict[str, FrozenSet[str]]], FrozenSet[str], FrozenSet[str]]:
    """(COMMANDS, ENV_ABI, WIRE_FRAMING) parsed from the repo under
    analysis — ``None`` commands when the registry file is absent."""
    tree = None
    for pf in ctx.files:
        if pf.rel == _REGISTRY_REL and pf.tree is not None:
            tree = pf.tree
            break
    if tree is None:
        return None, frozenset(), frozenset()
    commands: Dict[str, FrozenSet[str]] = {}
    env_abi: FrozenSet[str] = frozenset()
    framing: FrozenSet[str] = frozenset()
    for node in ast.walk(tree):
        if isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
            value = node.value
        elif isinstance(node, ast.Assign):
            targets = node.targets
            value = node.value
        else:
            continue
        names = {t.id for t in targets if isinstance(t, ast.Name)}
        if "COMMANDS" in names and isinstance(value, ast.Dict):
            for k, v in zip(value.keys, value.values):
                keys = _const_str_set(v)
                if (isinstance(k, ast.Constant) and isinstance(k.value, str)
                        and keys is not None):
                    commands[k.value] = keys
        elif "ENV_ABI" in names:
            env_abi = _const_str_set(value) or frozenset()
        elif "WIRE_FRAMING" in names:
            framing = _const_str_set(value) or frozenset()
    return commands, env_abi, framing


# -- wire-schema ------------------------------------------------------------

def _dict_cmd(node: ast.Dict) -> Tuple[bool, Optional[str], Set[str]]:
    """(has literal "cmd" key, cmd value if constant, all literal keys)."""
    has_cmd = False
    cmd: Optional[str] = None
    keys: Set[str] = set()
    for k, v in zip(node.keys, node.values):
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            continue                        # **spread / computed keys
        keys.add(k.value)
        if k.value == "cmd":
            has_cmd = True
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                cmd = v.value
    return has_cmd, cmd, keys


def _check_wire(ctx: AnalysisContext, pf: ParsedFile,
                commands: Optional[Dict[str, FrozenSet[str]]],
                framing: FrozenSet[str]) -> None:
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Dict):
            continue
        has_cmd, cmd, keys = _dict_cmd(node)
        if not has_cmd:
            continue
        if commands is None:
            ctx.add(pf, node.lineno, "wire-schema",
                    "message dict sent without a wire registry — create "
                    "base/wire_schemas.py and declare its cmd/keys",
                    key="registry-missing")
            continue
        if cmd is not None:
            if cmd not in commands:
                ctx.add(pf, node.lineno, "wire-schema",
                        f"message cmd {cmd!r} is not declared in "
                        f"base/wire_schemas.py", key=f"cmd:{cmd}")
                continue
            allowed = commands[cmd] | framing
            for k in sorted(keys - allowed):
                ctx.add(pf, node.lineno, "wire-schema",
                        f"key {k!r} is not in the declared schema for "
                        f"cmd {cmd!r} (allowed: "
                        f"{sorted(commands[cmd])})", key=f"{cmd}.{k}")
        else:
            # dynamic cmd (e.g. start|recover handshakes): every literal
            # key must at least exist in some declared command
            vocab = framing.union(*commands.values()) if commands \
                else framing
            for k in sorted(keys - vocab):
                ctx.add(pf, node.lineno, "wire-schema",
                        f"key {k!r} (dynamic cmd) appears in no declared "
                        f"command schema in base/wire_schemas.py",
                        key=f"dynamic.{k}")


def _check_env_abi(ctx: AnalysisContext, pf: ParsedFile,
                   env_abi: FrozenSet[str]) -> None:
    def flag(line: int, name: str) -> None:
        ctx.add(pf, line, "wire-schema",
                f"env key {name!r} is injected into a worker "
                f"environment but is not declared in "
                f"base/wire_schemas.py ENV_ABI", key=f"env:{name}")

    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.slice, ast.Constant)
                        and isinstance(t.slice.value, str)
                        and _ENV_KEY_RE.match(t.slice.value)
                        and t.slice.value not in env_abi):
                    flag(node.lineno, t.slice.value)
        elif (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "setdefault" and node.args):
            a0 = node.args[0]
            if (isinstance(a0, ast.Constant) and isinstance(a0.value, str)
                    and _ENV_KEY_RE.match(a0.value)
                    and a0.value not in env_abi):
                flag(node.lineno, a0.value)
        elif isinstance(node, ast.Dict):
            for k in node.keys:
                if (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        and _ENV_KEY_RE.match(k.value)
                        and k.value not in env_abi):
                    flag(k.lineno, k.value)


# -- collective-discipline --------------------------------------------------

def _reads_rank(test: ast.expr) -> bool:
    for n in ast.walk(test):
        if isinstance(n, ast.Name) and n.id in _RANK_NAMES:
            return True
        if isinstance(n, ast.Attribute) and n.attr in _RANK_NAMES:
            return True
    return False


class _RankBranchScanner(ast.NodeVisitor):
    """Flag collective calls under rank-conditional branches within ONE
    function (does not descend into nested defs/classes)."""

    def __init__(self, ctx: AnalysisContext, pf: ParsedFile,
                 fname: str) -> None:
        self.ctx = ctx
        self.pf = pf
        self.fname = fname
        self.depth = 0                      # rank-conditional nesting

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass                                # own walk

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        pass

    def visit_If(self, node: ast.If) -> None:
        ranked = _reads_rank(node.test)
        self.visit(node.test)
        if ranked:
            self.depth += 1
        for stmt in node.body + node.orelse:
            self.visit(stmt)
        if ranked:
            self.depth -= 1

    def visit_Call(self, node: ast.Call) -> None:
        if self.depth > 0:
            name = (node.func.attr if isinstance(node.func, ast.Attribute)
                    else node.func.id if isinstance(node.func, ast.Name)
                    else "")
            if name in _COLLECTIVES:
                self.ctx.add(
                    self.pf, node.lineno, "collective-discipline",
                    f"{self.fname}() issues collective {name!r} under a "
                    f"rank-conditional branch — ranks that skip it wedge "
                    f"the world; hoist it or suppress with the pairing "
                    f"rationale", key=f"{self.fname}:{name}")
        self.generic_visit(node)


def _check_collectives(ctx: AnalysisContext, pf: ParsedFile) -> None:
    for node in ast.walk(pf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name in _COLLECTIVES:
            continue                        # transport implementations
        sc = _RankBranchScanner(ctx, pf, node.name)
        for stmt in node.body:
            sc.visit(stmt)


def run(ctx: AnalysisContext, selected: Set[str]) -> None:
    """Run the protocol passes over every parsed repo file."""
    wire = "wire-schema" in selected
    coll = "collective-discipline" in selected
    if not (wire or coll):
        return
    commands, env_abi, framing = _load_registry(ctx) if wire \
        else (None, frozenset(), frozenset())
    for pf in ctx.files:
        if (pf.kind != "py" or pf.tree is None
                or not pf.rel.startswith("dmlc_core_tpu/")
                or pf.rel == _REGISTRY_REL):
            continue
        if coll:
            _check_collectives(ctx, pf)
        if wire:
            _check_wire(ctx, pf, commands, framing)
            if (pf.rel.startswith("dmlc_core_tpu/launch/")
                    or pf.rel.startswith("dmlc_core_tpu/tracker/")):
                _check_env_abi(ctx, pf, env_abi)
