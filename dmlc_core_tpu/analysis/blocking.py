"""``lock-blocking`` pass: no blocking calls while a lock is held.

Scope: the same lock-owning classes the ``lock-discipline`` pass walks
(classes in ``dmlc_core_tpu/`` owning a ``Lock``/``RLock``/``Condition``
attribute).  Inside a ``with self.<lock>:`` block — or anywhere in a
``*_locked`` method, whose name asserts the caller holds the lock — the
pass flags calls that can block for unbounded (or merely *long*) time
while every other thread queues on the monitor:

* ``time.sleep(...)`` — sleeping under a lock serializes the world;
* socket ops: ``.recv`` / ``.recvfrom`` / ``.recv_into`` / ``.accept``
  / ``.connect`` / ``.sendall`` — network time under a lock;
* HTTP helpers: ``http_request(...)`` / ``urlopen(...)``;
* subprocess waits: ``subprocess.run/call/check_call/check_output``,
  ``.communicate()``, ``os.waitpid``;
* ``.wait()`` with NO timeout on anything that is not one of the
  class's own condition variables (a ``Condition.wait`` **releases**
  the monitor it was built on — that is the one wait that belongs
  under the lock; an ``Event.wait()`` does not release anything);
* ``.join()`` with no arguments (thread/process join — ``str.join``
  always takes the iterable, so a zero-arg ``.join()`` is a blocking
  join);
* queue ``.get/.put/.push/.pop`` without a ``timeout=`` (and without
  ``block=False``) when the receiver *names* a queue (``queue`` in the
  name, or ``q``/``*_q``) — heuristic on purpose: ``dict.get(k)`` must
  not fire.

A timeout argument is accepted as evidence of boundedness; the pass
checks discipline, not worst-case latency.  Suppress intentional sites
with ``# dmlcheck: off:lock-blocking`` plus a rationale comment.
"""

from __future__ import annotations

import ast
from typing import Set

from dmlc_core_tpu.analysis.engine import AnalysisContext, ParsedFile
from dmlc_core_tpu.analysis.locks import _class_lock_attrs, _self_attr

__all__ = ["run", "EXPLAIN"]

_SOCKET_METHODS = {"recv", "recvfrom", "recv_into", "accept", "connect",
                   "sendall"}
_HTTP_CALLS = {"http_request", "urlopen"}
_SUBPROCESS_FUNCS = {"run", "call", "check_call", "check_output"}
_QUEUE_METHODS = {"get", "put", "push", "pop"}

EXPLAIN = {
    "lock-blocking": {
        "doc": "Blocking call (sleep / socket / HTTP / subprocess wait / "
               "untimed wait / join / untimed queue op) made while one of "
               "the class's locks is held — every other thread queues on "
               "the monitor for the call's full duration.  Condition.wait "
               "on the class's own condvars is exempt (it releases the "
               "monitor); a timeout argument is accepted as boundedness.",
        "flagged": (
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def step(self):\n"
            "        with self._lock:\n"
            "            time.sleep(1.0)      # world stops with you\n"),
        "clean": (
            "class Worker:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def step(self):\n"
            "        with self._lock:\n"
            "            todo = list(self._pending)\n"
            "        time.sleep(1.0)          # sleep outside the lock\n"),
    },
}


def _call_name(func: ast.expr) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _receiver_name(func: ast.expr) -> str:
    """Last name component of the receiver for ``recv.x(...)``, '' for
    bare-name calls."""
    if not isinstance(func, ast.Attribute):
        return ""
    v = func.value
    if isinstance(v, ast.Name):
        return v.id
    if isinstance(v, ast.Attribute):
        return v.attr
    return ""


def _has_kw(node: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in node.keywords)


def _kw_is_false(node: ast.Call, name: str) -> bool:
    for kw in node.keywords:
        if (kw.arg == name and isinstance(kw.value, ast.Constant)
                and kw.value.value is False):
            return True
    return False


def _looks_like_queue(name: str) -> bool:
    low = name.lower()
    return "queue" in low or low == "q" or low.endswith("_q")


class _BlockingScanner(ast.NodeVisitor):
    """Flag blocking calls made at ``held_depth > 0`` in one method."""

    def __init__(self, ctx: AnalysisContext, pf: ParsedFile,
                 cls_name: str, lock_attrs: Set[str], method: str) -> None:
        self.ctx = ctx
        self.pf = pf
        self.cls_name = cls_name
        self.lock_attrs = lock_attrs
        self.method = method
        self.held_depth = 1 if method.endswith("_locked") else 0

    def visit_With(self, node: ast.With) -> None:
        locks_here = sum(
            1 for item in node.items
            if _self_attr(item.context_expr) in self.lock_attrs)
        for item in node.items:
            self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        self.held_depth += locks_here
        for stmt in node.body:
            self.visit(stmt)
        self.held_depth -= locks_here

    def _flag(self, node: ast.Call, what: str) -> None:
        self.ctx.add(
            self.pf, node.lineno, "lock-blocking",
            f"{self.cls_name}.{self.method}() makes a blocking call "
            f"({what}) while holding a lock — move it outside the "
            f"critical section or bound it with a timeout",
            key=f"{self.cls_name}.{self.method}:{what}")

    def _classify(self, node: ast.Call) -> str:
        """'' when the call cannot block the monitor, else a short tag."""
        name = _call_name(node.func)
        recv = _receiver_name(node.func)
        if name == "sleep" and (recv in ("", "time")):
            return "time.sleep"
        if name in _SOCKET_METHODS and recv not in ("", "self"):
            return f"socket.{name}"
        if name in _HTTP_CALLS:
            return name
        if name in _SUBPROCESS_FUNCS and recv == "subprocess":
            return f"subprocess.{name}"
        if name == "waitpid" and recv == "os":
            return "os.waitpid"
        if name == "communicate" and not _has_kw(node, "timeout"):
            return "communicate"
        if name == "wait":
            # Condition.wait on the class's own condvars RELEASES the
            # monitor — that is the one wait that belongs under a lock.
            if _self_attr(node.func.value) in self.lock_attrs:
                return ""
            if node.args or _has_kw(node, "timeout"):
                return ""                       # bounded
            return "wait"
        if name == "join" and not node.args and not _has_kw(node, "timeout"):
            return "join"
        if (name in _QUEUE_METHODS
                and _looks_like_queue(recv or _self_attr(node.func.value))
                and not _has_kw(node, "timeout")
                and not _kw_is_false(node, "block")
                and not _kw_is_false(node, "blocking")):
            return f"queue.{name}"
        return ""

    def visit_Call(self, node: ast.Call) -> None:
        if self.held_depth > 0:
            what = self._classify(node)
            if what:
                self._flag(node, what)
        self.generic_visit(node)


def _check_class(ctx: AnalysisContext, pf: ParsedFile,
                 cls: ast.ClassDef) -> None:
    lock_attrs = _class_lock_attrs(cls)
    if not lock_attrs:
        return
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            sc = _BlockingScanner(ctx, pf, cls.name, lock_attrs, item.name)
            for stmt in item.body:
                sc.visit(stmt)


def run(ctx: AnalysisContext, selected: Set[str]) -> None:
    """Run the ``lock-blocking`` pass over every parsed repo file."""
    if "lock-blocking" not in selected:
        return
    for pf in ctx.files:
        if (pf.kind != "py" or pf.tree is None
                or not pf.rel.startswith("dmlc_core_tpu/")):
            continue
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.ClassDef):
                _check_class(ctx, pf, node)
