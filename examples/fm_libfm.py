"""Factorization machine on a .libfm file (the LibFM-parser consumer).

Writes a synthetic field-aware dataset in LibFM format (``label
field:index:value ...``), then trains a second-order FM through the full
data plane: Parser → RowBlockIter → per-page dense batches → jitted
data-parallel Adam steps.

Run: python examples/fm_libfm.py
"""
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.data.iter import RowBlockIter
from dmlc_core_tpu.models import FM


def write_libfm(path, n=20_000, F=10, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, F)).astype(np.float32)
    # purely pairwise signal — a linear model cannot fit this
    y = (1.5 * X[:, 0] * X[:, 1] - 2.0 * X[:, 2] * X[:, 3] > 0)
    with open(path, "w") as f:
        for i in range(n):
            feats = " ".join(f"{j % 4}:{j}:{X[i, j]:.6f}" for j in range(F))
            f.write(f"{int(y[i])} {feats}\n")
    return X, y.astype(np.float32)


def main():
    root = tempfile.mkdtemp()
    path = os.path.join(root, "train.libfm")
    X, y = write_libfm(path)

    model = FM(n_factors=8, n_epochs=20, learning_rate=0.1,
               batch_size=4096)
    it = RowBlockIter.create(path, 0, 1, "libfm")
    model.fit_iter(it)
    it.close()

    acc = float(((model.predict(X) > 0.5) == (y > 0.5)).mean())
    print(f"train accuracy {acc:.3f} in {model.last_fit_seconds:.1f}s "
          f"({model.param.n_epochs} epochs)")


if __name__ == "__main__":
    main()
