"""Serve a hist-GBT model over HTTP: train → checkpoint → registry load
→ query → hot-swap to v2 with zero downtime.

Run: python examples/serve_gbt.py  (CPU or TPU; no downloads — synthetic
HIGGS-like data; the server binds an ephemeral localhost port).
"""
import json
import os
import sys
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.models import HistGBT
from dmlc_core_tpu.serve import (ModelRegistry, ServeFrontend,
                                 checkpoint_model)


def make_data(n, seed):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 28)).astype(np.float32)
    margin = X[:, 0] * X[:, 1] + 0.5 * X[:, 2] - 0.8 * X[:, 3] * (X[:, 4] > 0)
    return X, (margin > 0).astype(np.float32)


def post_predict(url, rows):
    body = json.dumps({"rows": np.asarray(rows).tolist()}).encode()
    req = urllib.request.Request(
        url + "/predict", data=body,
        headers={"Content-Type": "application/json"})
    return json.loads(urllib.request.urlopen(req, timeout=30).read())


def main():
    X, y = make_data(100_000, seed=7)

    # two model generations, checkpointed with monotone versions (any
    # Stream URI works: file://, mem://, object stores)
    ck = "/tmp/serve_gbt_example"
    for version, n_trees in ((1, 20), (2, 60)):
        model = HistGBT(n_trees=n_trees, max_depth=5, n_bins=64,
                        learning_rate=0.3)
        model.fit(X, y)
        checkpoint_model(f"{ck}.v{version}", model, version=version)
        print(f"checkpointed v{version}: {n_trees} trees")

    registry = ModelRegistry(max_batch=256, min_bucket=8)
    registry.load(f"{ck}.v1")

    with ServeFrontend(registry, max_batch=256, max_delay=0.002) as fe:
        print(f"serving on {fe.url}")
        resp = post_predict(fe.url, X[:5])
        print(f"v{resp['version']} predictions: "
              f"{np.round(resp['predictions'], 4)}")

        # hot-swap: in-flight batches finish on v1, new batches see v2
        registry.load(f"{ck}.v2")
        resp = post_predict(fe.url, X[:5])
        print(f"after hot-swap, v{resp['version']} predictions: "
              f"{np.round(resp['predictions'], 4)}")

        health = json.loads(urllib.request.urlopen(
            fe.url + "/healthz", timeout=10).read())
        print(f"healthz: {health}")
        metrics = urllib.request.urlopen(
            fe.url + "/metrics", timeout=10).read().decode()
        print("sample /metrics lines:")
        for line in metrics.splitlines():
            if line.startswith("dmlc_serve_batch_rows_count") or \
                    line.startswith("dmlc_serve_version_requests_total"):
                print(" ", line)


if __name__ == "__main__":
    main()
