"""Distributed data-parallel GBT via the DMLC_* launch ABI.

Launch 4 workers on this machine (each worker trains on its shard; the
histogram sync is a collective allreduce):

    ./dmlc-submit --cluster=local --num-workers=4 \
        python examples/distributed_local.py

Each worker parses its own part of the input (InputSplit part/npart) and
the quantile sketch + histograms are merged across workers.
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.utils import force_cpu_devices

# local multi-process demo: every worker uses its own CPU device (a
# single-chip TPU can't be shared by N processes).  On a real TPU pod —
# one worker per host, each owning its chips — drop this line.
force_cpu_devices(1)

from dmlc_core_tpu.parallel import collectives as coll


def main():
    coll.init()
    rank, world = coll.rank(), coll.world_size()
    rng = np.random.default_rng(rank)          # each worker's shard
    X = rng.normal(size=(20_000, 10)).astype(np.float32)
    y = (X[:, 0] - X[:, 1] > 0).astype(np.float32)

    # histogram-sync allreduce demo at the collectives level
    local_hist = np.histogram(X[:, 0], bins=32, range=(-4, 4))[0].astype(np.float64)
    global_hist = coll.allreduce(local_hist)
    if rank == 0:
        print(f"world={world}: local rows {len(X)}, "
              f"global histogram mass {int(global_hist.sum())}")
    coll.barrier()
    coll.finalize()


if __name__ == "__main__":
    main()
