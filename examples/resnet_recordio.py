"""RecordIO → ResNet training pipeline (BASELINE config 2's shape).

Writes a small synthetic image dataset as sharded RecordIO files (the
MXNet `.rec` wire format), then trains a ResNet over them through the
full data plane: sharded `InputSplit` → record unpack → `DeviceFeed`
double-buffered infeed → jitted train steps, reporting throughput and
the infeed stall fraction.

Run: python examples/resnet_recordio.py
"""
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.data.image_record import pack_image_record
from dmlc_core_tpu.io.recordio import RecordIOWriter
from dmlc_core_tpu.models.resnet import ResNetTrainer


def write_shards(root, n_shards=2, per_shard=256, hw=32):
    rng = np.random.default_rng(0)
    for s in range(n_shards):
        with RecordIOWriter(os.path.join(root, f"part-{s}.rec")) as w:
            for _ in range(per_shard):
                label = int(rng.integers(0, 10))
                img = (rng.random((hw, hw, 3)) * 255).astype(np.uint8)
                # class signal: channel 0 brightness tracks the label
                img[..., 0] = np.clip(
                    img[..., 0].astype(np.int32) // 4 + label * 25,
                    0, 255).astype(np.uint8)
                w.write_record(pack_image_record(img, label))


def main():
    root = tempfile.mkdtemp()
    write_shards(root)

    trainer = ResNetTrainer(variant="resnet18", num_classes=10,
                            learning_rate=0.05)
    stats = trainer.fit_from_records(
        os.path.join(root, "part-*.rec"),
        batch_size=64, image_shape=(32, 32, 3), epochs=3, log_every=8)
    print({k: round(v, 4) if isinstance(v, float) else v
           for k, v in stats.items()})


if __name__ == "__main__":
    main()
