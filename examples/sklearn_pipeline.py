"""The sklearn-style surface: estimators, Pipeline, GridSearchCV.

Run: python examples/sklearn_pipeline.py  (CPU or TPU; synthetic data).

Code written against XGBClassifier/XGBRegressor/XGBRanker ports by
changing the import: same fit/predict/predict_proba/score shape, same
``booster=`` knob, composable with real sklearn utilities.
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.models import GBTClassifier, GBTRegressor


def main():
    rng = np.random.default_rng(7)
    X = rng.normal(size=(5000, 8)).astype(np.float32)
    y = np.where(X[:, 0] + 0.5 * X[:, 1] * X[:, 2] > 0, "spam", "ham")

    for booster in ("gbtree", "gblinear"):
        clf = GBTClassifier(booster=booster, n_estimators=60, max_depth=5)
        clf.fit(X[:4000], y[:4000])
        print(f"{booster:9s} holdout accuracy "
              f"{clf.score(X[4000:], y[4000:]):.4f}")

    reg = GBTRegressor(n_estimators=80)
    yr = 2 * X[:, 0] - X[:, 1] + 0.1 * rng.normal(size=len(X))
    reg.fit(X[:4000], yr[:4000])
    print(f"regressor holdout R2    {reg.score(X[4000:], yr[4000:]):.4f}")

    try:
        from sklearn.model_selection import GridSearchCV
    except ImportError:
        print("(sklearn not installed - skipping GridSearchCV demo)")
        return
    gs = GridSearchCV(GBTClassifier(n_estimators=30),
                      {"max_depth": [3, 5]}, cv=2, scoring="accuracy")
    gs.fit(X[:2000], y[:2000])
    print(f"grid search best        {gs.best_params_} "
          f"(cv acc {gs.best_score_:.4f})")


if __name__ == "__main__":
    main()
