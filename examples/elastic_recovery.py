"""Elastic recovery drill: kill a worker mid-fit, relaunch, resume, match.

The reference stack's distinctive distributed capability is the
*composition* of three mechanisms (SURVEY.md §5 failure-recovery):

* the tracker notices a dead worker and frees its rank
  (``tracker.py :: RabitTracker`` liveness),
* the cluster manager relaunches the attempt with a bumped
  ``DMLC_NUM_ATTEMPT`` (the YARN ApplicationMaster's restart counting),
* the restarted worker reclaims its rank (``cmd=recover``) and reloads
  model state, so training continues instead of starting over.

This drill proves the composition end to end on real processes:

1. an "application master" loop launches 2 workers through the DMLC env
   ABI; a :class:`RabitTracker` runs for the whole job (all attempts);
2. each worker trains HistGBT over the process-spanning mesh in
   SEGMENTS (a continued fit per segment), checkpointing to a URI after
   every segment (rank 0 writes, atomic meta rename, barrier);
3. on attempt 0, worker 1 SIGKILLs itself MID-FIT — between dispatch
   chunks inside segment ``DRILL_KILL_SEG``'s boosting loop, after the
   segment checkpoint machinery has already persisted earlier segments;
4. the AM reaps the -9, gang-kills the survivor (the YARN abort-kill
   semantics), bumps ``DMLC_NUM_ATTEMPT``, and relaunches; the tracker
   has marked both ranks dead and hands them back via ``recover``;
5. attempt 1 resumes from the last durable checkpoint and finishes;
6. the final model must match an UNINTERRUPTED run tree-for-tree.

Run it standalone:

    python examples/elastic_recovery.py

(The file is its own worker: the AM launches ``python <this file>
--worker`` per rank.  ``tests/test_parallel.py`` drives the same
``run_drill`` in the slow lane.)
"""
import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SEGS = 4            # checkpoint segments
SEG_TREES = 4       # boosting rounds per segment
KILL_SEG = 2        # worker 1 dies inside this segment's fit (attempt 0)
N_BINS = 32
KW = dict(max_depth=3, n_bins=N_BINS, learning_rate=0.5, n_trees=SEG_TREES)


def make_data():
    import numpy as np
    rng = np.random.default_rng(42)
    X = rng.normal(size=(512, 8)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 0.3 * X[:, 2] > 0).astype(np.float32)
    return X, y


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------

def worker_main():
    from dmlc_core_tpu.utils import force_cpu_devices
    force_cpu_devices(1)
    import numpy as np
    from dmlc_core_tpu.parallel import collectives as coll
    from dmlc_core_tpu.tracker.tracker import WorkerSession

    task = int(os.environ["DMLC_TASK_ID"])
    attempt = int(os.environ.get("DMLC_NUM_ATTEMPT", "0"))
    ckdir = os.environ["DRILL_CKPT_DIR"]
    kill_seg = int(os.environ.get("DRILL_KILL_SEG", "-1"))
    uri = os.environ["DMLC_TRACKER_URI"]
    legacy_port = int(os.environ["DMLC_LEGACY_TRACKER_PORT"])

    # host-level tracker session: fresh rank on attempt 0, RECLAIM the
    # freed rank on a restart (the rabit recover path)
    if attempt == 0:
        ws = WorkerSession(uri, legacy_port, host=f"host{task}")
    else:
        ws = WorkerSession(uri, legacy_port, cmd="recover", rank=task)
        assert ws.info["rank"] == task, ws.info

    coll.init()
    import jax
    from jax.sharding import Mesh
    from dmlc_core_tpu.models import HistGBT
    from dmlc_core_tpu.ops.quantile import compute_cuts

    class KillableGBT(HistGBT):
        """SIGKILL between dispatch chunks of one fit() — a genuine
        mid-fit crash (trees of the current segment already partially
        fetched, segment checkpoint not yet written)."""
        kill_at_chunk = -1

        def _boost_binned(self, *a, **kw):
            seen = {"n": 0}

            def cb(rounds_fetched, elapsed_s):
                seen["n"] += 1
                if seen["n"] == self.kill_at_chunk:
                    ws.print_msg(f"worker {task}: SIGKILL mid-fit "
                                 f"(chunk {seen['n']})")
                    os.kill(os.getpid(), signal.SIGKILL)

            kw["chunk_callback"] = cb
            return super()._boost_binned(*a, **kw)

    X, y = make_data()
    cuts = compute_cuts(X, N_BINS)
    mesh = Mesh(np.array(jax.devices()), ("data",))

    meta_path = os.path.join(ckdir, "meta.json")
    start_seg = 0
    model = None
    if os.path.exists(meta_path):
        meta = json.load(open(meta_path))
        start_seg = meta["segments_done"]
        model = HistGBT.load_model(
            os.path.join(ckdir, f"seg{start_seg}.bin"), mesh=mesh)
        model.param.init({"n_trees": SEG_TREES})
        ws.print_msg(f"worker {task}: resumed at segment {start_seg} "
                     f"({len(model.trees)} trees)")

    for seg in range(start_seg, SEGS):
        if model is None:
            model = KillableGBT(mesh=mesh, **KW)
            if attempt == 0 and task == 1 and seg == kill_seg:
                model.kill_at_chunk = 2
            model.fit(X, y, cuts=cuts)
        else:
            if isinstance(model, KillableGBT):
                model.kill_at_chunk = (
                    2 if attempt == 0 and task == 1 and seg == kill_seg
                    else -1)
            model.fit(X, y)                  # continued fit, cuts kept
        if coll.rank() == 0:
            model.save_model(os.path.join(ckdir, f"seg{seg + 1}.bin"))
            tmp = meta_path + ".tmp"
            json.dump({"segments_done": seg + 1}, open(tmp, "w"))
            os.replace(tmp, meta_path)       # atomic: no torn meta
        coll.barrier()                       # checkpoint durable for all
        ws.print_msg(f"worker {task}: segment {seg + 1}/{SEGS} done")

    if coll.rank() == 0:
        model.save_model(os.path.join(ckdir, "final.bin"))
    coll.barrier()
    ws.shutdown()
    coll.finalize()


# ---------------------------------------------------------------------------
# application-master side
# ---------------------------------------------------------------------------

def run_drill(ckdir, kill=True, max_attempts=3, timeout=600):
    """Run the full drill; returns a report dict.

    ``kill=False`` runs the same gang/segments with no crash (the
    uninterrupted comparator can also be produced in-process; see
    ``reference_fit``).
    """
    from dmlc_core_tpu.tracker.tracker import RabitTracker, _free_port

    os.makedirs(ckdir, exist_ok=True)
    tracker = RabitTracker(host_ip="127.0.0.1", nworker=2)
    tracker.start()
    report = {"attempts": [], "dead_seen": [], "recovered": False}
    try:
        for attempt in range(max_attempts):
            env = dict(os.environ)
            env.update({
                "DMLC_NUM_WORKER": "2",
                "DMLC_NUM_SERVER": "0",
                "DMLC_TRACKER_URI": "127.0.0.1",
                # fresh jax.distributed coordinator port per attempt (the
                # previous attempt's coordinator died with worker 0)
                "DMLC_TRACKER_PORT": str(_free_port("127.0.0.1")),
                "DMLC_LEGACY_TRACKER_PORT": str(tracker.port),
                "DMLC_NUM_ATTEMPT": str(attempt),
                "DMLC_ROLE": "worker",
                "DRILL_CKPT_DIR": ckdir,
                "DRILL_KILL_SEG": str(KILL_SEG if kill else -1),
                "PYTHONPATH": REPO,
                # several dispatch chunks per segment so "mid-fit"
                # (between chunks) is a real interior point
                "DMLC_TPU_ROUNDS_PER_DISPATCH": "2",
            })
            procs = []
            for task in range(2):
                e = dict(env)
                e["DMLC_TASK_ID"] = str(task)
                procs.append(subprocess.Popen(
                    [sys.executable, os.path.abspath(__file__), "--worker"],
                    env=e))
            deadline = time.monotonic() + timeout
            codes = [None, None]
            failed = False
            while any(c is None for c in codes):
                if time.monotonic() > deadline:
                    for p in procs:
                        p.kill()
                    raise TimeoutError("drill attempt timed out")
                for i, p in enumerate(procs):
                    if codes[i] is None and p.poll() is not None:
                        codes[i] = p.returncode
                        if p.returncode != 0 and not failed:
                            failed = True
                            # YARN AM semantics: one container down →
                            # abort-kill the gang, count the attempt
                            for q in procs:
                                if q.poll() is None:
                                    q.kill()
                time.sleep(0.05)
            report["attempts"].append({"attempt": attempt, "codes": codes})
            if not failed:
                report["recovered"] = attempt > 0
                break
            # liveness: the tracker must have noticed the deaths and
            # freed the ranks before the relaunch reclaims them
            t0 = time.monotonic()
            while time.monotonic() - t0 < 10:
                if len(tracker.dead_workers) >= 2:
                    break
                time.sleep(0.05)
            report["dead_seen"] = sorted(set(tracker.dead_workers))
        else:
            raise RuntimeError(f"drill failed all {max_attempts} attempts: "
                               f"{report}")
    finally:
        tracker.stop()
    report["final_model"] = os.path.join(ckdir, "final.bin")
    return report


def main():
    import tempfile

    import numpy as np

    from dmlc_core_tpu.utils import force_cpu_devices
    force_cpu_devices(1)

    with tempfile.TemporaryDirectory() as killed_dir, \
            tempfile.TemporaryDirectory() as clean_dir:
        report = run_drill(killed_dir, kill=True)
        print(f"attempts: {report['attempts']}")
        print(f"tracker saw dead ranks: {report['dead_seen']}")
        assert report["recovered"], "expected a restart to happen"

        # the comparator: the SAME 2-process job, never killed.  (The
        # crash must be invisible in the result — every segment replays
        # through the same continued-fit path either way, so parity is
        # tree-for-tree exact.  A 1-process fit is NOT the comparator:
        # psum rounding can flip near-tie splits in later trees.)
        clean = run_drill(clean_dir, kill=False)
        assert clean["attempts"] == [{"attempt": 0, "codes": [0, 0]}], clean

        from dmlc_core_tpu.models import HistGBT
        recovered = HistGBT.load_model(report["final_model"])
        ref = HistGBT.load_model(clean["final_model"])
        assert len(recovered.trees) == len(ref.trees) == SEGS * SEG_TREES
        for i, (tr, tf) in enumerate(zip(recovered.trees, ref.trees)):
            assert np.array_equal(tr["feat"], tf["feat"]), i
            assert np.array_equal(tr["thr"], tf["thr"]), i
            np.testing.assert_array_equal(tr["leaf"], tf["leaf"])
        X, y = make_data()
        np.testing.assert_array_equal(recovered.predict(X), ref.predict(X))
        acc = ((recovered.predict(X) > 0.5) == y).mean()
        print(f"recovered model == uninterrupted model, bit-exact "
              f"({len(ref.trees)} trees, train acc {acc:.3f})")


if __name__ == "__main__":
    if "--worker" in sys.argv:
        worker_main()
    else:
        main()
