"""Train hist-GBT end-to-end: binning, boosting, early stopping, save/load.

Run: python examples/train_gbt.py  (CPU or TPU; no downloads — synthetic
HIGGS-like data).
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.models import HistGBT


def make_data(n, seed):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 28)).astype(np.float32)
    margin = X[:, 0] * X[:, 1] + 0.5 * X[:, 2] - 0.8 * X[:, 3] * (X[:, 4] > 0)
    return X, (margin > 0).astype(np.float32)


def main():
    X, y = make_data(200_000, seed=7)
    Xv, yv = make_data(50_000, seed=8)

    model = HistGBT(
        n_trees=200, max_depth=6, n_bins=256, learning_rate=0.3,
        subsample=0.8, eval_metric="auc",
    )
    model.fit(X, y, eval_set=(Xv, yv), early_stopping_rounds=20)
    print(f"trained {len(model.trees)} trees in {model.last_fit_seconds:.1f}s "
          f"(best auc={model.best_score:.4f} @ iter {model.best_iteration})")

    acc = ((model.predict(Xv) > 0.5) == yv).mean()
    print(f"validation accuracy: {acc:.4f}")
    print(f"feature importances: {model.feature_importances()[:8]}...")

    model.save_model("/tmp/gbt_example.bin")
    again = HistGBT.load_model("/tmp/gbt_example.bin")
    assert (again.predict(Xv) == model.predict(Xv)).all()
    print("saved, reloaded, predictions identical")


if __name__ == "__main__":
    main()
