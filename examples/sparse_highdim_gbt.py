"""High-dimensional sparse boosting: LibSVM → CSR → SparseHistGBT.

The workload the LibSVM format exists for — bag-of-words / hashed
one-hot features (F ≈ 10⁴–10⁶, density < 1%) — where a dense ``[n, F]``
bin matrix is impossible and absent entries carry meaning (XGBoost's
sparsity-aware missing semantics).  The sparse engine bins PRESENT
values into ragged per-feature cuts, builds O(nnz) histograms, and
learns a default direction per node for the absent mass.

Run: python examples/sparse_highdim_gbt.py
"""
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.data import RowBlockIter
from dmlc_core_tpu.models.histgbt_sparse import SparseHistGBT


def main():
    tmp = tempfile.mkdtemp()
    svm = os.path.join(tmp, "train.svm")
    rng = np.random.default_rng(0)
    n, F, per_row = 8_000, 50_000, 30
    # power-law feature popularity; features 0/1 carry the label
    pop = 1.0 / np.arange(1, F + 1) ** 0.7
    pop /= pop.sum()
    rows = []
    y = np.empty(n, np.int32)
    for i in range(n):
        cols = np.unique(np.concatenate(
            [[0, 1], rng.choice(F, size=per_row, p=pop)]))
        vals = rng.normal(size=len(cols)).astype(np.float32)
        v0 = vals[cols == 0][0]
        v1 = vals[cols == 1][0]
        y[i] = int(v0 + 0.5 * v1 > 0)
        rows.append((cols, vals))
    with open(svm, "w") as f:
        for i, (cols, vals) in enumerate(rows):
            feats = " ".join(f"{c}:{v:.4f}" for c, v in zip(cols, vals))
            f.write(f"{y[i]} {feats}\n")

    # parse through the data plane, then hand the CSR arrays straight to
    # the sparse engine (one block here; concatenate for paged inputs)
    blocks = list(RowBlockIter.create(svm, 0, 1, "libsvm"))
    offset = np.concatenate(
        [[0]] + [np.diff(b.offset) for b in blocks]).cumsum()
    index = np.concatenate([b.index for b in blocks])
    value = np.concatenate(
        [b.value if b.value is not None else np.ones(len(b.index),
                                                     np.float32)
         for b in blocks])
    label = np.concatenate([b.label for b in blocks])

    model = SparseHistGBT(n_trees=20, max_depth=4, n_bins=32,
                          learning_rate=0.4)
    model.fit(offset, index, value, label, n_features=F)
    pred = model.predict(offset, index, value)
    acc = ((pred > 0.5) == label).mean()
    print(f"F={F}: {model.cuts.total_bins} ragged bins "
          f"(dense would need {F * 32}), train acc {acc:.3f}")
    assert acc > 0.9

    uri = os.path.join(tmp, "sparse_model.bin")
    model.save_model(uri)
    again = SparseHistGBT.load_model(uri)
    np.testing.assert_array_equal(
        again.predict(offset, index, value, output_margin=True),
        model.predict(offset, index, value, output_margin=True))
    print("save/load round trip OK")


if __name__ == "__main__":
    main()
