"""One command from a live event stream to continuously-updated,
served predictions — the closed train→serve loop (doc/streaming.md).

A writer thread appends synthetic events (with concept drift between
phases) to a growing RecordIO shard directory; a RecordIOTailer follows
it with a crash-safe cursor; an OnlineTrainer warm-start-boosts a
HistGBT on each fresh chunk; a ModelPublisher snapshots, eval-gates and
atomically activates every refresh into the serving ModelRegistry; a
ServeFrontend answers HTTP /predict on whatever version is live —
hot-swapped under traffic with zero dropped requests.

Run: python examples/stream_gbt.py          (CPU or TPU; no downloads)
     python examples/stream_gbt.py --smoke  (CI: bounded events, asserts
     ≥ 2 published versions and that the final registry serves)
"""
import json
import os
import shutil
import sys
import tempfile
import threading
import time
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.io.recordio import encode_records
from dmlc_core_tpu.models import HistGBT
from dmlc_core_tpu.serve import ModelRegistry, ServeFrontend
from dmlc_core_tpu.stream import (ModelPublisher, OnlineTrainer,
                                  RecordIOTailer, encode_dense_events)

N_FEATURES = 8


def make_events(rng, n, drift):
    X = rng.normal(size=(n, N_FEATURES)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + (0.5 + drift) * X[:, 2]
         - drift * X[:, 3] > 0).astype(np.float32)
    return X, y


def post_predict(url, rows):
    body = json.dumps({"rows": np.asarray(rows).tolist()}).encode()
    req = urllib.request.Request(
        url + "/predict", data=body,
        headers={"Content-Type": "application/json"})
    return json.loads(urllib.request.urlopen(req, timeout=30).read())


def main():
    smoke = "--smoke" in sys.argv
    chunk_rows = 768
    n_chunks = 3 if smoke else 4
    total_events = chunk_rows * n_chunks
    rng = np.random.default_rng(7)

    root = tempfile.mkdtemp(prefix="stream_gbt_")
    shard_dir = os.path.join(root, "events")
    os.makedirs(shard_dir)
    appended = [0]

    def writer():
        """Append events in bursts, one shard file per drift phase —
        the tailer picks new shards up as they appear."""
        for phase in range(n_chunks):
            X, y = make_events(rng, chunk_rows, drift=0.2 * phase)
            with open(os.path.join(shard_dir, f"part-{phase:03d}.rec"),
                      "ab") as f:
                for lo in range(0, chunk_rows, 256):
                    f.write(encode_records(
                        encode_dense_events(X[lo:lo + 256],
                                            y[lo:lo + 256])))
                    f.flush()
                    appended[0] += min(256, chunk_rows - lo)
                    time.sleep(0.02)

    Xh, yh = make_events(np.random.default_rng(99), 2048, drift=0.0)
    registry = ModelRegistry(max_batch=256, min_bucket=8)
    publisher = ModelPublisher(
        registry, holdout=(Xh, yh),
        checkpoint_uri=os.path.join(root, "model.ckpt"), name="example")
    model = HistGBT(n_trees=4, max_depth=3, n_bins=16, learning_rate=0.3)
    tailer = RecordIOTailer(shard_dir,
                            cursor_uri=os.path.join(root, "cursor.ckpt"),
                            name="example")
    trainer = OnlineTrainer(model, tailer, n_features=N_FEATURES,
                            chunk_rows=chunk_rows, window_chunks=2,
                            decay=1.0, publisher=publisher, name="example")

    t_writer = threading.Thread(target=writer, daemon=True)
    t_writer.start()

    with ServeFrontend(registry, max_batch=256, max_delay=0.002) as fe:
        print(f"serving on {fe.url}; tailing {shard_dir}")
        probe = Xh[:4]
        t_end = time.time() + 240
        while tailer.records_seen < total_events and time.time() < t_end:
            r = trainer.refresh(timeout=10.0)
            if r is None:
                if not t_writer.is_alive() \
                        and tailer.records_seen >= appended[0]:
                    break
                continue
            line = (f"refresh {r['refresh']}: {r['rows']} fresh rows, "
                    f"{r['trees_total']} trees, v{r['version']} "
                    f"{'activated' if r['activated'] else 'ROLLED BACK'}"
                    f" (holdout score {r['score']:.4f})")
            print(line)
            resp = post_predict(fe.url, probe)
            print(f"  HTTP /predict → v{resp['version']}: "
                  f"{np.round(resp['predictions'], 3)}")
        t_writer.join(timeout=30)

        versions = registry.versions()
        resp = post_predict(fe.url, probe)
        print(f"final: {len(versions)} published versions "
              f"{versions}, serving v{resp['version']}, "
              f"{tailer.records_seen}/{appended[0]} events consumed, "
              f"{publisher.rollbacks} rollbacks")
        if smoke:
            assert len(versions) >= 2, \
                f"smoke: expected >= 2 published versions, got {versions}"
            assert resp["version"] == registry.current_version(), \
                "smoke: frontend serves a version the registry disowns"
            assert len(resp["predictions"]) == len(probe), \
                "smoke: final registry does not serve predictions"
            print("SMOKE OK")
    shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
