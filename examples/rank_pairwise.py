"""Learning-to-rank with hist-GBT: rank:pairwise over qid groups.

Run: python examples/rank_pairwise.py  (CPU or TPU; synthetic queries).

The qid column — carried end-to-end by the data plane (Row/RowBlock,
LibSVM's ``label qid:n idx:val`` syntax) — groups documents into
queries; the objective optimizes pairwise order within each query and
``models.ranking`` scores the result (ndcg / map / pairwise accuracy).
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.models import HistGBT
from dmlc_core_tpu.models.ranking import (mean_average_precision, ndcg,
                                          pairwise_accuracy)


def make_queries(n_queries, seed, F=6):
    """Docs whose true relevance follows a hidden nonlinear score."""
    rng = np.random.default_rng(seed)
    rng_w = np.random.default_rng(42)      # same scorer for train/test
    wtrue = rng_w.normal(size=F)
    Xs, ys, qids = [], [], []
    for q in range(n_queries):
        nd = int(rng.integers(8, 40))
        X = rng.normal(size=(nd, F)).astype(np.float32)
        s = X @ wtrue + 0.5 * X[:, 0] * X[:, 1]
        rel = np.zeros(nd, np.float32)
        top = np.argsort(s)
        rel[top[-3:]] = 1.0
        rel[top[-1]] = 2.0
        Xs.append(X)
        ys.append(rel)
        qids.append(np.full(nd, q, np.int64))
    return np.concatenate(Xs), np.concatenate(ys), np.concatenate(qids)


def main():
    X, y, qid = make_queries(2000, seed=7)
    Xt, yt, qt = make_queries(200, seed=8)

    model = HistGBT(n_trees=120, max_depth=5, n_bins=64,
                    objective="rank:pairwise", learning_rate=0.2)
    model.fit(X, y, qid=qid)

    scores = model.predict(Xt)
    print(f"test ndcg@10           {ndcg(yt, scores, qt, k=10):.4f}")
    print(f"test map@10            "
          f"{mean_average_precision(yt, scores, qt, k=10):.4f}")
    print(f"test pairwise accuracy {pairwise_accuracy(yt, scores, qt):.4f}")
    print(f"(chance pairwise accuracy = 0.5)")


if __name__ == "__main__":
    main()
