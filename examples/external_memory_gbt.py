"""Out-of-core boosting: LibSVM file → sharded parse → disk-paged CSR →
fit_external (the Criteo-scale path, BASELINE config 3).

Run: python examples/external_memory_gbt.py
"""
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dmlc_core_tpu.data import RowBlockIter
from dmlc_core_tpu.models import HistGBT


def main():
    tmp = tempfile.mkdtemp()
    svm = os.path.join(tmp, "train.svm")
    rng = np.random.default_rng(0)
    n, F = 50_000, 16
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] > 0).astype(np.int32)
    with open(svm, "w") as f:
        for i in range(n):
            cols = rng.choice(F, size=F // 2, replace=False)  # sparse rows
            feats = " ".join(f"{j}:{X[i, j]:.4f}" for j in sorted(cols))
            f.write(f"{y[i]} {feats}\n")

    # '#cache' suffix → DiskRowIter: parse once, page through a cache file
    it = RowBlockIter.create(f"{svm}#{tmp}/cache.bin", 0, 1, "libsvm")
    model = HistGBT(n_trees=30, max_depth=5, n_bins=64, learning_rate=0.3)
    # device memory bounded by DMLC_TPU_EXTERNAL_DEVICE_BUDGET: small
    # datasets auto-run the in-core cached engine, big ones stream
    # fixed-shape chunks per level
    model.fit_external(it, num_col=F, eval_every=10)
    print(f"out-of-core trained {len(model.trees)} trees")

    # scoring is streaming too — the dense matrix never exists on the
    # host, for training OR inference (iterating rewinds automatically)
    preds = model.predict_iter(it)
    acc = float(((preds > 0.5) == y).mean())
    print(f"streamed predictions over {len(preds)} rows, train acc {acc:.3f}")
    it.close()


if __name__ == "__main__":
    main()
