#!/usr/bin/env python
"""Benchmark: hist-GBT boosting rounds/sec/chip (BASELINE config 1 proxy).

Runs on whatever jax.devices() provides (the real TPU chip under axon; CPU
elsewhere).  HIGGS-scale synthetic data — BENCH_ROWS×28 dense features,
binary labels — quantile-binned once, then ``BENCH_ROUNDS`` boosting rounds
of depth ``BENCH_DEPTH`` after ``BENCH_WARMUP`` discarded warmup rounds
(compile + cache), per BASELINE.md's measurement plan.

Prints ONE JSON line:
  {"metric": "histgbt_rounds_per_sec_per_chip", "value": N,
   "unit": "rounds/s/chip", "vs_baseline": N, ...}

vs_baseline: the reference publishes no numbers (SURVEY.md §6); the target
is the BASELINE.json north star — XGBoost 2.x hist on one 8×A100 NCCL node
trains HIGGS-10M at roughly 8 rounds/s aggregate (~1 round/s/GPU at depth
6, 256 bins; public xgboost-bench figures), so parity per chip ≈ 1.0
round/s/chip.  vs_baseline = value / 1.0.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402


def main() -> None:
    rows = int(os.environ.get("BENCH_ROWS", 4_000_000))
    feats = int(os.environ.get("BENCH_FEATURES", 28))
    rounds = int(os.environ.get("BENCH_ROUNDS", 100))
    warmup = int(os.environ.get("BENCH_WARMUP", 10))
    depth = int(os.environ.get("BENCH_DEPTH", 6))
    n_bins = int(os.environ.get("BENCH_BINS", 256))

    import threading

    import jax

    from dmlc_core_tpu.models import HistGBT
    from dmlc_core_tpu.parallel.mesh import local_mesh

    # Backend-init watchdog: if the TPU tunnel is wedged, device discovery
    # hangs in C land; fall back to CPU so the bench always emits its JSON
    # line (platform is recorded so a fallback run is visible).
    init_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", 180))
    probe: dict = {}

    def _probe():
        try:
            probe["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001
            probe["error"] = str(e)

    t = threading.Thread(target=_probe, daemon=True)
    t.start()
    t.join(init_timeout)
    if "devices" not in probe:
        print(json.dumps({
            "metric": "histgbt_rounds_per_sec_per_chip",
            "value": 0.0,
            "unit": "rounds/s/chip",
            "vs_baseline": 0.0,
            "error": f"device init did not complete in {init_timeout}s "
                     f"(TPU tunnel wedged?): {probe.get('error', 'timeout')}",
        }), flush=True)
        os._exit(2)

    devices = probe["devices"]
    platform = devices[0].platform

    # HIGGS-like synthetic: dense gaussians + a nonlinear decision rule
    rng = np.random.default_rng(7)
    X = rng.normal(size=(rows, feats)).astype(np.float32)
    margin = X[:, 0] * X[:, 1] + 0.5 * X[:, 2] - 0.8 * X[:, 3] * (X[:, 4] > 0)
    y = (margin > 0).astype(np.float32)

    mesh = local_mesh()  # all local devices on the data axis (1 chip → 1)
    n_chips = mesh.devices.size
    model = HistGBT(
        n_trees=rounds,
        max_depth=depth,
        n_bins=n_bins,
        learning_rate=0.1,
        mesh=mesh,
    )
    try:
        model.fit(X, y, warmup_rounds=warmup)
    except Exception as e:  # noqa: BLE001 — bench must always emit its JSON line
        print(json.dumps({
            "metric": "histgbt_rounds_per_sec_per_chip",
            "value": 0.0,
            "unit": "rounds/s/chip",
            "vs_baseline": 0.0,
            "platform": platform,
            "error": f"{type(e).__name__}: {e}"[:500],
        }), flush=True)
        os._exit(3)
    seconds = model.last_fit_seconds
    rounds_per_sec_per_chip = rounds / seconds / n_chips

    target = 1.0  # rounds/s/chip ≈ per-GPU rate of the 8×A100 NCCL baseline
    print(json.dumps({
        "metric": "histgbt_rounds_per_sec_per_chip",
        "value": round(rounds_per_sec_per_chip, 4),
        "unit": "rounds/s/chip",
        "vs_baseline": round(rounds_per_sec_per_chip / target, 4),
        "rows": rows,
        "features": feats,
        "rounds": rounds,
        "max_depth": depth,
        "n_bins": n_bins,
        "chips": n_chips,
        "platform": platform,
        "seconds": round(seconds, 3),
    }))


if __name__ == "__main__":
    main()
