#!/usr/bin/env python
"""Benchmark: hist-GBT boosting rounds/sec/chip (BASELINE config 1 proxy).

Runs on whatever jax.devices() provides (the real TPU chip under axon; CPU
elsewhere).  HIGGS-scale synthetic data — BENCH_ROWS×28 dense features,
binary labels — quantile-binned once, then ``BENCH_ROUNDS`` boosting rounds
of depth ``BENCH_DEPTH`` after ``BENCH_WARMUP`` discarded warmup rounds
(compile + cache), per BASELINE.md's measurement plan.

Prints ONE JSON line:
  {"metric": "histgbt_rounds_per_sec_per_chip", "value": N,
   "unit": "rounds/s/chip", "vs_baseline": N, ...}

vs_baseline: the reference publishes no numbers (SURVEY.md §6); the target
is the BASELINE.json north star — XGBoost+NCCL on one 8×A100 node at
HIGGS-10M.  Comparator derivation (BASELINE.md "comparator" section for
the full provenance and uncertainty band): public single-GPU
``gpu_hist``/``hist`` HIGGS benchmarks cluster around 10-17 rounds/s at
this config, and public multi-GPU scaling on a 10M-row dataset is poor
(allreduce-bound; dask-xgboost benchmarks show ≤2× aggregate on 8 GPUs),
giving an aggregate ≈ 16-34 rounds/s → **2.0 rounds/s per chip** as the
mid-band per-GPU effective rate.  vs_baseline = value / 2.0.  This
environment has no network and no xgboost wheel, so the comparator is
pinned from cited public figures, not re-measured here.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402


#: bf16 peak of the chips this bench is expected to land on, for the MFU
#: line.  v5e: 197 TFLOP/s bf16 (public spec).  Unknown platforms → 0 →
#: mfu reported as null rather than against a made-up peak.
_PEAK_BF16 = {"tpu": 197e12}


def _derived_metrics(rows, feats, depth, n_bins, seconds_per_round, platform,
                     n_chips=1):
    """Auditable per-round cost model of the sibling-subtracted round.

    MXU flops: per level ℓ the Pallas histogram dot is [A, T]·[T, lo]
    over all rows with A = 2·n_build·ceil(B/lo); sibling subtraction
    makes n_build = 1, 1, 2, 4, ... and ops._lo_factor picks lo.  HBM
    bytes: the bin matrix (uint8) is read once by each level's histogram
    pass and once by each level's descend pass, plus the f32 row vectors
    (g, h, preds, margin update).  psum bytes: the per-level left-child
    histogram [2, n_build, F, B] f32 — what each chip contributes to the
    in-step histogram-sync allreduce (the rabit-allreduce replacement)."""
    from dmlc_core_tpu.ops.histogram import _lo_factor

    rows = rows // n_chips    # per-chip row share: metrics are per chip,
    mxu_flops = 0             # matching rounds_per_sec_per_chip
    psum_bytes = 0
    for level in range(depth):
        n_build = 1 if level == 0 else 1 << (level - 1)
        lo = _lo_factor(n_build, n_bins)
        hi = -(-n_bins // lo)
        mxu_flops += 2 * (2 * n_build * hi) * lo * rows * feats
        psum_bytes += 2 * n_build * feats * n_bins * 4
    hbm = depth * rows * feats * 2        # hist read + descend read, uint8
    hbm += 6 * rows * 4                   # g/h/preds/update f32 vectors
    peak = _PEAK_BF16.get(platform, 0)
    mfu = (mxu_flops / seconds_per_round / peak) if peak else None
    return {
        "mxu_flops_per_round": mxu_flops,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "hbm_bytes_per_round": hbm,
        "hbm_gbps": round(hbm / seconds_per_round / 1e9, 1),
        "hist_psum_bytes_per_round": psum_bytes,
    }


def chunk_stats(chunk_times, total_rounds, total_seconds):
    """Per-chunk rate evidence from (rounds_done, t) arrival timestamps.

    Returns best/median/worst seconds-per-round and the anomaly flag
    (worst/best > 3 — the tunnel-degradation signature that made the
    round-2 official capture 68× wrong with no trace).  Pure so the
    anomaly machinery itself is unit-testable (tests/test_bench_stats)."""
    spr = []
    prev_done, prev_t = 0, 0.0
    for done_i, t_i in chunk_times:
        spr.append((t_i - prev_t) / (done_i - prev_done))
        prev_done, prev_t = done_i, t_i
    # wall fallback only when there is no chunk evidence at all
    spr_sorted = sorted(spr) or [total_seconds / total_rounds]
    med = spr_sorted[len(spr_sorted) // 2]
    return {
        "chunk_seconds_per_round": [round(s, 5) for s in spr],
        "rounds_per_sec_best_chunk": round(1.0 / spr_sorted[0], 4),
        "rounds_per_sec_median_chunk": round(1.0 / med, 4),
        "anomaly": (len(spr) >= 2
                    and spr_sorted[-1] / spr_sorted[0] > 3.0),
    }


def main() -> None:
    # default = the north-star config (BASELINE.md config 1): HIGGS-10M
    rows = int(os.environ.get("BENCH_ROWS", 10_000_000))
    feats = int(os.environ.get("BENCH_FEATURES", 28))
    rounds = int(os.environ.get("BENCH_ROUNDS", 100))
    warmup = int(os.environ.get("BENCH_WARMUP", 10))
    depth = int(os.environ.get("BENCH_DEPTH", 6))
    n_bins = int(os.environ.get("BENCH_BINS", 256))

    import threading

    import jax

    from dmlc_core_tpu.models import HistGBT
    from dmlc_core_tpu.parallel.mesh import local_mesh

    # Backend-init watchdog: if the TPU tunnel is wedged, device discovery
    # hangs in C land; fall back to CPU so the bench always emits its JSON
    # line (platform is recorded so a fallback run is visible).
    init_timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", 180))
    probe: dict = {}

    def _probe():
        try:
            probe["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001
            probe["error"] = str(e)

    t = threading.Thread(target=_probe, daemon=True)
    t.start()
    t.join(init_timeout)
    if "devices" not in probe:
        print(json.dumps({
            "metric": "histgbt_rounds_per_sec_per_chip",
            "value": 0.0,
            "unit": "rounds/s/chip",
            "vs_baseline": 0.0,
            "error": f"device init did not complete in {init_timeout}s "
                     f"(TPU tunnel wedged?): {probe.get('error', 'timeout')}",
        }), flush=True)
        os._exit(2)

    devices = probe["devices"]
    platform = devices[0].platform

    # HIGGS-like synthetic: dense gaussians + a nonlinear decision rule
    rng = np.random.default_rng(7)
    X = rng.normal(size=(rows, feats)).astype(np.float32)
    margin = X[:, 0] * X[:, 1] + 0.5 * X[:, 2] - 0.8 * X[:, 3] * (X[:, 4] > 0)
    y = (margin > 0).astype(np.float32)

    mesh = local_mesh()  # all local devices on the data axis (1 chip → 1)
    n_chips = mesh.devices.size
    model = HistGBT(
        n_trees=rounds,
        max_depth=depth,
        n_bins=n_bins,
        learning_rate=0.1,
        mesh=mesh,
    )
    def _run_once(warmup_rounds):
        """One timed fit; returns an evidence dict with per-chunk rates.

        ``model.last_chunk_times`` holds in-order (rounds_done, t) arrival
        timestamps of each chunk's tree fetch (rides the fetch loop that
        already existed, so recording adds no device traffic).  Per-chunk
        sec/round is the auditable unit: on a healthy chip all chunks run
        at the same rate; a degraded tunnel (the round-2 BENCH capture
        was 68× off) shows up as a worst/best chunk ratio ≫ 1."""
        model.fit(X, y, warmup_rounds=warmup_rounds)
        seconds = model.last_fit_seconds
        out = {
            "seconds": round(seconds, 3),
            "warmup_seconds": round(model.last_warmup_seconds, 3),
        }
        out.update(chunk_stats(model.last_chunk_times, rounds, seconds))
        return out

    try:
        runs = [_run_once(warmup)]
        if runs[0]["anomaly"]:
            # tunnel-degradation signature: one dispatch orders of
            # magnitude slower than its siblings.  Re-measure once and
            # report the better run as official, keeping both as
            # evidence.  The rerun is a continued fit: the jit cache is
            # reused but the matrix is re-uploaded and re-binned and the
            # prior trees replayed for init margins (untimed setup).  If
            # the rerun itself dies (likely on the very tunnel just
            # diagnosed as degraded), fall back to run 1's valid data.
            print("bench: chunk-rate anomaly detected, re-measuring once",
                  file=sys.stderr, flush=True)
            try:
                runs.append(_run_once(1))
            except Exception as e:  # noqa: BLE001
                print(f"bench: re-measure failed ({type(e).__name__}: "
                      f"{e}), keeping first run", file=sys.stderr, flush=True)
    except Exception as e:  # noqa: BLE001 — bench must always emit its JSON line
        print(json.dumps({
            "metric": "histgbt_rounds_per_sec_per_chip",
            "value": 0.0,
            "unit": "rounds/s/chip",
            "vs_baseline": 0.0,
            "platform": platform,
            "error": f"{type(e).__name__}: {e}"[:500],
        }), flush=True)
        os._exit(3)
    official = max(runs, key=lambda r: rounds / r["seconds"])
    seconds = official["seconds"]
    rounds_per_sec_per_chip = rounds / seconds / n_chips

    # per-GPU effective rate of the 8×A100 NCCL baseline (mid-band of the
    # 2-4 rounds/s/chip band; see module docstring + BASELINE.md
    # comparator section for provenance and uncertainty)
    target = 2.0
    out = {
        "metric": "histgbt_rounds_per_sec_per_chip",
        "value": round(rounds_per_sec_per_chip, 4),
        "unit": "rounds/s/chip",
        "vs_baseline": round(rounds_per_sec_per_chip / target, 4),
        "vs_baseline_band": [round(rounds_per_sec_per_chip / 4.0, 4),
                             round(rounds_per_sec_per_chip / 2.0, 4)],
        "rows": rows,
        "features": feats,
        "rounds": rounds,
        "max_depth": depth,
        "n_bins": n_bins,
        "chips": n_chips,
        "platform": platform,
        "seconds": seconds,
        "warmup_seconds": official["warmup_seconds"],
        "rounds_per_sec_best_chunk": official["rounds_per_sec_best_chunk"],
        "rounds_per_sec_median_chunk":
            official["rounds_per_sec_median_chunk"],
        "anomaly": official["anomaly"],
        "runs": runs,
    }
    out.update(_derived_metrics(rows, feats, depth, n_bins,
                                seconds / rounds, platform, n_chips))
    print(json.dumps(out))


if __name__ == "__main__":
    main()
