#!/usr/bin/env python
"""Benchmark: hist-GBT boosting rounds/sec/chip (BASELINE config 1 proxy).

Runs on whatever jax.devices() provides (the real TPU chip under axon; CPU
elsewhere).  HIGGS-scale synthetic data — BENCH_ROWS×28 dense features,
binary labels — quantile-binned once, then ``BENCH_ROUNDS`` boosting rounds
of depth ``BENCH_DEPTH`` after ``BENCH_WARMUP`` discarded warmup rounds
(compile + cache), per BASELINE.md's measurement plan.

Multi-chip mode (ISSUE 7): ``BENCH_CHIPS=N`` pins the data-mesh width
(default: every local device).  Rows stage through the sharded per-chip
ingest, the per-level histogram psum is the only cross-chip traffic
(``psum_probe`` measures its bytes/latency standalone), and when the
budget allows, a 1-chip re-measure on the same rows+cuts yields
``scaling.scaling_efficiency`` = per-chip rate at N chips / 1-chip rate
(``BENCH_SCALING=0`` skips).  The headline metric stays per-chip.

Output protocol (driver parses the LAST stdout line as JSON): this script
emits a *provisional* JSON line at every phase transition and at every
timed-chunk arrival, then one final line.  Whatever kills the process —
driver timeout (SIGTERM), our own wall-clock budget, SIGKILL — the last
line on stdout is always a valid record carrying the evidence gathered so
far.  Two earlier rounds lost their official capture to exactly this
failure mode (r02: tunnel-degraded number with no trace; r03: rc=124 with
empty stdout), so survivability is part of the bench's spec, not polish.

Robustness machinery:
  * ``BENCH_TIME_BUDGET`` (s, default 480): an internal deadline enforced
    by a watchdog *thread* (signal handlers can't run while the main
    thread is blocked inside a C-land device fetch; a thread can).  On
    expiry the evidence-so-far is flushed as the final line and the
    process exits 0.
  * SIGTERM/SIGINT handlers flush the same way (the driver's `timeout`
    sends SIGTERM first).
  * Config fallback: if the remaining budget can't fit the requested
    rows (datagen + H2D at the measured 12 MB/s tunnel floor + compile +
    timed fit), rows fall back 10M→4M→2M→1M→250k and the JSON says so
    (``fallback_from``); if rows bottom out, the round count shrinks to
    the leftover fit window.  ``BENCH_NO_FALLBACK=1`` pins the requested
    config regardless (self-tests, or a driver that wants exactly one
    config and accepts watchdog truncation).
  * The anomaly re-measure (tunnel-degradation signature: worst/best
    chunk ratio > 3) reuses the device-resident binned matrix via
    ``HistGBT.fit_device`` — zero re-upload — and is skipped entirely
    when the budget can't fit it.
  * Official-run selection prefers the NON-anomalous run; if every run
    is anomalous the median-chunk rate is reported (``value_basis`` says
    which), never a corrupted wall number and never best-of-2.

vs_baseline: the reference publishes no numbers (SURVEY.md §6); the target
is the BASELINE.json north star — XGBoost+NCCL on one 8×A100 node at
HIGGS-10M.  Comparator derivation (BASELINE.md "comparator" section for
the full provenance and uncertainty band): public single-GPU
``gpu_hist``/``hist`` HIGGS benchmarks cluster around 10-17 rounds/s at
this config, and public multi-GPU scaling on a 10M-row dataset is poor
(allreduce-bound; dask-xgboost benchmarks show ≤2× aggregate on 8 GPUs),
giving an aggregate ≈ 16-34 rounds/s → **2.0 rounds/s per chip** as the
mid-band per-GPU effective rate.  vs_baseline = value / 2.0.  This
environment has no network and no xgboost wheel, so the comparator is
pinned from cited public figures, not re-measured here.

Extra smoke fields (BASELINE configs 2/4, budget-gated, null on skip):
``infeed_stall_frac`` — DeviceFeed double-buffered infeed stall fraction
on a small synthetic stream; ``kvstore_sync_ms`` — KVStore dist_sync
fused push+pull per step on a small BERT-shaped key set.  Each is an
OBJECT ``{value, basis, full_scale, full_scale_source}``: the smoke
value is a tunnel-dominated probe and must not be scored against the
BASELINE targets — the embedded ``full_scale`` carries the measured
full-scale number the claim rests on.  Full-scale versions live in
scripts/bench_kvstore.py / tests/test_resnet_feed.py.
"""

import json
import os
import signal
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np  # noqa: E402

_COMPARATOR = 2.0          # rounds/s/chip, BASELINE.md mid-band
_TUNNEL_MBPS = 12e6        # measured H2D floor through the axon tunnel
# RLock: a SIGTERM handler runs ON the main thread and re-enters emit()
# if the signal lands mid-print; a plain Lock would self-deadlock there
_EMIT_LOCK = threading.RLock()

#: bf16 peak of the chips this bench is expected to land on, for the MFU
#: line.  v5e: 197 TFLOP/s bf16 (public spec).  Unknown platforms → 0 →
#: mfu reported as null rather than against a made-up peak.
_PEAK_BF16 = {"tpu": 197e12}

#: single shared evidence store; emit() renders it as one JSON line.
#: Written only by the main thread; read by the watchdog thread and
#: signal handlers.  Cross-thread safety contract: container VALUES are
#: only ever REBOUND wholesale (never mutated in place, except list
#: .append which cannot raise mid-iteration in CPython) — a concurrent
#: emit() therefore never sees a dict change size under iteration.
EV = {
    "phase": "start",
    "t0": None,              # process start (time.time())
    "config": {},            # rows/feats/rounds/... once chosen
    "platform": None,
    "chunk_times": [],       # (rounds_done, elapsed_s) of the LIVE run
    "runs": [],              # completed run evidence dicts
    "official": None,        # final selection
    "value_basis": None,
    "fallback_from": None,
    "smoke": {},
    "notes": [],
}


def _elapsed():
    return time.time() - EV["t0"] if EV["t0"] else 0.0


def _live_estimate():
    """Best per-CHIP rate estimate from the in-flight run's chunk
    arrivals (the metric is per chip: divide the mesh rate out, exactly
    as the official paths do)."""
    ct = EV["chunk_times"]
    if not ct:
        return None
    done, t = ct[-1]
    if t <= 0:
        return None
    return done / t / EV["config"].get("chips", 1)


def _metrics_out_path():
    """--metrics-out PATH / --metrics-out=PATH / BENCH_METRICS_OUT env —
    where to archive the full metrics snapshot (None = don't)."""
    for i, a in enumerate(sys.argv):
        if a == "--metrics-out" and i + 1 < len(sys.argv):
            return sys.argv[i + 1]
        if a.startswith("--metrics-out="):
            return a.split("=", 1)[1]
    return os.environ.get("BENCH_METRICS_OUT")


def _slo_path():
    """--slo PATH / --slo=PATH / DMLC_SLO_SPEC env — committed SLO spec
    to score the final record against (None = skip)."""
    for i, a in enumerate(sys.argv):
        if a == "--slo" and i + 1 < len(sys.argv):
            return sys.argv[i + 1]
        if a.startswith("--slo="):
            return a.split("=", 1)[1]
    return os.environ.get("DMLC_SLO_SPEC") or None


def _attach_metrics(out):
    """Final-record metrics: archive the full registry snapshot when
    --metrics-out/BENCH_METRICS_OUT names a path, and inline a compact
    phase breakdown (the BENCH_* artifact now says where the time went,
    not only how much there was).  Never fatal — the headline record
    must survive a metrics failure."""
    try:
        from dmlc_core_tpu.base.metrics import default_registry

        reg = default_registry()
        path = _metrics_out_path()
        if path:
            out["metrics_path"] = reg.save_json(path)
        snap = reg.snapshot()["metrics"]
        summary = {}
        ph = snap.get("dmlc_gbt_phase_seconds")
        if ph:
            for se in ph["series"]:
                lab = se["labels"]
                key = f"{lab['engine']}_{lab['phase']}"
                summary[f"{key}_p50_s"] = se["quantiles"]["p50"]
                summary[f"{key}_count"] = se["count"]
        for name, field in (("dmlc_gbt_rounds_total", "rounds_total"),
                            ("dmlc_collective_bytes_total",
                             "collective_bytes_total"),
                            ("dmlc_histogram_psum_bytes_total",
                             "histogram_psum_bytes_total")):
            m = snap.get(name)
            if m and m["series"]:
                summary[field] = sum(s["value"] for s in m["series"])
        # resilience evidence rides every final record (zeros included):
        # a perf run that silently degraded into a retry storm — or a
        # chaos run that injected nothing — must be visible in the
        # artifact, not only in a live scrape
        for name, field in (("dmlc_retries_total", "retries_total"),
                            ("dmlc_faults_injected_total",
                             "faults_injected")):
            m = snap.get(name)
            summary[field] = (sum(s["value"] for s in m["series"])
                              if m and m["series"] else 0.0)
        # fleet-wide view: when this process spools (DMLC_METRICS_SPOOL),
        # say how many processes the merged snapshot covers — a fleet
        # bench whose children never spooled reads 1, not silence
        from dmlc_core_tpu.base import metrics_agg
        sw = metrics_agg.installed_spool()
        if sw is not None:
            sw.flush()
            _, nprocs = metrics_agg.merge_spool(os.path.dirname(sw.path))
            summary["spool_processes_merged"] = nprocs
        # under DMLC_JITCHECK=1 the record carries the steady-state
        # compile count across every steady window this process opened
        # (0 = the PR 18 warmup fix holds under the dynamic gate)
        from dmlc_core_tpu.base import jitcheck
        if jitcheck.installed():
            summary["recompiles_steady_state"] = len(
                jitcheck.compiles("steady"))
        out["metrics_summary"] = summary
    except Exception as e:  # noqa: BLE001
        out["metrics_error"] = f"{type(e).__name__}: {e}"[:200]


def _attach_slo(out):
    """Score the final record against a committed SLO spec (--slo PATH /
    DMLC_SLO_SPEC).  The snapshot is the fleet-merged spool view when a
    spool is installed, else this process's registry; the record itself
    is the evidence dict, so objectives can reference headline fields
    (``{"evidence": "dropped"}``).  Never fatal — the headline record
    must survive a scorecard failure."""
    path = _slo_path()
    if not path:
        return
    try:
        from dmlc_core_tpu.base import metrics_agg, slo
        from dmlc_core_tpu.base.metrics import default_registry

        sw = metrics_agg.installed_spool()
        if sw is not None:
            sw.flush()
            snapshot, _ = metrics_agg.merge_spool(os.path.dirname(sw.path))
        else:
            snapshot = default_registry().snapshot()
        out["slo"] = slo.evaluate(slo.SLOSpec.load(path), snapshot,
                                  evidence=out)
    except Exception as e:  # noqa: BLE001
        out["slo_error"] = f"{type(e).__name__}: {e}"[:200]


def emit(final=False, **extra):
    """Print one JSON evidence line (the driver reads the LAST line)."""
    cfg = EV["config"]
    value = 0.0
    basis = None
    if EV["official"] is not None:
        value = EV["official"]["value"]
        basis = EV["value_basis"]
    else:
        live = _live_estimate()
        if live is not None:
            value = live
            basis = "wall_so_far"
    out = {
        "metric": "histgbt_rounds_per_sec_per_chip",
        "value": round(value, 4),
        "unit": "rounds/s/chip",
        "vs_baseline": round(value / _COMPARATOR, 4),
        "provisional": not final,
        "phase": EV["phase"],
        "elapsed_s": round(_elapsed(), 1),
        "platform": EV["platform"],
    }
    if basis:
        out["value_basis"] = basis
    out.update(cfg)
    if EV["fallback_from"]:
        out["fallback_from"] = EV["fallback_from"]
    if EV["chunk_times"] and EV["official"] is None:
        out["chunks_so_far"] = [[d, round(t, 3)] for d, t in
                                EV["chunk_times"]]
    if EV["official"] is not None:
        out.update(EV["official"])
        out["value"] = round(value, 4)          # official dict also has it
        out["vs_baseline"] = round(value / _COMPARATOR, 4)
        out["vs_baseline_band"] = [round(value / 4.0, 4),
                                   round(value / 2.0, 4)]
        out["runs"] = EV["runs"]
    for k, v in EV["smoke"].items():
        out[k] = v
    if EV["notes"]:
        out["notes"] = EV["notes"]
    if final:
        _attach_metrics(out)
        _attach_slo(out)
    out.update(extra)
    with _EMIT_LOCK:
        sys.stdout.write(json.dumps(out) + "\n")
        sys.stdout.flush()


def _flush_and_exit(reason):
    try:
        emit(final=True, terminated=reason)
    except Exception as e:  # noqa: BLE001 — the record must still exist
        with _EMIT_LOCK:
            sys.stdout.write(json.dumps({
                "metric": "histgbt_rounds_per_sec_per_chip",
                "value": 0.0, "unit": "rounds/s/chip", "vs_baseline": 0.0,
                "terminated": reason, "provisional": False,
                "emit_error": f"{type(e).__name__}: {e}"[:200]}) + "\n")
            sys.stdout.flush()
    os._exit(0)


def _install_guards(deadline):
    """SIGTERM/SIGINT flush + watchdog thread enforcing the deadline.

    The watchdog is a thread, not SIGALRM: a Python signal handler only
    runs between bytecodes on the main thread, and the main thread spends
    minutes at a time blocked inside C-land device fetches through the
    tunnel — exactly when the budget is most likely to expire."""
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda s, f: _flush_and_exit(
            signal.Signals(s).name))

    def watch():
        while True:
            left = deadline - time.time()
            if left <= 0:
                _flush_and_exit("budget_exhausted")
            time.sleep(min(5.0, max(0.5, left)))

    threading.Thread(target=watch, daemon=True).start()


def _derived_metrics(rows, feats, depth, n_bins, seconds_per_round, platform,
                     n_chips=1, layout=None, grow_policy="depthwise",
                     max_leaves=0, fused=False, quant=False):
    """Auditable per-round cost model of the sibling-subtracted round.

    MXU flops: per level ℓ the Pallas histogram dot is [A, T]·[T, lo]
    over all rows with A = 2·n_build·ceil(B/lo); sibling subtraction
    makes n_build = 1, 1, 2, 4, ... and ops._lo_factor picks lo.  HBM
    bytes: the bin matrix is read once by each level's histogram
    pass and once by each level's descend pass — at the PHYSICAL row
    width, so an int4-packed/bundled :class:`BinLayout` shrinks the bill
    — plus the f32 row vectors (g, h, preds, margin update).  psum
    bytes: the per-level left-child histogram [2, n_build, S, Bs] f32 —
    what each chip contributes to the in-step histogram-sync allreduce
    (the rabit-allreduce replacement).  The ``kernel`` block is the
    ISSUE 12 lever evidence: bin-matrix bytes one round's passes pull
    from HBM, and how many node histograms the round actually builds
    (loss-guide builds ``max_leaves`` instead of ``2^(depth-1)``).
    ``fused``/``quant`` are the ISSUE 18 levers: the fused round kernel
    halves the bin-matrix passes (descend rides the histogram read) and
    the int8 sync shrinks each synced node ~4×."""
    from dmlc_core_tpu.ops.histogram import (_lo_factor,
                                             bins_bytes_per_round,
                                             hist_psum_bytes_per_round,
                                             leaves_built_per_round)

    rows = rows // n_chips    # per-chip row share: metrics are per chip,
    mxu_flops = 0             # matching rounds_per_sec_per_chip
    # shared analytic traffic model (ops.histogram): also feeds the live
    # dmlc_histogram_psum_bytes_total counter the engine increments
    psum_bytes = hist_psum_bytes_per_round(
        depth, feats, n_bins, layout=layout, grow_policy=grow_policy,
        max_leaves=max_leaves, quant=quant)
    sync_bins = layout.sync_bins if layout is not None else n_bins
    for level in range(depth):
        n_build = 1 if level == 0 else 1 << (level - 1)
        lo = _lo_factor(n_build, sync_bins)
        hi = -(-sync_bins // lo)
        mxu_flops += 2 * (2 * n_build * hi) * lo * rows * feats
    # bin-matrix bytes per data row: F uint8 rows plain, fewer physical
    # rows when the layout packs int4 pairs / fuses bundles
    row_bytes = (layout.phys_bytes_per_row() if layout is not None
                 else feats)
    leaves_built = leaves_built_per_round(depth, grow_policy, max_leaves)
    bins_bytes = bins_bytes_per_round(
        depth, rows, row_bytes, grow_policy=grow_policy,
        max_leaves=max_leaves, fused=fused)
    hbm = bins_bytes + 6 * rows * 4       # + g/h/preds/update f32 vectors
    peak = _PEAK_BF16.get(platform, 0)
    mfu = (mxu_flops / seconds_per_round / peak) if peak else None
    return {
        "mxu_flops_per_round": mxu_flops,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "hbm_bytes_per_round": hbm,
        "hbm_gbps": round(hbm / seconds_per_round / 1e9, 1),
        "hist_psum_bytes_per_round": psum_bytes,
        "kernel": {
            "bins_bytes_per_round": bins_bytes,
            "bin_bytes_per_data_row": row_bytes,
            "leaves_built_per_round": leaves_built,
            "grow_policy": grow_policy,
            "bin_layout": (None if layout is None else
                           f"{layout.n_features}F->{layout.phys_rows}rows"
                           f"/{len(layout.pairs)}pairs"),
            "fused_round": fused,
            "hist_quant": quant,
        },
    }


def _fused_round_engaged(platform, n_chips, layout, feats, depth, n_bins):
    """Whether the DMLC_FUSED_ROUND lever actually engages for this
    bench config — mirrors the eligibility gate in
    models.histgbt._build_round_fn so the ``kernel`` evidence block
    reports what the round program really dispatched."""
    mode = os.environ.get("DMLC_FUSED_ROUND", "auto")
    if (mode == "0" or n_chips > 1
            or int(os.environ.get("DMLC_HIST_BLOCKS", "0") or 0)):
        return False
    if mode == "1":
        return True
    if platform != "tpu":
        return False
    from dmlc_core_tpu.ops.histogram import fused_round_ok

    sync_bins = layout.sync_bins if layout is not None else n_bins
    phys = layout.phys_rows if layout is not None else feats
    return fused_round_ok(sync_bins, phys,
                          max(1 << max(depth - 2, 0), 1),
                          with_layout=layout is not None)


def chunk_stats(chunk_times, total_rounds, total_seconds):
    """Per-chunk rate evidence from (rounds_done, t) arrival timestamps.

    Returns best/median/worst seconds-per-round and the anomaly flag
    (worst/best > 3 AND worst > 50 ms/round — a tunnel stall is a
    dispatch sitting for hundreds of ms to minutes, the signature that
    made the round-2 official capture 68× wrong with no trace; the
    absolute floor stops a near-zero timer delta on a fast local fit
    from flagging its sibling chunks as "slow").  Deltas are also
    clamped to 1 µs so a coarse timer can never divide-by-zero.  Pure
    so the anomaly machinery itself is unit-testable
    (tests/test_bench_stats)."""
    eps = 1e-6
    spr = []
    prev_done, prev_t = 0, 0.0
    for done_i, t_i in chunk_times:
        spr.append(max(t_i - prev_t, eps) / (done_i - prev_done))
        prev_done, prev_t = done_i, t_i
    # wall fallback only when there is no chunk evidence at all
    spr_sorted = sorted(spr) or [total_seconds / total_rounds]
    med = spr_sorted[len(spr_sorted) // 2]
    return {
        "chunk_seconds_per_round": [round(s, 5) for s in spr],
        "rounds_per_sec_best_chunk": round(1.0 / spr_sorted[0], 4),
        "rounds_per_sec_median_chunk": round(1.0 / med, 4),
        "anomaly": (len(spr) >= 2
                    and spr_sorted[-1] / spr_sorted[0] > 3.0
                    and spr_sorted[-1] > 0.05),
    }


def scaling_summary(n_chips, per_chip_rate, baseline_rate):
    """Multi-chip scaling evidence vs the 1-chip oracle run.

    ``scaling_efficiency`` = per-chip rate at N chips / 1-chip rate
    (1.0 = perfect linear scaling; the ISSUE 7 acceptance bar is 0.7 at
    the 10M x 28 config).  Pure so the math is unit-testable
    (tests/test_bench_stats) independent of the measurement harness."""
    if not baseline_rate or baseline_rate <= 0 or n_chips < 1:
        return None
    return {
        "chips": n_chips,
        "baseline_chips": 1,
        "baseline_rounds_per_sec_per_chip": round(baseline_rate, 4),
        "aggregate_rounds_per_sec": round(per_chip_rate * n_chips, 4),
        "scaling_efficiency": round(per_chip_rate / baseline_rate, 4),
    }


def _psum_probe(mesh, depth, feats, n_bins, reps=3):
    """Measured latency of one round's histogram-sync allreduce: a
    standalone device_allreduce of the per-round psum payload (the
    [2, n_build, F, B] per-level histograms, flattened) over the bench
    mesh.  An upper-bound probe — inside the real round program XLA
    overlaps the per-level psums with compute — but it pins the
    bytes/latency scale of the only cross-chip traffic the multi-chip
    flagship pays."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dmlc_core_tpu.ops.histogram import hist_psum_bytes_per_round
    from dmlc_core_tpu.parallel.collectives import device_allreduce

    nbytes = hist_psum_bytes_per_round(depth, feats, n_bins)
    W = mesh.devices.size
    x = jax.device_put(
        np.ones((W, nbytes // 4), np.float32),
        NamedSharding(mesh, P("data")))
    out = device_allreduce(x, mesh)            # warm the program
    np.asarray(out[:1])
    t0 = time.perf_counter()
    for _ in range(reps):
        out = device_allreduce(x, mesh)
    np.asarray(out[:1])                        # real fetch: sync
    ms = (time.perf_counter() - t0) / reps * 1e3
    return {
        "bytes_per_round": nbytes,
        "allreduce_ms": round(ms, 3),
        "effective_gbps": round(nbytes / (ms / 1e3) / 1e9, 2)
        if ms > 0 else None,
    }


def _scaling_probe() -> None:
    """``--scaling-probe``: subprocess body for the 1-chip host's N-chip
    scaling evidence.  Forces an 8-virtual-device CPU backend (own
    process — the forced backend must never contaminate the parent's
    live TPU client), fits the same synthetic task on the 8-way mesh
    and on 1 device with shared cuts, and prints ONE json line with the
    :func:`scaling_summary`.  The embedded ``basis`` keeps the number
    honest: this measures the round program's mesh fold + histogram-
    psum overhead on the XLA CPU backend at reduced rows, NOT TPU ICI
    bandwidth — it is the first published ``scaling_efficiency`` until
    a multi-chip slice runs the real thing."""
    from dmlc_core_tpu.utils import force_cpu_devices
    force_cpu_devices(8)

    from dmlc_core_tpu.base import compile_cache as _cc
    from dmlc_core_tpu.models import HistGBT
    from dmlc_core_tpu.ops.histogram import hist_psum_bytes_per_round
    from dmlc_core_tpu.parallel.mesh import local_mesh

    # the parent passes its DMLC_COMPILE_CACHE_DIR through the
    # environment — configure it here too, or the probe re-pays every
    # round-program compile the main run already cached (the r06
    # scaling_efficiency=0.1258 was mostly that compile wall)
    _cc.configure()

    # BENCH_PROBE_ROWS is pinned by the parent to the MAIN run's row
    # count, so baseline and probe rates are at comparable arithmetic
    # intensity; the 160k default only covers a bare --scaling-probe
    rows = int(os.environ.get("BENCH_PROBE_ROWS", 160_000))
    feats = int(os.environ.get("BENCH_FEATURES", 28))
    rounds = int(os.environ.get("BENCH_PROBE_ROUNDS", 10))
    depth = int(os.environ.get("BENCH_DEPTH", 6))
    n_bins = int(os.environ.get("BENCH_BINS", 256))

    rng = np.random.default_rng(7)
    X = rng.normal(size=(rows, feats)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 0.5 * X[:, 2] > 0).astype(np.float32)
    cuts = _host_cuts(X, n_bins)
    layout = {}

    def per_chip_rate(width):
        m = HistGBT(n_trees=rounds, max_depth=depth, n_bins=n_bins,
                    learning_rate=0.1, mesh=local_mesh(width))
        dd = m.make_device_data(X, y, cuts=cuts)
        m.fit_device(dd, warmup_rounds=1)
        layout[width] = m._bin_layout
        return rounds / m.last_fit_seconds / width

    r8 = per_chip_rate(8)
    out = scaling_summary(8, r8, per_chip_rate(1)) or {}
    # the byte bill behind the efficiency number: what each chip
    # contributes to the per-round histogram-sync allreduce
    out["hist_psum_bytes_per_round"] = hist_psum_bytes_per_round(
        depth, feats, n_bins, layout=layout.get(8),
        grow_policy=os.environ.get("DMLC_GROW_POLICY", "depthwise"),
        max_leaves=int(os.environ.get("DMLC_MAX_LEAVES", "0") or 0),
        quant=os.environ.get("DMLC_HIST_QUANT", "0") == "1")
    out["basis"] = (
        f"virtual-8-device CPU probe at rows={rows} (host exposes 1 "
        "chip), warm persistent compile cache: measures the round "
        "program's mesh fold + histogram-psum overhead on the XLA CPU "
        "backend, not TPU ICI bandwidth")
    with _EMIT_LOCK:
        sys.stdout.write(json.dumps(out) + "\n")
        sys.stdout.flush()


def _setup_estimate(rows, feats, rounds):
    """Pessimistic seconds to reach the end of the timed fit: datagen +
    host cuts/binning on one core + the uint8 H2D at the measured tunnel
    FLOOR (bandwidth swings 5-17 MB/s between runs — r4 measured the
    same 200 MB at 5.4 and 11.1 MB/s minutes apart) + compile/warmup +
    the fit itself at the measured per-row rate (8 r/s at 10M)."""
    bytes_up = rows * feats + rows * 8          # uint8 bins + y/mask f32
    datagen = rows * feats * 4 / 60e6
    host_prep = rows * feats * 4 / 40e6         # cuts + searchsorted bin
    upload = bytes_up / _TUNNEL_MBPS
    compile_warm = 75.0
    spr = max(rows * 1.25e-8, 0.005)
    return datagen + host_prep + upload + compile_warm + rounds * spr


def _host_cuts(X, n_bins, sample=2_000_000):
    """Sampled per-feature quantile cuts on the HOST (4 s at 10M×28).

    The r3 bench computed cuts on device, which shipped the f32 matrix
    through the tunnel TWICE (once for the quantile sort, once to bin) —
    439 s of a 497 s run on a slow-tunnel day (r4 instrumented
    breakdown).  Together with DMLC_TPU_BIN_BACKEND=cpu the setup now
    uploads only the uint8 bin matrix: 8× fewer bytes."""
    step = max(1, len(X) // sample)
    qs = np.linspace(0.0, 1.0, n_bins + 1)[1:-1]
    return np.ascontiguousarray(
        np.quantile(X[::step], qs, axis=0).T.astype(np.float32))


def _pick_config(budget_left):
    """Choose rows/rounds that fit the remaining budget (with margin for
    the final fetch + smoke lines), falling back from the requested
    config and recording the decision."""
    rows = int(os.environ.get("BENCH_ROWS", 10_000_000))
    feats = int(os.environ.get("BENCH_FEATURES", 28))
    rounds = int(os.environ.get("BENCH_ROUNDS", 100))
    requested = rows
    if os.environ.get("BENCH_NO_FALLBACK"):
        return rows, feats, rounds
    chain = [requested] + [c for c in (4_000_000, 2_000_000, 1_000_000,
                                       250_000) if c < requested]
    for cand in chain:
        if _setup_estimate(cand, feats, rounds) <= budget_left - 60:
            rows = cand
            break
    else:
        rows = chain[-1]
    if rows != requested:
        EV["fallback_from"] = requested
        EV["notes"].append(
            f"budget {budget_left:.0f}s left cannot fit rows={requested} "
            f"(est {_setup_estimate(requested, feats, rounds):.0f}s); "
            f"fell back to rows={rows}")
    if _setup_estimate(rows, feats, rounds) > budget_left - 60:
        # rows have bottomed out and it STILL doesn't fit: shrink the
        # round count to what the leftover fit window can hold
        setup_only = _setup_estimate(rows, feats, 0)
        spr = max(rows * 1.25e-8, 0.005)
        fit_window = budget_left - 60 - setup_only
        new_rounds = max(25, int(fit_window / spr)) if fit_window > 0 else 25
        if new_rounds < rounds:
            EV["notes"].append(
                f"rounds fallback {rounds}->{new_rounds}: setup alone "
                f"needs ~{setup_only:.0f}s of the {budget_left:.0f}s left")
            rounds = new_rounds
    return rows, feats, rounds


def _smoke_infeed(mesh):
    """BASELINE config-2 smoke: DeviceFeed stall fraction on a small
    synthetic stream with a jitted consumer (full-scale:
    tests/test_resnet_feed.py / examples/resnet_recordio.py)."""
    import jax
    import jax.numpy as jnp

    from dmlc_core_tpu.data.device_feed import DeviceFeed

    rng = np.random.default_rng(1)
    n_batches, B, D = 24, 2048, 128

    def host_iter():
        for _ in range(n_batches):
            yield (rng.normal(size=(B, D)).astype(np.float32),)

    w = jnp.asarray(rng.normal(size=(D, D)).astype(np.float32))
    step = jax.jit(lambda x, w: jnp.sum(jnp.tanh(x @ w)))
    out = None
    with DeviceFeed(host_iter, mesh, depth=2) as feed:
        for (x,) in feed:
            out = step(x, w)
        np.asarray(out)          # real fetch: proves the pipe drained
        return round(feed.stats.stall_fraction(), 4)


def _smoke_kvstore(mesh):
    """BASELINE config-4 smoke: fused dist_sync push+pull ms/step on a
    small BERT-shaped key set (full-scale: scripts/bench_kvstore.py —
    the collective COUNT contrast needs the 8-way mesh; this field
    records the fused sync path's per-step cost on the bench device).
    Median of 3 timed repeats (VERDICT r5 weak #3: single-shot swung
    ~4x between rounds on unchanged code; the median carries the
    signal)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dmlc_core_tpu.parallel.kvstore import KVStore

    W = mesh.devices.size
    hidden = 128
    shapes = [("embed", (4000, hidden))]
    for i in range(12):
        shapes += [(f"l{i}.w1", (hidden, 4 * hidden)),
                   (f"l{i}.w2", (4 * hidden, hidden)),
                   (f"l{i}.b", (hidden,))]
    rng = np.random.default_rng(2)
    sh = NamedSharding(mesh, P("data"))
    grads = {k: jax.device_put(
        rng.normal(size=(W, *s)).astype(np.float32) / W, sh)
        for k, s in shapes}
    kv = KVStore.create("dist_sync", mesh=mesh, learning_rate=0.01)
    keys = [k for k, _ in shapes]
    kv.init(keys, [np.zeros(s, np.float32) for _, s in shapes])
    kv.push(keys, [grads[k] for k in keys])    # warm the jit caches
    out = kv.pull(keys)
    np.asarray(out[0][:1])
    steps = 3
    reps = []
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(steps):
            kv.push(keys, [grads[k] for k in keys])
            out = kv.pull(keys)
        np.asarray(out[0][:1])                 # tunnel-proof sync
        reps.append((time.perf_counter() - t0) / steps * 1e3)
    return round(sorted(reps)[1], 2)


def latency_summary(lats_s):
    """p50/p95/p99/mean (ms) of a latency sample list — pure, so the
    serve-bench percentile math is unit-testable (tests/test_serve.py)."""
    if not lats_s:
        return {"latency_p50_ms": None, "latency_p95_ms": None,
                "latency_p99_ms": None, "latency_mean_ms": None}
    s = sorted(lats_s)

    def q(p):
        return s[min(len(s) - 1, max(0, int(round(p * (len(s) - 1)))))]

    return {
        "latency_p50_ms": round(q(0.50) * 1e3, 3),
        "latency_p95_ms": round(q(0.95) * 1e3, 3),
        "latency_p99_ms": round(q(0.99) * 1e3, 3),
        "latency_mean_ms": round(sum(s) / len(s) * 1e3, 3),
    }


def _serve_emit(rec, final=False):
    rec = {"metric": "serve_requests_per_sec", "unit": "req/s",
           "provisional": not final, **rec}
    if final:
        _attach_metrics(rec)
        _attach_slo(rec)
    with _EMIT_LOCK:
        sys.stdout.write(json.dumps(rec) + "\n")
        sys.stdout.flush()


def _serve_bench() -> None:
    """``--serve``: open-loop load over the serve batcher+runner.

    Trains a small GBT, publishes it to a ModelRegistry, then drives the
    DynamicBatcher directly (no HTTP — the socket layer has its own soak
    test) with Poisson arrivals at ``SERVE_QPS`` for ``SERVE_SECONDS``,
    request sizes drawn from ``SERVE_REQ_SIZES`` (comma list, sampled
    uniformly — repeat a size to weight it).  Emits the same JSON shape
    as the GBT bench: one provisional line per phase, a final line with
    throughput, latency percentiles, reject counts and a batch-size
    histogram summary; ``--metrics-out`` archives the full registry
    snapshot.  All buckets are warmed before the timed window so jit
    compiles don't pollute the latency sample."""
    t0 = time.time()
    budget = float(os.environ.get("BENCH_TIME_BUDGET", 480))
    qps = float(os.environ.get("SERVE_QPS", 300))
    duration = min(float(os.environ.get("SERVE_SECONDS", 10)),
                   max(budget - 120, 2.0))
    max_batch = int(os.environ.get("SERVE_MAX_BATCH", 256))
    max_delay = float(os.environ.get("SERVE_MAX_DELAY_MS", 2.0)) / 1e3
    sizes = [int(s) for s in
             os.environ.get("SERVE_REQ_SIZES", "1,1,1,1,2,4,8,16").split(",")]
    train_rows = int(os.environ.get("SERVE_TRAIN_ROWS", 50_000))
    n_trees = int(os.environ.get("SERVE_TREES", 20))
    feats = int(os.environ.get("BENCH_FEATURES", 28))

    if os.environ.get("BENCH_FORCE_CPU"):
        from dmlc_core_tpu.utils import force_cpu_devices
        force_cpu_devices(int(os.environ["BENCH_FORCE_CPU"]))

    cfg = {"qps": qps, "duration_s": duration, "max_batch": max_batch,
           "max_delay_ms": max_delay * 1e3, "req_sizes": sizes,
           "train_rows": train_rows, "n_trees": n_trees}
    _serve_emit({"value": 0.0, "phase": "train", **cfg})

    import jax  # noqa: F401 — device init before timing anything

    from dmlc_core_tpu.models import HistGBT
    from dmlc_core_tpu.serve import DynamicBatcher, ModelRegistry

    rng = np.random.default_rng(11)
    X = rng.normal(size=(train_rows, feats)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 0.5 * X[:, 2] > 0).astype(np.float32)
    model = HistGBT(n_trees=n_trees, max_depth=4, n_bins=64,
                    learning_rate=0.3)
    model.fit(X, y)

    registry = ModelRegistry(max_batch=max_batch, min_bucket=8)
    registry.publish(model, source="serve-bench")
    _, runner = registry.current()

    def execute(batch):
        version, r = registry.current()
        return r.predict(batch), version

    _serve_emit({"value": 0.0, "phase": "warmup", **cfg})
    # compile every ladder bucket (persistent-cache aware: a warm
    # restart deserializes instead of compiling — see doc/performance.md)
    warm_wall = runner.warmup(feats)

    batcher = DynamicBatcher(execute, max_batch=max_batch,
                             max_delay=max_delay, max_queue=512,
                             name="serve-bench")
    lats = []
    errors = [0]
    lock = threading.Lock()

    def record(fut, t_sub):
        try:
            fut.result()
        except Exception:  # noqa: BLE001
            with lock:
                errors[0] += 1
            return
        with lock:
            lats.append(time.perf_counter() - t_sub)

    from dmlc_core_tpu.serve import QueueFullError

    _serve_emit({"value": 0.0, "phase": "load", **cfg})
    submitted = rejected = 0
    start = time.perf_counter()
    next_t = start
    end = start + duration
    while (now := time.perf_counter()) < end:
        if now < next_t:
            time.sleep(min(next_t - now, 0.005))
            continue
        next_t += rng.exponential(1.0 / qps)
        k = int(rng.choice(sizes))
        lo = int(rng.integers(0, train_rows - k))
        t_sub = time.perf_counter()
        try:
            fut = batcher.submit(X[lo:lo + k], timeout=5.0)
        except QueueFullError:
            rejected += 1
            continue
        fut.add_done_callback(lambda f, t=t_sub: record(f, t))
        submitted += 1
    batcher.close(drain=True)
    wall = time.perf_counter() - start

    # batch-size evidence straight from the serve instruments
    batch_summary = {}
    try:
        from dmlc_core_tpu.base.metrics import default_registry
        snap = default_registry().snapshot()["metrics"]
        hs = snap.get("dmlc_serve_batch_rows", {}).get("series", [])
        se = next((s for s in hs
                   if s["labels"].get("batcher") == "serve-bench"), None)
        if se:
            batch_summary = {
                "batches": se["count"],
                "batch_rows_p50": se["quantiles"]["p50"],
                "batch_rows_p99": se["quantiles"]["p99"],
                "batch_rows_max": se["max"],
            }
    except Exception:  # noqa: BLE001 — evidence, not the headline
        pass

    done = len(lats)
    _serve_emit({
        "value": round(done / wall, 2) if wall > 0 else 0.0,
        "phase": "done",
        "elapsed_s": round(time.time() - t0, 1),
        "platform": jax.devices()[0].platform,
        "submitted": submitted,
        "completed": done,
        "rejected": rejected,
        "errors": errors[0],
        "warmup_seconds": round(warm_wall, 3),
        **latency_summary(lats),
        **batch_summary,
        "compiled_shapes": sorted(runner.compiled_shapes),
        "shape_bound": runner.shape_bound,
        **cfg,
    }, final=True)


def _fleet_emit(rec, final=False):
    rec = {"metric": "fleet_requests_per_sec", "unit": "req/s",
           "provisional": not final, **rec}
    if final:
        _attach_metrics(rec)
        _attach_slo(rec)
    with _EMIT_LOCK:
        sys.stdout.write(json.dumps(rec) + "\n")
        sys.stdout.flush()


def _fleet_bench() -> None:
    """``--fleet``: closed-loop load over a replica fleet behind the
    consistent-hash router, with a staged v1->v2 rollout mid-run.

    Trains two GBT versions, checkpoints both, then stands up the full
    fleet topology — FleetTracker + ``FLEET_REPLICAS`` replicas spawned
    through the launch subsystem (a :class:`LauncherScaler`-backed
    JobSet) + in-process FleetRouter — and drives it with the
    multi-process closed-loop load generator (heavy-tail request sizes,
    diurnal QPS ramp).  One third into the run a staged rollout
    (wave size 1) hot-swaps the fleet to v2 under load.  Every response
    is verified bit-exactly against the version it claims, so the final
    line's ``dropped``/``wrong`` counters ARE the zero-drop hot-swap
    acceptance evidence; per-replica balance comes from the router's
    ``fleet_routed_total`` series, and the supervisor's view lands in
    the final line's ``launch`` block (backend, respawns,
    spawn_ms_p95)."""
    t0 = time.time()
    budget = float(os.environ.get("BENCH_TIME_BUDGET", 480))
    n_replicas = int(os.environ.get("FLEET_REPLICAS", 3))
    duration = min(float(os.environ.get("FLEET_SECONDS", 8)),
                   max(budget - 180, 3.0))
    qps = float(os.environ.get("FLEET_QPS", 120))
    procs = int(os.environ.get("FLEET_PROCS", 2))
    threads = int(os.environ.get("FLEET_THREADS", 3))
    train_rows = int(os.environ.get("FLEET_TRAIN_ROWS", 20_000))
    serve_rows = int(os.environ.get("FLEET_SERVE_ROWS", 512))
    feats = int(os.environ.get("BENCH_FEATURES", 28))

    if os.environ.get("BENCH_FORCE_CPU"):
        from dmlc_core_tpu.utils import force_cpu_devices
        force_cpu_devices(int(os.environ["BENCH_FORCE_CPU"]))

    cfg = {"replicas": n_replicas, "qps": qps, "duration_s": duration,
           "procs": procs, "threads": threads, "train_rows": train_rows}
    _fleet_emit({"value": 0.0, "phase": "train", **cfg})

    import tempfile

    import jax  # noqa: F401 — device init before timing anything

    from dmlc_core_tpu.models import HistGBT
    from dmlc_core_tpu.serve import checkpoint_model
    from dmlc_core_tpu.serve.fleet import (FleetRouter, FleetTracker,
                                           HttpFleetAdmin, LauncherScaler,
                                           Rollout, run_loadgen)

    rng = np.random.default_rng(11)
    Xt = rng.normal(size=(train_rows, feats)).astype(np.float32)
    yt = (Xt[:, 0] * Xt[:, 1] + 0.5 * Xt[:, 2] > 0).astype(np.float32)
    m1 = HistGBT(n_trees=5, max_depth=4, n_bins=32,
                 learning_rate=0.3).fit(Xt, yt)
    m2 = HistGBT(n_trees=10, max_depth=4, n_bins=32,
                 learning_rate=0.3).fit(Xt, yt)
    X = Xt[:serve_rows]

    workdir = tempfile.mkdtemp(prefix="fleet_bench_")
    v1_uri = f"file://{workdir}/v1.ckpt"
    v2_uri = f"file://{workdir}/v2.ckpt"
    checkpoint_model(v1_uri, m1, version=1)
    checkpoint_model(v2_uri, m2, version=2)
    expected_npz = os.path.join(workdir, "expected.npz")
    np.savez(expected_npz, X=X, v1=m1.predict(X), v2=m2.predict(X))

    _fleet_emit({"value": 0.0, "phase": "spawn", **cfg})
    child_env = {"JAX_PLATFORMS": "cpu"} if os.environ.get(
        "BENCH_FORCE_CPU") else None
    tracker = FleetTracker(nworker=max(8, n_replicas + 2))
    tracker.start()
    scaler = LauncherScaler(tracker, v1_uri, initial=n_replicas,
                            spawn_env=child_env)
    router = None
    rollout_report = {}
    try:
        deadline = time.time() + 180
        while len(tracker.serve_endpoints()) < n_replicas:
            if time.time() > deadline:
                raise RuntimeError("fleet replicas never registered")
            time.sleep(0.2)
        router = FleetRouter(tracker, probe_s=0.2).start()

        def _rollout():
            time.sleep(duration / 3.0)
            admin = HttpFleetAdmin(tracker.serve_endpoints())
            rollout_report.update(
                Rollout(admin, wave_size=1, settle_s=0.3).run(v2_uri))

        _fleet_emit({"value": 0.0, "phase": "load", **cfg})
        roller = threading.Thread(target=_rollout, daemon=True)
        roller.start()
        merged = run_loadgen(
            router.url, expected_npz, duration_s=duration, procs=procs,
            threads=threads, base_qps=qps, amplitude=0.5,
            period_s=max(duration / 2.0, 2.0),
            timeout_ms=10_000, workdir=workdir)
        roller.join(timeout=120)

        balance = {}
        try:
            from dmlc_core_tpu.base.metrics import default_registry
            snap = default_registry().snapshot()["metrics"]
            for s in snap.get("dmlc_fleet_routed_total",
                              {}).get("series", []):
                balance[s["labels"]["replica"]] = s["value"]
        except Exception:  # noqa: BLE001 — evidence, not the headline
            pass

        _fleet_emit({
            "value": merged["throughput_rps"],
            "phase": "done",
            "elapsed_s": round(time.time() - t0, 1),
            "platform": jax.devices()[0].platform,
            "requests": merged["count"],
            "ok": merged["ok"],
            "dropped": merged["dropped"],
            "wrong": merged["wrong"],
            "by_version": merged["by_version"],
            "latency_p50_ms": merged["latency_p50_ms"],
            "latency_p95_ms": merged["latency_p95_ms"],
            "latency_p99_ms": merged["latency_p99_ms"],
            "per_replica_routed": balance,
            "rollout": {k: rollout_report.get(k) for k in
                        ("version", "outcome", "waves")},
            "launch": {k: scaler.jobset.stats()[k] for k in
                       ("backend", "respawns", "spawn_ms_p95")},
            **cfg,
        }, final=True)
    finally:
        if router is not None:
            router.close()
        scaler.reap(timeout=15)
        tracker.stop()


def _tenants_emit(rec, final=False):
    rec = {"metric": "tenant_requests_per_sec", "unit": "req/s",
           "provisional": not final, **rec}
    if final:
        _attach_metrics(rec)
        _attach_slo(rec)
    with _EMIT_LOCK:
        sys.stdout.write(json.dumps(rec) + "\n")
        sys.stdout.flush()


def _tenants_bench() -> None:
    """``--tenants``: multi-tenant registry under a Zipf tenant mix.

    Publishes ``TENANTS_N`` distinct HistGBT models into one
    :class:`TenantRegistry` capped at ``TENANTS_RESIDENT_CAP`` resident
    runners, then drives it closed-loop from ``TENANTS_THREADS`` threads
    sampling tenants from the same bounded-Zipf law the tenancy drill
    uses — the hot head stays warm, the long tail churns through
    eviction and compile-cache-backed warm restore.  Every response is
    verified bit-exactly against the publishing model, so ``wrong`` is
    paging-correctness evidence, not just a counter; the final line
    carries per-tenant p50/p99 plus the eviction/restore totals the
    scorecard gates."""
    t0 = time.time()
    budget = float(os.environ.get("BENCH_TIME_BUDGET", 480))
    n_tenants = int(os.environ.get("TENANTS_N", 12))
    cap = int(os.environ.get("TENANTS_RESIDENT_CAP", 4))
    duration = min(float(os.environ.get("TENANTS_SECONDS", 6)),
                   max(budget - 120, 2.0))
    n_threads = int(os.environ.get("TENANTS_THREADS", 4))
    zipf_a = float(os.environ.get("TENANTS_ZIPF_A", 1.1))
    train_rows = int(os.environ.get("TENANTS_TRAIN_ROWS", 4000))
    serve_rows = int(os.environ.get("TENANTS_SERVE_ROWS", 256))
    feats = int(os.environ.get("BENCH_FEATURES", 28))

    if os.environ.get("BENCH_FORCE_CPU"):
        from dmlc_core_tpu.utils import force_cpu_devices
        force_cpu_devices(int(os.environ["BENCH_FORCE_CPU"]))

    cfg = {"tenants": n_tenants, "resident_cap": cap, "zipf_a": zipf_a,
           "duration_s": duration, "threads": n_threads}
    _tenants_emit({"value": 0.0, "phase": "train", **cfg})

    import jax

    from dmlc_core_tpu.models import HistGBT
    from dmlc_core_tpu.serve.fleet.loadgen import (sample_tenant,
                                                   zipf_weights)
    from dmlc_core_tpu.serve.tenancy import TenantRegistry

    rng = np.random.default_rng(17)
    Xt = rng.normal(size=(train_rows, feats)).astype(np.float32)
    X = Xt[:serve_rows]
    reg = TenantRegistry(resident_cap=cap, max_batch=64)
    names = [f"t{i:02d}" for i in range(n_tenants)]
    expected = {}
    for i, name in enumerate(names):
        yt = (Xt[:, i % feats] + 0.5 * Xt[:, (i + 1) % feats]
              > 0).astype(np.float32)
        m = HistGBT(n_trees=3 + i % 3, max_depth=3, n_bins=32).fit(Xt, yt)
        reg.publish(name, m)
        # HistGBT is bit-exact across batch shapes, so any prefix of
        # this full-batch oracle is THE expected answer for a request
        expected[name] = np.asarray(m.predict(X))

    cum = zipf_weights(n_tenants, zipf_a)
    lat = {name: [] for name in names}   # list.append is GIL-atomic
    wrongs = [0] * n_threads
    stop = threading.Event()

    def worker(idx):
        r = np.random.default_rng(1000 + idx)
        while not stop.is_set():
            tenant = sample_tenant(r, names, cum)
            n = int(r.integers(1, serve_rows + 1))
            t1 = time.perf_counter()
            _, runner = reg.current(tenant)
            out = np.asarray(runner.predict(X[:n]))
            lat[tenant].append(time.perf_counter() - t1)
            if not np.array_equal(out, expected[tenant][:n]):
                wrongs[idx] += 1

    _tenants_emit({"value": 0.0, "phase": "load", **cfg})
    workers = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(n_threads)]
    t_load = time.perf_counter()
    for w in workers:
        w.start()
    time.sleep(duration)
    stop.set()
    for w in workers:
        w.join(timeout=60)
    wall = time.perf_counter() - t_load

    count = sum(len(v) for v in lat.values())
    by_tenant = {}
    for name in names:
        ms = np.sort(np.asarray(lat[name], dtype=np.float64)) * 1000.0
        by_tenant[name] = {"count": int(ms.size)}
        if ms.size:
            by_tenant[name].update(
                p50_ms=round(float(np.percentile(ms, 50)), 3),
                p99_ms=round(float(np.percentile(ms, 99)), 3))
    _tenants_emit({
        "value": round(count / max(wall, 1e-9), 2),
        "phase": "done",
        "elapsed_s": round(time.time() - t0, 1),
        "platform": jax.devices()[0].platform,
        "requests": count,
        "wrong": sum(wrongs),
        "evictions": reg.evictions,
        "warm_restores": reg.restores,
        "resident": reg.resident(),
        "by_tenant": by_tenant,
        **cfg,
    }, final=True)


def _stream_emit(rec, final=False):
    rec = {"metric": "stream_staleness_seconds", "unit": "s",
           "provisional": not final, **rec}
    if final:
        _attach_metrics(rec)
        _attach_slo(rec)
    with _EMIT_LOCK:
        sys.stdout.write(json.dumps(rec) + "\n")
        sys.stdout.flush()


def _stream_bench() -> None:
    """``--stream``: closed-loop online-learning benchmark.

    A generator thread appends synthetic events (dense-event codec,
    slight concept drift) to a growing RecordIO shard set at
    ``STREAM_EVENTS_PER_SEC``; the main loop runs the full train→serve
    path — tail → warm-start boost → eval-gate publish → registry
    hot-swap — for ``STREAM_SECONDS``.  The headline is **staleness**:
    the latency from an event being appended to an *activated* model
    version having trained on it (p50/p95/p99 over all served events),
    reported alongside refresh throughput.  ``--metrics-out`` archives
    the full registry snapshot (tailer/trainer/publisher counters plus
    the staleness histogram)."""
    t0 = time.time()
    budget = float(os.environ.get("BENCH_TIME_BUDGET", 480))
    duration = min(float(os.environ.get("STREAM_SECONDS", 10)),
                   max(budget - 120, 2.0))
    rate = float(os.environ.get("STREAM_EVENTS_PER_SEC", 1500))
    chunk_rows = int(os.environ.get("STREAM_CHUNK_ROWS", 1024))
    window_chunks = int(os.environ.get("STREAM_WINDOW_CHUNKS", 2))
    trees = int(os.environ.get("STREAM_TREES", 5))
    feats = int(os.environ.get("BENCH_FEATURES", 28))
    shard_events = int(os.environ.get("STREAM_SHARD_EVENTS",
                                      8 * chunk_rows))

    if os.environ.get("BENCH_FORCE_CPU"):
        from dmlc_core_tpu.utils import force_cpu_devices
        force_cpu_devices(int(os.environ["BENCH_FORCE_CPU"]))

    cfg = {"duration_s": duration, "events_per_sec": rate,
           "chunk_rows": chunk_rows, "window_chunks": window_chunks,
           "trees_per_refresh": trees, "features": feats}
    _stream_emit({"value": 0.0, "phase": "setup", **cfg})

    import shutil
    import tempfile

    import jax  # noqa: F401 — device init before timing anything

    from dmlc_core_tpu.base import jitcheck
    from dmlc_core_tpu.base.metrics import default_registry
    from dmlc_core_tpu.io.recordio import encode_records
    from dmlc_core_tpu.models import HistGBT
    from dmlc_core_tpu.serve import ModelRegistry
    from dmlc_core_tpu.stream import (ModelPublisher, OnlineTrainer,
                                      RecordIOTailer, encode_dense_events)

    stale_hist = default_registry().histogram(
        "stream_staleness_seconds",
        "event appended → servable prediction (an activated version "
        "has trained on it)",
        buckets=(0.25, 0.5, 1, 2, 4, 8, 16, 32, 64))

    rng = np.random.default_rng(13)

    def make_events(n, drift):
        X = rng.normal(size=(n, feats)).astype(np.float32)
        y = (X[:, 0] * X[:, 1] + (0.5 + drift) * X[:, 2]
             - drift * X[:, 3] > 0).astype(np.float32)
        return X, y

    root = tempfile.mkdtemp(prefix="bench_stream_")
    shard_dir = os.path.join(root, "events")
    os.makedirs(shard_dir)
    append_ts = []                    # wall clock per appended event seq
    stop_gen = threading.Event()

    def generator():
        """Paced appender: bursts every tick, fsync-free flush so the
        tailer sees bytes promptly; rotates shards so the tailer's
        growing-file-set path is exercised."""
        written = 0
        shard_idx = 0
        f = open(os.path.join(shard_dir, f"part-{shard_idx:04d}.rec"), "ab")
        start = time.perf_counter()
        try:
            while not stop_gen.is_set():
                target = int((time.perf_counter() - start) * rate)
                burst = min(target - written, 4096)
                if burst <= 0:
                    time.sleep(0.01)
                    continue
                drift = 0.2 * ((written // shard_events) % 3)
                X, y = make_events(burst, drift)
                blob = encode_records(encode_dense_events(X, y))
                f.write(blob)
                f.flush()
                now = time.time()
                append_ts.extend([now] * burst)
                written += burst
                if written // shard_events > shard_idx:
                    f.close()
                    shard_idx = written // shard_events
                    f = open(os.path.join(
                        shard_dir, f"part-{shard_idx:04d}.rec"), "ab")
        finally:
            f.close()

    Xh, yh = make_events(4096, drift=0.0)
    registry = ModelRegistry(max_batch=256, min_bucket=8)
    publisher = ModelPublisher(registry, holdout=(Xh, yh),
                               name="stream-bench")
    model = HistGBT(n_trees=trees, max_depth=4, n_bins=32,
                    learning_rate=0.3)
    tailer = RecordIOTailer(shard_dir,
                            cursor_uri=os.path.join(root, "cursor.ckpt"),
                            name="stream-bench")
    trainer = OnlineTrainer(model, tailer, n_features=feats,
                            chunk_rows=chunk_rows,
                            window_chunks=window_chunks, decay=1.0,
                            publisher=publisher, name="stream-bench")

    gen = threading.Thread(target=generator, daemon=True)
    gen.start()
    _stream_emit({"value": 0.0, "phase": "loop", **cfg})

    staleness = []
    served_floor = 0                  # events covered by an activation
    refreshes = []
    steady_marked = False
    end = time.perf_counter() + duration
    try:
        while time.perf_counter() < end:
            left = end - time.perf_counter()
            r = trainer.refresh(timeout=max(min(left, 5.0), 0.1))
            if r is None:
                continue
            refreshes.append(r)
            if (not steady_marked and jitcheck.installed()
                    and r["window_rows"] >= chunk_rows * window_chunks):
                # the sliding window just reached its final shape, so
                # every refresh program is compiled — from here on a
                # refresh that compiles is a steady-state stall
                jitcheck.steady()
                steady_marked = True
            if r.get("activated"):
                now = time.time()
                covered = min(r["records_total"], len(append_ts))
                for seq in range(served_floor, covered):
                    s = now - append_ts[seq]
                    staleness.append(s)
                    stale_hist.observe(s)
                served_floor = covered
    finally:
        stop_gen.set()
        gen.join(timeout=5.0)
        tailer.close()

    wall = time.time() - t0
    activated = sum(1 for r in refreshes if r.get("activated"))
    stale_sorted = sorted(staleness)

    def q(p):
        if not stale_sorted:
            return None
        return round(stale_sorted[min(len(stale_sorted) - 1,
                                      int(round(p * (len(stale_sorted)
                                                     - 1))))], 3)

    fit_s = [r["fit_seconds"] for r in refreshes]
    final = {
        "value": q(0.95) or 0.0,
        "phase": "done",
        "elapsed_s": round(wall, 1),
        "platform": jax.devices()[0].platform,
        "staleness_seconds": {"p50": q(0.50), "p95": q(0.95),
                              "p99": q(0.99)},
        "refreshes_published": activated,
        "refreshes_total": len(refreshes),
        "rollbacks": publisher.rollbacks,
        "refreshes_per_sec": round(len(refreshes) / max(duration, 1e-9), 3),
        "refresh_rows_per_sec": round(
            sum(r["rows"] for r in refreshes) / max(duration, 1e-9), 1),
        "fit_seconds_mean": (round(sum(fit_s) / len(fit_s), 3)
                             if fit_s else None),
        "events_appended": len(append_ts),
        "events_consumed": tailer.records_seen,
        "events_served": served_floor,
        "trees_total": len(model.trees),
        "registry_versions": len(registry.versions()),
        "recompiles_steady_state": (len(jitcheck.compiles("steady"))
                                    if steady_marked else None),
        **cfg,
    }
    _stream_emit(final, final=True)
    shutil.rmtree(root, ignore_errors=True)
    if steady_marked:
        # DMLC_JITCHECK=1 turns the record into a gate: any compile
        # after the window filled fails the bench outright
        jitcheck.check()


def _ps_bench() -> None:
    """``--ps``: web-scale sparse CTR over the sharded parameter server.

    In-process fleet (scheduler + ``PS_SERVERS`` server threads) with
    ``PS_WORKERS`` worker threads each running :meth:`GBLinear.fit_ps`
    over its own synthetic hashing-space CTR stream —
    ``PS_FEATURES`` (default 10M) feature cardinality, so the weight
    vector exists only range-sharded on the fleet and each minibatch
    moves only its touched ids.  Headlines: **keys_per_sec** (sparse
    ids crossing the wire, push+pull directions) and **staleness_p95**
    (SSP lag observed at pull, in rounds — bounded by
    ``DMLC_PS_STALENESS``)."""
    t0 = time.time()
    features = int(os.environ.get("PS_FEATURES", 10_000_000))
    rows = int(os.environ.get("PS_ROWS", 40_000))
    nnz = int(os.environ.get("PS_NNZ", 32))
    batch_rows = int(os.environ.get("PS_BATCH_ROWS", 2048))
    nserver = int(os.environ.get("PS_SERVERS", 2))
    nworker = int(os.environ.get("PS_WORKERS", 2))
    if os.environ.get("BENCH_FORCE_CPU"):
        from dmlc_core_tpu.utils import force_cpu_devices
        force_cpu_devices(int(os.environ["BENCH_FORCE_CPU"]))

    from dmlc_core_tpu.data.row_block import RowBlock
    from dmlc_core_tpu.models.linear import GBLinear
    from dmlc_core_tpu.parallel.kvstore import DistAsyncKVStore
    from dmlc_core_tpu.parallel.ps import PSClient, PSScheduler, PSServer

    class _CTRStream:
        """Re-iterable synthetic sparse CTR pages (hashing space)."""

        def __init__(self, seed):
            self.seed = seed
            self.num_col = features

        def __iter__(self):
            rng = np.random.default_rng(self.seed)
            hot = rng.choice(features, 256, replace=False)
            w_true = rng.normal(size=256).astype(np.float32)
            page = 4 * batch_rows
            for lo in range(0, rows, page):
                n = min(page, rows - lo)
                idx = rng.integers(0, features, size=(n, nnz))
                # every row carries a few signal features
                idx[:, :4] = hot[rng.integers(0, 256, size=(n, 4))]
                vals = rng.normal(size=(n, nnz)).astype(np.float32)
                sig = np.searchsorted(np.sort(hot), idx[:, :4])
                m = (vals[:, :4] * w_true[np.argsort(hot)][sig]).sum(1)
                y = (m > 0).astype(np.float32)
                off = np.arange(0, n * nnz + 1, nnz, dtype=np.int64)
                yield RowBlock(offset=off, label=y,
                               index=idx.ravel().astype(np.int64),
                               value=vals.ravel())

    sched = PSScheduler("127.0.0.1", nworker=nworker, nserver=nserver)
    sched.start()
    servers = [PSServer("127.0.0.1", sched.port, server_id=i)
               for i in range(nserver)]
    for s in servers:
        s.start()
    sthreads = [threading.Thread(target=s.serve_forever, daemon=True)
                for s in servers]
    for st in sthreads:
        st.start()

    stats = {}

    def worker(rank):
        client = PSClient(root_uri="127.0.0.1", root_port=sched.port,
                          rank=rank)
        kv = DistAsyncKVStore(client, learning_rate=0.1)
        model = GBLinear(learning_rate=0.1, reg_lambda=0.0)
        model.fit_ps(_CTRStream(seed=rank), kv, num_col=features,
                     batch_rows=batch_rows, finalize=False)
        stats[rank] = {"keys": kv.stats["keys_synced"],
                       "staleness": list(kv.staleness_samples)}
        kv.close(shutdown_job=(rank == 0))

    wthreads = [threading.Thread(target=worker, args=(r,))
                for r in range(nworker)]
    t_train = time.time()
    for wt in wthreads:
        wt.start()
    for wt in wthreads:
        wt.join()
    elapsed = time.time() - t_train
    for st in sthreads:
        st.join(timeout=30)
    sched.join(timeout=30)

    keys = sum(s["keys"] for s in stats.values())
    lags = np.array(sum((s["staleness"] for s in stats.values()), []),
                    np.float64)
    rec = {
        "bench": "ps_sparse_ctr", "provisional": False,
        "features": features, "rows_per_worker": rows, "nnz": nnz,
        "batch_rows": batch_rows, "servers": nserver, "workers": nworker,
        "elapsed_s": round(elapsed, 2),
        "rows_per_sec": round(nworker * rows / max(elapsed, 1e-9), 1),
        # each pushed id was pulled the same round: count both directions
        "keys_per_sec": round(2 * keys / max(elapsed, 1e-9), 1),
        "keys_moved": int(2 * keys),
        "staleness_p95": (float(np.percentile(lags, 95))
                          if len(lags) else None),
        "staleness_max": float(lags.max()) if len(lags) else None,
        "staleness_bound": int(os.environ.get("DMLC_PS_STALENESS", 4)),
        "pull_rounds": int(len(lags)),
        "wall_s": round(time.time() - t0, 2),
        "basis": "in-process fleet, single host: wire framing + server "
                 "aggregation are real, network hops are loopback",
    }
    _attach_metrics(rec)
    _attach_slo(rec)
    with _EMIT_LOCK:
        sys.stdout.write(json.dumps(rec) + "\n")
        sys.stdout.flush()


# ---------------------------------------------------------------------------
# --prodsim: production-day simulation — whole-stack chaos drill
# ---------------------------------------------------------------------------

_PRODSIM_TENANTS = ["t0", "t1", "t2", "t3", "t4"]
_PRODSIM_POISON = "t2"               # the tenant whose v2 publish is poisoned
_PRODSIM_LIVE = "live"               # the stream-refreshed tenant
_PRODSIM_HOSTS = ["p0", "p1", "p2", "p3", "p4", "p5"]


def _prodsim_emit(rec, final=False):
    rec = {"metric": "prodsim_availability", "unit": "ratio",
           "provisional": not final, **rec}
    if final:
        _attach_metrics(rec)
        _attach_slo(rec)
    with _EMIT_LOCK:
        sys.stdout.write(json.dumps(rec) + "\n")
        sys.stdout.flush()


def _prodsim_ps_blocks(rank, n_features, rows, nnz=8):
    """Deterministic per-worker CSR shard (32 shared signal features so
    every shard is learnable) — the sparse-CTR lane's data."""
    from dmlc_core_tpu.data.row_block import RowBlock

    sig_rng = np.random.default_rng(7)
    hot = sig_rng.choice(n_features, 32, replace=False)
    w_true = sig_rng.normal(size=32).astype(np.float32)
    rng = np.random.default_rng(100 + rank)
    blocks = []
    for _ in range(2):
        n = rows // 2
        idx = rng.integers(0, n_features, size=(n, nnz)).astype(np.int64)
        idx[:, :4] = hot[rng.integers(0, 32, size=(n, 4))]
        vals = rng.normal(size=(n, nnz)).astype(np.float32)
        order = np.argsort(hot)
        pos = order[np.searchsorted(hot[order], idx[:, :4])]
        y = ((vals[:, :4] * w_true[pos]).sum(1) > 0).astype(np.float32)
        off = np.arange(0, n * nnz + 1, nnz, dtype=np.int64)
        blocks.append(RowBlock(offset=off, label=y, index=idx.ravel(),
                               value=vals.ravel()))
    return blocks


def _prodsim_ps_server() -> None:
    """Internal ``--prodsim-ps-server`` entry (spawned by --prodsim)."""
    from dmlc_core_tpu.base import lockcheck
    from dmlc_core_tpu.parallel.ps import PSServer

    srv = PSServer("127.0.0.1", int(os.environ["PS_SCHED_PORT"]),
                   server_id=int(os.environ["DMLC_PS_SERVER_ID"]))
    srv.start()
    srv.serve_forever(timeout_s=600)
    out = os.environ.get("PS_SERVER_STATS")
    if out:
        with open(out, "w") as f:
            json.dump({"server_id": srv.server_id,
                       "restored_version": srv.restored_version}, f)
    lockcheck.check()


def _prodsim_ps_worker() -> None:
    """Internal ``--prodsim-ps-worker`` entry: loop ``GBLinear.fit_ps``
    passes until the stop file appears, so pushes span whatever chaos
    the parent schedules; then score train accuracy on the own shard."""
    from dmlc_core_tpu.base import lockcheck
    from dmlc_core_tpu.models.linear import GBLinear
    from dmlc_core_tpu.parallel.kvstore import DistAsyncKVStore
    from dmlc_core_tpu.parallel.ps import PSClient

    rank = int(os.environ["DMLC_TASK_ID"])
    stop_file = os.environ["PRODSIM_PS_STOP"]
    n_features = int(os.environ.get("PRODSIM_PS_FEATURES", "20000"))
    client = PSClient(root_uri="127.0.0.1",
                      root_port=int(os.environ["PS_SCHED_PORT"]), rank=rank)
    kv = DistAsyncKVStore(client, learning_rate=0.5)
    blocks = _prodsim_ps_blocks(
        rank, n_features, int(os.environ.get("PRODSIM_PS_ROWS", "1200")))
    model = None
    passes = 0
    while True:
        model = GBLinear(learning_rate=0.5, reg_lambda=0.0)
        model.fit_ps(blocks, kv, num_col=n_features, batch_rows=256,
                     n_epochs=1)
        passes += 1
        if os.path.exists(stop_file):
            break
        # server-side init is first-wins (idempotent across workers),
        # so dropping the client-side guard lets the next pass re-enter
        # fit_ps and keep training the SAME fleet-resident weights
        kv._shapes.pop("gblinear", None)
    correct = total = 0
    for blk in blocks:
        rows = np.repeat(np.arange(blk.size), np.diff(blk.offset))
        m = np.zeros(blk.size, np.float32)
        np.add.at(m, rows, model.weights[blk.index] * blk.value)
        m += model.bias
        correct += int(((m > 0) == (blk.label > 0.5)).sum())
        total += blk.size
    samples = kv.staleness_samples
    with open(os.path.join(os.environ["PS_OUT"],
                           f"worker-{rank}.json"), "w") as f:
        json.dump({"rank": rank, "accuracy": correct / total,
                   "passes": passes,
                   "staleness_max": max(samples) if samples else 0}, f)
    kv.close(shutdown_job=False)    # parent owns the scheduler
    lockcheck.check()


def _prodsim_bench() -> dict:
    """``--prodsim``: one production day in one run — every tier faulted.

    Composes everything the repo has grown into a single topology: a
    live event feed streaming into an :class:`OnlineTrainer` whose
    refreshes are published through tenant-scoped staged rollouts, a
    sparse-CTR ``fit_ps`` lane on a real multi-process PS fleet, and a
    multi-tenant replica fleet (FakeTransport "hosts" supervised by a
    :class:`LauncherScaler` JobSet) serving diurnal Zipf loadgen —
    while a deterministic chaos schedule (``DMLC_PRODSIM_CHAOS``, or a
    default derived from ``DMLC_PRODSIM_SECONDS``; wall-clock
    ``at=``/``every=`` triggers, seeded by ``DMLC_FAULT_SEED``) injects
    one fault in every tier mid-run:

    * ``prodsim_replica:kill``   — SIGKILL a serving replica
    * ``prodsim_ps:kill``        — SIGKILL a PS server (respawned same
      id, snapshot-restored)
    * ``launch_host:wave``       — spot-preemption wave: 30% of fake
      hosts down AT ONCE (fires inside the JobSet monitor tick)
    * ``prodsim_shard:corrupt``  — corrupt bytes appended to the live
      stream shard (tailer must resync)
    * ``prodsim_publish:poison`` — poisoned v2 publish for ONE tenant
      (eval gate must trip, rollback must stay tenant-scoped)

    The final line is the one SLO scorecard record: availability,
    dropped/wrong, per-tier chaos evidence, launch cause-fair respawn
    budgets, PS restore, stream staleness + resyncs, and rollback
    isolation.  Returns the record (``scripts/check_prodsim.py`` calls
    this in-process and gates GREEN on ``scripts/slo/prodsim.json``)."""
    t0 = time.time()
    budget = float(os.environ.get("BENCH_TIME_BUDGET", 480))

    import glob
    import signal as _signal
    import subprocess
    import tempfile

    from dmlc_core_tpu.base import faultinject
    from dmlc_core_tpu.base import jitcheck
    from dmlc_core_tpu.base import knobs as _knobs

    duration = min(float(_knobs.value("DMLC_PRODSIM_SECONDS")),
                   max(budget - 240, 6.0))
    chaos_spec = str(_knobs.value("DMLC_PRODSIM_CHAOS")).strip()
    if not chaos_spec:
        # the default all-tier schedule scales with the load window
        chaos_spec = ",".join([
            f"prodsim_replica:kill:at={0.25 * duration:.3f}:n=1",
            f"prodsim_ps:kill:at={0.35 * duration:.3f}:n=1",
            f"launch_host:wave=0.3:at={0.5 * duration:.3f}:n=1",
            f"prodsim_shard:corrupt:at={0.6 * duration:.3f}:n=1",
            f"prodsim_publish:poison:at={0.7 * duration:.3f}:n=1",
        ])
    seed = int(os.environ.get("DMLC_FAULT_SEED") or "1234")
    qps = float(os.environ.get("PRODSIM_QPS", 60))
    rate = float(os.environ.get("PRODSIM_EVENTS_PER_SEC", 800))
    feats = 8
    n_rows = 400

    if os.environ.get("BENCH_FORCE_CPU"):
        from dmlc_core_tpu.utils import force_cpu_devices
        force_cpu_devices(int(os.environ["BENCH_FORCE_CPU"]))

    cfg = {"duration_s": round(duration, 3), "qps": qps,
           "tenants": len(_PRODSIM_TENANTS), "hosts": len(_PRODSIM_HOSTS),
           "chaos_seed": seed}
    _prodsim_emit({"value": 0.0, "phase": "setup", **cfg})

    import jax  # noqa: F401 — device init before timing anything

    from dmlc_core_tpu.base.metrics import default_registry
    from dmlc_core_tpu.io.recordio import encode_records
    from dmlc_core_tpu.launch.transport import FakeTransport
    from dmlc_core_tpu.models import HistGBT
    from dmlc_core_tpu.parallel.ps import PSScheduler
    from dmlc_core_tpu.serve.client import ResilientClient
    from dmlc_core_tpu.serve.fleet import (FleetRouter, FleetTracker,
                                           HttpFleetAdmin, LauncherScaler,
                                           Rollout, run_loadgen)
    from dmlc_core_tpu.serve.registry import clone_model
    from dmlc_core_tpu.serve.tenancy import (TenantPolicy,
                                             checkpoint_tenant_model)
    from dmlc_core_tpu.stream import (OnlineTrainer, RecordIOTailer,
                                      encode_dense_events)

    stale_hist = default_registry().histogram(
        "stream_staleness_seconds",
        "event appended → servable prediction (an activated version "
        "has trained on it)",
        buckets=(0.25, 0.5, 1, 2, 4, 8, 16, 32, 64))

    # -- per-tenant v1 models, poisoned v2, and the live tenant's v1 -----
    root = tempfile.mkdtemp(prefix="prodsim_")
    rng = np.random.default_rng(42)
    X = rng.normal(size=(n_rows, feats)).astype(np.float32)
    models, npz = {}, {"X": X}
    for i, t in enumerate(_PRODSIM_TENANTS):
        y = (X[:, i % feats] + X[:, (i + 1) % feats]
             * X[:, (i + 2) % feats] > 0).astype(np.float32)
        m = HistGBT(n_trees=3 + i, max_depth=3, n_bins=16).fit(X, y)
        models[t] = (m, y)
        npz[f"{t}__v1"] = m.predict(X)
        checkpoint_tenant_model(f"file://{root}/{t}_v1.ckpt", t, m,
                                version=1)
    y_poison = np.random.default_rng(7).permutation(
        models[_PRODSIM_POISON][1])
    m_poison = HistGBT(n_trees=4, max_depth=3, n_bins=16).fit(X, y_poison)
    poison_uri = f"file://{root}/{_PRODSIM_POISON}_v2.ckpt"
    checkpoint_tenant_model(poison_uri, _PRODSIM_POISON, m_poison,
                            version=2)
    npz[f"{_PRODSIM_POISON}__v2"] = m_poison.predict(X)
    expected_npz = os.path.join(root, "expected.npz")
    np.savez(expected_npz, **npz)
    X_hold, y_hold = X[:64], models[_PRODSIM_POISON][1][:64]
    base_mse = float(np.mean(
        (models[_PRODSIM_POISON][0].predict(X_hold) - y_hold) ** 2))

    # the live (stream-refreshed) tenant never appears in the loadgen
    # mix; its oracle is a direct bit-equality probe after each rollout
    ev_rng = np.random.default_rng(13)

    def _make_events(gen, n, drift=0.0):
        Xe = gen.normal(size=(n, feats)).astype(np.float32)
        ye = (Xe[:, 0] * Xe[:, 1]
              + (0.5 + drift) * Xe[:, 2] > 0).astype(np.float32)
        return Xe, ye

    X_live, y_live = _make_events(np.random.default_rng(5), 256)
    m_live = HistGBT(n_trees=3, max_depth=3, n_bins=16,
                     learning_rate=0.3).fit(X_live, y_live)
    live_v1_uri = f"file://{root}/live_v1.ckpt"
    checkpoint_tenant_model(live_v1_uri, _PRODSIM_LIVE, m_live, version=1)

    # -- fleet: tracker + fake 6-host cluster + launcher-backed scaler ---
    _prodsim_emit({"value": 0.0, "phase": "spawn", **cfg})
    child_env = {"JAX_PLATFORMS": "cpu", "DMLC_TPU_FORCE_CPU": "1",
                 "FLEET_TENANCY": "1", "DMLC_FAULT_INJECT": ""}
    tracker = FleetTracker(nworker=16)
    tracker.start()
    transport = FakeTransport(hosts=list(_PRODSIM_HOSTS),
                              log_dir=os.path.join(root, "logs"))
    scaler = LauncherScaler(tracker, None, name="prodsim",
                            transport=transport, initial=3,
                            spawn_env=child_env, restart_limit=3)

    # -- shared state for the lanes --------------------------------------
    stop_gen = threading.Event()
    stop_stream = threading.Event()
    stop_chaos = threading.Event()
    stop_recon = threading.Event()
    live_lock = threading.Lock()
    live_state = {"version": 1, "activated": 1, "model": m_live,
                  "uri": live_v1_uri, "served_floor": 0}
    append_ts = []
    staleness = []
    refreshes = []
    live_rollouts = []
    chaos_log = []
    poison_report = {}
    ps_state = {}
    shard_dir = os.path.join(root, "events")
    os.makedirs(shard_dir)
    shard_events = 2048

    def _generator():
        written = 0
        shard_idx = 0
        f = open(os.path.join(shard_dir, f"part-{shard_idx:04d}.rec"), "ab")
        start = time.perf_counter()
        try:
            while not stop_gen.is_set():
                target = int((time.perf_counter() - start) * rate)
                burst = min(target - written, 2048)
                if burst <= 0:
                    time.sleep(0.01)
                    continue
                Xe, ye = _make_events(
                    ev_rng, burst, drift=0.2 * ((written // shard_events)
                                                % 3))
                f.write(encode_records(encode_dense_events(Xe, ye)))
                f.flush()
                now = time.time()
                append_ts.extend([now] * burst)
                written += burst
                if written // shard_events > shard_idx:
                    f.close()
                    shard_idx = written // shard_events
                    f = open(os.path.join(
                        shard_dir, f"part-{shard_idx:04d}.rec"), "ab")
        finally:
            f.close()

    tailer = RecordIOTailer(shard_dir,
                            cursor_uri=os.path.join(root, "cursor.ckpt"),
                            name="prodsim")
    live_model = HistGBT(n_trees=2, max_depth=3, n_bins=16,
                         learning_rate=0.3)
    trainer = OnlineTrainer(live_model, tailer, n_features=feats,
                            chunk_rows=512, window_chunks=2, decay=1.0,
                            name="prodsim")

    def _stream_lane():
        # tail → warm-start boost → tenant-scoped staged rollout; an
        # infra rollback (replica died mid-wave) is recorded and retried
        # by the next refresh — only a gate trip is a real rollback
        while not stop_stream.is_set():
            try:
                r = trainer.refresh(timeout=1.0, stop=stop_stream.is_set)
            except Exception as e:  # noqa: BLE001
                chaos_log.append({
                    "t": round(time.time() - t0, 3), "point": "stream",
                    "detail": f"refresh ERROR {type(e).__name__}: "
                              f"{e}"[:200]})
                time.sleep(0.2)
                continue
            if r is None:
                continue
            refreshes.append(r)
            if (jitcheck.installed()
                    and jitcheck.current_phase() == "warmup"
                    and r.get("window_rows", 0) >= 512 * 2):
                # trainer window (chunk_rows=512 × window_chunks=2) just
                # reached its final shape — the parent's only jax work
                # from here is refresh reuse, so compiles are stalls
                jitcheck.steady()
            with live_lock:
                version = live_state["version"] + 1
                live_state["version"] = version
            uri = f"file://{root}/live_v{version}.ckpt"
            snap = clone_model(live_model)
            checkpoint_tenant_model(uri, _PRODSIM_LIVE, snap,
                                    version=version)
            try:
                admin = HttpFleetAdmin(dict(tracker.serve_endpoints()))
                rep = Rollout(admin, wave_size=1, settle_s=0.1,
                              tenant=_PRODSIM_LIVE).run(uri)
            except Exception as e:  # noqa: BLE001
                live_rollouts.append({
                    "version": version,
                    "outcome": f"error: {type(e).__name__}"})
                continue
            live_rollouts.append({"version": version,
                                  "outcome": rep.get("outcome"),
                                  "waves": rep.get("waves")})
            if rep.get("outcome") != "activated":
                continue
            with live_lock:
                live_state.update(activated=version, model=snap, uri=uri)
                floor = live_state["served_floor"]
            now = time.time()
            covered = min(r["records_total"], len(append_ts))
            for seq in range(floor, covered):
                s = now - append_ts[seq]
                staleness.append(s)
                stale_hist.observe(s)
            with live_lock:
                live_state["served_floor"] = covered

    def _reconciler():
        # heal freshly-respawned replicas: any tenant missing from a
        # health doc is (re)loaded at its current good version — never
        # fights a rollout, which only moves tenants that ARE present
        all_tenants = _PRODSIM_TENANTS + [_PRODSIM_LIVE]
        while not stop_recon.is_set():
            try:
                eps = dict(tracker.serve_endpoints())
                admin = HttpFleetAdmin(eps)
                for rank in eps:
                    try:
                        tdoc = admin.health(rank).get("tenants", {})
                    except Exception:  # noqa: BLE001 — mid-respawn
                        continue
                    for t in all_tenants:
                        if t in tdoc:
                            continue
                        if t == _PRODSIM_LIVE:
                            with live_lock:
                                uri = live_state["uri"]
                        else:
                            uri = f"file://{root}/{t}_v1.ckpt"
                        try:
                            admin.load(rank, uri, activate=True, tenant=t)
                        except Exception:  # noqa: BLE001
                            pass
            except Exception:  # noqa: BLE001
                pass
            stop_recon.wait(0.4)

    # -- PS lane: scheduler in-parent, 2 servers + 2 workers as procs ----
    ps_dir = os.path.join(root, "ps")
    snap_dir = os.path.join(ps_dir, "snap")
    os.makedirs(snap_dir)
    ps_stop_file = os.path.join(ps_dir, "stop")
    sched = PSScheduler("127.0.0.1", nworker=2, nserver=2)
    sched.start()

    def _launch_ps(role, server_id=-1, rank=-1, stats=""):
        env = dict(os.environ,
                   JAX_PLATFORMS="cpu",
                   DMLC_TPU_FORCE_CPU="1",
                   DMLC_FAULT_INJECT="",
                   DMLC_PS_SNAPSHOT_DIR=snap_dir,
                   DMLC_PS_SNAPSHOT_STRIDE="1",
                   DMLC_PS_RECONNECT_S="120",
                   DMLC_PS_SERVER_ID=str(server_id),
                   DMLC_TASK_ID=str(rank),
                   PS_SCHED_PORT=str(sched.port),
                   PS_OUT=ps_dir,
                   PS_SERVER_STATS=stats,
                   PRODSIM_PS_STOP=ps_stop_file)
        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             f"--prodsim-ps-{role}"], env=env)

    ps_servers = [_launch_ps("server", server_id=i) for i in range(2)]
    ps_workers = [_launch_ps("worker", rank=r) for r in range(2)]
    ps_state["respawn_stats"] = os.path.join(ps_dir, "respawn.json")

    # -- chaos actions (one per tier; launch_host fires in the JobSet
    # monitor tick, inside FakeTransport) --------------------------------
    def _fault_replica(fault):
        st = scaler.jobset.stats()
        live = sorted(r for r, d in st["ranks"].items() if not d["done"])
        if not live:
            return "no live rank"
        scaler.jobset.kill(live[0], sig=_signal.SIGKILL, respawn=True)
        return f"SIGKILL replica rank {live[0]}"

    def _fault_ps(fault):
        victim = ps_servers[1]
        victim.send_signal(_signal.SIGKILL)
        victim.wait(timeout=60)
        ps_state["victim_rc"] = victim.returncode
        ps_servers[1] = _launch_ps("server", server_id=1,
                                   stats=ps_state["respawn_stats"])
        return (f"SIGKILL ps server 1 (rc={victim.returncode}); "
                "respawned same id")

    def _fault_shard(fault):
        # smash 64 bytes at the tailer's OWN read position: consumed
        # offsets always sit on record boundaries, so the very next
        # poll sees non-magic where a record must start and has to
        # resync forward — corrupting the newest shard instead would
        # sit unread until the (slower) trainer caught up to it
        shards = sorted(glob.glob(os.path.join(shard_dir, "part-*.rec")))
        offs = dict(tailer.cursor().offsets)
        target, off = shards[-1], 0
        for path in shards:
            done = offs.get(path, 0)
            if done < os.path.getsize(path):
                target, off = path, done
                break
        with open(target, "r+b") as f:
            f.seek(off)
            f.write(b"\x00" * 64)    # no magic, keeps 4-byte alignment
        return (f"smashed 64 bytes at {os.path.basename(target)}"
                f"+{off} (tailer cursor)")

    def _poison_gate(admin, endpoints):
        def gate(version):
            # honest gate: score the holdout against each replica that
            # actually serves the candidate version for the tenant
            for rank, url in endpoints.items():
                try:
                    tdoc = admin.health(rank).get("tenants", {}).get(
                        _PRODSIM_POISON, {})
                    if tdoc.get("version") != version:
                        continue
                    p, v = ResilientClient(url).predict(
                        X_hold, tenant=_PRODSIM_POISON)
                except Exception:  # noqa: BLE001 — replica mid-churn
                    continue
                if v != version:
                    continue
                mse = float(np.mean((p - y_hold) ** 2))
                if mse > 2.0 * base_mse + 1e-6:
                    return False
            return True
        return gate

    def _fault_publish(fault):
        endpoints = dict(tracker.serve_endpoints())
        admin = HttpFleetAdmin(endpoints)
        rep = Rollout(admin, wave_size=1, settle_s=0.3,
                      eval_gate=_poison_gate(admin, endpoints),
                      tenant=_PRODSIM_POISON).run(poison_uri)
        poison_report.update(rep)
        return f"poisoned publish outcome={rep.get('outcome')}"

    def _chaos_driver():
        actions = (("prodsim_replica", _fault_replica),
                   ("prodsim_ps", _fault_ps),
                   ("prodsim_shard", _fault_shard),
                   ("prodsim_publish", _fault_publish))
        while not stop_chaos.is_set():
            for point, action in actions:
                fault = faultinject.check(point)
                if fault is None:
                    continue
                try:
                    detail = action(fault)
                except Exception as e:  # noqa: BLE001
                    detail = f"ERROR {type(e).__name__}: {e}"[:200]
                chaos_log.append({"t": round(time.time() - t0, 3),
                                  "point": point, "kind": fault.kind,
                                  "detail": detail})
            stop_chaos.wait(0.05)

    router = None
    merged = {}
    chaos_fired = {}
    chaos_rules = []
    try:
        deadline = time.time() + 180
        while len(tracker.serve_endpoints()) < 3:
            if time.time() > deadline:
                raise RuntimeError("prodsim replicas never registered")
            time.sleep(0.2)
        endpoints = dict(tracker.serve_endpoints())
        admin = HttpFleetAdmin(endpoints)
        for rank in endpoints:
            for t in _PRODSIM_TENANTS:
                admin.load(rank, f"file://{root}/{t}_v1.ckpt",
                           activate=True, tenant=t)
            admin.load(rank, live_v1_uri, activate=True,
                       tenant=_PRODSIM_LIVE)
        policy = TenantPolicy(classes="gold:t0;bronze:t4",
                              default_class="silver", quota=0,
                              max_inflight=256, shed_fraction=0.5,
                              hedge_ms=0)
        router = FleetRouter(tracker, probe_s=0.2, policy=policy).start()
        probe, ver = ResilientClient(router.url).predict(X[:8],
                                                         tenant="t1")
        if ver != 1 or not np.array_equal(probe, npz["t1__v1"][:8]):
            raise RuntimeError("prodsim: routed warmup predict mismatch")

        gen_t = threading.Thread(target=_generator, daemon=True,
                                 name="prodsim-gen")
        lane_t = threading.Thread(target=_stream_lane, daemon=True,
                                  name="prodsim-stream")
        recon_t = threading.Thread(target=_reconciler, daemon=True,
                                   name="prodsim-recon")
        gen_t.start()
        lane_t.start()
        recon_t.start()

        _prodsim_emit({"value": 0.0, "phase": "load", **cfg})
        with faultinject.inject(chaos_spec, seed=seed):
            chaos_t = threading.Thread(target=_chaos_driver, daemon=True,
                                       name="prodsim-chaos")
            chaos_t.start()
            merged = run_loadgen(
                router.url, expected_npz, duration_s=duration, procs=2,
                threads=3, base_qps=qps, amplitude=0.5,
                period_s=max(duration / 2.0, 2.0), timeout_ms=20_000,
                workdir=root, env=child_env,
                tenants=list(_PRODSIM_TENANTS))
            # let straggler rules (and the wave, which fires in the
            # supervisor tick) finish before tearing the schedule down
            fire_deadline = time.time() + max(duration, 10.0)
            while time.time() < fire_deadline:
                if all(r["fires"] >= 1 for r in faultinject.rules()):
                    break
                time.sleep(0.2)
            stop_chaos.set()
            chaos_t.join(timeout=90)
            chaos_fired = faultinject.stats()
            chaos_rules = faultinject.rules()
        wave_hosts = transport.down_hosts()

        stop_gen.set()
        gen_t.join(timeout=10)
        stop_stream.set()
        lane_t.join(timeout=120)

        # the production day is over — close the steady window before
        # the oracle probes below (their fresh batch shapes may compile;
        # that is post-run bookkeeping, not a serving-path stall)
        recompiles_steady = (len(jitcheck.compiles("steady"))
                             if jitcheck.installed() else None)
        jitcheck.warmup()

        # live-tenant oracle: the routed answer must be bit-identical to
        # the snapshot of the last ACTIVATED refresh (reconciler still
        # healing respawned replicas, so allow convergence time)
        with live_lock:
            want_ver = live_state["activated"]
            want_model = live_state["model"]
        want_pred = want_model.predict(X_live[:32])
        live_ok = 0
        client = ResilientClient(router.url)
        probe_deadline = time.time() + 60
        while time.time() < probe_deadline:
            try:
                p, v = client.predict(X_live[:32], tenant=_PRODSIM_LIVE)
                if v == want_ver and np.array_equal(p, want_pred):
                    live_ok = 1
                    break
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.5)

        # rollback isolation: every static tenant on every replica back
        # on v1 — the poisoned v2 stuck nowhere
        isolated = 0
        iso_deadline = time.time() + 60
        while time.time() < iso_deadline:
            try:
                eps = dict(tracker.serve_endpoints())
                admin = HttpFleetAdmin(eps)
                if eps and all(
                        admin.health(rank).get("tenants", {})
                        .get(t, {}).get("version") == 1
                        for rank in eps for t in _PRODSIM_TENANTS):
                    isolated = 1
                    break
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.5)
        stop_recon.set()
        recon_t.join(timeout=10)

        # drain the PS lane: stop file → workers finish the pass and
        # exit; job completion lets the servers write stats and exit
        with open(ps_stop_file, "w") as f:
            f.write("stop\n")
        ps_rcs = {"workers": [], "servers": []}
        ps_deadline = time.time() + 180
        for p in ps_workers + ps_servers:
            try:
                p.wait(timeout=max(1.0, ps_deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        ps_rcs["workers"] = [p.returncode for p in ps_workers]
        ps_rcs["servers"] = [p.returncode for p in ps_servers]
        worker_stats = {}
        for r in range(2):
            path = os.path.join(ps_dir, f"worker-{r}.json")
            if os.path.exists(path):
                with open(path) as f:
                    worker_stats[r] = json.load(f)
        respawn = None
        if os.path.exists(ps_state["respawn_stats"]):
            with open(ps_state["respawn_stats"]) as f:
                respawn = json.load(f)

        st = scaler.jobset.stats()
        giveups = sum(1 for e in scaler.jobset.events()
                      if e.get("event") == "giveup")
        static_rb = 0.0
        snap = default_registry().snapshot()["metrics"]
        for s in snap.get("dmlc_tenant_rollbacks_total",
                          {}).get("series", []):
            tlabel = s["labels"].get("tenant")
            if tlabel in _PRODSIM_TENANTS and tlabel != _PRODSIM_POISON:
                static_rb += s["value"]

        stale_sorted = sorted(staleness)

        def q(p):
            if not stale_sorted:
                return None
            return round(stale_sorted[min(len(stale_sorted) - 1,
                                          int(round(p * (len(stale_sorted)
                                                         - 1))))], 3)

        tiers = {
            "replica": int(any(l.get("point") == "prodsim_replica"
                               for l in chaos_log)),
            "ps": int(any(l.get("point") == "prodsim_ps"
                          for l in chaos_log)),
            "host": int(chaos_fired.get("launch_host:wave", 0) >= 1),
            "shard": int(any(l.get("point") == "prodsim_shard"
                             for l in chaos_log)),
            "publish": int(any(l.get("point") == "prodsim_publish"
                               for l in chaos_log)),
        }
        availability = merged.get("ok", 0) / max(merged.get("count", 0), 1)

        rec = {
            "value": round(availability, 5),
            "phase": "done",
            "elapsed_s": round(time.time() - t0, 1),
            "platform": jax.devices()[0].platform,
            "availability": round(availability, 5),
            "dropped": merged.get("dropped"),
            "wrong": merged.get("wrong"),
            "loadgen": {k: merged.get(k) for k in
                        ("count", "ok", "dropped", "wrong", "shed",
                         "throughput_rps", "latency_p50_ms",
                         "latency_p95_ms", "latency_p99_ms",
                         "by_tenant")},
            "chaos": {
                "schedule": chaos_spec,
                "seed": seed,
                "fired": chaos_fired,
                "rules": chaos_rules,
                "tiers": tiers,
                "tiers_faulted": int(sum(tiers.values())),
                "wave_hosts": wave_hosts,
                "log": chaos_log,
            },
            "launch": {
                "backend": st["backend"],
                "respawns": st["respawns"],
                "respawns_by_cause": st["respawns_by_cause"],
                "host_faults": st["host_faults"],
                "spawn_ms_p95": st["spawn_ms_p95"],
                "giveups": giveups,
            },
            "ps": {
                "victim_rc": ps_state.get("victim_rc"),
                "victim_sigkilled": int(ps_state.get("victim_rc")
                                        == -_signal.SIGKILL),
                "respawn": respawn,
                "restored_version": (respawn or {}).get(
                    "restored_version"),
                "workers": worker_stats,
                "min_accuracy": (min(w["accuracy"]
                                     for w in worker_stats.values())
                                 if worker_stats else None),
                "rcs": ps_rcs,
            },
            "stream": {
                "refreshes": len(refreshes),
                "rollouts": live_rollouts,
                "activated": sum(1 for lr in live_rollouts
                                 if lr.get("outcome") == "activated"),
                "staleness_seconds": {"p50": q(0.50), "p95": q(0.95),
                                      "p99": q(0.99)},
                "resyncs": tailer.resyncs,
                "events_appended": len(append_ts),
                "events_consumed": tailer.records_seen,
                "live_version": want_ver,
                "live_verified": live_ok,
            },
            "rollback": {
                "poisoned": int(poison_report.get("outcome")
                                == "rolled_back"),
                "poison_waves": poison_report.get("waves"),
                "static_rollbacks": static_rb,
                "isolated": isolated,
            },
            "recompiles_steady_state": recompiles_steady,
            **cfg,
        }
        _prodsim_emit(rec, final=True)
        if recompiles_steady is not None:
            # DMLC_JITCHECK=1 makes the record a gate: a compile during
            # the load window is a steady-state stall, fail loudly
            jitcheck.check()
        return rec
    finally:
        stop_gen.set()
        stop_stream.set()
        stop_chaos.set()
        stop_recon.set()
        if router is not None:
            router.close()
        try:
            tailer.close()
        except Exception:  # noqa: BLE001
            pass
        scaler.reap(timeout=15)
        tracker.stop()
        transport.close()
        for p in ps_workers + ps_servers:
            if p.poll() is None:
                p.kill()
        try:
            sched.stop()
        except Exception:  # noqa: BLE001
            pass


def main() -> None:
    EV["t0"] = time.time()
    budget = float(os.environ.get("BENCH_TIME_BUDGET", 480))
    deadline = EV["t0"] + budget
    _install_guards(deadline)

    warmup = int(os.environ.get("BENCH_WARMUP", 10))
    depth = int(os.environ.get("BENCH_DEPTH", 6))
    n_bins = int(os.environ.get("BENCH_BINS", 256))

    # PR 12 levers are ON in the flagship config (BENCH_r06+): int4 bin
    # packing, exclusive-feature bundling, and loss-guide growth at half
    # the depth-wise build budget (16 expansions vs 2^(depth-1)=32
    # builds at depth 6).  setdefault so an operator can still A/B any
    # lever off (DMLC_BIN_PACK=0 etc.); the exact setting ships in the
    # record's config.levers block either way.
    os.environ.setdefault("DMLC_BIN_PACK", "1")
    os.environ.setdefault("DMLC_FEATURE_BUNDLE", "1")
    os.environ.setdefault("DMLC_GROW_POLICY", "lossguide")
    os.environ.setdefault("DMLC_MAX_LEAVES", str(max(1 << (depth - 2), 4)))

    if os.environ.get("BENCH_FORCE_CPU"):
        # self-test hook: the axon TPU plugin overrides JAX_PLATFORMS,
        # so tests must pin CPU through the supported entry point
        from dmlc_core_tpu.utils import force_cpu_devices
        force_cpu_devices(int(os.environ["BENCH_FORCE_CPU"]))

    import jax

    from dmlc_core_tpu.base import compile_cache as _cc
    from dmlc_core_tpu.base import jitcheck
    from dmlc_core_tpu.models import HistGBT
    from dmlc_core_tpu.parallel.mesh import local_mesh

    # persistent XLA compile cache (doc/performance.md): a warm rerun
    # of this bench deserializes the round program instead of paying
    # the ~30 s compile again; DMLC_COMPILE_CACHE=0 opts out
    _cc.configure()

    # Backend-init watchdog: if the TPU tunnel is wedged, device discovery
    # hangs in C land; fall back with an explanatory record rather than
    # hanging past the driver's patience.
    EV["phase"] = "probe"
    emit()
    # floor of 20s even under a tiny budget: the watchdog thread owns the
    # global deadline; this timeout only exists to produce a *descriptive*
    # wedged-tunnel record when there is still budget to continue in
    init_timeout = max(min(float(os.environ.get("BENCH_INIT_TIMEOUT", 180)),
                           deadline - time.time() - 30), 20.0)
    probe: dict = {}

    def _probe():
        try:
            probe["devices"] = jax.devices()
        except Exception as e:  # noqa: BLE001
            probe["error"] = str(e)

    t = threading.Thread(target=_probe, daemon=True)
    t.start()
    t.join(init_timeout)
    if "devices" not in probe:
        emit(final=True, error=(
            f"device init did not complete in {init_timeout:.0f}s "
            f"(TPU tunnel wedged?): {probe.get('error', 'timeout')}"))
        os._exit(2)
    EV["platform"] = probe["devices"][0].platform

    rows, feats, rounds = _pick_config(deadline - time.time())
    EV["config"] = {"rows": rows, "features": feats, "rounds": rounds,
                    "max_depth": depth, "n_bins": n_bins,
                    "levers": {
                        "bin_pack": os.environ["DMLC_BIN_PACK"] == "1",
                        "feature_bundle":
                            os.environ["DMLC_FEATURE_BUNDLE"] == "1",
                        "grow_policy": os.environ["DMLC_GROW_POLICY"],
                        "max_leaves":
                            int(os.environ["DMLC_MAX_LEAVES"] or 0),
                        "fused_round":
                            os.environ.get("DMLC_FUSED_ROUND", "auto"),
                        "hist_quant":
                            os.environ.get("DMLC_HIST_QUANT", "0") == "1",
                    }}

    # chips=N mode (ISSUE 7): BENCH_CHIPS pins the data-mesh width (0 /
    # unset = every local device — 1 chip on a single-chip host, 8 on a
    # v5e-8 slice).  Rows shard over the mesh, the per-level histogram
    # psum is the only cross-chip traffic, and the headline stays
    # per-chip so the scaling block below can score efficiency.
    chips_req = int(os.environ.get("BENCH_CHIPS", "0") or 0)
    avail = len(probe["devices"])
    if chips_req > avail:
        EV["notes"].append(
            f"BENCH_CHIPS={chips_req} clamped to {avail} local devices")
        chips_req = avail
    mesh = local_mesh(chips_req or None)  # all local devices by default
    n_chips = mesh.devices.size
    EV["config"] = {**EV["config"], "chips": n_chips}   # rebind, no mutate
    model = HistGBT(
        n_trees=rounds,
        max_depth=depth,
        n_bins=n_bins,
        learning_rate=0.1,
        mesh=mesh,
    )
    # cold-start overlap, bench half: the round-program compile (or its
    # persistent-cache deserialize) starts NOW, overlapping the whole
    # datagen + cuts + ingest stretch below — this is what collapses
    # warmup_seconds to compile-join residue when the ci.sh pre-seed
    # already warmed the cache (compile_cache: hit)
    model.start_warmup(rows, feats)
    EV["phase"] = "datagen"
    emit()

    # HIGGS-like synthetic: dense gaussians + a nonlinear decision rule
    rng = np.random.default_rng(7)
    X = rng.normal(size=(rows, feats)).astype(np.float32)
    margin = X[:, 0] * X[:, 1] + 0.5 * X[:, 2] - 0.8 * X[:, 3] * (X[:, 4] > 0)
    y = (margin > 0).astype(np.float32)

    EV["phase"] = "prepare"      # cuts + bin on host, uint8 H2D: setup
    emit()
    # host-side cuts + binning (see _host_cuts): only the uint8 bin
    # matrix crosses the tunnel.  setdefault so an operator can still
    # force the device path with DMLC_TPU_BIN_BACKEND="".
    os.environ.setdefault("DMLC_TPU_BIN_BACKEND", "cpu")
    dd = model.make_device_data(X, y, cuts=_host_cuts(X, n_bins))
    # everything from here runs off the device-resident handle; the host
    # copies (~1.2 GB at 10M×28) would otherwise sit in RAM to the end
    del X, y, margin
    # cold-start evidence: the quantize+stage wall (the round-program
    # compile overlaps it — see the per-run warmup breakdown)
    EV["config"] = {**EV["config"],
                    "bin_seconds": round(model.last_bin_seconds or 0.0, 3)}

    def _run_once(warmup_rounds):
        """One timed fit on the device-resident handle; returns an
        evidence dict with per-chunk rates.

        Each chunk arrival fires ``chunk_callback`` → a provisional JSON
        line, so even a SIGKILL mid-fit leaves the latest rate on
        stdout.  Per-chunk sec/round is the auditable unit: on a healthy
        chip all chunks run at the same rate; a degraded tunnel (the
        round-2 BENCH capture was 68× off) shows up as a worst/best
        chunk ratio ≫ 1."""
        EV["chunk_times"] = []
        steady_before = (len(jitcheck.compiles("steady"))
                         if jitcheck.installed() else 0)

        def cb(done, t_s):
            EV["chunk_times"].append((done, t_s))
            if (jitcheck.installed()
                    and jitcheck.current_phase() == "warmup"):
                # first chunk on host ⇒ warmup (compile-join + warm
                # dispatch) is over; any compile in chunks 2..N is the
                # PR 18 bug class resurfacing mid-fit
                jitcheck.steady()
            emit()

        model.fit_device(dd, warmup_rounds=warmup_rounds,
                         chunk_callback=cb)
        recompiles_steady = None
        if jitcheck.installed():
            recompiles_steady = (len(jitcheck.compiles("steady"))
                                 - steady_before)
            jitcheck.warmup()   # smokes/re-measures compile legitimately
        seconds = model.last_fit_seconds
        out = {
            "seconds": round(seconds, 3),
            "warmup_seconds": round(model.last_warmup_seconds, 3),
            "rounds_done": rounds,
        }
        if recompiles_steady is not None:
            out["recompiles_steady_state"] = recompiles_steady
        # cold-start breakdown (doc/performance.md): warmup_seconds =
        # compile-join residue + warm dispatch; compile_seconds is the
        # background compile's critical path (null on the inline path);
        # compile_cache says whether XLA read or wrote the persistent
        # cache ("warm" = no cache traffic at all — in-memory caches
        # served everything, e.g. the re-measure run)
        if model.last_compile_seconds is not None:
            out["compile_seconds"] = round(model.last_compile_seconds, 3)
        if model.last_warm_dispatch_seconds is not None:
            out["warm_dispatch_seconds"] = round(
                model.last_warm_dispatch_seconds, 3)
        # {trace, dispatch, device} attribution of warm_dispatch (the
        # r06 regression lever: 98 s of "warm dispatch" was the exec
        # warmup running the full K-round chunk on CPU — now the exec
        # is DMLC_WARMUP_EXEC-gated and trace = inline AOT compile)
        if model.last_warmup_breakdown is not None:
            out["warmup_breakdown"] = model.last_warmup_breakdown
        out["compile_cache"] = model.last_compile_cache or "warm"
        out.update(chunk_stats(model.last_chunk_times, rounds, seconds))
        # time from entering the timed fit to the FIRST trained trees
        # arriving on host = warmup + the first dispatch chunk (add
        # config.bin_seconds for the full cold start incl. staging)
        if model.last_chunk_times:
            out["time_to_first_tree"] = round(
                model.last_warmup_seconds + model.last_chunk_times[0][1],
                3)
        out["wall_rounds_per_sec"] = round(rounds / seconds / n_chips, 4)
        return out

    EV["phase"] = "warmup+timed"
    emit()
    try:
        runs = [_run_once(warmup)]
        EV["runs"] = runs
        if runs[0]["anomaly"]:
            # tunnel-degradation signature: one dispatch orders of
            # magnitude slower than its siblings.  Re-measure once ON THE
            # RESIDENT DATA (fit_device: no re-upload, jit cache warm) —
            # but only if the budget still fits a full run; otherwise the
            # median-chunk rate of run 1 is the defensible number.
            est = runs[0]["seconds"] * 1.5 + 30
            if deadline - time.time() > est:
                EV["notes"].append("chunk-rate anomaly: re-measuring once "
                                   "on resident data")
                emit()
                try:
                    runs.append(_run_once(1))
                except Exception as e:  # noqa: BLE001
                    EV["notes"].append(
                        f"re-measure failed ({type(e).__name__}: {e}), "
                        "keeping first run")
            else:
                EV["notes"].append(
                    f"chunk-rate anomaly but only {deadline - time.time():.0f}s "
                    f"budget left (< {est:.0f}s): re-measure skipped")
    except Exception as e:  # noqa: BLE001 — bench must always emit a line
        emit(final=True, error=f"{type(e).__name__}: {e}"[:500])
        os._exit(3)

    # Official selection: the FIRST non-anomalous run (never best-of-2 —
    # an upward-biased headline); if every run is anomalous, report the
    # best run's MEDIAN-chunk rate (the wall number is corrupted by the
    # stalled dispatch, the median chunk is not).
    non_anom = [r for r in runs if not r["anomaly"]]
    if non_anom:
        official = dict(non_anom[0])
        value = official["wall_rounds_per_sec"]
        EV["value_basis"] = "wall"
    else:
        official = dict(max(
            runs, key=lambda r: r["rounds_per_sec_median_chunk"]))
        value = official["rounds_per_sec_median_chunk"] / n_chips
        EV["value_basis"] = "median_chunk"
    official["value"] = value
    official.update(_derived_metrics(
        rows, feats, depth, n_bins,
        1.0 / (value * n_chips), EV["platform"], n_chips,
        layout=model._bin_layout,
        grow_policy=os.environ.get("DMLC_GROW_POLICY", "depthwise"),
        max_leaves=int(os.environ.get("DMLC_MAX_LEAVES", "0") or 0),
        fused=_fused_round_engaged(EV["platform"], n_chips,
                                   model._bin_layout, feats, depth,
                                   n_bins),
        quant=(os.environ.get("DMLC_HIST_QUANT", "0") == "1"
               and n_chips > 1
               and not int(os.environ.get("DMLC_HIST_BLOCKS", "0")
                           or 0))))
    EV["official"] = official
    EV["runs"] = runs
    emit()           # headline is now on stdout before scaling/smokes

    # -- multi-chip evidence (chips > 1 only): psum probe + 1-chip
    # oracle re-measure for scaling efficiency.  Both budget-gated and
    # non-fatal; the headline above is already emitted.
    if n_chips > 1:
        try:
            official["psum_probe"] = _psum_probe(mesh, depth, feats,
                                                 n_bins)
        except Exception as e:  # noqa: BLE001
            EV["notes"].append(
                f"psum probe failed: {type(e).__name__}: {e}"[:200])
        baseline_est = (EV["config"].get("bin_seconds", 30.0)
                        + rows * feats * 4 / 60e6 + 30.0
                        + rounds / max(value, 1e-6))
        if os.environ.get("BENCH_SCALING", "1") == "0":
            EV["notes"].append("scaling baseline skipped: BENCH_SCALING=0")
        elif deadline - time.time() < baseline_est + 90:
            EV["notes"].append(
                f"scaling baseline skipped: needs ~{baseline_est:.0f}s "
                f"of the {deadline - time.time():.0f}s left")
        else:
            EV["phase"] = "scaling_baseline"
            emit()
            try:
                # same global rows (same datagen seed), same cuts, one
                # chip: the denominator of scaling_efficiency
                rng_b = np.random.default_rng(7)
                Xb = rng_b.normal(size=(rows, feats)).astype(np.float32)
                mb = Xb[:, 0] * Xb[:, 1] + 0.5 * Xb[:, 2] \
                    - 0.8 * Xb[:, 3] * (Xb[:, 4] > 0)
                yb = (mb > 0).astype(np.float32)
                model1 = HistGBT(n_trees=rounds, max_depth=depth,
                                 n_bins=n_bins, learning_rate=0.1,
                                 mesh=local_mesh(1))
                dd1 = model1.make_device_data(
                    Xb, yb, cuts=np.asarray(model.cuts))
                del Xb, yb, mb
                model1.fit_device(dd1, warmup_rounds=1)
                base_rate = rounds / model1.last_fit_seconds
                official["scaling"] = scaling_summary(
                    n_chips, value, base_rate)
            except Exception as e:  # noqa: BLE001
                EV["notes"].append(
                    f"scaling baseline failed: "
                    f"{type(e).__name__}: {e}"[:200])
    elif os.environ.get("BENCH_SCALING", "1") != "0":
        # 1-chip host: the N-chip evidence still ships.  A subprocess
        # forces an 8-virtual-device CPU backend (the live TPU client in
        # THIS process can't be re-partitioned) and measures the same
        # round-program fold at reduced rows; scaling.basis carries the
        # honest caveat.  Budget-gated and never fatal.
        probe_left = deadline - time.time()
        if probe_left < 150:
            EV["notes"].append(
                f"virtual scaling probe skipped: {probe_left:.0f}s left")
        else:
            EV["phase"] = "scaling_probe"
            emit()
            try:
                import subprocess
                env = {**os.environ, "JAX_PLATFORMS": "cpu"}
                env.pop("BENCH_FORCE_CPU", None)
                # probe at the MAIN run's rows (not the 160k default)
                # with the same warmed compile-cache dir, so the
                # efficiency ratio compares like against like
                env.setdefault("BENCH_PROBE_ROWS", str(rows))
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--scaling-probe"],
                    capture_output=True, text=True,
                    timeout=min(probe_left - 60, 420), env=env)
                if r.returncode != 0:
                    raise RuntimeError(
                        f"rc={r.returncode}: {r.stderr.strip()[-200:]}")
                official["scaling"] = json.loads(
                    r.stdout.strip().splitlines()[-1])
            except Exception as e:  # noqa: BLE001
                EV["notes"].append(
                    f"virtual scaling probe failed: "
                    f"{type(e).__name__}: {e}"[:300])

    EV["phase"] = "smoke"
    emit()

    # configs 2/4 smoke fields — each budget-gated and non-fatal.  Each
    # value ships WITH its basis (VERDICT r4 weak #1): the smokes are
    # tiny probes whose absolute numbers are dominated by per-dispatch
    # tunnel latency on a remote-attached chip, so a reader holding only
    # this JSON must not score them against the BASELINE config 2/4
    # targets — the full-scale measured numbers ride along instead.
    smoke_basis = {
        "infeed_stall_frac": {
            "basis": "tunnel-smoke: 24x2048x128 synthetic batches; "
                     "dispatch-latency bound, NOT the config-2 claim",
            "full_scale": 0.0042,
            "full_scale_source": "BASELINE.md config 2: sharded RecordIO"
                                 " -> ResNet feed, real TPU (r4)",
        },
        "kvstore_sync_ms": {
            "basis": "tunnel-smoke: small BERT-shaped key set; "
                     "per-step dispatch latency, NOT the config-4 claim",
            "full_scale": 18.6,
            "full_scale_source": "BASELINE.md config 4: fused dist_sync"
                                 " at BERT-base size, real TPU (r4)",
        },
    }
    for name, fn, floor in (("infeed_stall_frac", _smoke_infeed, 75),
                            ("kvstore_sync_ms", _smoke_kvstore, 60)):
        if deadline - time.time() < floor:
            EV["smoke"] = {**EV["smoke"], name: None}    # rebind, no mutate
            EV["notes"].append(f"{name} skipped: budget")
            continue
        try:
            EV["smoke"] = {**EV["smoke"],
                           name: {"value": fn(mesh), **smoke_basis[name]}}
        except Exception as e:  # noqa: BLE001
            EV["smoke"] = {**EV["smoke"], name: None}
            EV["notes"].append(f"{name} failed: {type(e).__name__}: {e}"[:200])

    EV["phase"] = "done"
    emit(final=True)


if __name__ == "__main__":
    # observability plane: join the metrics spool when one is configured
    # (no-op otherwise) so the bench parent's registry merges with any
    # spawned replicas'/workers' under one DMLC_METRICS_SPOOL directory
    from dmlc_core_tpu.base.metrics_agg import install_spool
    if "--prodsim-ps-server" in sys.argv:
        install_spool("prodsim_ps_server",
                      int(os.environ.get("DMLC_PS_SERVER_ID", "0")))
        _prodsim_ps_server()
        sys.exit(0)
    if "--prodsim-ps-worker" in sys.argv:
        install_spool("prodsim_ps_worker",
                      int(os.environ.get("DMLC_TASK_ID", "0")))
        _prodsim_ps_worker()
        sys.exit(0)
    install_spool("bench", 0)
    if "--serve" in sys.argv:
        _serve_bench()
    elif "--fleet" in sys.argv:
        _fleet_bench()
    elif "--tenants" in sys.argv:
        _tenants_bench()
    elif "--stream" in sys.argv:
        _stream_bench()
    elif "--ps" in sys.argv:
        _ps_bench()
    elif "--prodsim" in sys.argv:
        _prodsim_bench()
    elif "--scaling-probe" in sys.argv:
        _scaling_probe()
    else:
        main()
