// Native RecordIO framing hot loop for dmlc_core_tpu.
//
// Reference parity: include/dmlc/recordio.h + src/recordio.cc ::
// RecordIOWriter/RecordIOChunkReader (SURVEY.md §2a).  Wire format:
//   [magic:u32le][lrec:u32le][payload][0-pad to 4]
//   lrec = (cflag << 29) | length, cflag ∈ {0 whole, 1 start, 2 mid, 3 end};
//   payloads containing the magic u32 at an aligned offset are split there
//   (magic consumed by the writer, re-inserted by the reader).
//
// The Python layer (dmlc_core_tpu/io/recordio.py) implements the same
// format; these entry points are the batch fast paths used by the RecordIO
// chunk decode (TPU infeed, BASELINE config 2) and bulk writers.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230aU;
constexpr int64_t kMaxLen = (int64_t(1) << 29) - 1;

inline uint32_t ReadU32(const char* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;  // little-endian hosts only (TPU hosts are x86/ARM LE)
}

inline void AppendU32(std::string* out, uint32_t v) {
  out->append(reinterpret_cast<const char*>(&v), 4);
}

}  // namespace

extern "C" {

// Shared growable result buffer.  For decode: `data` is the concatenated
// record payloads and `offsets` has n+1 entries framing each record.  For
// encode: `data` is the framed byte stream (offsets unused, n = records).
typedef struct {
  char* data;
  int64_t len;
  int64_t* offsets;
  int64_t n;
  char error[256];
} DmlcBuf;

void dmlc_buf_free(DmlcBuf* b) {
  if (b == nullptr) return;
  std::free(b->data);
  std::free(b->offsets);
  b->data = nullptr;
  b->offsets = nullptr;
  b->len = b->n = 0;
}

static int fail(DmlcBuf* out, const char* msg) {
  std::snprintf(out->error, sizeof(out->error), "%s", msg);
  return 1;
}

static char* copy_out(const std::string& s) {
  char* p = static_cast<char*>(std::malloc(s.size() ? s.size() : 1));
  if (p != nullptr) std::memcpy(p, s.data(), s.size());
  return p;
}

// Frame `n` records (concatenated in `data`, framed by `offsets[n+1]`) into
// a RecordIO byte stream.
int dmlc_recordio_encode(const char* data, const int64_t* offsets, int64_t n,
                         DmlcBuf* out) {
  std::memset(out, 0, sizeof(*out));
  std::string buf;
  buf.reserve(static_cast<size_t>(offsets[n] - offsets[0]) + 16 * n);
  for (int64_t r = 0; r < n; ++r) {
    const char* rec = data + offsets[r];
    const int64_t size = offsets[r + 1] - offsets[r];
    if (size < 0 || size > kMaxLen) return fail(out, "record too large");
    const int64_t lower = (size >> 2) << 2;
    const int64_t upper = ((size + 3) >> 2) << 2;
    int64_t dptr = 0;
    // split payload at 4-byte-aligned embedded magics (magic consumed)
    for (int64_t pos = 0; pos + 4 <= lower; pos += 4) {
      if (ReadU32(rec + pos) == kMagic) {
        const uint32_t cflag = (dptr == 0) ? 1 : 2;
        AppendU32(&buf, kMagic);
        AppendU32(&buf, (cflag << 29) | uint32_t(pos - dptr));
        buf.append(rec + dptr, pos - dptr);
        dptr = pos + 4;
      }
    }
    const uint32_t cflag = (dptr != 0) ? 3 : 0;
    AppendU32(&buf, kMagic);
    AppendU32(&buf, (cflag << 29) | uint32_t(size - dptr));
    buf.append(rec + dptr, size - dptr);
    buf.append(static_cast<size_t>(upper - size), '\0');
  }
  out->data = copy_out(buf);
  if (out->data == nullptr) return fail(out, "out of memory");
  out->len = static_cast<int64_t>(buf.size());
  out->n = n;
  return 0;
}

// Decode a chunk of complete RecordIO records into concatenated payloads +
// offsets.  The chunk must contain only whole parts (the InputSplit carry
// logic guarantees this).
int dmlc_recordio_decode(const char* chunk, int64_t len, DmlcBuf* out) {
  std::memset(out, 0, sizeof(*out));
  std::string payload;
  payload.reserve(static_cast<size_t>(len));
  std::vector<int64_t> offsets;
  offsets.push_back(0);
  int64_t pos = 0;
  bool in_record = false;
  while (pos < len) {
    if (pos + 8 > len) return fail(out, "truncated header");
    if (ReadU32(chunk + pos) != kMagic) return fail(out, "bad magic");
    const uint32_t lrec = ReadU32(chunk + pos + 4);
    const uint32_t cflag = (lrec >> 29) & 7;
    const int64_t clen = lrec & kMaxLen;
    if (pos + 8 + clen > len) return fail(out, "truncated payload");
    if ((cflag == 0 || cflag == 1) && in_record)
      return fail(out, "unexpected record start flag");
    if ((cflag == 2 || cflag == 3) && !in_record)
      return fail(out, "unexpected continuation flag");
    if (cflag == 2 || cflag == 3)
      payload.append(reinterpret_cast<const char*>(&kMagic), 4);
    payload.append(chunk + pos + 8, static_cast<size_t>(clen));
    pos += 8 + (((clen + 3) >> 2) << 2);
    if (cflag == 0 || cflag == 3) {
      offsets.push_back(static_cast<int64_t>(payload.size()));
      in_record = false;
    } else {
      in_record = true;
    }
  }
  if (in_record) return fail(out, "truncated multi-part record");
  out->data = copy_out(payload);
  out->offsets = static_cast<int64_t*>(
      std::malloc(offsets.size() * sizeof(int64_t)));
  if (out->data == nullptr || out->offsets == nullptr) {
    dmlc_buf_free(out);
    return fail(out, "out of memory");
  }
  std::memcpy(out->offsets, offsets.data(), offsets.size() * sizeof(int64_t));
  out->len = static_cast<int64_t>(payload.size());
  out->n = static_cast<int64_t>(offsets.size()) - 1;
  return 0;
}

}  // extern "C"
