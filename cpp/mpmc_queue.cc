// Native lock-free bounded MPMC queue + spinlock for dmlc_core_tpu.
//
// Reference parity: include/dmlc/concurrentqueue.h /
// blockingconcurrentqueue.h (vendored moodycamel lock-free MPMC queue) and
// include/dmlc/concurrency.h :: Spinlock (SURVEY.md §2a).  Instead of
// vendoring a third-party queue, this is an original bounded MPMC ring
// (Dmitry Vyukov's sequence-number design): each cell carries an atomic
// sequence counter; producers CAS the enqueue position and publish by
// bumping the cell sequence, consumers mirror it on dequeue.  Fast path is
// entirely lock-free; the *_block variants add a mutex+condvar slow path
// that producers/consumers fall back to only after a bounded spin, mirroring
// moodycamel's BlockingConcurrentQueue semantics (lock-free when busy,
// sleeping when idle).
//
// Payloads are opaque 64-bit handles; the Python wrapper
// (dmlc_core_tpu/io/lockfree.py) maps them onto object slots.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <mutex>
#include <new>

namespace {

constexpr size_t kCacheLine = 64;

struct Cell {
  std::atomic<size_t> seq;
  uint64_t value;
};

struct MpmcQueue {
  alignas(kCacheLine) std::atomic<size_t> enqueue_pos{0};
  alignas(kCacheLine) std::atomic<size_t> dequeue_pos{0};
  alignas(kCacheLine) Cell* cells = nullptr;
  size_t mask = 0;

  // Slow-path sleep support (blocking variants only touch this after a
  // bounded lock-free spin fails).
  std::mutex mu;
  std::condition_variable not_full;
  std::condition_variable not_empty;
  std::atomic<bool> killed{false};

  explicit MpmcQueue(size_t capacity_pow2) {
    mask = capacity_pow2 - 1;
    cells = static_cast<Cell*>(::operator new[](capacity_pow2 * sizeof(Cell)));
    for (size_t i = 0; i < capacity_pow2; ++i) {
      new (&cells[i]) Cell();
      cells[i].seq.store(i, std::memory_order_relaxed);
    }
  }
  ~MpmcQueue() {
    for (size_t i = 0; i <= mask; ++i) cells[i].~Cell();
    ::operator delete[](cells);
  }

  bool try_push(uint64_t v) {
    Cell* cell;
    size_t pos = enqueue_pos.load(std::memory_order_relaxed);
    for (;;) {
      cell = &cells[pos & mask];
      size_t seq = cell->seq.load(std::memory_order_acquire);
      intptr_t dif = static_cast<intptr_t>(seq) - static_cast<intptr_t>(pos);
      if (dif == 0) {
        if (enqueue_pos.compare_exchange_weak(pos, pos + 1,
                                              std::memory_order_relaxed)) {
          break;
        }
      } else if (dif < 0) {
        return false;  // full
      } else {
        pos = enqueue_pos.load(std::memory_order_relaxed);
      }
    }
    cell->value = v;
    cell->seq.store(pos + 1, std::memory_order_release);
    return true;
  }

  bool try_pop(uint64_t* out) {
    Cell* cell;
    size_t pos = dequeue_pos.load(std::memory_order_relaxed);
    for (;;) {
      cell = &cells[pos & mask];
      size_t seq = cell->seq.load(std::memory_order_acquire);
      intptr_t dif =
          static_cast<intptr_t>(seq) - static_cast<intptr_t>(pos + 1);
      if (dif == 0) {
        if (dequeue_pos.compare_exchange_weak(pos, pos + 1,
                                              std::memory_order_relaxed)) {
          break;
        }
      } else if (dif < 0) {
        return false;  // empty
      } else {
        pos = dequeue_pos.load(std::memory_order_relaxed);
      }
    }
    *out = cell->value;
    cell->seq.store(pos + mask + 1, std::memory_order_release);
    return true;
  }

  size_t size_approx() const {
    size_t enq = enqueue_pos.load(std::memory_order_relaxed);
    size_t deq = dequeue_pos.load(std::memory_order_relaxed);
    return enq >= deq ? enq - deq : 0;
  }
};

constexpr int kSpinIters = 256;

}  // namespace

extern "C" {

void* dmlc_mpmc_create(uint64_t capacity) {
  size_t cap = 1;
  while (cap < capacity) cap <<= 1;
  if (cap < 2) cap = 2;
  return new MpmcQueue(cap);
}

void dmlc_mpmc_destroy(void* q) { delete static_cast<MpmcQueue*>(q); }

int dmlc_mpmc_try_push(void* q, uint64_t v) {
  MpmcQueue* mq = static_cast<MpmcQueue*>(q);
  if (!mq->try_push(v)) return 0;
  // A sleeping consumer (if any) must learn a value arrived.
  mq->not_empty.notify_one();
  return 1;
}

int dmlc_mpmc_try_pop(void* q, uint64_t* out) {
  MpmcQueue* mq = static_cast<MpmcQueue*>(q);
  if (!mq->try_pop(out)) return 0;
  mq->not_full.notify_one();
  return 1;
}

// Blocking push.  timeout_ms < 0 → wait forever.  Returns 1 on success,
// 0 on timeout, -1 if the queue was killed.
int dmlc_mpmc_push_block(void* q, uint64_t v, int64_t timeout_ms) {
  MpmcQueue* mq = static_cast<MpmcQueue*>(q);
  for (int i = 0; i < kSpinIters; ++i) {
    if (mq->killed.load(std::memory_order_relaxed)) return -1;
    if (mq->try_push(v)) {
      mq->not_empty.notify_one();
      return 1;
    }
  }
  std::unique_lock<std::mutex> lk(mq->mu);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms < 0 ? 0 : timeout_ms);
  for (;;) {
    if (mq->killed.load(std::memory_order_relaxed)) return -1;
    if (mq->try_push(v)) {
      lk.unlock();
      mq->not_empty.notify_one();
      return 1;
    }
    // Chunked waits: the lock-free fast path publishes outside mq->mu, so a
    // notify can race a waiter into a miss — cap any miss at 10ms.
    if (timeout_ms >= 0 && std::chrono::steady_clock::now() >= deadline) {
      if (!mq->try_push(v)) return 0;
      mq->not_empty.notify_one();
      return 1;
    }
    mq->not_full.wait_for(lk, std::chrono::milliseconds(10));
  }
}

// Blocking pop.  Same return convention as push_block.
int dmlc_mpmc_pop_block(void* q, uint64_t* out, int64_t timeout_ms) {
  MpmcQueue* mq = static_cast<MpmcQueue*>(q);
  for (int i = 0; i < kSpinIters; ++i) {
    if (mq->try_pop(out)) {
      mq->not_full.notify_one();
      return 1;
    }
    if (mq->killed.load(std::memory_order_relaxed)) return -1;
  }
  std::unique_lock<std::mutex> lk(mq->mu);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms < 0 ? 0 : timeout_ms);
  for (;;) {
    if (mq->try_pop(out)) {
      lk.unlock();
      mq->not_full.notify_one();
      return 1;
    }
    if (mq->killed.load(std::memory_order_relaxed)) return -1;
    if (timeout_ms >= 0 && std::chrono::steady_clock::now() >= deadline) {
      if (!mq->try_pop(out)) return 0;
      mq->not_full.notify_one();
      return 1;
    }
    mq->not_empty.wait_for(lk, std::chrono::milliseconds(10));
  }
}

// SignalForKill parity (concurrency.h ConcurrentBlockingQueue): wake every
// blocked producer/consumer; subsequent blocking calls return -1.
void dmlc_mpmc_kill(void* q) {
  MpmcQueue* mq = static_cast<MpmcQueue*>(q);
  mq->killed.store(true, std::memory_order_relaxed);
  std::lock_guard<std::mutex> lk(mq->mu);
  mq->not_full.notify_all();
  mq->not_empty.notify_all();
}

uint64_t dmlc_mpmc_size_approx(void* q) {
  return static_cast<MpmcQueue*>(q)->size_approx();
}

// --- Spinlock (concurrency.h :: Spinlock) --------------------------------

void* dmlc_spinlock_create() {
  return new std::atomic_flag{};
}

void dmlc_spinlock_destroy(void* l) {
  delete static_cast<std::atomic_flag*>(l);
}

void dmlc_spinlock_lock(void* l) {
  auto* f = static_cast<std::atomic_flag*>(l);
  while (f->test_and_set(std::memory_order_acquire)) {
    // bounded pause; fall back nowhere — callers hold it for nanoseconds
  }
}

int dmlc_spinlock_trylock(void* l) {
  return static_cast<std::atomic_flag*>(l)->test_and_set(
             std::memory_order_acquire)
             ? 0
             : 1;
}

void dmlc_spinlock_unlock(void* l) {
  static_cast<std::atomic_flag*>(l)->clear(std::memory_order_release);
}

}  // extern "C"
