// fastparse.cc — multithreaded text → CSR parsing hot loop.
//
// Reference parity: src/data/text_parser.h :: TextParserBase::FillData
// (chunk → nthread line ranges → parallel ParseBlock) and the per-format
// ParseBlock loops of libsvm_parser.h / csv_parser.h / libfm_parser.h, with
// include/dmlc/strtonum.h's locale-free number parsing (SURVEY.md §2b).
//
// TPU-first redesign, not a translation: output is a single contiguous CSR
// arena (offset/label/index/value arrays) sized in a counting pre-pass, so
// the Python side wraps the buffers zero-copy as numpy arrays and stages
// them straight into jax.device_put — no per-row C++ objects, no
// std::string, no realloc churn.  Number parsing uses C++17 from_chars
// (locale-free, allocation-free), the modern equivalent of the reference's
// hand-rolled strtof.
//
// Build: make -C cpp   (→ ../build/libdmlctpu.so; OpenMP if available)

#include <algorithm>
#include <cctype>
#include <charconv>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

struct DmlcRows {
  int64_t n_rows;
  int64_t nnz;
  int64_t* offset;  // [n_rows + 1]
  float* label;     // [n_rows]
  float* weight;    // [n_rows] or null
  int64_t* qid;     // [n_rows] or null
  int32_t* field;   // [nnz] or null
  int64_t* index;   // [nnz]
  float* value;     // [nnz] or null
  int32_t has_weight, has_qid, has_field, has_value;
  char error[256];
};

int dmlc_parse_libsvm(const char* data, int64_t len, int nthread, DmlcRows* out);
int dmlc_parse_csv(const char* data, int64_t len, char delimiter, int64_t label_col,
                   int64_t weight_col, int nthread, DmlcRows* out);
int dmlc_parse_libfm(const char* data, int64_t len, int nthread, DmlcRows* out);
void dmlc_rows_free(DmlcRows* out);
int dmlc_num_threads();

}  // extern "C"

namespace {

struct ThreadRows {
  std::vector<int64_t> row_nnz;
  std::vector<float> label;
  std::vector<float> weight;
  std::vector<int64_t> qid;
  std::vector<int32_t> field;
  std::vector<int64_t> index;
  std::vector<float> value;
  bool any_weight = false, any_qid = false, any_field = false;
  std::string error;
};

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

inline bool parse_f32(const char*& p, const char* end, float* v) {
  // from_chars rejects a leading '+', but "+1" labels are canonical LibSVM
  const char* q = (p < end && *p == '+') ? p + 1 : p;
  auto res = std::from_chars(q, end, *v);
  if (res.ec != std::errc()) return false;
  p = res.ptr;
  return true;
}

inline bool parse_i64(const char*& p, const char* end, int64_t* v) {
  const char* q = (p < end && *p == '+') ? p + 1 : p;
  auto res = std::from_chars(q, end, *v);
  if (res.ec != std::errc()) return false;
  p = res.ptr;
  return true;
}

inline bool at_token_end(const char* p, const char* end) {
  return p >= end || *p == ' ' || *p == '\t' || *p == '\r';
}

// Split [data, data+len) into nthread ranges aligned on '\n'.
std::vector<std::pair<const char*, const char*>> line_ranges(const char* data,
                                                             int64_t len,
                                                             int nthread) {
  std::vector<std::pair<const char*, const char*>> out;
  const char* end = data + len;
  const char* cur = data;
  for (int t = 0; t < nthread; ++t) {
    const char* hi = data + len * (t + 1) / nthread;
    if (t == nthread - 1) {
      hi = end;
    } else {
      while (hi < end && *hi != '\n') ++hi;
      if (hi < end) ++hi;  // include the newline in this range
    }
    if (cur < hi) out.emplace_back(cur, hi);
    cur = hi;
    if (cur >= end) break;
  }
  return out;
}

bool parse_libsvm_range(const char* begin, const char* end, ThreadRows* tr) {
  const char* p = begin;
  while (p < end) {
    const char* line_end = static_cast<const char*>(memchr(p, '\n', end - p));
    if (line_end == nullptr) line_end = end;
    const char* q = skip_ws(p, line_end);
    if (q < line_end) {
      float lab;
      if (!parse_f32(q, line_end, &lab)) {
        tr->error = "libsvm: bad label near '" +
            std::string(q, std::min<int64_t>(line_end - q, 32)) + "'";
        return false;
      }
      int64_t nnz = 0;
      int64_t row_qid = 0;
      bool has_qid = false;
      float row_weight = 1.0f;
      q = skip_ws(q, line_end);
      while (q < line_end) {
        if (line_end - q > 4 && memcmp(q, "qid:", 4) == 0) {
          q += 4;
          if (!parse_i64(q, line_end, &row_qid)) {
            tr->error = "libsvm: bad qid";
            return false;
          }
          has_qid = true;
        } else {
          int64_t idx;
          if (!parse_i64(q, line_end, &idx)) {
            tr->error = "libsvm: bad feature index near '" +
                        std::string(q, std::min<int64_t>(line_end - q, 32)) + "'";
            return false;
          }
          float val = 1.0f;
          if (q < line_end && *q == ':') {
            ++q;
            // "idx:" with empty value means 1.0 (matches python fallback)
            if (!at_token_end(q, line_end) && !parse_f32(q, line_end, &val)) {
              tr->error = "libsvm: bad feature value";
              return false;
            }
          }
          tr->index.push_back(idx);
          tr->value.push_back(val);
          ++nnz;
        }
        q = skip_ws(q, line_end);
      }
      tr->label.push_back(lab);
      tr->weight.push_back(row_weight);
      tr->qid.push_back(row_qid);
      tr->any_qid |= has_qid;
      tr->row_nnz.push_back(nnz);
    }
    p = (line_end < end) ? line_end + 1 : end;
  }
  return true;
}

bool parse_csv_range(const char* begin, const char* end, char delim,
                     int64_t label_col, int64_t weight_col, ThreadRows* tr) {
  const char* p = begin;
  while (p < end) {
    const char* line_end = static_cast<const char*>(memchr(p, '\n', end - p));
    if (line_end == nullptr) line_end = end;
    const char* q = p;
    // skip blank lines (incl. lone '\r')
    const char* probe = skip_ws(q, line_end);
    if (probe < line_end) {
      float lab = 0.0f, wgt = 1.0f;
      int64_t col = 0, feat = 0, nnz = 0;
      while (q <= line_end) {
        const char* cell_end = q;
        while (cell_end < line_end && *cell_end != delim) ++cell_end;
        float v = 0.0f;
        const char* cp = skip_ws(q, cell_end);
        if (cp < cell_end && !parse_f32(cp, cell_end, &v)) {
          tr->error = "csv: bad number in column " + std::to_string(col) +
                      " near '" + std::string(q, std::min<int64_t>(cell_end - q, 32)) + "'";
          return false;
        }
        if (col == label_col) {
          lab = v;
        } else if (col == weight_col) {
          wgt = v;
          tr->any_weight = true;
        } else {
          tr->index.push_back(feat++);
          tr->value.push_back(v);
          ++nnz;
        }
        ++col;
        if (cell_end >= line_end) break;
        q = cell_end + 1;
      }
      tr->label.push_back(lab);
      tr->weight.push_back(wgt);
      tr->qid.push_back(0);
      tr->row_nnz.push_back(nnz);
    }
    p = (line_end < end) ? line_end + 1 : end;
  }
  return true;
}

bool parse_libfm_range(const char* begin, const char* end, ThreadRows* tr) {
  const char* p = begin;
  while (p < end) {
    const char* line_end = static_cast<const char*>(memchr(p, '\n', end - p));
    if (line_end == nullptr) line_end = end;
    const char* q = skip_ws(p, line_end);
    if (q < line_end) {
      float lab;
      if (!parse_f32(q, line_end, &lab)) {
        tr->error = "libfm: bad label";
        return false;
      }
      int64_t nnz = 0;
      q = skip_ws(q, line_end);
      while (q < line_end) {
        int64_t fld, idx;
        float val = 1.0f;
        if (!parse_i64(q, line_end, &fld) || q >= line_end || *q != ':') {
          tr->error = "libfm: bad field";
          return false;
        }
        ++q;
        if (!parse_i64(q, line_end, &idx)) {
          tr->error = "libfm: bad index";
          return false;
        }
        if (q < line_end && *q == ':') {
          ++q;
          if (!parse_f32(q, line_end, &val)) {
            tr->error = "libfm: bad value";
            return false;
          }
        }
        tr->field.push_back(static_cast<int32_t>(fld));
        tr->index.push_back(idx);
        tr->value.push_back(val);
        tr->any_field = true;
        ++nnz;
        q = skip_ws(q, line_end);
      }
      tr->label.push_back(lab);
      tr->weight.push_back(1.0f);
      tr->qid.push_back(0);
      tr->row_nnz.push_back(nnz);
    }
    p = (line_end < end) ? line_end + 1 : end;
  }
  return true;
}

template <typename RangeFn>
int run_parse(const char* data, int64_t len, int nthread, DmlcRows* out,
              RangeFn range_fn) {
  memset(out, 0, sizeof(DmlcRows));
  if (nthread <= 0) {
#ifdef _OPENMP
    nthread = omp_get_max_threads();
#else
    nthread = 1;
#endif
  }
  auto ranges = line_ranges(data, len, nthread);
  int nr = static_cast<int>(ranges.size());
  std::vector<ThreadRows> locals(nr);
  bool ok = true;
#ifdef _OPENMP
#pragma omp parallel for schedule(static) num_threads(nr)
#endif
  for (int t = 0; t < nr; ++t) {
    if (!range_fn(ranges[t].first, ranges[t].second, &locals[t])) {
#ifdef _OPENMP
#pragma omp critical
#endif
      ok = false;
    }
  }
  if (!ok) {
    for (auto& tr : locals) {
      if (!tr.error.empty()) {
        strncpy(out->error, tr.error.c_str(), sizeof(out->error) - 1);
        break;
      }
    }
    return 1;
  }
  int64_t n_rows = 0, nnz = 0;
  bool any_weight = false, any_qid = false, any_field = false;
  for (auto& tr : locals) {
    n_rows += static_cast<int64_t>(tr.label.size());
    nnz += static_cast<int64_t>(tr.index.size());
    any_weight |= tr.any_weight;
    any_qid |= tr.any_qid;
    any_field |= tr.any_field;
  }
  out->n_rows = n_rows;
  out->nnz = nnz;
  out->offset = static_cast<int64_t*>(malloc(sizeof(int64_t) * (n_rows + 1)));
  out->label = static_cast<float*>(malloc(sizeof(float) * std::max<int64_t>(n_rows, 1)));
  out->index = static_cast<int64_t*>(malloc(sizeof(int64_t) * std::max<int64_t>(nnz, 1)));
  out->value = static_cast<float*>(malloc(sizeof(float) * std::max<int64_t>(nnz, 1)));
  out->has_value = 1;
  if (any_weight) {
    out->weight = static_cast<float*>(malloc(sizeof(float) * std::max<int64_t>(n_rows, 1)));
    out->has_weight = 1;
  }
  if (any_qid) {
    out->qid = static_cast<int64_t*>(malloc(sizeof(int64_t) * std::max<int64_t>(n_rows, 1)));
    out->has_qid = 1;
  }
  if (any_field) {
    out->field = static_cast<int32_t*>(malloc(sizeof(int32_t) * std::max<int64_t>(nnz, 1)));
    out->has_field = 1;
  }
  int64_t row_base = 0, nnz_base = 0;
  out->offset[0] = 0;
  for (auto& tr : locals) {
    int64_t rows_here = static_cast<int64_t>(tr.label.size());
    memcpy(out->label + row_base, tr.label.data(), sizeof(float) * rows_here);
    if (any_weight) memcpy(out->weight + row_base, tr.weight.data(), sizeof(float) * rows_here);
    if (any_qid) memcpy(out->qid + row_base, tr.qid.data(), sizeof(int64_t) * rows_here);
    int64_t running = nnz_base;
    for (int64_t r = 0; r < rows_here; ++r) {
      running += tr.row_nnz[r];
      out->offset[row_base + r + 1] = running;
    }
    int64_t nnz_here = static_cast<int64_t>(tr.index.size());
    memcpy(out->index + nnz_base, tr.index.data(), sizeof(int64_t) * nnz_here);
    memcpy(out->value + nnz_base, tr.value.data(), sizeof(float) * nnz_here);
    if (any_field && !tr.field.empty())
      memcpy(out->field + nnz_base, tr.field.data(), sizeof(int32_t) * nnz_here);
    row_base += rows_here;
    nnz_base += nnz_here;
  }
  return 0;
}

}  // namespace

extern "C" {

int dmlc_parse_libsvm(const char* data, int64_t len, int nthread, DmlcRows* out) {
  return run_parse(data, len, nthread, out, parse_libsvm_range);
}

int dmlc_parse_csv(const char* data, int64_t len, char delimiter, int64_t label_col,
                   int64_t weight_col, int nthread, DmlcRows* out) {
  return run_parse(data, len, nthread, out,
                   [&](const char* b, const char* e, ThreadRows* tr) {
                     return parse_csv_range(b, e, delimiter, label_col, weight_col, tr);
                   });
}

int dmlc_parse_libfm(const char* data, int64_t len, int nthread, DmlcRows* out) {
  return run_parse(data, len, nthread, out, parse_libfm_range);
}

void dmlc_rows_free(DmlcRows* out) {
  free(out->offset);
  free(out->label);
  free(out->weight);
  free(out->qid);
  free(out->field);
  free(out->index);
  free(out->value);
  memset(out, 0, sizeof(DmlcRows));
}

int dmlc_num_threads() {
#ifdef _OPENMP
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
