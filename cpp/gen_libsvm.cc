// Criteo-shaped LibSVM generator for the external-memory benchmark.
//
// Writes ROWS rows of "label idx:val ..." with F sparse features (~3%
// missing per row, fixed-point values) at disk speed — formatting ~2e9
// fields in Python on this 1-core host would take the better part of an
// hour; this does it in minutes.  Deterministic per (seed, row), so a
// given (rows, features, seed) triple always produces the same file.
//
//   g++ -O2 -o ../build/gen_libsvm gen_libsvm.cc
//   ./build/gen_libsvm <rows> <features> <out_path> [seed]
//
// Reference context: the Criteo configs in BASELINE.md config 3; format
// per src/data/libsvm_parser.h (label idx:val with 0-based indices).
#include <cinttypes>
#include <cstdio>
#include <cstdlib>
#include <cstring>

static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

int main(int argc, char** argv) {
  if (argc < 4) {
    std::fprintf(stderr, "usage: %s <rows> <features> <out> [seed]\n",
                 argv[0]);
    return 2;
  }
  const int64_t rows = std::strtoll(argv[1], nullptr, 10);
  const int features = std::atoi(argv[2]);
  const char* path = argv[3];
  const uint64_t seed = argc > 4 ? std::strtoull(argv[4], nullptr, 10) : 7;

  std::FILE* f = std::fopen(path, "wb");
  if (!f) { std::perror("fopen"); return 1; }
  // ~4MB stdio buffer keeps fwrite syscalls rare
  static char iobuf[4 << 20];
  std::setvbuf(f, iobuf, _IOFBF, sizeof(iobuf));

  // per-feature worst case ≈ 16 bytes (" 99999:-8.00"); size from the
  // actual feature count so large F cannot overflow the row buffers
  const size_t cap = 32 + (size_t)features * 24;
  char* feats = (char*)std::malloc(cap);
  char* line = (char*)std::malloc(cap);
  if (!feats || !line) { std::perror("malloc"); return 1; }
  for (int64_t r = 0; r < rows; ++r) {
    uint64_t s = splitmix64(seed * 0x100000001b3ULL + (uint64_t)r);
    char* p = feats;
    long v0 = 0, v1 = 0, v2 = 0;           // fixed-point feature draws
    for (int j = 0; j < features; ++j) {
      s = splitmix64(s);
      if ((s & 31) == 0) continue;           // ~3% missing
      // fixed-point value in [-8.00, 8.00), two decimals
      int v = (int)(s >> 40 & 0x7ff) - 1024; // [-1024, 1023]
      if (j == 0) v0 = v; else if (j == 1) v1 = v; else if (j == 2) v2 = v;
      int whole = v / 128, frac = ((v < 0 ? -v : v) % 128) * 100 / 128;
      p += std::sprintf(p, " %d:%s%d.%02d", j,
                        (v < 0 && whole == 0) ? "-" : "", whole, frac);
    }
    // label: nonlinear rule over the first feature values so the data
    // is learnable, not pure noise (XGBoost-style smoke semantics)
    int label = (v0 * v1 + 256 * v2 > 0) ? 1 : 0;
    char* q = line;
    *q++ = '0' + label;
    std::memcpy(q, feats, (size_t)(p - feats));
    q += p - feats;
    *q++ = '\n';
    std::fwrite(line, 1, (size_t)(q - line), f);
    if ((r & 0xfffff) == 0xfffff)
      std::fprintf(stderr, "\r%" PRId64 "M rows", (r + 1) >> 20);
  }
  std::fprintf(stderr, "\ndone\n");
  std::fclose(f);
  std::free(feats);
  std::free(line);
  return 0;
}
