// Native test harness for the C++ hot loops — built and run under
// sanitizers by scripts/native_sanitize_test.sh (the reference's
// CMake USE_SANITIZER race/leak-detection story, SURVEY.md §4-5).
//
// Covers: MPMC queue under producer/consumer contention + kill, spinlock
// mutual exclusion, RecordIO encode/decode round trip (incl. embedded
// magic escaping), and the threaded LibSVM/CSV parsers.
//
// Build: g++ -std=c++17 -fsanitize=thread cpp/*.cc -o t && ./t

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* dmlc_mpmc_create(uint64_t capacity);
void dmlc_mpmc_destroy(void* q);
int dmlc_mpmc_try_push(void* q, uint64_t v);
int dmlc_mpmc_try_pop(void* q, uint64_t* out);
int dmlc_mpmc_push_block(void* q, uint64_t v, int64_t timeout_ms);
int dmlc_mpmc_pop_block(void* q, uint64_t* out, int64_t timeout_ms);
void dmlc_mpmc_kill(void* q);
uint64_t dmlc_mpmc_size_approx(void* q);
void* dmlc_spinlock_create();
void dmlc_spinlock_destroy(void* l);
void dmlc_spinlock_lock(void* l);
void dmlc_spinlock_unlock(void* l);

typedef struct {
  char* data;
  int64_t len;
  int64_t* offsets;
  int64_t n;
  char error[256];
} DmlcBuf;
int dmlc_recordio_encode(const char* data, const int64_t* offsets, int64_t n,
                         DmlcBuf* out);
int dmlc_recordio_decode(const char* data, int64_t len, DmlcBuf* out);
void dmlc_buf_free(DmlcBuf* b);

struct DmlcRows {
  int64_t n_rows;
  int64_t nnz;
  int64_t* offset;
  float* label;
  float* weight;
  int64_t* qid;
  int32_t* field;
  int64_t* index;
  float* value;
  int32_t has_weight, has_qid, has_field, has_value;
  char error[256];
};
int dmlc_parse_libsvm(const char* data, int64_t len, int nthread,
                      DmlcRows* out);
int dmlc_parse_csv(const char* data, int64_t len, char delimiter,
                   int64_t label_col, int64_t weight_col, int nthread,
                   DmlcRows* out);
void dmlc_rows_free(DmlcRows* out);
}

#define REQUIRE(cond)                                                   \
  do {                                                                  \
    if (!(cond)) {                                                      \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,      \
                   #cond);                                              \
      std::exit(1);                                                     \
    }                                                                   \
  } while (0)

static void test_mpmc_contention() {
  constexpr int kProducers = 4, kConsumers = 4;
  constexpr uint64_t kPerProducer = 20000;
  void* q = dmlc_mpmc_create(256);
  std::atomic<uint64_t> sum{0}, popped{0};
  std::vector<std::thread> ts;
  for (int p = 0; p < kProducers; ++p) {
    ts.emplace_back([q, p] {
      for (uint64_t i = 0; i < kPerProducer; ++i)
        REQUIRE(dmlc_mpmc_push_block(q, p * kPerProducer + i + 1, 10000) == 1);
    });
  }
  for (int c = 0; c < kConsumers; ++c) {
    ts.emplace_back([q, &sum, &popped] {
      uint64_t v;
      while (popped.load() < kProducers * kPerProducer) {
        if (dmlc_mpmc_pop_block(q, &v, 50) == 1) {
          sum.fetch_add(v);
          popped.fetch_add(1);
        }
      }
    });
  }
  for (auto& t : ts) t.join();
  const uint64_t total = kProducers * kPerProducer;
  REQUIRE(popped.load() == total);
  // sum of 1..N per producer block
  uint64_t want = 0;
  for (int p = 0; p < kProducers; ++p)
    for (uint64_t i = 0; i < kPerProducer; ++i) want += p * kPerProducer + i + 1;
  REQUIRE(sum.load() == want);
  REQUIRE(dmlc_mpmc_size_approx(q) == 0);
  dmlc_mpmc_destroy(q);
  std::puts("mpmc contention OK");
}

static void test_mpmc_kill_unblocks() {
  void* q = dmlc_mpmc_create(4);
  std::thread blocked([q] {
    uint64_t v;
    REQUIRE(dmlc_mpmc_pop_block(q, &v, 60000) == -1);  // killed, not timeout
  });
  std::this_thread::sleep_for(std::chrono::milliseconds(50));
  dmlc_mpmc_kill(q);
  blocked.join();
  dmlc_mpmc_destroy(q);
  std::puts("mpmc kill OK");
}

static void test_spinlock_mutex() {
  void* l = dmlc_spinlock_create();
  int64_t counter = 0;
  std::vector<std::thread> ts;
  for (int t = 0; t < 8; ++t) {
    ts.emplace_back([l, &counter] {
      for (int i = 0; i < 50000; ++i) {
        dmlc_spinlock_lock(l);
        ++counter;  // data race iff the lock is broken (TSan-visible)
        dmlc_spinlock_unlock(l);
      }
    });
  }
  for (auto& t : ts) t.join();
  REQUIRE(counter == 8 * 50000);
  dmlc_spinlock_destroy(l);
  std::puts("spinlock OK");
}

static void test_recordio_round_trip() {
  // records incl. one with an embedded aligned magic (escape path)
  std::string payload;
  std::vector<int64_t> offsets{0};
  const uint32_t magic = 0xced7230a;
  std::string rec1 = "hello-world-rec";
  std::string rec2(8, '\0');
  std::memcpy(&rec2[0], &magic, 4);  // aligned embedded magic
  std::memcpy(&rec2[4], "abcd", 4);
  std::string rec3 = "";
  for (const auto& r : {rec1, rec2, rec3}) {
    payload += r;
    offsets.push_back(static_cast<int64_t>(payload.size()));
  }
  DmlcBuf enc;
  REQUIRE(dmlc_recordio_encode(payload.data(), offsets.data(), 3, &enc) == 0);
  DmlcBuf dec;
  REQUIRE(dmlc_recordio_decode(enc.data, enc.len, &dec) == 0);
  REQUIRE(dec.n == 3);
  for (int r = 0; r < 3; ++r) {
    std::string got(dec.data + dec.offsets[r],
                    dec.data + dec.offsets[r + 1]);
    std::string want(payload.data() + offsets[r],
                     payload.data() + offsets[r + 1]);
    REQUIRE(got == want);
  }
  dmlc_buf_free(&enc);
  dmlc_buf_free(&dec);
  std::puts("recordio OK");
}

static void test_parsers() {
  const char* svm = "1 0:1.5 3:2.25\n0 1:0.5\n1 2:1.0 4:4.0\n";
  DmlcRows rows;
  REQUIRE(dmlc_parse_libsvm(svm, std::strlen(svm), 4, &rows) == 0);
  REQUIRE(rows.n_rows == 3);
  REQUIRE(rows.nnz == 5);
  REQUIRE(rows.label[0] == 1.0f && rows.label[1] == 0.0f);
  REQUIRE(rows.index[0] == 0 && rows.value[1] == 2.25f);
  dmlc_rows_free(&rows);

  const char* csv = "1,2.5,3\n0,1.5,2\n";
  DmlcRows crows;
  REQUIRE(dmlc_parse_csv(csv, std::strlen(csv), ',', 0, -1, 2, &crows) == 0);
  REQUIRE(crows.n_rows == 2);
  REQUIRE(crows.label[0] == 1.0f && crows.label[1] == 0.0f);
  dmlc_rows_free(&crows);
  std::puts("parsers OK");
}

int main() {
  test_mpmc_contention();
  test_mpmc_kill_unblocks();
  test_spinlock_mutex();
  test_recordio_round_trip();
  test_parsers();
  std::puts("ALL NATIVE TESTS PASSED");
  return 0;
}
